"""Fused Pallas TPU kernel for the LSTM recurrence — the hot op.

The reference leans on the cuDNN fused LSTM kernel for its hot loop
(reference: src/model.py:104, ``torch.nn.LSTM``). The TPU-native analog here
follows the same split cuDNN uses: the input projection for all timesteps is
one large MXU matmul (done OUTSIDE this kernel, where XLA already emits an
optimal batched dot), while the inherently sequential part — the per-timestep
recurrent matmul plus gate math — is fused into a single Pallas kernel:

- Hidden/cell state and the recurrent weight live in VMEM for the entire
  time loop; nothing round-trips to HBM between timesteps, and the per-step
  loop overhead is a hardware loop, not 60 unrolled XLA dynamic-slices.
- Each step is one ``(B_tile, H) @ (H, 4H)`` MXU matmul with the sigmoid/
  tanh gate math fused on the VPU, writing ``h_t`` straight into the VMEM
  output block.
- Training needs gradients, and Pallas kernels don't autodiff through
  in-kernel loops — so the backward pass (standard BPTT) is a second fused
  kernel wired via ``jax.custom_vjp``. Instead of stashing gate activations
  like cuDNN, the backward kernel RECOMPUTES them from the saved ``h``/``c``
  and the input projections (one extra MXU matmul per step) — that drops the
  ``(T, B, 4H)`` stash, which is what lets a whole ~100-row batch (the
  reference's 100-stock window) fit in VMEM as ONE program instead of
  serialized row tiles.
- When the batch does fit in one program, the backward kernel additionally
  writes ``dx`` in place over the input-projection buffer
  (``input_output_aliases``): the sweep runs t = T-1 → 0 and slot ``t`` is
  dead after step ``t``, so the overwrite is hazard-free and saves another
  ``(T, B, 4H)`` of VMEM. Larger batches fall back to a row-tiled grid
  (rows are independent) with per-tile partial ``dw`` summed outside.

Everything is time-major ``(T, B, ...)``: each timestep slice is then a
contiguous ``(rows, lanes)`` tile, matching the TPU's (8, 128) layout.

Stacked layers additionally fuse in PAIRS into a single wavefront program
(``lstm_pair_recurrence`` below) that runs layer l step t alongside layer
l+1 step t-1, halving the serial matmul chain — see the fused layer-pair
section for the scheduling and VMEM-budget analysis.

On non-TPU backends ``lstm_recurrence`` falls back to an identical
``lax.scan`` formulation; tests additionally run the Pallas kernels in
interpreter mode on CPU to pin parity between the two paths.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Single-program threshold / fallback row tile. ~104 rows keeps the aliased
# backward under ~12 MB of VMEM at the reference's largest shape (T=60,
# H=64); the tiled fallback uses 32-row blocks (double-buffered by the grid
# pipeline, so its budget is ~2x per-block bytes). The fallback tile is
# env-tunable (MT_LSTM_ROW_TILE, multiple of 8): RESULTS.md's batch sweep
# shows per-window efficiency halving when batches leave the single-program
# regime, and a larger tile trades VMEM for bigger (tile, H) MXU matmuls —
# measure on the target chip before changing the default.
SINGLE_TILE_MAX_ROWS = 104
ROW_TILE = 32


def _single_layer_vmem_bytes(
    n_t: int, b: int, hidden: int, itemsize: int = 4
) -> int:
    """VMEM footprint of the single-layer BACKWARD program, in bytes.

    The backward program is the high-water mark: per row-tile it holds the
    x/dx aliased ``(T, tile, 4H)`` plane, the dh cotangent and h/c stashes
    (3 ``(T, tile, H)`` planes), the weight and its grad, and the f32
    scratch — doubled when the row grid pipelines more than one tile
    (Pallas double-buffers blocked refs across grid steps).
    """
    four_h = 4 * hidden
    tile = _row_tile(b)
    b_pad = -(-b // 8) * 8
    if b_pad <= tile:
        # Single program: dx aliases over x (one 4H plane), no pipelining.
        planes = n_t * tile * (four_h + 3 * hidden)
    else:
        # Row grid: _bwd_pallas disables the dx alias (separate x and dx
        # planes) and the grid pipeline double-buffers every blocked ref.
        planes = n_t * tile * (2 * four_h + 3 * hidden) * 2
    scratch = 2 * tile * hidden + hidden * four_h
    weights = 2 * hidden * four_h
    return (planes + weights) * itemsize + scratch * 4


def single_layer_fits(
    n_t: int, b: int, hidden: int, itemsize: int = 4
) -> bool:
    """VMEM feasibility of the single-layer kernel at (T, rows, H).

    Long lookbacks blow the budget no matter the row tile; callers must
    fall back to the time-blocked kernel or the scan formulation instead
    of hitting a Mosaic scoped-VMEM compile error.
    """
    return _single_layer_vmem_bytes(n_t, b, hidden, itemsize) <= _PAIR_VMEM_BUDGET


def _fallback_row_tile() -> int:
    raw = os.environ.get("MT_LSTM_ROW_TILE", str(ROW_TILE))
    try:
        tile = int(raw)
    except ValueError:
        tile = -1  # fall through to the descriptive error
    if tile <= 0 or tile % 8:
        raise ValueError(
            f"MT_LSTM_ROW_TILE must be a positive multiple of 8, got {raw!r}"
        )
    return tile


def _pad_rows(a: jax.Array, b_pad: int) -> jax.Array:
    b = a.shape[1]
    if b == b_pad:
        return a
    return jnp.pad(a, ((0, 0), (0, b_pad - b), (0, 0)))


def _row_tile(b: int) -> int:
    b_pad8 = -(-b // 8) * 8
    if b_pad8 <= SINGLE_TILE_MAX_ROWS:
        return b_pad8
    return _fallback_row_tile()


def _gate_math(gates):
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    return jax.nn.sigmoid(i), jax.nn.sigmoid(f), jnp.tanh(g), jax.nn.sigmoid(o)


# ----------------------------------------------------------------- forward


def _fwd_kernel(x_ref, w_ref, h_out, c_out, h_scr, c_scr):
    n_t = x_ref.shape[0]
    h_scr[:] = jnp.zeros_like(h_scr)
    c_scr[:] = jnp.zeros_like(c_scr)
    w = w_ref[:].astype(jnp.float32)

    def body(t, _):
        gates = x_ref[t].astype(jnp.float32) + lax.dot_general(
            h_scr[:],
            w,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        i, f, g, o = _gate_math(gates)
        c = f * c_scr[:] + i * g
        h = o * jnp.tanh(c)
        h_scr[:] = h
        c_scr[:] = c
        h_out[t] = h.astype(h_out.dtype)
        c_out[t] = c.astype(c_out.dtype)
        return 0

    lax.fori_loop(0, n_t, body, 0)


def _fwd_pallas(x_proj, w_hh_t, *, interpret):
    n_t, b, four_h = x_proj.shape
    hidden = four_h // 4
    tile = _row_tile(b)
    b_pad = -(-b // tile) * tile
    x_padded = _pad_rows(x_proj, b_pad)
    grid = (b_pad // tile,)

    row_block = lambda width: pl.BlockSpec(  # noqa: E731
        (n_t, tile, width), lambda i: (0, i, 0), memory_space=pltpu.VMEM
    )
    hs, cs = pl.pallas_call(
        _fwd_kernel,
        grid=grid,
        in_specs=[
            row_block(four_h),
            pl.BlockSpec(
                (hidden, four_h), lambda i: (0, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=[row_block(hidden), row_block(hidden)],
        out_shape=[
            jax.ShapeDtypeStruct((n_t, b_pad, hidden), x_proj.dtype),
            jax.ShapeDtypeStruct((n_t, b_pad, hidden), x_proj.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((tile, hidden), jnp.float32),
            pltpu.VMEM((tile, hidden), jnp.float32),
        ],
        interpret=interpret,
    )(x_padded, w_hh_t)
    # tile rides the residuals: the backward grid must use the SAME tile
    # the forward padded for, even if MT_LSTM_ROW_TILE changes in between.
    return hs[:, :b], (x_padded, hs, cs, w_hh_t, b, tile)


# ---------------------------------------------------------------- backward


def _bwd_kernel(
    dh_ref, x_ref, h_ref, c_ref, w_ref, dx_out, dw_out, dh_scr, dc_scr, dw_scr
):
    n_t = dh_ref.shape[0]
    dh_scr[:] = jnp.zeros_like(dh_scr)
    dc_scr[:] = jnp.zeros_like(dc_scr)
    dw_scr[:] = jnp.zeros_like(dw_scr)
    w = w_ref[:].astype(jnp.float32)

    def body(k, _):
        t = n_t - 1 - k
        t_prev = jnp.maximum(t - 1, 0)
        not_first = jnp.float32(1.0) - (t == 0).astype(jnp.float32)
        c_prev = c_ref[t_prev].astype(jnp.float32) * not_first
        h_prev = h_ref[t_prev].astype(jnp.float32) * not_first
        # Recompute the activated gates (cheaper in VMEM than stashing them).
        gates = x_ref[t].astype(jnp.float32) + lax.dot_general(
            h_prev, w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        i, f, g, o = _gate_math(gates)
        tanh_c = jnp.tanh(c_ref[t].astype(jnp.float32))

        dh = dh_ref[t].astype(jnp.float32) + dh_scr[:]
        do = dh * tanh_c
        dc = dh * o * (1.0 - tanh_c * tanh_c) + dc_scr[:]
        di = dc * g
        dg = dc * i
        df = dc * c_prev
        dc_scr[:] = dc * f
        d_pre = jnp.concatenate(
            [
                di * i * (1.0 - i),
                df * f * (1.0 - f),
                dg * (1.0 - g * g),
                do * o * (1.0 - o),
            ],
            axis=-1,
        )
        # Slot t of the (aliased) input buffer is dead from here on.
        dx_out[t] = d_pre.astype(dx_out.dtype)
        # d h_{t-1} = d_pre @ w_hh_tᵀ : contract the 4H axes.
        dh_scr[:] = lax.dot_general(
            d_pre, w, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # d w_hh_t += h_{t-1}ᵀ @ d_pre : contract the row axes.
        dw_scr[:] += lax.dot_general(
            h_prev, d_pre, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return 0

    lax.fori_loop(0, n_t, body, 0)
    dw_out[0] = dw_scr[:].astype(dw_out.dtype)


def _bwd_pallas(interpret, residuals, dhs):
    x_padded, hs, cs, w_hh_t, b, tile = residuals
    n_t, b_pad, four_h = x_padded.shape
    hidden = four_h // 4
    dhs = _pad_rows(dhs, b_pad)
    grid = (b_pad // tile,)

    row_block = lambda width: pl.BlockSpec(  # noqa: E731
        (n_t, tile, width), lambda i: (0, i, 0), memory_space=pltpu.VMEM
    )
    dx, dw_partial = pl.pallas_call(
        _bwd_kernel,
        grid=grid,
        in_specs=[
            row_block(hidden),   # dhs
            row_block(four_h),   # x_proj (aliased to dx when grid == 1)
            row_block(hidden),   # hs
            row_block(hidden),   # cs
            pl.BlockSpec(
                (hidden, four_h), lambda i: (0, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=[
            row_block(four_h),
            pl.BlockSpec(
                (1, hidden, four_h), lambda i: (i, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_t, b_pad, four_h), x_padded.dtype),
            jax.ShapeDtypeStruct((grid[0], hidden, four_h), w_hh_t.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((tile, hidden), jnp.float32),
            pltpu.VMEM((tile, hidden), jnp.float32),
            pltpu.VMEM((hidden, four_h), jnp.float32),
        ],
        input_output_aliases={1: 0} if grid[0] == 1 else {},
        interpret=interpret,
    )(dhs, x_padded, hs, cs, w_hh_t)
    return dx[:, :b], jnp.sum(dw_partial, axis=0)


# ------------------------------------------ time-blocked long-lookback path
#
# The kernels above keep every (T, tile, ...) plane VMEM-resident for the
# whole time loop — the right call at the reference's T=60, but a long
# lookback override (the reference exposes datamodule.lookback_window
# freely) scales those planes linearly in T past the ~16 MB budget at ANY
# row tile. This is the framework's long-context mechanism (SURVEY.md §5:
# the LSTM recurrence is inherently serial, so long sequences cannot shard
# over devices the way attention rings do — they must stream through VMEM):
# a 2-D grid over (row tiles, time chunks) where the hidden/cell carry
# lives in scratch ACROSS sequential grid steps (Pallas TPU grids execute
# in order, innermost axis fastest), so VMEM holds one time chunk at a
# time while the recurrence itself never leaves the chip. The backward
# sweep runs the time-chunk axis REVERSED via the index maps, reads its
# cross-chunk h/c predecessors from per-chunk boundary slivers (no
# cross-chunk block reads), accumulates dw in scratch, and keeps x and dx
# as SEPARATE planes — multi-program grids don't get the resident
# kernel's dx alias, and the chunk-size model budgets both.


def _tb_time_chunk(tile: int, hidden: int, itemsize: int) -> int:
    """Largest time-chunk whose backward block set fits the VMEM budget."""
    four_h = 4 * hidden
    fixed = (
        (2 * tile * hidden + hidden * four_h) * 4  # f32 carries + dw scratch
        + 2 * hidden * four_h * itemsize           # w in + dw partial out
        + 2 * 2 * tile * hidden * itemsize         # h/c chunk-boundary blocks
    )
    # Double-buffered blocked planes per time step: x and dx (4H each — no
    # aliasing under a multi-program grid) + dh, h, c (H each).
    per_step = 2 * itemsize * tile * (2 * four_h + 3 * hidden)
    return max(1, (_PAIR_VMEM_BUDGET - fixed) // per_step)


def _tb_fwd_kernel(x_ref, w_ref, h_out, c_out, h_scr, c_scr):
    @pl.when(pl.program_id(1) == 0)
    def _init():
        h_scr[:] = jnp.zeros_like(h_scr)
        c_scr[:] = jnp.zeros_like(c_scr)

    w = w_ref[:].astype(jnp.float32)

    def body(k, _):
        gates = x_ref[k].astype(jnp.float32) + lax.dot_general(
            h_scr[:], w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        gi, gf, gg, go = _gate_math(gates)
        c = gf * c_scr[:] + gi * gg
        h = go * jnp.tanh(c)
        h_scr[:] = h
        c_scr[:] = c
        h_out[k] = h.astype(h_out.dtype)
        c_out[k] = c.astype(c_out.dtype)
        return 0

    lax.fori_loop(0, x_ref.shape[0], body, 0)


def _tb_fwd_pallas(x_proj, w_hh_t, *, interpret):
    n_t, b, four_h = x_proj.shape
    hidden = four_h // 4
    tile = _row_tile(b)
    b_pad = -(-b // tile) * tile
    itemsize = jnp.dtype(x_proj.dtype).itemsize
    tc = min(_tb_time_chunk(tile, hidden, itemsize), n_t)
    t_pad = -(-n_t // tc) * tc
    x_padded = jnp.pad(
        _pad_rows(x_proj, b_pad), ((0, t_pad - n_t), (0, 0), (0, 0))
    )
    grid = (b_pad // tile, t_pad // tc)

    tblock = lambda width: pl.BlockSpec(  # noqa: E731
        (tc, tile, width), lambda r, t: (t, r, 0), memory_space=pltpu.VMEM
    )
    hs, cs = pl.pallas_call(
        _tb_fwd_kernel,
        grid=grid,
        in_specs=[
            tblock(four_h),
            pl.BlockSpec(
                (hidden, four_h), lambda r, t: (0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=[tblock(hidden), tblock(hidden)],
        out_shape=[
            jax.ShapeDtypeStruct((t_pad, b_pad, hidden), x_proj.dtype),
        ] * 2,
        scratch_shapes=[
            pltpu.VMEM((tile, hidden), jnp.float32),
            pltpu.VMEM((tile, hidden), jnp.float32),
        ],
        interpret=interpret,
    )(x_padded, w_hh_t)
    res = (x_padded, hs, cs, w_hh_t, n_t, b, tile, tc)
    return hs[:n_t, :b], res


def _tb_bwd_kernel(
    dh_ref, x_ref, hb_ref, cb_ref, h_ref, c_ref, w_ref,
    dx_out, dw_out, dh_scr, dc_scr, dw_scr,
):
    @pl.when(pl.program_id(1) == 0)
    def _init():
        dh_scr[:] = jnp.zeros_like(dh_scr)
        dc_scr[:] = jnp.zeros_like(dc_scr)
        dw_scr[:] = jnp.zeros_like(dw_scr)

    w = w_ref[:].astype(jnp.float32)
    tc = dh_ref.shape[0]

    def body(kk, _):
        k = tc - 1 - kk
        k_prev = jnp.maximum(k - 1, 0)
        # Step k's h/c predecessors live in this chunk for k>0; the chunk's
        # first step reads the (1, tile, H) boundary block — h/c at the
        # END of the previous chunk (zeros for the global first chunk).
        first = (k == 0)
        h_prev = jnp.where(
            first, hb_ref[0].astype(jnp.float32),
            h_ref[k_prev].astype(jnp.float32),
        )
        c_prev = jnp.where(
            first, cb_ref[0].astype(jnp.float32),
            c_ref[k_prev].astype(jnp.float32),
        )
        gates = x_ref[k].astype(jnp.float32) + lax.dot_general(
            h_prev, w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        gi, gf, gg, go = _gate_math(gates)
        tanh_c = jnp.tanh(c_ref[k].astype(jnp.float32))
        dh = dh_ref[k].astype(jnp.float32) + dh_scr[:]
        do = dh * tanh_c
        dc = dh * go * (1.0 - tanh_c * tanh_c) + dc_scr[:]
        di = dc * gg
        dg = dc * gi
        df = dc * c_prev
        dc_scr[:] = dc * gf
        d_pre = jnp.concatenate(
            [
                di * gi * (1.0 - gi),
                df * gf * (1.0 - gf),
                dg * (1.0 - gg * gg),
                do * go * (1.0 - go),
            ],
            axis=-1,
        )
        dx_out[k] = d_pre.astype(dx_out.dtype)
        dh_scr[:] = lax.dot_general(
            d_pre, w, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dw_scr[:] += lax.dot_general(
            h_prev, d_pre, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return 0

    lax.fori_loop(0, tc, body, 0)

    @pl.when(pl.program_id(1) == pl.num_programs(1) - 1)
    def _emit():
        dw_out[0] = dw_scr[:].astype(dw_out.dtype)


def _tb_bwd_pallas(interpret, res, dhs):
    x_padded, hs, cs, w_hh_t, n_t, b, tile, tc = res
    t_pad, b_pad, four_h = x_padded.shape
    hidden = four_h // 4
    dhs = jnp.pad(
        _pad_rows(dhs, b_pad), ((0, t_pad - n_t), (0, 0), (0, 0))
    )
    grid = (b_pad // tile, t_pad // tc)
    n_tb = grid[1]
    # Chunk-boundary stashes: h/c at each chunk's LAST step, shifted one
    # chunk (zeros for the global first) — a (n_tb, B, H) sliver instead of
    # full shifted copies of the stash planes.
    boundary = lambda a: jnp.concatenate(  # noqa: E731
        [jnp.zeros_like(a[:1]), a[tc - 1 :: tc][:-1]], axis=0
    )

    rev = lambda width: pl.BlockSpec(  # noqa: E731
        (tc, tile, width), lambda r, t: (n_tb - 1 - t, r, 0),
        memory_space=pltpu.VMEM,
    )
    rev1 = pl.BlockSpec(
        (1, tile, hidden), lambda r, t: (n_tb - 1 - t, r, 0),
        memory_space=pltpu.VMEM,
    )
    dx, dw_partial = pl.pallas_call(
        _tb_bwd_kernel,
        grid=grid,
        in_specs=[
            rev(hidden),    # dh
            rev(four_h),    # x
            rev1,           # h boundary (prev chunk's last step)
            rev1,           # c boundary
            rev(hidden),    # h stash
            rev(hidden),    # c stash
            pl.BlockSpec(
                (hidden, four_h), lambda r, t: (0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=[
            rev(four_h),
            pl.BlockSpec(
                (1, hidden, four_h), lambda r, t: (r, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((t_pad, b_pad, four_h), x_padded.dtype),
            jax.ShapeDtypeStruct((grid[0], hidden, four_h), w_hh_t.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((tile, hidden), jnp.float32),
            pltpu.VMEM((tile, hidden), jnp.float32),
            pltpu.VMEM((hidden, four_h), jnp.float32),
        ],
        interpret=interpret,
    )(dhs, x_padded, boundary(hs), boundary(cs), hs, cs, w_hh_t)
    return dx[:n_t, :b], jnp.sum(dw_partial, axis=0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _lstm_recurrence_tblocked(x_proj, w_hh_t, interpret=False):
    hs, _ = _tb_fwd_pallas(x_proj, w_hh_t, interpret=interpret)
    return hs


def _tb_vjp_fwd(x_proj, w_hh_t, interpret):
    return _tb_fwd_pallas(x_proj, w_hh_t, interpret=interpret)


_lstm_recurrence_tblocked.defvjp(_tb_vjp_fwd, _tb_bwd_pallas)


# ----------------------------------------------- fused layer-pair kernels
#
# A stacked LSTM's serial bottleneck is the chain of tiny recurrent matmuls:
# L layers x T timesteps run back-to-back, so the reference workload
# (2 layers, T=60) sits ~120 dependent MXU ops deep before overheads.  The
# layers form a wavefront, though: layer 2 at step t-1 only needs layer 1's
# h up to t-1, so one fused kernel can run layer 1 step t and layer 2 step
# t-1 in the SAME loop iteration — two independent matmuls the MXU pipeline
# can overlap — cutting the dependent chain from 2T to ~T+2.  The layer-2
# input projection moves inside the kernel (it consumes h1, which never
# leaves VMEM now), as does the inter-layer dropout, applied as a
# precomputed mask.  Deeper stacks apply the fused kernel to consecutive
# layer pairs, halving their chains.
#
# Single-program only: the pair's residual stash (x1_proj + mask + four
# state planes) fits VMEM for the reference's ~100-row windows but not for
# large batches — and the footprint scales with T and hidden too, not just
# rows. Feasibility is therefore a BYTE check: the backward program (the
# high-water mark; it holds every plane the forward does plus the gradient
# scratch, with dx1 aliased over x1_proj) must fit under the byte budget of
# the known-good canonical shape (T=60, 104 rows, H=64, mask present —
# measured working on TPU v5e, RESULTS.md). Callers fall back to the
# per-layer/xla path when the check fails instead of hitting a Mosaic
# scoped-VMEM compile error.


def _stack_bwd_vmem_bytes(
    n_t: int,
    b_pad: int,
    hidden: int,
    n_layers: int,
    has_mask: bool,
    itemsize: int = 4,
) -> int:
    """VMEM footprint of an L-layer wavefront BACKWARD program, in bytes.

    ``itemsize`` is the compute dtype's size (4 for f32, 2 for the
    bf16-mixed mode); gradient-accumulator scratch is always f32. L=2 is
    exactly the fused-pair program's footprint.
    """
    four_h = 4 * hidden
    ell = n_layers
    # (T, B, H) planes in compute dtype: dh_top + 2L h/c stashes
    # (+ L-1 optional dropout masks).
    planes = n_t * b_pad * hidden * (1 + 2 * ell + (ell - 1) * int(has_mask))
    # (T, B, 4H): x1_proj, aliased over the dx1 output (counted once).
    planes += n_t * b_pad * four_h
    # In/out weight planes in compute dtype: L recurrent + (L-1) input
    # weights (+ bias rows), each appearing once as input, once as grad.
    weights = 2 * ((2 * ell - 1) * hidden * four_h + (ell - 1) * four_h)
    # f32 scratch: per-layer dh/dc + (L-1) seam-cotangent planes, plus the
    # f32 gradient accumulators for every weight.
    scratch = (3 * ell - 1) * b_pad * hidden + (
        (2 * ell - 1) * hidden * four_h + (ell - 1) * four_h
    )
    return (planes + weights) * itemsize + scratch * 4


_PAIR_VMEM_BUDGET = _stack_bwd_vmem_bytes(60, 104, 64, 2, True, 4)


def stack_fits(
    n_t: int,
    b: int,
    hidden: int,
    n_layers: int,
    has_mask: bool = True,
    itemsize: int = 4,
) -> bool:
    """True when an ``n_layers``-deep wavefront over ``b`` rows fits the
    single-program VMEM budget (the measured-working canonical pair's byte
    count)."""
    b_pad = -(-b // 8) * 8
    return (
        _stack_bwd_vmem_bytes(n_t, b_pad, hidden, n_layers, has_mask, itemsize)
        <= _PAIR_VMEM_BUDGET
    )


def pair_fits(
    n_t: int, b: int, hidden: int, has_mask: bool = True, itemsize: int = 4
) -> bool:
    """True when a (T=n_t, rows=b, H=hidden) layer pair fits the fused
    single-program kernel's VMEM budget (conservatively assumes the
    dropout-mask plane is present unless told otherwise)."""
    return stack_fits(n_t, b, hidden, 2, has_mask, itemsize)


def pair_rows_ok(b: int, n_t: int = 60, hidden: int = 64) -> bool:
    """Row-count feasibility at the canonical window shape (T=60, H=64)."""
    return pair_fits(n_t, b, hidden)


# Shape classes where the bf16 stack wavefront MEASURED faster on real TPU
# than the f32 pair default (sweeps/bench_fused_pair.py A/B; RESULTS.md
# "precision defaults" table). An entry (min_layers, hidden) qualifies
# every model with that hidden size and at least that many layers. EMPTY
# until the hardware A/B records the win — ``precision=auto`` then keeps
# the reference-parity f32 numerics everywhere; flipping a shape class in
# is a one-line change backed by a measured row.
MEASURED_BF16_WAVEFRONT_WINS: tuple[tuple[int, int], ...] = ()


def max_wavefront_depth(
    n_t: int, b: int, hidden: int, n_layers: int,
    has_mask: bool = True, itemsize: int = 4,
) -> int:
    """Deepest fused wavefront the VMEM byte model admits for this shape."""
    depth = 1
    while depth < n_layers and stack_fits(
        n_t, b, hidden, depth + 1, has_mask, itemsize
    ):
        depth += 1
    return depth


def preferred_compute_dtype(
    num_layers: int, hidden: int, n_t: int = 60, rows: int = 100,
    kernel_impl: str = "auto", backend: str | None = None,
):
    """Resolve ``precision=auto`` for one model shape.

    bf16 compute halves every VMEM stash plane, which can admit a strictly
    deeper wavefront (shorter serial recurrence chain — the measured
    latency lever, RESULTS.md). Auto picks bfloat16 only when ALL hold:

    - the fused wavefront path will actually run — Pallas-capable
      ``kernel_impl``, fusion + wavefront kill-switches on, TPU backend
      (the scan fallback has no VMEM wavefront, so flipping numerics
      there buys nothing),
    - the byte model says bf16 unlocks depth this f32 shape can't reach,
    - the shape class has a measured on-TPU win recorded in
      ``MEASURED_BF16_WAVEFRONT_WINS`` (defaults are flipped by evidence,
      not by the model alone).

    Everything else keeps float32 — the reference-parity numerics
    (reference: train.py:13 pins only torch's matmul precision; this is a
    measured, shape-aware policy instead).
    """
    import jax
    import jax.numpy as jnp

    qualifies = any(
        num_layers >= min_layers and hidden == h
        for min_layers, h in MEASURED_BF16_WAVEFRONT_WINS
    )
    if not qualifies:
        return jnp.float32
    if kernel_impl not in ("auto", "pallas", "interpret"):
        return jnp.float32
    if not (pair_fusion_enabled() and wavefront_enabled()):
        return jnp.float32
    if (backend or jax.default_backend()) != "tpu":
        return jnp.float32
    # `rows` is the kernel's leading dim — stocks per window (canonical
    # 100), NOT the optimizer batch: window-granular scheduling runs one
    # window's rows per fused program regardless of batch_size.
    unlocks = max_wavefront_depth(
        n_t, rows, hidden, num_layers, True, 2
    ) > max_wavefront_depth(n_t, rows, hidden, num_layers, True, 4)
    return jnp.bfloat16 if unlocks else jnp.float32


def pair_fusion_enabled() -> bool:
    """Kill-switch for the fused layer-pair kernel (MT_LSTM_FUSED_PAIR=0).

    Default ON: measured 1.14x (model=small) / 1.16x (model=medium)
    train-step throughput on TPU v5e vs the per-layer kernels
    (sweeps/bench_fused_pair.py, RESULTS.md). Any value other than the
    literal "0" — including unset or empty — leaves fusion enabled.
    """
    return os.environ.get("MT_LSTM_FUSED_PAIR", "1") != "0"


def _pair_fwd_kernel(*refs, has_mask=True):
    # The dropout mask is an OPTIONAL input: deterministic/eval calls and
    # dropout=0 training skip it entirely (no (T, B, H) all-ones plane in
    # VMEM, no per-step multiply) — `has_mask` is static, bound by partial.
    if has_mask:
        (x1_ref, mask_ref, w1_ref, wi2_ref, b2_ref, w2_ref,
         h2_out, h1_out, c1_out, c2_out,
         h1_scr, c1_scr, h2_scr, c2_scr, x2_scr) = refs
    else:
        (x1_ref, w1_ref, wi2_ref, b2_ref, w2_ref,
         h2_out, h1_out, c1_out, c2_out,
         h1_scr, c1_scr, h2_scr, c2_scr, x2_scr) = refs
    n_t = x1_ref.shape[0]
    h1_scr[:] = jnp.zeros_like(h1_scr)
    c1_scr[:] = jnp.zeros_like(c1_scr)
    h2_scr[:] = jnp.zeros_like(h2_scr)
    c2_scr[:] = jnp.zeros_like(c2_scr)
    w1 = w1_ref[:].astype(jnp.float32)
    wi2 = wi2_ref[:].astype(jnp.float32)
    b2 = b2_ref[:].astype(jnp.float32)
    w2 = w2_ref[:].astype(jnp.float32)

    def body(s, _):
        # Layer 2, step s-1 — reads x2_scr (projection of h1[s-1]) BEFORE
        # the layer-1 block below overwrites it with h1[s]'s projection.
        @pl.when(s > 0)
        def _l2():
            t = s - 1
            gates = x2_scr[:] + lax.dot_general(
                h2_scr[:], w2, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            i, f, g, o = _gate_math(gates)
            c = f * c2_scr[:] + i * g
            h = o * jnp.tanh(c)
            h2_scr[:] = h
            c2_scr[:] = c
            h2_out[t] = h.astype(h2_out.dtype)
            c2_out[t] = c.astype(c2_out.dtype)

        # Layer 1, step s (one step ahead of layer 2 — the wavefront).
        @pl.when(s < n_t)
        def _l1():
            gates = x1_ref[s].astype(jnp.float32) + lax.dot_general(
                h1_scr[:], w1, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            i, f, g, o = _gate_math(gates)
            c = f * c1_scr[:] + i * g
            h = o * jnp.tanh(c)
            h1_scr[:] = h
            c1_scr[:] = c
            h1_out[s] = h.astype(h1_out.dtype)
            c1_out[s] = c.astype(c1_out.dtype)
            h_seam = (
                h * mask_ref[s].astype(jnp.float32) if has_mask else h
            )
            x2_scr[:] = b2 + lax.dot_general(
                h_seam, wi2, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

        return 0

    lax.fori_loop(0, n_t + 1, body, 0)


def _pair_fwd_pallas(x1_proj, mask, w1t, wi2t, b2, w2t, *, interpret):
    """mask may be None (deterministic / dropout=0): the maskless kernel
    variant runs, with no mask plane in VMEM at all."""
    n_t, b, four_h = x1_proj.shape
    hidden = four_h // 4
    b_pad = -(-b // 8) * 8
    if not pair_fits(
        n_t, b, hidden, has_mask=mask is not None,
        itemsize=jnp.dtype(x1_proj.dtype).itemsize,
    ):
        raise ValueError(
            f"fused layer pair exceeds the VMEM budget at "
            f"(T={n_t}, rows={b}, H={hidden}, {x1_proj.dtype})"
        )
    x1_padded = _pad_rows(x1_proj, b_pad)
    mask_padded = None if mask is None else _pad_rows(mask, b_pad)
    b2_row = b2.reshape(1, four_h)

    full_block = lambda width: pl.BlockSpec(  # noqa: E731
        (n_t, b_pad, width), lambda: (0, 0, 0), memory_space=pltpu.VMEM
    )
    weight_block = lambda shape: pl.BlockSpec(  # noqa: E731
        shape, lambda: (0, 0), memory_space=pltpu.VMEM
    )
    has_mask = mask is not None
    in_specs = [full_block(four_h)]
    inputs = [x1_padded]
    if has_mask:
        in_specs.append(full_block(hidden))
        inputs.append(mask_padded)
    in_specs += [
        weight_block((hidden, four_h)),
        weight_block((hidden, four_h)),
        weight_block((1, four_h)),
        weight_block((hidden, four_h)),
    ]
    inputs += [w1t, wi2t, b2_row, w2t]
    h2s, h1s, c1s, c2s = pl.pallas_call(
        functools.partial(_pair_fwd_kernel, has_mask=has_mask),
        in_specs=in_specs,
        out_specs=[full_block(hidden)] * 4,
        out_shape=[
            jax.ShapeDtypeStruct((n_t, b_pad, hidden), x1_proj.dtype)
        ] * 4,
        scratch_shapes=[
            pltpu.VMEM((b_pad, hidden), jnp.float32),
            pltpu.VMEM((b_pad, hidden), jnp.float32),
            pltpu.VMEM((b_pad, hidden), jnp.float32),
            pltpu.VMEM((b_pad, hidden), jnp.float32),
            pltpu.VMEM((b_pad, four_h), jnp.float32),
        ],
        interpret=interpret,
    )(*inputs)
    res = (
        x1_padded, mask_padded, h1s, c1s, h2s, c2s, w1t, wi2t, b2_row, w2t, b
    )
    return h2s[:, :b], res


def _pair_bwd_kernel(*refs, has_mask=True):
    if has_mask:
        (dh2_ref, x1_ref, mask_ref, h1_ref, c1_ref, h2_ref, c2_ref,
         w1_ref, wi2_ref, b2_ref, w2_ref,
         dx1_out, dw1_out, dwi2_out, db2_out, dw2_out,
         dh1_scr, dc1_scr, dh2_scr, dc2_scr,
         dw1_scr, dwi2_scr, db2_scr, dw2_scr, dh1_in_scr) = refs
    else:
        (dh2_ref, x1_ref, h1_ref, c1_ref, h2_ref, c2_ref,
         w1_ref, wi2_ref, b2_ref, w2_ref,
         dx1_out, dw1_out, dwi2_out, db2_out, dw2_out,
         dh1_scr, dc1_scr, dh2_scr, dc2_scr,
         dw1_scr, dwi2_scr, db2_scr, dw2_scr, dh1_in_scr) = refs
    n_t = dh2_ref.shape[0]
    for scr in (dh1_scr, dc1_scr, dh2_scr, dc2_scr,
                dw1_scr, dwi2_scr, db2_scr, dw2_scr, dh1_in_scr):
        scr[:] = jnp.zeros_like(scr)
    w1 = w1_ref[:].astype(jnp.float32)
    wi2 = wi2_ref[:].astype(jnp.float32)
    b2 = b2_ref[:].astype(jnp.float32)
    w2 = w2_ref[:].astype(jnp.float32)

    def body(k, _):
        # Layer 1 bwd at t = n_t - k, one step BEHIND layer 2's reverse
        # sweep: it consumes dh1_in_scr written by the layer-2 block at
        # iteration k-1, so it must run before that block overwrites it.
        @pl.when(k > 0)
        def _l1():
            t = n_t - k
            t_prev = jnp.maximum(t - 1, 0)
            not_first = jnp.float32(1.0) - (t == 0).astype(jnp.float32)
            c_prev = c1_ref[t_prev].astype(jnp.float32) * not_first
            h_prev = h1_ref[t_prev].astype(jnp.float32) * not_first
            gates = x1_ref[t].astype(jnp.float32) + lax.dot_general(
                h_prev, w1, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            i, f, g, o = _gate_math(gates)
            tanh_c = jnp.tanh(c1_ref[t].astype(jnp.float32))
            dh = dh1_in_scr[:] + dh1_scr[:]
            do = dh * tanh_c
            dc = dh * o * (1.0 - tanh_c * tanh_c) + dc1_scr[:]
            di = dc * g
            dg = dc * i
            df = dc * c_prev
            dc1_scr[:] = dc * f
            d_pre = jnp.concatenate(
                [
                    di * i * (1.0 - i),
                    df * f * (1.0 - f),
                    dg * (1.0 - g * g),
                    do * o * (1.0 - o),
                ],
                axis=-1,
            )
            # Slot t of the aliased x1 buffer is dead from here on.
            dx1_out[t] = d_pre.astype(dx1_out.dtype)
            dh1_scr[:] = lax.dot_general(
                d_pre, w1, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dw1_scr[:] += lax.dot_general(
                h_prev, d_pre, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

        # Layer 2 bwd at t = n_t - 1 - k.
        @pl.when(k < n_t)
        def _l2():
            t = n_t - 1 - k
            t_prev = jnp.maximum(t - 1, 0)
            not_first = jnp.float32(1.0) - (t == 0).astype(jnp.float32)
            c_prev = c2_ref[t_prev].astype(jnp.float32) * not_first
            h_prev = h2_ref[t_prev].astype(jnp.float32) * not_first
            h1m = h1_ref[t].astype(jnp.float32)
            if has_mask:
                mask_t = mask_ref[t].astype(jnp.float32)
                h1m = h1m * mask_t
            # Recompute layer 2's input projection AND gates from VMEM
            # stashes (cheaper than stashing the (T, B, 4H) projection).
            x2 = b2 + lax.dot_general(
                h1m, wi2, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            gates = x2 + lax.dot_general(
                h_prev, w2, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            i, f, g, o = _gate_math(gates)
            tanh_c = jnp.tanh(c2_ref[t].astype(jnp.float32))
            dh = dh2_ref[t].astype(jnp.float32) + dh2_scr[:]
            do = dh * tanh_c
            dc = dh * o * (1.0 - tanh_c * tanh_c) + dc2_scr[:]
            di = dc * g
            dg = dc * i
            df = dc * c_prev
            dc2_scr[:] = dc * f
            d_pre = jnp.concatenate(
                [
                    di * i * (1.0 - i),
                    df * f * (1.0 - f),
                    dg * (1.0 - g * g),
                    do * o * (1.0 - o),
                ],
                axis=-1,
            )
            dh2_scr[:] = lax.dot_general(
                d_pre, w2, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dw2_scr[:] += lax.dot_general(
                h_prev, d_pre, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dwi2_scr[:] += lax.dot_general(
                h1m, d_pre, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            db2_scr[:] += jnp.sum(d_pre, axis=0, keepdims=True)
            dh1_in = lax.dot_general(
                d_pre, wi2, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dh1_in_scr[:] = mask_t * dh1_in if has_mask else dh1_in

        return 0

    lax.fori_loop(0, n_t + 1, body, 0)
    dw1_out[:] = dw1_scr[:].astype(dw1_out.dtype)
    dwi2_out[:] = dwi2_scr[:].astype(dwi2_out.dtype)
    db2_out[:] = db2_scr[:].astype(db2_out.dtype)
    dw2_out[:] = dw2_scr[:].astype(dw2_out.dtype)


def _pair_bwd_pallas(interpret, res, dh2s):
    (x1_padded, mask_padded, h1s, c1s, h2s, c2s,
     w1t, wi2t, b2_row, w2t, b) = res
    n_t, b_pad, four_h = x1_padded.shape
    hidden = four_h // 4
    dh2s = _pad_rows(dh2s, b_pad)

    full_block = lambda width: pl.BlockSpec(  # noqa: E731
        (n_t, b_pad, width), lambda: (0, 0, 0), memory_space=pltpu.VMEM
    )
    weight_block = lambda shape: pl.BlockSpec(  # noqa: E731
        shape, lambda: (0, 0), memory_space=pltpu.VMEM
    )
    has_mask = mask_padded is not None
    in_specs = [
        full_block(hidden),    # dh2s
        full_block(four_h),    # x1_proj (aliased to dx1)
    ]
    inputs = [dh2s, x1_padded]
    if has_mask:
        in_specs.append(full_block(hidden))
        inputs.append(mask_padded)
    in_specs += [
        full_block(hidden),    # h1s
        full_block(hidden),    # c1s
        full_block(hidden),    # h2s
        full_block(hidden),    # c2s
        weight_block((hidden, four_h)),
        weight_block((hidden, four_h)),
        weight_block((1, four_h)),
        weight_block((hidden, four_h)),
    ]
    inputs += [h1s, c1s, h2s, c2s, w1t, wi2t, b2_row, w2t]
    dx1, dw1t, dwi2t, db2_row, dw2t = pl.pallas_call(
        functools.partial(_pair_bwd_kernel, has_mask=has_mask),
        in_specs=in_specs,
        out_specs=[
            full_block(four_h),
            weight_block((hidden, four_h)),
            weight_block((hidden, four_h)),
            weight_block((1, four_h)),
            weight_block((hidden, four_h)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_t, b_pad, four_h), x1_padded.dtype),
            jax.ShapeDtypeStruct((hidden, four_h), w1t.dtype),
            jax.ShapeDtypeStruct((hidden, four_h), wi2t.dtype),
            jax.ShapeDtypeStruct((1, four_h), b2_row.dtype),
            jax.ShapeDtypeStruct((hidden, four_h), w2t.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((b_pad, hidden), jnp.float32),
            pltpu.VMEM((b_pad, hidden), jnp.float32),
            pltpu.VMEM((b_pad, hidden), jnp.float32),
            pltpu.VMEM((b_pad, hidden), jnp.float32),
            pltpu.VMEM((hidden, four_h), jnp.float32),
            pltpu.VMEM((hidden, four_h), jnp.float32),
            pltpu.VMEM((1, four_h), jnp.float32),
            pltpu.VMEM((hidden, four_h), jnp.float32),
            pltpu.VMEM((b_pad, hidden), jnp.float32),
        ],
        input_output_aliases={1: 0},
        interpret=interpret,
    )(*inputs)
    grads = (dx1[:, :b], dw1t, dwi2t, db2_row.reshape(four_h), dw2t)
    if has_mask:
        # dropout mask: nondiff
        return grads + (jnp.zeros_like(mask_padded[:, :b]),)
    return grads


@functools.partial(jax.custom_vjp, nondiff_argnums=(6,))
def _lstm_pair_pallas(x1_proj, w_hh1_t, w_ih2_t, bias2, w_hh2_t, mask,
                      interpret=False):
    h2s, _ = _pair_fwd_pallas(
        x1_proj, mask, w_hh1_t, w_ih2_t, bias2, w_hh2_t, interpret=interpret
    )
    return h2s


def _pair_vjp_fwd(x1_proj, w_hh1_t, w_ih2_t, bias2, w_hh2_t, mask, interpret):
    return _pair_fwd_pallas(
        x1_proj, mask, w_hh1_t, w_ih2_t, bias2, w_hh2_t, interpret=interpret
    )


_lstm_pair_pallas.defvjp(_pair_vjp_fwd, _pair_bwd_pallas)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def _lstm_pair_pallas_nomask(x1_proj, w_hh1_t, w_ih2_t, bias2, w_hh2_t,
                             interpret=False):
    h2s, _ = _pair_fwd_pallas(
        x1_proj, None, w_hh1_t, w_ih2_t, bias2, w_hh2_t, interpret=interpret
    )
    return h2s


def _pair_nomask_vjp_fwd(x1_proj, w_hh1_t, w_ih2_t, bias2, w_hh2_t,
                         interpret):
    return _pair_fwd_pallas(
        x1_proj, None, w_hh1_t, w_ih2_t, bias2, w_hh2_t, interpret=interpret
    )


_lstm_pair_pallas_nomask.defvjp(_pair_nomask_vjp_fwd, _pair_bwd_pallas)


def lstm_pair_xla(x1_proj, w_hh1_t, w_ih2_t, bias2, w_hh2_t, mask=None):
    """Reference formulation of the fused pair: two scans + projection."""
    h1s = lstm_recurrence_xla(x1_proj, w_hh1_t)
    seam = h1s if mask is None else h1s * mask
    x2_proj = seam @ w_ih2_t + bias2
    return lstm_recurrence_xla(x2_proj, w_hh2_t)


# --------------------------------------------- L-layer wavefront (stack)
#
# The pair kernel's wavefront generalizes: L stacked layers can run as ONE
# program with layer l at step s-l — L mutually independent recurrent
# matmuls per loop iteration, a dependent chain of ~T+L instead of
# (L/2)*(T+2) pair-serialized. What stops arbitrary depth is VMEM: the
# backward stash grows ~2 (T,B,H) planes (+1 mask) per layer, so at the
# canonical f32 shape L=2 is the frontier (that is the pair kernel). In the
# bf16-mixed compute mode every plane halves and a 4-5 deep wavefront fits
# — this section is what turns that mode from "neutral at bs=1" into the
# deep-model chain-shortener (cuDNN's multi-layer fused kernel analog;
# reference: src/model.py:88-94 via torch.nn.LSTM num_layers).
#
# Layout conventions (L = n_layers static, bound by closure):
# - layer 0 consumes x1_proj (projections + both biases, like every kernel
#   here); layers 1..L-1 project the seam INSIDE the kernel (their h input
#   never leaves VMEM) from per-seam scratch, exactly like the pair.
# - masks: L-1 optional dropout planes (torch semantics: every layer's
#   output except the stack's last gets dropout).
# - backward recomputes gates from the h/c stashes, aliases dx1 over
#   x1_proj, and accumulates all weight grads in f32 scratch.


def _stack_fwd_kernel(*refs, n_layers, has_mask):
    ell = n_layers
    i = 0
    x1_ref = refs[i]; i += 1
    masks = refs[i:i + (ell - 1)] if has_mask else ()
    i += (ell - 1) if has_mask else 0
    w_hh = refs[i:i + ell]; i += ell
    w_in = refs[i:i + ell - 1]; i += ell - 1
    bias = refs[i:i + ell - 1]; i += ell - 1
    h_out = refs[i:i + ell]; i += ell
    c_out = refs[i:i + ell]; i += ell
    h_scr = refs[i:i + ell]; i += ell
    c_scr = refs[i:i + ell]; i += ell
    x_scr = refs[i:i + ell - 1]; i += ell - 1

    n_t = x1_ref.shape[0]
    for scr in (*h_scr, *c_scr):
        scr[:] = jnp.zeros_like(scr)
    w = [r[:].astype(jnp.float32) for r in w_hh]
    wi = [r[:].astype(jnp.float32) for r in w_in]
    b = [r[:].astype(jnp.float32) for r in bias]

    def body(s, _):
        # Highest layer first: layer l consumes x_scr[l-1] (written by
        # layer l-1 at iteration s-1) BEFORE layer l-1 overwrites it below.
        for layer in reversed(range(ell)):

            @pl.when((s >= layer) & (s < n_t + layer))
            def _run(layer=layer):
                t = s - layer
                if layer == 0:
                    x_t = x1_ref[t].astype(jnp.float32)
                else:
                    x_t = x_scr[layer - 1][:]
                gates = x_t + lax.dot_general(
                    h_scr[layer][:], w[layer], (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                gi, gf, gg, go = _gate_math(gates)
                c = gf * c_scr[layer][:] + gi * gg
                h = go * jnp.tanh(c)
                h_scr[layer][:] = h
                c_scr[layer][:] = c
                h_out[layer][t] = h.astype(h_out[layer].dtype)
                c_out[layer][t] = c.astype(c_out[layer].dtype)
                if layer < ell - 1:
                    seam = (
                        h * masks[layer][t].astype(jnp.float32)
                        if has_mask else h
                    )
                    x_scr[layer][:] = b[layer] + lax.dot_general(
                        seam, wi[layer], (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )

        return 0

    lax.fori_loop(0, n_t + ell - 1, body, 0)


def _stack_fwd_pallas(x1_proj, masks, w_hh_ts, w_in_ts, biases, *, interpret):
    """masks: tuple of L-1 ``(T, B, H)`` planes, or None (maskless)."""
    ell = len(w_hh_ts)
    n_t, batch, four_h = x1_proj.shape
    hidden = four_h // 4
    b_pad = -(-batch // 8) * 8
    has_mask = masks is not None
    if not stack_fits(
        n_t, batch, hidden, ell, has_mask, jnp.dtype(x1_proj.dtype).itemsize
    ):
        raise ValueError(
            f"{ell}-layer wavefront exceeds the VMEM budget at "
            f"(T={n_t}, rows={batch}, H={hidden}, {x1_proj.dtype})"
        )
    x1_padded = _pad_rows(x1_proj, b_pad)
    masks_padded = (
        tuple(_pad_rows(m, b_pad) for m in masks) if has_mask else None
    )
    bias_rows = tuple(bv.reshape(1, four_h) for bv in biases)

    full_block = lambda width: pl.BlockSpec(  # noqa: E731
        (n_t, b_pad, width), lambda: (0, 0, 0), memory_space=pltpu.VMEM
    )
    weight_block = lambda shape: pl.BlockSpec(  # noqa: E731
        shape, lambda: (0, 0), memory_space=pltpu.VMEM
    )
    in_specs = [full_block(four_h)]
    inputs = [x1_padded]
    if has_mask:
        in_specs += [full_block(hidden)] * (ell - 1)
        inputs += list(masks_padded)
    in_specs += [weight_block((hidden, four_h))] * ell
    inputs += list(w_hh_ts)
    in_specs += [weight_block((hidden, four_h))] * (ell - 1)
    inputs += list(w_in_ts)
    in_specs += [weight_block((1, four_h))] * (ell - 1)
    inputs += list(bias_rows)

    outs = pl.pallas_call(
        functools.partial(
            _stack_fwd_kernel, n_layers=ell, has_mask=has_mask
        ),
        in_specs=in_specs,
        out_specs=[full_block(hidden)] * (2 * ell),
        out_shape=[
            jax.ShapeDtypeStruct((n_t, b_pad, hidden), x1_proj.dtype)
        ] * (2 * ell),
        scratch_shapes=(
            [pltpu.VMEM((b_pad, hidden), jnp.float32)] * (2 * ell)
            + [pltpu.VMEM((b_pad, four_h), jnp.float32)] * (ell - 1)
        ),
        interpret=interpret,
    )(*inputs)
    hs, cs = tuple(outs[:ell]), tuple(outs[ell:])
    res = (
        x1_padded, masks_padded, hs, cs,
        tuple(w_hh_ts), tuple(w_in_ts), bias_rows, batch,
    )
    return hs[ell - 1][:, :batch], res


def _stack_bwd_kernel(*refs, n_layers, has_mask):
    ell = n_layers
    i = 0
    dh_ref = refs[i]; i += 1
    x1_ref = refs[i]; i += 1
    masks = refs[i:i + (ell - 1)] if has_mask else ()
    i += (ell - 1) if has_mask else 0
    h_ref = refs[i:i + ell]; i += ell
    c_ref = refs[i:i + ell]; i += ell
    w_hh = refs[i:i + ell]; i += ell
    w_in = refs[i:i + ell - 1]; i += ell - 1
    bias = refs[i:i + ell - 1]; i += ell - 1
    dx1_out = refs[i]; i += 1
    dw_hh_out = refs[i:i + ell]; i += ell
    dw_in_out = refs[i:i + ell - 1]; i += ell - 1
    db_out = refs[i:i + ell - 1]; i += ell - 1
    dh_scr = refs[i:i + ell]; i += ell
    dc_scr = refs[i:i + ell]; i += ell
    dh_in_scr = refs[i:i + ell - 1]; i += ell - 1
    dw_hh_scr = refs[i:i + ell]; i += ell
    dw_in_scr = refs[i:i + ell - 1]; i += ell - 1
    db_scr = refs[i:i + ell - 1]; i += ell - 1

    n_t = dh_ref.shape[0]
    for scr in (*dh_scr, *dc_scr, *dh_in_scr,
                *dw_hh_scr, *dw_in_scr, *db_scr):
        scr[:] = jnp.zeros_like(scr)
    w = [r[:].astype(jnp.float32) for r in w_hh]
    wi = [r[:].astype(jnp.float32) for r in w_in]
    b = [r[:].astype(jnp.float32) for r in bias]

    def body(k, _):
        # Lowest layer first: layer l consumes dh_in_scr[l] (written by
        # layer l+1 at iteration k-1) BEFORE layer l+1 overwrites it below.
        for layer in range(ell):
            lag = ell - 1 - layer  # reverse sweep: top layer leads

            @pl.when((k >= lag) & (k < n_t + lag))
            def _run(layer=layer, lag=lag):
                t = n_t - 1 - k + lag
                t_prev = jnp.maximum(t - 1, 0)
                not_first = jnp.float32(1.0) - (t == 0).astype(jnp.float32)
                c_prev = c_ref[layer][t_prev].astype(jnp.float32) * not_first
                h_prev = h_ref[layer][t_prev].astype(jnp.float32) * not_first
                if layer == 0:
                    x_t = x1_ref[t].astype(jnp.float32)
                    h_below = None
                else:
                    h_below = h_ref[layer - 1][t].astype(jnp.float32)
                    if has_mask:
                        h_below = h_below * masks[layer - 1][t].astype(
                            jnp.float32
                        )
                    # Recompute the seam projection from the VMEM stash.
                    x_t = b[layer - 1] + lax.dot_general(
                        h_below, wi[layer - 1], (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )
                gates = x_t + lax.dot_general(
                    h_prev, w[layer], (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                gi, gf, gg, go = _gate_math(gates)
                tanh_c = jnp.tanh(c_ref[layer][t].astype(jnp.float32))
                if layer == ell - 1:
                    dh_top = dh_ref[t].astype(jnp.float32)
                else:
                    dh_top = dh_in_scr[layer][:]
                dh = dh_top + dh_scr[layer][:]
                do = dh * tanh_c
                dc = dh * go * (1.0 - tanh_c * tanh_c) + dc_scr[layer][:]
                di = dc * gg
                dg = dc * gi
                df = dc * c_prev
                dc_scr[layer][:] = dc * gf
                d_pre = jnp.concatenate(
                    [
                        di * gi * (1.0 - gi),
                        df * gf * (1.0 - gf),
                        dg * (1.0 - gg * gg),
                        do * go * (1.0 - go),
                    ],
                    axis=-1,
                )
                if layer == 0:
                    # Slot t of the aliased x1 buffer is dead from here on.
                    dx1_out[t] = d_pre.astype(dx1_out.dtype)
                else:
                    dw_in_scr[layer - 1][:] += lax.dot_general(
                        h_below, d_pre, (((0,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )
                    db_scr[layer - 1][:] += jnp.sum(
                        d_pre, axis=0, keepdims=True
                    )
                    dh_below = lax.dot_general(
                        d_pre, wi[layer - 1], (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )
                    if has_mask:
                        dh_below = dh_below * masks[layer - 1][t].astype(
                            jnp.float32
                        )
                    dh_in_scr[layer - 1][:] = dh_below
                dh_scr[layer][:] = lax.dot_general(
                    d_pre, w[layer], (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                dw_hh_scr[layer][:] += lax.dot_general(
                    h_prev, d_pre, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )

        return 0

    lax.fori_loop(0, n_t + ell - 1, body, 0)
    for layer in range(ell):
        dw_hh_out[layer][:] = dw_hh_scr[layer][:].astype(
            dw_hh_out[layer].dtype
        )
    for layer in range(ell - 1):
        dw_in_out[layer][:] = dw_in_scr[layer][:].astype(
            dw_in_out[layer].dtype
        )
        db_out[layer][:] = db_scr[layer][:].astype(db_out[layer].dtype)


def _stack_bwd_pallas(interpret, res, dhs):
    (x1_padded, masks_padded, hs, cs, w_hh_ts, w_in_ts, bias_rows, batch) = res
    ell = len(w_hh_ts)
    n_t, b_pad, four_h = x1_padded.shape
    hidden = four_h // 4
    dhs = _pad_rows(dhs, b_pad)
    has_mask = masks_padded is not None

    full_block = lambda width: pl.BlockSpec(  # noqa: E731
        (n_t, b_pad, width), lambda: (0, 0, 0), memory_space=pltpu.VMEM
    )
    weight_block = lambda shape: pl.BlockSpec(  # noqa: E731
        shape, lambda: (0, 0), memory_space=pltpu.VMEM
    )
    in_specs = [full_block(hidden), full_block(four_h)]
    inputs = [dhs, x1_padded]
    if has_mask:
        in_specs += [full_block(hidden)] * (ell - 1)
        inputs += list(masks_padded)
    in_specs += [full_block(hidden)] * (2 * ell)
    inputs += list(hs) + list(cs)
    in_specs += [weight_block((hidden, four_h))] * (2 * ell - 1)
    inputs += list(w_hh_ts) + list(w_in_ts)
    in_specs += [weight_block((1, four_h))] * (ell - 1)
    inputs += list(bias_rows)

    out_specs = (
        [full_block(four_h)]
        + [weight_block((hidden, four_h))] * (2 * ell - 1)
        + [weight_block((1, four_h))] * (ell - 1)
    )
    out_shape = (
        [jax.ShapeDtypeStruct((n_t, b_pad, four_h), x1_padded.dtype)]
        + [
            jax.ShapeDtypeStruct((hidden, four_h), wt.dtype)
            for wt in (*w_hh_ts, *w_in_ts)
        ]
        + [
            jax.ShapeDtypeStruct((1, four_h), br.dtype)
            for br in bias_rows
        ]
    )
    scratch_shapes = (
        [pltpu.VMEM((b_pad, hidden), jnp.float32)] * (3 * ell - 1)
        + [pltpu.VMEM((hidden, four_h), jnp.float32)] * (2 * ell - 1)
        + [pltpu.VMEM((1, four_h), jnp.float32)] * (ell - 1)
    )
    outs = pl.pallas_call(
        functools.partial(
            _stack_bwd_kernel, n_layers=ell, has_mask=has_mask
        ),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch_shapes,
        input_output_aliases={1: 0},
        interpret=interpret,
    )(*inputs)
    dx1 = outs[0][:, :batch]
    dw_hh = tuple(outs[1:1 + ell])
    dw_in = tuple(outs[1 + ell:2 * ell])
    db = tuple(o.reshape(four_h) for o in outs[2 * ell:])
    mask_grads = (
        tuple(jnp.zeros_like(m[:, :batch]) for m in masks_padded)
        if has_mask else None
    )
    return dx1, (dw_hh, dw_in, db), mask_grads


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _lstm_stack_pallas(x1_proj, weights, masks, interpret=False):
    """weights = (w_hh_ts tuple[L], w_in_ts tuple[L-1], biases tuple[L-1]);
    masks = tuple[L-1] of (T, B, H) planes, or None."""
    h_last, _ = _stack_fwd_pallas(
        x1_proj, masks, *weights, interpret=interpret
    )
    return h_last


def _stack_vjp_fwd(x1_proj, weights, masks, interpret):
    return _stack_fwd_pallas(x1_proj, masks, *weights, interpret=interpret)


_lstm_stack_pallas.defvjp(_stack_vjp_fwd, _stack_bwd_pallas)


def lstm_stack_xla(x1_proj, weights, masks=None):
    """Reference formulation of the L-layer stack: chained scans."""
    w_hh_ts, w_in_ts, biases = weights
    hs = lstm_recurrence_xla(x1_proj, w_hh_ts[0])
    for layer in range(1, len(w_hh_ts)):
        seam = hs if masks is None else hs * masks[layer - 1]
        x_proj = seam @ w_in_ts[layer - 1] + biases[layer - 1]
        hs = lstm_recurrence_xla(x_proj, w_hh_ts[layer])
    return hs


def wavefront_enabled() -> bool:
    """Kill-switch for >2-layer wavefront fusion (MT_LSTM_WAVEFRONT=0).

    Engages only when the stack's byte model fits the VMEM budget — at the
    canonical f32 shape that caps depth at 2 (the pair), so deep wavefronts
    are in practice a property of the bf16-mixed compute mode."""
    return os.environ.get("MT_LSTM_WAVEFRONT", "1") != "0"


def lstm_stack_recurrence(
    x1_proj: jax.Array,
    weights: tuple,
    masks: tuple | None = None,
    impl: str = "auto",
    window_rows: int | None = None,
) -> jax.Array:
    """Run L stacked LSTM layers as one fused wavefront recurrence.

    Args:
        x1_proj: ``(T, B, 4H)`` time-major layer-1 input projections.
        weights: ``(w_hh_ts, w_in_ts, biases)`` — tuples of per-layer
            ``(H, 4H)`` transposed recurrent weights (length L), seam input
            weights (length L-1), and combined seam biases ``(4H,)``
            (length L-1).
        masks: optional tuple of L-1 ``(T, B, H)`` pre-scaled dropout
            planes for the in-stack seams; ``None`` = maskless variant.
        impl: ``"pallas"`` | ``"xla"`` | ``"interpret"`` | ``"auto"``.
        window_rows: rows per window for window-granular scheduling when B
            exceeds the stack's VMEM budget (see lstm_recurrence).

    Returns:
        ``(T, B, H)`` top-layer hidden states for every timestep.
    """
    w_hh_ts, w_in_ts, biases = (tuple(part) for part in weights)
    weights = (w_hh_ts, w_in_ts, biases)
    masks = None if masks is None else tuple(masks)
    # TL102 suppressions below: `impl` and the shape ints are static host
    # config, never tracers — the taint analysis only flags them because
    # cost profiling (telemetry/costs.py lstm_route_cost) jits this
    # dispatcher directly, making its params look trace-reachable.
    if impl == "auto":  # mtt: disable=TL102 -- impl is static host config, not a tracer; only cost profiling jits this dispatcher
        impl = (
            "xla"
            if os.environ.get("MT_TPU_DISABLE_PALLAS")
            else ("pallas" if jax.default_backend() == "tpu" else "xla")  # mtt: disable=TL102 -- backend name is host-side config, never traced
        )
    ell = len(w_hh_ts)
    n_t, batch = x1_proj.shape[0], x1_proj.shape[1]
    hidden = w_hh_ts[0].shape[0]
    itemsize = jnp.dtype(x1_proj.dtype).itemsize
    has_mask = masks is not None
    if impl in ("pallas", "interpret") and not stack_fits(  # mtt: disable=TL102 -- static shape/VMEM feasibility math on Python ints
        n_t, batch, hidden, ell, has_mask, itemsize
    ):
        if window_schedulable(batch, window_rows) and stack_fits(
            n_t, window_rows, hidden, ell, has_mask, itemsize
        ):
            interpret = impl == "interpret"
            pack = window_pack_width(
                batch,
                window_rows,
                lambda rows: stack_fits(
                    n_t, rows, hidden, ell, has_mask, itemsize
                ),
            )
            n_chunks = batch // (pack * window_rows)
            if masks is None:
                return _map_row_chunks(
                    lambda xs: _lstm_stack_pallas(
                        xs[0], weights, None, interpret
                    ),
                    n_chunks,
                    x1_proj,
                )
            return _map_row_chunks(
                lambda xs: _lstm_stack_pallas(
                    xs[0], weights, tuple(xs[1:]), interpret
                ),
                n_chunks,
                x1_proj,
                *masks,
            )
        impl = "xla"
    if impl in ("pallas", "interpret"):  # mtt: disable=TL102 -- impl is static host config, not a tracer
        return _lstm_stack_pallas(x1_proj, weights, masks, impl == "interpret")
    if impl == "xla":  # mtt: disable=TL102 -- impl is static host config, not a tracer
        return lstm_stack_xla(x1_proj, weights, masks)
    raise ValueError(f"unknown lstm impl: {impl!r}")


def lstm_pair_recurrence(
    x1_proj: jax.Array,
    w_hh1_t: jax.Array,
    w_ih2_t: jax.Array,
    bias2: jax.Array,
    w_hh2_t: jax.Array,
    mask: jax.Array | None = None,
    impl: str = "auto",
    window_rows: int | None = None,
) -> jax.Array:
    """Run TWO stacked LSTM layers as one fused wavefront recurrence.

    Args:
        x1_proj: ``(T, B, 4H)`` time-major layer-1 input projections
            (``x @ w_ihᵀ`` plus both biases), gate order i, f, g, o.
        w_hh1_t: ``(H, 4H)`` transposed layer-1 recurrent weight.
        w_ih2_t: ``(H, 4H)`` transposed layer-2 input weight.
        bias2: ``(4H,)`` layer-2 combined bias (``b_ih + b_hh``).
        w_hh2_t: ``(H, 4H)`` transposed layer-2 recurrent weight.
        mask: optional ``(T, B, H)`` inter-layer dropout mask (already
            scaled by ``1/(1-p)``), applied to layer-1 outputs before the
            layer-2 projection. ``None`` (deterministic / dropout=0) runs
            the maskless kernel variant — no mask plane in VMEM.
        impl: ``"pallas"`` | ``"xla"`` | ``"interpret"`` | ``"auto"``.
        window_rows: rows per window when B is a flattened window stack;
            batches past the pair's VMEM budget are then scheduled
            window-per-program (fused kernel kept) instead of degrading to
            the scan formulation.

    Returns:
        ``(T, B, H)`` layer-2 hidden states for every timestep.
    """
    if impl == "auto":
        impl = (
            "xla"
            if os.environ.get("MT_TPU_DISABLE_PALLAS")
            else ("pallas" if jax.default_backend() == "tpu" else "xla")
        )
    n_t, b = x1_proj.shape[0], x1_proj.shape[1]
    hidden = w_hh1_t.shape[0]
    has_mask = mask is not None
    itemsize = jnp.dtype(x1_proj.dtype).itemsize
    if impl in ("pallas", "interpret") and not pair_fits(
        n_t, b, hidden, has_mask=has_mask, itemsize=itemsize
    ):
        if window_schedulable(b, window_rows) and pair_fits(
            n_t, window_rows, hidden, has_mask=has_mask, itemsize=itemsize
        ):
            interpret = impl == "interpret"
            pack = window_pack_width(
                b,
                window_rows,
                lambda rows: pair_fits(
                    n_t, rows, hidden, has_mask=has_mask, itemsize=itemsize
                ),
            )
            n_chunks = b // (pack * window_rows)
            if mask is None:
                return _map_row_chunks(
                    lambda xs: _lstm_pair_pallas_nomask(
                        xs[0], w_hh1_t, w_ih2_t, bias2, w_hh2_t, interpret
                    ),
                    n_chunks,
                    x1_proj,
                )
            return _map_row_chunks(
                lambda xs: _lstm_pair_pallas(
                    xs[0], w_hh1_t, w_ih2_t, bias2, w_hh2_t, xs[1], interpret
                ),
                n_chunks,
                x1_proj,
                mask,
            )
        impl = "xla"  # residual stash would not fit one VMEM program
    if impl in ("pallas", "interpret"):
        interpret = impl == "interpret"
        if mask is None:
            return _lstm_pair_pallas_nomask(
                x1_proj, w_hh1_t, w_ih2_t, bias2, w_hh2_t, interpret
            )
        return _lstm_pair_pallas(
            x1_proj, w_hh1_t, w_ih2_t, bias2, w_hh2_t, mask, interpret
        )
    if impl == "xla":
        return lstm_pair_xla(x1_proj, w_hh1_t, w_ih2_t, bias2, w_hh2_t, mask)
    raise ValueError(f"unknown lstm impl: {impl!r}")


# ------------------------------------------- window-granular row scheduling
#
# Batched training flattens (B windows x K stocks) into B*K rows, and past
# ~104 rows the kernels above fall off the single-program path onto a 32-row
# tiled grid whose per-step matmuls are 3x further below MXU tile efficiency
# — RESULTS.md's measured bs>1 throughput cliff. But the rows of a batch are
# not anonymous: they come in K-row windows, and ONE window is exactly the
# shape the single-program path already runs best (the reference's cuDNN
# LSTM batches flat because its kernel tiles internally; reference:
# src/model.py:88-94). A Pallas grid executes sequentially on the core
# anyway, so scheduling the batch as a ``lax.map`` over windows — each
# iteration one single-program kernel at the window's own row count — keeps
# every recurrent matmul at the ~104-row MXU shape and recovers flat
# per-window cost. When K rows per window sits well below the VMEM budget,
# ``window_pack_width`` packs several whole windows into one program (one
# wavefront over the concatenated row axis — rows are independent, so the
# packed result is bitwise the per-window result) so small-universe batches
# don't pay one program launch per window. Callers that know the window
# size (the train/eval steps flatten it themselves) pass ``window_rows``;
# without it behavior is unchanged.


def _map_row_chunks(fn, n_chunks: int, *arrays):
    """Run ``fn`` over ``n_chunks`` equal row-chunks of time-major arrays.

    Each array is ``(T, B, X)``; ``fn`` receives one ``(T, B/n, X)`` chunk
    per array and returns ``(T, B/n, H)``; chunks are restitched to
    ``(T, B, H)``. ``lax.map`` keeps the chunk programs sequential — the
    recurrence is latency-bound, so there is no parallelism to lose."""
    t = arrays[0].shape[0]
    b = arrays[0].shape[1]
    win = b // n_chunks
    chunked = tuple(
        a.reshape(t, n_chunks, win, a.shape[2]).swapaxes(0, 1)
        for a in arrays
    )
    out = lax.map(fn, chunked)
    return out.swapaxes(0, 1).reshape(t, b, out.shape[-1])


def window_schedulable(b: int, window_rows: int | None) -> bool:
    return (
        window_rows is not None
        and 0 < window_rows < b
        and b % window_rows == 0
    )


def window_pack_width(b: int, window_rows: int | None, fits) -> int:
    """Windows per Pallas program under a VMEM feasibility predicate.

    One window per program keeps the recurrent matmuls at good MXU shapes,
    but when K rows per window is far below the single-program row budget
    (small universes), serializing one K-row program per window leaves the
    budget idle and pays a program launch per window. Packing p windows
    into one program — one wavefront over the concatenated row axis, legal
    because rows are independent across the batch dim — gives flat
    per-window cost up to the budget.

    Returns the largest ``p`` dividing the window count with
    ``fits(p * window_rows)`` true (``fits`` is the caller's byte-model
    check at a row count: single_layer_fits / pair_fits / stack_fits plus
    any row-cap). Degenerates to 1 — today's serial window-per-program
    schedule — when nothing larger fits; callers never lose the fallback.
    """
    if not window_schedulable(b, window_rows):
        return 1
    n_windows = b // window_rows
    best = 1
    for p in range(2, n_windows + 1):
        # Static host-side scheduling math (ints); flagged only because
        # cost profiling jits the dispatchers that call this.
        if n_windows % p == 0 and fits(p * window_rows):  # mtt: disable=TL102 -- static host-side scheduling math on Python ints
            best = p
    return best


# -------------------------------------------------------------- public API


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _lstm_recurrence_pallas(x_proj, w_hh_t, interpret=False):
    hs, _ = _fwd_pallas(x_proj, w_hh_t, interpret=interpret)
    return hs


def _vjp_fwd(x_proj, w_hh_t, interpret):
    return _fwd_pallas(x_proj, w_hh_t, interpret=interpret)


_lstm_recurrence_pallas.defvjp(_vjp_fwd, _bwd_pallas)


def lstm_recurrence_xla(x_proj: jax.Array, w_hh_t: jax.Array) -> jax.Array:
    """Reference formulation: ``lax.scan`` over time (XLA-fused fallback)."""
    b = x_proj.shape[1]
    hidden = w_hh_t.shape[0]
    carry0 = (
        jnp.zeros((b, hidden), x_proj.dtype),
        jnp.zeros((b, hidden), x_proj.dtype),
    )

    def step(carry, xt):
        h, c = carry
        i, f, g, o = _gate_math(xt + h @ w_hh_t)
        c = f * c + i * g
        h = o * jnp.tanh(c)
        return (h, c), h

    _, hs = lax.scan(step, carry0, x_proj)
    return hs


def lstm_recurrence(
    x_proj: jax.Array,
    w_hh_t: jax.Array,
    impl: str = "auto",
    window_rows: int | None = None,
) -> jax.Array:
    """Run the LSTM time recurrence over pre-projected inputs.

    Args:
        x_proj: ``(T, B, 4H)`` time-major input projections (``x @ w_ihᵀ``
            plus both biases), gate order i, f, g, o as in ``torch.nn.LSTM``.
        w_hh_t: ``(H, 4H)`` transposed recurrent weight.
        impl: ``"pallas"`` | ``"xla"`` | ``"interpret"`` | ``"auto"``
            (pallas on TPU, xla elsewhere).
        window_rows: rows per window when the B axis is a flattened stack
            of independent windows; batches past the single-program limit
            are then scheduled window-per-program instead of falling onto
            the 32-row tiled grid (see the window-granular section above).

    Returns:
        ``(T, B, H)`` hidden states for every timestep.
    """
    if impl == "auto":
        impl = (
            "xla"
            if os.environ.get("MT_TPU_DISABLE_PALLAS")
            else ("pallas" if jax.default_backend() == "tpu" else "xla")
        )
    if impl in ("pallas", "interpret"):
        interpret = impl == "interpret"
        n_t, b = x_proj.shape[0], x_proj.shape[1]
        hidden = w_hh_t.shape[0]
        itemsize = jnp.dtype(x_proj.dtype).itemsize
        if (
            -(-b // 8) * 8 > SINGLE_TILE_MAX_ROWS
            and window_schedulable(b, window_rows)
            and -(-window_rows // 8) * 8 <= SINGLE_TILE_MAX_ROWS
            and single_layer_fits(n_t, window_rows, hidden, itemsize)
        ):
            pack = window_pack_width(
                b,
                window_rows,
                lambda rows: -(-rows // 8) * 8 <= SINGLE_TILE_MAX_ROWS
                and single_layer_fits(n_t, rows, hidden, itemsize),
            )
            return _map_row_chunks(
                lambda xs: _lstm_recurrence_pallas(xs[0], w_hh_t, interpret),
                b // (pack * window_rows),
                x_proj,
            )
        if single_layer_fits(n_t, b, hidden, itemsize):
            return _lstm_recurrence_pallas(x_proj, w_hh_t, interpret)
        # Long-lookback: full-T VMEM planes don't fit at any row tile —
        # run the time-blocked kernel (h/c carried across sequential grid
        # steps; VMEM holds one T-chunk at a time).
        return _lstm_recurrence_tblocked(x_proj, w_hh_t, interpret)
    if impl == "xla":
        return lstm_recurrence_xla(x_proj, w_hh_t)
    raise ValueError(f"unknown lstm impl: {impl!r}")


def route_plan(
    n_t: int,
    b: int,
    hidden: int,
    n_layers: int = 2,
    *,
    has_mask: bool = False,
    itemsize: int = 4,
    window_rows: int | None = None,
    backend: str | None = None,
) -> dict:
    """The routing decision the recurrence dispatchers would take, as data.

    Mirrors the ``impl="auto"`` predicates of :func:`lstm_recurrence`
    (``n_layers == 1``) and :func:`lstm_stack_recurrence` (deeper stacks)
    without building any program: which implementation runs at this shape
    on this backend, how many windows pack per Pallas program, and what
    the VMEM byte model predicts for the per-program footprint next to the
    budget it is held against. Telemetry (``telemetry/costs.py``) emits
    this plan alongside the compiler-reported actual temp bytes so the
    byte model stays auditable against the compiler instead of trusted
    blindly. ``backend=None`` reads the live default backend.
    """
    if backend is None:
        backend = jax.default_backend()
    pallas = backend == "tpu" and not os.environ.get("MT_TPU_DISABLE_PALLAS")
    b_pad = -(-b // 8) * 8
    plan = {
        "n_t": n_t,
        "rows": b,
        "rows_padded": b_pad,
        "hidden": hidden,
        "n_layers": n_layers,
        "has_mask": has_mask,
        "itemsize": itemsize,
        "window_rows": window_rows,
        "backend": backend,
        "vmem_budget_bytes": _PAIR_VMEM_BUDGET,
        "pack_width": 1,
    }
    if n_layers == 1:
        fits = lambda rows: single_layer_fits(n_t, rows, hidden, itemsize)  # noqa: E731
        rows_per_program = b
        if not pallas:
            route = "xla-scan"
        elif (
            b_pad > SINGLE_TILE_MAX_ROWS
            and window_schedulable(b, window_rows)
            and -(-window_rows // 8) * 8 <= SINGLE_TILE_MAX_ROWS
            and fits(window_rows)
        ):
            route = "pallas-packed"
            plan["pack_width"] = window_pack_width(
                b,
                window_rows,
                lambda rows: -(-rows // 8) * 8 <= SINGLE_TILE_MAX_ROWS
                and fits(rows),
            )
            rows_per_program = plan["pack_width"] * window_rows
        elif fits(b):
            route = "pallas-single"
        else:
            route = "pallas-timeblocked"
        predicted = _single_layer_vmem_bytes(n_t, rows_per_program, hidden,
                                             itemsize)
    else:
        fits = lambda rows: stack_fits(  # noqa: E731
            n_t, rows, hidden, n_layers, has_mask, itemsize
        )
        rows_per_program = b
        if not pallas:
            route = "xla-scan"
        elif fits(b):
            route = "pallas-resident"
        elif window_schedulable(b, window_rows) and fits(window_rows):
            route = "pallas-packed"
            plan["pack_width"] = window_pack_width(b, window_rows, fits)
            rows_per_program = plan["pack_width"] * window_rows
        else:
            route = "xla-scan"  # stack budget blown at every window shape
        predicted = _stack_bwd_vmem_bytes(
            n_t, -(-rows_per_program // 8) * 8, hidden, n_layers, has_mask,
            itemsize,
        )
    plan["route"] = route
    plan["rows_per_program"] = rows_per_program
    plan["predicted_vmem_bytes"] = predicted
    plan["fits"] = predicted <= _PAIR_VMEM_BUDGET
    return plan
