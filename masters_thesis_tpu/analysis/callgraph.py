"""Jit-reachability call graph over a set of Python sources.

The AST rules need to know which functions execute *under a JAX trace*:
``float(x)`` is perfectly fine in the trainer's host loop and a correctness
bug inside the scan-epoch program. Runtime introspection can't answer this
(the lint must run without building the model), so we approximate it
statically:

1. **Seeds** — a function is trace-context if it is decorated with a JIT
   wrapper (``jax.jit``, ``partial(jax.jit, ...)``) or its *name* is passed
   to a wrapper call (``jax.jit(f)``, ``shard_map(f, ...)``,
   ``lax.scan(f, ...)``, ``jax.vmap(f)``, ``jax.value_and_grad(f)``, ...).
2. **Propagation** — anything a trace-context function calls (resolvable
   within the analysed sources, through same-module names or package
   imports) is trace-context, as are its nested ``def``s.

Name-based resolution is deliberately conservative-toward-marking: two
functions sharing a name both get marked. False *negatives* (a function
called only through a variable or a method) are accepted — the lint's
contract is high precision on what it does flag.
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path

# Call targets whose function-valued arguments execute under trace.
JIT_WRAPPERS = {
    "jit",
    "pjit",
    "pmap",
    "vmap",
    "grad",
    "value_and_grad",
    "scan",
    "while_loop",
    "fori_loop",
    "cond",
    "switch",
    "shard_map",
    "checkpoint",
    "remat",
    "custom_vjp",
    "custom_jvp",
    "pallas_call",
    "named_call",
}


def dotted_name(node: ast.AST) -> str | None:
    """'jax.lax.scan' for nested Attribute/Name chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_jit_wrapper(callee: str | None) -> bool:
    return callee is not None and callee.split(".")[-1] in JIT_WRAPPERS


@dataclasses.dataclass
class FunctionInfo:
    key: str  # "<module>:<qualpath>"
    name: str  # bare def name
    module: str
    node: ast.FunctionDef
    params: list[str]
    calls: set[str] = dataclasses.field(default_factory=set)
    children: list[str] = dataclasses.field(default_factory=list)
    seeded: bool = False


class _ModuleCollector(ast.NodeVisitor):
    def __init__(self, module: str, graph: "CallGraph"):
        self.module = module
        self.graph = graph
        self.stack: list[str] = []

    # ------------------------------------------------------------- imports

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module:
            for alias in node.names:
                local = alias.asname or alias.name
                self.graph.imports[self.module][local] = (
                    f"{node.module}.{alias.name}"
                )
        self.generic_visit(node)

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            local = alias.asname or alias.name
            self.graph.imports[self.module][local] = alias.name
        self.generic_visit(node)

    # ----------------------------------------------------------- functions

    def _handle_def(self, node: ast.FunctionDef) -> None:
        qual = ".".join(self.stack + [node.name])
        key = f"{self.module}:{qual}"
        params = [a.arg for a in node.args.args] + [
            a.arg for a in node.args.kwonlyargs
        ]
        if node.args.vararg:
            params.append(node.args.vararg.arg)
        info = FunctionInfo(
            key=key, name=node.name, module=self.module, node=node,
            params=params,
        )
        for dec in node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            callee = dotted_name(target)
            if _is_jit_wrapper(callee):
                info.seeded = True
            # @partial(jax.jit, ...) / @functools.partial(jax.jit, ...)
            if (
                isinstance(dec, ast.Call)
                and callee is not None
                and callee.split(".")[-1] == "partial"
                and dec.args
                and _is_jit_wrapper(dotted_name(dec.args[0]))
            ):
                info.seeded = True
        self.graph.functions[key] = info
        self.graph.by_name.setdefault((self.module, node.name), []).append(key)
        if self.stack:
            parent = f"{self.module}:{'.'.join(self.stack)}"
            if parent in self.graph.functions:
                self.graph.functions[parent].children.append(key)
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    visit_FunctionDef = _handle_def
    visit_AsyncFunctionDef = _handle_def

    # --------------------------------------------------------------- calls

    def visit_Call(self, node: ast.Call) -> None:
        callee = dotted_name(node.func)
        if self.stack:
            current = f"{self.module}:{'.'.join(self.stack)}"
            if callee is not None:
                self.graph.functions[current].calls.add(callee)
        if _is_jit_wrapper(callee):
            # Every plain-name argument of a jit wrapper call is a seed:
            # jax.jit(f), shard_map(local_epoch, ...), lax.scan(step, ...).
            for arg in node.args:
                name = dotted_name(arg)
                if name is not None and "." not in name:
                    self.graph.seed_names.add((self.module, name))
        self.generic_visit(node)


class CallGraph:
    """Package-wide function index with trace-context propagation."""

    def __init__(self) -> None:
        self.functions: dict[str, FunctionInfo] = {}
        self.by_name: dict[tuple[str, str], list[str]] = {}
        self.imports: dict[str, dict[str, str]] = {}
        self.seed_names: set[tuple[str, str]] = set()
        self.modules: dict[str, Path] = {}

    # ------------------------------------------------------------ building

    @classmethod
    def build(cls, trees: dict[str, tuple[Path, ast.AST]]) -> "CallGraph":
        """``trees``: module name -> (path, parsed AST)."""
        graph = cls()
        for module, (path, tree) in trees.items():
            graph.modules[module] = path
            graph.imports.setdefault(module, {})
            _ModuleCollector(module, graph).visit(tree)
        graph._propagate()
        return graph

    # ---------------------------------------------------------- resolution

    def _resolve(self, module: str, callee: str) -> list[str]:
        """Keys of analysed functions a call name may refer to."""
        imports = self.imports.get(module, {})
        head, _, rest = callee.partition(".")
        if not rest:
            # Bare name: same-module def, or `from X import name`.
            hits = list(self.by_name.get((module, callee), []))
            target = imports.get(callee)
            if target is not None:
                t_mod, _, t_name = target.rpartition(".")
                hits += self.by_name.get((t_mod, t_name), [])
            return hits
        # Dotted: `import X as head; head.rest()`.
        target_mod = imports.get(head)
        if target_mod is not None:
            return list(self.by_name.get((target_mod, rest), []))
        return []

    # --------------------------------------------------------- propagation

    def _propagate(self) -> None:
        work: list[str] = []
        for (module, name) in self.seed_names:
            work.extend(self.by_name.get((module, name), []))
        work.extend(k for k, f in self.functions.items() if f.seeded)
        traced: set[str] = set()
        while work:
            key = work.pop()
            if key in traced:
                continue
            traced.add(key)
            info = self.functions[key]
            info.seeded = True
            work.extend(info.children)
            for callee in info.calls:
                work.extend(self._resolve(info.module, callee))
        self._traced = traced

    def traced_functions(self) -> set[str]:
        return set(self._traced)

    def is_traced(self, key: str) -> bool:
        return key in self._traced
