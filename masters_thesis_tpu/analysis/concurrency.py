"""Pass 3a — static concurrency lint over the stdlib-threaded host stack.

The TL/TA/SV/CP rules guard everything *traced and compiled*; this pass
guards the host-side threads that feed them — fleet dispatch, the deadline
queue, flight-recorder heartbeat/signal handlers, the supervisor, circuit
breakers. It builds, purely from the AST:

- a **lock inventory** — ``self.X = threading.Lock()/RLock()/Condition()``
  attributes per class, plus module-level locks (queues and thread attrs
  ride along for the blocking-call and lifecycle rules);
- a **thread-spawn graph** — ``threading.Thread(target=...)`` /
  ``Timer(...)`` sites with daemon flags and storage bindings, plus
  ``signal.signal(...)`` handler registrations;
- a **call graph** (reusing :mod:`callgraph` for imports and module-level
  resolution, extended with class-aware method resolution: ``self.m()``
  binds to the enclosing class, annotated parameters (``replica:
  Replica``) bind ``replica.m()`` to that class, and otherwise a method
  name resolves only when exactly one analysed class defines it and the
  name is not a stdlib-common method like ``get``/``put``/``update``).

Rules (ids registered in :mod:`findings`):

- **CL501** lock-order inversion: a cycle in the acquires-while-holding
  graph (lock A held while B is acquired on one path, B while A on
  another — including transitively through calls), or a re-acquire of a
  non-reentrant ``Lock``. Bounded acquires (``acquire(timeout=...)`` /
  ``acquire(False)``) never form edges — a trylock recovers.
- **CL502** unguarded shared state: an attribute of a *concurrency-
  involved* class (spawns threads, owns a lock, or has thread-reachable
  methods) is read-modify-written outside any lock, or accessed without
  the lock that dominates (guards the majority of) its other accesses.
  ``__init__`` bodies are exempt — construction happens-before the object
  is shared.
- **CL503** blocking call under a held lock: file I/O, ``subprocess``,
  ``time.sleep``, queue/event waits, thread joins, device compute
  (``.predict``/``.warmup``/``block_until_ready``) while any lock is
  held, including transitively through resolvable calls. ``cond.wait()``
  while holding *that* condition is the correct idiom and exempt.
- **CL504** non-signal-safe work in signal-handler-reachable code: a
  blocking (unbounded) lock acquire, sleep, join, or wait. CPython runs
  handlers on the main thread between bytecodes, so a blocking acquire of
  a lock the interrupted frame already holds is a self-deadlock. File I/O
  is deliberately *not* flagged here: the flight recorder's entire job on
  SIGTERM is to write the crashdump.
- **CL505** thread lifecycle: a non-daemon thread never joined, or a
  thread spawned in ``__init__`` whose class has no join/stop path.

Precision over recall, like Pass 1: what the analysis cannot resolve it
does not flag. ``# mtt: disable=CL50x -- reason`` suppresses deliberate
exceptions per line; this pass also owns the ``SP001`` suppression-hygiene
scan (reason-less suppressions) for the whole file set it analyses.
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path

from masters_thesis_tpu.analysis.astlint import _module_name, discover_files
from masters_thesis_tpu.analysis.callgraph import CallGraph, dotted_name
from masters_thesis_tpu.analysis.findings import (
    Finding,
    is_suppressed,
    suppressed_rules_by_line,
    suppression_findings,
)

# Constructors that create a lock-like object (value side of an
# inventory assignment), after import-alias resolution.
LOCK_CTORS = {
    "threading.Lock": "lock",
    "threading.RLock": "rlock",
    "threading.Condition": "cond",  # default wraps an RLock -> reentrant
    "threading.Semaphore": "sem",
    "threading.BoundedSemaphore": "sem",
}
QUEUE_CTORS = {
    "queue.Queue", "queue.LifoQueue", "queue.PriorityQueue",
    "queue.SimpleQueue",
}
# Attrs holding these are synchronization plumbing, not shared *data*:
# an Event IS the cross-thread signal, so reading it unlocked is the
# entire point and CL502 must not group it with guarded state.
SYNC_CTORS = {"threading.Event", "threading.Barrier"}
THREAD_CTORS = {"threading.Thread", "threading.Timer"}
REENTRANT = {"rlock", "cond"}

# Method names too common to resolve by name alone (dict.get, list.append,
# str.join, set.add, Event.set, ... would all mis-bind).
AMBIGUOUS_METHOD_NAMES = {
    "get", "put", "update", "items", "keys", "values", "append", "pop",
    "add", "close", "join", "wait", "set", "clear", "copy", "extend",
    "remove", "insert", "sort", "read", "write", "open", "start", "run",
    "result", "acquire", "release", "notify", "notify_all", "is_set",
    "format", "strip", "split", "encode", "decode", "mkdir", "exists",
    "resolve", "touch", "unlink", "flush", "send", "recv", "name", "main",
}

# Direct blocking operations, by fully-resolved dotted name. Category
# "sync" can deadlock (CL503 + CL504); "io"/"compute" merely stall the
# lock (CL503 only).
BLOCKING_CALLS = {
    "time.sleep": ("time.sleep", "sync"),
    "os.system": ("os.system", "sync"),
    "os.waitpid": ("os.waitpid", "sync"),
    "subprocess.run": ("subprocess.run", "sync"),
    "subprocess.call": ("subprocess.call", "sync"),
    "subprocess.check_call": ("subprocess.check_call", "sync"),
    "subprocess.check_output": ("subprocess.check_output", "sync"),
    "open": ("open()", "io"),
    "os.replace": ("os.replace", "io"),
    "os.fsync": ("os.fsync", "io"),
    "shutil.copy": ("shutil.copy", "io"),
    "shutil.copytree": ("shutil.copytree", "io"),
    "shutil.move": ("shutil.move", "io"),
}
# Blocking *method* names (matched on the final attribute); `.join` only
# fires on receivers that resolve to a known thread binding, `.get` only
# on known queue attrs (never dict.get), and `.wait` while holding the
# same condition is exempt — handled in _blocking_method().
BLOCKING_METHODS = {
    "read_text": ("file read", "io"),
    "write_text": ("file write", "io"),
    "read_bytes": ("file read", "io"),
    "write_bytes": ("file write", "io"),
    "communicate": ("process wait", "sync"),
    "predict": ("device compute", "compute"),
    "warmup": ("device compute", "compute"),
    "block_until_ready": ("device sync", "compute"),
}

# lock identity: ("C", class_name, attr) | ("M", module, name)
LockId = tuple[str, str, str]


@dataclasses.dataclass
class Acq:
    lock: LockId
    line: int
    held: tuple[LockId, ...]
    bounded: bool


@dataclasses.dataclass
class CallSite:
    callee: str
    line: int
    held: tuple[LockId, ...]


@dataclasses.dataclass
class Access:
    owner: str  # class name
    attr: str
    line: int
    held: tuple[LockId, ...]
    write: bool
    rmw: bool


@dataclasses.dataclass
class Block:
    desc: str
    category: str
    line: int
    held: tuple[LockId, ...]


@dataclasses.dataclass
class Spawn:
    target: str | None  # dotted call-target name, e.g. "self._worker_loop"
    daemon: bool | None  # None = not statically known
    line: int
    binding: tuple[str, str] | None  # (class, attr) the thread is stored on
    in_init: bool
    kind: str  # "Thread" | "Timer"


@dataclasses.dataclass
class ClassFacts:
    module: str
    name: str
    path: str
    locks: dict[str, str] = dataclasses.field(default_factory=dict)
    queues: set[str] = dataclasses.field(default_factory=set)
    thread_attrs: set[str] = dataclasses.field(default_factory=set)
    sync_attrs: set[str] = dataclasses.field(default_factory=set)
    attrs: set[str] = dataclasses.field(default_factory=set)
    spawns_threads: bool = False


@dataclasses.dataclass
class FuncFacts:
    key: str
    module: str
    cls: str | None
    name: str
    path: str
    param_types: dict[str, str]  # param -> analysed class name
    acquires: list[Acq] = dataclasses.field(default_factory=list)
    calls: list[CallSite] = dataclasses.field(default_factory=list)
    accesses: list[Access] = dataclasses.field(default_factory=list)
    blocking: list[Block] = dataclasses.field(default_factory=list)
    spawns: list[Spawn] = dataclasses.field(default_factory=list)
    handlers: list[tuple[str, int]] = dataclasses.field(default_factory=list)


class _Inventory:
    """Package-wide class/lock/queue/thread-attr inventory."""

    def __init__(self) -> None:
        self.classes: dict[str, ClassFacts] = {}  # class name -> facts
        self.methods: dict[str, list[str]] = {}  # method name -> func keys
        self.attr_owner: dict[str, str | None] = {}  # attr -> unique class

    def klass(self, module: str, name: str, path: str) -> ClassFacts:
        if name not in self.classes:
            self.classes[name] = ClassFacts(module, name, path)
        return self.classes[name]

    def note_attr(self, cls: str, attr: str) -> None:
        self.classes[cls].attrs.add(attr)
        if attr not in self.attr_owner:
            self.attr_owner[attr] = cls
        elif self.attr_owner[attr] != cls:
            self.attr_owner[attr] = None  # ambiguous across classes


def _ctor_fullname(node: ast.AST, imports: dict[str, str]) -> str | None:
    """Import-alias-resolved dotted name of a call target."""
    name = dotted_name(node)
    if name is None:
        return None
    head, _, rest = name.partition(".")
    target = imports.get(head)
    if target is not None:
        return f"{target}.{rest}" if rest else target
    return name


@dataclasses.dataclass
class _FnDef:
    """One function definition with its *class* context.

    The shared ``callgraph.py`` indexes methods under their bare name
    (``module:__init__``), which collides across classes — fine for the
    jit-reachability pass it serves, fatal for lock attribution. This
    pass therefore enumerates functions itself: methods get
    ``module:Class.method`` keys and an explicit ``cls``; defs nested
    inside a method inherit its class (they close over ``self``).
    """

    key: str
    module: str
    cls: str | None
    name: str
    node: ast.FunctionDef


def _collect_functions(
    trees: dict[str, tuple[Path, ast.AST]],
) -> dict[str, _FnDef]:
    defs: dict[str, _FnDef] = {}

    def walk(node, module, quals: list[str], cls: str | None):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                walk(child, module, quals + [child.name], child.name)
            elif isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                q = quals + [child.name]
                key = f"{module}:{'.'.join(q)}"
                defs[key] = _FnDef(key, module, cls, child.name, child)
                walk(child, module, q, cls)

    for module, (_path, tree) in trees.items():
        walk(tree, module, [], None)
    return defs


def _collect_inventory(
    graph: CallGraph, trees: dict[str, tuple[Path, ast.AST]]
) -> _Inventory:
    inv = _Inventory()
    for module, (path, tree) in trees.items():
        imports = graph.imports.get(module, {})
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef):
                continue
            facts = inv.klass(module, node.name, str(path))
            for sub in ast.walk(node):
                targets: list[ast.AST] = []
                value: ast.AST | None = None
                if isinstance(sub, ast.Assign):
                    targets, value = sub.targets, sub.value
                elif isinstance(sub, (ast.AnnAssign, ast.AugAssign)):
                    targets, value = [sub.target], sub.value
                for tgt in targets:
                    if not (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    ):
                        continue
                    inv.note_attr(node.name, tgt.attr)
                    if isinstance(value, ast.Call):
                        full = _ctor_fullname(value.func, imports)
                        if full in LOCK_CTORS:
                            facts.locks[tgt.attr] = LOCK_CTORS[full]
                        elif full in QUEUE_CTORS:
                            facts.queues.add(tgt.attr)
                        elif full in THREAD_CTORS:
                            facts.thread_attrs.add(tgt.attr)
                        elif full in SYNC_CTORS:
                            facts.sync_attrs.add(tgt.attr)
    return inv


class _Resolver:
    """Class-aware call/lock resolution on top of the module-level graph."""

    def __init__(
        self, graph: CallGraph, inv: _Inventory, defs: dict[str, _FnDef]
    ):
        self.graph = graph
        self.inv = inv
        self.defs = defs

    def resolve_call(self, callee: str, fn: FuncFacts) -> list[str]:
        head, _, rest = callee.partition(".")
        last = callee.split(".")[-1]
        if not rest:
            # Bare name: the shared graph resolves through imports and
            # by_name; keep only hits that exist in *our* class-qualified
            # table (methods indexed under bare names drop out here).
            hits = self.graph._resolve(fn.module, callee)
            return [h for h in hits if h in self.defs]
        if head == "self" and fn.cls is not None:
            key = f"{fn.module}:{fn.cls}.{rest}"
            if "." not in rest and key in self.defs:
                return [key]
            return self._by_method_name(last)
        ann = fn.param_types.get(head)
        if ann is not None and "." not in rest:
            facts = self.inv.classes.get(ann)
            if facts is not None:
                key = f"{facts.module}:{ann}.{rest}"
                if key in self.defs:
                    return [key]
                return []
        hits = [
            h
            for h in self.graph._resolve(fn.module, callee)
            if h in self.defs
        ]
        if hits:
            return hits
        return self._by_method_name(last)

    def _by_method_name(self, name: str) -> list[str]:
        if name in AMBIGUOUS_METHOD_NAMES or name.startswith("__"):
            return []
        keys = self.inv.methods.get(name, [])
        return keys if len(keys) == 1 else []

    def lock_of(self, expr: ast.AST, fn: FuncFacts) -> LockId | None:
        name = dotted_name(expr)
        if name is None:
            return None
        parts = name.split(".")
        if len(parts) == 2:
            base, attr = parts
            if base == "self" and fn.cls is not None:
                facts = self.inv.classes.get(fn.cls)
                if facts is not None and attr in facts.locks:
                    return ("C", fn.cls, attr)
                return None
            ann = fn.param_types.get(base)
            if ann is not None:
                facts = self.inv.classes.get(ann)
                if facts is not None and attr in facts.locks:
                    return ("C", ann, attr)
            owner = self.inv.attr_owner.get(attr)
            if owner is not None and attr in self.inv.classes[owner].locks:
                return ("C", owner, attr)
            return None
        if len(parts) == 1:
            # Module-level lock: `_LOCK = threading.Lock()` at top level.
            mod_locks = _MODULE_LOCKS.get(fn.module, {})
            if parts[0] in mod_locks:
                return ("M", fn.module, parts[0])
        return None

    def lock_kind(self, lock: LockId) -> str:
        scope, owner, attr = lock
        if scope == "C":
            return self.inv.classes[owner].locks.get(attr, "lock")
        return _MODULE_LOCKS.get(owner, {}).get(attr, "lock")

    def attr_access_owner(
        self, node: ast.Attribute, fn: FuncFacts
    ) -> str | None:
        """Class owning ``<base>.<attr>`` for a Name base, else None."""
        if not isinstance(node.value, ast.Name):
            return None
        base = node.value.id
        if base == "self":
            return fn.cls
        ann = fn.param_types.get(base)
        if ann is not None and ann in self.inv.classes:
            return ann if node.attr in self.inv.classes[ann].attrs else None
        # Unique-attr fallback for untyped locals (`for r in replicas:`):
        # only within the owning class's own module — cross-module name
        # collisions ("state", "completed") would mis-attribute.
        owner = self.inv.attr_owner.get(node.attr)
        if owner is not None and self.inv.classes[owner].module == fn.module:
            return owner
        return None


_MODULE_LOCKS: dict[str, dict[str, str]] = {}


def _collect_module_locks(
    graph: CallGraph, trees: dict[str, tuple[Path, ast.AST]]
) -> None:
    _MODULE_LOCKS.clear()
    for module, (_path, tree) in trees.items():
        imports = graph.imports.get(module, {})
        locks: dict[str, str] = {}
        for node in ast.iter_child_nodes(tree):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                full = _ctor_fullname(node.value.func, imports)
                if full in LOCK_CTORS:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            locks[tgt.id] = LOCK_CTORS[full]
        _MODULE_LOCKS[module] = locks


# --------------------------------------------------------------- function walk


class _FunctionWalker:
    """One pass over a function body tracking the held-lock context.

    Held regions come from ``with <lock>:`` blocks plus two explicit
    bounded-acquire idioms (the shapes the signal-safe flight-recorder
    path uses)::

        got = self._lock.acquire(timeout=0.5)
        try: ...            # held if got
        finally:
            if got: self._lock.release()

        if not self._lock.acquire(blocking=False):
            return
        ...rest of function held...
    """

    def __init__(
        self, fn: FuncFacts, node: ast.FunctionDef, res: _Resolver,
        imports: dict[str, str],
    ):
        self.fn = fn
        self.node = node
        self.res = res
        self.imports = imports
        self._local_threads: dict[str, Spawn] = {}

    def run(self) -> None:
        self._stmts(self.node.body, ())

    # ------------------------------------------------------------- statements

    def _stmts(self, body: list[ast.stmt], held: tuple[LockId, ...]) -> None:
        i = 0
        while i < len(body):
            stmt = body[i]
            consumed = self._acquire_idiom(body, i, held)
            if consumed:
                i += consumed
                continue
            self._stmt(stmt, held)
            i += 1

    def _acquire_idiom(
        self, body: list[ast.stmt], i: int, held: tuple[LockId, ...]
    ) -> int:
        """Handle the two bounded-acquire idioms; returns #stmts consumed."""
        stmt = body[i]
        # got = lock.acquire(timeout=..); try: ... finally: ... release()
        if (
            isinstance(stmt, ast.Assign)
            and isinstance(stmt.value, ast.Call)
            and isinstance(stmt.value.func, ast.Attribute)
            and stmt.value.func.attr == "acquire"
        ):
            lock = self.res.lock_of(stmt.value.func.value, self.fn)
            if lock is not None and i + 1 < len(body) and isinstance(
                body[i + 1], ast.Try
            ):
                self._record_acquire(stmt.value, lock, held)
                tr = body[i + 1]
                self._stmts(tr.body, held + (lock,))
                self._stmts(tr.finalbody, held)
                for h in tr.handlers:
                    self._stmts(h.body, held + (lock,))
                self._stmts(tr.orelse, held + (lock,))
                return 2
        # if not lock.acquire(...): return   -> remainder of body is held
        if isinstance(stmt, ast.If) and isinstance(stmt.test, ast.UnaryOp):
            test = stmt.test
            if (
                isinstance(test.op, ast.Not)
                and isinstance(test.operand, ast.Call)
                and isinstance(test.operand.func, ast.Attribute)
                and test.operand.func.attr == "acquire"
                and any(isinstance(s, ast.Return) for s in stmt.body)
            ):
                lock = self.res.lock_of(test.operand.func.value, self.fn)
                if lock is not None:
                    self._record_acquire(test.operand, lock, held)
                    self._stmts(stmt.body, held)
                    self._stmts(body[i + 1:], held + (lock,))
                    return len(body) - i
        return 0

    def _stmt(self, stmt: ast.stmt, held: tuple[LockId, ...]) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested defs analysed as their own functions
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            inner = held
            for item in stmt.items:
                self._exprs(item.context_expr, inner, stmt)
                lock = self.res.lock_of(item.context_expr, self.fn)
                if lock is not None:
                    self.fn.acquires.append(
                        Acq(lock, stmt.lineno, inner, bounded=False)
                    )
                    inner = inner + (lock,)
            self._stmts(stmt.body, inner)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._exprs(stmt.test, held, stmt)
            self._stmts(stmt.body, held)
            self._stmts(stmt.orelse, held)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._exprs(stmt.iter, held, stmt)
            self._exprs(stmt.target, held, stmt)
            self._stmts(stmt.body, held)
            self._stmts(stmt.orelse, held)
            return
        if isinstance(stmt, ast.Try):
            self._stmts(stmt.body, held)
            for h in stmt.handlers:
                self._stmts(h.body, held)
            self._stmts(stmt.orelse, held)
            self._stmts(stmt.finalbody, held)
            return
        self._exprs(stmt, held, stmt)

    # ------------------------------------------------------------ expressions

    def _exprs(
        self, root: ast.AST, held: tuple[LockId, ...], stmt: ast.stmt
    ) -> None:
        rmw_attrs = self._rmw_attrs(stmt)
        stack = [root]
        while stack:
            node = stack.pop()
            if isinstance(
                node,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                 ast.ClassDef),
            ):
                continue
            if isinstance(node, ast.Call):
                self._call(node, held)
            if isinstance(node, ast.Attribute):
                self._attribute(node, held, rmw_attrs)
            stack.extend(ast.iter_child_nodes(node))

    def _rmw_attrs(self, stmt: ast.stmt) -> set[tuple[str | None, str]]:
        """(base-name, attr) pairs written read-modify-write by ``stmt``:
        AugAssign targets, and plain assigns whose target attr also appears
        in the value (the EWMA ``self.x = a*v + (1-a)*self.x`` shape)."""
        out: set[tuple[str | None, str]] = set()
        if isinstance(stmt, ast.AugAssign) and isinstance(
            stmt.target, ast.Attribute
        ):
            tgt = stmt.target
            if isinstance(tgt.value, ast.Name):
                out.add((tgt.value.id, tgt.attr))
        if isinstance(stmt, ast.Assign):
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Attribute) and isinstance(
                    tgt.value, ast.Name
                ):
                    for sub in ast.walk(stmt.value):
                        if (
                            isinstance(sub, ast.Attribute)
                            and sub.attr == tgt.attr
                            and isinstance(sub.value, ast.Name)
                            and sub.value.id == tgt.value.id
                        ):
                            out.add((tgt.value.id, tgt.attr))
        return out

    def _attribute(
        self,
        node: ast.Attribute,
        held: tuple[LockId, ...],
        rmw_attrs: set[tuple[str | None, str]],
    ) -> None:
        owner = self.res.attr_access_owner(node, self.fn)
        if owner is None:
            return
        base = node.value.id if isinstance(node.value, ast.Name) else None
        write = isinstance(node.ctx, (ast.Store, ast.Del))
        rmw = (base, node.attr) in rmw_attrs
        self.fn.accesses.append(
            Access(owner, node.attr, node.lineno, held, write or rmw, rmw)
        )

    def _call(self, node: ast.Call, held: tuple[LockId, ...]) -> None:
        callee = dotted_name(node.func)
        full = _ctor_fullname(node.func, self.imports)
        # Thread spawn / signal registration.
        if full in THREAD_CTORS:
            self._spawn(node, full.rsplit(".", 1)[-1])
            return
        if full == "signal.signal" and len(node.args) >= 2:
            handler = dotted_name(node.args[1])
            if handler is not None:
                self.fn.handlers.append((handler, node.lineno))
        # Explicit .acquire() outside the recognised idioms still records
        # an acquisition event for the lock-order graph.
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "acquire"
        ):
            lock = self.res.lock_of(node.func.value, self.fn)
            if lock is not None:
                self._record_acquire(node, lock, held)
                return
        if callee is not None:
            self.fn.calls.append(CallSite(callee, node.lineno, held))
        blk = self._blocking(node, full, held)
        if blk is not None:
            self.fn.blocking.append(
                Block(blk[0], blk[1], node.lineno, held)
            )

    def _record_acquire(
        self, call: ast.Call, lock: LockId, held: tuple[LockId, ...]
    ) -> None:
        bounded = bool(call.args) or any(
            kw.arg in ("timeout", "blocking") for kw in call.keywords
        )
        self.fn.acquires.append(Acq(lock, call.lineno, held, bounded))

    def _spawn(self, node: ast.Call, kind: str) -> None:
        target: str | None = None
        daemon: bool | None = None
        for kw in node.keywords:
            if kw.arg == "target":
                target = dotted_name(kw.value)
            if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
                daemon = bool(kw.value.value)
        if kind == "Timer" and target is None and len(node.args) >= 2:
            target = dotted_name(node.args[1])
        spawn = Spawn(
            target, daemon, node.lineno, None,
            in_init=self.fn.name == "__init__", kind=kind,
        )
        self.fn.spawns.append(spawn)
        if self.fn.cls is not None and self.fn.cls in self.res.inv.classes:
            self.res.inv.classes[self.fn.cls].spawns_threads = True

    def _blocking(
        self, node: ast.Call, full: str | None, held: tuple[LockId, ...]
    ) -> tuple[str, str] | None:
        if full in BLOCKING_CALLS:
            return BLOCKING_CALLS[full]
        if not isinstance(node.func, ast.Attribute):
            return None
        name = node.func.attr
        recv = node.func.value
        if name in BLOCKING_METHODS:
            return BLOCKING_METHODS[name]
        if name == "sleep":
            return ("sleep", "sync")
        if name == "wait":
            # cond.wait() while holding that same condition is the idiom.
            lock = self.res.lock_of(recv, self.fn)
            if lock is not None and lock in held:
                return None
            return ("wait()", "sync")
        if name == "join":
            if self._is_thread_receiver(recv):
                return ("thread join", "sync")
            return None
        if name == "get":
            attr = recv.attr if isinstance(recv, ast.Attribute) else None
            if attr is not None and any(
                attr in c.queues for c in self.res.inv.classes.values()
            ):
                return ("queue get", "sync")
            return None
        return None

    def _is_thread_receiver(self, recv: ast.AST) -> bool:
        name = dotted_name(recv)
        if name is None:
            return False
        last = name.split(".")[-1]
        if name in self._local_threads:
            return True
        return any(
            last in c.thread_attrs for c in self.res.inv.classes.values()
        )


# ------------------------------------------------------------------- bindings


def _bind_spawns(fn: FuncFacts, node: ast.FunctionDef, inv: _Inventory) -> None:
    """Attach storage bindings to spawn sites: ``self.X = Thread(...)``,
    ``obj.X = Thread(...)`` (annotated param), or a local var that is later
    stored on an attribute. Also notes locally-joined locals."""
    local_spawn: dict[str, Spawn] = {}
    spawn_by_line = {s.line: s for s in fn.spawns}
    for stmt in ast.walk(node):
        if isinstance(stmt, ast.Assign):
            spawn = None
            if isinstance(stmt.value, ast.Call):
                spawn = spawn_by_line.get(stmt.value.lineno)
            elif isinstance(stmt.value, ast.Name):
                spawn = local_spawn.get(stmt.value.id)
            if spawn is None:
                continue
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name):
                    local_spawn[tgt.id] = spawn
                elif isinstance(tgt, ast.Attribute) and isinstance(
                    tgt.value, ast.Name
                ):
                    base = tgt.value.id
                    cls = (
                        fn.cls if base == "self"
                        else fn.param_types.get(base)
                    )
                    if cls is not None:
                        spawn.binding = (cls, tgt.attr)
                        inv.classes[cls].thread_attrs.add(tgt.attr)
        # Local `t.join()` marks the spawn joined within this function.
        if (
            isinstance(stmt, ast.Call)
            and isinstance(stmt.func, ast.Attribute)
            and stmt.func.attr == "join"
            and isinstance(stmt.func.value, ast.Name)
            and stmt.func.value.id in local_spawn
        ):
            local_spawn[stmt.func.value.id].binding = ("<local>", "joined")


def _param_types(
    node: ast.FunctionDef, inv: _Inventory
) -> dict[str, str]:
    out: dict[str, str] = {}
    args = node.args
    for a in args.args + args.posonlyargs + args.kwonlyargs:
        if a.annotation is None:
            continue
        ann = dotted_name(a.annotation)
        if ann is not None and ann.split(".")[-1] in inv.classes:
            out[a.arg] = ann.split(".")[-1]
    return out


# ----------------------------------------------------------------- reachability


def _reachable(
    entries: list[str], funcs: dict[str, FuncFacts], res: _Resolver
) -> set[str]:
    seen: set[str] = set()
    work = [k for k in entries if k in funcs]
    while work:
        key = work.pop()
        if key in seen:
            continue
        seen.add(key)
        fn = funcs[key]
        for call in fn.calls:
            for tgt in res.resolve_call(call.callee, fn):
                if tgt in funcs and tgt not in seen:
                    work.append(tgt)
    return seen


def _resolve_target(
    target: str | None, fn: FuncFacts, res: _Resolver
) -> list[str]:
    if target is None:
        return []
    return res.resolve_call(target, fn)


def _fixpoint_summaries(
    funcs: dict[str, FuncFacts], res: _Resolver
) -> tuple[dict[str, set[LockId]], dict[str, set[tuple[str, str]]]]:
    """Transitive (may_acquire, may_block) per function."""
    resolved_calls = {
        key: [
            tgt
            for call in fn.calls
            for tgt in res.resolve_call(call.callee, fn)
            if tgt in funcs
        ]
        for key, fn in funcs.items()
    }
    may_acquire = {
        key: {a.lock for a in fn.acquires if not a.bounded}
        for key, fn in funcs.items()
    }
    may_block = {
        key: {(b.desc, b.category) for b in fn.blocking}
        for key, fn in funcs.items()
    }
    changed = True
    while changed:
        changed = False
        for key in funcs:
            for tgt in resolved_calls[key]:
                if not may_acquire[key].issuperset(may_acquire[tgt]):
                    may_acquire[key] |= may_acquire[tgt]
                    changed = True
                if not may_block[key].issuperset(may_block[tgt]):
                    may_block[key] |= may_block[tgt]
                    changed = True
    return may_acquire, may_block


def _lock_name(lock: LockId) -> str:
    scope, owner, attr = lock
    return f"{owner}.{attr}" if scope == "C" else f"{owner}:{attr}"


# ------------------------------------------------------------------ rule logic


def _rule_cl501(
    funcs: dict[str, FuncFacts],
    res: _Resolver,
    may_acquire: dict[str, set[LockId]],
) -> list[Finding]:
    edges: dict[tuple[LockId, LockId], tuple[str, int, str]] = {}
    findings: list[Finding] = []

    def add_edge(a: LockId, b: LockId, fn: FuncFacts, line: int, via: str):
        if a == b:
            if res.lock_kind(a) not in REENTRANT and not via:
                findings.append(
                    Finding(
                        "CL501",
                        f"non-reentrant lock {_lock_name(a)} re-acquired "
                        "while already held (self-deadlock)",
                        fn.path,
                        line,
                    )
                )
            return
        edges.setdefault((a, b), (fn.path, line, via))

    for key, fn in funcs.items():
        for acq in fn.acquires:
            if acq.bounded:
                continue
            for h in acq.held:
                add_edge(h, acq.lock, fn, acq.line, "")
        for call in fn.calls:
            if not call.held:
                continue
            for tgt in res.resolve_call(call.callee, fn):
                if tgt not in funcs:
                    continue
                for lock in may_acquire.get(tgt, ()):
                    for h in call.held:
                        add_edge(h, lock, fn, call.line, f"via {call.callee}")

    # Tarjan-free SCC via iterative Kosaraju on the tiny lock graph.
    adj: dict[LockId, set[LockId]] = {}
    for (a, b) in edges:
        adj.setdefault(a, set()).add(b)
        adj.setdefault(b, set())
    order: list[LockId] = []
    seen: set[LockId] = set()
    for start in adj:
        if start in seen:
            continue
        stack = [(start, iter(adj[start]))]
        seen.add(start)
        while stack:
            node, it = stack[-1]
            for nxt in it:
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, iter(adj[nxt])))
                    break
            else:
                order.append(node)
                stack.pop()
    radj: dict[LockId, set[LockId]] = {n: set() for n in adj}
    for (a, b) in edges:
        radj[b].add(a)
    comp: dict[LockId, int] = {}
    for root in reversed(order):
        if root in comp:
            continue
        cid = len(comp)
        work = [root]
        while work:
            n = work.pop()
            if n in comp:
                continue
            comp[n] = cid
            work.extend(m for m in radj[n] if m not in comp)
    for (a, b), (path, line, via) in sorted(edges.items()):
        if comp.get(a) is not None and comp.get(a) == comp.get(b):
            suffix = f" ({via})" if via else ""
            findings.append(
                Finding(
                    "CL501",
                    f"lock-order inversion: {_lock_name(b)} acquired while "
                    f"holding {_lock_name(a)}{suffix}, and the reverse "
                    "order exists on another path",
                    path,
                    line,
                )
            )
    return findings


def _rule_cl502(
    funcs: dict[str, FuncFacts],
    inv: _Inventory,
    thread_reachable: set[str],
) -> list[Finding]:
    involved = {
        name
        for name, c in inv.classes.items()
        if c.spawns_threads or c.locks
    }
    for key in thread_reachable:
        fn = funcs.get(key)
        if fn is not None and fn.cls is not None:
            involved.add(fn.cls)

    # Group accesses by (class, attr), excluding the owner's __init__ and
    # lock/queue/thread plumbing attrs.
    groups: dict[tuple[str, str], list[tuple[FuncFacts, Access]]] = {}
    for key, fn in funcs.items():
        in_owner_init = fn.name == "__init__"
        for acc in fn.accesses:
            if acc.owner not in involved:
                continue
            facts = inv.classes[acc.owner]
            if (
                acc.attr in facts.locks
                or acc.attr in facts.queues
                or acc.attr in facts.thread_attrs
                or acc.attr in facts.sync_attrs
            ):
                continue
            if in_owner_init and fn.cls == acc.owner:
                continue
            groups.setdefault((acc.owner, acc.attr), []).append((fn, acc))

    # A class is *concurrent* when it spawns threads, or any of its own
    # methods — or any function touching its attributes — runs on a
    # spawned thread. Owning a lock alone marks it "involved" (analysed)
    # but not concurrent.
    reachable_classes = {
        funcs[k].cls for k in thread_reachable if k in funcs
    } - {None}

    findings: list[Finding] = []
    for (owner, attr), entries in sorted(groups.items()):
        writes = [(f, a) for f, a in entries if a.write]
        if not writes:
            continue
        concurrent = (
            inv.classes[owner].spawns_threads
            or owner in reachable_classes
            or any(f.key in thread_reachable for f, _ in entries)
        )
        if not concurrent:
            continue
        flagged: set[tuple[str, int]] = set()
        # (a) unguarded read-modify-write in a concurrent context.
        for f, a in writes:
            if a.rmw and not a.held:
                where = (f.path, a.line)
                if where in flagged:
                    continue
                flagged.add(where)
                findings.append(
                    Finding(
                        "CL502",
                        f"read-modify-write of {owner}.{attr} without a "
                        f"lock in {f.name}() — concurrent increments lose "
                        "updates",
                        f.path,
                        a.line,
                    )
                )
        # (b) a dominating lock guards the other accesses.
        by_lock: dict[LockId, int] = {}
        for _f, a in entries:
            for lock in a.held:
                by_lock[lock] = by_lock.get(lock, 0) + 1
        for lock, n in sorted(by_lock.items()):
            if n < 2 or n * 2 < len(entries):
                continue
            for f, a in entries:
                if lock in a.held or (f.path, a.line) in flagged:
                    continue
                flagged.add((f.path, a.line))
                kind = "written" if a.write else "read"
                findings.append(
                    Finding(
                        "CL502",
                        f"{owner}.{attr} {kind} in {f.name}() without "
                        f"{_lock_name(lock)}, which guards {n} of its "
                        f"{len(entries)} other accesses",
                        f.path,
                        a.line,
                    )
                )
            break  # one dominating lock is enough
    return findings


def _rule_cl503(
    funcs: dict[str, FuncFacts],
    res: _Resolver,
    may_block: dict[str, set[tuple[str, str]]],
) -> list[Finding]:
    findings: list[Finding] = []
    for key, fn in funcs.items():
        for b in fn.blocking:
            if b.held:
                findings.append(
                    Finding(
                        "CL503",
                        f"blocking {b.desc} while holding "
                        f"{_lock_name(b.held[-1])}",
                        fn.path,
                        b.line,
                    )
                )
        for call in fn.calls:
            if not call.held:
                continue
            for tgt in res.resolve_call(call.callee, fn):
                ops = may_block.get(tgt, set())
                if ops:
                    desc = ", ".join(sorted(d for d, _c in ops)[:3])
                    findings.append(
                        Finding(
                            "CL503",
                            f"call {call.callee}() while holding "
                            f"{_lock_name(call.held[-1])} may block "
                            f"({desc})",
                            fn.path,
                            call.line,
                        )
                    )
                    break
    return findings


def _rule_cl504(
    funcs: dict[str, FuncFacts],
    res: _Resolver,
    handler_entries: list[str],
) -> list[Finding]:
    reachable = _reachable(handler_entries, funcs, res)
    findings: list[Finding] = []
    for key in sorted(reachable):
        fn = funcs[key]
        for acq in fn.acquires:
            if not acq.bounded:
                findings.append(
                    Finding(
                        "CL504",
                        f"blocking acquire of {_lock_name(acq.lock)} in "
                        f"signal-handler-reachable {fn.name}() — if the "
                        "interrupted main-thread frame holds it, the "
                        "process self-deadlocks",
                        fn.path,
                        acq.line,
                    )
                )
        for b in fn.blocking:
            if b.category == "sync":
                findings.append(
                    Finding(
                        "CL504",
                        f"{b.desc} in signal-handler-reachable "
                        f"{fn.name}()",
                        fn.path,
                        b.line,
                    )
                )
    return findings


def _rule_cl505(
    funcs: dict[str, FuncFacts], inv: _Inventory
) -> list[Finding]:
    # Join inventory: (class, attr) pairs some function joins.
    joined_attrs: set[tuple[str, str]] = set()
    for fn in funcs.values():
        for call in fn.calls:
            parts = call.callee.split(".")
            if parts[-1] != "join" or len(parts) < 2:
                continue
            attr = parts[-2]
            if attr == "self" or attr in ("", "os", "path"):
                continue
            for cname, c in inv.classes.items():
                if attr in c.thread_attrs:
                    joined_attrs.add((cname, attr))
    findings: list[Finding] = []
    for fn in funcs.values():
        for spawn in fn.spawns:
            joined = (
                spawn.binding in joined_attrs
                or spawn.binding == ("<local>", "joined")
            )
            if spawn.daemon is not True and not joined:
                findings.append(
                    Finding(
                        "CL505",
                        f"non-daemon {spawn.kind} spawned in {fn.name}() "
                        "is never joined — interpreter shutdown will hang "
                        "on it (set daemon=True or join it on the stop "
                        "path)",
                        fn.path,
                        spawn.line,
                    )
                )
            elif spawn.in_init and not joined:
                findings.append(
                    Finding(
                        "CL505",
                        f"{spawn.kind} spawned in __init__ with no "
                        "join/stop path on the class — the object can "
                        "never be torn down deterministically",
                        fn.path,
                        spawn.line,
                    )
                )
    return findings


# ----------------------------------------------------------------- entry point


def lint_concurrency(
    paths: list[Path | str],
    package_root: Path | str | None = None,
    include_suppressed: bool = False,
) -> list[Finding]:
    """Run CL501–CL505 (+ the SP001 hygiene scan) over files/directories.

    ``include_suppressed=True`` keeps suppression-matched findings,
    marked via ``Finding.suppressed``, instead of dropping them — the
    ``--json`` CI surface audits the suppression inventory that way.
    """
    paths = [Path(p) for p in paths]
    if package_root is None:
        package_root = next((p for p in paths if p.is_dir()), None)
    files = discover_files(paths)

    sources: dict[str, str] = {}
    trees: dict[str, tuple[Path, ast.AST]] = {}
    findings: list[Finding] = []
    for f in files:
        module = _module_name(f, Path(package_root) if package_root else None)
        try:
            src = f.read_text()
            tree = ast.parse(src, filename=str(f))
        except SyntaxError:
            continue  # Pass 1 owns the syntax-error finding (TL100)
        sources[module] = src
        trees[module] = (f, tree)

    graph = CallGraph.build(trees)
    _collect_module_locks(graph, trees)
    inv = _collect_inventory(graph, trees)
    defs = _collect_functions(trees)
    for key, d in defs.items():
        if d.cls is not None and key == f"{d.module}:{d.cls}.{d.name}":
            inv.methods.setdefault(d.name, []).append(key)
    res = _Resolver(graph, inv, defs)

    funcs: dict[str, FuncFacts] = {}
    for key, d in defs.items():
        fn = FuncFacts(
            key=key,
            module=d.module,
            cls=d.cls,
            name=d.name,
            path=str(trees[d.module][0]),
            param_types=_param_types(d.node, inv),
        )
        _FunctionWalker(
            fn, d.node, res, graph.imports.get(d.module, {})
        ).run()
        _bind_spawns(fn, d.node, inv)
        funcs[key] = fn

    # Thread entries: spawn targets + signal handlers.
    thread_entries: list[str] = []
    handler_entries: list[str] = []
    for fn in funcs.values():
        for spawn in fn.spawns:
            thread_entries.extend(_resolve_target(spawn.target, fn, res))
        for handler, _line in fn.handlers:
            keys = _resolve_target(handler, fn, res)
            handler_entries.extend(keys)
            thread_entries.extend(keys)
    thread_reachable = _reachable(thread_entries, funcs, res)
    may_acquire, may_block = _fixpoint_summaries(funcs, res)

    findings.extend(_rule_cl501(funcs, res, may_acquire))
    findings.extend(_rule_cl502(funcs, inv, thread_reachable))
    findings.extend(_rule_cl503(funcs, res, may_block))
    findings.extend(_rule_cl504(funcs, res, handler_entries))
    findings.extend(_rule_cl505(funcs, inv))

    # Suppression filtering + the SP001 hygiene scan, per module.
    by_path: dict[str, str] = {
        str(p): sources[m] for m, (p, _t) in trees.items()
    }
    out: list[Finding] = []
    sup_cache = {
        path: suppressed_rules_by_line(src) for path, src in by_path.items()
    }
    for f in findings:
        sup = sup_cache.get(f.path, {})
        if not is_suppressed(f, sup):
            out.append(f)
        elif include_suppressed:
            out.append(dataclasses.replace(f, suppressed=True))
    for path, src in sorted(by_path.items()):
        for f in suppression_findings(src, path):
            if not is_suppressed(f, sup_cache.get(path, {})):
                out.append(f)
            elif include_suppressed:
                out.append(dataclasses.replace(f, suppressed=True))

    seen: set[tuple[str, str, int, str]] = set()
    unique: list[Finding] = []
    for f in sorted(out, key=lambda f: (f.path, f.line, f.rule)):
        k = (f.rule, f.path, f.line, f.message)
        if k not in seen:
            seen.add(k)
            unique.append(f)
    return unique
