"""tracelint — static + trace-time analysis for the TPU hot path.

The framework's performance contract is "one jitted shard_map+scan program
per epoch" (train/steps.py). That contract degrades *silently*: a stray
``float()`` on a tracer forces a host sync, a reused PRNG key correlates
dropout masks, an f64 literal promotes the whole loss graph, a host
transfer inside the loop serializes every step on the relay link, and a
bad sharding annotation turns the gradient psum into an all-gather. None
of those raise; they just make training slow or subtly wrong.

Two cooperating passes enforce the contract:

- **Pass 1 — AST lint** (:mod:`astlint`): repo-specific rules over the
  package source, driven by a jit-reachability call graph
  (:mod:`callgraph`) so host-side code is not held to trace-time rules.
- **Pass 2 — trace-time audit** (:mod:`traceaudit`): builds the real
  train-epoch program from a small config, runs it, and asserts the
  compiled-artifact invariants — compile count stays 1 across steps,
  ``jax.transfer_guard("disallow")`` holds over the hot loop, the batch
  axis is sharded / params replicated, and dtypes match the precision
  policy.

CLI: ``python -m masters_thesis_tpu.analysis`` (exits non-zero on
findings). The trainer runs Pass 2 before ``fit`` when constructed with
``preflight=True``.
"""

from masters_thesis_tpu.analysis.findings import (
    Finding,
    RULES,
    format_report,
)
from masters_thesis_tpu.analysis.astlint import lint_paths

__all__ = [
    "Finding",
    "RULES",
    "format_report",
    "lint_paths",
]
