"""Pass 4 — SPMD divergence & collective-safety lint (DV701–DV705).

Every rank of an SPMD fleet must issue the *same* collective schedule:
the same barriers, the same all-reduces, in the same order, over the
same shapes. The moment host-divergent state — ``jax.process_index``,
``os.environ``, wall clock, unseeded RNG, a per-host ``len()`` — steers
control flow around a collective, the fleet deadlocks silently: the
divergent rank skips a ``sync_global_devices`` the others are blocked
in, and nothing crashes until a watchdog condemns the generation. This
pass finds those schedules *statically*, before a DCN mesh does.

Taint sources (each tagged with a kind so the message names the origin):

- ``rank`` — ``jax.process_index()``; parameters named ``rank`` /
  ``process_index`` / ``proc`` / ``host_id`` / ``local_rank``;
  functions whose return derives from one of those (interprocedural
  fixpoint, e.g. ``telemetry.run.process_identity``).
- ``env``  — ``os.environ[...]`` / ``os.environ.get`` / ``os.getenv``.
- ``time`` — ``time.time/monotonic/perf_counter``, ``datetime.now``.
- ``rng``  — module-level ``random.*`` draws, ``uuid.uuid4``,
  ``os.urandom``, legacy ``np.random.*``, ``random.Random()`` with no
  seed (``Random(seed)`` is deterministic and stays clean).
- ``host`` — ``socket.gethostname``, ``os.getpid``,
  ``jax.local_devices`` / ``local_device_count``.

``jax.process_count()`` is deliberately NOT a source: it is uniform
across ranks, so ``if process_count() <= 1: return`` guards are clean.

Taint propagates through assignments, arithmetic, f-strings, subscripts
and a small builtin whitelist (``len``/``int``/``sorted``/...); any
other call laundders it — the same precision-over-recall contract as
Pass 1–3: what the analysis cannot prove divergent, it does not flag.

Rules:

- **DV701** host-divergent control flow where only one side reaches a
  collective: a tainted ``if`` with collectives down exactly one branch,
  a tainted early exit (``return``/``raise``/``continue``) before
  collectives in the rest of the function, or a tainted loop bound
  around a collective (per-host trip counts).
- **DV702** both sides of tainted control flow reach collectives but the
  schedules differ (order or kind) — ranks disagree on *which* program
  they are running, not just whether.
- **DV703** a host-divergent value flows into a collective operand or a
  traced array shape (``jnp.zeros(n_local)``) — per-rank shapes break
  the single-program contract even when the schedule matches.
- **DV704** nondeterminism reachable from the checkpoint publish/resume
  path: wall clock, unseeded RNG, or unsorted set/directory iteration —
  the repo's hardest invariant is bit-identical multi-rank resume.
- **DV705** a rank-0-only gate with side effects (file writes, renames)
  in a function whose schedule contains no named barrier — other ranks
  race past the mutation.

Suppress with ``# mtt: disable=DV70x -- reason`` (findings.py owns the
parser; reason-less suppressions are SP001 via the Pass-3 scan).

The runtime counterpart lives in :mod:`masters_thesis_tpu.telemetry.schedule`:
each rank chains its *actual* collective schedule into a sha256 the
postmortem cross-checks bitwise — this pass is the compile-time half.
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path

from masters_thesis_tpu.analysis.astlint import _module_name, discover_files
from masters_thesis_tpu.analysis.callgraph import CallGraph, dotted_name
from masters_thesis_tpu.analysis.concurrency import (
    CallSite,
    _collect_functions,
    _collect_inventory,
    _param_types,
    _reachable,
    _Resolver,
)
from masters_thesis_tpu.analysis.findings import (
    Finding,
    is_suppressed,
    suppressed_rules_by_line,
)

# --------------------------------------------------------------- vocabulary

#: Host-level + in-trace collectives, by final attribute segment.
COLLECTIVE_NAMES = {
    "fleet_barrier": "barrier",
    "sync_global_devices": "barrier",
    "broadcast_one_to_all": "broadcast",
    "process_allgather": "all_gather",
    "psum": "psum",
    "pmean": "pmean",
    "pmax": "pmax",
    "pmin": "pmin",
    "all_gather": "all_gather",
    "all_to_all": "all_to_all",
    "ppermute": "ppermute",
}

#: Parameters that carry a per-rank identity by convention.
RANK_PARAM_NAMES = {
    "rank", "process_index", "process_id", "proc", "host_id", "local_rank",
}

#: Full dotted call → taint kind. Matched after import-alias expansion
#: is NOT attempted — these are the spellings the repo actually uses.
TIME_CALLS = {
    "time.time", "time.monotonic", "time.perf_counter",
    "time.perf_counter_ns", "time.time_ns",
    "datetime.now", "datetime.utcnow", "datetime.datetime.now",
    "datetime.datetime.utcnow", "datetime.date.today",
}
RNG_CALLS = {
    "random.random", "random.randint", "random.choice", "random.shuffle",
    "random.uniform", "random.randrange", "random.sample", "random.betavariate",
    "random.gauss", "uuid.uuid4", "os.urandom",
    "np.random.rand", "np.random.randn", "np.random.randint",
    "np.random.random", "np.random.permutation", "np.random.shuffle",
    "numpy.random.rand", "numpy.random.randn", "numpy.random.randint",
    "numpy.random.random", "numpy.random.permutation",
    "numpy.random.shuffle",
}
HOST_ID_CALLS = {
    "socket.gethostname", "os.getpid",
    "jax.local_devices", "jax.local_device_count",
}
RANK_CALL_SUFFIX = "process_index"

#: Builtins that preserve taint from their arguments.
TAINT_PRESERVING_BUILTINS = {
    "len", "int", "float", "str", "bool", "abs", "round", "sorted",
    "min", "max", "sum", "tuple", "list", "set", "frozenset", "repr",
    "range", "enumerate", "reversed", "zip",
}

#: Array constructors whose arguments become traced shapes (DV703).
SHAPE_CTOR_NAMES = {
    "zeros", "ones", "full", "empty", "arange", "linspace", "reshape",
    "broadcast_to",
}
ARRAY_NS_HEADS = {"jnp", "np", "numpy", "jax"}

#: File-mutation vocabulary for DV705 side effects.
MUTATING_METHODS = {
    "write_text", "write_bytes", "rename", "replace", "unlink", "rmtree",
    "rmdir", "mkdir", "makedirs", "symlink_to", "touch",
}
MUTATING_CALLS = {
    "os.replace", "os.rename", "os.remove", "os.unlink", "os.makedirs",
    "os.mkdir", "os.rmdir", "shutil.rmtree", "shutil.copy", "shutil.copy2",
    "shutil.copytree", "shutil.move", "np.save", "numpy.save", "np.savez",
    "numpy.savez", "atomic_write_text", "atomic_write_json",
}

#: Functions whose reachable closure is the checkpoint publish/resume
#: path (DV704's scope) — matched by bare function name.
CHECKPOINT_ENTRY_NAMES = {
    "save_checkpoint", "restore_checkpoint", "checkpoint_restorable",
    "last_verified_checkpoint", "verify_checkpoint", "write_manifest",
    "read_manifest", "_run_recovery", "_recover_staged", "_publish",
}

#: Unsorted-iteration producers (DV704 "order" nondeterminism).
UNORDERED_ITER_CALLS = {"iterdir", "glob", "rglob", "listdir", "scandir"}

_FLATTEN_CAP = 64  # bounded schedule expansion per function
_FIXPOINT_ROUNDS = 4


# ------------------------------------------------------------------- facts


@dataclasses.dataclass
class SpmdFn:
    """Per-function facts, duck-typing what ``_Resolver`` needs."""

    key: str
    module: str
    cls: str | None
    name: str
    path: str
    param_types: dict[str, str]
    calls: list[CallSite] = dataclasses.field(default_factory=list)
    seq: list[tuple] = dataclasses.field(default_factory=list)
    tainted_ifs: list["TaintedIf"] = dataclasses.field(default_factory=list)
    tainted_loops: list[tuple] = dataclasses.field(default_factory=list)
    operand_sinks: list[tuple] = dataclasses.field(default_factory=list)
    nondet: list[tuple] = dataclasses.field(default_factory=list)
    return_taint: frozenset[str] = frozenset()


@dataclasses.dataclass
class TaintedIf:
    line: int
    kinds: frozenset[str]
    body: list[tuple]
    orelse: list[tuple]
    rest: list[tuple]
    body_exits: bool
    orelse_exits: bool
    gate_branch: str | None  # "body"/"orelse" when the test is rank == 0


# -------------------------------------------------------- event collection


def _call_name(node: ast.Call) -> str | None:
    return dotted_name(node.func)


def _barrier_label(node: ast.Call) -> str | None:
    """Static rendering of a collective's ``name`` argument."""
    cand = None
    if node.args:
        cand = node.args[0]
    for kw in node.keywords:
        if kw.arg == "name":
            cand = kw.value
    if cand is None:
        return None
    if isinstance(cand, ast.Constant) and isinstance(cand.value, str):
        return cand.value
    if isinstance(cand, ast.JoinedStr):
        parts = []
        for v in cand.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            else:
                parts.append("{}")
        return "".join(parts)
    return None


def _side_effect_desc(node: ast.Call, dotted: str | None) -> str | None:
    if dotted is None:
        # Method call on a computed receiver — `(d / tag).replace(x)` is
        # the canonical atomic-publish idiom; the receiver expression is
        # unknowable statically but the method name still is.
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in MUTATING_METHODS
        ):
            return f"<expr>.{node.func.attr}"
        return None
    last = dotted.split(".")[-1]
    if dotted in MUTATING_CALLS or last in MUTATING_CALLS:
        return dotted
    if last in MUTATING_METHODS:
        return dotted
    if last == "open" and len(node.args) >= 2:
        mode = node.args[1]
        if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
            if any(c in mode.value for c in "wax"):
                return f"open(..., {mode.value!r})"
    return None


def _events_of(stmts: list[ast.stmt]) -> list[tuple]:
    """Ordered may-happen events under a block (recurses everywhere).

    Tuples: ``("C", kind, label, line)`` collective, ``("F", callee,
    line)`` call, ``("S", desc, line)`` file mutation, ``("X", kind,
    line)`` control exit. Both branches of nested ``if``s are included —
    these feed *may-reach* questions, never must-reach ones.
    """
    out: list[tuple] = []

    def visit_expr(node: ast.AST) -> None:
        for call in [
            n for n in ast.walk(node) if isinstance(n, ast.Call)
        ]:
            dotted = _call_name(call)
            if dotted is None:
                desc = _side_effect_desc(call, dotted)
                if desc is not None:
                    out.append(("S", desc, call.lineno))
                continue
            last = dotted.split(".")[-1]
            if last in COLLECTIVE_NAMES:
                out.append(
                    (
                        "C",
                        COLLECTIVE_NAMES[last],
                        _barrier_label(call),
                        call.lineno,
                    )
                )
                continue
            desc = _side_effect_desc(call, dotted)
            if desc is not None:
                out.append(("S", desc, call.lineno))
            out.append(("F", dotted, call.lineno))

    def visit_block(stmts: list[ast.stmt]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.Return, ast.Raise)):
                if isinstance(stmt, ast.Return) and stmt.value is not None:
                    visit_expr(stmt.value)
                elif isinstance(stmt, ast.Raise) and stmt.exc is not None:
                    visit_expr(stmt.exc)
                out.append(("X", type(stmt).__name__.lower(), stmt.lineno))
            elif isinstance(stmt, (ast.Break, ast.Continue)):
                out.append(("X", type(stmt).__name__.lower(), stmt.lineno))
            elif isinstance(stmt, ast.If):
                visit_expr(stmt.test)
                visit_block(stmt.body)
                visit_block(stmt.orelse)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                visit_expr(stmt.iter)
                visit_block(stmt.body)
                visit_block(stmt.orelse)
            elif isinstance(stmt, ast.While):
                visit_expr(stmt.test)
                visit_block(stmt.body)
                visit_block(stmt.orelse)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    visit_expr(item.context_expr)
                visit_block(stmt.body)
            elif isinstance(stmt, ast.Try):
                visit_block(stmt.body)
                for h in stmt.handlers:
                    visit_block(h.body)
                visit_block(stmt.orelse)
                visit_block(stmt.finalbody)
            elif isinstance(
                stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue  # nested defs get their own SpmdFn
            else:
                visit_expr(stmt)

    visit_block(stmts)
    return out


def _definitely_exits(stmts: list[ast.stmt]) -> bool:
    return any(
        isinstance(s, (ast.Return, ast.Raise, ast.Break, ast.Continue))
        for s in stmts
    )


# ------------------------------------------------------------- taint walk


class _TaintWalker:
    """Flow-sensitive single-pass taint walk over one function body."""

    def __init__(
        self,
        fn: SpmdFn,
        node: ast.FunctionDef,
        res: _Resolver,
        return_taint: dict[str, frozenset[str]],
    ):
        self.fn = fn
        self.node = node
        self.res = res
        self.return_taint = return_taint
        self.env: dict[str, set[str]] = {}
        self.ret: set[str] = set()

    def run(self) -> None:
        args = self.node.args
        for a in args.args + args.posonlyargs + args.kwonlyargs:
            if a.arg in RANK_PARAM_NAMES:
                self.env[a.arg] = {"rank"}
        self.block(self.node.body, enclosing_rest=[])

    # -- taint of an expression under the current env

    def taint(self, node: ast.AST | None) -> set[str]:
        if node is None:
            return set()
        if isinstance(node, ast.Name):
            return set(self.env.get(node.id, ()))
        if isinstance(node, ast.Attribute):
            dotted = dotted_name(node)
            if dotted is not None and "environ" in dotted.split("."):
                return {"env"}
            return self.taint(node.value)
        if isinstance(node, ast.Subscript):
            return self.taint(node.value) | self.taint(node.slice)
        if isinstance(node, ast.Call):
            return self.call_taint(node)
        if isinstance(node, ast.BinOp):
            return self.taint(node.left) | self.taint(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.taint(node.operand)
        if isinstance(node, ast.BoolOp):
            out: set[str] = set()
            for v in node.values:
                out |= self.taint(v)
            return out
        if isinstance(node, ast.Compare):
            out = self.taint(node.left)
            for c in node.comparators:
                out |= self.taint(c)
            return out
        if isinstance(node, ast.IfExp):
            return (
                self.taint(node.test)
                | self.taint(node.body)
                | self.taint(node.orelse)
            )
        if isinstance(node, (ast.JoinedStr,)):
            out = set()
            for v in node.values:
                out |= self.taint(v)
            return out
        if isinstance(node, ast.FormattedValue):
            return self.taint(node.value)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            out = set()
            for e in node.elts:
                out |= self.taint(e)
            return out
        if isinstance(node, ast.Dict):
            out = set()
            for k in node.keys:
                out |= self.taint(k)
            for v in node.values:
                out |= self.taint(v)
            return out
        if isinstance(node, ast.Starred):
            return self.taint(node.value)
        return set()

    def call_taint(self, node: ast.Call) -> set[str]:
        dotted = _call_name(node)
        if dotted is None:
            return set()
        last = dotted.split(".")[-1]
        arg_taint: set[str] = set()
        for a in node.args:
            arg_taint |= self.taint(a)
        for kw in node.keywords:
            arg_taint |= self.taint(kw.value)
        # Direct sources.
        if last == RANK_CALL_SUFFIX:
            return {"rank"}
        if dotted in ("os.getenv",) or "environ" in dotted.split("."):
            return {"env"}
        if dotted in TIME_CALLS:
            return {"time"}
        if dotted in RNG_CALLS:
            return {"rng"}
        if dotted in ("random.Random",) and not node.args:
            return {"rng"}
        if dotted in HOST_ID_CALLS:
            return {"host"}
        # Taint-preserving builtins.
        if dotted in TAINT_PRESERVING_BUILTINS:
            return arg_taint
        # Interprocedural: the callee's return taint (fixpoint map).
        out: set[str] = set()
        for tgt in self.res.resolve_call(dotted, self.fn):
            out |= self.return_taint.get(tgt, frozenset())
        return out

    # -- statements

    def assign_target(self, tgt: ast.AST, kinds: set[str]) -> None:
        if isinstance(tgt, ast.Name):
            if kinds:
                self.env[tgt.id] = set(kinds)
            else:
                self.env.pop(tgt.id, None)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for e in tgt.elts:
                self.assign_target(e, kinds)
        elif isinstance(tgt, ast.Starred):
            self.assign_target(tgt.value, kinds)

    def _rank_zero_gate(self, test: ast.AST) -> str | None:
        """"body"/"orelse" when the test pins rank against 0, else None."""
        for node in ast.walk(test):
            if not isinstance(node, ast.Compare) or len(node.ops) != 1:
                continue
            left, op, right = node.left, node.ops[0], node.comparators[0]
            sides = [(left, right), (right, left)]
            for val, const in sides:
                if not (
                    isinstance(const, ast.Constant) and const.value == 0
                ):
                    continue
                if "rank" not in self.taint(val):
                    continue
                if isinstance(op, ast.Eq):
                    return "body"
                if isinstance(op, ast.NotEq):
                    return "orelse"
        return None

    def scan_calls(self, node: ast.AST) -> None:
        """Record call sites + DV703 operand/shape sinks in any expr."""
        for call in [n for n in ast.walk(node) if isinstance(n, ast.Call)]:
            dotted = _call_name(call)
            if dotted is None:
                continue
            self.fn.calls.append(CallSite(dotted, call.lineno, ()))
            parts = dotted.split(".")
            last = parts[0] if len(parts) == 1 else parts[-1]
            if last in COLLECTIVE_NAMES:
                for a in list(call.args) + [k.value for k in call.keywords]:
                    kinds = self.taint(a)
                    if kinds:
                        self.fn.operand_sinks.append(
                            (
                                "collective",
                                dotted,
                                sorted(kinds),
                                call.lineno,
                            )
                        )
                        break
            if (
                last in SHAPE_CTOR_NAMES
                and len(parts) > 1
                and parts[0] in ARRAY_NS_HEADS
            ):
                for a in call.args:
                    kinds = self.taint(a)
                    if kinds:
                        self.fn.operand_sinks.append(
                            ("shape", dotted, sorted(kinds), call.lineno)
                        )
                        break
            # DV704 raw material: time / unseeded-RNG draws.
            if dotted in TIME_CALLS:
                self.fn.nondet.append(("time", dotted, call.lineno))
            elif dotted in RNG_CALLS or (
                dotted == "random.Random" and not call.args
            ):
                self.fn.nondet.append(("rng", dotted, call.lineno))

    def block(
        self, stmts: list[ast.stmt], enclosing_rest: list[ast.stmt]
    ) -> None:
        for i, stmt in enumerate(stmts):
            rest = stmts[i + 1 :] + enclosing_rest
            self.stmt(stmt, rest)

    def stmt(self, stmt: ast.stmt, rest: list[ast.stmt]) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested defs analyzed as their own functions
        if isinstance(stmt, ast.Assign):
            self.scan_calls(stmt.value)
            kinds = self.taint(stmt.value)
            for tgt in stmt.targets:
                self.assign_target(tgt, kinds)
            return
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self.scan_calls(stmt.value)
                self.assign_target(stmt.target, self.taint(stmt.value))
            return
        if isinstance(stmt, ast.AugAssign):
            self.scan_calls(stmt.value)
            kinds = self.taint(stmt.value) | self.taint(stmt.target)
            self.assign_target(stmt.target, kinds)
            return
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self.scan_calls(stmt.value)
                self.ret |= self.taint(stmt.value)
            return
        if isinstance(stmt, ast.If):
            self.scan_calls(stmt.test)
            kinds = self.taint(stmt.test)
            gate = self._rank_zero_gate(stmt.test)
            saved = {k: set(v) for k, v in self.env.items()}
            self.block(stmt.body, rest)
            body_env = self.env
            self.env = saved
            self.block(stmt.orelse, rest)
            for k, v in body_env.items():
                self.env[k] = self.env.get(k, set()) | v
            if kinds or gate is not None:
                self.fn.tainted_ifs.append(
                    TaintedIf(
                        line=stmt.lineno,
                        kinds=frozenset(kinds),
                        body=_events_of(stmt.body),
                        orelse=_events_of(stmt.orelse),
                        rest=_events_of(rest),
                        body_exits=_definitely_exits(stmt.body),
                        orelse_exits=_definitely_exits(stmt.orelse),
                        gate_branch=gate,
                    )
                )
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self.scan_calls(stmt.iter)
            iter_taint = self.taint(stmt.iter)
            self.assign_target(stmt.target, iter_taint)
            self._nondet_iteration(stmt.iter)
            self.block(stmt.body, rest)
            self.block(stmt.orelse, rest)
            if iter_taint:
                self.fn.tainted_loops.append(
                    (
                        "for",
                        frozenset(iter_taint),
                        _events_of(stmt.body),
                        stmt.lineno,
                    )
                )
            return
        if isinstance(stmt, ast.While):
            self.scan_calls(stmt.test)
            kinds = self.taint(stmt.test)
            self.block(stmt.body, rest)
            self.block(stmt.orelse, rest)
            if kinds:
                self.fn.tainted_loops.append(
                    ("while", frozenset(kinds), _events_of(stmt.body),
                     stmt.lineno)
                )
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self.scan_calls(item.context_expr)
                if item.optional_vars is not None:
                    self.assign_target(
                        item.optional_vars, self.taint(item.context_expr)
                    )
            self.block(stmt.body, rest)
            return
        if isinstance(stmt, ast.Try):
            self.block(stmt.body, rest)
            for h in stmt.handlers:
                self.block(h.body, rest)
            self.block(stmt.orelse, rest)
            self.block(stmt.finalbody, rest)
            return
        if isinstance(stmt, ast.Raise):
            if stmt.exc is not None:
                self.scan_calls(stmt.exc)
            return
        if isinstance(stmt, ast.Expr):
            self.scan_calls(stmt.value)
            return
        if isinstance(stmt, (ast.Assert, ast.Delete, ast.Global,
                             ast.Nonlocal, ast.Pass, ast.Break,
                             ast.Continue, ast.Import, ast.ImportFrom)):
            if isinstance(stmt, ast.Assert):
                self.scan_calls(stmt.test)
            return
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self.scan_calls(child)

    def _nondet_iteration(self, it: ast.AST) -> None:
        """DV704 "order": iteration over sets / unsorted directory walks."""
        if isinstance(it, (ast.Set, ast.SetComp)):
            self.fn.nondet.append(
                ("order", "iteration over a set literal", it.lineno)
            )
            return
        if isinstance(it, ast.Call):
            dotted = _call_name(it)
            if dotted is None:
                return
            last = dotted.split(".")[-1]
            if last in UNORDERED_ITER_CALLS:
                self.fn.nondet.append(
                    ("order", f"unsorted {dotted}(...)", it.lineno)
                )


# --------------------------------------------------------------- schedules


class _ScheduleExpander:
    """Bounded, memoized expansion of event lists into collective tuples."""

    def __init__(self, funcs: dict[str, SpmdFn], res: _Resolver):
        self.funcs = funcs
        self.res = res
        self.memo: dict[str, tuple] = {}
        self.in_progress: set[str] = set()

    def of_fn(self, key: str) -> tuple:
        if key in self.memo:
            return self.memo[key]
        if key in self.in_progress:
            return ()
        self.in_progress.add(key)
        fn = self.funcs.get(key)
        out = self.of_events(fn.seq, fn) if fn is not None else ()
        self.in_progress.discard(key)
        self.memo[key] = out
        return out

    def of_events(self, events: list[tuple], fn: SpmdFn) -> tuple:
        out: list[tuple] = []
        for ev in events:
            if len(out) >= _FLATTEN_CAP:
                break
            if ev[0] == "C":
                out.append((ev[1], ev[2]))
            elif ev[0] == "F":
                targets = self.res.resolve_call(ev[1], fn)
                if len(targets) == 1:
                    out.extend(self.of_fn(targets[0]))
        return tuple(out[:_FLATTEN_CAP])


def _sched_desc(sched: tuple) -> str:
    if not sched:
        return "<none>"
    return ", ".join(
        kind if label is None else f"{kind}:{label}"
        for kind, label in sched[:6]
    ) + ("…" if len(sched) > 6 else "")


# ------------------------------------------------------------------- rules


def _taint_desc(kinds) -> str:
    return "/".join(sorted(kinds)) if kinds else "rank"


def _rule_dv701_702(
    funcs: dict[str, SpmdFn], exp: _ScheduleExpander
) -> list[Finding]:
    out: list[Finding] = []
    for fn in funcs.values():
        for ti in fn.tainted_ifs:
            if not ti.kinds:
                continue  # pure rank-0 gates are DV705's business
            body = exp.of_events(ti.body, fn)
            orelse = exp.of_events(ti.orelse, fn)
            src = _taint_desc(ti.kinds)
            if body and orelse:
                if body != orelse:
                    out.append(
                        Finding(
                            "DV702",
                            f"{fn.name}: both branches of "
                            f"{src}-divergent control flow issue "
                            f"collectives, but the schedules differ — "
                            f"if: [{_sched_desc(body)}] vs else: "
                            f"[{_sched_desc(orelse)}]",
                            fn.path,
                            ti.line,
                        )
                    )
                continue
            if body or orelse:
                reached = body or orelse
                out.append(
                    Finding(
                        "DV701",
                        f"{fn.name}: {src}-divergent branch guards "
                        f"[{_sched_desc(reached)}] — only one side "
                        f"reaches it, so ranks disagree on whether the "
                        f"collective runs",
                        fn.path,
                        ti.line,
                    )
                )
                continue
            # Early-exit divergence: one branch bails out of a function
            # whose remainder still issues collectives.
            rest = exp.of_events(ti.rest, fn)
            if rest and (ti.body_exits != ti.orelse_exits):
                out.append(
                    Finding(
                        "DV701",
                        f"{fn.name}: {src}-divergent early exit skips "
                        f"the rest of the collective schedule "
                        f"[{_sched_desc(rest)}]",
                        fn.path,
                        ti.line,
                    )
                )
        for loop_kind, kinds, body_events, line in fn.tainted_loops:
            body = exp.of_events(body_events, fn)
            if body:
                out.append(
                    Finding(
                        "DV701",
                        f"{fn.name}: {_taint_desc(kinds)}-divergent "
                        f"{loop_kind}-loop bound around "
                        f"[{_sched_desc(body)}] — per-host trip counts "
                        f"desynchronize the schedule",
                        fn.path,
                        line,
                    )
                )
    return out


def _rule_dv703(funcs: dict[str, SpmdFn]) -> list[Finding]:
    out: list[Finding] = []
    for fn in funcs.values():
        for sink, dotted, kinds, line in fn.operand_sinks:
            what = (
                "collective operand"
                if sink == "collective"
                else "traced array shape"
            )
            out.append(
                Finding(
                    "DV703",
                    f"{fn.name}: {_taint_desc(kinds)}-divergent value "
                    f"flows into a {what} ({dotted}) — per-rank "
                    f"values/shapes break the SPMD program contract",
                    fn.path,
                    line,
                )
            )
    return out


def _rule_dv704(
    funcs: dict[str, SpmdFn], res: _Resolver
) -> list[Finding]:
    entries = [
        k for k, fn in funcs.items() if fn.name in CHECKPOINT_ENTRY_NAMES
    ]
    reach = _reachable(entries, funcs, res)
    out: list[Finding] = []
    for key in sorted(reach):
        fn = funcs[key]
        for kind, desc, line in fn.nondet:
            what = {
                "time": "wall clock",
                "rng": "unseeded RNG",
                "order": "nondeterministic iteration order",
            }[kind]
            out.append(
                Finding(
                    "DV704",
                    f"{fn.name}: {what} ({desc}) on the checkpoint "
                    f"publish/resume path — breaks bit-identical "
                    f"multi-rank resume",
                    fn.path,
                    line,
                )
            )
    return out


def _rule_dv705(
    funcs: dict[str, SpmdFn], res: _Resolver, exp: _ScheduleExpander
) -> list[Finding]:
    # Transitive may-mutate fixpoint.
    may_mutate: set[str] = {
        k
        for k, fn in funcs.items()
        if any(ev[0] == "S" for ev in fn.seq)
    }
    for _ in range(_FIXPOINT_ROUNDS * 4):
        grew = False
        for key, fn in funcs.items():
            if key in may_mutate:
                continue
            for call in fn.calls:
                if any(
                    t in may_mutate
                    for t in res.resolve_call(call.callee, fn)
                ):
                    may_mutate.add(key)
                    grew = True
                    break
        if not grew:
            break

    def branch_mutates(events: list[tuple], fn: SpmdFn) -> str | None:
        for ev in events:
            if ev[0] == "S":
                return ev[1]
            if ev[0] == "F":
                for t in res.resolve_call(ev[1], fn):
                    if t in may_mutate:
                        return f"{ev[1]}(...)"
        return None

    out: list[Finding] = []
    for fn in funcs.values():
        fenced = any(kind == "barrier" for kind, _ in exp.of_fn(fn.key))
        if fenced:
            continue
        for ti in fn.tainted_ifs:
            if ti.gate_branch is None:
                continue
            gate_events = ti.body if ti.gate_branch == "body" else ti.orelse
            effect = branch_mutates(gate_events, fn)
            if effect is None:
                continue
            out.append(
                Finding(
                    "DV705",
                    f"{fn.name}: rank-0-only side effect ({effect}) with "
                    f"no named barrier in the function's schedule — "
                    f"other ranks race past the mutation",
                    fn.path,
                    ti.line,
                )
            )
    return out


# ------------------------------------------------------------- entry point


def lint_spmd(
    paths: list[Path | str],
    package_root: Path | str | None = None,
    include_suppressed: bool = False,
) -> list[Finding]:
    """Run DV701–DV705 over files/directories.

    With ``include_suppressed=True``, findings a per-line suppression
    matched are *kept* and marked (``Finding.suppressed``) instead of
    dropped — the ``--json`` CI surface audits suppressions this way.
    """
    paths = [Path(p) for p in paths]
    if package_root is None:
        package_root = next((p for p in paths if p.is_dir()), None)
    files = discover_files(paths)

    sources: dict[str, str] = {}
    trees: dict[str, tuple[Path, ast.AST]] = {}
    for f in files:
        module = _module_name(f, Path(package_root) if package_root else None)
        try:
            src = f.read_text()
            tree = ast.parse(src, filename=str(f))
        except SyntaxError:
            continue  # Pass 1 owns the syntax-error finding
        sources[module] = src
        trees[module] = (f, tree)

    graph = CallGraph.build(trees)
    inv = _collect_inventory(graph, trees)
    defs = _collect_functions(trees)
    for key, d in defs.items():
        if d.cls is not None and key == f"{d.module}:{d.cls}.{d.name}":
            inv.methods.setdefault(d.name, []).append(key)
    res = _Resolver(graph, inv, defs)

    # Interprocedural return-taint fixpoint: re-walk until the map of
    # tainted-return functions stabilizes (process_identity() and kin).
    return_taint: dict[str, frozenset[str]] = {k: frozenset() for k in defs}
    funcs: dict[str, SpmdFn] = {}
    for _round in range(_FIXPOINT_ROUNDS):
        changed = False
        funcs = {}
        for key, d in defs.items():
            fn = SpmdFn(
                key=key,
                module=d.module,
                cls=d.cls,
                name=d.name,
                path=str(trees[d.module][0]),
                param_types=_param_types(d.node, inv),
            )
            walker = _TaintWalker(fn, d.node, res, return_taint)
            walker.run()
            fn.seq = _events_of(d.node.body)
            fn.return_taint = frozenset(walker.ret)
            funcs[key] = fn
            if fn.return_taint != return_taint[key]:
                return_taint[key] = fn.return_taint
                changed = True
        if not changed:
            break

    exp = _ScheduleExpander(funcs, res)
    findings: list[Finding] = []
    findings.extend(_rule_dv701_702(funcs, exp))
    findings.extend(_rule_dv703(funcs))
    findings.extend(_rule_dv704(funcs, res))
    findings.extend(_rule_dv705(funcs, res, exp))

    by_path: dict[str, str] = {
        str(p): sources[m] for m, (p, _t) in trees.items()
    }
    sup_cache = {
        path: suppressed_rules_by_line(src) for path, src in by_path.items()
    }
    out: list[Finding] = []
    for f in findings:
        if is_suppressed(f, sup_cache.get(f.path, {})):
            if include_suppressed:
                out.append(dataclasses.replace(f, suppressed=True))
        else:
            out.append(f)

    seen: set[tuple] = set()
    unique: list[Finding] = []
    for f in sorted(out, key=lambda f: (f.path, f.line, f.rule)):
        k = (f.rule, f.path, f.line, f.message)
        if k not in seen:
            seen.add(k)
            unique.append(f)
    return unique
