"""``python -m masters_thesis_tpu.analysis`` — run tracelint.

Pass 1 (AST lint) over the given paths (default: the installed package),
then Pass 2 (trace-time audit) on a hermetic 8-device virtual CPU mesh.
Exits non-zero iff there are findings, so it gates CI (tools/check.sh).
"""

from __future__ import annotations

import argparse
import os
import sys
from pathlib import Path


def _force_cpu_mesh(n_devices: int) -> None:
    """Pin the audit to a virtual CPU mesh regardless of ambient
    accelerators/plugins — the audited invariants are properties of the
    traced program, and CI machines differ."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    # An ambient PJRT plugin (e.g. a TPU proxy) overrides JAX_PLATFORMS
    # set this late; the config update wins as long as no backend has
    # been initialized yet in this process.
    jax.config.update("jax_platforms", "cpu")


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m masters_thesis_tpu.analysis",
        description="tracelint: static + trace-time TPU hot-path analysis",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        type=Path,
        help="files/directories to lint (default: the package source)",
    )
    parser.add_argument(
        "--skip-trace",
        action="store_true",
        help="run only Pass 1 (AST lint), skip the trace-time audit",
    )
    parser.add_argument(
        "--skip-lint",
        action="store_true",
        help="run only Pass 2 (trace-time audit)",
    )
    parser.add_argument(
        "--json", action="store_true", help="machine-readable findings"
    )
    parser.add_argument(
        "--trace-steps",
        type=int,
        default=3,
        metavar="N",
        help="epochs the trace audit runs through the compiled program",
    )
    parser.add_argument(
        "--trace-devices",
        type=int,
        default=8,
        metavar="N",
        help="virtual CPU devices for the audit mesh",
    )
    parser.add_argument(
        "--stacked-replicas",
        type=int,
        default=3,
        metavar="R",
        help="replica count for the stacked-program audit (TA207); "
        "0 skips it",
    )
    args = parser.parse_args(argv)

    import masters_thesis_tpu

    package_root = Path(masters_thesis_tpu.__file__).parent
    paths = args.paths or [package_root]

    findings = []
    if not args.skip_lint:
        from masters_thesis_tpu.analysis.astlint import lint_paths

        findings.extend(lint_paths(paths, package_root=package_root))
    if not args.skip_trace:
        _force_cpu_mesh(args.trace_devices)
        from masters_thesis_tpu.analysis.traceaudit import run_trace_audit

        findings.extend(
            run_trace_audit(
                steps=args.trace_steps,
                stacked_replicas=args.stacked_replicas or None,
            )
        )

    from masters_thesis_tpu.analysis.findings import format_report

    print(format_report(findings, as_json=args.json))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
