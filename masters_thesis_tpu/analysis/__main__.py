"""``python -m masters_thesis_tpu.analysis`` — run tracelint.

Pass 1 (AST lint) over the given paths (default: the installed package),
then Pass 2 (trace-time audit) on a hermetic 8-device virtual CPU mesh.
Pass 3 — static concurrency lint (``--concurrency``, CL5xx) and the
event-schema contract check (``--contracts``, EC6xx) — and Pass 4 —
the SPMD divergence lint (``--spmd``, DV7xx, over the
train/parallel/resilience/telemetry stack) — are opt-in from this CLI
and gated by tools/check.sh; passing any of those flags runs *only* the
requested static checks (jax never imports, so they are fast enough for
a pre-commit hook). ``--emit-schema`` regenerates the
``analysis/event_schema.json`` lockfile.

Exit codes (documented contract, see docs/analysis.md): 0 — no
unsuppressed findings; 1 — at least one unsuppressed finding. With
``--json`` the static passes also *include* suppressed findings, each
marked ``"suppressed": true`` — they never affect the exit code, but CI
can audit the suppression inventory from the same artifact.
"""

from __future__ import annotations

import argparse
import os
import sys
from pathlib import Path


def _force_cpu_mesh(n_devices: int) -> None:
    """Pin the audit to a virtual CPU mesh regardless of ambient
    accelerators/plugins — the audited invariants are properties of the
    traced program, and CI machines differ."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    # An ambient PJRT plugin (e.g. a TPU proxy) overrides JAX_PLATFORMS
    # set this late; the config update wins as long as no backend has
    # been initialized yet in this process.
    jax.config.update("jax_platforms", "cpu")


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m masters_thesis_tpu.analysis",
        description="tracelint: static + trace-time TPU hot-path analysis",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        type=Path,
        help="files/directories to lint (default: the package source)",
    )
    parser.add_argument(
        "--skip-trace",
        action="store_true",
        help="run only Pass 1 (AST lint), skip the trace-time audit",
    )
    parser.add_argument(
        "--skip-lint",
        action="store_true",
        help="run only Pass 2 (trace-time audit)",
    )
    parser.add_argument(
        "--json", action="store_true", help="machine-readable findings"
    )
    parser.add_argument(
        "--trace-steps",
        type=int,
        default=3,
        metavar="N",
        help="epochs the trace audit runs through the compiled program",
    )
    parser.add_argument(
        "--trace-devices",
        type=int,
        default=8,
        metavar="N",
        help="virtual CPU devices for the audit mesh",
    )
    parser.add_argument(
        "--stacked-replicas",
        type=int,
        default=3,
        metavar="R",
        help="replica count for the stacked-program audit (TA207); "
        "0 skips it",
    )
    parser.add_argument(
        "--n-factors",
        type=int,
        default=1,
        metavar="K",
        help="factor count of the audited model/window schema (1 = the "
        "scalar market-model default; >1 audits the K-factor program)",
    )
    parser.add_argument(
        "--shard-axis",
        choices=("window", "asset"),
        default="window",
        help="train-split shard axis the audit builds the epoch program "
        "with ('asset' = the universe-scale mode; the factor leaf stays "
        "replicated by design)",
    )
    parser.add_argument(
        "--concurrency",
        action="store_true",
        help="run only the Pass-3 concurrency lint (CL501-CL505)",
    )
    parser.add_argument(
        "--contracts",
        action="store_true",
        help="run only the Pass-3 event-schema contract check "
        "(EC601-EC603; checks the lockfile when linting the package)",
    )
    parser.add_argument(
        "--spmd",
        action="store_true",
        help="run only the Pass-4 SPMD divergence lint (DV701-DV705) "
        "over the train/parallel/resilience/telemetry stack",
    )
    parser.add_argument(
        "--emit-schema",
        action="store_true",
        help="regenerate analysis/event_schema.json from the emitter "
        "sites and exit (0 on success)",
    )
    args = parser.parse_args(argv)

    import masters_thesis_tpu

    package_root = Path(masters_thesis_tpu.__file__).parent
    paths = args.paths or [package_root]
    lockfile = package_root / "analysis" / "event_schema.json"

    if args.emit_schema:
        import json

        from masters_thesis_tpu.analysis.contracts import build_schema

        schema = build_schema(paths, package_root=package_root)
        lockfile.write_text(json.dumps(schema, indent=2) + "\n")
        print(
            f"wrote {lockfile} "
            f"({len(schema['kinds'])} event kinds)"
        )
        return 0

    if args.concurrency or args.contracts or args.spmd:
        # --json keeps suppressed findings (marked) for CI's suppression
        # inventory; they never count toward the exit code.
        include_suppressed = args.json
        static: list = []
        if args.concurrency:
            from masters_thesis_tpu.analysis.concurrency import (
                lint_concurrency,
            )

            static.extend(
                lint_concurrency(
                    paths,
                    package_root=package_root,
                    include_suppressed=include_suppressed,
                )
            )
        if args.contracts:
            from masters_thesis_tpu.analysis.contracts import (
                lint_contracts,
            )

            static.extend(
                lint_contracts(
                    paths,
                    package_root=package_root,
                    schema_path=lockfile if not args.paths else None,
                    include_suppressed=include_suppressed,
                )
            )
        if args.spmd:
            from masters_thesis_tpu.analysis.spmd import lint_spmd

            # The SPMD stack: where collectives are issued (train/
            # parallel), supervised (resilience), and chained into the
            # runtime schedule audit (telemetry).
            spmd_paths = args.paths or [
                package_root / "train",
                package_root / "parallel",
                package_root / "resilience",
                package_root / "telemetry",
            ]
            static.extend(
                lint_spmd(
                    spmd_paths,
                    package_root=package_root,
                    include_suppressed=include_suppressed,
                )
            )
        from masters_thesis_tpu.analysis.findings import format_report

        static = sorted(
            set(static), key=lambda f: (f.path, f.line, f.rule, f.message)
        )
        print(format_report(static, as_json=args.json))
        return 1 if any(not f.suppressed for f in static) else 0

    findings = []
    if not args.skip_lint:
        from masters_thesis_tpu.analysis.astlint import lint_paths

        findings.extend(lint_paths(paths, package_root=package_root))
    if not args.skip_trace:
        _force_cpu_mesh(args.trace_devices)
        from masters_thesis_tpu.analysis.traceaudit import run_trace_audit

        spec = None
        if args.n_factors != 1:
            from masters_thesis_tpu.models.objectives import ModelSpec

            spec = ModelSpec(
                objective="mse", hidden_size=8, num_layers=1, dropout=0.0,
                kernel_impl="xla", n_factors=args.n_factors,
            )
        findings.extend(
            run_trace_audit(
                spec=spec,
                steps=args.trace_steps,
                stacked_replicas=args.stacked_replicas or None,
                shard_axis=args.shard_axis,
            )
        )

    from masters_thesis_tpu.analysis.findings import format_report

    print(format_report(findings, as_json=args.json))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
