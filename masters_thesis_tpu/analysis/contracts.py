"""Pass 3b — event-schema contract checker (EC601–EC603).

The telemetry stream is a wire protocol between two code populations that
never import each other: **emitters** (``EventSink.emit`` /
``TelemetryRun.event`` / the serve/fleet/supervisor ``_event`` wrappers /
``Tracer.emit_span``) and the **jax-free readers**
(``telemetry/report.py``, ``aggregate.py``, ``trace.py``, ``ledger.py``).
Nothing checks that protocol: an emitter renaming ``wall_s`` to
``wall_seconds`` silently turns every roofline into ``None``. This pass
recovers both sides of the contract from the AST:

- **Emitted shapes** — every call named ``emit``/``event``/``_event``/
  ``try_emit``/``record`` whose kind is a string literal (or a
  module-level string constant) contributes ``kind -> {field: types}``;
  keyword values are typed from constants (``str``/``number``/``bool``/
  ``list``/``dict``). A ``**payload`` expansion marks the kind *dynamic*
  (its field set is statically unknowable, so EC601 stands down for it).
  ``emit_span`` sites contribute the fixed span envelope. The sink's own
  envelope keys (``ts``/``kind``/``run``/``seq``/...) are always present.
- **Consumed fields** — reader functions are detected structurally, not
  by module list: a variable becomes *kind-bound* through
  ``if ev.get("kind") == "epoch":``, ``kind = ev.get("kind")`` +
  ``if kind == ...``, ``by_kind.get("epoch")`` on a kind-bucketed map,
  or a comprehension filtered on kind; ``v.get("field")`` / ``v["field"]``
  on a bound variable is a consumption. ``float(...)``/``int(...)``
  around a consumption records a numeric expectation.

Rules:

- **EC601** a field consumed under a kind no emitter ever emits (or a
  kind that is never emitted at all). Reserved envelope keys and dynamic
  kinds are exempt.
- **EC602** type disagreement: two emit sites give one field conflicting
  types, or a reader casts to a number a field only ever emitted as str.
- **EC603** drift against the checked-in ``analysis/event_schema.json``
  lockfile — regenerate with ``--emit-schema`` and review the diff like
  any other API change.

Same precision contract as every other pass: what the extraction cannot
prove, it does not flag. ``# mtt: disable=EC60x -- reason`` suppresses.
"""

from __future__ import annotations

import ast
import dataclasses
import json
from pathlib import Path

from masters_thesis_tpu.analysis.astlint import _module_name, discover_files
from masters_thesis_tpu.analysis.findings import (
    Finding,
    is_suppressed,
    suppressed_rules_by_line,
)

EMIT_METHOD_NAMES = {"emit", "event", "_event", "try_emit"}

# Keys the sink injects on every event (telemetry/events.py
# RESERVED_KEYS) — always considered emitted.
ENVELOPE_KEYS = {
    "ts", "kind", "run", "seq", "host", "pid", "proc", "nproc", "attempt",
    "generation",
}

# Fields Tracer._emit writes for every span event; an ``emit_span`` call
# site contributes exactly these (its **attrs land inside "attrs").
SPAN_ENVELOPE = {
    "name": "str", "cat": "str", "span_id": "str", "parent_id": "str",
    "trace_id": "str", "start_ts": "number", "dur_s": "number",
    "status": "str", "ext": "bool", "attrs": "dict",
}

_NUMERIC = {"number", "bool"}
_TYPE_GROUPS = ("str", "number", "list", "dict")


def _type_group(t: str) -> str | None:
    if t in _NUMERIC:
        return "number"
    if t in _TYPE_GROUPS:
        return t
    return None  # null/unknown never conflict


def _expr_type(node: ast.AST, consts: dict[str, str]) -> str:
    if isinstance(node, ast.Constant):
        v = node.value
        if isinstance(v, bool):
            return "bool"
        if isinstance(v, (int, float)):
            return "number"
        if isinstance(v, str):
            return "str"
        if v is None:
            return "null"
        return "unknown"
    if isinstance(node, ast.JoinedStr):
        return "str"
    if isinstance(node, ast.Dict):
        return "dict"
    if isinstance(node, (ast.List, ast.Tuple, ast.Set, ast.ListComp)):
        return "list"
    if isinstance(node, ast.Compare):
        return "bool"
    if isinstance(node, ast.BoolOp):
        # `x or "default"` yields one of the operands, not a boolean.
        types = {
            t
            for v in node.values
            for t in (_expr_type(v, consts),)
            if t not in ("unknown", "null")
        }
        return types.pop() if len(types) == 1 else "unknown"
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not):
        return "bool"
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        if node.func.id in ("float", "int", "len", "round", "abs", "sum"):
            return "number"
        if node.func.id in ("str", "repr"):
            return "str"
        if node.func.id == "bool":
            return "bool"
        if node.func.id in ("list", "sorted", "tuple"):
            return "list"
        if node.func.id == "dict":
            return "dict"
    if isinstance(node, ast.Name):
        const = consts.get(node.id)
        if const is not None:
            return "str"  # module-level string constant
    return "unknown"


def _literal_kind(node: ast.AST, consts: dict[str, str]) -> str | None:
    """String-literal (or module string-constant) event kind, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    return None


def _module_str_consts(tree: ast.AST) -> dict[str, str]:
    out: dict[str, str] = {}
    for node in ast.iter_child_nodes(tree):
        if isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Constant
        ) and isinstance(node.value.value, str):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out[tgt.id] = node.value.value
    return out


# ------------------------------------------------------------------- emitters


class EmittedSchema:
    def __init__(self) -> None:
        # kind -> field -> set of type names
        self.fields: dict[str, dict[str, set[str]]] = {}
        self.dynamic: set[str] = set()
        # (kind, field, type) -> first (path, line) witness
        self.sites: dict[tuple[str, str], list[tuple[str, int, str]]] = {}

    def note(
        self, kind: str, field: str, typ: str, path: str, line: int
    ) -> None:
        self.fields.setdefault(kind, {}).setdefault(field, set()).add(typ)
        self.sites.setdefault((kind, field), []).append((path, line, typ))

    def note_kind(self, kind: str) -> None:
        self.fields.setdefault(kind, {})


def _collect_emitters(
    trees: dict[str, tuple[Path, ast.AST]],
    consts_by_module: dict[str, dict[str, str]],
) -> EmittedSchema:
    schema = EmittedSchema()
    for module, (path, tree) in trees.items():
        consts = consts_by_module[module]
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            name = (
                func.attr if isinstance(func, ast.Attribute)
                else func.id if isinstance(func, ast.Name) else None
            )
            if name == "emit_span":
                schema.note_kind("span")
                for field, typ in SPAN_ENVELOPE.items():
                    schema.note(
                        "span", field, typ, str(path), node.lineno
                    )
                continue
            if name in EMIT_METHOD_NAMES and node.args:
                kind = _literal_kind(node.args[0], consts)
                if kind is None:
                    continue
                schema.note_kind(kind)
                for kw in node.keywords:
                    if kw.arg is None:  # **payload
                        schema.dynamic.add(kind)
                        continue
                    schema.note(
                        kind, kw.arg, _expr_type(kw.value, consts),
                        str(path), node.lineno,
                    )
            elif name == "record" and len(node.args) == 1 and isinstance(
                node.args[0], ast.Dict
            ):
                # flightrec-style `rec.record({"kind": "...", ...})`.
                d = node.args[0]
                keys = [
                    k.value
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)
                    else None
                    for k in d.keys
                ]
                if "kind" not in keys:
                    continue
                kind = None
                for k, v in zip(keys, d.values):
                    if k == "kind":
                        kind = _literal_kind(v, consts)
                if kind is None:
                    continue
                schema.note_kind(kind)
                for k, v in zip(keys, d.values):
                    if k is None:
                        schema.dynamic.add(kind)
                    elif k != "kind":
                        schema.note(
                            kind, k, _expr_type(v, consts),
                            str(path), node.lineno,
                        )
    return schema


# -------------------------------------------------------------------- readers


class Consumption:
    __slots__ = ("kind", "field", "expect", "path", "line")

    def __init__(self, kind, field, expect, path, line):
        self.kind, self.field = kind, field
        self.expect, self.path, self.line = expect, path, line


def _is_kind_map(name: str) -> bool:
    return "kind" in name


class _ReaderWalker:
    """Per-function kind-binding and consumption extraction.

    Flow handling is optimistic and scoped: ``if`` bodies get branch-local
    bindings, loops bind their element var for the body, comprehensions
    bind generator vars locally. Anything unresolvable is simply not
    attributed — precision over recall.
    """

    def __init__(self, path: str, consts: dict[str, str]):
        self.path = path
        self.consts = consts
        self.out: list[Consumption] = []

    def run(self, fn: ast.FunctionDef) -> list[Consumption]:
        env: dict[str, str] = {}  # dict-var -> kind
        lists: dict[str, str] = {}  # list-var -> kind
        sel: dict[str, str] = {}  # kind-selector var -> source dict var
        self._stmts(fn.body, env, lists, sel)
        return self.out

    # -- statements ------------------------------------------------------

    def _stmts(self, body, env, lists, sel) -> None:
        for stmt in body:
            self._stmt(stmt, env, lists, sel)

    def _stmt(self, stmt, env, lists, sel) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and (
            isinstance(stmt.targets[0], ast.Name)
        ):
            tgt = stmt.targets[0].id
            self._bind(tgt, stmt.value, env, lists, sel)
            self._expr(stmt.value, env, lists, sel)
            return
        if isinstance(stmt, ast.If):
            bound = self._kind_test(stmt.test, env, lists, sel)
            self._expr(stmt.test, env, lists, sel)
            if bound is not None:
                var, kind = bound
                inner = dict(env)
                inner[var] = kind
                self._stmts(stmt.body, inner, lists, sel)
            else:
                self._stmts(stmt.body, dict(env), dict(lists), dict(sel))
            self._stmts(stmt.orelse, env, lists, sel)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._expr(stmt.iter, env, lists, sel)
            inner = dict(env)
            if isinstance(stmt.target, ast.Name):
                kind = self._list_kind(stmt.iter, lists)
                if kind is not None:
                    inner[stmt.target.id] = kind
            self._stmts(stmt.body, inner, lists, sel)
            self._stmts(stmt.orelse, env, lists, sel)
            return
        if isinstance(stmt, (ast.While,)):
            self._expr(stmt.test, env, lists, sel)
            self._stmts(stmt.body, dict(env), dict(lists), dict(sel))
            self._stmts(stmt.orelse, env, lists, sel)
            return
        if isinstance(stmt, ast.With):
            for item in stmt.items:
                self._expr(item.context_expr, env, lists, sel)
            self._stmts(stmt.body, env, lists, sel)
            return
        if isinstance(stmt, ast.Try):
            self._stmts(stmt.body, env, lists, sel)
            for h in stmt.handlers:
                self._stmts(h.body, env, lists, sel)
            self._stmts(stmt.orelse, env, lists, sel)
            self._stmts(stmt.finalbody, env, lists, sel)
            return
        for child in ast.iter_child_nodes(stmt):
            self._expr(child, env, lists, sel)

    # -- binding patterns ------------------------------------------------

    def _bind(self, tgt: str, value: ast.AST, env, lists, sel) -> None:
        # k = ev.get("kind")
        got = self._get_call(value)
        if got is not None:
            recv, key, _default = got
            if key == "kind" and isinstance(recv, ast.Name):
                sel[tgt] = recv.id
                return
        # xs = by_kind.get("epoch" [, []]) / by_kind["epoch"]
        kind = self._kind_map_lookup(value)
        if kind is not None:
            lists[tgt] = kind
            return
        # d = (by_kind.get("run_finished") or [None])[-1]
        if isinstance(value, ast.Subscript):
            base = value.value
            if isinstance(base, ast.BoolOp):
                for operand in base.values:
                    kind = self._kind_map_lookup(operand)
                    if kind is not None:
                        env[tgt] = kind
                        return
            kind = self._list_kind(base, lists)
            if kind is not None:
                env[tgt] = kind
                return
        # xs = [e for e in events if e.get("kind") == "epoch"]
        if isinstance(value, (ast.ListComp, ast.GeneratorExp)):
            kind = self._comp_kind(value, lists)
            if kind is not None:
                lists[tgt] = kind
                return
        # alias copies
        if isinstance(value, ast.Name):
            if value.id in lists:
                lists[tgt] = lists[value.id]
            if value.id in env:
                env[tgt] = env[value.id]

    def _kind_map_lookup(self, node: ast.AST) -> str | None:
        got = self._get_call(node)
        if got is not None:
            recv, key, _d = got
            if isinstance(recv, ast.Name) and _is_kind_map(recv.id):
                return key
        if isinstance(node, ast.Subscript) and isinstance(
            node.value, ast.Name
        ) and _is_kind_map(node.value.id):
            key = self._const_str(node.slice)
            if key is not None:
                return key
        return None

    def _comp_kind(self, comp, lists) -> str | None:
        """Kind of a single-generator comprehension over events filtered
        on kind, walking its interior consumptions along the way."""
        if len(comp.generators) != 1:
            return None
        gen = comp.generators[0]
        kind = self._list_kind(gen.iter, lists)
        var = gen.target.id if isinstance(gen.target, ast.Name) else None
        if kind is None and var is not None:
            for cond in gen.ifs:
                bound = self._kind_test(cond, {}, lists, {})
                if bound is not None and bound[0] == var:
                    kind = bound[1]
        if var is not None and kind is not None:
            inner = {var: kind}
            self._expr(comp.elt, inner, lists, {})
            for cond in gen.ifs:
                self._expr(cond, inner, lists, {})
        return kind

    def _list_kind(self, node: ast.AST, lists) -> str | None:
        if isinstance(node, ast.Name):
            return lists.get(node.id)
        kind = self._kind_map_lookup(node)
        if kind is not None:
            return kind
        if isinstance(node, ast.BoolOp):
            for operand in node.values:
                k = self._list_kind(operand, lists)
                if k is not None:
                    return k
        if isinstance(node, (ast.ListComp, ast.GeneratorExp)):
            return self._comp_kind(node, lists)
        if isinstance(node, ast.Call) and isinstance(
            node.func, ast.Name
        ) and node.func.id in ("reversed", "sorted", "list"):
            if node.args:
                return self._list_kind(node.args[0], lists)
        return None

    def _kind_test(self, test, env, lists, sel) -> tuple[str, str] | None:
        """`ev.get("kind") == "K"` / `ev["kind"] == "K"` / `k == "K"`
        (k a kind-selector var) -> (dict var, kind)."""
        if not (
            isinstance(test, ast.Compare)
            and len(test.ops) == 1
            and isinstance(test.ops[0], ast.Eq)
        ):
            if isinstance(test, ast.BoolOp) and isinstance(
                test.op, ast.And
            ):
                for operand in test.values:
                    bound = self._kind_test(operand, env, lists, sel)
                    if bound is not None:
                        return bound
            return None
        left, right = test.left, test.comparators[0]
        for a, b in ((left, right), (right, left)):
            kind = self._literal(b)
            if kind is None:
                continue
            got = self._get_call(a)
            if got is not None and got[1] == "kind" and isinstance(
                got[0], ast.Name
            ):
                return (got[0].id, kind)
            if isinstance(a, ast.Subscript) and isinstance(
                a.value, ast.Name
            ) and self._const_str(a.slice) == "kind":
                return (a.value.id, kind)
            if isinstance(a, ast.Name) and a.id in sel:
                return (sel[a.id], kind)
        return None

    # -- consumption -----------------------------------------------------

    def _expr(self, node: ast.AST, env, lists, sel, expect=None) -> None:
        if node is None or isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            return
        if isinstance(node, (ast.ListComp, ast.GeneratorExp, ast.SetComp)):
            self._comp_kind(node, lists)
            return
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Name) and node.func.id in (
                "float", "int"
            ) and len(node.args) == 1:
                self._expr(node.args[0], env, lists, sel, expect="number")
                return
            got = self._get_call(node)
            if got is not None:
                recv, key, default = got
                kind = self._recv_kind(recv, env, lists)
                if kind is not None and key != "kind":
                    self.out.append(
                        Consumption(
                            kind, key, expect, self.path, node.lineno
                        )
                    )
                self._expr(recv, env, lists, sel)
                if default is not None:
                    self._expr(default, env, lists, sel)
                return
        if isinstance(node, ast.Subscript):
            key = self._const_str(node.slice)
            if key is not None and key != "kind":
                kind = self._recv_kind(node.value, env, lists)
                if kind is not None:
                    self.out.append(
                        Consumption(
                            kind, key, expect, self.path, node.lineno
                        )
                    )
        for child in ast.iter_child_nodes(node):
            self._expr(child, env, lists, sel, expect)

    def _recv_kind(self, recv: ast.AST, env, lists) -> str | None:
        if isinstance(recv, ast.Name):
            return env.get(recv.id)
        # crash_events[-1].get("reason") — subscript of a kind list.
        if isinstance(recv, ast.Subscript):
            base_kind = self._list_kind(recv.value, lists)
            if base_kind is not None:
                return base_kind
        return None

    # -- small helpers ---------------------------------------------------

    def _get_call(self, node):
        """(receiver, literal key, default|None) for `x.get("k"[, d])`."""
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "get"
            and node.args
        ):
            key = self._literal(node.args[0])
            if key is not None:
                default = node.args[1] if len(node.args) > 1 else None
                return (node.func.value, key, default)
        return None

    def _literal(self, node) -> str | None:
        return _literal_kind(node, self.consts)

    def _const_str(self, node) -> str | None:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        return None


def _collect_consumptions(
    trees: dict[str, tuple[Path, ast.AST]],
    consts_by_module: dict[str, dict[str, str]],
) -> list[Consumption]:
    out: list[Consumption] = []
    for module, (path, tree) in trees.items():
        consts = consts_by_module[module]
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.extend(
                    _ReaderWalker(str(path), consts).run(node)
                )
    return out


# --------------------------------------------------------------------- schema


def build_schema(
    paths: list[Path | str], package_root: Path | str | None = None
) -> dict:
    """Emitted-event inventory as the lockfile JSON structure."""
    trees, consts, _sources = _parse(paths, package_root)
    emitted = _collect_emitters(trees, consts)
    kinds = {}
    for kind in sorted(emitted.fields):
        kinds[kind] = {
            "dynamic": kind in emitted.dynamic,
            "fields": {
                f: sorted(t for t in types)
                for f, types in sorted(emitted.fields[kind].items())
            },
        }
    return {"version": 1, "kinds": kinds}


def _parse(paths, package_root):
    paths = [Path(p) for p in paths]
    if package_root is None:
        package_root = next((p for p in paths if p.is_dir()), None)
    trees: dict[str, tuple[Path, ast.AST]] = {}
    consts: dict[str, dict[str, str]] = {}
    sources: dict[str, str] = {}
    for f in discover_files(paths):
        module = _module_name(
            f, Path(package_root) if package_root else None
        )
        try:
            src = f.read_text()
            tree = ast.parse(src, filename=str(f))
        except SyntaxError:
            continue
        trees[module] = (f, tree)
        consts[module] = _module_str_consts(tree)
        sources[module] = src
    return trees, consts, sources


# ---------------------------------------------------------------- entry point


def lint_contracts(
    paths: list[Path | str],
    package_root: Path | str | None = None,
    schema_path: Path | str | None = None,
    include_suppressed: bool = False,
) -> list[Finding]:
    """Run EC601–EC603 over files/directories.

    ``schema_path``: lockfile to diff against (EC603); ``None`` skips the
    drift check (used when linting ad-hoc paths rather than the package).
    ``include_suppressed=True`` keeps suppression-matched findings
    (marked ``Finding.suppressed``) for the ``--json`` CI surface.
    """
    trees, consts, sources = _parse(paths, package_root)
    emitted = _collect_emitters(trees, consts)
    consumed = _collect_consumptions(trees, consts)
    findings: list[Finding] = []

    # EC601 — consumed but never emitted.
    seen_601: set[tuple[str, str]] = set()
    for c in consumed:
        if c.field in ENVELOPE_KEYS or c.kind in emitted.dynamic:
            continue
        if (c.kind, c.field) in seen_601:
            continue
        if c.kind not in emitted.fields:
            seen_601.add((c.kind, c.field))
            findings.append(
                Finding(
                    "EC601",
                    f"reader consumes '{c.field}' of kind '{c.kind}', "
                    "but no emitter ever emits that kind",
                    c.path,
                    c.line,
                )
            )
        elif c.field not in emitted.fields[c.kind]:
            seen_601.add((c.kind, c.field))
            findings.append(
                Finding(
                    "EC601",
                    f"reader consumes field '{c.field}' of kind "
                    f"'{c.kind}', which no emitter site emits "
                    f"(emitted fields: "
                    f"{sorted(emitted.fields[c.kind]) or '(none)'})",
                    c.path,
                    c.line,
                )
            )

    # EC602a — emitter sites disagree on a field's type.
    for (kind, field), sites in sorted(emitted.sites.items()):
        groups = {}
        for path, line, typ in sites:
            g = _type_group(typ)
            if g is not None:
                groups.setdefault(g, (path, line, typ))
        if len(groups) > 1:
            detail = ", ".join(
                f"{typ} at {Path(path).name}:{line}"
                for _g, (path, line, typ) in sorted(groups.items())
            )
            path, line, _t = sites[0]
            findings.append(
                Finding(
                    "EC602",
                    f"emit sites disagree on the type of "
                    f"'{kind}.{field}': {detail}",
                    path,
                    line,
                )
            )

    # EC602b — reader numeric cast of a str-only field.
    seen_602: set[tuple[str, str]] = set()
    for c in consumed:
        if c.expect != "number" or (c.kind, c.field) in seen_602:
            continue
        types = emitted.fields.get(c.kind, {}).get(c.field)
        if types and all(_type_group(t) == "str" for t in types):
            seen_602.add((c.kind, c.field))
            findings.append(
                Finding(
                    "EC602",
                    f"reader casts '{c.kind}.{c.field}' to a number, but "
                    "every emit site emits it as str",
                    c.path,
                    c.line,
                )
            )

    # EC603 — lockfile drift.
    if schema_path is not None:
        findings.extend(
            _schema_drift(
                build_schema(paths, package_root), Path(schema_path)
            )
        )

    # Per-line suppressions.
    sup_by_path = {
        str(p): suppressed_rules_by_line(sources[m])
        for m, (p, _t) in trees.items()
    }
    out: list[Finding] = []
    for f in findings:
        if not is_suppressed(f, sup_by_path.get(f.path, {})):
            out.append(f)
        elif include_suppressed:
            out.append(dataclasses.replace(f, suppressed=True))
    return sorted(out, key=lambda f: (f.path, f.line, f.rule, f.message))


def _schema_drift(current: dict, schema_path: Path) -> list[Finding]:
    path = str(schema_path)
    if not schema_path.exists():
        return [
            Finding(
                "EC603",
                "event-schema lockfile missing — generate it with "
                "`python -m masters_thesis_tpu.analysis --emit-schema`",
                path,
                0,
            )
        ]
    try:
        locked = json.loads(schema_path.read_text())
    except (OSError, json.JSONDecodeError) as exc:
        return [
            Finding("EC603", f"unreadable lockfile: {exc}", path, 0)
        ]
    findings: list[Finding] = []
    cur_kinds = current.get("kinds", {})
    old_kinds = locked.get("kinds", {})
    for kind in sorted(set(cur_kinds) - set(old_kinds)):
        findings.append(
            Finding(
                "EC603",
                f"new event kind '{kind}' is not in the lockfile "
                "(--emit-schema to accept)",
                path,
                0,
            )
        )
    for kind in sorted(set(old_kinds) - set(cur_kinds)):
        findings.append(
            Finding(
                "EC603",
                f"event kind '{kind}' is in the lockfile but no longer "
                "emitted (--emit-schema to accept the removal)",
                path,
                0,
            )
        )
    for kind in sorted(set(cur_kinds) & set(old_kinds)):
        cur_f = cur_kinds[kind].get("fields", {})
        old_f = old_kinds[kind].get("fields", {})
        for field in sorted(set(cur_f) - set(old_f)):
            findings.append(
                Finding(
                    "EC603",
                    f"'{kind}.{field}' emitted but not in the lockfile",
                    path,
                    0,
                )
            )
        for field in sorted(set(old_f) - set(cur_f)):
            findings.append(
                Finding(
                    "EC603",
                    f"'{kind}.{field}' in the lockfile but no longer "
                    "emitted",
                    path,
                    0,
                )
            )
        for field in sorted(set(cur_f) & set(old_f)):
            if sorted(cur_f[field]) != sorted(old_f[field]):
                findings.append(
                    Finding(
                        "EC603",
                        f"'{kind}.{field}' types changed: lockfile "
                        f"{sorted(old_f[field])} vs emitted "
                        f"{sorted(cur_f[field])}",
                        path,
                        0,
                    )
                )
    return findings
