"""Finding records, rule registry, per-line suppression, and reporting.

Every rule — AST (``TL1xx``) and trace-time (``TA2xx``) — registers here so
the CLI, the docs, and the suppression parser share one source of truth.
"""

from __future__ import annotations

import dataclasses
import json
import re

# Rule registry: id -> (title, rationale category). Categories mirror the
# four ways the hot path degrades: retrace, transfer, precision, sharding
# (plus tracer-safety, which is a correctness hazard before it is a perf
# one).
RULES: dict[str, tuple[str, str]] = {
    "TL101": (
        "tracer leaked to host cast (float()/int()/bool()/.item()/.tolist() "
        "on a traced value inside jitted code)",
        "tracer-safety / transfer",
    ),
    "TL102": (
        "Python control flow on a traced value (if/while/for over a jnp "
        "expression inside jitted code)",
        "tracer-safety / recompile",
    ),
    "TL103": (
        "PRNG key consumed more than once without split/fold_in",
        "correctness (correlated randomness)",
    ),
    "TL104": (
        "float64 literal / x64 enablement (dtype-promotion hazard)",
        "precision",
    ),
    "TL105": (
        "host transfer inside jit-reachable code (jax.device_get/device_put, "
        "np.* on traced values, block_until_ready)",
        "transfer",
    ),
    "TA201": (
        "train step recompiled across steps (compile count > 1)",
        "recompile",
    ),
    "TA202": (
        "host<->device transfer inside the hot loop (transfer_guard tripped)",
        "transfer",
    ),
    "TA203": (
        "bad sharding: batch axis not sharded / params not replicated / "
        "unexpected all-gather in the compiled program",
        "sharding",
    ),
    "TA204": (
        "output dtype does not match the configured precision policy",
        "precision",
    ),
    "TA205": (
        "trace-time audit could not run to completion",
        "infrastructure",
    ),
    # SV3xx: serve preflight (serve/preflight.py) — same categories, but
    # the program under audit is the AOT predict executable per bucket.
    "SV301": (
        "serve bucket compiled more than once / recompiled after warmup "
        "(steady-state serving must never trace)",
        "recompile",
    ),
    "SV302": (
        "implicit host<->device transfer in the serve hot path "
        "(transfer_guard tripped; request I/O must be explicit device_put/"
        "device_get only)",
        "transfer",
    ),
    "SV303": (
        "serve preflight could not run to completion",
        "infrastructure",
    ),
    "SV304": (
        "serve bucket peak memory (memory_analysis) exceeds the backend's "
        "reported device memory — the bucket would OOM at first request",
        "memory",
    ),
    # CP4xx: cost & utilization observability (telemetry/costs.py) — static
    # cost models from compiled executables plus roofline attribution.
    "CP401": (
        "cost model unavailable: the backend reported no cost_analysis for "
        "a hot program, so utilization/roofline gauges are flying blind",
        "infrastructure",
    ),
    "CP402": (
        "compiled-program peak memory exceeds the device memory budget",
        "memory",
    ),
    "CP403": (
        "achieved FLOP/s below the utilization floor on a real TPU backend "
        "(the program cannot feed the MXU; see docs/telemetry.md roofline "
        "playbook)",
        "utilization",
    ),
}

_SUPPRESS_RE = re.compile(
    r"#\s*(?:tracelint:\s*disable|noqa:?)\s*(?:=\s*)?(?P<ids>[A-Z]{2}\d{3}(?:\s*,\s*[A-Z]{2}\d{3})*)?"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    message: str
    path: str = "<trace>"
    line: int = 0

    def format(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"{loc}: {self.rule} {self.message}"


def suppressed_rules_by_line(source: str) -> dict[int, set[str] | None]:
    """Map 1-based line number -> suppressed rule ids (None = all rules).

    Recognises ``# tracelint: disable=TL101`` (per-rule, comma-separable),
    ``# tracelint: disable`` (whole line), and ``# noqa: TL101`` for
    composition with standard linting.
    """
    out: dict[int, set[str] | None] = {}
    for lineno, text in enumerate(source.splitlines(), start=1):
        if "#" not in text:
            continue
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        ids = m.group("ids")
        # A bare "# noqa" (no rule list) from standard linting should not
        # silently swallow tracelint findings unless it is the tracelint
        # spelling.
        if ids is None:
            if "tracelint" in text:
                out[lineno] = None
            continue
        out[lineno] = {part.strip() for part in ids.split(",")}
    return out


def is_suppressed(
    finding: Finding, suppressions: dict[int, set[str] | None]
) -> bool:
    rules = suppressions.get(finding.line, ())
    return rules is None or finding.rule in rules


def format_report(findings: list[Finding], as_json: bool = False) -> str:
    if as_json:
        return json.dumps(
            [dataclasses.asdict(f) for f in findings], indent=2
        )
    if not findings:
        return "tracelint: no findings"
    lines = [f.format() for f in findings]
    lines.append(f"tracelint: {len(findings)} finding(s)")
    return "\n".join(lines)
