"""Finding records, rule registry, per-line suppression, and reporting.

Every rule — AST (``TL1xx``), trace-time (``TA2xx``), serve preflight
(``SV3xx``), cost (``CP4xx``), concurrency (``CL5xx``), and event contract
(``EC6xx``) — registers here so the CLI, the docs, and the suppression
parser share one source of truth.

Suppression syntax is unified across every pass. The canonical spelling::

    self._beats += 1  # mtt: disable=CL502 -- single-writer heartbeat counter

requires a justification after ``--``; a rule-bearing suppression without
one still suppresses (so a migration never *adds* noise) but is itself
reported as ``SP001`` by the gate. The legacy ``# tracelint: disable=...``
spelling and ``# noqa: TLxxx`` remain parsed for back-compat and ruff
interop; a bare ``# noqa`` never swallows findings.
"""

from __future__ import annotations

import dataclasses
import json
import re

# Rule registry: id -> (title, rationale category). Categories mirror the
# four ways the hot path degrades: retrace, transfer, precision, sharding
# (plus tracer-safety, which is a correctness hazard before it is a perf
# one).
RULES: dict[str, tuple[str, str]] = {
    "TL101": (
        "tracer leaked to host cast (float()/int()/bool()/.item()/.tolist() "
        "on a traced value inside jitted code)",
        "tracer-safety / transfer",
    ),
    "TL102": (
        "Python control flow on a traced value (if/while/for over a jnp "
        "expression inside jitted code)",
        "tracer-safety / recompile",
    ),
    "TL103": (
        "PRNG key consumed more than once without split/fold_in",
        "correctness (correlated randomness)",
    ),
    "TL104": (
        "float64 literal / x64 enablement (dtype-promotion hazard)",
        "precision",
    ),
    "TL105": (
        "host transfer inside jit-reachable code (jax.device_get/device_put, "
        "np.* on traced values, block_until_ready)",
        "transfer",
    ),
    "TA201": (
        "train step recompiled across steps (compile count > 1)",
        "recompile",
    ),
    "TA202": (
        "host<->device transfer inside the hot loop (transfer_guard tripped)",
        "transfer",
    ),
    "TA203": (
        "bad sharding: batch axis not sharded / params not replicated / "
        "unexpected all-gather in the compiled program",
        "sharding",
    ),
    "TA204": (
        "output dtype does not match the configured precision policy",
        "precision",
    ),
    "TA205": (
        "trace-time audit could not run to completion",
        "infrastructure",
    ),
    # SV3xx: serve preflight (serve/preflight.py) — same categories, but
    # the program under audit is the AOT predict executable per bucket.
    "SV301": (
        "serve bucket compiled more than once / recompiled after warmup "
        "(steady-state serving must never trace)",
        "recompile",
    ),
    "SV302": (
        "implicit host<->device transfer in the serve hot path "
        "(transfer_guard tripped; request I/O must be explicit device_put/"
        "device_get only)",
        "transfer",
    ),
    "SV303": (
        "serve preflight could not run to completion",
        "infrastructure",
    ),
    "SV304": (
        "serve bucket peak memory (memory_analysis) exceeds the backend's "
        "reported device memory — the bucket would OOM at first request",
        "memory",
    ),
    # CP4xx: cost & utilization observability (telemetry/costs.py) — static
    # cost models from compiled executables plus roofline attribution.
    "CP401": (
        "cost model unavailable: the backend reported no cost_analysis for "
        "a hot program, so utilization/roofline gauges are flying blind",
        "infrastructure",
    ),
    "CP402": (
        "compiled-program peak memory exceeds the device memory budget",
        "memory",
    ),
    "CP403": (
        "achieved FLOP/s below the utilization floor on a real TPU backend "
        "(the program cannot feed the MXU; see docs/telemetry.md roofline "
        "playbook)",
        "utilization",
    ),
    # CL5xx: host-side concurrency lint (analysis/concurrency.py) — the
    # threaded serving/telemetry stack, where the hazard is a deadlock or
    # a torn read rather than a retrace.
    "CL501": (
        "lock-order inversion: a cycle in the acquires-while-holding graph "
        "— two code paths take the same locks in opposite orders",
        "concurrency / deadlock",
    ),
    "CL502": (
        "unguarded shared state: an attribute of a thread-shared object is "
        "mutated (read-modify-write) or accessed without the lock that "
        "guards its other accesses",
        "concurrency / race",
    ),
    "CL503": (
        "blocking call under a held lock (I/O, subprocess, time.sleep, "
        "queue waits, device compute) — every other thread contending on "
        "the lock stalls for the duration",
        "concurrency / latency",
    ),
    "CL504": (
        "non-signal-safe work in signal-handler-reachable code (blocking "
        "lock acquire, sleep, join, wait) — Python handlers run on the "
        "main thread, so a blocking acquire of a lock the interrupted "
        "frame holds is a self-deadlock",
        "concurrency / deadlock",
    ),
    "CL505": (
        "thread lifecycle: a non-daemon thread that is never joined, or a "
        "thread spawned in __init__ with no stop/join path on the class",
        "concurrency / lifecycle",
    ),
    # EC6xx: event-stream contract (analysis/contracts.py) — emitters
    # (EventSink.emit / TelemetryRun.event / _event wrappers / emit_span)
    # versus the jax-free readers (report/aggregate/trace/ledger).
    "EC601": (
        "event field consumed by a reader but never emitted under that "
        "kind by any emitter site",
        "contract",
    ),
    "EC602": (
        "emitter/reader type disagreement for an event field (e.g. a "
        "reader casts to float a field only ever emitted as str)",
        "contract",
    ),
    "EC603": (
        "event schema drift: the emitted-event inventory no longer "
        "matches analysis/event_schema.json (regenerate with "
        "--emit-schema and review the diff)",
        "contract",
    ),
    # DV7xx: SPMD divergence lint (analysis/spmd.py) — host-divergent
    # values (rank, env, wall clock, unseeded RNG, per-host sizes)
    # steering the collective schedule, the canonical multi-host wedge.
    "DV701": (
        "rank-divergent control flow guards a collective / fleet_barrier: "
        "only one branch (or a host-divergent early exit) reaches it, so "
        "ranks disagree on whether the collective runs",
        "spmd / deadlock",
    ),
    "DV702": (
        "collective-order divergence: both branches of host-divergent "
        "control flow reach collectives, but in different order or kind — "
        "ranks issue mismatched schedules",
        "spmd / deadlock",
    ),
    "DV703": (
        "host-divergent value flows into a collective operand or a traced "
        "array shape — per-rank shapes/operands break the SPMD program "
        "contract",
        "spmd / correctness",
    ),
    "DV704": (
        "nondeterminism on the checkpoint publish/resume path (wall clock, "
        "unseeded RNG, unsorted set/dir iteration) — breaks bit-identical "
        "multi-rank resume",
        "spmd / determinism",
    ),
    "DV705": (
        "rank-0-only side effect not fenced by a named barrier in the same "
        "function — other ranks can race past the mutation",
        "spmd / race",
    ),
    # SP0xx: suppression hygiene (enforced by the Pass-3 file scan).
    "SP001": (
        "suppression without justification: '# mtt: disable=<RULE>' "
        "requires a reason after ' -- '",
        "hygiene",
    ),
}

_DISABLE_RE = re.compile(
    r"#\s*(?P<spelling>mtt|tracelint):\s*disable"
    r"(?:\s*=\s*(?P<ids>[A-Z]{2}\d{3}(?:\s*,\s*[A-Z]{2}\d{3})*))?"
    r"(?:\s*--\s*(?P<reason>\S.*?))?\s*$"
)
_NOQA_RE = re.compile(
    r"#\s*noqa:?\s*(?:=\s*)?(?P<ids>[A-Z]{2}\d{3}(?:\s*,\s*[A-Z]{2}\d{3})*)?"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    message: str
    path: str = "<trace>"
    line: int = 0
    #: True when a per-line suppression matched this finding. Suppressed
    #: findings are dropped from text reports and exit codes; ``--json``
    #: keeps them (marked) so CI can audit the suppression inventory.
    suppressed: bool = False

    def format(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        mark = " [suppressed]" if self.suppressed else ""
        return f"{loc}: {self.rule} {self.message}{mark}"


@dataclasses.dataclass(frozen=True)
class Suppression:
    """One parsed per-line suppression comment."""

    line: int
    rules: frozenset[str] | None  # None = every rule on this line
    reason: str | None
    spelling: str  # "mtt" | "tracelint" | "noqa"


def parse_suppressions(source: str) -> list[Suppression]:
    """The ONE suppression parser shared by every pass (TL/TA/CL/EC).

    Recognises, in priority order on each line:

    - ``# mtt: disable=CL502 -- reason`` — canonical; comma-separable
      rule list; the reason is mandatory (``SP001`` otherwise).
    - ``# tracelint: disable[=TL101]`` — legacy alias, same semantics
      (a missing reason is still ``SP001``); bare form disables all
      rules on the line.
    - ``# noqa: TL103`` — ruff/flake8 interop; only with explicit rule
      ids (a bare ``# noqa`` never swallows findings).
    """
    out: list[Suppression] = []
    for lineno, text in enumerate(source.splitlines(), start=1):
        if "#" not in text:
            continue
        m = _DISABLE_RE.search(text)
        if m is not None:
            ids = m.group("ids")
            rules = (
                frozenset(p.strip() for p in ids.split(","))
                if ids is not None
                else None
            )
            out.append(
                Suppression(
                    line=lineno,
                    rules=rules,
                    reason=m.group("reason"),
                    spelling=m.group("spelling"),
                )
            )
            continue
        m = _NOQA_RE.search(text)
        if m is not None and m.group("ids") is not None:
            out.append(
                Suppression(
                    line=lineno,
                    rules=frozenset(
                        p.strip() for p in m.group("ids").split(",")
                    ),
                    reason=None,
                    spelling="noqa",
                )
            )
    return out


def suppressed_rules_by_line(source: str) -> dict[int, set[str] | None]:
    """Map 1-based line number -> suppressed rule ids (None = all rules)."""
    out: dict[int, set[str] | None] = {}
    for sup in parse_suppressions(source):
        out[sup.line] = None if sup.rules is None else set(sup.rules)
    return out


def suppression_findings(source: str, path: str) -> list[Finding]:
    """``SP001`` for every mtt/tracelint suppression lacking a reason.

    Emitted by the Pass-3 file scan (concurrency.py) so the gate sees it
    exactly once per line; ``noqa`` spellings are ruff's jurisdiction and
    exempt. The reason-less suppression still *works* — the gate fails on
    the hygiene finding instead of surprising the author with the
    original rule re-firing.
    """
    out = []
    for sup in parse_suppressions(source):
        if sup.spelling in ("mtt", "tracelint") and not sup.reason:
            rules = ",".join(sorted(sup.rules)) if sup.rules else "<all>"
            out.append(
                Finding(
                    "SP001",
                    f"suppression of {rules} has no reason — write "
                    "'# mtt: disable=<RULE> -- <why this is safe>'",
                    path,
                    sup.line,
                )
            )
    return out


def is_suppressed(
    finding: Finding, suppressions: dict[int, set[str] | None]
) -> bool:
    rules = suppressions.get(finding.line, ())
    return rules is None or finding.rule in rules


def format_report(findings: list[Finding], as_json: bool = False) -> str:
    if as_json:
        return json.dumps(
            [dataclasses.asdict(f) for f in findings], indent=2
        )
    if not findings:
        return "tracelint: no findings"
    lines = [f.format() for f in findings]
    lines.append(f"tracelint: {len(findings)} finding(s)")
    return "\n".join(lines)
