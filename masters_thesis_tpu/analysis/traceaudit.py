"""Pass 2 — trace-time audit of the real train-epoch program.

The AST lint (Pass 1) reasons about source; this pass builds the ACTUAL
jitted shard_map+scan epoch program from a tiny synthetic config, runs it,
and asserts the invariants the framework's performance contract rests on:

- **TA201** — the epoch program compiles exactly once. Running N epochs
  with varying rngs must not grow the jit cache (a second entry means a
  shape/dtype/sharding leak in the epoch signature — the multi-second
  recompile bug class that explicit in/out shardings in steps.py exist to
  prevent).
- **TA202** — ``jax.transfer_guard("disallow")`` holds over the hot loop:
  with all inputs device-resident, no step may touch the host.
- **TA203** — sharding: the compiled program takes the train split sharded
  on the batch axis and params replicated, and the HLO contains no
  all-gather (a sharding regression turns the psum/pmean pattern into
  gathering the full split onto every device).
- **TA204** — dtype policy: parameters come back in their input dtype
  (no silent upcast/downcast through the optimizer fold) and metric sums
  accumulate in float32.
- **TA205** — the audit itself could not run; the finding carries the
  exception. Infrastructure failures must be loud, not a green check.
- **TA206** — the per-step hot path syncs gradients as exactly ONE
  cross-replica reduction: the compiled epoch program's while-loop body
  contains a single ``all-reduce`` (the flat-buffer ``pmean``,
  train/flatparams.py). A second in-loop collective means the flat update
  path regressed to per-leaf reductions — the r4 sharding-overhead bug
  class (8-device slower than 1 at equal total work, RESULTS.md).
- **TA207** — the STACKED epoch program (R replicas as a vmap axis,
  train/steps.py:make_stacked_train_epoch) compiles exactly once across
  varied-input epochs AND still lowers to exactly one all-reduce per
  dtype buffer per step: ``lax.pmean`` under ``vmap`` must batch into a
  single collective over the ``[R, n]`` buffer. R per-replica collectives
  (or a recompile per replica-count/lr change) would erase the entire
  cells/hour win the stacked path exists for.

Everything is sized to run in seconds on CPU (``JAX_PLATFORMS=cpu`` with
the 8-device virtual mesh) — the same invariants transfer to TPU because
they are properties of the traced program, not the backend.
"""

from __future__ import annotations

import re

import jax
import jax.numpy as jnp
import numpy as np

from masters_thesis_tpu.analysis.findings import Finding

# Tiny-but-real audit geometry: 2 local steps per device per epoch.
AUDIT_STOCKS = 4
AUDIT_LOOKBACK = 8
AUDIT_FEATURES = 3
AUDIT_BATCH = 2
AUDIT_STEPS = 3


def count_step_collectives(compiled_hlo: str) -> int:
    """Count cross-replica reductions in the per-step hot path (TA206/TA207).

    Counts compiled-HLO ``all-reduce`` ops whose ``op_name`` metadata
    places them inside the scan's while-loop body (``.../while/body/...``,
    or ``.../vmap(while)/body/...`` when the scan runs under the stacked
    path's replica vmap). The epoch program legitimately owns other
    collectives — the metric ``psum`` (once per epoch, after the scan) and
    the shuffle permutation's sort machinery (epoch setup) — but those run
    per EPOCH; only while-body ops pay per step. Shared with telemetry/bench
    so "collectives per step" means the same thing everywhere.
    """
    n = 0
    for line in compiled_hlo.splitlines():
        if _ALL_REDUCE_RE.search(line) is None:
            continue
        op_name = _OP_NAME_RE.search(line)
        if op_name is not None and _STEP_BODY_RE.search(op_name.group(1)):
            n += 1
    return n


_ALL_REDUCE_RE = re.compile(r"= \S+ all-reduce(?:-start)?\(")
_OP_NAME_RE = re.compile(r'op_name="([^"]*)"')
_STEP_BODY_RE = re.compile(r"(?:vmap\()?while\)?/body")


class PreflightError(RuntimeError):
    """Raised by ``assert_trace_clean`` when the audit reports findings."""

    def __init__(self, findings: list[Finding]):
        self.findings = findings
        super().__init__(
            "trace audit failed:\n" + "\n".join(f.format() for f in findings)
        )


def _synthetic_split(
    n_windows: int,
    rng: np.random.Generator,
    n_factors: int = 1,
    n_stocks: int = AUDIT_STOCKS,
):
    """A Batch-shaped train split with the pipeline's window schema:
    x (N,K,T,2F+1), y (N,K,T,2F+2), factor (N,F+F²), inv_psi (N,K).
    At ``n_factors=1`` this is the original scalar schema (features=3,
    y channels=4, factor=(mean, var))."""
    from masters_thesis_tpu.data.pipeline import Batch

    k, t, nf = n_stocks, AUDIT_LOOKBACK, n_factors
    if nf == 1:
        factor = (
            np.abs(rng.standard_normal((n_windows, 2))).astype(np.float32)
            + 0.1
        )
    else:
        # [f_mean | f_cov.ravel()] with an SPD covariance per window, so the
        # K-factor NLL's slogdet/solve path stays finite under the audit.
        f_mean = rng.standard_normal((n_windows, nf)).astype(np.float32)
        a = rng.standard_normal((n_windows, nf, nf)).astype(np.float32)
        f_cov = np.einsum("wij,wkj->wik", a, a) / nf
        f_cov += 0.1 * np.eye(nf, dtype=np.float32)
        factor = np.concatenate(
            [f_mean, f_cov.reshape(n_windows, -1)], axis=-1
        ).astype(np.float32)
    return Batch(
        rng.standard_normal((n_windows, k, t, 2 * nf + 1)).astype(np.float32),
        rng.standard_normal((n_windows, k, t, 2 * nf + 2)).astype(np.float32),
        factor,
        np.ones((n_windows, k), np.float32),
    )


def _leaf_shardings(sharding_tree):
    return [
        s
        for s in jax.tree_util.tree_leaves(
            sharding_tree,
            is_leaf=lambda x: hasattr(x, "is_fully_replicated"),
        )
        if hasattr(s, "is_fully_replicated")
    ]


def run_trace_audit(
    spec=None,
    mesh=None,
    steps: int = AUDIT_STEPS,
    check_collectives: bool = True,
    stacked_replicas: int | None = None,
    shard_axis: str = "window",
) -> list[Finding]:
    """Build + run the real epoch program on synthetic data; return findings.

    ``spec`` (ModelSpec) and ``mesh`` default to a tiny MSE model over all
    visible devices; the audit geometry follows ``spec.n_factors`` (K-factor
    window schema). With ``stacked_replicas`` set, the stacked epoch
    program is audited too (TA207). ``shard_axis='asset'`` audits the
    universe-scale program: the split shards on the asset axis, the factor
    leaf stays replicated by design, and TA203's data check adapts
    accordingly. Returns an empty list when every invariant holds.
    """
    try:
        findings = _run_trace_audit(
            spec, mesh, steps, check_collectives, shard_axis
        )
    except Exception as exc:  # noqa: BLE001 — TA205 carries the cause
        return [
            Finding(
                rule="TA205",
                message=f"audit could not run: {type(exc).__name__}: {exc}",
            )
        ]
    if stacked_replicas is not None:
        findings.extend(
            run_stacked_trace_audit(
                spec=spec, mesh=mesh, replicas=stacked_replicas, steps=steps
            )
        )
    return findings


def run_stacked_trace_audit(
    spec=None,
    mesh=None,
    replicas: int = 3,
    steps: int = AUDIT_STEPS,
) -> list[Finding]:
    """TA207: audit the stacked (vmapped-replica) epoch program.

    Builds the real ``make_stacked_train_epoch`` program with ``replicas``
    heterogeneous (lr, seed) replicas and asserts the two invariants the
    stacked throughput win rests on: the program compiles exactly once
    across varied-input epochs, and its scan body carries exactly one
    all-reduce per dtype buffer — the batched ``[R, n]`` gradient pmean —
    independent of R.
    """
    try:
        return _run_stacked_trace_audit(spec, mesh, replicas, steps)
    except Exception as exc:  # noqa: BLE001 — TA205 carries the cause
        return [
            Finding(
                rule="TA205",
                message=f"stacked audit could not run: "
                f"{type(exc).__name__}: {exc}",
            )
        ]


def _run_stacked_trace_audit(spec, mesh, replicas, steps) -> list[Finding]:
    from masters_thesis_tpu.models.objectives import ModelSpec
    from masters_thesis_tpu.parallel import (
        batch_sharding,
        global_put,
        make_data_mesh,
        replicated_sharding,
    )
    from masters_thesis_tpu.train.flatparams import (
        FlatAdam,
        flatten,
        flatten_spec,
        num_buffers,
        stack_flat,
        stack_opt_states,
    )
    from masters_thesis_tpu.train.steps import (
        jit_cache_size,
        make_stacked_train_epoch,
    )

    findings: list[Finding] = []
    if spec is None:
        spec = ModelSpec(
            objective="mse", hidden_size=8, num_layers=1, dropout=0.0,
            kernel_impl="xla",
        )
    if mesh is None:
        mesh = make_data_mesh(None)

    module = spec.build_module()
    tx = FlatAdam(None, spec.weight_decay)
    n_factors = getattr(spec, "n_factors", 1)
    split = _synthetic_split(
        mesh.size * AUDIT_BATCH * 2, np.random.default_rng(0),
        n_factors=n_factors,
    )

    dummy = jnp.zeros(
        (1, AUDIT_LOOKBACK, 2 * n_factors + 1), jnp.float32
    )

    def init(seed):
        return module.init(jax.random.key(seed), dummy)["params"]

    params0 = init(0)
    fspec = flatten_spec(params0)
    repl = replicated_sharding(mesh)
    pstack = global_put(
        stack_flat([flatten(init(s), fspec) for s in range(replicas)]), repl
    )
    ostack = global_put(
        stack_opt_states([tx.init(params0) for _ in range(replicas)]), repl
    )
    # Heterogeneous per-replica lrs: the point of the stack is differing
    # hyperparameters riding one program.
    lrs = global_put(
        jnp.asarray([1e-3 * (2.0**r) for r in range(replicas)], jnp.float32),
        repl,
    )
    data = global_put(split, batch_sharding(mesh))
    epoch_rngs = [
        global_put(
            jnp.stack(
                [
                    jax.random.fold_in(jax.random.key(10 + r), e)
                    for r in range(replicas)
                ]
            ),
            repl,
        )
        for e in range(steps)
    ]

    epoch_fn = make_stacked_train_epoch(
        module, spec.window_objective(), spec.metric_keys, tx, mesh, fspec,
        batch_size=AUDIT_BATCH,
    )

    # ------------------------------------------------ TA207 (collectives)
    lowered = epoch_fn.lower(pstack, ostack, lrs, epoch_rngs[0], data)
    n_reduce = count_step_collectives(lowered.compile().as_text())
    expected = num_buffers(fspec)
    if n_reduce != expected:
        findings.append(
            Finding(
                rule="TA207",
                message=f"stacked epoch program (R={replicas}) contains "
                f"{n_reduce} cross-replica reductions in the scan body "
                f"(expected exactly {expected}: one batched [R, n] pmean "
                "per dtype buffer) — the replica vmap is splitting or "
                "duplicating the gradient collective",
            )
        )

    # --------------------------------------------------- TA207 (compiles)
    out = None
    for e in range(steps):
        out = epoch_fn(pstack, ostack, lrs, epoch_rngs[e], data)
        pstack, ostack, _ = out
    jax.block_until_ready(out)
    cache_size = jit_cache_size(epoch_fn)
    if cache_size is not None and cache_size != 1:
        findings.append(
            Finding(
                rule="TA207",
                message=f"stacked epoch program (R={replicas}) compiled "
                f"{cache_size} times across {steps} varied-input epochs "
                "(expected exactly 1) — the stacked jit signature is not "
                "stable",
            )
        )
    return findings


def _run_trace_audit(
    spec, mesh, steps, check_collectives, shard_axis="window"
) -> list[Finding]:
    from masters_thesis_tpu.models.objectives import ModelSpec
    from masters_thesis_tpu.parallel import (
        batch_sharding,
        global_put,
        make_data_mesh,
        replicated_sharding,
    )
    from masters_thesis_tpu.train.flatparams import FlatAdam
    from masters_thesis_tpu.train.steps import make_train_epoch

    findings: list[Finding] = []
    if spec is None:
        spec = ModelSpec(
            objective="mse", hidden_size=8, num_layers=1, dropout=0.0,
            kernel_impl="xla",
        )
    if mesh is None:
        mesh = make_data_mesh(None)

    module = spec.build_module()
    objective = spec.window_objective()
    n_factors = getattr(spec, "n_factors", 1)
    # The audit runs the flat update path — the one the Trainer runs — so
    # TA206's "one collective per step" is checked on the real program.
    tx = FlatAdam(None, spec.weight_decay)

    rng = np.random.default_rng(0)
    if shard_axis == "asset":
        # Asset mode: every device sees all windows; the cross-section is
        # what shards, so it must cover the mesh.
        n_windows = AUDIT_BATCH * 2
        n_stocks = mesh.size * AUDIT_STOCKS
    else:
        n_windows = mesh.size * AUDIT_BATCH * 2
        n_stocks = AUDIT_STOCKS
    split = _synthetic_split(
        n_windows, rng, n_factors=n_factors, n_stocks=n_stocks
    )

    init_key = jax.random.key(0)
    dummy = jnp.zeros(
        (1, AUDIT_LOOKBACK, 2 * n_factors + 1), jnp.float32
    )
    params = module.init(init_key, dummy)["params"]
    opt_state = tx.init(params)
    in_dtypes = [p.dtype for p in jax.tree_util.tree_leaves(params)]

    repl = replicated_sharding(mesh)
    params = global_put(params, repl)
    opt_state = global_put(opt_state, repl)
    if shard_axis == "asset":
        asset_sh = batch_sharding(mesh, batch_dim=1)
        from masters_thesis_tpu.data.pipeline import Batch

        data = Batch(
            global_put(split.x, asset_sh),
            global_put(split.y, asset_sh),
            global_put(split.factor, repl),
            global_put(split.inv_psi, asset_sh),
        )
    else:
        data = global_put(split, batch_sharding(mesh))

    epoch_fn = make_train_epoch(
        module, objective, spec.metric_keys, tx, mesh,
        batch_size=AUDIT_BATCH, shard_axis=shard_axis,
    )

    # Every input the measured loop will touch is created and materialized
    # BEFORE the transfer guard goes up — the guard must see the step's own
    # behavior, not the audit harness's argument construction.
    lr = global_put(jnp.float32(1e-3), repl)
    epoch_rngs = [
        global_put(jax.random.fold_in(jax.random.key(7), e), repl)
        for e in range(steps)
    ]
    jax.block_until_ready((lr, epoch_rngs, data, params, opt_state))

    # ------------------------------------------------- TA203 (AOT program)
    # Lower/compile ahead-of-time FIRST: it shares no cache with the jitted
    # call below, so doing it before the warmup keeps the TA201 accounting
    # (cache size of the jitted function) independent of it.
    if check_collectives:
        lowered = epoch_fn.lower(params, opt_state, lr, epoch_rngs[0], data)
        hlo = lowered.as_text()
        if "all-gather" in hlo or "all_gather" in hlo:
            findings.append(
                Finding(
                    rule="TA203",
                    message="compiled epoch program contains an all-gather "
                    "(params or data are being gathered instead of psum'd)",
                )
            )
        compiled = lowered.compile()
        # --------------------------------------------------------- TA206
        n_reduce = count_step_collectives(compiled.as_text())
        if n_reduce != 1:
            findings.append(
                Finding(
                    rule="TA206",
                    message=f"compiled train step contains {n_reduce} "
                    "cross-replica reductions in the scan body (expected "
                    "exactly 1: the flat-buffer gradient pmean) — the "
                    "update path is reducing per leaf again",
                )
            )
        arg_shardings = compiled.input_shardings[0]
        param_sh = _leaf_shardings(arg_shardings[0])
        if not all(s.is_fully_replicated for s in param_sh):
            findings.append(
                Finding(
                    rule="TA203",
                    message="params are not replicated across the mesh in "
                    "the compiled epoch program",
                )
            )
        data_sh = _leaf_shardings(arg_shardings[4])
        if shard_axis == "asset":
            # The factor leaf (index 2: per-window factor stats, no asset
            # axis) is replicated BY DESIGN; the per-asset leaves must shard.
            sharded_leaves = [
                s for i, s in enumerate(data_sh) if i != 2
            ]
        else:
            sharded_leaves = data_sh
        if mesh.size > 1 and any(
            s.is_fully_replicated for s in sharded_leaves
        ):
            findings.append(
                Finding(
                    rule="TA203",
                    message="train split is not sharded over the data axis "
                    "(every device holds the full split)",
                )
            )

    # --------------------------------------------- warmup (the one compile)
    params, opt_state, sums = epoch_fn(
        params, opt_state, lr, epoch_rngs[0], data
    )
    jax.block_until_ready((params, opt_state, sums))

    # ------------------------------------------- TA202 + TA201 (hot loop)
    try:
        with jax.transfer_guard("disallow"):
            for e in range(1, steps):
                params, opt_state, sums = epoch_fn(
                    params, opt_state, lr, epoch_rngs[e], data
                )
        jax.block_until_ready((params, opt_state, sums))
    except Exception as exc:  # noqa: BLE001 — the guard raises plain errors
        findings.append(
            Finding(
                rule="TA202",
                message=f"host transfer inside the hot loop: {exc}",
            )
        )

    from masters_thesis_tpu.train.steps import jit_cache_size

    cache_size = jit_cache_size(epoch_fn)
    if cache_size is not None and cache_size != 1:
        findings.append(
            Finding(
                rule="TA201",
                message=f"epoch program compiled {cache_size} times across "
                f"{steps} varied-input epochs (expected exactly 1) — the "
                "jit signature is not stable",
            )
        )

    # --------------------------------------------------------------- TA204
    out_dtypes = [p.dtype for p in jax.tree_util.tree_leaves(params)]
    if out_dtypes != in_dtypes:
        findings.append(
            Finding(
                rule="TA204",
                message=f"parameter dtypes changed through the epoch: "
                f"{sorted(set(map(str, in_dtypes)))} -> "
                f"{sorted(set(map(str, out_dtypes)))}",
            )
        )
    bad_sums = {
        k: (str(v.dtype), str(w.dtype))
        for k, (v, w) in sums.items()
        if v.dtype != jnp.float32 or w.dtype != jnp.float32
    }
    if bad_sums:
        findings.append(
            Finding(
                rule="TA204",
                message=f"metric sums not accumulated in float32: {bad_sums}",
            )
        )
    return findings


def assert_trace_clean(**kwargs) -> None:
    """Run the audit; raise :class:`PreflightError` on any finding."""
    findings = run_trace_audit(**kwargs)
    if findings:
        raise PreflightError(findings)
