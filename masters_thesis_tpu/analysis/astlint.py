"""Pass 1 — AST lint with repo-specific TPU hot-path rules.

Rules (ids registered in :mod:`findings`):

- **TL101** tracer leaked to a host cast: ``float()``/``int()``/``bool()``/
  ``.item()``/``.tolist()`` applied to a traced value inside jit-reachable
  code. Forces a device sync at trace time (or a ConcretizationTypeError).
- **TL102** Python control flow on a traced value: ``if``/``while`` whose
  condition computes a jnp/jax expression, or ``for`` iterating a jnp/jax
  call, inside jit-reachable code. Either crashes at trace time or unrolls/
  retraces per value.
- **TL103** PRNG key reuse: the same key consumed by two sampling calls
  (or by a sampler inside a loop the key doesn't vary over) without an
  intervening ``split``/``fold_in``. Correlated randomness, silently.
- **TL104** f64 literal / x64 enablement: ``float64`` dtypes and
  ``jax_enable_x64`` promote the whole graph off the MXU fast path.
- **TL105** host transfer in jit-reachable code: ``jax.device_get``/
  ``jax.device_put``, ``np.*`` on traced values, ``block_until_ready``.

"Jit-reachable" comes from :mod:`callgraph`: functions passed to / decorated
with JIT wrappers, plus everything they transitively call within the linted
sources. Host-side code (the trainer loop, checkpointing, benchmarking) is
deliberately exempt from TL101/TL102/TL105 — host casts and transfers are
its job there.

Value tracking is a per-function taint pass: parameters and results of
jnp/jax calls are "traced"; attribute reads that are static under trace
(``.shape``, ``.dtype``, ...) break the taint. High precision is the
contract; a construct the analysis can't prove traced is not flagged, and
``# tracelint: disable=TLxxx`` suppresses deliberate exceptions per line.
"""

from __future__ import annotations

import ast
from pathlib import Path

from masters_thesis_tpu.analysis.callgraph import CallGraph, dotted_name
from masters_thesis_tpu.analysis.findings import (
    Finding,
    is_suppressed,
    suppressed_rules_by_line,
)

# Attribute reads that are static (host) values even on a tracer.
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "itemsize", "sharding"}

HOST_CASTS = {"float", "int", "bool", "complex"}
HOST_METHODS = {"item", "tolist"}

# jax.random functions that PRODUCE keys (their use is key hygiene, not
# consumption); everything else under jax.random consumes its key argument.
KEY_PRODUCERS = {
    "key", "PRNGKey", "split", "fold_in", "clone", "wrap_key_data",
    "key_data", "key_impl",
}

# Builtins whose result is always a host value (len of a tracer is a static
# int; range over a tracer cannot execute). They break the taint chain.
HOST_BUILTINS = {
    "range", "len", "enumerate", "reversed", "zip", "sorted", "isinstance",
    "hasattr", "getattr", "type", "id", "repr", "str", "format",
}

# Parameter annotations that mark a host scalar (not a tracer).
HOST_ANNOTATIONS = {"int", "float", "bool", "str", "bytes", "Path"}


def _host_params(fn_node: ast.FunctionDef) -> set[str]:
    """Parameters provably host-side: annotated as a Python scalar, or
    bound through a default (the ``def _run(layer=layer)`` closure idiom
    captures host loop variables; traced positional args don't default)."""
    host: set[str] = set()
    args = fn_node.args
    for a in args.args + args.posonlyargs + args.kwonlyargs:
        ann = dotted_name(a.annotation) if a.annotation is not None else None
        if ann is not None and ann.split(".")[-1] in HOST_ANNOTATIONS:
            host.add(a.arg)
    positional = args.posonlyargs + args.args
    for a, default in zip(positional[len(positional) - len(args.defaults):],
                          args.defaults):
        del default
        host.add(a.arg)
    for a, default in zip(args.kwonlyargs, args.kw_defaults):
        if default is not None:
            host.add(a.arg)
    return host


def _module_aliases(imports: dict[str, str]) -> tuple[set[str], set[str]]:
    """(jax-like local names, numpy local names) for one module."""
    jax_like = {"jax", "jnp", "lax"}
    numpy_like = set()
    for local, target in imports.items():
        root = target.split(".")[0]
        if root == "jax":
            jax_like.add(local)
        elif root == "numpy":
            numpy_like.add(local)
    return jax_like, numpy_like


def _target_names(target: ast.AST) -> list[str]:
    """Names actually (re)bound by an assignment target. For subscript /
    attribute targets only the base is bound — index expressions
    (``h_out[layer][t] = ...``) are reads, not writes."""
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        return [n for elt in target.elts for n in _target_names(elt)]
    if isinstance(target, ast.Starred):
        return _target_names(target.value)
    if isinstance(target, (ast.Subscript, ast.Attribute)):
        return _target_names(target.value)
    return []


def _walk_expr(expr: ast.AST):
    """ast.walk over an expression, pruning lambda bodies (their params
    shadow the enclosing taint environment)."""
    stack = [expr]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if not isinstance(child, ast.Lambda):
                stack.append(child)


class _FunctionLinter:
    """Taint + rule pass over ONE function body (nested defs excluded —
    they are linted as their own scope with their own trace context)."""

    def __init__(
        self,
        fn_node: ast.FunctionDef,
        params: list[str],
        traced_context: bool,
        jax_aliases: set[str],
        numpy_aliases: set[str],
        path: str,
    ):
        self.fn = fn_node
        self.traced_context = traced_context
        self.jax = jax_aliases
        self.np = numpy_aliases
        self.path = path
        self.tainted: set[str] = set(params) - _host_params(fn_node)
        self.findings: list[Finding] = []
        # TL103 state, in source order: key name -> production loop stack /
        # consumption count / first-use line. Parameters count as keys
        # produced at function entry (loop depth 0), so a key argument
        # consumed inside a Python loop is caught too.
        self.key_prod: dict[str, tuple[int, ...]] = {p: () for p in params}
        self.key_uses: dict[str, tuple[int, int]] = {}
        self.key_flagged: set[str] = set()
        self.loop_stack: tuple[int, ...] = ()

    # ------------------------------------------------------------- helpers

    def _is_jax_call(self, call: ast.Call) -> bool:
        name = dotted_name(call.func)
        return name is not None and name.split(".")[0] in self.jax

    def _is_numpy_call(self, call: ast.Call) -> bool:
        name = dotted_name(call.func)
        return name is not None and name.split(".")[0] in self.np

    def _traced(self, node: ast.AST) -> bool:
        """Whether an expression may hold a traced value."""
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                return False
            return self._traced(node.value)
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Call):
            if (
                isinstance(node.func, ast.Name)
                and node.func.id in HOST_BUILTINS
            ):
                return False
            if self._is_jax_call(node):
                return True
            return any(self._traced(a) for a in node.args) or any(
                self._traced(k.value) for k in node.keywords
            )
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return False
        return any(self._traced(c) for c in ast.iter_child_nodes(node))

    def _test_traced(self, test: ast.AST) -> bool:
        """Stricter traced-ness for branch conditions.

        A bare name is NOT enough (it may be a container or host bool, e.g.
        ``x if sums else y`` over a metric dict); require an actual
        computation: a jnp/jax call, or a comparison/boolean/arithmetic
        expression with a traced operand. ``is``/``is not`` compare
        identity, which is host-safe.
        """
        if isinstance(test, ast.Call):
            return self._is_jax_call(test) or any(
                self._test_traced(a) for a in test.args
            )
        if isinstance(test, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
                return False
            return self._traced(test)
        if isinstance(test, (ast.BoolOp, ast.BinOp)):
            return self._traced(test)
        if isinstance(test, ast.UnaryOp):
            return self._test_traced(test.operand)
        return False

    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        self.findings.append(
            Finding(rule=rule, message=message, path=self.path,
                    line=getattr(node, "lineno", 0))
        )

    # ---------------------------------------------------------- taint pass

    def _taint_statements(self, stmts: list[ast.stmt]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                value = stmt.value
                if value is not None and self._traced(value):
                    targets = (
                        stmt.targets
                        if isinstance(stmt, ast.Assign)
                        else [stmt.target]
                    )
                    for target in targets:
                        self.tainted.update(_target_names(target))
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                if self._traced(stmt.iter):
                    self.tainted.update(_target_names(stmt.target))
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    if item.optional_vars is not None and self._traced(
                        item.context_expr
                    ):
                        self.tainted.update(
                            _target_names(item.optional_vars)
                        )
            for attr in ("body", "orelse", "finalbody"):
                inner = getattr(stmt, attr, None)
                if inner:
                    self._taint_statements(inner)
            for handler in getattr(stmt, "handlers", []) or []:
                self._taint_statements(handler.body)

    # ----------------------------------------------------------- rule pass

    def run(self) -> list[Finding]:
        # Two taint sweeps: the second catches names tainted by statements
        # later in source order than their first read (loop-carried values).
        self._taint_statements(self.fn.body)
        self._taint_statements(self.fn.body)
        self._visit_block(self.fn.body)
        return self.findings

    def _visit_block(self, stmts: list[ast.stmt]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            self._visit_stmt(stmt)

    def _visit_stmt(self, stmt: ast.stmt) -> None:
        self._reset_keys_on_assign(stmt)
        if self.traced_context:
            if isinstance(stmt, (ast.If, ast.While)) and self._test_traced(
                stmt.test
            ):
                self._emit(
                    "TL102", stmt,
                    "Python branch on a traced expression inside jitted "
                    "code (use jnp.where / lax.cond)",
                )
            if isinstance(stmt, (ast.For, ast.AsyncFor)) and isinstance(
                stmt.iter, ast.Call
            ) and self._is_jax_call(stmt.iter):
                self._emit(
                    "TL102", stmt,
                    "Python loop over a traced array inside jitted code "
                    "(unrolls at trace time; use lax.scan)",
                )
        # Expression-level rules on this statement's own expressions
        # (headers + simple statements); bodies recurse as statements.
        for child in ast.iter_child_nodes(stmt):
            if not isinstance(child, ast.expr):
                continue
            for node in _walk_expr(child):
                if (
                    self.traced_context
                    and isinstance(node, ast.IfExp)
                    and self._test_traced(node.test)
                ):
                    self._emit(
                        "TL102", node,
                        "conditional expression on a traced value inside "
                        "jitted code (use jnp.where)",
                    )
                if isinstance(node, ast.Call):
                    self._check_call(node)
        in_loop = isinstance(stmt, (ast.For, ast.AsyncFor, ast.While))
        prev = self.loop_stack
        if in_loop:
            self.loop_stack = prev + (id(stmt),)
        for attr in ("body", "orelse", "finalbody"):
            inner = getattr(stmt, attr, None)
            if inner:
                self._visit_block(inner)
        for handler in getattr(stmt, "handlers", []) or []:
            self._visit_block(handler.body)
        self.loop_stack = prev

    # ------------------------------------------------------------- calls

    def _check_call(self, call: ast.Call) -> None:
        callee = dotted_name(call.func)
        # TL104 applies host-side too: an f64 literal anywhere poisons
        # whatever jitted code consumes the produced array.
        if (
            callee is not None
            and callee.endswith("config.update")
            and call.args
            and isinstance(call.args[0], ast.Constant)
            and call.args[0].value == "jax_enable_x64"
        ):
            self._emit("TL104", call, "jax_enable_x64 enabled in library code")
        self._check_key_call(call)
        if not self.traced_context:
            return
        # TL101 — host casts on traced values.
        if (
            isinstance(call.func, ast.Name)
            and call.func.id in HOST_CASTS
            and len(call.args) == 1
            and self._traced(call.args[0])
        ):
            self._emit(
                "TL101", call,
                f"{call.func.id}() on a traced value inside jitted code "
                "(forces device sync / ConcretizationTypeError)",
            )
        if (
            isinstance(call.func, ast.Attribute)
            and call.func.attr in HOST_METHODS
            and self._traced(call.func.value)
        ):
            self._emit(
                "TL101", call,
                f".{call.func.attr}() on a traced value inside jitted code",
            )
        # TL105 — host transfers.
        if callee in ("jax.device_get", "jax.device_put"):
            self._emit(
                "TL105", call,
                f"{callee} inside jit-reachable code (host<->device "
                "round-trip in the hot path)",
            )
        if (
            isinstance(call.func, ast.Attribute)
            and call.func.attr == "block_until_ready"
        ):
            self._emit(
                "TL105", call, "block_until_ready inside jit-reachable code"
            )
        if self._is_numpy_call(call) and (
            any(self._traced(a) for a in call.args)
            or any(self._traced(k.value) for k in call.keywords)
        ):
            self._emit(
                "TL105", call,
                f"{callee} on a traced value inside jitted code (silent "
                "host transfer; use jnp)",
            )

    # ------------------------------------------------------- TL103 (keys)

    def _reset_keys_on_assign(self, stmt: ast.stmt) -> None:
        """Any rebinding of a name resets its key-consumption count; a
        producer call additionally records WHERE the fresh key was made
        (loop depth), for the reuse-across-iterations check."""
        if not isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            return
        targets = (
            stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
        )
        names = [n for target in targets for n in _target_names(target)]
        for name in names:
            self.key_uses.pop(name, None)
            self.key_prod.pop(name, None)
            self.key_flagged.discard(name)
        value = getattr(stmt, "value", None)
        if isinstance(value, ast.Call):
            callee = dotted_name(value.func) or ""
            parts = callee.split(".")
            if len(parts) >= 2 and parts[-2] == "random" and (
                parts[-1] in KEY_PRODUCERS
            ):
                for name in names:
                    self.key_prod[name] = self.loop_stack

    def _check_key_call(self, call: ast.Call) -> None:
        callee = dotted_name(call.func) or ""
        parts = callee.split(".")
        if len(parts) < 2 or parts[-2] != "random":
            return
        if parts[-1] in KEY_PRODUCERS:
            return
        if not call.args or not isinstance(call.args[0], ast.Name):
            return
        name = call.args[0].id
        if name in self.key_flagged:
            return
        prod_stack = self.key_prod.get(name)
        if prod_stack is not None and (
            len(self.loop_stack) > len(prod_stack)
            and self.loop_stack[: len(prod_stack)] == prod_stack
        ):
            self.key_flagged.add(name)
            self._emit(
                "TL103", call,
                f"PRNG key '{name}' produced outside this loop but "
                "consumed every iteration (fold_in the loop index)",
            )
            return
        count, first_line = self.key_uses.get(name, (0, call.lineno))
        count += 1
        self.key_uses[name] = (count, first_line)
        if count == 2:
            self.key_flagged.add(name)
            self._emit(
                "TL103", call,
                f"PRNG key '{name}' consumed again without split/fold_in "
                f"(first use line {first_line})",
            )


# --------------------------------------------------------------- driver


def _module_name(path: Path, root: Path | None) -> str:
    if root is not None:
        try:
            rel = path.resolve().relative_to(root.resolve().parent)
            return ".".join(rel.with_suffix("").parts)
        except ValueError:
            pass
    return path.stem


def discover_files(paths: list[Path]) -> list[Path]:
    files: list[Path] = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            files.extend(
                f for f in sorted(p.rglob("*.py"))
                if "__pycache__" not in f.parts
            )
        elif p.suffix == ".py":
            files.append(p)
    return files


def _module_level_findings(
    tree: ast.AST, path: str, linter: _FunctionLinter
) -> list[Finding]:
    """TL104 outside any function: calls at module scope, ``jnp.float64``
    attribute literals, and ``dtype='float64'`` strings anywhere."""
    findings: list[Finding] = []
    # Module-scope statements only (function bodies already ran through
    # their own _FunctionLinter).
    stack = [
        n for n in ast.iter_child_nodes(tree)
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if isinstance(node, ast.Call):
            linter._check_call(node)
        stack.extend(ast.iter_child_nodes(node))
    findings.extend(linter.findings)
    # File-wide f64 dtype literals (functions included; unambiguous).
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) and node.attr == "float64":
            findings.append(
                Finding(
                    rule="TL104",
                    message="float64 dtype in library code",
                    path=path,
                    line=node.lineno,
                )
            )
        if (
            isinstance(node, ast.keyword)
            and node.arg == "dtype"
            and isinstance(node.value, ast.Constant)
            and node.value.value in ("float64", "f8", ">f8", "<f8")
        ):
            findings.append(
                Finding(
                    rule="TL104",
                    message="dtype='float64' literal",
                    path=path,
                    line=node.value.lineno,
                )
            )
    return findings


def lint_paths(
    paths: list[Path | str], package_root: Path | str | None = None
) -> list[Finding]:
    """Run the AST lint over files/directories; returns surviving findings.

    ``package_root`` anchors dotted module names (cross-module jit
    reachability); when omitted, the first directory argument is used.
    """
    paths = [Path(p) for p in paths]
    if package_root is None:
        package_root = next((p for p in paths if p.is_dir()), None)
    files = discover_files(paths)

    sources: dict[str, str] = {}
    trees: dict[str, tuple[Path, ast.AST]] = {}
    findings: list[Finding] = []
    for f in files:
        module = _module_name(f, Path(package_root) if package_root else None)
        try:
            src = f.read_text()
            tree = ast.parse(src, filename=str(f))
        except SyntaxError as exc:
            findings.append(
                Finding(
                    rule="TL100",
                    message=f"syntax error: {exc.msg}",
                    path=str(f),
                    line=exc.lineno or 0,
                )
            )
            continue
        sources[module] = src
        trees[module] = (f, tree)

    graph = CallGraph.build(trees)

    for module, (path, tree) in trees.items():
        jax_aliases, numpy_aliases = _module_aliases(
            graph.imports.get(module, {})
        )
        suppressions = suppressed_rules_by_line(sources[module])
        module_findings: list[Finding] = []
        for info in graph.functions.values():
            if info.module != module:
                continue
            linter = _FunctionLinter(
                info.node, info.params, graph.is_traced(info.key),
                jax_aliases, numpy_aliases, str(path),
            )
            module_findings.extend(linter.run())
        top = _FunctionLinter(
            ast.parse("def _m(): pass").body[0], [], False,
            jax_aliases, numpy_aliases, str(path),
        )
        module_findings.extend(_module_level_findings(tree, str(path), top))
        findings.extend(
            f for f in module_findings if not is_suppressed(f, suppressions)
        )

    seen: set[tuple[str, str, int, str]] = set()
    unique: list[Finding] = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        key = (f.rule, f.path, f.line, f.message)
        if key not in seen:
            seen.add(key)
            unique.append(f)
    return unique
