"""Shared host-side utilities."""

from masters_thesis_tpu.utils.backend_probe import (
    BackendHealth,
    CircuitBreaker,
    HealthDecision,
    ProbeResult,
    distributed_client_initialized,
    multihost_rank,
    probe_tpu_backend,
)
from masters_thesis_tpu.utils.compilation_cache import (
    enable_persistent_compilation_cache,
)
from masters_thesis_tpu.utils.io import (
    atomic_publish,
    atomic_write_text,
    fsync_path,
    wait_until,
)

__all__ = [
    "BackendHealth",
    "CircuitBreaker",
    "HealthDecision",
    "ProbeResult",
    "atomic_publish",
    "atomic_write_text",
    "distributed_client_initialized",
    "enable_persistent_compilation_cache",
    "fsync_path",
    "multihost_rank",
    "probe_tpu_backend",
    "wait_until",
]
