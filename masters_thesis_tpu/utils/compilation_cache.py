"""Persistent XLA compilation cache for the CLI drivers.

First compilation of the train/eval programs costs tens of seconds on TPU;
a multirun sweep pays it once per process. Pointing JAX's persistent cache
at a stable on-disk location makes every job after the first start hot
(same-shape programs are fetched instead of recompiled). Off by default in
library code — the CLI drivers opt in (set ``MT_NO_COMPILE_CACHE=1`` to
disable, e.g. when benchmarking compile time itself).

The reference's analog is ``model.compile()`` — torch.compile graph
capture redone from scratch every process (reference: train.py:137); the
persistent cache is what makes whole-program jit compilation cheaper than
that across sweep jobs, not just within one.
"""

from __future__ import annotations

import os
from pathlib import Path

DEFAULT_CACHE_DIR = Path.home() / ".cache" / "masters_thesis_tpu" / "xla"


def enable_persistent_compilation_cache(cache_dir: Path | None = None) -> bool:
    """Enable JAX's persistent compilation cache; returns False if disabled."""
    if os.environ.get("MT_NO_COMPILE_CACHE"):
        return False
    if "--xla_force_host_platform_device_count" in os.environ.get(
        "XLA_FLAGS", ""
    ):
        # Executables deserialized from the persistent cache on the forced
        # multi-device host platform diverge numerically from fresh
        # compiles (observed: the 8-device shard_map train step computes a
        # 0.7%-different epoch loss on reload than the executable that was
        # serialized, jaxlib 0.4.x). The env check is deliberate — probing
        # jax.devices() here would initialize the backend (and can wedge on
        # a held TPU relay lease).
        return False
    import jax

    cache_dir = Path(cache_dir or DEFAULT_CACHE_DIR)
    cache_dir.mkdir(parents=True, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", str(cache_dir))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    return True
