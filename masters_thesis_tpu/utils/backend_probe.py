"""Subprocess probe for TPU backend liveness.

The axon relay lease can wedge so that ``jax.devices()`` blocks forever
with no client-side timeout (observed multi-hour outages; RESULTS.md).
Every script that intends to touch the TPU must therefore probe backend
init in a SHORT-LIVED subprocess first — this module is the one shared
implementation of that pattern (bench.py, sweeps/profile_breakdown.py;
the shell-side grid runner re-implements the same probe in bash).

Policy knobs:

- ``timeout_s``: per-attempt subprocess timeout. A wedged lease hangs the
  child; the timeout converts that into a retriable failure.
- ``budget_s``: total retry budget. Wedges often clear within minutes, so
  callers that can afford to wait should; one-shot callers pass
  ``budget_s=0``.
- A CalledProcessError (instant non-zero exit) is a deterministic init
  crash — broken libtpu, bad platform pin — and is NOT retried: the same
  crash would reproduce for the whole budget. Its stderr tail is returned
  so the failure is diagnosable.
"""

from __future__ import annotations

import subprocess
import sys
import time
from dataclasses import dataclass

DEFAULT_TIMEOUT_S = 120.0
DEFAULT_BACKOFF_S = 15.0


@dataclass
class ProbeResult:
    ok: bool
    attempts: int
    detail: str  # "" when ok; reason + child stderr tail otherwise


def distributed_client_initialized() -> bool:
    """Whether ``jax.distributed.initialize`` has run, across JAX versions.

    ``jax.distributed.is_initialized`` only exists in newer JAX releases;
    older ones (e.g. 0.4.37, the pinned toolchain) expose the same fact via
    the private distributed client state. Neither path initializes the XLA
    backend.
    """
    import jax

    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None:
        return bool(is_init())
    try:
        from jax._src import distributed
    except ImportError:  # pragma: no cover - future JAX without _src layout
        return False
    return getattr(distributed.global_state, "client", None) is not None


def _xla_backend_initialized() -> bool:
    """Whether any XLA backend is already live (so querying it is free)."""
    try:
        from jax._src import xla_bridge
    except ImportError:  # pragma: no cover - future JAX without _src layout
        return False
    probe = getattr(xla_bridge, "backends_are_initialized", None)
    return bool(probe()) if probe is not None else False


def multihost_rank() -> tuple[int, int]:
    """(process_index, process_count) WITHOUT initializing the XLA backend.

    ``jax.process_count()`` forces device-backend init; on the relay-attached
    TPU that makes the calling process take the single relay lease as a side
    effect of a host-side bookkeeping question, after which any measurement
    subprocess it spawns contends with it (documented UNAVAILABLE crash +
    wedge risk, docs/OPERATIONS.md). Multi-process runs in this framework
    always go through ``parallel.mesh.distributed_initialize`` (which calls
    ``jax.distributed.initialize``), so an uninitialized distributed client
    proves the run is single-process — answerable with no backend touch.
    When a backend is ALREADY live the query costs nothing, so ask it
    directly (this is also what lets tests monkeypatch process_count).
    """
    import jax

    if distributed_client_initialized() or _xla_backend_initialized():
        return jax.process_index(), jax.process_count()
    return 0, 1


def probe_tpu_backend(
    timeout_s: float = DEFAULT_TIMEOUT_S,
    budget_s: float = 0.0,
    backoff_s: float = DEFAULT_BACKOFF_S,
) -> ProbeResult:
    """Probe ``jax.devices()`` in a subprocess; retry timeouts for budget_s."""
    deadline = time.monotonic() + budget_s
    attempts = 0
    detail = ""
    while True:
        attempts += 1
        remaining = deadline - time.monotonic()
        try:
            subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=max(10.0, min(timeout_s, remaining))
                if budget_s else timeout_s,
                check=True,
                capture_output=True,
            )
            return ProbeResult(True, attempts, "")
        except subprocess.CalledProcessError as exc:
            stderr = (exc.stderr or b"").decode(errors="replace")
            detail = f"init crashed (rc={exc.returncode}): {stderr[-500:]}"
            break  # deterministic crash: retrying reproduces it
        except subprocess.TimeoutExpired:
            detail = f"probe timed out after attempt {attempts} (wedged lease)"
            # Per-attempt progress to stderr: an operator tailing the log
            # must be able to tell "probe retrying through a wedge" from
            # "caller hung" (the no-kill rule makes that distinction
            # consequential).
            print(
                f"device probe attempt {attempts} timed out; "
                f"{max(0.0, remaining):.0f}s budget left",
                file=sys.stderr,
                flush=True,
            )
            if time.monotonic() + backoff_s >= deadline:
                break
            time.sleep(backoff_s)
    return ProbeResult(False, attempts, detail)
