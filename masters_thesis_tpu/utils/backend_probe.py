"""Subprocess probe for TPU backend liveness.

The axon relay lease can wedge so that ``jax.devices()`` blocks forever
with no client-side timeout (observed multi-hour outages; RESULTS.md).
Every script that intends to touch the TPU must therefore probe backend
init in a SHORT-LIVED subprocess first — this module is the one shared
implementation of that pattern (bench.py, sweeps/profile_breakdown.py;
the shell-side grid runner re-implements the same probe in bash).

Policy knobs:

- ``timeout_s``: per-attempt subprocess timeout. A wedged lease hangs the
  child; the timeout converts that into a retriable failure.
- ``budget_s``: total retry budget. Wedges often clear within minutes, so
  callers that can afford to wait should; one-shot callers pass
  ``budget_s=0``.
- A CalledProcessError (instant non-zero exit) is a deterministic init
  crash — broken libtpu, bad platform pin — and is NOT retried: the same
  crash would reproduce for the whole budget. Its stderr tail is returned
  so the failure is diagnosable.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from dataclasses import dataclass
from pathlib import Path

from masters_thesis_tpu.resilience import faults

DEFAULT_TIMEOUT_S = 120.0
DEFAULT_BACKOFF_S = 15.0
DEFAULT_BUDGET_S = 600.0
DEFAULT_CACHE_TTL_S = 900.0


@dataclass
class ProbeResult:
    ok: bool
    attempts: int
    detail: str  # "" when ok; reason + child stderr tail otherwise


@dataclass
class HealthDecision:
    """Outcome of :meth:`BackendHealth.ensure_responsive`."""

    ok: bool
    degraded: bool  # not ok: caller should fail over to the CPU mesh
    attempts: int
    detail: str
    known_wedged: bool  # cache said wedged within TTL -> single attempt
    cached_age_s: float | None


def pin_cpu(env: dict) -> dict:
    """The one CPU-pinning incantation: ``JAX_PLATFORMS`` alone is NOT
    enough — the relay plugin trigger env must go too or the axon
    sitecustomize re-selects the TPU plugin regardless (ADVICE r4)."""
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    return env


def pin_cpu_in_process() -> None:
    """Force THIS process onto the CPU backend, even after ``import jax``.

    JAX captures ``JAX_PLATFORMS`` at import time, so the env var alone is
    not enough once anything has imported jax (ADVICE r4); the config
    update is what actually pins the platform pre-init.
    """
    pin_cpu(os.environ)
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass


class BackendHealth:
    """Shared probe-cache + wedge-detection policy (lifted from bench.py).

    The last probe outcome is persisted (atomic write, short TTL); within
    the TTL a known-wedged lease gets ONE probe attempt (``budget_s=0``)
    instead of re-burning the full retry budget re-timing-out against a
    lease a previous run already found dead (BENCH_r05 lost all 600s that
    way). Consumers: bench.py (perf evidence capture) and the resilience
    supervisor (pre-attempt health gate / CPU degradation).
    """

    def __init__(
        self,
        cache_path: Path | str,
        ttl_s: float = DEFAULT_CACHE_TTL_S,
        timeout_s: float = DEFAULT_TIMEOUT_S,
        budget_s: float = DEFAULT_BUDGET_S,
        backoff_s: float = DEFAULT_BACKOFF_S,
    ) -> None:
        self.cache_path = Path(cache_path)
        self.ttl_s = ttl_s
        self.timeout_s = timeout_s
        self.budget_s = budget_s
        self.backoff_s = backoff_s

    def read_cache(self) -> dict | None:
        """Last probe outcome, or None when absent/corrupt/expired."""
        try:
            cached = json.loads(self.cache_path.read_text())
        except (OSError, json.JSONDecodeError):
            return None
        if not isinstance(cached, dict):
            return None
        at = cached.get("at")
        if not isinstance(at, (int, float)) or time.time() - at > self.ttl_s:
            return None
        return cached

    def record(self, ok: bool, detail: str = "") -> None:
        """Best-effort persist: the cache must never cost the run."""
        try:
            from masters_thesis_tpu.utils.io import atomic_write_text

            self.cache_path.parent.mkdir(parents=True, exist_ok=True)
            atomic_write_text(
                self.cache_path,
                json.dumps(
                    {"ok": ok, "at": time.time(), "detail": detail[-500:]},
                    indent=2,
                ),
            )
        except OSError:
            pass

    def record_wedge(self, detail: str) -> None:
        """A mid-run watchdog kill established the lease is wedged."""
        self.record(False, detail)

    def ensure_responsive(
        self, single_attempt: bool = False, log=None
    ) -> HealthDecision:
        """Probe backend init under the cache policy.

        ``single_attempt=True`` forces budget 0 regardless of the cache
        (the supervisor's policy: IT owns retries, so the probe gets one
        shot per attempt). Does NOT pin CPU itself — degradation is the
        caller's decision to apply and record.
        """
        log = log or (lambda msg: print(msg, file=sys.stderr, flush=True))
        cached = self.read_cache()
        known_wedged = cached is not None and not cached.get("ok")
        cached_age_s = (
            time.time() - cached["at"] if cached is not None else None
        )
        if known_wedged:
            # ONE attempt (budget_s=0 -> no retries), then fail over on
            # the first timeout instead of re-burning the retry budget.
            log(
                "probe cache says lease was wedged "
                f"{cached_age_s:.0f}s ago; single probe attempt"
            )
        budget_s = 0.0 if (known_wedged or single_attempt) else self.budget_s
        probe = probe_tpu_backend(
            timeout_s=self.timeout_s,
            budget_s=budget_s,
            backoff_s=self.backoff_s,
        )
        self.record(probe.ok, probe.detail or "")
        if not probe.ok:
            log(
                f"device probe failed {probe.attempts}x over "
                f"{budget_s:.0f}s ({probe.detail})"
            )
        return HealthDecision(
            ok=probe.ok,
            degraded=not probe.ok,
            attempts=probe.attempts,
            detail=probe.detail,
            known_wedged=known_wedged,
            cached_age_s=cached_age_s,
        )


def backend_fingerprint(mesh=None) -> dict:
    """Identity of the compiled-program environment, for cache keying.

    The serving program cache (serve/program_cache.py) refuses any entry
    whose fingerprint disagrees with the booting process: a serialized
    executable is only meaningful under the jax/jaxlib pair, backend
    platform, device kind, and device set it was compiled for — and on
    the forced-multi-device host platform reloads have been observed to
    diverge numerically, so that flag is part of the identity too.
    Requires a live backend (callers hold a mesh already); never probes.
    """
    import os as _os

    import jax

    try:
        import jaxlib

        jaxlib_version = getattr(jaxlib, "__version__", None)
    except ImportError:  # pragma: no cover - jaxlib rides with jax
        jaxlib_version = None
    devices = list(mesh.devices.flat) if mesh is not None else jax.devices()
    first = devices[0] if devices else None
    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib_version,
        "platform": (
            first.platform if first is not None else jax.default_backend()
        ),
        "device_kind": getattr(first, "device_kind", None),
        "device_ids": [int(d.id) for d in devices],
        "forced_host_devices": (
            "--xla_force_host_platform_device_count"
            in _os.environ.get("XLA_FLAGS", "")
        ),
    }


class CircuitBreaker:
    """Consecutive-failure breaker in front of :class:`BackendHealth`.

    The serving engine (and any future device-touching loop) feeds it one
    ``record_failure``/``record_success`` per dispatch. ``threshold``
    consecutive failures TRIP the breaker — the caller then runs exactly
    one backend probe (``ensure_responsive(single_attempt=True)``) and
    decides degradation, mirroring the supervisor's policy: isolated
    errors are absorbed, repeated ones cost one probe, never a retry
    storm against a wedged lease.
    """

    def __init__(self, threshold: int = 3) -> None:
        if threshold < 1:
            raise ValueError(f"breaker threshold must be >= 1: {threshold}")
        self.threshold = threshold
        self.consecutive = 0
        self.trips = 0
        # Fleet replica workers each own a breaker, but the monitor
        # thread reads trip counts and the supervisor shares one across
        # attempt boundaries — the counters must be update-atomic.
        self._lock = threading.Lock()

    def record_success(self) -> None:
        with self._lock:
            self.consecutive = 0

    def record_failure(self) -> bool:
        """Count a failure; True when this one trips the breaker (the
        consecutive count resets so the caller probes once per trip, not
        once per failure past the threshold)."""
        with self._lock:
            self.consecutive += 1
            if self.consecutive >= self.threshold:
                self.consecutive = 0
                self.trips += 1
                return True
            return False


def distributed_client_initialized() -> bool:
    """Whether ``jax.distributed.initialize`` has run, across JAX versions.

    ``jax.distributed.is_initialized`` only exists in newer JAX releases;
    older ones (e.g. 0.4.37, the pinned toolchain) expose the same fact via
    the private distributed client state. Neither path initializes the XLA
    backend.
    """
    import jax

    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None:
        return bool(is_init())
    try:
        from jax._src import distributed
    except ImportError:  # pragma: no cover - future JAX without _src layout
        return False
    return getattr(distributed.global_state, "client", None) is not None


def free_coordinator_address(host: str = "127.0.0.1") -> str:
    """A ``host:port`` the OS just confirmed free, for a fresh
    ``jax.distributed`` coordinator.

    The fleet supervisor allocates a NEW address per fleet generation:
    the old coordinator died with the old rank 0, and its port can
    linger in TIME_WAIT — rebinding it from a relaunched rank 0 races
    the kernel. Jax-free (a plain socket bind)."""
    import socket

    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind((host, 0))
        return f"{host}:{s.getsockname()[1]}"


def coordinator_reachable(address: str, timeout_s: float = 1.0) -> bool:
    """TCP-connect probe of a coordinator address — jax-free, so the
    fleet supervisor can tell 'rank 0 never opened the coordinator
    service' (boot failure) from 'ranks are up but wedged' (hang)."""
    import socket

    host, _, port = address.rpartition(":")
    try:
        with socket.create_connection((host or "127.0.0.1", int(port)),
                                      timeout=timeout_s):
            return True
    except (OSError, ValueError):
        return False


def wait_for_coordinator(
    address: str, timeout_s: float, interval_s: float = 0.1
) -> bool:
    """Poll :func:`coordinator_reachable` until it answers or the boot
    budget runs out."""
    deadline = time.monotonic() + timeout_s
    while True:
        if coordinator_reachable(address, timeout_s=min(1.0, interval_s * 5)):
            return True
        if time.monotonic() >= deadline:
            return False
        time.sleep(interval_s)


def _xla_backend_initialized() -> bool:
    """Whether any XLA backend is already live (so querying it is free)."""
    try:
        from jax._src import xla_bridge
    except ImportError:  # pragma: no cover - future JAX without _src layout
        return False
    probe = getattr(xla_bridge, "backends_are_initialized", None)
    return bool(probe()) if probe is not None else False


def multihost_rank() -> tuple[int, int]:
    """(process_index, process_count) WITHOUT initializing the XLA backend.

    ``jax.process_count()`` forces device-backend init; on the relay-attached
    TPU that makes the calling process take the single relay lease as a side
    effect of a host-side bookkeeping question, after which any measurement
    subprocess it spawns contends with it (documented UNAVAILABLE crash +
    wedge risk, docs/OPERATIONS.md). Multi-process runs in this framework
    always go through ``parallel.mesh.distributed_initialize`` (which calls
    ``jax.distributed.initialize``), so an uninitialized distributed client
    proves the run is single-process — answerable with no backend touch.
    When a backend is ALREADY live the query costs nothing, so ask it
    directly (this is also what lets tests monkeypatch process_count).
    """
    import jax

    if distributed_client_initialized() or _xla_backend_initialized():
        return jax.process_index(), jax.process_count()
    return 0, 1


def probe_tpu_backend(
    timeout_s: float = DEFAULT_TIMEOUT_S,
    budget_s: float = 0.0,
    backoff_s: float = DEFAULT_BACKOFF_S,
) -> ProbeResult:
    """Probe ``jax.devices()`` in a subprocess; retry timeouts for budget_s."""
    deadline = time.monotonic() + budget_s
    attempts = 0
    detail = ""
    while True:
        attempts += 1
        remaining = deadline - time.monotonic()
        # Fault point: a `wedge` fault simulates the subprocess hanging to
        # its timeout (a wedged lease) without burning the real timeout —
        # the retry/backoff/budget policy below runs unchanged.
        timed_out = faults.fire("probe.attempt", n=attempts) == "wedge"
        if not timed_out:
            try:
                subprocess.run(
                    [sys.executable, "-c", "import jax; jax.devices()"],
                    timeout=max(10.0, min(timeout_s, remaining))
                    if budget_s else timeout_s,
                    check=True,
                    capture_output=True,
                )
                return ProbeResult(True, attempts, "")
            except subprocess.CalledProcessError as exc:
                stderr = (exc.stderr or b"").decode(errors="replace")
                detail = f"init crashed (rc={exc.returncode}): {stderr[-500:]}"
                break  # deterministic crash: retrying reproduces it
            except subprocess.TimeoutExpired:
                timed_out = True
        if timed_out:
            detail = f"probe timed out after attempt {attempts} (wedged lease)"
            # Per-attempt progress to stderr: an operator tailing the log
            # must be able to tell "probe retrying through a wedge" from
            # "caller hung" (the no-kill rule makes that distinction
            # consequential).
            print(
                f"device probe attempt {attempts} timed out; "
                f"{max(0.0, remaining):.0f}s budget left",
                file=sys.stderr,
                flush=True,
            )
            if time.monotonic() + backoff_s >= deadline:
                break
            time.sleep(backoff_s)
    return ProbeResult(False, attempts, detail)
