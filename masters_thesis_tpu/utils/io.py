"""Crash-safe file publishing, in one place.

Several subsystems (the dataset cache, checkpoint sidecars) rely on the same
invariant: readers must never observe a torn file. The idiom is write-to-tmp
then atomic rename; the tmp name carries a uuid (pids alone are only unique
per host) so concurrent writers — including processes on different hosts
sharing a filesystem — each use their own scratch file and the last rename
wins with an intact artifact.

The reference gets torn-file safety implicitly from Lightning's checkpoint
machinery and writes its dataset cache with a bare ``torch.save``
(reference: src/data.py:216-219, train.py:151-161); here the invariant is
owned explicitly and shared by every writer.
"""

from __future__ import annotations

import uuid
from contextlib import contextmanager
from pathlib import Path
from typing import Iterator


@contextmanager
def atomic_publish(path: Path | str) -> Iterator[Path]:
    """Yield a scratch path; on clean exit, atomically rename onto ``path``.

    On exception the scratch file is removed and ``path`` is untouched.
    """
    path = Path(path)
    tmp = path.with_name(f"{path.name}.tmp{uuid.uuid4().hex[:12]}")
    try:
        yield tmp
        tmp.replace(path)
    finally:
        tmp.unlink(missing_ok=True)


def atomic_write_text(path: Path | str, text: str) -> None:
    with atomic_publish(path) as tmp:
        tmp.write_text(text)


def wait_until(predicate, timeout_s: float, interval_s: float = 0.5) -> bool:
    """Poll ``predicate`` until it returns True; False on timeout.

    Used by multi-process rendezvous (non-writer processes waiting for a
    writer's atomically-published artifact to appear).
    """
    import time

    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval_s)
    return False
