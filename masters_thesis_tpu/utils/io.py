"""Crash-safe file publishing, in one place.

Several subsystems (the dataset cache, checkpoint sidecars) rely on the same
invariant: readers must never observe a torn file. The idiom is write-to-tmp
then atomic rename; the tmp name carries a uuid (pids alone are only unique
per host) so concurrent writers — including processes on different hosts
sharing a filesystem — each use their own scratch file and the last rename
wins with an intact artifact.

The reference gets torn-file safety implicitly from Lightning's checkpoint
machinery and writes its dataset cache with a bare ``torch.save``
(reference: src/data.py:216-219, train.py:151-161); here the invariant is
owned explicitly and shared by every writer.
"""

from __future__ import annotations

import os
import uuid
from contextlib import contextmanager
from pathlib import Path
from typing import Iterator


@contextmanager
def atomic_publish(path: Path | str, fsync: bool = False) -> Iterator[Path]:
    """Yield a scratch path; on clean exit, atomically rename onto ``path``.

    On exception the scratch file is removed and ``path`` is untouched.
    With ``fsync=True`` the scratch file's bytes and the directory entry
    are flushed to stable storage before/after the rename — rename alone
    is atomic against concurrent readers but not against power loss, and
    checkpoint manifests must survive both.
    """
    path = Path(path)
    tmp = path.with_name(f"{path.name}.tmp{uuid.uuid4().hex[:12]}")
    try:
        yield tmp
        if fsync:
            fsync_path(tmp)
        tmp.replace(path)
        if fsync:
            fsync_path(path.parent)
    finally:
        tmp.unlink(missing_ok=True)


def atomic_write_text(path: Path | str, text: str, fsync: bool = False) -> None:
    with atomic_publish(path, fsync=fsync) as tmp:
        tmp.write_text(text)


def fsync_path(path: Path | str) -> None:
    """fsync a file or directory, best-effort (not all filesystems allow
    opening directories, and a failed flush must not fail the publish)."""
    try:
        fd = os.open(str(path), os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def wait_until(predicate, timeout_s: float, interval_s: float = 0.5) -> bool:
    """Poll ``predicate`` until it returns True; False on timeout.

    Used by multi-process rendezvous (non-writer processes waiting for a
    writer's atomically-published artifact to appear).
    """
    import time

    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval_s)
    return False
