"""Device mesh, sharding, and multi-host helpers.

This is the framework's native replacement for the distribution machinery the
reference delegates to Lightning/NCCL (reference: train.py:169-180 constructs
a DDP-capable Trainer; src/model.py:24-25 relies on torchmetrics'
``dist_reduce_fx="sum"`` cross-process reduction). Here the same roles are
played by a ``jax.sharding.Mesh`` over ICI, ``NamedSharding`` annotations on
the batch axis, and XLA-inserted collectives (psum for grads and metric
states) — the scaling-book recipe: pick a mesh, annotate shardings, let XLA
insert collectives.
"""

from masters_thesis_tpu.parallel.mesh import (
    DATA_AXIS,
    balanced_shard_sizes,
    batch_sharding,
    distributed_initialize,
    distributed_run_context,
    fleet_barrier,
    global_put,
    join_fleet,
    make_data_mesh,
    replicated_sharding,
    shard_bounds,
    shard_map,
)

__all__ = [
    "DATA_AXIS",
    "balanced_shard_sizes",
    "batch_sharding",
    "distributed_initialize",
    "distributed_run_context",
    "fleet_barrier",
    "global_put",
    "join_fleet",
    "make_data_mesh",
    "replicated_sharding",
    "shard_bounds",
    "shard_map",
]
