"""Mesh construction and batch-axis sharding for data-parallel training.

The one real parallel axis in this workload is the window/batch dimension
(SURVEY.md §2.2): windows are i.i.d. training examples, so data parallelism
shards the leading batch axis across chips and lets XLA psum the gradients
over ICI. Params stay replicated (the LSTM is ~100k params — far below the
point where model parallelism would pay).

Multi-host: each process calls :func:`distributed_initialize` first (wraps
``jax.distributed.initialize``), then builds the same mesh over
``jax.devices()`` — the global mesh spans all hosts, ICI within a slice,
DCN across slices, with XLA routing collectives accordingly.

This module natively owns what the reference leaves latent in its
dependency stack: the Lightning Trainer's DDP capability (reference:
train.py:169-180 passes no strategy, so DDP would only engage with
multiple visible devices) and torchmetrics' NCCL metric reduction hook
(reference: src/model.py:24-25, ``dist_reduce_fx="sum"``).
"""

from __future__ import annotations

import os

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from masters_thesis_tpu.resilience import faults
from masters_thesis_tpu.telemetry.schedule import record_collective

DATA_AXIS = "data"

#: Coordinator address exported by the fleet supervisor for each
#: generation (a fresh address per relaunch: the old coordinator died
#: with the old fleet). Read by :func:`join_fleet`.
COORDINATOR_ENV = "MTT_COORDINATOR"


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """Version-compatible ``shard_map``.

    Newer JAX exposes ``jax.shard_map(..., check_vma=...)``; the pinned
    0.4.x toolchain only has ``jax.experimental.shard_map.shard_map`` whose
    equivalent knob is ``check_rep``. All in-repo call sites (and tests) go
    through this wrapper so the hot path is source-compatible with both.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


def _enable_cpu_collectives() -> None:
    """Switch the CPU client to gloo collectives before distributed init.

    The XLA CPU backend refuses cross-process computations under its
    default collectives ("Multiprocess computations aren't implemented
    on the CPU backend"); the gloo implementation shipped with jaxlib
    handles them. Must run before ``jax.distributed.initialize`` / the
    first backend touch — hence called from the init guards, never after.
    A TPU/GPU platform ignores the flag, and older jax without the
    option just keeps its default.
    """
    platforms = (
        getattr(jax.config, "jax_platforms", None)
        or os.environ.get("JAX_PLATFORMS", "")
        or ""
    )
    if "cpu" not in platforms.split(","):
        return
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass


def distributed_initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    required: bool = False,
) -> None:
    """Initialize multi-host JAX (no-op for single-process runs).

    Replaces the torch.distributed/NCCL process-group setup Lightning would
    perform under DDP (latent in the reference; SURVEY.md §2.2). With no
    arguments, reads the standard cluster env (TPU pod metadata / SLURM /
    ``JAX_COORDINATOR_ADDRESS``).

    ``required=True`` (set when the user explicitly asked for distributed
    training, e.g. ``trainer.distributed=true``) turns an init failure into
    an error — silently degrading a misconfigured pod to single-host
    training would burn a full training run before anyone noticed.
    """
    # NOT jax.process_count(): that would itself initialize the XLA backend,
    # after which jax.distributed.initialize() refuses to run — the guard
    # must be side-effect-free (and version-compatible: older JAX has no
    # jax.distributed.is_initialized).
    from masters_thesis_tpu.utils.backend_probe import (
        distributed_client_initialized,
    )

    if distributed_client_initialized():
        return
    _enable_cpu_collectives()
    try:
        if coordinator_address is None and num_processes is None:
            jax.distributed.initialize()
        else:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
            )
    except (ValueError, RuntimeError) as exc:
        if required:
            raise RuntimeError(
                "distributed initialization was explicitly requested but "
                f"failed ({exc}); check the coordinator address / cluster "
                "env (JAX_COORDINATOR_ADDRESS, process count, process id)"
            ) from exc
        # Single-process environment without coordinator metadata.
        return
    # Export identity into the env so child processes and jax-free host
    # tooling (telemetry.run.process_identity, the fleet aggregator's
    # workers) resolve the same process index this backend holds —
    # setdefault, so an operator's explicit override wins.
    try:
        os.environ.setdefault("JAX_PROCESS_INDEX", str(jax.process_index()))
        os.environ.setdefault("JAX_PROCESS_COUNT", str(jax.process_count()))
    except Exception:
        pass


def join_fleet(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> tuple[int, int]:
    """World-size-parameterized (re-)initialization for a supervised
    fleet rank: read the identity the fleet supervisor exported
    (``MTT_COORDINATOR`` + ``JAX_PROCESS_INDEX``/``JAX_PROCESS_COUNT``,
    which change across generations when the fleet is elastically
    resized), initialize ``jax.distributed`` against this generation's
    coordinator, and return ``(process_id, num_processes)``.

    Init is *required* when a coordinator was exported: a rank that
    silently fell back to single-process training would train on 1/Nth
    of the data and publish a checkpoint the rest of the fleet never
    agreed on. Single-process launches (no coordinator in the env) are a
    no-op, so workers can use this unconditionally.
    """
    coordinator_address = coordinator_address or os.environ.get(
        COORDINATOR_ENV
    )
    if num_processes is None:
        num_processes = int(os.environ.get("JAX_PROCESS_COUNT", "1") or 1)
    if process_id is None:
        process_id = int(os.environ.get("JAX_PROCESS_INDEX", "0") or 0)
    if coordinator_address and num_processes > 1:
        distributed_initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            required=True,
        )
    return process_id, num_processes


def shard_bounds(n: int, world: int, rank: int) -> tuple[int, int]:
    """Balanced contiguous ``[lo, hi)`` bounds of ``rank``'s shard of
    ``n`` items across ``world`` processes.

    The remainder spreads over the FIRST ``n % world`` ranks, so shard
    sizes differ by at most one and the assignment is a pure function of
    ``(n, world, rank)`` — after an elastic resize every survivor
    recomputes its shard from the new world size and the union still
    covers all ``n`` items exactly once. This is the re-balancing rule
    the fleet supervisor relies on when it relaunches at N-1.
    """
    if world <= 0:
        raise ValueError(f"world must be positive, got {world}")
    if not 0 <= rank < world:
        raise ValueError(f"rank {rank} outside [0, {world})")
    base, extra = divmod(n, world)
    lo = rank * base + min(rank, extra)
    return lo, lo + base + (1 if rank < extra else 0)


def balanced_shard_sizes(n: int, world: int) -> list[int]:
    """Per-rank shard sizes under :func:`shard_bounds` (diagnostics and
    batch-divisibility checks)."""
    return [hi - lo for lo, hi in
            (shard_bounds(n, world, r) for r in range(world))]


def fleet_barrier(name: str) -> None:
    """Named cross-process sync point; no-op in single-process runs.

    Wraps ``multihost_utils.sync_global_devices`` behind the
    ``dist.barrier`` fault point so chaos plans can wedge one rank
    inside the barrier — the exact survivor pathology a dead host
    induces in a real collective, and what the fleet supervisor's
    hang watchdog must convert into an all-rank relaunch.
    """
    # Chain the entry BEFORE the fault point / sync: a rank wedged inside
    # the barrier has already published the schedule entry it is stuck
    # on, so the cross-rank audit can name it from the heartbeat alone.
    record_collective("barrier", name=name)
    faults.fire("dist.barrier", name=name)
    try:
        if jax.process_count() <= 1:
            return
    except RuntimeError:
        return  # no backend yet: nothing to synchronize
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def distributed_run_context() -> dict:
    """The fleet identity a run should stamp into its telemetry stream.

    Safe both before and after ``jax.distributed`` init: prefers the live
    backend's view, falls back to the cluster env the same way telemetry
    does (``JAX_PROCESS_INDEX``/``MT_HOST_INDEX``), so a ``run_started``
    event carries a usable identity in every launch mode.
    """
    from masters_thesis_tpu.telemetry.run import process_identity

    proc, nproc = process_identity()
    return {
        "process_index": proc,
        "process_count": nproc,
        "coordinator": os.environ.get("JAX_COORDINATOR_ADDRESS"),
    }


def global_put(tree, sharding: NamedSharding):
    """Place a host pytree onto a (possibly multi-process) sharding.

    Single-process meshes take the fast ``jax.device_put`` path. When the
    mesh spans processes, ``device_put`` would reject the non-addressable
    shards; instead each process materializes the shards its own devices
    hold via ``make_array_from_callback``. Every process passes the SAME
    full host value (the datamodule cache is shared/deterministic per
    host — SURVEY.md §7 multi-host data), and the callback slices out the
    local blocks.
    """

    def put(a):
        if sharding.is_fully_addressable:
            # Fast path: device-side resharding; no host round-trip for
            # already-device-resident leaves (params/opt_state after init).
            return jax.device_put(a, sharding)
        a = np.asarray(a)
        return jax.make_array_from_callback(
            a.shape, sharding, lambda idx: a[idx]
        )

    return jax.tree_util.tree_map(put, tree)


def make_data_mesh(n_devices: int | None = None) -> Mesh:
    """1-D mesh over the data axis using the first ``n_devices`` devices.

    On a real slice the device order from ``jax.devices()`` is
    torus-contiguous, so neighbouring mesh coordinates are ICI neighbours and
    the gradient psum rides ICI, not DCN.
    """
    devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devices)} visible"
            )
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), axis_names=(DATA_AXIS,))


def batch_sharding(mesh: Mesh, batch_dim: int = 0) -> NamedSharding:
    """Sharding that splits ``batch_dim`` over the data axis, rest replicated."""
    spec = [None] * batch_dim + [DATA_AXIS]
    return NamedSharding(mesh, PartitionSpec(*spec))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully-replicated sharding (params, opt state, scalars)."""
    return NamedSharding(mesh, PartitionSpec())


