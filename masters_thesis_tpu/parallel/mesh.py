"""Mesh construction and batch-axis sharding for data-parallel training.

The one real parallel axis in this workload is the window/batch dimension
(SURVEY.md §2.2): windows are i.i.d. training examples, so data parallelism
shards the leading batch axis across chips and lets XLA psum the gradients
over ICI. Params stay replicated (the LSTM is ~100k params — far below the
point where model parallelism would pay).

Multi-host: each process calls :func:`distributed_initialize` first (wraps
``jax.distributed.initialize``), then builds the same mesh over
``jax.devices()`` — the global mesh spans all hosts, ICI within a slice,
DCN across slices, with XLA routing collectives accordingly.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DATA_AXIS = "data"


def distributed_initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Initialize multi-host JAX (no-op for single-process runs).

    Replaces the torch.distributed/NCCL process-group setup Lightning would
    perform under DDP (latent in the reference; SURVEY.md §2.2). With no
    arguments, reads the standard cluster env (TPU pod metadata / SLURM /
    ``JAX_COORDINATOR_ADDRESS``).
    """
    if jax.process_count() > 1:
        return  # already initialized
    try:
        if coordinator_address is None and num_processes is None:
            jax.distributed.initialize()
        else:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
            )
    except (ValueError, RuntimeError):
        # Single-process environment without coordinator metadata.
        pass


def make_data_mesh(n_devices: int | None = None) -> Mesh:
    """1-D mesh over the data axis using the first ``n_devices`` devices.

    On a real slice the device order from ``jax.devices()`` is
    torus-contiguous, so neighbouring mesh coordinates are ICI neighbours and
    the gradient psum rides ICI, not DCN.
    """
    devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devices)} visible"
            )
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), axis_names=(DATA_AXIS,))


def batch_sharding(mesh: Mesh, batch_dim: int = 0) -> NamedSharding:
    """Sharding that splits ``batch_dim`` over the data axis, rest replicated."""
    spec = [None] * batch_dim + [DATA_AXIS]
    return NamedSharding(mesh, PartitionSpec(*spec))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully-replicated sharding (params, opt state, scalars)."""
    return NamedSharding(mesh, PartitionSpec())


