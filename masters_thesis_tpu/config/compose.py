"""Config composition: groups + defaults list + interpolation + overrides.

Semantics mirror the subset of Hydra the reference exercises
(reference: configs/config.yaml, train.py:39-42,70 and the ``-m`` sweeps in
sweeps/*.sh):

- ``defaults`` list in the primary config selects one YAML per config group
  (``- model: small`` loads ``<config_dir>/model/small.yaml`` under the
  ``model`` key); ``_self_`` positions the primary config's own keys in the
  merge order.
- CLI overrides: ``group=option`` re-selects a group, ``a.b=value`` sets a
  leaf (yaml-typed), ``+a.b=value`` adds a new key, ``~a.b`` deletes one.
- Interpolations: ``${a.b}`` references another config node;
  ``${resolver:arg1,arg2}`` calls a registered resolver; interpolations
  nest (``${f:${a.b}}`` — reference: configs/model/small.yaml:1).
- Multirun: comma-separated values in overrides expand to the cartesian
  product of single-run override lists (reference: sweeps/example.sh).
"""

from __future__ import annotations

import copy
import itertools
from pathlib import Path
from typing import Any, Callable

import yaml


class Config(dict):
    """Nested dict with attribute access (``cfg.model.hidden_size``)."""

    def __getattr__(self, name: str) -> Any:
        try:
            return self[name]
        except KeyError:
            raise AttributeError(name) from None

    def __setattr__(self, name: str, value: Any) -> None:
        self[name] = value

    @staticmethod
    def wrap(obj: Any) -> Any:
        """Recursively convert plain dicts to Config."""
        if isinstance(obj, dict):
            return Config({k: Config.wrap(v) for k, v in obj.items()})
        if isinstance(obj, list):
            return [Config.wrap(v) for v in obj]
        return obj


_RESOLVERS: dict[str, Callable[..., Any]] = {}


def register_resolver(name: str, fn: Callable[..., Any]) -> None:
    """Register a ``${name:args}`` resolver (reference: train.py:39-42 uses
    OmegaConf.register_new_resolver for ``input_size_from_interaction``)."""
    _RESOLVERS[name] = fn


# --------------------------------------------------------------- primitives


def _parse_value(text: str) -> Any:
    """YAML-typed parse of an override value ('1e-4' -> float, 'true' -> bool).

    YAML 1.1 only floats exponent literals with a dot ('1.0e-4'), but CLI
    sweeps write '1e-4' (reference: sweeps/example.sh) — try numbers first.
    """
    try:
        return int(text)
    except ValueError:
        pass
    try:
        return float(text)
    except ValueError:
        pass
    try:
        return yaml.safe_load(text)
    except yaml.YAMLError:
        return text


def _get_path(cfg: dict, path: str) -> Any:
    node: Any = cfg
    for part in path.split("."):
        if not isinstance(node, dict) or part not in node:
            raise KeyError(f"config path not found: {path!r} (missing {part!r})")
        node = node[part]
    return node


def _set_path(cfg: dict, path: str, value: Any, *, allow_new: bool) -> None:
    parts = path.split(".")
    node: Any = cfg
    for part in parts[:-1]:
        if part not in node:
            if not allow_new:
                raise KeyError(
                    f"override path not found: {path!r} (missing {part!r}); "
                    f"use +{path} to add new keys"
                )
            node[part] = Config()
        node = node[part]
    if parts[-1] not in node and not allow_new:
        raise KeyError(
            f"override path not found: {path!r}; use +{path} to add new keys"
        )
    node[parts[-1]] = value


def _del_path(cfg: dict, path: str) -> None:
    parts = path.split(".")
    node = _get_path(cfg, ".".join(parts[:-1])) if len(parts) > 1 else cfg
    node.pop(parts[-1], None)


# ------------------------------------------------------------ interpolation


def _find_interpolation(text: str) -> tuple[int, int] | None:
    """Locate the first ``${...}`` span, honouring nested braces."""
    start = text.find("${")
    if start < 0:
        return None
    depth = 0
    i = start
    while i < len(text):
        if text.startswith("${", i):
            depth += 1
            i += 2
            continue
        if text[i] == "}":
            depth -= 1
            if depth == 0:
                return start, i + 1
        i += 1
    raise ValueError(f"unterminated interpolation in {text!r}")


def _resolve_expr(expr: str, root: dict, stack: tuple[str, ...]) -> Any:
    """Resolve the inside of one ``${...}``: resolver call or config path."""
    expr = _resolve_str(expr, root, stack)
    if isinstance(expr, str) and ":" in expr:
        name, _, argstr = expr.partition(":")
        if name in _RESOLVERS:
            args = [_parse_value(a) for a in argstr.split(",")] if argstr else []
            return _RESOLVERS[name](*args)
    if expr in stack:
        raise ValueError(f"interpolation cycle: {' -> '.join(stack + (expr,))}")
    value = _get_path(root, expr)
    return _resolve_node(value, root, stack + (expr,))


def _resolve_str(value: str, root: dict, stack: tuple[str, ...]) -> Any:
    span = _find_interpolation(value) if isinstance(value, str) else None
    if span is None:
        return value
    start, end = span
    inner = _resolve_expr(value[start + 2 : end - 1], root, stack)
    if start == 0 and end == len(value):
        return inner  # whole-string interpolation keeps the value's type
    rest = _resolve_str(value[end:], root, stack)
    return f"{value[:start]}{inner}{rest}"


def _resolve_node(node: Any, root: dict, stack: tuple[str, ...] = ()) -> Any:
    if isinstance(node, str):
        return _resolve_str(node, root, stack)
    if isinstance(node, dict):
        return Config({k: _resolve_node(v, root, stack) for k, v in node.items()})
    if isinstance(node, list):
        return [_resolve_node(v, root, stack) for v in node]
    return node


# ---------------------------------------------------------------- composing


def _deep_merge(base: dict, extra: dict) -> dict:
    for key, value in extra.items():
        if isinstance(value, dict) and isinstance(base.get(key), dict):
            _deep_merge(base[key], value)
        else:
            base[key] = copy.deepcopy(value)
    return base


def _load_yaml(path: Path) -> Config:
    if not path.exists():
        raise FileNotFoundError(f"config file not found: {path}")
    with open(path) as f:
        return Config.wrap(yaml.safe_load(f) or {})


def parse_overrides(
    overrides: list[str],
) -> tuple[dict[str, str], list[tuple[str, str, Any]]]:
    """Split CLI overrides into (group selections, value edits).

    Group selections are ``name=option`` where ``name`` has no dot and no
    ``+``/``~`` prefix; whether a name actually is a group is decided by the
    caller against the config tree.
    """
    groups: dict[str, str] = {}
    edits: list[tuple[str, str, Any]] = []
    for ov in overrides:
        if ov.startswith("~"):
            edits.append(("del", ov[1:], None))
            continue
        if "=" not in ov:
            raise ValueError(f"malformed override (expected key=value): {ov!r}")
        key, _, raw = ov.partition("=")
        if key.startswith("+"):
            edits.append(("add", key[1:], _parse_value(raw)))
        elif "." not in key:
            groups[key] = raw
        else:
            edits.append(("set", key, _parse_value(raw)))
    return groups, edits


def compose(
    config_dir: str | Path,
    config_name: str = "config",
    overrides: list[str] | None = None,
    resolve: bool = True,
) -> Config:
    """Compose the run config exactly as Hydra would (see module docstring)."""
    config_dir = Path(config_dir)
    overrides = list(overrides or [])
    groups, edits = parse_overrides(overrides)

    primary = _load_yaml(config_dir / f"{config_name}.yaml")
    defaults = primary.pop("defaults", [{"_self_": None}])

    cfg = Config()
    self_merged = False
    for entry in defaults:
        if entry == "_self_":
            _deep_merge(cfg, primary)
            self_merged = True
            continue
        if not isinstance(entry, dict) or len(entry) != 1:
            raise ValueError(f"malformed defaults entry: {entry!r}")
        (group, option), = entry.items()
        option = groups.pop(group, option)
        if option in (None, "null"):
            continue
        cfg[group] = _load_yaml(config_dir / group / f"{option}.yaml")
    if not self_merged:
        _deep_merge(cfg, primary)

    # Group-style overrides for groups not in the defaults list: treat a
    # bare name as a group if <config_dir>/<name>/ exists, else as a
    # top-level value edit (e.g. `checkpoint=path`).
    for name, raw in groups.items():
        if (config_dir / name).is_dir():
            cfg[name] = _load_yaml(config_dir / name / f"{raw}.yaml")
        else:
            edits.append(("set", name, _parse_value(raw)))

    for action, path, value in edits:
        if action == "del":
            _del_path(cfg, path)
        else:
            _set_path(cfg, path, value, allow_new=(action == "add"))

    return _resolve_node(cfg, cfg) if resolve else cfg


def expand_multirun(overrides: list[str]) -> list[list[str]]:
    """Expand comma-separated override values into the cartesian sweep.

    ``["lr=1e-3,1e-4", "model=large"]`` -> two single-run override lists
    (reference: sweeps/example.sh drives Hydra ``-m`` the same way).
    Commas inside brackets are value syntax (``dims=[16,32]``), not sweep
    separators.
    """
    choice_lists: list[list[str]] = []
    for ov in overrides:
        key, eq, raw = ov.partition("=")
        choices = _split_top_level(raw) if eq else [raw]
        if len(choices) > 1:
            choice_lists.append([f"{key}={v}" for v in choices])
        else:
            choice_lists.append([ov])
    return [list(combo) for combo in itertools.product(*choice_lists)]


def _split_top_level(raw: str) -> list[str]:
    """Split on commas not nested inside []/{}."""
    parts, depth, start = [], 0, 0
    for i, ch in enumerate(raw):
        if ch in "[{":
            depth += 1
        elif ch in "]}":
            depth -= 1
        elif ch == "," and depth == 0:
            parts.append(raw[start:i])
            start = i + 1
    parts.append(raw[start:])
    return parts


def to_flat_dict(cfg: dict, prefix: str = "") -> dict[str, Any]:
    """Flatten to ``{'model.hidden_size': 64, ...}`` — for hparam logging."""
    flat: dict[str, Any] = {}
    for key, value in cfg.items():
        path = f"{prefix}{key}"
        if isinstance(value, dict):
            flat.update(to_flat_dict(value, f"{path}."))
        else:
            flat[path] = value
    return flat
