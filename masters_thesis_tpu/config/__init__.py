"""Native Hydra-compatible configuration engine.

The reference composes its run config with Hydra (reference: train.py:70,
configs/config.yaml:1-7): a ``defaults`` list selects one option per config
group, ``${...}`` interpolations derive values, CLI ``key=value`` overrides
mutate anything, and ``-m`` expands comma-separated overrides into a
cartesian sweep. Hydra is not part of this framework's dependency set, so
the same semantics are implemented natively here in ~300 lines: the CLI
surface (``python train.py model=large loss=nll``, ``-m lr=1e-3,1e-4``)
is part of the capability contract (SURVEY.md §7) and must keep working.
"""

from masters_thesis_tpu.config.compose import (
    Config,
    compose,
    expand_multirun,
    register_resolver,
    to_flat_dict,
)

__all__ = [
    "Config",
    "compose",
    "expand_multirun",
    "register_resolver",
    "to_flat_dict",
]
