#!/bin/bash
# Round-4 TPU work queue: wait for relay health, run the interactive
# measurement stack while the grid runner is PAUSEd (results/PAUSE), then
# hand the chip to the grid (rm PAUSE). Timeouts are generous backstops —
# killing TPU-attached processes can wedge the relay, so they should never
# fire in a healthy run.
cd /root/repo || exit 1

# Never leave the grid runner paused if this script dies mid-queue: the
# PAUSE marker must not outlive the process that owns it.
trap 'rm -f results/PAUSE results/BENCH_REQUEST' EXIT

while true; do
  if timeout 90 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    break
  fi
  echo "$(date -u +%H:%M:%S) relay wedged; retry in 240s"
  sleep 240
done
echo "$(date -u +%H:%M:%S) relay healthy; starting TPU queue"

echo "== stack kernel Mosaic check =="
timeout 900 python sweeps/check_stack_tpu.py 2>&1

echo "== fresh bench capture =="
timeout 2700 python bench.py > results/bench_r4_tpu.json 2> results/bench_r4_tpu.log
tail -c 400 results/bench_r4_tpu.json

echo "== wavefront A/B sweep =="
timeout 4500 python sweeps/bench_fused_pair.py 2>&1 | tee results/bench_fused_r4.log

echo "== profile breakdown =="
timeout 1800 python sweeps/profile_breakdown.py 2>&1 | tee results/profile_r4.log

rm -f results/PAUSE results/BENCH_REQUEST
echo "$(date -u +%H:%M:%S) TPU queue done; grid unpaused"
