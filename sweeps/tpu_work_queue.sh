#!/bin/bash
# Round-4 TPU work queue: pause the grid runner, wait for relay health AND
# the grid's in-flight cell to finish, run the interactive measurement
# stack, then hand the chip back (remove our pause). Timeouts are generous
# backstops — killing TPU-attached processes can wedge the relay, so they
# should never fire in a healthy run.
cd /root/repo || exit 1

# Queue-level heartbeat: the queue runs unattended and a SIGKILL (driver
# budget cap) leaves no log tail — the heartbeat file shows which stage
# was in flight, same protocol as the trainer's heartbeat.json. Atomic
# via mv so readers never see a torn file.
HB=results/heartbeats/tpu_queue.json
beat() {
  mkdir -p results/heartbeats
  printf '{"stage": "%s", "ts": %s, "pid": %d}\n' \
    "$1" "$(date -u +%s)" "$$" > "$HB.tmp" && mv "$HB.tmp" "$HB"
}
beat "starting"

# Own the pause: create it if absent, and on ANY exit remove it only if WE
# created it (an operator's pre-existing PAUSE is theirs to lift). A
# pending BENCH_REQUEST is left alone on early death — it is only consumed
# at the end, once this queue has actually captured a bench itself.
CREATED_PAUSE=0
if [ ! -f results/PAUSE ]; then
  touch results/PAUSE
  CREATED_PAUSE=1
fi
trap '[ "$CREATED_PAUSE" = 1 ] && rm -f results/PAUSE' EXIT

beat "waiting_relay"
while true; do
  if timeout 90 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    break
  fi
  echo "$(date -u +%H:%M:%S) relay wedged; retry in 240s"
  sleep 240
done
echo "$(date -u +%H:%M:%S) relay healthy"

# PAUSE only stops the runner from LAUNCHING new cells; an in-flight
# train.py cell owns the chip until it finishes. Concurrent use crashes it
# (documented failure mode) — wait it out.
beat "waiting_cell"
while pgrep -f "python train.py" > /dev/null 2>&1; do
  echo "$(date -u +%H:%M:%S) grid cell in flight; waiting 120s"
  sleep 120
done
echo "$(date -u +%H:%M:%S) chip free; starting TPU queue"

echo "== stack kernel Mosaic check =="
beat "stack_kernel_check"
timeout 900 python sweeps/check_stack_tpu.py 2>&1

echo "== fresh bench capture =="
beat "bench"
# --telemetry-dir makes every watchdogged point write its events.jsonl +
# flight-recorder files under one root, so a failed capture has something
# for the postmortem below to read. The resilience supervisor classifies
# a dead capture (preempted? relay UNAVAILABLE? reproducible crash?) and
# retries transient failures once; bench handles its own probe-and-pin-CPU
# degradation in-process, so the supervisor's probe stays off. Supervisor
# chatter goes to stderr — stdout stays bench's JSON line. The outer
# timeout is the same last-resort backstop as before.
BENCH_TEL=results/bench_r4_telemetry
timeout 2700 python -m masters_thesis_tpu.resilience run \
  --run-dir results/bench_r4_supervisor --watch-dir "$BENCH_TEL" \
  --max-retries 1 --backoff-s 30 --attempt-timeout-s 1800 \
  --retry-budget-s 2400 \
  -- python bench.py --telemetry-dir "$BENCH_TEL" \
  > results/bench_r4_tpu.json 2> results/bench_r4_tpu.log
BENCH_RC=$?
tail -c 400 results/bench_r4_tpu.json
if [ "$BENCH_RC" -ne 0 ] || ! [ -s results/bench_r4_tpu.json ]; then
  # No JSON line (hang/SIGKILL) or nonzero exit: reconstruct what died
  # from the per-point streams. The postmortem CLI is jax-free by
  # contract, so it works exactly when the chip is wedged.
  echo "== bench failed (rc=$BENCH_RC); postmortem =="
  beat "bench_postmortem"
  timeout 300 python -m masters_thesis_tpu.telemetry postmortem \
    "$BENCH_TEL" 2>&1 | tee -a results/bench_r4_tpu.log
fi

echo "== wavefront A/B sweep =="
beat "fused_pair_sweep"
timeout 4500 python sweeps/bench_fused_pair.py 2>&1 | tee results/bench_fused_r4.log

echo "== profile breakdown =="
beat "profile_breakdown"
timeout 1800 python sweeps/profile_breakdown.py 2>&1 | tee results/profile_r4.log

# Queue complete: the opportunistic-bench request is satisfied by the
# capture above, and the chip goes back to the grid.
rm -f results/BENCH_REQUEST results/PAUSE
CREATED_PAUSE=0
beat "done"
echo "$(date -u +%H:%M:%S) TPU queue done; grid unpaused"
