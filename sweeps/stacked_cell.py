#!/usr/bin/env python
"""Train a stack-compatible group of grid cells in ONE compiled program.

The grid runner (run_grid_canonical.py) groups cells that share
(model, loss, trainer) and differ only in seed / learning rate — exactly
the stack-compatibility contract of masters_thesis_tpu.train.stacked —
and launches this script once per group under the resilience supervisor.
Each replica gets its own checkpoints under ``<ckpt-dir>/<name>/`` in the
same layout train.py produces, so sweeps/eval_cell.py evaluates each cell
of the group unchanged.

Usage::

    python sweeps/stacked_cell.py model=small loss=mse trainer=slow \
        --replicas '[{"name": "s0", "seed": 0}, {"name": "s1", "seed": 1}]' \
        --ckpt-dir logs/FinancialLstm/synthetic_stacked/mse_small_slow

Replica entries take an optional ``"lr"``; omitted means the config's
model.learning_rate. Prints ONE JSON line with per-replica outcomes; exit
0 iff at least one replica finished unmasked (the supervisor treats
nonzero like any crashed training attempt).
"""

from __future__ import annotations

import argparse
import json
import math
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from train import CONFIG_DIR, bootstrap, build_datamodule, build_spec  # noqa: E402
from masters_thesis_tpu.config import compose  # noqa: E402


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("overrides", nargs="*", help="key=value overrides")
    parser.add_argument(
        "--replicas", required=True,
        help='JSON list of {"name", "seed", optional "lr"} entries',
    )
    parser.add_argument(
        "--ckpt-dir", required=True, type=Path,
        help="root dir; each replica checkpoints under <ckpt-dir>/<name>/",
    )
    parser.add_argument(
        "--max-epochs", type=int, default=None,
        help="override trainer.max_epochs from the composed config",
    )
    args = parser.parse_args()

    cfg = compose(str(CONFIG_DIR), overrides=args.overrides)
    if not bootstrap(cfg):
        return 1
    dm = build_datamodule(cfg)
    spec = build_spec(cfg)

    from masters_thesis_tpu.train import ReplicaSpec, StackedTrainer
    from masters_thesis_tpu.utils import enable_persistent_compilation_cache

    enable_persistent_compilation_cache()
    replicas = [
        ReplicaSpec(
            name=str(r["name"]),
            seed=int(r["seed"]),
            learning_rate=float(r.get("lr") or spec.learning_rate),
        )
        for r in json.loads(args.replicas)
    ]

    t = cfg.trainer
    trainer = StackedTrainer(
        max_epochs=args.max_epochs or t.max_epochs,
        gradient_clip_val=t.gradient_clip_val,
        check_val_every_n_epoch=t.get("check_val_every_n_epoch", 1),
        strategy=t.strategy,
        n_devices=t.get("n_devices", None),
        enable_progress_bar=t.enable_progress_bar,
        ckpt_dir=args.ckpt_dir,
        # The supervisor relaunches this process after preemptions/crashes;
        # resume picks the group up at its last common 'last' epoch.
        resume=True,
        preflight=t.get("preflight", False),
        telemetry=args.ckpt_dir / "telemetry",
    )
    result = trainer.fit(spec, dm, replicas)

    rows = [
        {
            "name": r.name,
            "status": r.status,
            "best_val": (
                r.best_val_loss if math.isfinite(r.best_val_loss) else None
            ),
            "rollbacks": r.rollbacks,
            "checkpoint": str(args.ckpt_dir / r.name / "best"),
        }
        for r in result.replicas
    ]
    print(json.dumps({
        "replicas": rows,
        "steps_per_sec": result.steps_per_sec,
        "epochs": result.epochs,
    }))
    return 0 if any(r.status != "masked" for r in result.replicas) else 1


if __name__ == "__main__":
    sys.exit(main())
