#!/usr/bin/env python
"""Render results/grid_r3.jsonl into the RESULTS.md ΔL markdown table.

Takes the LAST row per cell (earlier rows may be truncated runs that a
re-run of sweeps/run_grid_canonical.py resumed). Prints markdown to stdout;
paste/commit into RESULTS.md. The ΔL convention matches the thesis table
(reference: tex/diplomski_rad.tex:1155-1176): ΔL_MSE reported ×1e-5,
ΔL_MIX with ζ=1e5.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

RESULTS_DIR = Path(__file__).resolve().parent.parent / "results"
OUT = RESULTS_DIR / "grid_r3.jsonl"
MIDSCALE = RESULTS_DIR / "warmup_cpu_midscale.jsonl"


def load_cells(path: Path) -> tuple[dict, float]:
    """(last row per cell, total wall across ALL rows — truncated runs that
    were later resumed each contributed real compute)."""
    cells: dict = {}
    total_wall = 0.0
    if not path.exists():
        return cells, total_wall
    for line in path.read_text().splitlines():
        if line.strip():
            row = json.loads(line)
            cells[row["cell"]] = row  # last row per cell wins
            total_wall += row.get("train_wall_s", 0)
    return cells, total_wall


def fmt(row: dict, who: str) -> str:
    d = row[who]
    return (
        f"{d['delta_mse'] * 1e5:.3f} | {d['delta_nll']:.3f} | "
        f"{d['delta_mix']:.3f}"
    )


def warmup_table(cells: dict, prefix: str, model_size: str,
                 header: str) -> bool:
    """Scratch-vs-warmup comparison (the thesis' headline protocol,
    tex/diplomski_rad.tex:1134-1147): for each objective on the fine-tune
    dataset, from-scratch training vs warm-started from the
    synthetic-pretrained weights, plus the OLS baseline on that data.
    Prints ``header`` + table only when at least one pair exists; returns
    whether anything rendered (no orphan headers)."""
    pairs = []
    for loss in ("mse", "nll", "combined"):
        scratch = cells.get(f"{prefix}{loss}_{model_size}_scratch")
        warm = cells.get(f"{prefix}{loss}_{model_size}_warmup")
        if scratch or warm:
            pairs.append((loss, scratch, warm))
    if not pairs:
        return False
    print(header)
    print("\n| Objective | ΔL_MIX scratch | ΔL_MIX warmup | ΔL_MIX OLS | "
          "warmup wins? |")
    print("|---|---|---|---|---|")
    for loss, scratch, warm in pairs:
        s = scratch["model"]["delta_mix"] if scratch else None
        w = warm["model"]["delta_mix"] if warm else None
        ols = (scratch or warm)["ols"]["delta_mix"]
        verdict = (
            "?" if s is None or w is None
            else ("yes" if w < s else "no")
        )
        print(
            f"| {loss} | {s if s is None else f'{s:.3f}'} | "
            f"{w if w is None else f'{w:.3f}'} | {ols:.3f} | "
            f"{verdict} |"
        )
    return True


def main() -> None:
    cells, total_wall = load_cells(OUT)
    if not cells:
        sys.exit("no recorded cells")

    print("| Cell | epochs | ΔL_MSE(×1e-5) | ΔL_NLL | ΔL_MIX(ζ=1e5) | "
          "OLS ΔL_MSE | OLS ΔL_NLL | OLS ΔL_MIX |")
    print("|---|---|---|---|---|---|---|---|")
    order = sorted(cells)
    for name in order:
        row = cells[name]
        epochs = (row.get("epoch", "?"), "T" if row.get("truncated") else "")
        print(
            f"| {name} | {epochs[0]}{epochs[1]} | {fmt(row, 'model')} | "
            f"{fmt(row, 'ols')} |"
        )
    print(f"\n{len(cells)} cells; total train wall {total_wall / 3600:.2f}h "
          "(all runs incl. resumed); truncated: "
          f"{sum(1 for r in cells.values() if r.get('truncated'))}")

    warmup_table(
        cells, "outliers_", "large",
        "\n### Warmup protocol (fine-tune dataset: outliers DGP)",
    )

    # CPU insurance capture of the same protocol at 1/20th scale
    # (sweeps/run_warmup_cpu_midscale.py) — rendered separately and
    # clearly labeled; never mixed with the canonical rows.
    mid_cells, mid_wall = load_cells(MIDSCALE)
    if warmup_table(
        mid_cells, "mid_outliers_", "small",
        "\n### Warmup protocol at 1/20th scale "
        "(CPU insurance capture: 50k-sample bootstrap, model=small)",
    ):
        print(f"\n{len(mid_cells)} midscale cells; total train wall "
              f"{mid_wall / 3600:.2f}h on the CPU backend")


if __name__ == "__main__":
    main()
