#!/bin/bash
# Round-5 TPU orchestrator. The r4 lesson (docs/OPERATIONS.md): the relay
# may give ONE healthy window all round — when it opens, capture every
# queued on-chip deliverable, cheapest-evidence first, before the
# long-running grid takes the chip.
#
# Queue (VERDICT r4 "Next round" items, cheap->expensive):
#   1. check_timeblocked_tpu.py  — the only kernel with zero Mosaic evidence
#   2. check_stack_tpu.py        — re-gate the wavefront stack
#   3. bench.py                  — fresh TPU headline + regenerate the
#                                  last_tpu_measurement cache (reset wiped it)
#   4. bench_fused_pair.py       — per-model wavefront A/B table
#   5. profile_breakdown.py      — step-time attribution trace
#   6. run_grid_canonical.py     — warmup/scratch cells, then slowest column
#
# Timeouts are generous backstops sized never to fire in a healthy run —
# SIGKILLing a TPU-attached child is the suspected r4 wedge trigger.
# State goes to results/R5_STATE so the operator knows when the chip (and
# the single host core) is in use: no heavy CPU work while state != wait.
# An abnormal exit leaves state=interrupted (NOT done): a TERMed script's
# foreground child may still hold the chip, so the operator must check
# for survivors before assuming the core is free.
cd /root/repo || exit 1
STATE=results/R5_STATE
GRID_DEADLINE="2026-08-01T04:30"
FINISHED=0

state() { echo "$1" > "$STATE"; echo "$(date -u +%H:%M:%S) state: $1"; }

CREATED_PAUSE=0
if [ ! -f results/PAUSE ]; then
  touch results/PAUSE
  CREATED_PAUSE=1
fi
on_exit() {
  [ "$CREATED_PAUSE" = 1 ] && rm -f results/PAUSE
  if [ "$FINISHED" = 1 ]; then echo done > "$STATE"; else echo interrupted > "$STATE"; fi
}
trap on_exit EXIT

state wait
# A train.py whose cmdline carries "midscale" is the CPU-pinned,
# nice-19 insurance runner (sweeps/run_warmup_cpu_midscale.py) — it never
# touches the relay and must NOT starve heal detection. Only relay-backed
# cells (everything else) demand exclusivity.
tpu_train_running() {
  for pid in $(pgrep -f "python train.py" 2>/dev/null); do
    if ! tr '\0' ' ' < "/proc/$pid/cmdline" 2>/dev/null | grep -q midscale; then
      return 0
    fi
  done
  return 1
}
# ORDER MATTERS (one TPU process at a time): an in-flight train.py cell
# owns both the chip and the relay — probing the relay while it runs
# crashes both with UNAVAILABLE. Wait out any cell FIRST, then probe.
while tpu_train_running; do
  echo "$(date -u +%H:%M:%S) train.py holds the chip; waiting 120s"
  sleep 120
done
while true; do
  if timeout 90 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    break
  fi
  echo "$(date -u +%H:%M:%S) relay wedged; retry in 240s"
  sleep 240
  # A cell could in principle appear while we slept (grid runner from a
  # prior round); re-assert exclusivity before the next probe.
  while tpu_train_running; do
    echo "$(date -u +%H:%M:%S) train.py holds the chip; waiting 120s"
    sleep 120
  done
done
echo "$(date -u +%H:%M:%S) relay healthy; chip free; starting TPU queue"

state gates
echo "== time-blocked kernel Mosaic gate (first ever on-chip run) =="
timeout 1800 python sweeps/check_timeblocked_tpu.py 2>&1 | tee results/check_timeblocked_r5.log
echo "== stack wavefront Mosaic gate =="
timeout 1200 python sweeps/check_stack_tpu.py 2>&1 | tee results/check_stack_r5.log

state bench
echo "== fresh bench capture =="
# Backstop must EXCEED bench.py's internal watchdog worst case (~600s
# probe + 2400s headline + 3x700s aux + 3000s scaling ≈ 8100s): a fired
# outer timeout SIGTERMs only the parent python, orphaning a TPU-attached
# watchdog grandchild that then contends with the next queue stage for
# the one relay lease (code review r5).
timeout 8700 python bench.py > results/bench_r5_tpu.json 2> results/bench_r5_tpu.log
tail -c 400 results/bench_r5_tpu.json

state ab_sweep
echo "== wavefront A/B sweep =="
timeout 7200 python sweeps/bench_fused_pair.py 2>&1 | tee results/bench_fused_r5.log

state profile
echo "== profile breakdown =="
timeout 2400 python sweeps/profile_breakdown.py 2>&1 | tee results/profile_r5.log

# Hand the chip to the grid: it has its own probe/pause/deadline logic.
# Only lift a PAUSE this script created — an operator's pre-existing hold
# stays theirs to lift (code review r5).
if [ "$CREATED_PAUSE" = 1 ]; then
  rm -f results/PAUSE
fi
CREATED_PAUSE=0
state grid
python sweeps/run_grid_canonical.py --deadline "$GRID_DEADLINE" \
  > results/grid_r5_runner.log 2>&1
FINISHED=1
echo "$(date -u +%H:%M:%S) round-5 TPU queue complete"
