#!/bin/bash
# 18-job synthetic experiment grid: 3 models x 3 losses x 2 trainers
# (reference: sweeps/experiment_synthetic.sh — same grid).
python train.py -m datamodule=synthetic \
    model=small,medium,large \
    loss=mse,nll,combined \
    trainer=slow,slowest
