#!/usr/bin/env python
"""Epoch-scale bf16-vs-f32 training parity check.

The bf16 stack wavefront is the measured latency lever for deep models
(RESULTS.md), but flipping ``precision`` defaults needs evidence that
bf16 COMPUTE (f32 params/loss math, ops/lstm_kernel.py) does not bend the
training trajectory at epoch scale — the reference's entire precision
story is one global ``torch.set_float32_matmul_precision('medium')``
(reference: train.py:13) with no such check at all.

Trains the same cell twice (32-true vs bf16-mixed), compares the
validation-loss trajectory and final best-val, prints ONE JSON line:
``{"parity": bool, "rel_final_gap": float, "curve": {...}}``. Parity =
final best-val relative gap under --tolerance (default 2%).

Runs on whatever backend the environment provides: the CPU backend at
reduced scale is the wedged-relay insurance capture; the TPU at canonical
scale is the real deliverable. Device is recorded in the JSON line.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def run_one(precision: str, args) -> dict:
    import math

    from masters_thesis_tpu.data.pipeline import (
        FinancialWindowDataModule,
        bootstrap_synthetic,
    )
    from masters_thesis_tpu.models.objectives import ModelSpec
    from masters_thesis_tpu.train import Trainer

    data_dir = REPO / args.data_dir
    bootstrap_synthetic(
        data_dir, n_stocks=100, n_samples=args.n_samples, seed=0
    )
    dm = FinancialWindowDataModule(
        data_dir, lookback_window=60, target_window=30, stride=90,
        batch_size=1,
    )
    trainer = Trainer(
        max_epochs=args.epochs,
        gradient_clip_val=2.0,  # trainer=slow preset
        precision=precision,
        # Never larger than the epoch budget, or no val point ever fires
        # and best_val stays inf.
        check_val_every_n_epoch=min(4, args.epochs),
        enable_progress_bar=False,
        enable_model_summary=False,
        seed=0,
    )
    result = trainer.fit(ModelSpec(objective=args.loss), dm)
    val_curve = [
        h["loss/total/val"] for h in result.history
        if h.get("loss/total/val") is not None
    ]
    # A halted/diverged run (the exact failure this check exists to catch)
    # must fail parity outright, not sneak through on an early good val.
    diverged = any(
        not math.isfinite(h.get("loss/total/train", 0.0))
        for h in result.history
    ) or not math.isfinite(result.best_val_loss)
    return {
        "best_val": result.best_val_loss,
        "val_curve": val_curve,
        "diverged": diverged,
    }


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--n-samples", type=int, default=50_000)
    parser.add_argument("--epochs", type=int, default=32)
    parser.add_argument(
        "--loss", default="mse",
        help="mse is the meaningful parity objective (strictly positive "
        "losses); nll/combined values can cross zero, where a relative "
        "gap overstates divergence — gaps are computed against "
        "max(|f32|, 1e-6) to stay finite either way",
    )
    parser.add_argument(
        "--data-dir", default=None,
        help="defaults to data/parity_<n_samples> (a dataset dir is "
        "pinned to one generation config; 50k reuses the midscale "
        "runner's cache)",
    )
    parser.add_argument("--tolerance", type=float, default=0.02)
    args = parser.parse_args()
    if args.data_dir is None:
        args.data_dir = (
            "data/midscale_synthetic" if args.n_samples == 50_000
            else f"data/parity_{args.n_samples}"
        )

    f32 = run_one("32-true", args)
    bf16 = run_one("bf16-mixed", args)

    def rel(b: float, f: float) -> float:
        return abs(b - f) / max(abs(f), 1e-6)

    rel_gap = rel(bf16["best_val"], f32["best_val"])
    curve_gaps = [
        rel(b, f) for b, f in zip(bf16["val_curve"], f32["val_curve"])
    ]
    # Unequal curve lengths mean one run halted early — that is itself a
    # parity failure, and zip() must not silently hide it.
    lengths_match = len(bf16["val_curve"]) == len(f32["val_curve"])
    clean = not (f32["diverged"] or bf16["diverged"]) and lengths_match
    import math

    import jax

    def js(v):
        """Non-finite floats (diverged runs) become null, keeping the one
        output line strict JSON."""
        return v if isinstance(v, (int, float)) and math.isfinite(v) else None

    print(json.dumps({
        "parity": bool(clean and rel_gap < args.tolerance),
        "diverged": {"f32": f32["diverged"], "bf16": bf16["diverged"]},
        "rel_final_gap": js(round(rel_gap, 5)),
        "f32_best_val": js(f32["best_val"]),
        "bf16_best_val": js(bf16["best_val"]),
        "max_curve_rel_gap": (
            js(round(max(curve_gaps), 5)) if curve_gaps else None
        ),
        "val_points": [len(f32["val_curve"]), len(bf16["val_curve"])],
        "epochs": args.epochs,
        "n_samples": args.n_samples,
        "loss": args.loss,
        "device": jax.devices()[0].platform,
    }, allow_nan=False))


if __name__ == "__main__":
    main()
