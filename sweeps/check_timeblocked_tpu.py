#!/usr/bin/env python
"""Mosaic compile + parity check for the time-blocked long-lookback kernel.

The time-blocked path (2-D grid over row tiles x time chunks, h/c carry
in scratch across sequential grid steps; ops/lstm_kernel.py) is
interpreter-validated on CPU by the test suite — this script is its
real-hardware gate, mirroring sweeps/check_stack_tpu.py for the stack
kernel: jit value_and_grad through a long-lookback shape that exceeds
the resident kernels' VMEM budget (so dispatch lands on the time-blocked
path), compare against the scan formulation, and print per-call timings.
Run under the grid runner's PAUSE protocol.

Usage: python sweeps/check_timeblocked_tpu.py
"""

from __future__ import annotations

import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp
import numpy as np

from masters_thesis_tpu.ops.lstm_kernel import (
    lstm_recurrence,
    lstm_recurrence_xla,
    single_layer_fits,
)

# NO persistent compile cache here (unlike bench/profile): this gate's
# reported compile_s must measure a real Mosaic compile, not cache
# deserialization, and exercising that compile IS the gate.


def main() -> None:
    # T=1024 at 104 rows/H=64 f32: the full (T, B, 4H) + state planes are
    # ~120 MB more than VMEM — resident/window paths must refuse and the
    # auto dispatch must stream through the time-blocked kernel.
    n_t, b, hidden = 1024, 104, 64
    itemsize = jnp.dtype(jnp.float32).itemsize
    assert not single_layer_fits(n_t, b, hidden, itemsize), (
        "shape unexpectedly fits the resident kernel; gate is vacuous"
    )
    rng = np.random.default_rng(0)
    x_proj = jnp.asarray(
        rng.normal(size=(n_t, b, 4 * hidden)) * 0.1, jnp.float32
    )
    w_hh_t = jnp.asarray(
        rng.normal(size=(hidden, 4 * hidden)) * 0.2, jnp.float32
    )
    w_out = jnp.asarray(rng.normal(size=(n_t, b, hidden)), jnp.float32)

    print(f"backend: {jax.default_backend()}", flush=True)
    if jax.default_backend() != "tpu":
        # The CPU interpreter already pins correctness in the unit tests;
        # at this gate's T=1024 shape it would run for hours. Real Mosaic
        # behavior is the one thing only the chip can show.
        sys.exit("TPU backend required for the Mosaic gate; aborting")

    def run(tag, fn):
        loss = jax.jit(
            jax.value_and_grad(
                lambda xp, w: jnp.sum(fn(xp, w) * w_out), argnums=(0, 1)
            )
        )
        t0 = time.perf_counter()
        (val, grads) = loss(x_proj, w_hh_t)
        jax.block_until_ready(grads)
        compile_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        reps = 20
        for _ in range(reps):
            (val, grads) = loss(x_proj, w_hh_t)
        jax.block_until_ready(grads)
        per_call = (time.perf_counter() - t0) / reps * 1e3
        print(
            f"{tag}: loss={float(val):.4f} compile={compile_s:.1f}s "
            f"per_call={per_call:.3f}ms",
            flush=True,
        )
        return float(val), grads

    v_tb, g_tb = run(
        "time-blocked", lambda xp, w: lstm_recurrence(xp, w, impl="pallas")
    )
    v_ref, g_ref = run("xla-scan", lstm_recurrence_xla)
    rel = abs(v_tb - v_ref) / max(abs(v_ref), 1e-9)
    g_rel = float(
        jnp.linalg.norm(g_tb[1] - g_ref[1]) / jnp.linalg.norm(g_ref[1])
    )
    print(f"loss rel err: {rel:.2e}  w_hh grad rel err: {g_rel:.2e}")
    assert rel < 1e-4 and g_rel < 1e-3, "time-blocked parity FAILED on TPU"
    print("time-blocked kernel TPU check ok")


if __name__ == "__main__":
    main()
