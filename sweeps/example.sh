#!/bin/bash
# 6-job example sweep (reference: sweeps/example.sh — same grid).
python train.py -m datamodule=real model=large \
    model.learning_rate=1e-3,1e-4,1e-5 \
    trainer.max_epochs=100,200
