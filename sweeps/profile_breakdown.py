#!/usr/bin/env python
"""Attribute the canonical train step's wall time on real TPU.

Two instruments (VERDICT r3 #7):

1. A ``jax.profiler`` trace of one canonical epoch (written under
   logs/.../profile — the raw artifact for trace viewers).
2. A micro-timing attribution by differences at the canonical shape
   (100 rows x T=60 x H=64, model=small, fused pair): recurrence forward
   alone, recurrence forward+backward, whole fused train step (adds input
   projections, loss, optimizer, metric sums). Differences bound where the
   0.22 ms/step goes without trace-file parsing.

Run under the grid runner's PAUSE protocol. Prints one JSON line last.
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def _probe_backend_or_exit() -> None:
    """A wedged relay blocks jax backend init forever (bench.py's known
    failure mode) — probe in a subprocess first and exit loudly (with the
    child's stderr for crash diagnosis) instead of silently burning the
    PAUSE-protocol slot."""
    from masters_thesis_tpu.utils import probe_tpu_backend

    # Retry across a 10-minute budget: this script runs LAST in the TPU
    # measurement queue, right after long kernel sweeps — the moment a
    # transient wedge is most likely to be present and also most likely
    # to clear shortly.
    probe = probe_tpu_backend(timeout_s=90.0, budget_s=600.0)
    if not probe.ok:
        sys.exit(
            f"backend probe failed: {probe.detail}; not starting the "
            "profile run"
        )


_probe_backend_or_exit()

from masters_thesis_tpu.utils import enable_persistent_compilation_cache

enable_persistent_compilation_cache()

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, *args, reps=200):
    fn(*args)  # compile
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps * 1e3  # ms


def main() -> None:
    from masters_thesis_tpu.data.pipeline import (
        FinancialWindowDataModule,
        bootstrap_synthetic,
    )
    from masters_thesis_tpu.models.objectives import ModelSpec
    from masters_thesis_tpu.ops.lstm_kernel import lstm_pair_recurrence
    from masters_thesis_tpu.train import Trainer

    print(f"backend: {jax.default_backend()}", flush=True)
    smoke = "--smoke" in sys.argv  # CPU plumbing check: tiny shapes
    n_t, b, hidden = (8, 12, 8) if smoke else (60, 100, 64)
    rng = np.random.default_rng(0)
    x1 = jnp.asarray(rng.normal(size=(n_t, b, 4 * hidden)), jnp.float32)
    w1, wi2, w2 = (
        jnp.asarray(rng.normal(size=(hidden, 4 * hidden)) * 0.2, jnp.float32)
        for _ in range(3)
    )
    b2 = jnp.asarray(rng.normal(size=(4 * hidden,)) * 0.1, jnp.float32)
    w_out = jnp.asarray(rng.normal(size=(n_t, b, hidden)), jnp.float32)

    reps = 5 if smoke else 200
    fwd = jax.jit(
        lambda *a: lstm_pair_recurrence(*a, impl="auto")
    )
    fwd_ms = timeit(fwd, x1, w1, wi2, b2, w2, None, reps=reps)

    def loss(x1, w1, wi2, b2, w2):
        return jnp.sum(
            lstm_pair_recurrence(x1, w1, wi2, b2, w2, None, impl="auto")
            * w_out
        )

    fwdbwd = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2, 3, 4)))
    fwdbwd_ms = timeit(fwdbwd, x1, w1, wi2, b2, w2, reps=reps)

    # Whole-step cost from a short canonical fit (per-step wall incl.
    # projections, loss math, optimizer, on-device shuffle, metric sums).
    data_dir = REPO / "data" / "bench_synthetic"
    n_stocks, n_samples = (6, 20_000) if smoke else (100, 100_000)
    if smoke:
        data_dir = REPO / "data" / "smoke_profile"
    bootstrap_synthetic(data_dir, n_stocks=n_stocks, n_samples=n_samples, seed=0)
    dm = FinancialWindowDataModule(
        data_dir, lookback_window=60, target_window=30, stride=90,
        batch_size=1,
    )
    dm.prepare_data(verbose=False)
    dm.setup()
    trainer = Trainer(
        max_epochs=2 if smoke else 5, gradient_clip_val=5.0,
        check_val_every_n_epoch=10_000,
        enable_progress_bar=False, enable_model_summary=False, seed=0,
    )
    result = trainer.fit(ModelSpec(objective="mse"), dm)
    step_ms = 1e3 / result.steps_per_sec

    # Profiler trace artifact of one canonical epoch.
    trace_dir = REPO / "logs" / "profile_r4"
    trainer2 = Trainer(
        max_epochs=2 if smoke else 3, gradient_clip_val=5.0,
        check_val_every_n_epoch=10_000, profile=True,
        enable_progress_bar=False, enable_model_summary=False, seed=0,
    )
    from masters_thesis_tpu.train.logging import TensorBoardLogger

    logger = TensorBoardLogger(str(trace_dir.parent), "profile_r4", "trace")
    trainer2.logger = logger
    trainer2.fit(ModelSpec(objective="mse"), dm)
    trace_glob = list(
        (logger.log_dir / "profile").rglob("*.xplane.pb")
    )

    print(json.dumps({
        "recurrence_fwd_ms": round(fwd_ms, 4),
        "recurrence_fwd_bwd_ms": round(fwdbwd_ms, 4),
        "recurrence_bwd_ms": round(fwdbwd_ms - fwd_ms, 4),
        "full_step_ms": round(step_ms, 4),
        "non_recurrence_ms": round(step_ms - fwdbwd_ms, 4),
        "steps_per_sec": round(result.steps_per_sec, 1),
        "trace_files": [str(p) for p in trace_glob[:3]],
    }))


if __name__ == "__main__":
    main()
