#!/bin/bash
# Warmup protocol: pretrain on the synthetic DGP with the combined (L_MIX)
# objective — the best synthetic-trained configuration in the thesis — then
# fine-tune on the real Fama-French data from those weights with a fresh
# optimizer (reference: tex/diplomski_rad.tex:1134-1147; the reference has
# no code for this and does it "by hand via checkpoints", SURVEY.md §2.3).
set -e

# Stage 1: synthetic pretraining (L_MIX objective).
python train.py datamodule=synthetic model=large loss=combined trainer=slow

PRETRAINED="logs/FinancialLstm/synthetic/combined_large_lr0.0001_slow/checkpoints/best"

# Stage 2: real-data fine-tune sweep from the pretrained weights
# (fresh optimizer: checkpoint_mode=params).
python train.py -m datamodule=real model=large \
    loss=mse,nll,combined \
    model.learning_rate=1e-4,1e-5 \
    trainer=slow \
    checkpoint="$PRETRAINED" \
    checkpoint_mode=params \
    logger.name=FinancialLstm/warmup
