#!/usr/bin/env python
"""Reduced-scale warmup-vs-scratch insurance run on the CPU backend.

The thesis' headline protocol (synthetic pretrain -> fine-tune beats
scratch training; reference: tex/diplomski_rad.tex:1134-1147, 1170-1174)
is queued for the canonical 1M-sample capture on the TPU
(sweeps/run_grid_canonical.py) — but the relay can stay wedged for an
entire round (it did in r4). This runner reproduces the SAME protocol at
1/20th scale (50k-sample bootstrap, model=small) on the CPU backend so
the round has a real measured ordering even if the chip never comes back.
Rows land in results/warmup_cpu_midscale.jsonl, clearly labeled with
their scale — they never touch the canonical grid results.

Chip-politeness contract (docs/OPERATIONS.md): every training child runs
``nice -n 19`` with the CPU platform pinned, and the runner exits BEFORE
launching the next cell the moment results/R5_STATE leaves "wait" (the
TPU orchestrator owns the host core from that point).
"""

from __future__ import annotations

import datetime
import json
import os
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
OUT = REPO / "results" / "warmup_cpu_midscale.jsonl"
STATE = REPO / "results" / "R5_STATE"

N_SAMPLES = 50_000
LOSSES = ("mse", "nll", "combined")
# NB: keys here must not collide with eval_cell.py's row schema —
# "model" there is the model's ΔL dict, hence "model_size".
SCALE_META = {
    "scale": "cpu_midscale_1_20th",
    "n_samples": N_SAMPLES,
    "model_size": "small",
    "trainer_preset": "slow",
    "device": "cpu",
}

SYN_DIR = "data/midscale_synthetic"
OUT_DIR = "data/midscale_outliers"
PRETRAIN_VERSION = "combined_small_lr0.0001_slow"
PRETRAIN_CKPT = (
    REPO / "logs/FinancialLstm/midscale_syn" / PRETRAIN_VERSION
    / "checkpoints/best"
)


def log(msg: str) -> None:
    print(f"{datetime.datetime.now():%H:%M:%S} {msg}", flush=True)


def cpu_env() -> dict:
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    return env


# Phases in which the orchestrator's foreground measurement owns the core
# even between its subprocesses (gates/bench/A-B/profile children are
# timeout-capped: host contention can push a HEALTHY child past its cap,
# the documented wedge trigger).
MEASUREMENT_PHASES = {"gates", "bench", "ab_sweep", "profile"}
# Cmdline fragments identifying a relay-backed process (grid cell,
# bench, kernel sweep, canonical eval). Mirrors the orchestrator's
# tpu_train_running plus the non-train measurement drivers.
TPU_PROC_PATTERNS = (
    "train.py", "bench.py", "bench_fused_pair", "profile_breakdown",
    "check_stack_tpu", "check_timeblocked_tpu", "eval_cell",
)


def _tpu_process_alive() -> bool:
    """True while any relay-backed python process is running.

    Scans /proc directly and keys on comm==python*: a plain cmdline grep
    would self-match supervisor processes whose argv embeds script names
    (observed: the session driver's prompt text contains "train.py").
    This runner's own children are excluded by the ``midscale`` marker in
    their cmdline — the same marker the orchestrator's exclusivity check
    filters on."""
    proc = Path("/proc")
    for p in proc.iterdir():
        if not p.name.isdigit():
            continue
        try:
            comm = (p / "comm").read_text().strip()
            if not comm.startswith("python"):
                continue
            cmd = (p / "cmdline").read_bytes().decode(
                errors="replace").replace("\0", " ")
        except OSError:
            continue  # raced a process exit
        if "midscale" in cmd:
            continue
        if any(pat in cmd for pat in TPU_PROC_PATTERNS):
            return True
    return False


def tpu_queue_active() -> bool:
    """Should the insurance runner yield the host core right now?

    - measurement phases: always yes (see MEASUREMENT_PHASES).
    - ``wait`` / no state file: no — the core is ours.
    - ``grid`` / ``done`` / ``interrupted``: the state file alone cannot
      distinguish "grid cell training on the chip" from "grid idling
      through a multi-hour relay wedge" (observed: the r5 wedge pinned
      the state at ``grid`` with the core idle for hours, starving this
      runner for the rest of the round) — the live process table decides.
      This also covers the surviving-children case the ``interrupted``
      state exists to flag."""
    try:
        phase = STATE.read_text().strip()
    except OSError:
        return False  # no orchestrator running: the core is ours
    if phase in MEASUREMENT_PHASES:
        return True
    if phase == "wait":
        return False
    return _tpu_process_alive()


def done_cells() -> set:
    if not OUT.exists():
        return set()
    return {
        json.loads(line)["cell"]
        for line in OUT.read_text().splitlines()
        if line.strip()
    }


def run_child(
    args: list[str], timeout_s: float, check: bool = False
) -> subprocess.CompletedProcess:
    return subprocess.run(
        ["nice", "-n", "19", sys.executable, *args],
        cwd=REPO,
        env=cpu_env(),
        timeout=timeout_s,
        check=check,
        capture_output=True,
        text=True,
    )


def train_cell(cell: str, overrides: list[str], timeout_s: float) -> bool:
    log(f"train {cell}")
    t0 = time.time()
    try:
        out = run_child(
            ["train.py", *overrides, "trainer.resume=true",
             "trainer.enable_progress_bar=false",
             "trainer.enable_model_summary=false"],
            timeout_s,
        )
    except subprocess.TimeoutExpired:
        log(f"{cell}: timed out after {timeout_s:.0f}s (resume continues it)")
        return False
    if out.returncode != 0:
        log(f"{cell}: FAILED rc={out.returncode}\n{out.stdout[-800:]}\n"
            f"{out.stderr[-800:]}")
        return False
    log(f"{cell}: trained in {time.time() - t0:.0f}s")
    return True


def record_cell(cell: str, ckpt: Path, eval_overrides: list[str],
                wall_s: float) -> None:
    try:
        ev = run_child(
            ["sweeps/eval_cell.py", f"checkpoint={ckpt}", *eval_overrides],
            1800,
            check=True,
        )
        row = json.loads(ev.stdout.strip().splitlines()[-1])
    except Exception as exc:  # noqa: BLE001 - log and move on; cell rerunnable
        # TimeoutExpired/CalledProcessError carry the child's stderr (None
        # when nothing was captured); other exceptions carry none at all.
        stderr = getattr(exc, "stderr", None) or ""
        log(f"{cell}: eval failed ({type(exc).__name__}) {stderr[-500:]}")
        return
    row.update({"cell": cell, "train_wall_s": round(wall_s, 1), **SCALE_META})
    OUT.parent.mkdir(exist_ok=True)
    with open(OUT, "a") as f:
        f.write(json.dumps(row) + "\n")
    log(f"{cell}: recorded")


def run_and_record(cell: str, train_ov: list[str], ckpt: Path,
                   eval_ov: list[str], timeout_s: float = 3600) -> bool:
    if cell in done_cells():
        log(f"skip {cell}: already recorded")
        return True
    if tpu_queue_active():
        log("TPU queue active (R5_STATE != wait); yielding the core")
        raise SystemExit(0)
    t0 = time.time()
    if not train_cell(cell, train_ov, timeout_s):
        return False
    if not ckpt.exists():
        log(f"{cell}: no checkpoint at {ckpt}")
        return False
    record_cell(cell, ckpt, eval_ov, time.time() - t0)
    return True


def main() -> None:
    base = [
        "model=small", "trainer=slow",
        f"datamodule.n_samples={N_SAMPLES}",
    ]
    syn_ov = [f"datamodule.data_dir={SYN_DIR}",
              "logger.name=FinancialLstm/midscale_syn"]
    out_ov = ["datamodule.dgp_variant=outliers",
              f"datamodule.data_dir={OUT_DIR}",
              "logger.name=FinancialLstm/midscale_out"]

    # 1. Pretrain on the base synthetic DGP (the warmup source weights).
    pretrain_ov = ["loss=combined", *base, *syn_ov]
    ok = run_and_record(
        "mid_pretrain_combined_small",
        pretrain_ov,
        PRETRAIN_CKPT,
        [f"datamodule.data_dir={SYN_DIR}",
         f"datamodule.n_samples={N_SAMPLES}"],
    )
    # Recorded-but-missing checkpoint (environment resets wipe logs/ while
    # the results JSONL is committed): retrain to completion WITHOUT
    # re-recording — the recorded metrics stand, only the weights the
    # warmup block warm-starts from are restored (same rationale as
    # run_grid_canonical.ensure_checkpoint).
    if ok and not PRETRAIN_CKPT.exists():
        if tpu_queue_active():
            log("TPU queue active before pretrain ensure; yielding the core")
            raise SystemExit(0)
        log("pretrain recorded but checkpoint missing; retraining (not "
            "re-recorded)")
        ok = train_cell("mid_pretrain_ensure", pretrain_ov, 3600)

    # 2. From-scratch baselines on the fine-tune (outliers) dataset.
    for loss in LOSSES:
        run_and_record(
            f"mid_outliers_{loss}_small_scratch",
            [f"loss={loss}", *base, *out_ov],
            REPO / "logs/FinancialLstm/midscale_out"
            / f"{loss}_small_lr0.0001_slow/checkpoints/best",
            ["datamodule.dgp_variant=outliers",
             f"datamodule.data_dir={OUT_DIR}",
             f"datamodule.n_samples={N_SAMPLES}"],
        )

    # 3. Warm-started cells (pretrained weights, fresh optimizer).
    if ok and PRETRAIN_CKPT.exists():
        warm_name = "logger.name=FinancialLstm/midscale_warm"
        for loss in LOSSES:
            run_and_record(
                f"mid_outliers_{loss}_small_warmup",
                [f"loss={loss}", *base, *out_ov[:-1], warm_name,
                 f"checkpoint={PRETRAIN_CKPT}", "checkpoint_mode=params"],
                REPO / "logs/FinancialLstm/midscale_warm"
                / f"{loss}_small_lr0.0001_slow/checkpoints/best",
                ["datamodule.dgp_variant=outliers",
                 f"datamodule.data_dir={OUT_DIR}",
                 f"datamodule.n_samples={N_SAMPLES}"],
            )
    else:
        log("warmup cells skipped: pretrain checkpoint unavailable")
    log("midscale runner finished")


if __name__ == "__main__":
    main()
