#!/usr/bin/env python
"""Mosaic compile + parity check for the L-layer wavefront on real TPU.

The stack kernel is interpreter-validated on CPU by the test suite; this
script is the real-hardware gate: jit value_and_grad through the fused
4-layer wavefront at the canonical medium shape in bf16 (the mode whose
VMEM budget admits it), compare against the chained-scan formulation, and
print per-call timings. Run it under the grid runner's PAUSE protocol.

Usage: python sweeps/check_stack_tpu.py
"""

from __future__ import annotations

import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp
import numpy as np

from masters_thesis_tpu.ops.lstm_kernel import (
    lstm_stack_recurrence,
    lstm_stack_xla,
    stack_fits,
)

# NO persistent compile cache here (unlike bench/profile): this gate's
# reported compile_s must measure a real Mosaic compile, not cache
# deserialization, and exercising that compile IS the gate.


def main() -> None:
    n_t, b, hidden, ell = 60, 100, 64, 4
    dtype = jnp.bfloat16
    assert stack_fits(n_t, b, hidden, ell, True, jnp.dtype(dtype).itemsize)
    rng = np.random.default_rng(0)
    x1 = jnp.asarray(rng.normal(size=(n_t, b, 4 * hidden)), dtype)
    mk = lambda: jnp.asarray(  # noqa: E731
        rng.normal(size=(hidden, 4 * hidden)) * 0.2, dtype
    )
    weights = (
        tuple(mk() for _ in range(ell)),
        tuple(mk() for _ in range(ell - 1)),
        tuple(
            jnp.asarray(rng.normal(size=(4 * hidden,)) * 0.1, dtype)
            for _ in range(ell - 1)
        ),
    )
    masks = tuple(
        jnp.asarray((rng.random(size=(n_t, b, hidden)) > 0.3) / 0.7, dtype)
        for _ in range(ell - 1)
    )
    w_out = jnp.asarray(rng.normal(size=(n_t, b, hidden)), jnp.float32)

    def loss(fn):
        return lambda xp, w: jnp.sum(
            fn(xp, w, masks).astype(jnp.float32) * w_out
        )

    print(f"backend: {jax.default_backend()}", flush=True)
    for name, fn in (
        ("pallas", lambda xp, w, m: lstm_stack_recurrence(
            xp, w, m, impl="pallas")),
        ("xla", lstm_stack_xla),
    ):
        vg = jax.jit(jax.value_and_grad(loss(fn), argnums=(0, 1)))
        t0 = time.perf_counter()
        val, grads = vg(x1, weights)
        jax.block_until_ready((val, grads))
        compile_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        reps = 50
        for _ in range(reps):
            val, grads = vg(x1, weights)
        jax.block_until_ready((val, grads))
        per_call_ms = (time.perf_counter() - t0) / reps * 1e3
        print(
            f"{name}: loss={float(val):.4f} compile={compile_s:.1f}s "
            f"per_call={per_call_ms:.3f}ms",
            flush=True,
        )
        if name == "pallas":
            ref_val = float(
                jax.jit(loss(lstm_stack_xla))(x1, weights)
            )
            rel = abs(float(val) - ref_val) / max(abs(ref_val), 1e-9)
            print(f"pallas-vs-xla loss rel err: {rel:.2e}", flush=True)
            assert rel < 0.05, "wavefront diverges from scan formulation"
    print("stack kernel TPU check ok", flush=True)


if __name__ == "__main__":
    main()
