#!/usr/bin/env python
"""A/B micro-bench: wavefront fusion modes vs the per-layer kernel path.

Measures canonical-workload train-step throughput (100-stock windows,
batch_size=1) for model=small (2 layers), medium (4), large (8) across:

- ``perlayer``:        MT_LSTM_FUSED_PAIR=0 (f32) — no fusion
- ``pair``:            fused layer pairs, f32 (the round-3 default)
- ``pair_bf16``:       fused pairs under precision=bf16-mixed (control:
                       isolates the dtype effect from the fusion effect)
- ``wavefront_bf16``:  deep wavefront under bf16-mixed — at the canonical
                       shape the VMEM byte model admits 4-layer groups, so
                       medium runs as ONE program and large as two
                       (ops/lstm_kernel.py, stack section)

Each point runs in a subprocess so env switches cannot leak across jit
traces.

Usage: python sweeps/bench_fused_pair.py                    # orchestrate
       python sweeps/bench_fused_pair.py --child pair small # one point
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

MODEL_LAYERS = {"small": 2, "medium": 4, "large": 8}

MODES = {
    "perlayer": {"MT_LSTM_FUSED_PAIR": "0", "precision": "32-true"},
    "pair": {
        "MT_LSTM_FUSED_PAIR": "1",
        "MT_LSTM_WAVEFRONT": "0",
        "precision": "32-true",
    },
    "pair_bf16": {
        "MT_LSTM_FUSED_PAIR": "1",
        "MT_LSTM_WAVEFRONT": "0",
        "precision": "bf16-mixed",
    },
    "wavefront_bf16": {
        "MT_LSTM_FUSED_PAIR": "1",
        "MT_LSTM_WAVEFRONT": "1",
        "precision": "bf16-mixed",
    },
}


def child(mode: str, model: str) -> None:
    cfg = MODES[mode]
    for key in ("MT_LSTM_FUSED_PAIR", "MT_LSTM_WAVEFRONT"):
        if key in cfg:
            os.environ[key] = cfg[key]
    from masters_thesis_tpu.utils import enable_persistent_compilation_cache

    enable_persistent_compilation_cache()
    from masters_thesis_tpu.data.pipeline import (
        FinancialWindowDataModule,
        bootstrap_synthetic,
    )
    from masters_thesis_tpu.models.objectives import ModelSpec
    from masters_thesis_tpu.train import Trainer

    data_dir = REPO / "data" / "bench_synthetic"
    bootstrap_synthetic(data_dir, n_stocks=100, n_samples=100_000, seed=0)
    dm = FinancialWindowDataModule(
        data_dir, lookback_window=60, target_window=30, stride=90,
        batch_size=1,
    )
    dm.prepare_data(verbose=False)
    dm.setup()
    spec = ModelSpec(
        objective="mse",
        num_layers=MODEL_LAYERS[model],
        dropout=0.2 if model == "small" else 0.3,
    )
    trainer = Trainer(
        max_epochs=7,  # epoch 0 absorbs compile
        gradient_clip_val=5.0,
        check_val_every_n_epoch=10_000,
        precision=cfg["precision"],
        enable_progress_bar=False,
        enable_model_summary=False,
        seed=0,
    )
    result = trainer.fit(spec, dm)
    print(json.dumps({
        "mode": mode, "model": model,
        "steps_per_sec": round(result.steps_per_sec, 2),
    }))


def main() -> None:
    # A wedged relay would otherwise cost 900s PER CHILD x 12 points; probe
    # once up front (retrying through a transient wedge) and bail with an
    # explicit line so the orchestrator's next stage gets its own chance.
    from masters_thesis_tpu.utils import probe_tpu_backend

    probe = probe_tpu_backend(timeout_s=90.0, budget_s=1200.0)
    if not probe.ok:
        print(f"backend probe failed: {probe.detail}; skipping the A/B sweep",
              flush=True)
        return
    models = sys.argv[1:] or list(MODEL_LAYERS)
    rows = []
    for model in models:
        for mode in MODES:
            t0 = time.time()
            # Sized for a COLD persistent cache (environment resets wipe
            # ~/.cache): a healthy cold epoch-program compile through the
            # relay has run past 1200s, and SIGKILLing a healthy TPU child
            # is the documented wedge trigger (docs/OPERATIONS.md).
            cap_s = 1800
            try:
                out = subprocess.run(
                    [sys.executable, __file__, "--child", mode, model],
                    cwd=REPO, timeout=cap_s, capture_output=True, text=True,
                )
            except subprocess.TimeoutExpired:
                # A starved host or wedged relay must cost this POINT, not
                # the whole sweep (observed: a 1-core host under concurrent
                # load pushed one child past its cap and killed the run).
                print(f"[{model} {mode}] TIMEOUT after {cap_s}s; skipping",
                      flush=True)
                continue
            if out.returncode != 0:
                print(f"[{model} {mode}] FAILED:\n{out.stderr[-2000:]}")
                continue
            row = json.loads(out.stdout.strip().splitlines()[-1])
            row["wall_s"] = round(time.time() - t0, 1)
            rows.append(row)
            print(json.dumps(row), flush=True)
    by = {(r["model"], r["mode"]): r["steps_per_sec"] for r in rows}
    for model in models:
        base = by.get((model, "perlayer"))
        if not base:
            continue
        parts = [f"{model}: perlayer {base}"]
        for mode in ("pair", "pair_bf16", "wavefront_bf16"):
            v = by.get((model, mode))
            if v:
                parts.append(f"{mode} {v} ({v / base:.2f}x)")
        print(" | ".join(parts))


if __name__ == "__main__":
    if "--child" in sys.argv:
        i = sys.argv.index("--child")
        child(sys.argv[i + 1], sys.argv[i + 2])
    else:
        main()
