#!/usr/bin/env python
"""A/B micro-bench: fused layer-pair Pallas kernel vs the per-layer path.

Measures canonical-workload train-step throughput (100-stock windows,
batch_size=1, model=small -> 2 layers, and model=medium -> 4 layers) with
MT_LSTM_FUSED_PAIR=0 and =1. Each point runs in a subprocess so the env
switch cannot leak across jit traces.

Usage: python sweeps/bench_fused_pair.py            # orchestrate A/B
       python sweeps/bench_fused_pair.py --child 1 small   # one point
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

MODEL_LAYERS = {"small": 2, "medium": 4}


def child(fused: str, model: str) -> None:
    os.environ["MT_LSTM_FUSED_PAIR"] = fused
    sys.path.insert(0, str(REPO))
    from masters_thesis_tpu.data.pipeline import (
        FinancialWindowDataModule,
        bootstrap_synthetic,
    )
    from masters_thesis_tpu.models.objectives import ModelSpec
    from masters_thesis_tpu.train import Trainer

    data_dir = REPO / "data" / "bench_synthetic"
    bootstrap_synthetic(data_dir, n_stocks=100, n_samples=100_000, seed=0)
    dm = FinancialWindowDataModule(
        data_dir, lookback_window=60, target_window=30, stride=90,
        batch_size=1,
    )
    dm.prepare_data(verbose=False)
    dm.setup()
    spec = ModelSpec(
        objective="mse",
        num_layers=MODEL_LAYERS[model],
        dropout=0.2 if model == "small" else 0.3,
    )
    trainer = Trainer(
        max_epochs=7,  # epoch 0 absorbs compile
        gradient_clip_val=5.0,
        check_val_every_n_epoch=10_000,
        enable_progress_bar=False,
        enable_model_summary=False,
        seed=0,
    )
    result = trainer.fit(spec, dm)
    print(json.dumps({
        "fused": fused, "model": model,
        "steps_per_sec": round(result.steps_per_sec, 2),
    }))


def main() -> None:
    rows = []
    for model in MODEL_LAYERS:
        for fused in ("0", "1"):
            t0 = time.time()
            out = subprocess.run(
                [sys.executable, __file__, "--child", fused, model],
                cwd=REPO, timeout=900, capture_output=True, text=True,
            )
            if out.returncode != 0:
                print(f"[{model} fused={fused}] FAILED:\n{out.stderr[-2000:]}")
                continue
            row = json.loads(out.stdout.strip().splitlines()[-1])
            row["wall_s"] = round(time.time() - t0, 1)
            rows.append(row)
            print(json.dumps(row), flush=True)
    by = {(r["model"], r["fused"]): r["steps_per_sec"] for r in rows}
    for model in MODEL_LAYERS:
        a, b = by.get((model, "0")), by.get((model, "1"))
        if a and b:
            print(f"{model}: unfused {a} -> fused {b} steps/s "
                  f"({b / a:.2f}x)")


if __name__ == "__main__":
    if "--child" in sys.argv:
        i = sys.argv.index("--child")
        child(sys.argv[i + 1], sys.argv[i + 2])
    else:
        main()
