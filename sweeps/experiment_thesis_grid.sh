#!/bin/bash
# The thesis' own experiment grid (reference: tex/diplomski_rad.tex:1106-1122
# — hidden in {16,32}, layers in {1,2}, lr in {1e-3..1e-6}, all three
# objectives), as a runnable sweep. The reference repo never shipped this
# grid as code (its sweeps use the small/medium/large config groups instead;
# SURVEY.md §2.3 "code wins" note) — provided here because the thesis table
# is the published quality baseline (BASELINE.md).
#
# 2 x 2 x 4 x 3 = 48 jobs per datamodule. Pass datamodule=real for the
# Fama-French variant once the CSVs are present (bootstrap_real).
python train.py -m datamodule=synthetic \
    model.hidden_size=16,32 \
    model.num_layers=1,2 \
    model.learning_rate=1e-3,1e-4,1e-5,1e-6 \
    loss=mse,nll,combined \
    trainer=slow \
    "$@"
