#!/usr/bin/env python
"""Collect one trained grid cell's thesis-table quality numbers as JSON.

A headless, figure-free subset of test.py: restore the checkpoint, rebuild
the datamodule it was trained on, compute the ΔL-above-OLS metrics
(reference: tex/diplomski_rad.tex:1077-1084, 1155-1176) and print ONE JSON
line. Used by sweeps/run_grid_canonical.py to build the RESULTS.md table.

Usage: python sweeps/eval_cell.py checkpoint=<dir> [overrides...]
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from train import CONFIG_DIR, bootstrap, build_datamodule  # noqa: E402
from masters_thesis_tpu.config import compose  # noqa: E402


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("overrides", nargs="*", help="key=value overrides")
    args = parser.parse_args()
    cfg = compose(str(CONFIG_DIR), overrides=args.overrides)
    assert cfg.checkpoint, "checkpoint=<dir> override required"

    from masters_thesis_tpu.evaluation import delta_losses
    from masters_thesis_tpu.train.checkpoint import (
        apply_datamodule_sidecar,
        restore_checkpoint,
    )
    from masters_thesis_tpu.utils import enable_persistent_compilation_cache

    enable_persistent_compilation_cache()
    params, _, spec, meta = restore_checkpoint(Path(cfg.checkpoint))
    # Evaluate on the SAME windowing the checkpoint was trained with.
    apply_datamodule_sidecar(cfg, meta)
    if not bootstrap(cfg):
        raise SystemExit("bootstrap failed")
    dm = build_datamodule(cfg)
    dm.prepare_data(verbose=False)
    deltas = delta_losses(spec, params, dm)
    print(
        json.dumps(
            {
                "checkpoint": str(cfg.checkpoint),
                "objective": spec.objective,
                "num_layers": spec.num_layers,
                "epoch": meta.get("epoch"),
                "val_loss": meta.get("val_loss"),
                "zeta": deltas["zeta"],
                "model": deltas["model"],
                "ols": deltas["ols"],
                "baseline": deltas["baseline"],
            }
        )
    )


if __name__ == "__main__":
    main()
