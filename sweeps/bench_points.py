#!/usr/bin/env python
"""Measured perf points for RESULTS.md's headroom items.

Two measurements, each point in its own subprocess (env/trace isolation):

1. bf16 recurrence: canonical bs=1 workload at precision=bf16-mixed vs the
   32-true default (headroom item 2 — does halving MXU cycles help a
   latency-bound chain?).
2. Tiled-fallback row block: bs=8/32 windows/s at MT_LSTM_ROW_TILE in
   {32, 64, 96} (headroom item 1 — larger (tile, H) recurrent matmuls vs
   VMEM pressure in the grid-pipelined per-layer kernels).

Usage: python sweeps/bench_points.py          # orchestrate all points
"""

from __future__ import annotations

import itertools
import json
import os
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def child(batch_size: int, precision: str, row_tile: str) -> None:
    if row_tile:
        os.environ["MT_LSTM_ROW_TILE"] = row_tile
    sys.path.insert(0, str(REPO))
    from masters_thesis_tpu.data.pipeline import (
        FinancialWindowDataModule,
        bootstrap_synthetic,
    )
    from masters_thesis_tpu.models.objectives import ModelSpec
    from masters_thesis_tpu.train import Trainer

    data_dir = REPO / "data" / "bench_synthetic"
    bootstrap_synthetic(data_dir, n_stocks=100, n_samples=100_000, seed=0)
    dm = FinancialWindowDataModule(
        data_dir, lookback_window=60, target_window=30, stride=90,
        batch_size=batch_size,
    )
    dm.prepare_data(verbose=False)
    dm.setup()
    trainer = Trainer(
        max_epochs=5,  # epoch 0 absorbs compile
        gradient_clip_val=5.0,
        precision=precision,
        check_val_every_n_epoch=10_000,
        enable_progress_bar=False,
        enable_model_summary=False,
        seed=0,
    )
    result = trainer.fit(ModelSpec(objective="mse"), dm)
    print(json.dumps({
        "batch_size": batch_size, "precision": precision,
        "row_tile": row_tile or "default",
        "steps_per_sec": round(result.steps_per_sec, 2),
        "windows_per_sec": round(result.steps_per_sec * batch_size, 2),
    }))


def run_point(batch_size: int, precision: str, row_tile: str) -> dict | None:
    t0 = time.time()
    out = subprocess.run(
        [sys.executable, __file__, "--child",
         str(batch_size), precision, row_tile],
        cwd=REPO, timeout=900, capture_output=True, text=True,
    )
    if out.returncode != 0:
        print(f"[bs={batch_size} {precision} tile={row_tile}] FAILED:\n"
              f"{out.stderr[-1500:]}")
        return None
    row = json.loads(out.stdout.strip().splitlines()[-1])
    row["wall_s"] = round(time.time() - t0, 1)
    print(json.dumps(row), flush=True)
    return row


def main() -> None:
    rows = []
    # bf16 vs f32 at the canonical parity point.
    for precision in ("32-true", "bf16-mixed"):
        rows.append(run_point(1, precision, ""))
    # Row-tile sweep in the tiled-fallback regime.
    for bs, tile in itertools.product((8, 32), ("32", "64", "96")):
        rows.append(run_point(bs, "32-true", tile))
    print(json.dumps([r for r in rows if r], indent=2))


if __name__ == "__main__":
    if "--child" in sys.argv:
        i = sys.argv.index("--child")
        child(int(sys.argv[i + 1]), sys.argv[i + 2], sys.argv[i + 3])
    else:
        main()
