#!/usr/bin/env python
"""Canonical-scale experiment grid runner (relay-wedge resilient).

Runs the reference's full 18-cell `experiment_synthetic.sh` grid —
model=small,medium,large x loss=mse,nll,combined x trainer=slow,slowest —
at the canonical 1M-sample bootstrap (reference:
sweeps/experiment_synthetic.sh, train.py:32), plus the thesis' warmup
protocol (synthetic -> fine-tune; real Fama-French CSVs cannot be
downloaded in this environment, so the fine-tune target is the DGP's
"outliers" variant — the same pretrain-then-adapt protocol on data this
environment can generate; reference: tex/diplomski_rad.tex:1134-1147).

Engineering constraints this runner absorbs:

- The TPU relay lease can wedge for long stretches: every cell waits for a
  subprocess device probe to pass before launching, and sleeps/retries
  while wedged.
- Cells run cheapest-first (slow column, then warmup, then slowest column
  small->large) so a wall-clock cutoff loses the most expensive cells
  last; `--deadline` stops LAUNCHING new cells and caps each cell's
  subprocess timeout.
- Every cell trains under the resilience supervisor
  (masters_thesis_tpu.resilience) with trainer.resume=auto: a preempted
  or crashed attempt is classified and relaunched from its last
  checkpoint INSIDE the cell's budget, re-running this script resumes
  truncated cells instead of restarting, and completed cells are skipped
  via the results JSONL.
- ``--stack-seeds N`` expands every synthetic cell into N seed replicas
  and trains each stack-compatible group — same (model, loss, trainer),
  differing seed — as ONE supervised stacked process
  (sweeps/stacked_cell.py -> train/stacked.py): one compile and one
  batched gradient all-reduce per step for the whole group, per-cell
  heartbeats/JSONL rows/resume preserved. Warmup cells keep the
  per-cell subprocess path.

Results: one JSON line per finished cell in results/grid_r3.jsonl
(training wall, best-val, and the ΔL-above-OLS table numbers via
sweeps/eval_cell.py).

Usage:
    nohup python sweeps/run_grid_canonical.py \
        --deadline 2026-07-30T06:30 > results/grid_r3_runner.log 2>&1 &
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import subprocess
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from masters_thesis_tpu.resilience.supervisor import (  # noqa: E402
    RunSupervisor,
    SupervisorConfig,
)
from masters_thesis_tpu.telemetry.trace import (  # noqa: E402
    TRACE_ENV,
    new_trace_id,
)

REPO = Path(__file__).resolve().parent.parent
RESULTS_DIR = REPO / "results"
OUT = RESULTS_DIR / "grid_r3.jsonl"

MODELS = ("small", "medium", "large")
LOSSES = ("mse", "nll", "combined")
PER_CELL_CAP_S = 3 * 3600


def log(msg: str) -> None:
    print(f"{datetime.datetime.now():%H:%M:%S} {msg}", flush=True)


def cell_heartbeat(cell: str, phase: str, **extra) -> None:
    """Atomic per-cell heartbeat under results/heartbeats/<cell>.json.

    The runner's own last-sign-of-life channel: when the whole runner is
    SIGKILLed (budget cap, environment reset) the log just stops, but the
    heartbeat file shows which cell was in flight and in which phase —
    the same role heartbeat.json plays for a training process. Best-effort:
    a full disk must not take the sweep down."""
    hb_dir = RESULTS_DIR / "heartbeats"
    try:
        hb_dir.mkdir(parents=True, exist_ok=True)
        path = hb_dir / f"{cell}.json"
        tmp = path.with_name(path.name + ".tmp")
        tmp.write_text(json.dumps(
            {"cell": cell, "phase": phase, "ts": time.time(),
             "pid": os.getpid(), **extra}
        ))
        os.replace(tmp, path)
    except OSError:
        pass


def tpu_ready() -> bool:
    try:
        subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=90,
            check=True,
            capture_output=True,
        )
        return True
    except Exception:
        return False


def wait_for_tpu(deadline: float) -> bool:
    while time.time() < deadline - 300:
        # Cooperative pause: `touch results/PAUSE` stops the runner from
        # LAUNCHING new cells (the in-flight cell finishes), freeing the
        # chip for interactive measurements; `rm` it to resume the grid.
        if (RESULTS_DIR / "PAUSE").exists():
            log("paused via results/PAUSE; checking again in 15s")
            time.sleep(15)
            continue
        if tpu_ready():
            return True
        log("TPU relay not ready; retrying in 60s")
        time.sleep(60)
    return False


def maybe_run_bench(deadline: float) -> None:
    """Opportunistic bench capture: if results/BENCH_REQUEST exists when the
    device probe has just passed, run bench.py NOW (the relay is healthy at
    this instant — the best moment for the round's primary perf evidence)
    and append its JSON line to results/bench_opportunistic.jsonl. The
    marker is consumed either way; re-touch it to request another capture.
    The subprocess timeout is capped by the runner's deadline, same as
    cells."""
    req = RESULTS_DIR / "BENCH_REQUEST"
    if not req.exists():
        return
    budget = min(3600.0, deadline - time.time())
    if budget < 300:
        return  # too close to the deadline to spend TPU time on a bench
    log("BENCH_REQUEST: relay healthy, capturing bench.py")
    try:
        out = subprocess.run(
            [sys.executable, "bench.py"],
            cwd=REPO, timeout=budget, capture_output=True, text=True,
        )
        if out.returncode == 0 and out.stdout.strip():
            with open(RESULTS_DIR / "bench_opportunistic.jsonl", "a") as f:
                f.write(out.stdout.strip().splitlines()[-1] + "\n")
            log("bench captured -> results/bench_opportunistic.jsonl")
        else:
            log(f"bench failed rc={out.returncode}: {out.stderr[-500:]}")
    except subprocess.TimeoutExpired:
        log(f"bench timed out after {budget:.0f}s")
    finally:
        req.unlink(missing_ok=True)


def done_cells() -> set:
    """Cells with a COMPLETE recorded run. Truncated rows don't count: a
    re-run resumes them from their last checkpoint and appends a fresher
    row (consumers take the last row per cell)."""
    if not OUT.exists():
        return set()
    done = set()
    for line in OUT.read_text().splitlines():
        if line.strip():
            row = json.loads(line)
            if not row.get("truncated"):
                done.add(row["cell"])
    return done


def version_for(loss: str, model: str, trainer: str) -> str:
    return f"{loss}_{model}_lr0.0001_{trainer}"


def train_with_retry(
    cell: str,
    train_overrides: list[str],
    budget: float,
    deadline: float,
    ckpt: Path | None = None,
    cmd: list[str] | None = None,
) -> tuple[bool, bool]:
    """Run train.py (with resume) under the resilience supervisor, within
    a wall budget. Returns ``(completed, truncated)``: completed means the
    supervised run reached a ``completed`` verdict; truncated means the
    budget/timeout cut training short (the checkpoint, if any, is partial
    — a re-run with trainer.resume=auto continues it).

    The supervisor subsumes this function's old hand-rolled retry: a
    preempted/killed/UNAVAILABLE attempt is classified transient and
    relaunched with backoff (resume makes the retry CONTINUE from the last
    checkpoint, not restart the cell), an instantly-reproduced crash halts
    with a deterministic verdict instead of burning the cell budget, and a
    NaN-diverged fit rolls back to the last good checkpoint at a halved
    LR. Per-attempt stdout/stderr land in <log_dir>/supervisor/."""
    budget = min(budget, max(60.0, deadline - time.time()))
    log_dir = ckpt.parent.parent if ckpt is not None else None
    # Fresh trace id per cell: each cell is its own trace (all its
    # supervisor attempts ride it), never inherited from the runner's own
    # environment — a runner-wide id would fuse every cell into one trace.
    env = dict(os.environ)
    env[TRACE_ENV] = new_trace_id()
    sup = RunSupervisor(
        cmd or [sys.executable, "train.py", *train_overrides,
                "trainer.resume=auto", "trainer.enable_model_summary=false"],
        run_dir=(log_dir / "supervisor") if log_dir else RESULTS_DIR / "supervisor" / cell,
        cfg=SupervisorConfig(
            max_retries=2,
            backoff_s=60.0,
            backoff_factor=2.0,
            retry_budget_s=budget,
            attempt_timeout_s=budget,
        ),
        env=env,
        cwd=REPO,
        watch_dir=(log_dir / "telemetry") if log_dir else None,
        ckpt_dir=(ckpt.parent if ckpt is not None else None),
    )
    result = sup.run()
    if result.ok:
        return True, False
    if result.verdict == "budget_exhausted":
        log(f"{cell}: cell budget ({budget:.0f}s) cut training short; "
            "resume will continue it on a re-run")
        return False, True
    last = result.attempts[-1] if result.attempts else None
    reason = last.classification.reason if last else "no attempt launched"
    err_tail = ""
    if last is not None:
        err_file = sup.run_dir / f"attempt_{last.attempt}.err"
        if err_file.exists():
            err_tail = err_file.read_text(errors="replace")[-1500:]
    log(f"{cell}: train FAILED verdict={result.verdict} "
        f"after {result.n_attempts} attempt(s): {reason}\n{err_tail}")
    return False, False


def ensure_checkpoint(
    cell: str, train_overrides: list[str], ckpt: Path, deadline: float
) -> bool:
    """Regenerate a checkpoint whose CELL is already recorded but whose
    files are gone (checkpoints don't survive an environment reset; only
    the results JSONL does). Trains without recording a new row — the
    recorded metrics stand; this only restores the weights that downstream
    cells (the warmup block's pretrain) need to warm-start from.

    A checkpoint counts as restored only once train.py has COMPLETED
    (exit 0): a budget-truncated retrain leaves a partial val-epoch
    checkpoint at the same path, and warm-starting the scratch-vs-warmup
    comparison from under-trained pretrain weights would silently
    invalidate it. Completion is recorded in a marker file next to the
    checkpoint (same lifetime: both live in logs/, both die in a reset);
    the checkpoint protocol never touches foreign files in its dir.

    INTENTIONAL (ADVICE r4): a complete checkpoint written before the
    marker protocol (or by an older runner) is relaunched once rather than
    trusted — the marker is the only completion evidence with checkpoint
    lifetime, and ``trainer.resume=true`` makes that relaunch exit almost
    immediately when the checkpoint really was complete, so the cost is
    bounded startup churn, not a retrain."""
    marker = ckpt.parent / f"{ckpt.name}.ENSURED"
    if ckpt.exists() and marker.exists():
        return True
    if not wait_for_tpu(deadline):
        log(f"ensure {cell}: TPU never became ready before deadline")
        return False
    budget = min(PER_CELL_CAP_S, deadline - time.time())
    if budget < 300:
        log(f"ensure {cell}: deadline reached")
        return False
    log(f"ensure {cell}: checkpoint missing or unconfirmed; training to "
        "completion (not re-recorded)")
    completed, truncated = train_with_retry(
        cell, train_overrides, budget, deadline, ckpt=ckpt
    )
    if not completed:
        if truncated and ckpt.exists():
            log(f"ensure {cell}: retrain truncated; partial checkpoint NOT "
                "used (re-run resumes it)")
        return False
    if not ckpt.exists():
        log(f"ensure {cell}: train completed but no checkpoint at {ckpt}")
        return False
    marker.touch()
    return True


def run_cell(
    cell: str,
    train_overrides: list[str],
    ckpt: Path,
    eval_overrides: list[str],
    deadline: float,
) -> None:
    if cell in done_cells():
        log(f"skip {cell}: already recorded")
        return
    if not wait_for_tpu(deadline):
        log(f"skip {cell}: TPU never became ready before deadline")
        return
    maybe_run_bench(deadline)
    # Budget AFTER the TPU wait: a long wedge must shrink the cell's cap,
    # not let the subprocess run past the deadline.
    budget = min(PER_CELL_CAP_S, deadline - time.time())
    if budget < 300:
        log(f"skip {cell}: deadline reached")
        return

    log(f"train {cell}")
    cell_heartbeat(cell, "train", budget_s=round(budget, 1))
    t0 = time.time()
    completed, truncated = train_with_retry(
        cell, train_overrides, budget, deadline, ckpt=ckpt
    )
    if not completed and not truncated:
        # Hard failure, already logged — attach the fleet verdict the way
        # telemetry_summary headlines successful cells: which process died
        # or hung, and where (jax-free, so this can't hang on the backend).
        post = postmortem_headline(ckpt)
        if post is not None:
            log(f"{cell}: postmortem: {post['headline']}")
        cell_heartbeat(cell, "failed", postmortem=post)
        return
    if truncated:
        log(f"{cell}: evaluating the last checkpoint")
    cell_heartbeat(cell, "eval", truncated=truncated)
    if completed and ckpt.exists():
        # Record completion for ensure_checkpoint: a cell run_cell finished
        # is exactly as confirmed as one ensure_checkpoint finished, and
        # without the marker a later ensure would re-launch train.py.
        (ckpt.parent / f"{ckpt.name}.ENSURED").touch()
    wall = time.time() - t0

    if not ckpt.exists():
        log(f"{cell}: no checkpoint at {ckpt}; nothing to record")
        post = postmortem_headline(ckpt)
        if post is not None:
            log(f"{cell}: postmortem: {post['headline']}")
        cell_heartbeat(cell, "failed", postmortem=post)
        return
    try:
        ev = subprocess.run(
            [sys.executable, "sweeps/eval_cell.py", f"checkpoint={ckpt}",
             *eval_overrides],
            cwd=REPO,
            timeout=1800,
            check=True,
            capture_output=True,
            text=True,
        )
    except (subprocess.TimeoutExpired, subprocess.CalledProcessError) as exc:
        err = getattr(exc, "stderr", "") or ""
        log(f"{cell}: eval failed ({type(exc).__name__})\n{err[-1500:]}")
        cell_heartbeat(cell, "failed", stage="eval")
        return
    row = json.loads(ev.stdout.strip().splitlines()[-1])
    row.update({"cell": cell, "train_wall_s": round(wall, 1),
                "truncated": truncated,
                "telemetry": telemetry_summary(ckpt)})
    if truncated:
        # A truncated cell is a partial failure: record WHY training was
        # cut short (hang? killed? straggler?) next to its metrics.
        row["postmortem"] = postmortem_headline(ckpt)
    RESULTS_DIR.mkdir(exist_ok=True)
    with open(OUT, "a") as f:
        f.write(json.dumps(row) + "\n")
    cell_heartbeat(cell, "done", truncated=truncated,
                   wall_s=round(wall, 1))
    log(f"{cell}: recorded (wall {wall:.0f}s, truncated={truncated})")


def telemetry_summary(ckpt: Path) -> dict | None:
    """Perf summary of the cell's training run, from its telemetry stream.

    train.py (trainer.telemetry=auto) writes <log_dir>/telemetry/events.jsonl
    next to <log_dir>/checkpoints/<tag>; the summarize CLI is jax-free, so
    this never touches (or hangs on) the backend. Returns the headline
    numbers worth a grid row — or None when the run predates telemetry.
    """
    tel_dir = ckpt.parent.parent / "telemetry"
    if not (tel_dir / "events.jsonl").exists():
        return None
    try:
        out = subprocess.run(
            [sys.executable, "-m", "masters_thesis_tpu.telemetry",
             "summarize", str(tel_dir), "--json"],
            cwd=REPO,
            timeout=120,
            capture_output=True,
            text=True,
        )
        report = json.loads(out.stdout)
    except (subprocess.TimeoutExpired, json.JSONDecodeError, OSError) as exc:
        log(f"telemetry summary failed for {tel_dir}: {type(exc).__name__}")
        return None
    return {
        "steps_per_sec": report.get("steps_per_sec"),
        "step_time_ms_p50": report.get("step_time_ms", {}).get("p50"),
        "step_time_ms_p99": report.get("step_time_ms", {}).get("p99"),
        "compiles": report.get("compiles", {}).get("train_epoch"),
        "data_wait_s": report.get("data", {}).get("data_wait_s"),
        "peak_bytes": report.get("memory", {}).get("peak_bytes"),
        "violations": report.get("violations"),
    }


def postmortem_headline(ckpt: Path) -> dict | None:
    """Fleet verdict on a failed/truncated cell, from its telemetry dir.

    Mirrors telemetry_summary for the failure path: the postmortem CLI is
    jax-free by contract (it must work exactly when the backend is wedged),
    so this never hangs on the relay. Returns the one-line verdict plus the
    finding list — or None when the run left no stream to read."""
    tel_dir = ckpt.parent.parent / "telemetry"
    if not (tel_dir / "events.jsonl").exists():
        return None
    try:
        out = subprocess.run(
            [sys.executable, "-m", "masters_thesis_tpu.telemetry",
             "postmortem", str(tel_dir), "--json"],
            cwd=REPO,
            timeout=120,
            capture_output=True,
            text=True,
        )
        report = json.loads(out.stdout)
    except (subprocess.TimeoutExpired, json.JSONDecodeError, OSError) as exc:
        log(f"postmortem failed for {tel_dir}: {type(exc).__name__}")
        return None
    return {
        "headline": report.get("headline"),
        "exit_code": out.returncode,
        "failures": report.get("failures"),
    }


def run_stacked_group(
    loss: str, model: str, trainer_name: str, seeds: list[int],
    deadline: float,
) -> None:
    """Train a stack-compatible group of seed cells in ONE supervised
    stacked process (sweeps/stacked_cell.py -> train.stacked).

    Same contracts as run_cell, per cell of the group: cells already
    recorded complete are not retrained (the stacked child only gets the
    PENDING replicas), each cell keeps its own heartbeat file and its own
    results-JSONL row, a budget-cut group is recorded truncated and a
    re-run resumes every replica from its last common checkpoint.
    Supervisor preemption/crash retries relaunch the whole group; a
    replica that diverges is rolled back or masked individually by the
    stacked trainer without costing its siblings the run.
    """
    group = f"{loss}_{model}_{trainer_name}_stack"
    names = {s: f"{loss}_{model}_{trainer_name}_s{s}" for s in seeds}
    done = done_cells()
    pending = [s for s in seeds if names[s] not in done]
    if not pending:
        log(f"skip {group}: all {len(seeds)} cells recorded")
        return
    if not wait_for_tpu(deadline):
        log(f"skip {group}: TPU never became ready before deadline")
        return
    maybe_run_bench(deadline)
    budget = min(PER_CELL_CAP_S, deadline - time.time())
    if budget < 300:
        log(f"skip {group}: deadline reached")
        return

    ckpt_root = (REPO / "logs/FinancialLstm/synthetic_stacked"
                 / version_for(loss, model, trainer_name))
    replicas = [{"name": f"s{s}", "seed": s} for s in pending]
    log(f"train {group}: {len(pending)} stacked cell(s) "
        f"{[names[s] for s in pending]}")
    for s in pending:
        cell_heartbeat(names[s], "train", stack_group=group,
                       budget_s=round(budget, 1))
    t0 = time.time()
    completed, truncated = train_with_retry(
        group, [], budget, deadline,
        ckpt=ckpt_root / "checkpoints" / "group",
        cmd=[sys.executable, "sweeps/stacked_cell.py",
             f"model={model}", f"loss={loss}", f"trainer={trainer_name}",
             "--replicas", json.dumps(replicas),
             "--ckpt-dir", str(ckpt_root)],
    )
    if not completed and not truncated:
        for s in pending:
            cell_heartbeat(names[s], "failed", stack_group=group)
        return
    wall = time.time() - t0
    if truncated:
        log(f"{group}: evaluating the last per-replica checkpoints")

    for s in pending:
        cell = names[s]
        ckpt = ckpt_root / f"s{s}" / "best"
        cell_heartbeat(cell, "eval", stack_group=group, truncated=truncated)
        if not ckpt.exists():
            log(f"{cell}: no checkpoint at {ckpt}; nothing to record")
            cell_heartbeat(cell, "failed", stack_group=group)
            continue
        try:
            ev = subprocess.run(
                [sys.executable, "sweeps/eval_cell.py", f"checkpoint={ckpt}",
                 "datamodule=synthetic"],
                cwd=REPO,
                timeout=1800,
                check=True,
                capture_output=True,
                text=True,
            )
        except (subprocess.TimeoutExpired,
                subprocess.CalledProcessError) as exc:
            err = getattr(exc, "stderr", "") or ""
            log(f"{cell}: eval failed ({type(exc).__name__})\n{err[-1500:]}")
            cell_heartbeat(cell, "failed", stage="eval", stack_group=group)
            continue
        row = json.loads(ev.stdout.strip().splitlines()[-1])
        row.update({"cell": cell, "stack_group": group, "seed": s,
                    "train_wall_s": round(wall, 1),
                    "truncated": truncated,
                    "telemetry": telemetry_summary(ckpt)})
        RESULTS_DIR.mkdir(exist_ok=True)
        with open(OUT, "a") as f:
            f.write(json.dumps(row) + "\n")
        cell_heartbeat(cell, "done", stack_group=group, truncated=truncated,
                       wall_s=round(wall, 1))
        log(f"{cell}: recorded (stacked, wall {wall:.0f}s shared, "
            f"truncated={truncated})")


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--deadline", required=True,
        help="ISO time (local) after which no new cells launch",
    )
    parser.add_argument(
        "--stack-seeds", type=int, default=1, metavar="N",
        help="expand each synthetic grid cell into N seed replicas and "
        "train each (model, loss, trainer) group as ONE stacked process "
        "(train/stacked.py); 1 (default) keeps the canonical per-cell "
        "subprocess path. Warmup cells always use the subprocess path — "
        "warm-started runs are not stack-compatible with scratch runs.",
    )
    args = parser.parse_args()
    deadline = datetime.datetime.fromisoformat(args.deadline).timestamp()
    log(f"grid runner start; deadline {args.deadline} "
        f"({(deadline - time.time()) / 3600:.1f}h away)")

    # ---- 1. slow column, cheapest models first --------------------------
    for model in MODELS:
        for loss in LOSSES:
            if args.stack_seeds > 1:
                run_stacked_group(
                    loss, model, "slow",
                    list(range(args.stack_seeds)), deadline,
                )
                continue
            cell = f"{loss}_{model}_slow"
            ckpt = (REPO / "logs/FinancialLstm/synthetic"
                    / version_for(loss, model, "slow") / "checkpoints/best")
            run_cell(
                cell,
                [f"model={model}", f"loss={loss}", "trainer=slow"],
                ckpt,
                ["datamodule=synthetic"],
                deadline,
            )

    # ---- 2. warmup protocol (pretrain variant -> outliers variant) ------
    pre = (REPO / "logs/FinancialLstm/synthetic"
           / version_for("combined", "large", "slow") / "checkpoints/best")
    outlier_ov = [
        "datamodule.dgp_variant=outliers",
        "datamodule.data_dir=data/synthetic_outliers",
    ]
    # From-scratch baselines on the fine-tune dataset: independent of the
    # pretrain checkpoint, so they run regardless of the ensure below.
    for loss in LOSSES:
        run_cell(
            f"outliers_{loss}_large_scratch",
            ["model=large", f"loss={loss}", "trainer=slow", *outlier_ov,
             "logger.name=FinancialLstm/outliers"],
            REPO / "logs/FinancialLstm/outliers"
            / version_for(loss, "large", "slow") / "checkpoints/best",
            outlier_ov,
            deadline,
        )
    # Warm-started cells need the pretrain weights; only spend TPU time
    # restoring those (ensure_checkpoint may retrain for hours) if at
    # least one warmup cell is still unrecorded.
    pending_warmup = [
        loss for loss in LOSSES
        if f"outliers_{loss}_large_warmup" not in done_cells()
    ]
    if not pending_warmup:
        log("warmup cells all recorded; pretrain ensure skipped")
    elif ensure_checkpoint(
        "combined_large_slow",
        ["model=large", "loss=combined", "trainer=slow"],
        pre,
        deadline,
    ):
        for loss in pending_warmup:
            # Warm-started from the synthetic-pretrained weights
            # (fresh optimizer: checkpoint_mode=params).
            run_cell(
                f"outliers_{loss}_large_warmup",
                ["model=large", f"loss={loss}", "trainer=slow", *outlier_ov,
                 f"checkpoint={pre}", "checkpoint_mode=params",
                 "logger.name=FinancialLstm/warmup"],
                REPO / "logs/FinancialLstm/warmup"
                / version_for(loss, "large", "slow") / "checkpoints/best",
                outlier_ov,
                deadline,
            )
    else:
        log("warmup cells skipped: pretrain checkpoint unavailable "
            "(missing, unconfirmed, or its retrain did not finish — see "
            "ensure log above)")

    # ---- 3. slowest column, cheapest models first -----------------------
    for model in MODELS:
        for loss in LOSSES:
            if args.stack_seeds > 1:
                run_stacked_group(
                    loss, model, "slowest",
                    list(range(args.stack_seeds)), deadline,
                )
                continue
            cell = f"{loss}_{model}_slowest"
            ckpt = (REPO / "logs/FinancialLstm/synthetic"
                    / version_for(loss, model, "slowest") / "checkpoints/best")
            run_cell(
                cell,
                [f"model={model}", f"loss={loss}", "trainer=slowest"],
                ckpt,
                ["datamodule=synthetic"],
                deadline,
            )

    log("grid runner finished")


if __name__ == "__main__":
    main()
