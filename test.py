#!/usr/bin/env python
"""Evaluation/plot driver — loads a checkpoint, compares model vs OLS vs
ground truth on the test split, and renders TensorBoard figures.

Usage (reference: test.py:147-218)::

    python test.py checkpoint=logs/FinancialLstm/synthetic/<version>/checkpoints

Figure set and tags match the reference's ``plot`` (reference:
test.py:91-145): residual scatter/hist pairs, per-stock estimation series,
and truth-vs-estimate scatters for alpha and beta.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from train import CONFIG_DIR, build_datamodule, bootstrap
from masters_thesis_tpu.config import Config, compose


def derive_logger_dirs(checkpoint: Path, cfg: Config) -> tuple[str, str, str]:
    """Recover (save_dir, name, version) from the checkpoint path layout
    ``<save_dir>/<name...>/<version>/checkpoints[/tag]``
    (reference: test.py:182-192 parses the same parts)."""
    parts = list(Path(checkpoint).resolve().parts)
    if "checkpoints" in parts:
        i = parts.index("checkpoints")
        version = parts[i - 1]
        save_root = Path(cfg.logger.save_dir).resolve()
        try:
            # name = whatever sits between save_dir and version
            rel = Path(*parts[: i - 1]).relative_to(save_root)
            return str(save_root), str(rel), version
        except ValueError:
            pass
    return cfg.logger.save_dir, cfg.logger.name, cfg.logger.version


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("overrides", nargs="*", help="key=value config overrides")
    args = parser.parse_args(argv)
    cfg = compose(str(CONFIG_DIR), overrides=args.overrides)

    if not cfg.checkpoint:
        # (reference: test.py:153 exits early with the same complaint)
        print("No model checkpoint found, exiting...", file=sys.stderr)
        return

    from masters_thesis_tpu.evaluation import collect_test_results, delta_losses
    from masters_thesis_tpu.train.checkpoint import (
        apply_datamodule_sidecar,
        restore_checkpoint,
    )
    from masters_thesis_tpu.utils import enable_persistent_compilation_cache

    enable_persistent_compilation_cache()
    from masters_thesis_tpu.train.logging import TensorBoardLogger
    from masters_thesis_tpu.viz import (
        estimation_plots,
        estimation_scatter,
        hist_plot,
        scatter_plot,
    )

    params, _, spec, meta = restore_checkpoint(Path(cfg.checkpoint))
    # Evaluate on the SAME windowing the checkpoint was trained with.
    apply_datamodule_sidecar(cfg, meta)
    if not bootstrap(cfg):
        return
    dm = build_datamodule(cfg)
    dm.prepare_data()

    results = collect_test_results(spec, params, dm)

    save_dir, name, version = derive_logger_dirs(Path(cfg.checkpoint), cfg)
    tb = TensorBoardLogger(save_dir, name, version)

    tb.log_figure(
        "scatter/recon_residuals",
        scatter_plot(
            results["recon_residuals"]["model"],
            results["recon_residuals"]["ols"],
            title="Model vs OLS Reconstruction Residuals",
        ),
    )
    tb.log_figure(
        "scatter/alphas",
        scatter_plot(
            results["alpha"]["model"], results["alpha"]["ols"],
            title="Model vs OLS Alphas",
        ),
    )
    tb.log_figure(
        "scatter/betas",
        scatter_plot(
            results["beta"]["model"], results["beta"]["ols"],
            title="Model vs OLS Betas",
        ),
    )
    tb.log_figure(
        "hist/recon_residuals",
        hist_plot(
            results["recon_residuals"]["model"],
            results["recon_residuals"]["ols"],
            title="Model vs OLS Reconstruction Residuals",
        ),
    )
    tb.log_figure(
        "hist/alphas",
        hist_plot(
            results["alpha_residuals"]["model"],
            results["alpha_residuals"]["ols"],
            title="Model vs OLS Alpha Residuals",
        ),
    )
    tb.log_figure(
        "hist/betas",
        hist_plot(
            results["beta_residuals"]["model"],
            results["beta_residuals"]["ols"],
            title="Model vs OLS Beta Residuals",
        ),
    )
    for kind in ("alpha", "beta"):
        estimation_plots(
            tb,
            results[kind]["model"],
            results[kind]["ols"],
            results[kind]["true"],
            est_kind=kind,
        )
        tb.log_figure(
            f"estimation/{kind}",
            estimation_scatter(
                results[kind]["model"],
                results[kind]["ols"],
                results[kind]["true"],
                est_kind=kind,
            ),
        )
    # Thesis results-table metrics: losses above the OLS-on-target baseline
    # (reference: tex/diplomski_rad.tex:1155-1176 reports ΔL_MSE ×1e-5,
    # ΔL_NLL, and ΔL_MIX(ζ=1e5) for the model and the lookback-OLS row).
    deltas = delta_losses(spec, params, dm, estimates=results)
    scalars = {}
    for key in ("model", "ols"):
        d = deltas[key]
        scalars.update(
            {
                f"delta/{key}/mse": d["delta_mse"],
                f"delta/{key}/nll": d["delta_nll"],
                f"delta/{key}/mix": d["delta_mix"],
            }
        )
        print(
            f"{key:>6}: dL_MSE(x1e-5)={d['delta_mse'] * 1e5:7.3f}  "
            f"dL_NLL={d['delta_nll']:7.3f}  "
            f"dL_MIX(zeta=1e5)={d['delta_mix']:7.3f}"
        )
    tb.log_scalars(scalars, 0)
    tb.close()
    print(f"figures written to {tb.log_dir}")


if __name__ == "__main__":
    main()
