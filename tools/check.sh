#!/usr/bin/env bash
# Repo gate: lint config -> tracelint (both passes) -> tier-1 tests.
# Usage: tools/check.sh [--fast]   (--fast skips the pytest tier)
set -uo pipefail

cd "$(dirname "$0")/.."
fail=0

# 1. ruff, when the environment has it (the pinned container does not ship
#    it; config lives in pyproject.toml so local/CI runs that do have ruff
#    agree on the rules).
if command -v ruff >/dev/null 2>&1; then
    echo "== ruff =="
    ruff check masters_thesis_tpu tests bench.py train.py test.py || fail=1
else
    echo "== ruff == (not installed; skipping)"
fi

# 2. tracelint: AST lint over the package + trace-time audit on the
#    hermetic 8-device virtual CPU mesh (includes TA206: the compiled
#    train step carries exactly ONE cross-replica reduction — the flat
#    gradient pmean — and TA207: the stacked R-replica program compiles
#    once with the same single batched all-reduce per dtype buffer).
echo "== tracelint =="
JAX_PLATFORMS=cpu python -m masters_thesis_tpu.analysis || fail=1

# 2a. The same trace audit (TA201-TA207) on the universe-scale K=3
#     asset-sharded program: the K-factor epoch must hold the identical
#     invariants — one compile, clean transfer guard, params replicated +
#     per-asset leaves sharded (factor stats replicated BY DESIGN), and
#     still exactly one all-reduce per dtype buffer in the scan body.
echo "== tracelint (K=3 universe, asset-sharded) =="
JAX_PLATFORMS=cpu python -m masters_thesis_tpu.analysis --skip-lint \
    --n-factors 3 --shard-axis asset || fail=1

# 2b. Pass 3: concurrency lint (CL501-CL505 — lock-order inversions,
#     unguarded shared state, blocking calls under locks / in signal
#     handlers, thread lifecycle) + event-schema contract check
#     (EC601-EC603) against the checked-in lockfile.
echo "== concurrency + contract lint =="
python -m masters_thesis_tpu.analysis --concurrency --contracts || fail=1

# 2b'. Pass 4: SPMD divergence lint (DV701-DV705 — host-divergent
#      control flow around collectives, divergent schedules/operands,
#      checkpoint-path nondeterminism, unfenced rank-0 side effects)
#      over the train/parallel/resilience/telemetry stack.
echo "== spmd divergence lint =="
python -m masters_thesis_tpu.analysis --spmd || fail=1

# 2c. The event-schema lockfile must match what the code actually emits;
#     regenerate with `python -m masters_thesis_tpu.analysis --emit-schema`
#     after changing emitters.
echo "== event schema freshness =="
python - <<'PY' || fail=1
import json, sys
from pathlib import Path
from masters_thesis_tpu.analysis.contracts import build_schema

root = Path("masters_thesis_tpu")
schema = build_schema([root], package_root=root)
lock = root / "analysis" / "event_schema.json"
if json.loads(lock.read_text()) != schema:
    print(
        "event_schema.json is stale — run "
        "`python -m masters_thesis_tpu.analysis --emit-schema`",
        file=sys.stderr,
    )
    raise SystemExit(1)
PY

# 3. telemetry: hermetic registry -> events -> report smoke, plus the
#    simulated-fleet flight-recorder -> aggregate -> postmortem smoke
#    (both jax-free by contract — they must work on a wedged host).
echo "== telemetry selfcheck =="
python -m masters_thesis_tpu.telemetry selfcheck || fail=1
echo "== telemetry postmortem selfcheck =="
python -m masters_thesis_tpu.telemetry postmortem --selfcheck || fail=1
echo "== telemetry ledger selfcheck =="
python -m masters_thesis_tpu.telemetry ledger --selfcheck || fail=1
echo "== telemetry trace selfcheck =="
python -m masters_thesis_tpu.telemetry trace --selfcheck || fail=1
echo "== telemetry watch selfcheck =="
python -m masters_thesis_tpu.telemetry watch --selfcheck || fail=1
echo "== telemetry quality selfcheck =="
python -m masters_thesis_tpu.telemetry quality --selfcheck || fail=1

# 3b. resilience: supervisor end-to-end against jax-free workers
#     (preempt -> resume, deterministic crash -> halt, NaN -> rollback)
#     plus the jax-free failure-classification unit.
echo "== resilience selfcheck =="
python -m masters_thesis_tpu.resilience selfcheck || fail=1
echo "== resilience classify (unit) =="
python -m masters_thesis_tpu.resilience classify --rc -15 \
    | grep '"kind": "transient"' >/dev/null || fail=1

# 3b'. fleet supervisor: hermetic 2-rank fleet, one rank SIGKILLed
#      mid-epoch -> whole-fleet relaunch resumes bit-identically; a
#      deterministic rank loss -> elastic resize to 1 rank (jax-free).
echo "== resilience fleet selfcheck =="
python -m masters_thesis_tpu.resilience fleet --selfcheck || fail=1

# 3c. serving: jax-free smoke of the request path (queue/admission/
#     deadline/breaker/canary/multi-tenant stacked dispatch with a fake
#     engine), then the serve preflight on the hermetic 8-device virtual
#     CPU mesh — every predict bucket compiles exactly once, the hot path
#     is clean under transfer_guard("disallow"), stacked lanes share one
#     program per bucket, and a lane hot-swap is zero-compile with zero
#     late answers (rules SV301-SV308).
echo "== serve selfcheck =="
python -m masters_thesis_tpu.serve selfcheck || fail=1
echo "== serve preflight =="
JAX_PLATFORMS=cpu python -m masters_thesis_tpu.serve preflight || fail=1

if [ "${1:-}" = "--fast" ]; then
    exit $fail
fi

# 4. Tier-1 tests (the ROADMAP.md quick loop).
echo "== pytest (tier 1) =="
JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider || fail=1

exit $fail
