#!/usr/bin/env python
"""Benchmark: train-step throughput per chip on the synthetic workload.

Measures steps/sec/chip for the canonical benchmark configuration
(BASELINE.json: "train.py steps/sec/chip (synthetic datamodule)"): the
reference's synthetic datamodule shape — 100 stocks per window, 60-day
lookback, 3 features, batch_size=1 window per optimizer step, model=small,
loss=mse (reference: configs/datamodule/synthetic.yaml, configs/model/
small.yaml) — run through the device-resident scan-epoch trainer on ONE
chip.

vs_baseline: the reference publishes no throughput numbers (SURVEY.md §6).
The denominator used here is 200 steps/sec/chip — a deliberately generous
ceiling estimate for the reference's per-step Python dispatch pipeline
(Lightning training_step + DataLoader worker handoff + per-step CUDA launch
costs >= ~5 ms/step at batch_size=1 regardless of GPU speed). Any value >1
means this framework beats that ceiling.

Prints exactly one JSON line.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from pathlib import Path

BASELINE_STEPS_PER_SEC = 200.0
DEVICE_PROBE_TIMEOUT_S = 180.0


def _ensure_responsive_backend() -> bool:
    """Fall back to CPU if the TPU relay is wedged; True if degraded.

    A hung relay session blocks ``jax.devices()`` forever (no client-side
    timeout), which would hang the whole benchmark run. Probe device init in
    a subprocess with a timeout; on failure, force the CPU backend so the
    bench still produces a real (if degraded) measurement, flagged by the
    ``device`` field in the output.
    """
    try:
        subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=DEVICE_PROBE_TIMEOUT_S,
            check=True,
            capture_output=True,
        )
        return False
    except (subprocess.TimeoutExpired, subprocess.CalledProcessError) as exc:
        print(
            f"device probe failed ({type(exc).__name__}); "
            "falling back to CPU backend",
            file=sys.stderr,
        )
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
        return True

# Scaled-down sample count (100k vs the reference's 1M bootstrap) keeps the
# bench wall-clock to a couple of minutes; per-step work is IDENTICAL to the
# canonical workload (same window/stock/feature shapes, same model).
N_STOCKS = 100
N_SAMPLES = 100_000
MEASURE_EPOCHS = 8


def main() -> None:
    degraded = _ensure_responsive_backend()
    # CPU fallback is ~300x slower per step: trim the measurement window so
    # the run still finishes inside a driver timeout.
    measure_epochs = 2 if degraded else MEASURE_EPOCHS
    from masters_thesis_tpu.data.pipeline import (
        FinancialWindowDataModule,
        bootstrap_synthetic,
    )
    from masters_thesis_tpu.models.objectives import ModelSpec
    from masters_thesis_tpu.train import Trainer

    data_dir = Path(__file__).resolve().parent / "data" / "bench_synthetic"
    bootstrap_synthetic(data_dir, n_stocks=N_STOCKS, n_samples=N_SAMPLES, seed=0)
    dm = FinancialWindowDataModule(
        data_dir, lookback_window=60, target_window=30, stride=90, batch_size=1
    )
    dm.prepare_data(verbose=False)
    dm.setup()

    spec = ModelSpec(objective="mse")  # model=small defaults, loss=mse
    trainer = Trainer(
        max_epochs=1 + measure_epochs,  # epoch 0 absorbs compile
        gradient_clip_val=5.0,
        check_val_every_n_epoch=10_000,  # pure train throughput
        strategy="single_device",
        enable_progress_bar=False,
        enable_model_summary=False,
        seed=0,
    )
    t0 = time.perf_counter()
    result = trainer.fit(spec, dm)
    wall = time.perf_counter() - t0

    value = result.steps_per_sec
    print(
        json.dumps(
            {
                "metric": "train_steps_per_sec_per_chip",
                "value": round(value, 2),
                "unit": "steps/s",
                "vs_baseline": round(value / BASELINE_STEPS_PER_SEC, 3),
                "detail": {
                    "windows_per_epoch": len(dm.train_range),
                    "batch_size": 1,
                    "measure_epochs": measure_epochs,
                    "wall_s": round(wall, 1),
                    "device": str(trainer.mesh.devices.ravel()[0].platform),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
