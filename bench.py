#!/usr/bin/env python
"""Benchmark: train-step throughput per chip on the synthetic workload.

Measures steps/sec/chip for the canonical benchmark configuration
(BASELINE.json: "train.py steps/sec/chip (synthetic datamodule)"): the
reference's synthetic datamodule shape — 100 stocks per window, 60-day
lookback, 3 features, batch_size=1 window per optimizer step, model=small,
loss=mse (reference: configs/datamodule/synthetic.yaml, configs/model/
small.yaml) — run through the device-resident scan-epoch trainer on ONE
chip.

The single JSON line also carries (in "detail"):

- ``nll``: the same measurement for loss=nll — the fused O(K·n)
  single-factor NLL (ops/losses.py) replacing the reference's dense
  O(K³) path (reference: src/model.py:44-69, src/common.py:50-78).
- ``batch_sweep``: windows/sec at batch_size 1/8/32 — where throughput
  saturates once the per-step dispatch floor is amortized (the tiny-batch
  regime is the known TPU hard part, SURVEY.md §7).
- ``scaling``: 1-device vs 8-device scan-epoch throughput on the virtual
  CPU mesh (run in a subprocess so the backend choice doesn't leak into
  this process) — strong scaling at fixed global batch (the honest
  tiny-batch hard case) plus a same-total-work sharding-overhead ratio
  (the transferable cost of partitioning + psum at the weak-scaling
  program shape) — the methodology artifact for the 1→8→32-chip north
  star; on virtual devices it measures program structure, not real ICI.

vs_baseline: the reference publishes no throughput numbers (SURVEY.md §6).
The denominator used here is 200 steps/sec/chip — a deliberately generous
ceiling estimate for the reference's per-step Python dispatch pipeline
(Lightning training_step + DataLoader worker handoff + per-step CUDA launch
costs >= ~5 ms/step at batch_size=1 regardless of GPU speed). Any value >1
means this framework beats that ceiling.

Prints exactly one JSON line on stdout.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from pathlib import Path

BASELINE_STEPS_PER_SEC = 200.0

# A hung relay session blocks ``jax.devices()`` forever (no client-side
# timeout). Probe device init in a subprocess under a timeout, and RETRY
# across a ~10-minute budget — a wedged lease often clears within minutes,
# and a single failed probe permanently degrading the round's perf evidence
# to a CPU number is worse than waiting out a flake.
PROBE_TIMEOUT_S = 120.0
PROBE_BUDGET_S = 600.0
PROBE_BACKOFF_S = 15.0

# Scaled-down sample count (100k vs the reference's 1M bootstrap) keeps the
# bench wall-clock to a couple of minutes; per-step work is IDENTICAL to the
# canonical workload (same window/stock/feature shapes, same model).
N_STOCKS = 100
N_SAMPLES = 100_000
MEASURE_EPOCHS = 8


def _ensure_responsive_backend() -> tuple[bool, int]:
    """Probe TPU init with retries; returns (degraded_to_cpu, attempts)."""
    from masters_thesis_tpu.utils import probe_tpu_backend

    probe = probe_tpu_backend(
        timeout_s=PROBE_TIMEOUT_S,
        budget_s=PROBE_BUDGET_S,
        backoff_s=PROBE_BACKOFF_S,
    )
    if probe.ok:
        return False, probe.attempts
    print(
        f"device probe failed {probe.attempts}x over {PROBE_BUDGET_S:.0f}s "
        f"({probe.detail}); falling back to CPU backend",
        file=sys.stderr,
    )
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    return True, probe.attempts


def _make_trainer(
    measure_epochs: int,
    strategy: str = "single_device",
    n_devices: int | None = None,
):
    from masters_thesis_tpu.train import Trainer

    return Trainer(
        max_epochs=1 + measure_epochs,  # epoch 0 absorbs compile
        gradient_clip_val=5.0,
        check_val_every_n_epoch=10_000,  # pure train throughput
        strategy=strategy,
        n_devices=n_devices,
        enable_progress_bar=False,
        enable_model_summary=False,
        seed=0,
    )


def _measure(dm, objective: str, measure_epochs: int) -> float:
    """steps/sec for one (datamodule, objective) point; compile excluded."""
    from masters_thesis_tpu.models.objectives import ModelSpec

    spec = ModelSpec(objective=objective)  # model=small defaults
    result = _make_trainer(measure_epochs).fit(spec, dm)
    return result.steps_per_sec


def _scaling_child() -> None:
    """1-dev vs 8-dev scan-epoch throughput at fixed global batch (CPU mesh).

    Runs in a subprocess with JAX_PLATFORMS=cpu +
    --xla_force_host_platform_device_count=8 set by the parent BEFORE jax
    imports. Prints one JSON object on stdout.
    """
    from masters_thesis_tpu.data.pipeline import (
        FinancialWindowDataModule,
        bootstrap_synthetic,
    )

    data_dir = Path(__file__).resolve().parent / "data" / "bench_scaling"
    bootstrap_synthetic(data_dir, n_stocks=25, n_samples=50_000, seed=0)

    def run(n_devices: int, batch_size: int) -> float:
        dm = FinancialWindowDataModule(
            data_dir, lookback_window=60, target_window=30, stride=90,
            batch_size=batch_size,
        )
        dm.prepare_data(verbose=False)
        dm.setup()
        from masters_thesis_tpu.models.objectives import ModelSpec

        trainer = _make_trainer(
            6,
            strategy="single_device" if n_devices == 1 else "tpu_xla",
            n_devices=n_devices,
        )
        result = trainer.fit(ModelSpec(objective="mse"), dm)
        return result.steps_per_sec

    global_batch = 8
    sps_1 = run(1, global_batch)  # 1 device x 8 windows/step
    sps_8 = run(8, 1)  # 8 devices x 1 window/step, pmean over the mesh
    speedup = sps_8 / sps_1 if sps_1 > 0 else 0.0
    # WEAK-scaling curve at fixed windows/device (8), n = 1/2/4/8 devices.
    # On a virtual mesh the devices share the host's core(s), so wall-clock
    # weak scaling is bounded at 1/n by construction; the transferable
    # quantity is PROGRAM efficiency: n-device sharded throughput vs ONE
    # device running the same total windows per step unsharded. That ratio
    # isolates what sharding costs — partitioning, psum collectives,
    # per-device dispatch (ideal 1.0). On real chips each device brings its
    # own compute, so this same program shape IS the weak-scaling step and
    # the ratio here is the efficiency to expect (BASELINE.json north star:
    # scaling eff 1→8→32).
    per_dev = 8
    weak = {}
    for n in (2, 4, 8):
        sps_unsharded = run(1, per_dev * n)  # same total work, no mesh
        sps_sharded = run(n, per_dev)        # n devices x 8 windows each
        weak[str(n)] = {
            "global_batch": per_dev * n,
            "steps_per_sec_1dev_unsharded": round(sps_unsharded, 2),
            f"steps_per_sec_{n}dev_sharded": round(sps_sharded, 2),
            "program_efficiency": round(
                sps_sharded / sps_unsharded if sps_unsharded > 0 else 0.0, 3
            ),
        }
    print(
        json.dumps(
            {
                "strong_fixed_global_batch": {
                    "global_batch": global_batch,
                    "steps_per_sec_1dev": round(sps_1, 2),
                    "steps_per_sec_8dev": round(sps_8, 2),
                    "speedup_8dev": round(speedup, 3),
                    "efficiency": round(speedup / 8.0, 3),
                },
                "weak_fixed_windows_per_device": {
                    "windows_per_device": per_dev,
                    "by_devices": weak,
                },
                # r3 alias: the n=8 weak point is the same-total-work
                # sharding-overhead measurement previous rounds reported.
                "sharding_overhead_same_total_work": {
                    "global_batch": 64,
                    "steps_per_sec_1dev": weak["8"][
                        "steps_per_sec_1dev_unsharded"
                    ],
                    "steps_per_sec_8dev": weak["8"]["steps_per_sec_8dev_sharded"],
                    "ratio_8dev_vs_1dev": weak["8"]["program_efficiency"],
                },
            }
        )
    )


def _run_scaling_subprocess() -> dict | None:
    env = dict(os.environ)
    # The TPU-relay plugin trigger would override JAX_PLATFORMS=cpu in the
    # child (and contend for the one relay session); strip it.
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    try:
        out = subprocess.run(
            [sys.executable, __file__, "--scaling-child"],
            env=env,
            # 8 CPU-mesh fits (strong pair + 3-point weak curve, sharded
            # and unsharded sides).
            timeout=3000,
            check=True,
            capture_output=True,
            text=True,
        )
        return json.loads(out.stdout.strip().splitlines()[-1])
    except Exception as exc:  # never let the scaling probe kill the bench
        print(f"scaling subprocess failed: {exc!r}", file=sys.stderr)
        # CalledProcessError's repr omits the child's output — surface it,
        # or the failure is undiagnosable after the fact.
        for stream in ("stdout", "stderr"):
            text = getattr(exc, stream, None)
            if text:
                print(f"child {stream} tail: {text[-500:]}", file=sys.stderr)
        return None


def _fused_pair_enabled() -> bool:
    from masters_thesis_tpu.ops.lstm_kernel import pair_fusion_enabled

    return pair_fusion_enabled()


# The relay can also wedge MID-measurement — after a passing probe — which
# would hang this process inside an epoch dispatch with no JSON line ever
# printed (the probe only guards backend INIT). Every TPU-touching
# measurement therefore runs in a watchdog subprocess: a hang costs that
# SECTION (or degrades the headline to the CPU path), never the one JSON
# line the driver records. Children share the persistent XLA compile
# cache, so the extra process startups re-trace but rarely re-compile.
POINT_TIMEOUT_HEADLINE_S = 1200.0
POINT_TIMEOUT_AUX_S = 700.0


def _point_child(objective: str, batch_size: int, epochs: int) -> None:
    """Measure one (objective, batch_size) point; prints one JSON line."""
    from masters_thesis_tpu.data.pipeline import FinancialWindowDataModule

    data_dir = Path(__file__).resolve().parent / "data" / "bench_synthetic"
    dm = FinancialWindowDataModule(
        data_dir, lookback_window=60, target_window=30, stride=90,
        batch_size=batch_size,
    )
    dm.prepare_data(verbose=False)
    dm.setup()
    sps = _measure(dm, objective, epochs)
    import jax

    print(json.dumps({
        "steps_per_sec": sps,
        "platform": jax.devices()[0].platform,
        "windows_per_epoch": len(dm.train_range),
    }))


def _measure_point(
    objective: str, batch_size: int, epochs: int, timeout_s: float
) -> dict | None:
    """Watchdogged measurement; None on hang/crash (logged, never raised)."""
    try:
        out = subprocess.run(
            [sys.executable, __file__, "--point", objective,
             str(batch_size), str(epochs)],
            cwd=Path(__file__).resolve().parent,
            timeout=timeout_s,
            capture_output=True,
            text=True,
        )
    except subprocess.TimeoutExpired:
        print(
            f"point {objective}/bs={batch_size} hung past {timeout_s:.0f}s "
            "(mid-measurement relay wedge); skipping the section",
            file=sys.stderr,
        )
        return None
    if out.returncode != 0:
        print(
            f"point {objective}/bs={batch_size} failed rc={out.returncode}: "
            f"{(out.stderr or '')[-500:]}",
            file=sys.stderr,
        )
        return None
    try:
        return json.loads(out.stdout.strip().splitlines()[-1])
    except (json.JSONDecodeError, IndexError):
        print(
            f"point {objective}/bs={batch_size} printed no JSON: "
            f"{out.stdout[-300:]}",
            file=sys.stderr,
        )
        return None


def main() -> None:
    degraded, probe_attempts = _ensure_responsive_backend()
    from masters_thesis_tpu.data.pipeline import (
        FinancialWindowDataModule,
        bootstrap_synthetic,
    )

    data_dir = Path(__file__).resolve().parent / "data" / "bench_synthetic"
    bootstrap_synthetic(data_dir, n_stocks=N_STOCKS, n_samples=N_SAMPLES, seed=0)

    t0 = time.perf_counter()
    headline = None
    if not degraded:
        # Healthy probe: all device-touching measurements run behind
        # watchdog subprocesses (a mid-measurement wedge must not hang
        # this process — see the watchdog comment above).
        headline = _measure_point(
            "mse", 1, MEASURE_EPOCHS, POINT_TIMEOUT_HEADLINE_S
        )
        if headline is None:
            degraded = True
            os.environ["JAX_PLATFORMS"] = "cpu"

    # CPU fallback is ~300x slower per step: trim the measurement window so
    # the run still finishes inside a driver timeout. Measured in-process —
    # the CPU backend cannot wedge.
    measure_epochs = 2 if degraded else MEASURE_EPOCHS
    if degraded:
        dm1 = FinancialWindowDataModule(
            data_dir, lookback_window=60, target_window=30, stride=90,
            batch_size=1,
        )
        dm1.prepare_data(verbose=False)
        dm1.setup()
        value = _measure(dm1, "mse", measure_epochs)
        windows_per_epoch = len(dm1.train_range)
        import jax

        platform = jax.devices()[0].platform
    else:
        value = headline["steps_per_sec"]
        windows_per_epoch = headline["windows_per_epoch"]
        platform = headline["platform"]

    # Degraded (wedged relay, CPU fallback): the probe/watchdog already
    # burned its budget — measure ONLY the headline point so the one JSON
    # line is guaranteed to print inside the driver timeout; the auxiliary
    # sections go null rather than risking no measurement at all.
    nll_sps = None
    batch_sweep = {"1": round(value, 2)}
    scaling = None
    if not degraded:
        aux_epochs = max(2, MEASURE_EPOCHS // 2)
        point = _measure_point("nll", 1, aux_epochs, POINT_TIMEOUT_AUX_S)
        if point is not None:
            nll_sps = point["steps_per_sec"]
        # Batch sweep: amortizing the per-step dispatch floor. windows/sec
        # = steps/sec * batch_size, comparable across points.
        for bs in (8, 32):
            point = _measure_point("mse", bs, aux_epochs, POINT_TIMEOUT_AUX_S)
            if point is not None:
                batch_sweep[str(bs)] = round(point["steps_per_sec"] * bs, 2)
        scaling = _run_scaling_subprocess()
    wall = time.perf_counter() - t0

    result = {
        "metric": "train_steps_per_sec_per_chip",
        "value": round(value, 2),
        "unit": "steps/s",
        "vs_baseline": round(value / BASELINE_STEPS_PER_SEC, 3),
        "detail": {
            "windows_per_epoch": windows_per_epoch,
            "batch_size": 1,
            "measure_epochs": measure_epochs,
            "wall_s": round(wall, 1),
            "device": platform,
            "probe_attempts": probe_attempts,
            # Whether pair fusion was ENABLED (env kill-switch); the Pallas
            # pair kernel additionally requires a TPU backend and a shape
            # inside the VMEM byte budget (ops/lstm_kernel.py pair_fits) —
            # on the degraded CPU path it lowers to the scan form.
            "fused_pair_enabled": _fused_pair_enabled(),
            "nll_steps_per_sec": (
                None if nll_sps is None else round(nll_sps, 2)
            ),
            "batch_sweep_windows_per_sec": batch_sweep,
            "scaling": scaling,
            # r2/r3 artifacts exposed the strong-scaling record under this
            # key; aliased for one round so cross-round consumers keep
            # resolving it (ADVICE r3).
            "scaling_fixed_global_batch": (
                scaling.get("strong_fixed_global_batch") if scaling else None
            ),
        },
    }
    # The relay can wedge for HOURS (observed 2026-07-29: 3.5h+), far past
    # any sane probe budget. Cache every healthy TPU measurement; a
    # degraded run then carries the last one — clearly labeled with its
    # timestamp — so a transient relay outage doesn't erase the chip's
    # measured history. The headline `value` is always THIS run's fresh
    # measurement, never the cache.
    cache = data_dir / "last_tpu_measurement.json"
    if not degraded and result["detail"]["device"] == "tpu":
        from masters_thesis_tpu.utils import atomic_write_text

        atomic_write_text(
            cache,
            json.dumps({"measured_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
                        **result}),
        )
    elif degraded and cache.exists():
        try:
            result["detail"]["last_known_tpu"] = json.loads(cache.read_text())
        except (OSError, json.JSONDecodeError):
            # A corrupt cache must never cost the run its one JSON line.
            pass
    print(json.dumps(result))


if __name__ == "__main__":
    if "--scaling-child" in sys.argv:
        _scaling_child()
    elif "--point" in sys.argv:
        i = sys.argv.index("--point")
        _point_child(
            sys.argv[i + 1], int(sys.argv[i + 2]), int(sys.argv[i + 3])
        )
    else:
        main()
