#!/usr/bin/env python
"""Benchmark: train-step throughput per chip on the synthetic workload.

Measures steps/sec/chip for the canonical benchmark configuration
(BASELINE.json: "train.py steps/sec/chip (synthetic datamodule)"): the
reference's synthetic datamodule shape — 100 stocks per window, 60-day
lookback, 3 features, batch_size=1 window per optimizer step, model=small,
loss=mse (reference: configs/datamodule/synthetic.yaml, configs/model/
small.yaml) — run through the device-resident scan-epoch trainer on ONE
chip.

The single JSON line also carries (in "detail"):

- ``nll``: the same measurement for loss=nll — the fused O(K·n)
  single-factor NLL (ops/losses.py) replacing the reference's dense
  O(K³) path (reference: src/model.py:44-69, src/common.py:50-78).
- ``batch_sweep``: windows/sec at batch_size 1/8/32 (unit recorded in the
  object — r4 consumers misread the old flat map as steps/sec) plus the
  Pallas window pack width per point — where throughput saturates once
  the per-step dispatch floor is amortized (the tiny-batch regime is the
  known TPU hard part, SURVEY.md §7).
- ``collectives_per_step`` / ``grad_reduce_bytes``: the flat update
  path's gradient-sync footprint (train/flatparams.py) — exactly one
  fused pmean per step, and the bytes it moves.
- ``scaling``: 1-device vs 8-device scan-epoch throughput on the virtual
  CPU mesh (run in a subprocess so the backend choice doesn't leak into
  this process) — strong scaling at fixed global batch (the honest
  tiny-batch hard case) plus a same-total-work sharding-overhead ratio
  (the transferable cost of partitioning + psum at the weak-scaling
  program shape), measured as a median over interleaved replicas with
  spread — the methodology artifact for the 1→8→32-chip north star; on
  virtual devices it measures program structure, not real ICI.

vs_baseline: the reference publishes no throughput numbers (SURVEY.md §6).
The denominator used here is 200 steps/sec/chip — a deliberately generous
ceiling estimate for the reference's per-step Python dispatch pipeline
(Lightning training_step + DataLoader worker handoff + per-step CUDA launch
costs >= ~5 ms/step at batch_size=1 regardless of GPU speed). Any value >1
means this framework beats that ceiling.

``--stacked`` runs a separate mode: cells/hour for the stacked-replica
trainer (train/stacked.py) at R=1/2/4/8 on the 8-device virtual CPU mesh.
One cell = one replica trained end-to-end (cold program build + epochs)
through an underfilled-cell workload — see ``_stacked_child`` for why
both choices are the honest ones. Per-point ``cells_per_hour`` rows land
in the perf ledger under ``stacked/R=<r>`` (gated by ``python -m
masters_thesis_tpu.telemetry ledger`` like every other point).

``--universe`` runs the universe-scale sweep: n_assets x K-factor points
through the asset-sharded scan trainer on the 8-device virtual CPU mesh,
with windows served from the memory-mapped window store
(data/window_store.py). Each point reports steps/sec, asset-rows/sec,
FLOPs/step + achieved-FLOPs utilization (from the compiled program's own
cost model), and the store's streaming health (data-wait starvation and
page-fault share through the double-buffered prefetch path). Ledger rows
land under ``universe/n<assets>xK<k>``. The point of the sweep: per-step
utilization must RISE monotonically with n_assets at fixed K — a wider
cross-section fills the per-device batch (and the MXU) instead of adding
dispatch overhead — and the largest point must carry >=5x the FLOPs/step
of the 25-portfolio baseline shape.

Prints exactly one JSON line on stdout.
"""

from __future__ import annotations

import json
import os
import statistics
import subprocess
import sys
import time
from pathlib import Path

BASELINE_STEPS_PER_SEC = 200.0

# A hung relay session blocks ``jax.devices()`` forever (no client-side
# timeout). Probe device init in a subprocess under a timeout, and RETRY
# across a ~10-minute budget — a wedged lease often clears within minutes,
# and a single failed probe permanently degrading the round's perf evidence
# to a CPU number is worse than waiting out a flake.
PROBE_TIMEOUT_S = 120.0
PROBE_BUDGET_S = 600.0
PROBE_BACKOFF_S = 15.0
# The retry budget assumes the wedge MIGHT clear; when a probe (or a
# mid-measurement watchdog kill) already established the lease is wedged
# minutes ago, re-burning the full budget re-timing-out is pure waste —
# BENCH_r05 spent all 600s on 5 consecutive timeouts against a lease a
# previous run had already found dead. The last probe outcome is persisted
# to results/probe_cache.json with a short TTL; within the TTL a
# known-wedged lease gets ONE probe attempt (budget 0) and the run fails
# over to CPU after the first timeout instead of retrying.
PROBE_CACHE_TTL_S = 900.0


def _probe_cache_path() -> Path:
    return Path(__file__).resolve().parent / "results" / "probe_cache.json"


def _backend_health():
    """The shared probe-cache/wedge policy, pinned to bench's knobs.

    The implementation lives in utils.backend_probe.BackendHealth so the
    resilience supervisor applies the identical policy; bench keeps its
    constants and cache location (results/probe_cache.json) unchanged.
    """
    from masters_thesis_tpu.utils import BackendHealth

    return BackendHealth(
        _probe_cache_path(),
        ttl_s=PROBE_CACHE_TTL_S,
        timeout_s=PROBE_TIMEOUT_S,
        budget_s=PROBE_BUDGET_S,
        backoff_s=PROBE_BACKOFF_S,
    )


def _write_probe_cache(ok: bool, detail: str) -> None:
    """Best-effort: the cache must never cost the run its JSON line."""
    _backend_health().record(ok, detail)

# Scaled-down sample count (100k vs the reference's 1M bootstrap) keeps the
# bench wall-clock to a couple of minutes; per-step work is IDENTICAL to the
# canonical workload (same window/stock/feature shapes, same model).
N_STOCKS = 100
N_SAMPLES = 100_000
MEASURE_EPOCHS = 8


def _ensure_responsive_backend() -> tuple[bool, int]:
    """Probe TPU init with retries; returns (degraded_to_cpu, attempts).

    Known-wedged leases (probe cache within TTL) get a single attempt
    instead of the full 600s budget — the policy lives in BackendHealth.
    """
    health = _backend_health().ensure_responsive()
    if health.ok:
        return False, health.attempts
    print("falling back to CPU backend", file=sys.stderr)
    _pin_cpu_in_process()
    return True, health.attempts


def _pin_cpu(env: dict) -> dict:
    """See utils.backend_probe.pin_cpu (relay plugin env + platform pin)."""
    from masters_thesis_tpu.utils.backend_probe import pin_cpu

    return pin_cpu(env)


def _pin_cpu_in_process() -> None:
    """Force THIS process onto the CPU backend, even after ``import jax``.

    JAX captures ``JAX_PLATFORMS`` at import time, so the env var alone is
    not enough once anything has imported jax (ADVICE r4); the config update
    is what actually pins the platform pre-init.
    """
    _pin_cpu(os.environ)
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass


def _make_trainer(
    measure_epochs: int,
    strategy: str = "single_device",
    n_devices: int | None = None,
    telemetry=None,
):
    from masters_thesis_tpu.train import Trainer

    return Trainer(
        max_epochs=1 + measure_epochs,  # epoch 0 absorbs compile
        gradient_clip_val=5.0,
        check_val_every_n_epoch=10_000,  # pure train throughput
        strategy=strategy,
        n_devices=n_devices,
        enable_progress_bar=False,
        enable_model_summary=False,
        seed=0,
        telemetry=telemetry,
        # Static cost model of the hot program (telemetry/costs.py): every
        # measured point reports FLOPs/step + bytes/step + utilization and
        # lands one row in results/perf_ledger.jsonl.
        cost_profile=True,
    )


def _point_telemetry(objective: str, batch_size: int):
    """TelemetryRun for one measured point, or None when not requested.

    ``--telemetry-dir`` travels parent -> watchdog child via
    MTT_TELEMETRY_DIR (children only inherit the environment), so every
    point's events.jsonl lands under one root the operator named.
    """
    root = os.environ.get("MTT_TELEMETRY_DIR")
    if not root:
        return None
    from masters_thesis_tpu.telemetry import TelemetryRun

    return TelemetryRun(Path(root) / f"point_{objective}_bs{batch_size}")


def _measure(dm, objective: str, measure_epochs: int, telemetry=None):
    """(steps/sec, cost payload|None) for one (datamodule, objective)
    point; compile excluded from the timing, the cost model extracted from
    the very executable that ran."""
    from masters_thesis_tpu.models.objectives import ModelSpec

    spec = ModelSpec(objective=objective)  # model=small defaults
    result = _make_trainer(measure_epochs, telemetry=telemetry).fit(spec, dm)
    return result.steps_per_sec, result.cost_profile


def _cost_with_utilization(cost: dict | None, sps: float, platform: str):
    """Attach roofline numbers to a point's static cost payload: achieved
    FLOP/s and bytes/s follow from the MEASURED steps/sec, so this is the
    one place static compiler counters meet wall-clock throughput."""
    if not cost or not cost.get("available"):
        return cost
    from masters_thesis_tpu.telemetry.costs import utilization

    out = dict(cost)
    out["utilization"] = utilization(
        cost.get("flops_per_step"),
        cost.get("bytes_per_step"),
        sps,
        platform,
    )
    return out


def _scaling_child() -> None:
    """1-dev vs 8-dev scan-epoch throughput at fixed global batch (CPU mesh).

    Runs in a subprocess with JAX_PLATFORMS=cpu +
    --xla_force_host_platform_device_count=8 set by the parent BEFORE jax
    imports. Prints one JSON object on stdout.
    """
    _enable_compile_cache()
    from masters_thesis_tpu.data.pipeline import (
        FinancialWindowDataModule,
        bootstrap_synthetic,
    )

    data_dir = Path(__file__).resolve().parent / "data" / "bench_scaling"
    bootstrap_synthetic(data_dir, n_stocks=25, n_samples=50_000, seed=0)

    def run(n_devices: int, batch_size: int) -> float:
        dm = FinancialWindowDataModule(
            data_dir, lookback_window=60, target_window=30, stride=90,
            batch_size=batch_size,
        )
        dm.prepare_data(verbose=False)
        dm.setup()
        from masters_thesis_tpu.models.objectives import ModelSpec

        trainer = _make_trainer(
            6,
            strategy="single_device" if n_devices == 1 else "tpu_xla",
            n_devices=n_devices,
        )
        result = trainer.fit(ModelSpec(objective="mse"), dm)
        return result.steps_per_sec

    global_batch = 8
    sps_1 = run(1, global_batch)  # 1 device x 8 windows/step
    sps_8 = run(8, 1)  # 8 devices x 1 window/step, pmean over the mesh
    speedup = sps_8 / sps_1 if sps_1 > 0 else 0.0
    # Sharding overhead at the weak-scaling program shape: 8 devices x 8
    # windows/step vs ONE device running the same 64-window step unsharded.
    # That ratio isolates what sharding costs — partitioning, psum
    # collectives, per-device dispatch (ideal 1.0); on real chips each
    # device brings its own compute, so this program shape IS the
    # weak-scaling step. On the virtual mesh all devices share one host
    # core, and single-shot readings produced "efficiencies" of 0.69–1.16
    # for the SAME program across r3/r4 captures (XLA:CPU batch
    # nonlinearity + host noise) — so this is measured as the MEDIAN of
    # interleaved replicas with the spread reported, and the per-device
    # n=2/4 curve points (which only re-sampled the same noise) are gone
    # (VERDICT r4).
    reps = 3
    unsharded: list[float] = []
    sharded: list[float] = []
    for _ in range(reps):  # interleave sides so host drift hits both
        unsharded.append(run(1, 64))
        sharded.append(run(8, 8))
    med_u = statistics.median(unsharded)
    med_s = statistics.median(sharded)
    print(
        json.dumps(
            {
                "strong_fixed_global_batch": {
                    "global_batch": global_batch,
                    "steps_per_sec_1dev": round(sps_1, 2),
                    "steps_per_sec_8dev": round(sps_8, 2),
                    "speedup_8dev": round(speedup, 3),
                    "efficiency": round(speedup / 8.0, 3),
                },
                "sharding_overhead_same_total_work": {
                    "global_batch": 64,
                    "replicas": reps,
                    "steps_per_sec_1dev_unsharded": [
                        round(v, 2) for v in unsharded
                    ],
                    "steps_per_sec_8dev_sharded": [
                        round(v, 2) for v in sharded
                    ],
                    "median_1dev": round(med_u, 2),
                    "median_8dev": round(med_s, 2),
                    "ratio_8dev_vs_1dev": round(
                        med_s / med_u if med_u > 0 else 0.0, 3
                    ),
                    # Conservative interval: worst and best replica pairing.
                    "ratio_bounds": [
                        round(min(sharded) / max(unsharded), 3)
                        if max(unsharded) > 0 else 0.0,
                        round(max(sharded) / min(unsharded), 3)
                        if min(unsharded) > 0 else 0.0,
                    ],
                },
            }
        )
    )


def _run_scaling_subprocess() -> dict | None:
    env = _pin_cpu(dict(os.environ))
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    try:
        out = subprocess.run(
            [sys.executable, __file__, "--scaling-child"],
            env=env,
            # 8 CPU-mesh fits (strong pair + 3 replicas of the sharded and
            # unsharded sharding-overhead sides).
            timeout=3000,
            check=True,
            capture_output=True,
            text=True,
        )
        return json.loads(out.stdout.strip().splitlines()[-1])
    except Exception as exc:  # never let the scaling probe kill the bench
        print(f"scaling subprocess failed: {exc!r}", file=sys.stderr)
        # CalledProcessError's repr omits the child's output — surface it,
        # or the failure is undiagnosable after the fact.
        for stream in ("stdout", "stderr"):
            text = getattr(exc, stream, None)
            if text:
                print(f"child {stream} tail: {text[-500:]}", file=sys.stderr)
        return None


def _fused_pair_enabled() -> bool:
    from masters_thesis_tpu.ops.lstm_kernel import pair_fusion_enabled

    return pair_fusion_enabled()


# The relay can also wedge MID-measurement — after a passing probe — which
# would hang this process inside an epoch dispatch with no JSON line ever
# printed (the probe only guards backend INIT). Every TPU-touching
# measurement therefore runs in a watchdog subprocess: a hang costs that
# SECTION (or degrades the headline to the CPU path), never the one JSON
# line the driver records. Children enable the persistent XLA compile
# cache (_enable_compile_cache), so the extra process startups re-trace
# but rarely re-compile. The headline budget must absorb a COLD cache
# (environment resets wipe ~/.cache): a healthy-but-cold epoch-program
# compile through the relay ran past 1200s on 2026-07-31, and the
# watchdog SIGKILLing a healthy TPU child is itself the documented wedge
# trigger (docs/OPERATIONS.md) — so the cap is sized for the cold case.
POINT_TIMEOUT_HEADLINE_S = 2400.0
POINT_TIMEOUT_AUX_S = 700.0


def _enable_compile_cache() -> None:
    from masters_thesis_tpu.utils import enable_persistent_compilation_cache

    enable_persistent_compilation_cache()


def _point_pack_width(batch_size: int, objective: str) -> int:
    """Windows per Pallas program the kernel scheduler would pack for this
    point's flattened row count on a TPU backend (1 = one window per
    program, the serial fallback). Computed from the same fits predicate
    the pair recurrence uses, so the reported width tracks the scheduler
    rather than guessing. batch_size=1 is the single-program path."""
    from masters_thesis_tpu.models.objectives import ModelSpec
    from masters_thesis_tpu.ops.lstm_kernel import pair_fits, window_pack_width

    if batch_size <= 1:
        return 1
    spec = ModelSpec(objective=objective)
    return window_pack_width(
        batch_size * N_STOCKS,
        N_STOCKS,
        lambda rows: pair_fits(
            60, rows, spec.hidden_size, spec.dropout > 0, 4
        ),
    )


def _grad_sync_stats(objective: str) -> dict:
    """Gradient-sync footprint of the flat update path at this model shape:
    collectives per step (one per flat dtype buffer — the count TA206 pins
    to 1) and the bytes one step's pmean reduces. Derived from the view
    table (train/flatparams.py), not measured — the numbers are exact."""
    import jax
    import jax.numpy as jnp

    from masters_thesis_tpu.models.objectives import ModelSpec
    from masters_thesis_tpu.train.flatparams import (
        flat_size_bytes,
        flatten_spec,
        num_buffers,
    )

    spec = ModelSpec(objective=objective)
    module = spec.build_module()
    shapes = jax.eval_shape(
        module.init,
        jax.random.key(0),
        jnp.zeros((1, 60, spec.input_size), jnp.float32),
    )
    fspec = flatten_spec(shapes["params"])
    return {
        "collectives_per_step": num_buffers(fspec),
        "grad_reduce_bytes": flat_size_bytes(fspec),
    }


def _point_child(objective: str, batch_size: int, epochs: int) -> None:
    """Measure one (objective, batch_size) point; prints one JSON line."""
    _enable_compile_cache()
    from masters_thesis_tpu.data.pipeline import FinancialWindowDataModule

    data_dir = Path(__file__).resolve().parent / "data" / "bench_synthetic"
    dm = FinancialWindowDataModule(
        data_dir, lookback_window=60, target_window=30, stride=90,
        batch_size=batch_size,
    )
    dm.prepare_data(verbose=False)
    dm.setup()
    tel = _point_telemetry(objective, batch_size)
    rec = None
    if tel is None:
        # No telemetry run to hang the recorder off — attach a standalone
        # one under the parent-chosen dir (MTT_FLIGHTREC_DIR) so a watchdog
        # SIGTERM still leaves a crashdump explaining where the point died.
        flight_dir = os.environ.get("MTT_FLIGHTREC_DIR")
        if flight_dir:
            from masters_thesis_tpu.telemetry.flightrec import FlightRecorder

            rec = FlightRecorder(flight_dir)
            rec.beat(phase="point")
    # With telemetry on, Trainer.fit attaches the recorder to tel's run dir
    # itself (telemetry/run.py attach_flight_recorder is idempotent).
    sps, cost = _measure(dm, objective, epochs, telemetry=tel)
    if rec is not None:
        rec.close()
    if tel is not None:
        tel.close()
    import jax

    platform = jax.devices()[0].platform
    print(json.dumps({
        "steps_per_sec": sps,
        "platform": platform,
        "windows_per_epoch": len(dm.train_range),
        "pack_width": _point_pack_width(batch_size, objective),
        "grad_sync": _grad_sync_stats(objective),
        "telemetry": None if tel is None else str(tel.run_dir),
        # Static cost model + roofline attribution for this measured point
        # (None when the backend reports no cost model — the parent still
        # writes a ledger row from the measured steps/sec alone).
        "cost": _cost_with_utilization(cost, sps, platform),
    }))


# After a watchdog timeout the child gets SIGTERM and this long to write
# its crashdump before SIGKILL. The flight recorder's dump is sub-second;
# the margin covers a loaded host.
TERM_GRACE_S = 15.0


def _point_crash_dir(objective: str, batch_size: int) -> Path:
    """Where a point child's flight recorder writes crashdump/heartbeat:
    the point's telemetry run dir when --telemetry-dir is on (the recorder
    attaches there), else a dedicated dir under data/."""
    root = os.environ.get("MTT_TELEMETRY_DIR")
    base = (
        Path(root)
        if root
        else Path(__file__).resolve().parent / "data" / "bench_crash"
    )
    return base / f"point_{objective}_bs{batch_size}"


def _failure(
    objective: str, batch_size: int, reason: str, rc: int | None,
    stdout: str | None, stderr: str | None,
) -> dict:
    """A failed point's record: what died, its output tails, and the
    child's crashdump when the flight recorder got one out. This is what
    MULTICHIP-style point records previously lost (always-empty "tail")."""
    tail = "\n".join(
        f"[{name}] {text[-500:].strip()}"
        for name, text in (("stdout", stdout), ("stderr", stderr))
        if text and text.strip()
    )
    crash = _point_crash_dir(objective, batch_size) / "crashdump.json"
    record = {
        "failed": True,
        "point": f"{objective}/bs={batch_size}",
        "reason": reason,
        "rc": rc,
        "tail": tail,
        "crashdump": str(crash) if crash.exists() else None,
    }
    print(
        f"point {record['point']} {reason}"
        + (f" rc={rc}" if rc is not None else "")
        + (f"; crashdump: {record['crashdump']}" if record["crashdump"]
           else "")
        + (f"\n{tail}" if tail else ""),
        file=sys.stderr,
    )
    return record


def _point_ok(point: dict | None) -> bool:
    return point is not None and not point.get("failed")


def _measure_point(
    objective: str, batch_size: int, epochs: int, timeout_s: float,
    force_cpu: bool = False,
) -> dict | None:
    """Watchdogged measurement; a failure record dict (``failed: True``,
    with output tails and any crashdump path) on hang/crash — logged,
    never raised. A hung child gets SIGTERM first so its flight recorder
    dumps crashdump.json, then SIGKILL after TERM_GRACE_S.

    ``force_cpu`` pins the child to the CPU backend the only reliable way —
    via its environment, before its jax import — so the degraded fallback
    can never touch (and hang on) the wedged relay (ADVICE r4).
    """
    env = _pin_cpu(dict(os.environ)) if force_cpu else dict(os.environ)
    env["MTT_FLIGHTREC_DIR"] = str(_point_crash_dir(objective, batch_size))
    proc = subprocess.Popen(
        [sys.executable, __file__, "--point", objective,
         str(batch_size), str(epochs)],
        cwd=Path(__file__).resolve().parent,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )
    timed_out = False
    try:
        stdout, stderr = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        timed_out = True
        proc.terminate()  # SIGTERM: let the flight recorder dump
        try:
            stdout, stderr = proc.communicate(timeout=TERM_GRACE_S)
        except subprocess.TimeoutExpired:
            proc.kill()  # too wedged even to die; no dump is coming
            stdout, stderr = proc.communicate()
    if timed_out:
        return _failure(
            objective, batch_size,
            f"hung past {timeout_s:.0f}s (mid-measurement relay wedge)",
            proc.returncode, stdout, stderr,
        )
    if proc.returncode != 0:
        return _failure(
            objective, batch_size, "crashed", proc.returncode, stdout, stderr
        )
    try:
        return json.loads((stdout or "").strip().splitlines()[-1])
    except (json.JSONDecodeError, IndexError):
        return _failure(
            objective, batch_size, "printed no JSON", proc.returncode,
            stdout, stderr,
        )


# ---------------------------------------------------------------- --serve
# Serving-latency bench: p50/p99/QPS/shed% of the AOT predict engine under
# a paced open-loop load, measured through the telemetry registry. Exits
# nonzero if ANY ok response was delivered past its deadline — the serving
# contract (serve/server.py) says late answers are rejected, never served.
SERVE_BUCKETS = (1, 4, 8)
SERVE_REQUESTS = 200
SERVE_STOCKS = 25
SERVE_LOOKBACK = 60
SERVE_FEATURES = 3
# Latency target the p99 is scored against (vs_baseline > 1 = under
# target). 50 ms is the interactive-serving budget from ROADMAP item 1.
BASELINE_SERVE_P99_MS = 50.0


def _program_cache_dir() -> Path:
    """On-disk exported-program cache shared across bench runs, so repeat
    ``--serve`` invocations boot warm (MTT_PROGRAM_CACHE overrides)."""
    env = os.environ.get("MTT_PROGRAM_CACHE")
    if env:
        return Path(env)
    return Path(__file__).resolve().parent / "results" / "program_cache"


def _serve_bench() -> int:
    """One JSON line: serve_p99_latency_ms + detail.serve block."""
    import tempfile

    # The serving bench measures the request path (queue + admission +
    # AOT dispatch + deadline enforcement), which is backend-agnostic;
    # pin CPU so a wedged relay can never hang the gate.
    _pin_cpu_in_process()
    import numpy as np

    import jax
    import jax.numpy as jnp

    from masters_thesis_tpu.models.objectives import ModelSpec
    from masters_thesis_tpu.serve.engine import PredictEngine
    from masters_thesis_tpu.serve.program_cache import ProgramCache
    from masters_thesis_tpu.serve.server import PredictServer
    from masters_thesis_tpu.telemetry import TelemetryRun

    t0 = time.perf_counter()
    spec = ModelSpec(
        objective="mse", hidden_size=32, num_layers=1, dropout=0.0,
        kernel_impl="xla",
    )
    module = spec.build_module()
    params = module.init(
        jax.random.key(0),
        jnp.zeros((1, SERVE_LOOKBACK, SERVE_FEATURES), jnp.float32),
    )["params"]
    # Warm-start policy: the bench boots against the persistent on-disk
    # program cache, so repeat runs (and their ledger rows) measure the
    # production restart path — zero compiles — not a cold compile burst.
    cache = ProgramCache(_program_cache_dir())
    engine = PredictEngine(
        spec, params,
        n_stocks=SERVE_STOCKS, lookback=SERVE_LOOKBACK,
        n_features=SERVE_FEATURES, buckets=SERVE_BUCKETS,
        program_cache=cache,
    )
    tel_dir = os.environ.get("MTT_TELEMETRY_DIR")
    tmp_ctx = tempfile.TemporaryDirectory() if tel_dir is None else None
    tel_root = Path(tel_dir) / "serve" if tel_dir else Path(tmp_ctx.name)
    tel = TelemetryRun(tel_root, run_id="serve-bench")
    server = PredictServer(engine, telemetry=tel, max_wait_s=0.002)
    server.start()
    # Deadline: generous vs the measured batch time, so the bench scores
    # latency under a feasible SLO (admission sheds the excess if the
    # machine is slow — that's the mechanism under test, not a failure).
    batch_s = server.service_model.batch_s
    deadline_s = max(0.05, 20.0 * batch_s)
    # Open-loop pacing at ~75% of the engine's measured capacity: the
    # steady-state regime (an unpaced flood only measures the admission
    # controller; capacity overruns still shed and are reported).
    gap_s = batch_s / (0.75 * engine.max_bucket)
    rng = np.random.default_rng(0)
    windows = rng.standard_normal(
        (8, SERVE_STOCKS, SERVE_LOOKBACK, SERVE_FEATURES)
    ).astype(np.float32)
    pending = []
    for i in range(SERVE_REQUESTS):
        pending.append(server.submit(windows[i % 8], deadline_s=deadline_s))
        time.sleep(gap_s)
    results = [p.result(timeout=120.0) for p in pending]
    stats = server.stop()
    tel.snapshot_metrics()
    tel.close()
    if tmp_ctx is not None:
        tmp_ctx.cleanup()
    # Belt and braces: recheck delivery times from the caller's side too.
    client_late = sum(
        1 for p, r in zip(pending, results)
        if r.ok and r.delivered_ts > p.request.deadline_ts
    )
    late = int(stats["late_deliveries"]) + client_late
    requests = stats["requests"] or 1
    p99 = stats["p99_ms"]
    result = {
        "metric": "serve_p99_latency_ms",
        "value": None if p99 is None else round(p99, 3),
        "unit": "ms",
        "vs_baseline": (
            None if not p99 else round(BASELINE_SERVE_P99_MS / p99, 3)
        ),
        "detail": {
            "device": engine.platform,
            "wall_s": round(time.perf_counter() - t0, 1),
            "serve": {
                "requests": stats["requests"],
                "completed": stats["completed"],
                "shed": stats["shed"],
                "shed_pct": round(100.0 * stats["shed"] / requests, 2),
                "late_rejected": stats["late_converted"],
                "late_deliveries": late,
                "errors": stats["errors"],
                "p50_ms": (
                    None if stats["p50_ms"] is None
                    else round(stats["p50_ms"], 3)
                ),
                "p99_ms": None if p99 is None else round(p99, 3),
                "qps": round(stats["qps"], 2),
                "deadline_ms": round(deadline_s * 1e3, 1),
                "buckets": list(SERVE_BUCKETS),
                "compile_events": engine.compile_events,
                "cache_hits": engine.cache_hits,
                "program_cache": cache.stats(),
                # Latency attribution from the per-request spans: where a
                # completed request's wall actually went, and why sheds
                # happened (categories from serve/server.py shed_category).
                "queue_wait_share": (
                    None if stats["queue_wait_share"] is None
                    else round(stats["queue_wait_share"], 4)
                ),
                "compute_share": (
                    None if stats["compute_share"] is None
                    else round(stats["compute_share"], 4)
                ),
                "shed_by_reason": stats["shed_by_reason"],
            },
        },
    }
    try:
        from masters_thesis_tpu.telemetry.ledger import (
            DEFAULT_LEDGER_PATH,
            append_record,
            ledger_record,
        )

        path = Path(__file__).resolve().parent / DEFAULT_LEDGER_PATH
        round_id = os.environ.get("MTT_BENCH_ROUND") or time.strftime(
            "%Y%m%dT%H%M%S"
        )
        append_record(path, ledger_record(
            point="serve/p99",
            round_id=round_id,
            platform=engine.platform,
            steps_per_sec=None,
            objective="mse",
            p99_latency_ms=p99,
            p50_latency_ms=stats["p50_ms"],
            qps=stats["qps"],
            shed=stats["shed"],
            queue_wait_share=stats["queue_wait_share"],
            compute_share=stats["compute_share"],
        ))
    except Exception as exc:  # noqa: BLE001 — observability, not the bench
        print(f"perf ledger append failed: {exc!r}", file=sys.stderr)
    print(json.dumps(result))
    if late:
        print(
            f"serve bench: {late} response(s) delivered past their "
            "deadline — the no-late-answers contract is broken",
            file=sys.stderr,
        )
        return 1
    return 0


# ------------------------------------------------------- --serve-sustained
# Sustained-load fleet bench: a 4-replica FleetServer on disjoint CPU
# submeshes, driven by an open-loop QPS ramp until the SLO breaks. Emits
# the knee QPS (last sustainable stage), per-replica utilization, and the
# cold-vs-warm fleet restart time — warm boots from the exported-program
# cache the cold boot populated and must perform ZERO compiles. Exits
# nonzero on any late delivery, a compiling warm boot, or a warm fleet
# that cannot serve.
SUSTAINED_REPLICAS = 4
SUSTAINED_BUCKETS = (1, 4, 8)
SUSTAINED_STAGE_S = 1.5
SUSTAINED_RAMP = 1.4
SUSTAINED_MAX_STAGES = 7
SUSTAINED_SHED_PCT_MAX = 10.0


def _serve_sustained_bench() -> int:
    """One JSON line: serve_knee_qps + restart timings; two ledger rows."""
    import tempfile

    # Four replicas need >= 4 devices: force the 8-device virtual CPU
    # mesh BEFORE anything imports jax (the flag is read at backend init).
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count=8"
        ).strip()
    _pin_cpu_in_process()
    import numpy as np

    import jax
    import jax.numpy as jnp

    from masters_thesis_tpu.models.objectives import ModelSpec
    from masters_thesis_tpu.resilience.supervisor import ReplicaRestartPolicy
    from masters_thesis_tpu.serve.engine import PredictEngine
    from masters_thesis_tpu.serve.fleet import FleetServer, partition_meshes
    from masters_thesis_tpu.serve.program_cache import ProgramCache

    t0 = time.perf_counter()
    spec = ModelSpec(
        objective="mse", hidden_size=32, num_layers=1, dropout=0.0,
        kernel_impl="xla",
    )
    module = spec.build_module()
    params = module.init(
        jax.random.key(0),
        jnp.zeros((1, SERVE_LOOKBACK, SERVE_FEATURES), jnp.float32),
    )["params"]
    meshes = partition_meshes(SUSTAINED_REPLICAS)
    # Fresh cache dir per run so the first boot is genuinely cold; the
    # second boot of the SAME config measures the production restart path.
    cache_ctx = tempfile.TemporaryDirectory()
    cache = ProgramCache(cache_ctx.name)

    # Live telemetry plane over the ramp: the fleet's serve.request spans
    # feed a manually-ticked SLO engine plus a /metrics exposition server
    # the bench scrapes at every stage boundary — so the JSON line records
    # WHERE on the QPS ladder each alert first fired (detail.alerts), and
    # the alert knee can be cross-checked against the measured knee.
    from masters_thesis_tpu.telemetry import TelemetryRun

    tel_ctx = tempfile.TemporaryDirectory()
    tel = TelemetryRun(Path(tel_ctx.name) / "serve-sustained")

    def factory_for(m):
        return lambda: PredictEngine(
            spec, params,
            n_stocks=SERVE_STOCKS, lookback=SERVE_LOOKBACK,
            n_features=SERVE_FEATURES, buckets=SUSTAINED_BUCKETS,
            mesh=m, program_cache=cache,
        )

    factories = {f"r{i}": factory_for(m) for i, m in enumerate(meshes)}

    def boot():
        fleet = FleetServer(
            factories, max_wait_s=0.002,
            restart_policy=ReplicaRestartPolicy(backoff_s=0.01),
            telemetry=tel,
        )
        t_boot = time.perf_counter()
        fleet.start()
        return fleet, time.perf_counter() - t_boot

    def fleet_compiles(fleet):
        return sum(
            r.engine.compile_events
            for r in fleet.replicas.values() if r.engine is not None
        )

    def fleet_cache_hits(fleet):
        return sum(
            r.engine.cache_hits
            for r in fleet.replicas.values() if r.engine is not None
        )

    fleet, restart_cold_s = boot()
    cold_compiles = fleet_compiles(fleet)
    platform = fleet.replicas["r0"].engine.platform

    batch_s = max(r.service_model.batch_s for r in fleet.replicas.values())
    deadline_s = max(0.05, 20.0 * batch_s)
    slo_ms = deadline_s * 1e3
    capacity_qps = SUSTAINED_REPLICAS * max(SUSTAINED_BUCKETS) / batch_s
    rng = np.random.default_rng(0)
    windows = rng.standard_normal(
        (8, SERVE_STOCKS, SERVE_LOOKBACK, SERVE_FEATURES)
    ).astype(np.float32)

    # SLO rules scaled to the ramp's 1.5s stages (the defaults' 60s/300s
    # windows would never fill here): one tick per stage boundary, so a
    # rule fires the first stage its windows breach. The engine is ticked
    # from THIS thread only — single-writer, same contract as the
    # monitor-thread mode the servers use.
    import urllib.request

    from masters_thesis_tpu.telemetry.exposition import attach_exposition
    from masters_thesis_tpu.telemetry.slo import SLOEngine, SLORule

    _fast = 2.0 * SUSTAINED_STAGE_S
    _slow = 8.0 * SUSTAINED_STAGE_S
    slo_rules = [
        SLORule(
            "p99-latency", "p99_latency", threshold=deadline_s,
            fast_window_s=_fast, slow_window_s=_slow,
        ),
        SLORule(
            "shed-rate", "shed_pct", threshold=SUSTAINED_SHED_PCT_MAX,
            fast_window_s=_fast, slow_window_s=_slow,
        ),
        SLORule(
            "error-budget-burn", "burn_rate", threshold=2.0,
            fast_window_s=_fast, slow_window_s=_slow,
        ),
    ]
    slo_engine = SLOEngine(tel.run_dir, rules=slo_rules, sink=tel.sink)
    expo = attach_exposition(tel, port=0, slo=slo_engine)
    alert_timeline: list[dict] = []
    alert_first_fire: dict[str, float] = {}
    metrics_scrapes = 0

    def scrape_stage(qps: float) -> list[str]:
        """Tick the SLO engine over the stage's spans, scrape /metrics
        (the pull path a real Prometheus would take), note first fires."""
        nonlocal metrics_scrapes
        state = slo_engine.tick()
        body = urllib.request.urlopen(
            expo.url + "/metrics", timeout=10
        ).read().decode()
        if "mtt_slo_firing" in body:
            metrics_scrapes += 1
        firing = sorted(state.get("firing") or [])
        for rule in firing:
            alert_first_fire.setdefault(rule, round(qps, 2))
        alert_timeline.append(
            {"offered_qps": round(qps, 2), "firing": firing}
        )
        return firing

    def run_stage(qps: float) -> dict:
        gap = 1.0 / qps
        pendings = []
        t_end = time.monotonic() + SUSTAINED_STAGE_S
        i = 0
        while time.monotonic() < t_end:
            pendings.append(
                fleet.submit(windows[i % 8], deadline_s=deadline_s)
            )
            i += 1
            time.sleep(gap)
        ok_lat: list[float] = []
        shed = 0
        for p in pendings:
            r = p.result(timeout=60.0)
            if r.ok:
                ok_lat.append(r.latency_s * 1e3)
            elif r.status == "shed":
                shed += 1
        n = len(pendings) or 1
        ok_lat.sort()
        p99 = (
            ok_lat[min(len(ok_lat) - 1, int(0.99 * len(ok_lat)))]
            if ok_lat else None
        )
        return {
            "offered_qps": round(qps, 2),
            "requests": len(pendings),
            "completed": len(ok_lat),
            "shed_pct": round(100.0 * shed / n, 2),
            "p99_ms": None if p99 is None else round(p99, 3),
        }

    # Open-loop ramp: x1.4 per stage from 25% of nominal capacity until
    # p99 breaks the SLO or the shed fraction exceeds the bound. The knee
    # is the LAST sustainable stage — what an operator provisions to.
    stages: list[dict] = []
    knee = None
    qps = max(1.0, 0.25 * capacity_qps)
    for _ in range(SUSTAINED_MAX_STAGES):
        stage = run_stage(qps)
        stage["alerts_firing"] = scrape_stage(qps)
        stage["sustainable"] = (
            stage["completed"] > 0
            and stage["shed_pct"] <= SUSTAINED_SHED_PCT_MAX
            and stage["p99_ms"] is not None
            and stage["p99_ms"] <= slo_ms
        )
        stages.append(stage)
        if not stage["sustainable"]:
            break
        knee = stage
        qps *= SUSTAINED_RAMP
    stats = fleet.stop()
    # Cooldown: with load off, the breach windows age out and two clean
    # ticks (clear_ticks=2) resolve whatever fired at the knee — the
    # fire->resolve round trip, observed through the same live plane.
    resolved_rules: list[str] = []
    if alert_first_fire:
        deadline = time.monotonic() + 4.0 * SUSTAINED_STAGE_S
        while time.monotonic() < deadline:
            time.sleep(0.5 * SUSTAINED_STAGE_S)
            state = slo_engine.tick()
            if not state.get("firing"):
                break
        resolved_rules = sorted(
            set(alert_first_fire) - set(slo_engine.state().get("firing") or [])
        )
    util = {
        name: round(rep["utilization"], 4)
        for name, rep in stats["replicas"].items()
    }
    late = int(stats["late_deliveries"])

    # Warm restart: the same fleet config booted against the cache the
    # cold boot just populated — the production restart path. It must be
    # zero-compile AND actually serve.
    fleet2, restart_warm_s = boot()
    warm_compiles = fleet_compiles(fleet2)
    warm_hits = fleet_cache_hits(fleet2)
    warm_pend = [
        fleet2.submit(windows[i % 8], deadline_s=deadline_s)
        for i in range(8)
    ]
    warm_ok = sum(1 for p in warm_pend if p.result(timeout=60.0).ok)
    stats2 = fleet2.stop()
    late += int(stats2["late_deliveries"])
    cache_stats = cache.stats()
    cache_ctx.cleanup()
    expo.close()
    slo_engine.stop()
    tel.close()
    tel_ctx.cleanup()

    knee_qps = None if knee is None else knee["offered_qps"]
    # The alert plane's view of the knee: the lowest offered QPS at which
    # ANY rule first fired. A healthy plane agrees with the measured knee
    # to within one ramp stage (x1.4).
    alert_knee_qps = (
        min(alert_first_fire.values()) if alert_first_fire else None
    )
    result = {
        "metric": "serve_knee_qps",
        "value": knee_qps,
        "unit": "qps",
        "detail": {
            "device": platform,
            "wall_s": round(time.perf_counter() - t0, 1),
            "sustained": {
                "replicas": SUSTAINED_REPLICAS,
                "buckets": list(SUSTAINED_BUCKETS),
                "deadline_ms": round(slo_ms, 1),
                "stages": stages,
                "knee": knee,
                "utilization": util,
                "late_deliveries": late,
                "deaths": int(stats["deaths"]),
                "restart_cold_s": round(restart_cold_s, 3),
                "restart_warm_s": round(restart_warm_s, 3),
                "restart_speedup": (
                    None if restart_warm_s <= 0
                    else round(restart_cold_s / restart_warm_s, 2)
                ),
                "cold_compiles": cold_compiles,
                "warm_compiles": warm_compiles,
                "warm_cache_hits": warm_hits,
                "warm_served_ok": warm_ok,
                "program_cache": cache_stats,
            },
            "alerts": {
                "rules": [r.name for r in slo_rules],
                "first_fire_qps": alert_first_fire,
                "alert_knee_qps": alert_knee_qps,
                "resolved_after_cooldown": resolved_rules,
                "timeline": alert_timeline,
                "metrics_scrapes": metrics_scrapes,
            },
        },
    }
    try:
        from masters_thesis_tpu.telemetry.ledger import (
            DEFAULT_LEDGER_PATH,
            append_record,
            ledger_record,
        )

        path = Path(__file__).resolve().parent / DEFAULT_LEDGER_PATH
        round_id = os.environ.get("MTT_BENCH_ROUND") or time.strftime(
            "%Y%m%dT%H%M%S"
        )
        append_record(path, ledger_record(
            point="serve/knee_qps",
            round_id=round_id,
            platform=platform,
            steps_per_sec=None,
            objective="mse",
            knee_qps=knee_qps,
            p99_at_knee_ms=None if knee is None else knee["p99_ms"],
            shed_pct_at_knee=None if knee is None else knee["shed_pct"],
            replica_utilization=util,
            alert_knee_qps=alert_knee_qps,
            alert_first_fire=alert_first_fire,
        ))
        append_record(path, ledger_record(
            point="serve/restart_s",
            round_id=round_id,
            platform=platform,
            steps_per_sec=None,
            objective="mse",
            restart_s=round(restart_warm_s, 3),
            restart_cold_s=round(restart_cold_s, 3),
            cold_compiles=cold_compiles,
            warm_compiles=warm_compiles,
            warm_cache_hits=warm_hits,
        ))
    except Exception as exc:  # noqa: BLE001 — observability, not the bench
        print(f"perf ledger append failed: {exc!r}", file=sys.stderr)
    print(json.dumps(result))
    failed = []
    if late:
        failed.append(f"{late} late deliveries (no-late-answers broken)")
    if warm_compiles:
        failed.append(
            f"warm boot compiled {warm_compiles} program(s) — the "
            "exported-program cache did not take the restart cold path "
            "to zero"
        )
    if not warm_ok:
        failed.append("warm fleet served zero ok responses")
    if failed:
        print("serve-sustained: " + "; ".join(failed), file=sys.stderr)
        return 1
    return 0


# --------------------------------------------------------- --serve-stacked
# Multi-tenant stacked-inference bench: ONE StackedPredictEngine serves R
# checkpoints through one AOT program per bucket, ramped open-loop to its
# knee at each R against a solo-engine baseline. The headline is aggregate
# model-answers/sec (R x delivered QPS at the knee): a stacked dispatch
# answers for all R tenants at once, so aggregate throughput must scale
# well past the solo engine while per-request p99 stays bounded. The bench
# also drives a lane hot-swap and a replica kill under live load — both
# must deliver zero late answers, and the swap zero new compiles. Exits
# nonzero on any invariant or scaling-criteria miss.
STACKED_SERVE_LANES = (1, 2, 4, 8)
STACKED_SERVE_BUCKETS = (1, 4, 8)
STACKED_SERVE_STOCKS = 4
STACKED_SERVE_LOOKBACK = 4
STACKED_SERVE_STAGE_S = 1.2
STACKED_SERVE_RAMP = 1.5
STACKED_SERVE_MAX_STAGES = 6
STACKED_SERVE_SHED_PCT_MAX = 10.0
STACKED_SERVE_MIN_SCALE = 3.0  # R=8 aggregate answers/sec >= 3x solo
STACKED_SERVE_MAX_P99_X = 2.0  # R=8 p99 <= 2x solo p99, matched load
# Fixed offered load for the tail-latency comparison. Knee p99 is an
# overload artifact (each engine's last sustainable stage sits at a
# different depth past saturation), so the <=2x bound is judged at one
# common light load that every R sustains.
STACKED_SERVE_REF_QPS = 400.0


def _serve_stacked_bench() -> int:
    """One JSON line: stacked R-scaling; ledger rows serve_stacked/R=<r>."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count=8"
        ).strip()
    _pin_cpu_in_process()
    import numpy as np

    import jax
    import jax.numpy as jnp

    from masters_thesis_tpu.models.objectives import ModelSpec
    from masters_thesis_tpu.serve.engine import PredictEngine, resolve_buckets
    from masters_thesis_tpu.serve.server import PredictServer
    from masters_thesis_tpu.serve.stacked import StackedPredictEngine

    t0 = time.perf_counter()
    if "--buckets" in sys.argv:
        buckets = resolve_buckets(sys.argv[sys.argv.index("--buckets") + 1])
    else:
        buckets = resolve_buckets(
            os.environ.get("MTT_SERVE_BUCKETS") or STACKED_SERVE_BUCKETS
        )
    # Deliberately tiny geometry: stacked serving pays R x the lane
    # compute inside one dispatch, so the win is amortized DISPATCH
    # overhead — the regime universe cross-section serving actually runs
    # in (many tenants, small per-window compute).
    k, t, f = STACKED_SERVE_STOCKS, STACKED_SERVE_LOOKBACK, SERVE_FEATURES
    spec = ModelSpec(
        objective="mse", hidden_size=8, num_layers=1, dropout=0.0,
        kernel_impl="xla",
    )
    module = spec.build_module()
    max_r = max(STACKED_SERVE_LANES)
    params = [
        module.init(
            jax.random.key(seed), jnp.zeros((1, t, f), jnp.float32)
        )["params"]
        for seed in range(max_r + 1)  # +1: the lane-swap candidate
    ]
    rng = np.random.default_rng(0)
    windows = rng.standard_normal((8, k, t, f)).astype(np.float32)
    late_total = 0

    def run_stage(server, qps: float, deadline_s: float, r_lanes: int,
                  slo_ms: float) -> dict:
        gap = 1.0 / qps
        pendings = []
        t_end = time.monotonic() + STACKED_SERVE_STAGE_S
        i = 0
        while time.monotonic() < t_end:
            # Fan the offered load across one tenant per lane, so the
            # per-tenant admission accounting runs under real load.
            pendings.append(server.submit(
                windows[i % 8], deadline_s, tenant=f"t{i % r_lanes}"
            ))
            i += 1
            time.sleep(gap)
        ok_lat: list[float] = []
        shed = 0
        for p in pendings:
            r = p.result(timeout=60.0)
            if r.ok:
                ok_lat.append(r.latency_s * 1e3)
            elif r.status == "shed":
                shed += 1
        n = len(pendings) or 1
        ok_lat.sort()
        p99 = (
            ok_lat[min(len(ok_lat) - 1, int(0.99 * len(ok_lat)))]
            if ok_lat else None
        )
        stage = {
            "offered_qps": round(qps, 2),
            "requests": len(pendings),
            "completed": len(ok_lat),
            "delivered_qps": round(len(ok_lat) / STACKED_SERVE_STAGE_S, 2),
            "shed_pct": round(100.0 * shed / n, 2),
            "p99_ms": None if p99 is None else round(p99, 3),
        }
        stage["sustainable"] = (
            stage["completed"] > 0
            and stage["shed_pct"] <= STACKED_SERVE_SHED_PCT_MAX
            and stage["p99_ms"] is not None
            and stage["p99_ms"] <= slo_ms
        )
        return stage

    def run_ramp(engine, r_lanes: int) -> dict:
        nonlocal late_total
        server = PredictServer(engine, max_wait_s=0.002)
        server.start()
        batch_s = server.service_model.batch_s
        deadline_s = max(0.05, 20.0 * batch_s)
        slo_ms = deadline_s * 1e3
        # One fixed light-load stage first: the matched point every R's
        # tail latency is compared at (knee p99 depends on overload depth).
        ref = run_stage(
            server, STACKED_SERVE_REF_QPS, deadline_s, r_lanes, slo_ms
        )
        stages: list[dict] = []
        knee = None
        qps = max(1.0, 0.25 * engine.max_bucket / batch_s)
        for _ in range(STACKED_SERVE_MAX_STAGES):
            stage = run_stage(server, qps, deadline_s, r_lanes, slo_ms)
            stages.append(stage)
            if not stage["sustainable"]:
                break
            knee = stage
            qps *= STACKED_SERVE_RAMP
        stats = server.stop()
        late_total += int(stats["late_deliveries"])
        knee_delivered = 0.0 if knee is None else knee["delivered_qps"]
        return {
            "lanes": r_lanes,
            "deadline_ms": round(slo_ms, 1),
            "ref_qps": STACKED_SERVE_REF_QPS,
            "ref_p99_ms": ref["p99_ms"],
            "stages": stages,
            "knee_qps": None if knee is None else knee["offered_qps"],
            "p99_at_knee_ms": None if knee is None else knee["p99_ms"],
            "shed_pct_at_knee": None if knee is None else knee["shed_pct"],
            "delivered_qps_at_knee": knee_delivered,
            "answers_per_sec": round(r_lanes * knee_delivered, 2),
            "compile_events": int(engine.compile_events),
            "tenants": stats.get("tenants"),
            "late_deliveries": int(stats["late_deliveries"]),
        }

    # Solo baseline: the single-checkpoint engine every prior round
    # benched — the stacked engine's scaling is judged against it.
    solo_engine = PredictEngine(
        spec, params[0], n_stocks=k, lookback=t, n_features=f,
        buckets=buckets,
    )
    solo = run_ramp(solo_engine, 1)
    platform = solo_engine.platform

    ramps: dict[int, dict] = {}
    engines: dict[int, StackedPredictEngine] = {}
    for r_lanes in STACKED_SERVE_LANES:
        eng = StackedPredictEngine(
            spec, params[:r_lanes], n_stocks=k, lookback=t,
            n_features=f, buckets=buckets,
        )
        engines[r_lanes] = eng
        ramps[r_lanes] = run_ramp(eng, r_lanes)

    # Lane hot-swap under live load on the widest stack: zero new
    # compiles, zero late answers, siblings bit-untouched.
    eng = engines[max_r]
    swap_server = PredictServer(eng, max_wait_s=0.002)
    swap_server.start()
    swap_deadline_s = max(0.05, 20.0 * swap_server.service_model.batch_s)
    swap_qps = max(
        4.0, 0.5 * (ramps[max_r]["knee_qps"] or 8.0)
    )
    gx = eng.golden_batch(min(2, eng.max_bucket), seed=5)
    pre_a, pre_b = eng.predict(gx)
    baseline_compiles = eng.compile_events
    pendings = []
    n_swap_requests = max(16, int(swap_qps * STACKED_SERVE_STAGE_S))
    for i in range(n_swap_requests):
        if i == n_swap_requests // 2:
            eng.set_lane(max_r - 1, params[max_r])
        pendings.append(swap_server.submit(
            windows[i % 8], swap_deadline_s, tenant=f"t{i % max_r}"
        ))
        time.sleep(1.0 / swap_qps)
    swap_ok = sum(1 for p in pendings if p.result(timeout=60.0).ok)
    swap_stats = swap_server.stop()
    late_total += int(swap_stats["late_deliveries"])
    post_a, post_b = eng.predict(gx)
    siblings_bitwise = all(
        np.array_equal(pre_a[:, r, :], post_a[:, r, :])
        and np.array_equal(pre_b[:, r, :], post_b[:, r, :])
        for r in range(max_r) if r != max_r - 1
    )
    swap = {
        "lane": max_r - 1,
        "requests": n_swap_requests,
        "served_ok": swap_ok,
        "compile_delta": int(eng.compile_events - baseline_compiles),
        "late_deliveries": int(swap_stats["late_deliveries"]),
        "siblings_bitwise": siblings_bitwise,
    }

    # Replica kill under load: a 2-replica stacked fleet loses one to an
    # injected dispatch crash; every request resolves explicitly and
    # nothing is delivered late.
    from masters_thesis_tpu.resilience import faults
    from masters_thesis_tpu.resilience.supervisor import ReplicaRestartPolicy
    from masters_thesis_tpu.serve.fleet import FleetServer, partition_meshes

    meshes = partition_meshes(2)

    def factory_for(m):
        return lambda: StackedPredictEngine(
            spec, params[:4], n_stocks=k, lookback=t, n_features=f,
            buckets=buckets, mesh=m,
        )

    fleet = FleetServer(
        {f"r{i}": factory_for(m) for i, m in enumerate(meshes)},
        max_wait_s=0.002,
        hang_timeout_s=2.0,
        restart_policy=ReplicaRestartPolicy(backoff_s=0.01),
    )
    fleet.start()
    plan = faults.FaultPlan(faults=[faults.FaultSpec(
        point="serve.replica_dispatch", kind="raise",
        attempt=None, match={"replica": "r0"},
    )])
    faults.install_plan(plan)
    try:
        chaos_pend = [
            fleet.submit(windows[i % 8], deadline_s=2.0)
            for i in range(30)
        ]
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if fleet.replicas["r0"].state == "dead":
                break
            time.sleep(0.01)
        chaos_results = [p.result(timeout=10.0) for p in chaos_pend]
    finally:
        faults.clear_plan()
    chaos_stats = fleet.stop()
    late_total += int(chaos_stats["late_deliveries"])
    chaos_bad = sorted({
        r.status for r in chaos_results
        if r.status not in ("ok", "shed", "rejected_late")
    })
    chaos = {
        "replicas": 2,
        "lanes": 4,
        "deaths": int(chaos_stats["deaths"]),
        "n_live_after": int(chaos_stats["n_live"]),
        "late_deliveries": int(chaos_stats["late_deliveries"]),
        "non_explicit_statuses": chaos_bad,
    }

    solo_aps = solo["answers_per_sec"]
    top = ramps[max_r]
    scale_x = (
        None if not solo_aps
        else round(top["answers_per_sec"] / solo_aps, 2)
    )
    p99_x = (
        None
        if not solo["ref_p99_ms"] or not top["ref_p99_ms"]
        else round(top["ref_p99_ms"] / solo["ref_p99_ms"], 2)
    )
    result = {
        "metric": "serve_stacked_answers_per_sec",
        "value": top["answers_per_sec"],
        "unit": "answers/s",
        "detail": {
            "device": platform,
            "wall_s": round(time.perf_counter() - t0, 1),
            "buckets": list(buckets),
            "window": [k, t, f],
            "solo": solo,
            "stacked": {str(r): ramps[r] for r in STACKED_SERVE_LANES},
            "scale_x_vs_solo": scale_x,
            "p99_x_vs_solo": p99_x,
            "lane_swap": swap,
            "replica_kill": chaos,
            "late_deliveries": late_total,
        },
    }
    try:
        from masters_thesis_tpu.telemetry.ledger import (
            DEFAULT_LEDGER_PATH,
            append_record,
            ledger_record,
        )

        path = Path(__file__).resolve().parent / DEFAULT_LEDGER_PATH
        round_id = os.environ.get("MTT_BENCH_ROUND") or time.strftime(
            "%Y%m%dT%H%M%S"
        )
        for r_lanes in STACKED_SERVE_LANES:
            row = ramps[r_lanes]
            append_record(path, ledger_record(
                point=f"serve_stacked/R={r_lanes}",
                round_id=round_id,
                platform=platform,
                steps_per_sec=None,
                objective="mse",
                knee_qps=row["knee_qps"],
                p99_at_knee_ms=row["p99_at_knee_ms"],
                ref_p99_ms=row["ref_p99_ms"],
                shed_pct_at_knee=row["shed_pct_at_knee"],
                answers_per_sec=row["answers_per_sec"],
                solo_answers_per_sec=solo_aps,
                buckets=list(buckets),
            ))
    except Exception as exc:  # noqa: BLE001 — observability, not the bench
        print(f"perf ledger append failed: {exc!r}", file=sys.stderr)
    print(json.dumps(result))

    failed = []
    if late_total:
        failed.append(
            f"{late_total} late deliveries (no-late-answers broken)"
        )
    if swap["compile_delta"]:
        failed.append(
            f"lane swap compiled {swap['compile_delta']} program(s) — a "
            "row write must never retrace"
        )
    if not swap["siblings_bitwise"]:
        failed.append("lane swap perturbed a sibling lane's outputs")
    if not swap["served_ok"]:
        failed.append("zero ok responses through the lane swap")
    if chaos["deaths"] < 1:
        failed.append("injected crash never killed the victim replica")
    if chaos["n_live_after"] < 1 and chaos["deaths"]:
        failed.append("no stacked replica survived the kill")
    if chaos["non_explicit_statuses"]:
        failed.append(
            f"non-explicit request outcomes {chaos['non_explicit_statuses']}"
        )
    if scale_x is None or scale_x < STACKED_SERVE_MIN_SCALE:
        failed.append(
            f"R={max_r} aggregate scaling {scale_x}x < "
            f"{STACKED_SERVE_MIN_SCALE}x solo"
        )
    if p99_x is None or p99_x > STACKED_SERVE_MAX_P99_X:
        failed.append(
            f"R={max_r} matched-load p99 {p99_x}x solo exceeds the "
            f"{STACKED_SERVE_MAX_P99_X}x bound"
        )
    if failed:
        print("serve-stacked: " + "; ".join(failed), file=sys.stderr)
        return 1
    return 0


def _detail_cost(cost: dict | None) -> dict | None:
    """The JSON-line's `detail.cost`: the roofline essentials of the
    headline point (full payloads live in the ledger/telemetry stream)."""
    if not cost:
        return None
    util = cost.get("utilization") or {}
    return {
        "program": cost.get("program"),
        "available": cost.get("available"),
        "flops_per_step": cost.get("flops_per_step"),
        "bytes_per_step": cost.get("bytes_per_step"),
        "peak_memory_bytes": cost.get("peak_bytes"),
        "arithmetic_intensity": util.get("arithmetic_intensity"),
        "flops_utilization_pct": util.get("flops_utilization_pct"),
        "regime": util.get("regime"),
    }


def _append_perf_ledger(points: list[tuple[str, int, dict]]) -> str | None:
    """One schema-versioned row per successful measured point, appended to
    results/perf_ledger.jsonl under a shared round id (MTT_BENCH_ROUND or
    this run's timestamp). Ledger I/O must never cost the run its JSON
    line — any failure is logged to stderr and swallowed."""
    if not points:
        return None
    try:
        from masters_thesis_tpu.telemetry.ledger import (
            DEFAULT_LEDGER_PATH,
            append_record,
            ledger_record,
        )

        path = Path(__file__).resolve().parent / DEFAULT_LEDGER_PATH
        round_id = os.environ.get("MTT_BENCH_ROUND") or time.strftime(
            "%Y%m%dT%H%M%S"
        )
        for objective, batch_size, point in points:
            cost = point.get("cost") or {}
            util = cost.get("utilization") or {}
            meta = cost.get("meta") or {}
            append_record(path, ledger_record(
                point=f"{objective}/bs={batch_size}",
                round_id=round_id,
                platform=point.get("platform"),
                steps_per_sec=point.get("steps_per_sec"),
                objective=objective,
                batch_size=batch_size,
                mesh_shape=meta.get("mesh_shape"),
                pack_width=point.get("pack_width"),
                flops_per_step=cost.get("flops_per_step"),
                bytes_per_step=cost.get("bytes_per_step"),
                peak_memory_bytes=cost.get("peak_bytes"),
                utilization_pct=util.get("flops_utilization_pct"),
                regime=util.get("regime"),
            ))
        return str(path)
    except Exception as exc:  # noqa: BLE001 — observability, not the bench
        print(f"perf ledger append failed: {exc!r}", file=sys.stderr)
        return None


STACKED_REPLICA_COUNTS = (1, 2, 4, 8)
STACKED_EPOCHS = 6


def _stacked_child(replicas: int) -> None:
    """Measure the stacked trainer at one replica count (CPU mesh).

    Runs in a subprocess with JAX_PLATFORMS=cpu +
    --xla_force_host_platform_device_count=8 set by the parent BEFORE jax
    imports. One cell = one replica trained end-to-end through this
    child's workload (trace + compile + STACKED_EPOCHS epochs), so
    cells/hour = R * 3600 / fit-wall seconds. The program build is IN the
    measurement on purpose: the subprocess grid pays one cold build per
    cell (checkpoints and compile caches don't survive environment
    resets — docs/OPERATIONS.md) while a stack pays one build per R
    cells, and that amortization is most of the stacked win. For the
    same reason this child does NOT enable the persistent compile cache:
    a warm cache from a previous round would make the numbers depend on
    history instead of the build being measured.

    The cell itself is deliberately small (8 stocks, lookback 8, H=4):
    stacking exists for cells that UNDERFILL the device (the CP403
    regime — on real TPU even the canonical cell sits under the 1%
    utilization floor). On this 1-core CPU host only a small cell
    reproduces that regime; the canonical cell saturates the core at
    R=1 and would measure the host's arithmetic throughput, not the
    per-program overhead the stacked path removes.
    Prints one JSON object on stdout.
    """
    from masters_thesis_tpu.data.pipeline import (
        FinancialWindowDataModule,
        bootstrap_synthetic,
    )
    from masters_thesis_tpu.models.objectives import ModelSpec
    from masters_thesis_tpu.train import ReplicaSpec, StackedTrainer

    data_dir = Path(__file__).resolve().parent / "data" / "bench_stacked"
    bootstrap_synthetic(data_dir, n_stocks=8, n_samples=20_000, seed=0)
    dm = FinancialWindowDataModule(
        data_dir, lookback_window=8, target_window=4, stride=12,
        batch_size=1,
    )
    dm.prepare_data(verbose=False)
    dm.setup()
    trainer = StackedTrainer(
        max_epochs=STACKED_EPOCHS,
        gradient_clip_val=5.0,
        # No val fence: the measurement wants the pipelined epoch loop.
        check_val_every_n_epoch=STACKED_EPOCHS + 1,
        strategy="tpu_xla",
        n_devices=8,
        enable_progress_bar=False,
    )
    reps = [
        # Heterogeneous lrs/seeds: the realistic grid-cell stack, and a
        # guard against benchmarking an accidentally-broadcast program.
        ReplicaSpec(f"cell{r}", seed=r, learning_rate=1e-3 * (1 + r))
        for r in range(replicas)
    ]
    spec = ModelSpec(
        objective="mse", hidden_size=4, num_layers=1, dropout=0.0
    )
    t0 = time.perf_counter()
    result = trainer.fit(spec, dm, reps)
    fit_wall_s = time.perf_counter() - t0
    sps = result.steps_per_sec
    steps_per_epoch = (
        len(dm.train_range) // (8 * dm.batch_size)
    )
    step_s = (
        steps_per_epoch * STACKED_EPOCHS / sps if sps > 0 else float("inf")
    )
    print(json.dumps({
        "replicas": replicas,
        "epochs": STACKED_EPOCHS,
        "steps_per_epoch": steps_per_epoch,
        "steps_per_sec": round(sps, 2),
        "replica_steps_per_sec": round(sps * replicas, 2),
        "step_s": round(step_s, 2),
        "build_s": round(max(fit_wall_s - step_s, 0.0), 2),
        "fit_wall_s": round(fit_wall_s, 2),
        "cells_per_hour": round(
            replicas * 3600.0 / fit_wall_s if fit_wall_s > 0 else 0.0, 2
        ),
        "statuses": [r.status for r in result.replicas],
    }))


def _stacked_bench() -> int:
    """``bench.py --stacked``: cells/hour vs replica count R.

    One watchdog subprocess per R in STACKED_REPLICA_COUNTS (each gets a
    fresh CPU-pinned backend); per-point cells_per_hour rows land in the
    perf ledger under point="stacked/R=<r>" so ``telemetry ledger`` gates
    regressions round over round. Prints exactly one JSON line.
    """
    t0 = time.perf_counter()
    points: dict[str, dict] = {}
    failures: list[dict] = []
    for r in STACKED_REPLICA_COUNTS:
        env = _pin_cpu(dict(os.environ))
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
        try:
            out = subprocess.run(
                [sys.executable, __file__, "--stacked-child", str(r)],
                env=env,
                timeout=1200,
                check=True,
                capture_output=True,
                text=True,
            )
            points[str(r)] = json.loads(out.stdout.strip().splitlines()[-1])
        except Exception as exc:  # a dead point must not kill the bench
            print(f"stacked point R={r} failed: {exc!r}", file=sys.stderr)
            for stream in ("stdout", "stderr"):
                text = getattr(exc, stream, None)
                if text:
                    print(
                        f"child {stream} tail: {text[-500:]}",
                        file=sys.stderr,
                    )
            failures.append({"replicas": r, "reason": repr(exc)[:300]})

    ledger_path = None
    try:
        from masters_thesis_tpu.telemetry.ledger import (
            DEFAULT_LEDGER_PATH,
            append_record,
            ledger_record,
        )

        path = Path(__file__).resolve().parent / DEFAULT_LEDGER_PATH
        round_id = os.environ.get("MTT_BENCH_ROUND") or time.strftime(
            "%Y%m%dT%H%M%S"
        )
        for r_key, point in points.items():
            append_record(path, ledger_record(
                point=f"stacked/R={r_key}",
                round_id=round_id,
                platform="cpu",
                steps_per_sec=point.get("steps_per_sec"),
                objective="mse",
                batch_size=1,
                cells_per_hour=point.get("cells_per_hour"),
                stacked_replicas=point.get("replicas"),
            ))
        ledger_path = str(path)
    except Exception as exc:  # noqa: BLE001 — observability, not the bench
        print(f"perf ledger append failed: {exc!r}", file=sys.stderr)

    r1 = points.get("1", {}).get("cells_per_hour")
    r8 = points.get("8", {}).get("cells_per_hour")
    speedup = (r8 / r1) if r1 and r8 else None
    result = {
        "metric": "stacked_cells_per_hour",
        "value": r8 if r8 is not None else 0.0,
        "unit": "cells/h (R=8)",
        "detail": {
            "stacked": points,
            "cells_per_hour_R1": r1,
            "cells_per_hour_R8": r8,
            "speedup_R8_vs_R1": (
                None if speedup is None else round(speedup, 2)
            ),
            "wall_s": round(time.perf_counter() - t0, 1),
            "perf_ledger": ledger_path,
            "failures": failures,
        },
    }
    print(json.dumps(result))
    return 0 if points and not failures else 1


# Universe-scale sweep geometry: asset counts are multiples of the 8-way
# mesh so the asset axis shards without truncation; the factor counts
# cover the scalar anchor and the K-factor path. (25, 1) is the
# 25-portfolio baseline shape the FLOPs/step ratio is measured against.
#
# The RAMP (UNIVERSE_ASSET_COUNTS) is sized to the virtual-CPU harness:
# all 8 "devices" share one host, which saturates around n=128 assets
# (~150-160 MFLOP/s achieved on this kernel mix) — past that, rows/sec
# flattens and cache pressure bends it down, so monotone-rising
# utilization is only a meaningful claim on the unsaturated ramp. The
# HEADLINE point (n=2048, K=3 — the "thousands of assets" claim) is
# measured separately: it carries the FLOPs-per-step ratio against the
# baseline and the store-starvation check, not the monotonicity check.
#
# FLOPs convention: XLA's cost analysis counts a while/scan body ONCE
# (verified empirically: the epoch program's `flops` tracks the per-step
# body size, not body x trip count), so the compiled epoch program's raw
# `flops` IS the per-step cost, and CostModel.flops_per_step (which
# divides by scan length) would deflate points with more steps/epoch.
# Everything below therefore reports the raw body cost as flops/step.
UNIVERSE_ASSET_COUNTS = (8, 32, 128)
UNIVERSE_FACTOR_COUNTS = (1, 3)
UNIVERSE_BASELINE = (25, 1)
UNIVERSE_HEADLINE = (2048, 3)
UNIVERSE_BATCH = 4
UNIVERSE_EPOCHS = 2


def _universe_child(n_assets: int, k: int) -> None:
    """Measure one universe point: n_assets x K factors (8-dev CPU mesh).

    Runs in a subprocess with JAX_PLATFORMS=cpu +
    --xla_force_host_platform_device_count=8 set by the parent BEFORE jax
    imports. Two phases:

    - Phase A (throughput): the scan-epoch trainer with the ASSET axis
      sharded over the mesh (train/steps.py shard_axis='asset'), windows
      served from the memory-mapped window store. Compile excluded
      (epoch 0 absorbs it); FLOPs/step + utilization come from the
      compiled program's own cost model (telemetry/costs.py).
    - Phase B (streaming health): a short STREAM-mode fit over the same
      store-backed datamodule, read back through ``telemetry summarize``
      — the run's own data-wait starvation split plus the window_store
      line (page-fault wait vs total data wait, data/prefetch.py fault
      accounting). The store must feed the device without starving it.

    The baseline point (25, 1) is the 25-portfolio scalar shape: built
    in memory and window-sharded at its canonical batch size 2, exactly
    like the canonical bench, so the FLOPs/step ratio compares universe
    points against the real baseline program. Prints one JSON object on
    stdout.
    """
    import tempfile

    from masters_thesis_tpu.data.pipeline import (
        FinancialWindowDataModule,
        bootstrap_synthetic,
    )
    from masters_thesis_tpu.models.objectives import ModelSpec
    from masters_thesis_tpu.telemetry.costs import utilization
    from masters_thesis_tpu.train import Trainer

    baseline = (n_assets, k) == UNIVERSE_BASELINE
    batch_size = 2 if baseline else UNIVERSE_BATCH
    data_dir = (
        Path(__file__).resolve().parent
        / "data"
        / f"bench_universe_n{n_assets}K{k}"
    )
    bootstrap_synthetic(
        data_dir, n_stocks=n_assets, n_samples=4848, seed=0, n_factors=k
    )
    dm = FinancialWindowDataModule(
        data_dir,
        lookback_window=32,
        target_window=16,
        stride=48,
        batch_size=batch_size,
        engine="python",
        store_shards=None if baseline else 8,
    )
    dm.prepare_data(verbose=False)
    dm.setup()

    spec = ModelSpec(
        objective="mse",
        input_size=2 * k + 1,
        hidden_size=32,
        num_layers=1,
        dropout=0.0,
        n_factors=k,
        kernel_impl="xla",
    )
    trainer = Trainer(
        max_epochs=1 + UNIVERSE_EPOCHS,  # epoch 0 absorbs compile
        gradient_clip_val=5.0,
        check_val_every_n_epoch=10_000,  # pure train throughput
        strategy="tpu_xla",
        n_devices=8,
        shard_axis="window" if baseline else "asset",
        enable_progress_bar=False,
        enable_model_summary=False,
        seed=0,
        cost_profile=True,
    )
    result = trainer.fit(spec, dm)
    sps = result.steps_per_sec
    cost = result.cost_profile or {}
    # Per-step FLOPs = the compiled epoch program's raw cost: the cost
    # analysis counts the scan body once (see the convention note at
    # UNIVERSE_ASSET_COUNTS), so the program total IS one step's work —
    # dividing by steps/epoch (CostModel.flops_per_step) would deflate
    # long-scan points relative to the 4-step baseline program.
    body_flops = cost.get("flops")
    util = utilization(body_flops, cost.get("bytes_accessed"), sps, "cpu")

    store = None
    if not baseline:
        # Phase B: a short stream-mode fit over the same store, then read
        # the run's OWN telemetry: starvation is data-wait over
        # steady-state wall (epoch 0's compile excluded by the report),
        # and the window_store section splits page-fault wait out of it
        # — the same accounting an operator sees in `telemetry
        # summarize`. Single device at the reference batch size 1: a
        # one-window take is a contiguous zero-copy memmap slice
        # (data/window_store.py), so batches reach the prefetcher AS
        # memmaps and the fault accounting measures real page-ins
        # (shuffled multi-window takes gather into fresh arrays inside
        # `next()`, which the get-wait split already covers).
        from masters_thesis_tpu.telemetry import TelemetryRun
        from masters_thesis_tpu.telemetry.report import summarize_path

        dm_stream = FinancialWindowDataModule(
            data_dir,
            lookback_window=32,
            target_window=16,
            stride=48,
            batch_size=1,
            engine="python",
            store_shards=8,
        )
        dm_stream.prepare_data(verbose=False)  # cache hit: same store
        dm_stream.setup()
        tel_dir = Path(tempfile.mkdtemp(prefix="bench_universe_tel_"))
        tel = TelemetryRun(tel_dir)
        stream_trainer = Trainer(
            max_epochs=3,
            gradient_clip_val=5.0,
            check_val_every_n_epoch=10_000,
            strategy="single_device",
            epoch_mode="stream",
            enable_progress_bar=False,
            enable_model_summary=False,
            seed=0,
            telemetry=tel,
        )
        stream_trainer.fit(spec, dm_stream)
        tel.close()
        report = summarize_path(tel_dir)
        ws = report.get("window_store") or {}
        store = {
            "starvation_pct": round(
                report["data"]["starvation_pct"], 2
            ),
            "data_wait_s": round(report["data"]["data_wait_s"], 4),
            "fault_wait_s": ws.get("fault_wait_s"),
            "fault_share_pct": ws.get("fault_share_pct"),
            "mmap_bytes": ws.get("bytes_read"),
        }

    print(json.dumps({
        "n_assets": n_assets,
        "n_factors": k,
        "windows": len(dm.train_range),
        "batch_size": batch_size,
        "steps_per_sec": round(sps, 3),
        # Work throughput: asset rows pushed through the model per second
        # (batch windows x assets per step). THIS is what must rise with
        # n_assets — steps/sec alone falls as each step carries more work.
        "asset_rows_per_sec": round(sps * dm.batch_size * n_assets, 1),
        "flops_per_step": body_flops,
        "achieved_flops_per_sec": util.get("achieved_flops_per_sec"),
        "utilization_pct": util.get("flops_utilization_pct"),
        "store": store,
    }))


def _universe_bench() -> int:
    """``bench.py --universe``: the universe-scale n_assets x K sweep.

    One watchdog subprocess per point (fresh CPU-pinned backend each);
    per-point rows land in the perf ledger under
    ``universe/n<assets>xK<k>``. The summary carries the acceptance
    checks: utilization and asset-rows/sec monotone in n_assets at fixed
    K over the unsaturated ramp, the headline (n=2048, K=3) point's
    FLOPs/step >= 5x the 25-portfolio baseline, and data-wait starvation
    ~0% through the store at the headline point. Prints exactly one JSON
    line.
    """
    t0 = time.perf_counter()
    sweep = [UNIVERSE_BASELINE] + [
        (n, k)
        for k in UNIVERSE_FACTOR_COUNTS
        for n in UNIVERSE_ASSET_COUNTS
    ] + [UNIVERSE_HEADLINE]
    points: dict[str, dict] = {}
    failures: list[dict] = []
    for n, k in sweep:
        env = _pin_cpu(dict(os.environ))
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
        try:
            out = subprocess.run(
                [
                    sys.executable, __file__,
                    "--universe-child", str(n), str(k),
                ],
                env=env,
                timeout=1800,
                check=True,
                capture_output=True,
                text=True,
            )
            points[f"n{n}xK{k}"] = json.loads(
                out.stdout.strip().splitlines()[-1]
            )
        except Exception as exc:  # a dead point must not kill the bench
            print(
                f"universe point n={n} K={k} failed: {exc!r}",
                file=sys.stderr,
            )
            for stream in ("stdout", "stderr"):
                text = getattr(exc, stream, None)
                if text:
                    print(
                        f"child {stream} tail: {text[-500:]}",
                        file=sys.stderr,
                    )
            failures.append(
                {"n_assets": n, "n_factors": k, "reason": repr(exc)[:300]}
            )

    ledger_path = None
    try:
        from masters_thesis_tpu.telemetry.ledger import (
            DEFAULT_LEDGER_PATH,
            append_record,
            ledger_record,
        )

        path = Path(__file__).resolve().parent / DEFAULT_LEDGER_PATH
        round_id = os.environ.get("MTT_BENCH_ROUND") or time.strftime(
            "%Y%m%dT%H%M%S"
        )
        for key, point in points.items():
            store = point.get("store") or {}
            append_record(path, ledger_record(
                point=f"universe/{key}",
                round_id=round_id,
                platform="cpu",
                steps_per_sec=point.get("steps_per_sec"),
                objective="mse",
                batch_size=point.get("batch_size"),
                n_assets=point.get("n_assets"),
                n_factors=point.get("n_factors"),
                asset_rows_per_sec=point.get("asset_rows_per_sec"),
                flops_per_step=point.get("flops_per_step"),
                achieved_flops_per_sec=point.get("achieved_flops_per_sec"),
                utilization_pct=point.get("utilization_pct"),
                store_starvation_pct=store.get("starvation_pct"),
            ))
        ledger_path = str(path)
    except Exception as exc:  # noqa: BLE001 — observability, not the bench
        print(f"perf ledger append failed: {exc!r}", file=sys.stderr)

    def series(k: int, field: str) -> list:
        vals = [
            points.get(f"n{n}xK{k}", {}).get(field)
            for n in UNIVERSE_ASSET_COUNTS
        ]
        return [v for v in vals if v is not None]

    def monotone(vals: list) -> bool | None:
        if len(vals) < 2:
            return None
        return all(b >= a for a, b in zip(vals, vals[1:]))

    base = points.get(
        f"n{UNIVERSE_BASELINE[0]}xK{UNIVERSE_BASELINE[1]}", {}
    )
    headline = points.get(
        f"n{UNIVERSE_HEADLINE[0]}xK{UNIVERSE_HEADLINE[1]}", {}
    )
    base_flops = base.get("flops_per_step")
    headline_flops = headline.get("flops_per_step")
    flops_ratio = (
        round(headline_flops / base_flops, 1)
        if base_flops and headline_flops
        else None
    )
    headline_starvation = (headline.get("store") or {}).get("starvation_pct")
    checks = {
        # Monotonicity is claimed over the unsaturated RAMP only — see
        # the geometry note at UNIVERSE_ASSET_COUNTS: the shared-host
        # virtual mesh tops out ~n=128, so larger points plateau.
        "utilization_monotone": {
            f"K{k}": monotone(series(k, "utilization_pct"))
            for k in UNIVERSE_FACTOR_COUNTS
        },
        "asset_rows_monotone": {
            f"K{k}": monotone(series(k, "asset_rows_per_sec"))
            for k in UNIVERSE_FACTOR_COUNTS
        },
        "flops_ratio_vs_baseline": flops_ratio,
        "flops_ratio_ok": (
            flops_ratio is not None and flops_ratio >= 5.0
        ),
        # Starvation is judged at the HEADLINE point: per-step compute
        # grows with n_assets while the store's per-window bytes are
        # flat, so a healthy store trends to ~0% as the universe fills
        # the device (the small points are dispatch-floor bound, not
        # store bound).
        "headline_store_starvation_pct": headline_starvation,
        "store_starvation_ok": (
            headline_starvation is not None and headline_starvation < 5.0
        ),
    }
    result = {
        "metric": "universe_asset_rows_per_sec",
        "value": headline.get("asset_rows_per_sec", 0.0),
        "unit": f"asset rows/s (n={UNIVERSE_HEADLINE[0]}, "
        f"K={UNIVERSE_HEADLINE[1]})",
        "detail": {
            "universe": points,
            "checks": checks,
            "wall_s": round(time.perf_counter() - t0, 1),
            "perf_ledger": ledger_path,
            "failures": failures,
        },
    }
    print(json.dumps(result))
    return 0 if points and not failures else 1


def main() -> None:
    if "--telemetry-dir" in sys.argv:
        # Export before the first watchdog child spawns: points write their
        # event streams under <dir>/point_<objective>_bs<bs>, and the
        # parent records the bench envelope under <dir>/bench.
        i = sys.argv.index("--telemetry-dir")
        try:
            os.environ["MTT_TELEMETRY_DIR"] = str(Path(sys.argv[i + 1]))
        except IndexError:
            print("--telemetry-dir needs a path argument", file=sys.stderr)
            sys.exit(2)
    if "--preflight" in sys.argv:
        # Gate the benchmark on the static Pass-3 lints first (jax-free,
        # sub-second): a lock-order inversion or unguarded counter in the
        # serving stack corrupts the very numbers this run exists to
        # produce, and an event-schema drift breaks the summarize tooling
        # that reads them.
        import masters_thesis_tpu
        from masters_thesis_tpu.analysis.concurrency import lint_concurrency
        from masters_thesis_tpu.analysis.contracts import lint_contracts
        from masters_thesis_tpu.analysis.findings import format_report
        from masters_thesis_tpu.analysis.spmd import lint_spmd

        pkg_root = Path(masters_thesis_tpu.__file__).parent
        static = lint_concurrency([pkg_root], package_root=pkg_root)
        static += lint_contracts(
            [pkg_root],
            package_root=pkg_root,
            schema_path=pkg_root / "analysis" / "event_schema.json",
        )
        # Pass 4: a rank-divergent collective schedule wedges the very
        # fleet the benchmark is about to time.
        static += lint_spmd(
            [
                pkg_root / "train",
                pkg_root / "parallel",
                pkg_root / "resilience",
                pkg_root / "telemetry",
            ],
            package_root=pkg_root,
        )
        if static:
            print(format_report(static), file=sys.stderr)
            sys.exit(2)
        print(
            "preflight: concurrency + contract + spmd lint ok",
            file=sys.stderr,
        )

        # Then the tracelint trace-time audit: a recompile / transfer /
        # sharding regression makes every number below meaningless, so
        # fail loudly before burning the measurement budget.
        from masters_thesis_tpu.analysis.traceaudit import run_trace_audit

        # stacked_replicas=3 also audits the stacked program (TA207: one
        # batched all-reduce per dtype buffer per step, one compile).
        findings = run_trace_audit(stacked_replicas=3)
        if findings:
            print(format_report(findings), file=sys.stderr)
            sys.exit(2)
        print("preflight: trace audit ok", file=sys.stderr)
        # Serving twin (SV301–SV308: zero recompiles, no implicit
        # transfers, warm-cache zero-compile boot, single-death survival,
        # one stacked program per bucket at any lane count, zero-compile
        # lane hot-swap) runs in a child so its forced 8-device CPU mesh
        # can never leak into this process's backend selection.
        import subprocess

        serve_pf = subprocess.run(
            [sys.executable, "-m", "masters_thesis_tpu.serve", "preflight"],
            cwd=Path(__file__).resolve().parent,
            timeout=600,
        )
        if serve_pf.returncode != 0:
            print(
                "preflight: serve preflight failed "
                f"(exit {serve_pf.returncode})",
                file=sys.stderr,
            )
            sys.exit(2)
        print("preflight: serve audit ok", file=sys.stderr)
        # Fleet supervisor smoke (jax-free, runs as a child like serve):
        # a 2-rank fleet loses a rank to SIGKILL and must relaunch from
        # committed progress bit-identically, and a deterministic rank
        # loss must elastically resize — a long bench run leans on
        # exactly this recovery path when a host dies mid-sweep.
        fleet_pf = subprocess.run(
            [
                sys.executable, "-m", "masters_thesis_tpu.resilience",
                "fleet", "--selfcheck",
            ],
            cwd=Path(__file__).resolve().parent,
            timeout=600,
        )
        if fleet_pf.returncode != 0:
            print(
                "preflight: fleet selfcheck failed "
                f"(exit {fleet_pf.returncode})",
                file=sys.stderr,
            )
            sys.exit(2)
        print("preflight: fleet recovery ok", file=sys.stderr)
    degraded, probe_attempts = _ensure_responsive_backend()
    from masters_thesis_tpu.data.pipeline import (
        FinancialWindowDataModule,
        bootstrap_synthetic,
    )

    data_dir = Path(__file__).resolve().parent / "data" / "bench_synthetic"
    bootstrap_synthetic(data_dir, n_stocks=N_STOCKS, n_samples=N_SAMPLES, seed=0)

    t0 = time.perf_counter()
    bench_tel = None
    if os.environ.get("MTT_TELEMETRY_DIR"):
        from masters_thesis_tpu.telemetry import TelemetryRun

        bench_tel = TelemetryRun(Path(os.environ["MTT_TELEMETRY_DIR"]) / "bench")
        bench_tel.event(
            "bench_started", degraded=degraded, probe_attempts=probe_attempts
        )
    # Failed point records (reason + output tails + crashdump path from the
    # child's flight recorder) survive into detail.failures — the driver's
    # per-round capture previously recorded such deaths as `"tail": ""`.
    failures: list[dict] = []
    # Every successful measured point lands one append-only row in
    # results/perf_ledger.jsonl (objective, batch_size, point record);
    # `python -m masters_thesis_tpu.telemetry ledger` diffs rounds.
    ledger_points: list[tuple[str, int, dict]] = []

    def collect(point: dict | None) -> dict | None:
        if point is not None and point.get("failed"):
            failures.append(point)
        return point

    headline = None
    if not degraded:
        # Healthy probe: all device-touching measurements run behind
        # watchdog subprocesses (a mid-measurement wedge must not hang
        # this process — see the watchdog comment above).
        headline = collect(_measure_point(
            "mse", 1, MEASURE_EPOCHS, POINT_TIMEOUT_HEADLINE_S
        ))
        if not _point_ok(headline):
            degraded = True
            # A mid-measurement hang is the same wedged-lease evidence a
            # failed probe is: record it so the NEXT run (within the TTL)
            # goes straight to the single-attempt probe.
            _write_probe_cache(
                False, f"headline point failed: {headline.get('reason')}"
            )
            _pin_cpu_in_process()

    # CPU fallback is ~300x slower per step: trim the measurement window so
    # the run still finishes inside a driver timeout. Measured in a
    # force_cpu subprocess (a mid-measurement wedge in the parent's backend
    # state can't leak into a child whose env pins CPU before jax imports);
    # in-process only as a last resort, with the platform pinned.
    measure_epochs = 2 if degraded else MEASURE_EPOCHS
    grad_sync = None
    pack_widths: dict[str, int | None] = {}
    if degraded:
        point = collect(_measure_point(
            "mse", 1, measure_epochs, POINT_TIMEOUT_AUX_S, force_cpu=True
        ))
        if _point_ok(point):
            value = point["steps_per_sec"]
            windows_per_epoch = point["windows_per_epoch"]
            platform = point["platform"]
            grad_sync = point.get("grad_sync")
            pack_widths["1"] = point.get("pack_width", 1)
            headline_cost = point.get("cost")
            ledger_points.append(("mse", 1, point))
        else:
            _pin_cpu_in_process()
            dm1 = FinancialWindowDataModule(
                data_dir, lookback_window=60, target_window=30, stride=90,
                batch_size=1,
            )
            dm1.prepare_data(verbose=False)
            dm1.setup()
            value, in_cost = _measure(dm1, "mse", measure_epochs)
            windows_per_epoch = len(dm1.train_range)
            import jax

            platform = jax.devices()[0].platform
            grad_sync = _grad_sync_stats("mse")
            pack_widths["1"] = 1
            headline_cost = _cost_with_utilization(in_cost, value, platform)
            ledger_points.append(("mse", 1, {
                "steps_per_sec": value, "platform": platform,
                "pack_width": 1, "cost": headline_cost,
            }))
    else:
        value = headline["steps_per_sec"]
        windows_per_epoch = headline["windows_per_epoch"]
        platform = headline["platform"]
        grad_sync = headline.get("grad_sync")
        pack_widths["1"] = headline.get("pack_width", 1)
        headline_cost = headline.get("cost")
        ledger_points.append(("mse", 1, headline))

    # Degraded (wedged relay, CPU fallback): the probe/watchdog already
    # burned its budget — measure ONLY the headline point so the one JSON
    # line is guaranteed to print inside the driver timeout; the auxiliary
    # sections go null rather than risking no measurement at all.
    nll_sps = None
    batch_sweep = {"1": round(value, 2)}
    scaling = None
    if not degraded:
        aux_epochs = max(2, MEASURE_EPOCHS // 2)
        point = collect(_measure_point("nll", 1, aux_epochs,
                                       POINT_TIMEOUT_AUX_S))
        if _point_ok(point):
            nll_sps = point["steps_per_sec"]
            ledger_points.append(("nll", 1, point))
        # Batch sweep: amortizing the per-step dispatch floor. windows/sec
        # = steps/sec * batch_size, comparable across points.
        for bs in (8, 32):
            point = collect(_measure_point("mse", bs, aux_epochs,
                                           POINT_TIMEOUT_AUX_S))
            if _point_ok(point):
                batch_sweep[str(bs)] = round(point["steps_per_sec"] * bs, 2)
                pack_widths[str(bs)] = point.get("pack_width")
                ledger_points.append(("mse", bs, point))
        scaling = _run_scaling_subprocess()
    wall = time.perf_counter() - t0

    result = {
        "metric": "train_steps_per_sec_per_chip",
        "value": round(value, 2),
        "unit": "steps/s",
        "vs_baseline": round(value / BASELINE_STEPS_PER_SEC, 3),
        "detail": {
            "windows_per_epoch": windows_per_epoch,
            "batch_size": 1,
            "measure_epochs": measure_epochs,
            "wall_s": round(wall, 1),
            "device": platform,
            "probe_attempts": probe_attempts,
            # Whether pair fusion was ENABLED (env kill-switch); the Pallas
            # pair kernel additionally requires a TPU backend and a shape
            # inside the VMEM byte budget (ops/lstm_kernel.py pair_fits) —
            # on the degraded CPU path it lowers to the scan form.
            "fused_pair_enabled": _fused_pair_enabled(),
            "nll_steps_per_sec": (
                None if nll_sps is None else round(nll_sps, 2)
            ),
            # Flat update path (train/flatparams.py): collectives per
            # compiled train step (TA206 pins this to 1) and the bytes one
            # step's fused pmean reduces across the mesh.
            "collectives_per_step": (
                None if grad_sync is None
                else grad_sync.get("collectives_per_step")
            ),
            "grad_reduce_bytes": (
                None if grad_sync is None
                else grad_sync.get("grad_reduce_bytes")
            ),
            # Sweep values are windows/sec (= steps/sec * batch_size), NOT
            # steps/sec like the top-level `value` — r4 consumers misread
            # the old flat map as steps/sec, so the unit is now explicit.
            # pack_width: windows the Pallas scheduler packs per program at
            # each point's row count (1 = serial window-per-program).
            "batch_sweep": {
                "unit": "windows_per_sec",
                "headline_unit": "steps_per_sec (top-level value)",
                "points": batch_sweep,
                "pack_width": pack_widths,
            },
            # Deprecated flat alias of batch_sweep["points"]; kept one
            # round for cross-round consumers.
            "batch_sweep_windows_per_sec": batch_sweep,
            "scaling": scaling,
            # r2/r3 artifacts exposed the strong-scaling record under this
            # key; aliased for one round so cross-round consumers keep
            # resolving it (ADVICE r3).
            "scaling_fixed_global_batch": (
                scaling.get("strong_fixed_global_batch") if scaling else None
            ),
            # Headline point's static cost model + roofline attribution
            # (telemetry/costs.py); full per-point rows go to the ledger.
            "cost": _detail_cost(headline_cost),
            "perf_ledger": _append_perf_ledger(ledger_points),
            "failures": failures,
        },
    }
    # The relay can wedge for HOURS (observed 2026-07-29: 3.5h+), far past
    # any sane probe budget. Cache every healthy TPU measurement; a
    # degraded run then carries the last one — clearly labeled with its
    # timestamp — so a transient relay outage doesn't erase the chip's
    # measured history. The headline `value` is always THIS run's fresh
    # measurement, never the cache.
    cache = data_dir / "last_tpu_measurement.json"
    if not degraded and result["detail"]["device"] == "tpu":
        from masters_thesis_tpu.utils import atomic_write_text

        atomic_write_text(
            cache,
            json.dumps({"measured_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
                        **result}),
        )
    elif degraded:
        carried = _carry_last_tpu(
            cache, Path(__file__).resolve().parent / "results"
        )
        if carried is not None:
            result["detail"]["last_known_tpu"] = carried
    if bench_tel is not None:
        bench_tel.event("bench_finished", degraded=degraded, result=result)
        bench_tel.close()
    print(json.dumps(result))


def _carry_last_tpu(cache: Path, results_dir: Path) -> dict | None:
    """The healthy-TPU measurement a degraded run should report alongside
    its CPU fallback: this run's cache if present, else the newest
    COMMITTED per-round capture artifact. Environment resets wipe data/
    (and the cache with it) while results/ is committed and survives, so
    without the artifact fallback a reset followed by a wedged relay would
    erase the chip's measured history. Carried rows are labeled with their
    source; corrupt/missing files must never cost the run its JSON line."""
    if cache.exists():
        try:
            cached = json.loads(cache.read_text())
        except (OSError, json.JSONDecodeError):
            cached = None
        if isinstance(cached, dict):
            return cached
    # Newest round first; discovered by glob so next round's artifact is
    # picked up without editing this list.
    def round_no(p: Path) -> int:
        digits = "".join(c for c in p.stem if c.isdigit())
        return int(digits) if digits else -1

    for path in sorted(
        results_dir.glob("bench_r*_tpu.json"), key=round_no, reverse=True
    ):
        try:
            row = json.loads(path.read_text().strip().splitlines()[-1])
        except (OSError, json.JSONDecodeError, IndexError):
            continue
        if (
            isinstance(row, dict)
            and isinstance(row.get("detail"), dict)
            and row["detail"].get("device") == "tpu"
        ):
            return {"carried_from": f"results/{path.name}", **row}
    return None


if __name__ == "__main__":
    if "--serve-sustained" in sys.argv:
        sys.exit(_serve_sustained_bench())
    elif "--serve-stacked" in sys.argv:
        sys.exit(_serve_stacked_bench())
    elif "--serve" in sys.argv:
        if "--telemetry-dir" in sys.argv:
            i = sys.argv.index("--telemetry-dir")
            os.environ["MTT_TELEMETRY_DIR"] = str(Path(sys.argv[i + 1]))
        sys.exit(_serve_bench())
    elif "--scaling-child" in sys.argv:
        _scaling_child()
    elif "--stacked-child" in sys.argv:
        i = sys.argv.index("--stacked-child")
        _stacked_child(int(sys.argv[i + 1]))
    elif "--stacked" in sys.argv:
        sys.exit(_stacked_bench())
    elif "--universe-child" in sys.argv:
        i = sys.argv.index("--universe-child")
        _universe_child(int(sys.argv[i + 1]), int(sys.argv[i + 2]))
    elif "--universe" in sys.argv:
        sys.exit(_universe_bench())
    elif "--point" in sys.argv:
        i = sys.argv.index("--point")
        _point_child(
            sys.argv[i + 1], int(sys.argv[i + 2]), int(sys.argv[i + 3])
        )
    else:
        main()
