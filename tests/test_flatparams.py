"""Flat-buffer update path: layout round-trips, BIT-parity vs the optax
pytree path, packed-window kernel parity, checkpoint portability.

The flat path (train/flatparams.py) exists for one reason — collapsing the
per-leaf gradient all-reduces into ONE pmean over a contiguous buffer
(TA206) — and its license to exist is bitwise equivalence: every test here
asserts exact equality, not tolerances. If a refactor breaks bit parity
with ``make_optimizer``'s chain, that is a bug in the refactor, not a
reason to loosen these asserts (the clip-norm reduction order is the only
numerically delicate part; see _leaf_square_sum).
"""

import tempfile
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import masters_thesis_tpu.ops.lstm_kernel as lk
from masters_thesis_tpu.analysis.traceaudit import (
    AUDIT_BATCH,
    AUDIT_FEATURES,
    AUDIT_LOOKBACK,
    _synthetic_split,
    count_step_collectives,
)
from masters_thesis_tpu.models.objectives import ModelSpec
from masters_thesis_tpu.parallel import (
    batch_sharding,
    global_put,
    make_data_mesh,
    replicated_sharding,
)
from masters_thesis_tpu.train.checkpoint import (
    restore_checkpoint,
    restore_opt_state,
    save_checkpoint,
)
from masters_thesis_tpu.train.flatparams import (
    FlatAdam,
    FlatOptState,
    flat_size_bytes,
    flatten,
    flatten_spec,
    num_buffers,
    unflatten,
)
from masters_thesis_tpu.train.optim import make_optimizer
from masters_thesis_tpu.train.steps import make_train_epoch


def small_spec(**kw) -> ModelSpec:
    defaults = dict(
        objective="mse", hidden_size=8, num_layers=2, dropout=0.0,
        kernel_impl="xla",
    )
    defaults.update(kw)
    return ModelSpec(**defaults)


def init_params(spec: ModelSpec, module=None):
    module = module or spec.build_module()
    return module.init(
        jax.random.key(0),
        jnp.zeros((1, AUDIT_LOOKBACK, AUDIT_FEATURES), jnp.float32),
    )["params"]


class TestLayout:
    def test_flatten_unflatten_roundtrip_bitwise(self):
        """unflatten(flatten(t)) == t exactly, mixed dtypes included."""
        tree = {
            "dense": {
                "kernel": jnp.arange(12, dtype=jnp.float32).reshape(3, 4),
                "bias": jnp.ones((4,), jnp.float32) * 0.5,
            },
            "scale": jnp.float32(2.0).reshape(()),
            "steps": jnp.arange(3, dtype=jnp.int32),
        }
        spec = flatten_spec(tree)
        bufs = flatten(tree, spec)
        # One 1-D buffer per dtype, sized to the dtype's total elements.
        assert set(bufs) == {"float32", "int32"}
        assert bufs["float32"].shape == (12 + 4 + 1,)
        assert bufs["int32"].shape == (3,)
        back = unflatten(bufs, spec)
        for a, b in zip(
            jax.tree_util.tree_leaves(tree), jax.tree_util.tree_leaves(back)
        ):
            assert a.dtype == b.dtype
            assert np.array_equal(np.asarray(a), np.asarray(b))

    def test_spec_accounting(self):
        params = init_params(small_spec())
        spec = flatten_spec(params)
        n = sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))
        assert num_buffers(spec) == 1  # all-float32 model -> one buffer
        assert flat_size_bytes(spec) == n * 4

    def test_spec_works_on_shape_structs(self):
        """flatten_spec needs only shape/dtype — eval_shape trees are enough
        (bench.py derives grad-sync stats without touching a backend)."""
        spec_model = small_spec()
        module = spec_model.build_module()
        shapes = jax.eval_shape(
            module.init,
            jax.random.key(0),
            jnp.zeros((1, AUDIT_LOOKBACK, AUDIT_FEATURES), jnp.float32),
        )["params"]
        concrete = flatten_spec(init_params(spec_model, module))
        assert flatten_spec(shapes) == concrete


class TestFlatVsPytreeParity:
    """The tentpole contract: the flat epoch program (one fused pmean, one
    fused Adam pass) is BIT-identical to the per-leaf optax path over a
    multi-epoch run on the 8-device virtual mesh — with clipping both
    triggered and untriggered, and weight decay on."""

    def _run_epochs(self, tx, spec, module, split, mesh, n_epochs=3):
        repl = replicated_sharding(mesh)
        params = init_params(spec, module)
        opt_state = tx.init(params)
        params = global_put(params, repl)
        opt_state = global_put(opt_state, repl)
        data = global_put(split, batch_sharding(mesh))
        fn = make_train_epoch(
            module, spec.window_objective(), spec.metric_keys, tx, mesh,
            batch_size=AUDIT_BATCH,
        )
        lr = global_put(jnp.float32(1e-2), repl)
        for e in range(n_epochs):
            rng = global_put(jax.random.fold_in(jax.random.key(7), e), repl)
            params, opt_state, sums = fn(params, opt_state, lr, rng, data)
        return jax.device_get(params), jax.device_get(sums)

    @pytest.mark.parametrize("clip", [0.5, None], ids=["clipped", "unclipped"])
    def test_three_epoch_bit_parity_8dev(self, clip):
        assert len(jax.devices()) == 8  # conftest forces the virtual mesh
        spec = small_spec()
        mesh = make_data_mesh(None)
        module = spec.build_module()
        split = _synthetic_split(
            mesh.size * AUDIT_BATCH * 2, np.random.default_rng(0)
        )
        p_ref, s_ref = self._run_epochs(
            make_optimizer(clip, spec.weight_decay), spec, module, split, mesh
        )
        p_flat, s_flat = self._run_epochs(
            FlatAdam(clip, spec.weight_decay), spec, module, split, mesh
        )
        ref_leaves = jax.tree_util.tree_leaves(p_ref)
        flat_leaves = jax.tree_util.tree_leaves(p_flat)
        assert len(ref_leaves) == len(flat_leaves) > 1
        for a, b in zip(ref_leaves, flat_leaves):
            assert np.array_equal(np.asarray(a), np.asarray(b))  # bitwise
        for k in s_ref:
            assert np.array_equal(s_ref[k][0], s_flat[k][0])
            assert np.array_equal(s_ref[k][1], s_flat[k][1])

    def test_flat_epoch_has_exactly_one_step_collective(self):
        """The point of the layout: the compiled epoch's while-body carries
        ONE all-reduce (the flat gradient pmean) — same count TA206 pins."""
        spec = small_spec()
        mesh = make_data_mesh(None)
        module = spec.build_module()
        split = _synthetic_split(
            mesh.size * AUDIT_BATCH * 2, np.random.default_rng(0)
        )
        repl = replicated_sharding(mesh)
        tx = FlatAdam(0.5, spec.weight_decay)
        params = global_put(init_params(spec, module), repl)
        opt_state = global_put(tx.init(jax.device_get(params)), repl)
        data = global_put(split, batch_sharding(mesh))
        fn = make_train_epoch(
            module, spec.window_objective(), spec.metric_keys, tx, mesh,
            batch_size=AUDIT_BATCH,
        )
        lowered = fn.lower(
            params, opt_state, jnp.float32(1e-2), jax.random.key(7), data
        )
        assert count_step_collectives(lowered.compile().as_text()) == 1

    def test_pytree_epoch_has_per_leaf_collectives(self):
        """Control for the TA206 counter: the optax path reduces per leaf,
        so the same counter must see MORE than one in-loop all-reduce."""
        spec = small_spec()
        mesh = make_data_mesh(None)
        module = spec.build_module()
        split = _synthetic_split(
            mesh.size * AUDIT_BATCH * 2, np.random.default_rng(0)
        )
        repl = replicated_sharding(mesh)
        tx = make_optimizer(0.5, spec.weight_decay)
        params = global_put(init_params(spec, module), repl)
        opt_state = global_put(tx.init(jax.device_get(params)), repl)
        data = global_put(split, batch_sharding(mesh))
        fn = make_train_epoch(
            module, spec.window_objective(), spec.metric_keys, tx, mesh,
            batch_size=AUDIT_BATCH,
        )
        lowered = fn.lower(
            params, opt_state, jnp.float32(1e-2), jax.random.key(7), data
        )
        n = count_step_collectives(lowered.compile().as_text())
        assert n == len(jax.tree_util.tree_leaves(jax.device_get(params)))
        assert n > 1


class TestWindowPacking:
    """VMEM-budgeted multi-window packing (ops/lstm_kernel.py): packing p
    windows into one program is a pure scheduling change — rows are
    independent across the batch axis, so packed == serial bitwise."""

    T, B, H, K = 12, 160, 8, 4

    def _inputs(self):
        rng = np.random.default_rng(1)
        x = jnp.asarray(
            rng.standard_normal((self.T, self.B, 4 * self.H)).astype(np.float32)
        )
        w = jnp.asarray(
            rng.standard_normal((self.H, 4 * self.H)).astype(np.float32) * 0.1
        )
        return x, w

    def test_pack_width_selection(self):
        def fits(rows):
            padded = -(-rows // 8) * 8
            return padded <= lk.SINGLE_TILE_MAX_ROWS and lk.single_layer_fits(
                self.T, rows, self.H, 4
            )

        # 40 windows of 4 rows; the budget admits up to 104 rows -> the
        # widest divisor of 40 with 4p <= 104 rows is 20 (80 rows).
        assert lk.window_pack_width(self.B, self.K, fits) == 20
        # Unschedulable layouts (no window_rows / non-dividing) stay serial.
        assert lk.window_pack_width(self.B, None, fits) == 1
        assert lk.window_pack_width(self.B, 3, fits) == 1
        # A fits predicate that never admits more than one window -> 1.
        assert lk.window_pack_width(self.B, self.K, lambda rows: False) == 1

    def test_packed_matches_serial_bitwise(self, monkeypatch):
        x, w = self._inputs()
        packed = lk.lstm_recurrence(x, w, impl="interpret", window_rows=self.K)
        monkeypatch.setattr(lk, "window_pack_width", lambda *a, **k: 1)
        serial = lk.lstm_recurrence(x, w, impl="interpret", window_rows=self.K)
        assert jnp.array_equal(packed, serial)

    def test_packed_matches_xla_reference(self):
        x, w = self._inputs()
        packed = lk.lstm_recurrence(x, w, impl="interpret", window_rows=self.K)
        xla = lk.lstm_recurrence(x, w, impl="xla")
        assert np.allclose(np.asarray(packed), np.asarray(xla), atol=1e-5)


class TestCheckpointPortability:
    def test_flat_opt_state_roundtrip_bitwise(self):
        """Checkpoints store moments UNFLATTENED (params-shaped pytrees) so
        the on-disk layout survives flat-buffer layout changes; restore
        re-flattens against current params. Moments must round-trip
        bitwise, count must stay int32."""
        spec = small_spec()
        params = init_params(spec)
        tx = FlatAdam(0.5, spec.weight_decay)
        state = tx.init(params)
        fs = flatten_spec(params)
        grads = {k: jnp.full_like(v, 0.25) for k, v in flatten(params, fs).items()}
        _, state = tx.update_flat(grads, state, flatten(params, fs), fs)
        with tempfile.TemporaryDirectory() as d:
            save_checkpoint(Path(d), "last", params, state, spec, {"epoch": 0})
            r_params, r_opt, _, _ = restore_checkpoint(Path(d), "last")
            template = jax.device_get(tx.init(params))
            restored = restore_opt_state(template, r_opt, params=r_params)
        assert isinstance(restored, FlatOptState)
        assert restored.count.dtype == jnp.int32
        assert int(restored.count) == 1
        for moment, ref in (("mu", state.mu), ("nu", state.nu)):
            got = getattr(restored, moment)
            assert set(got) == set(ref)
            for k in ref:
                assert np.array_equal(np.asarray(got[k]), np.asarray(ref[k]))

    def test_restore_without_params_refuses(self):
        params = init_params(small_spec())
        template = FlatAdam(None, 0.0).init(params)
        with pytest.raises(ValueError, match="params"):
            restore_opt_state(template, {"count": 0}, params=None)
