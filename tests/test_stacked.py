"""Stacked-replica training path: parity vs independent runs, bitwise
replica isolation, TA207 collective/compile invariants, stacked opt-state
checkpoint round-trip, per-replica divergence handling.

Parity contract (what is bitwise and what is not): the stacked program is
the SAME epoch body as the single-replica flat path, batched by ``vmap``
over a leading replica axis. Everything host-controlled or elementwise is
bit-identical per lane — RNG folds/splits/permutations, the fused Adam
update including the clip-norm reduction, the lr application — and replica
ISOLATION is bitwise end to end (row r of every stacked buffer is a
function of row r's inputs only). The one layer that is NOT bitwise on
XLA:CPU is the batched LSTM gemm backward: batching a gemm changes how XLA
reassociates the reduction, so gradients drift at ULP scale (~1e-9) and
compound to ~1e-6 relative in params over a few epochs. On TPU the MXU
accumulates in a shape-invariant systolic order, so this gap is
CPU-specific. The end-to-end test therefore pins the first epoch's metric
sums bitwise (identical starting params, reassociation-stable forward)
and later epochs/params to a tight tolerance, while the optimizer-layer
and isolation tests assert exact equality.
"""

import tempfile
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from masters_thesis_tpu.analysis.traceaudit import (
    AUDIT_BATCH,
    AUDIT_FEATURES,
    AUDIT_LOOKBACK,
    _synthetic_split,
    count_step_collectives,
    run_stacked_trace_audit,
)
from masters_thesis_tpu.data.pipeline import FinancialWindowDataModule
from masters_thesis_tpu.data.synthetic import SyntheticLogReturns
from masters_thesis_tpu.models.objectives import ModelSpec
from masters_thesis_tpu.parallel import (
    batch_sharding,
    global_put,
    make_data_mesh,
    replicated_sharding,
)
from masters_thesis_tpu.resilience import faults
from masters_thesis_tpu.train import ReplicaSpec, StackedTrainer
from masters_thesis_tpu.train.checkpoint import (
    restore_checkpoint,
    restore_opt_state,
    save_checkpoint,
)
from masters_thesis_tpu.train.flatparams import (
    FlatAdam,
    flatten,
    flatten_spec,
    num_buffers,
    replica_flat,
    replica_opt_state,
    stack_flat,
    stack_opt_states,
    unflatten,
)
from masters_thesis_tpu.train.steps import (
    jit_cache_size,
    make_stacked_train_epoch,
    make_train_epoch,
)

LRS = (1e-2, 5e-3, 2e-2)
SEEDS = (0, 1, 2)


def small_spec(**kw) -> ModelSpec:
    defaults = dict(
        objective="mse", hidden_size=8, num_layers=2, dropout=0.0,
        kernel_impl="xla",
    )
    defaults.update(kw)
    return ModelSpec(**defaults)


def init_params(spec: ModelSpec, module, seed: int):
    return module.init(
        jax.random.key(seed),
        jnp.zeros((1, AUDIT_LOOKBACK, AUDIT_FEATURES), jnp.float32),
    )["params"]


def epoch_rng(seed: int, epoch: int):
    return jax.random.fold_in(jax.random.key(100 + seed), epoch)


def run_independent(spec, module, split, mesh, seed, lr, n_epochs, clip=0.5):
    """One solo run through the single-replica flat epoch program."""
    repl = replicated_sharding(mesh)
    tx = FlatAdam(clip, spec.weight_decay)
    params = init_params(spec, module, seed)
    opt_state = global_put(tx.init(params), repl)
    params = global_put(params, repl)
    data = global_put(split, batch_sharding(mesh))
    fn = make_train_epoch(
        module, spec.window_objective(), spec.metric_keys, tx, mesh,
        batch_size=AUDIT_BATCH,
    )
    lr_dev = global_put(jnp.float32(lr), repl)
    sums_hist = []
    for e in range(n_epochs):
        rng = global_put(epoch_rng(seed, e), repl)
        params, opt_state, sums = fn(params, opt_state, lr_dev, rng, data)
        sums_hist.append(jax.device_get(sums))
    return jax.device_get(params), jax.device_get(opt_state), sums_hist


def run_stacked(
    spec, module, split, mesh, seeds, lrs, n_epochs, clip=0.5
):
    """The same runs as a stack: one program, R replicas."""
    repl = replicated_sharding(mesh)
    tx = FlatAdam(clip, spec.weight_decay)
    params_list = [init_params(spec, module, s) for s in seeds]
    fspec = flatten_spec(params_list[0])
    pstack = global_put(
        stack_flat([flatten(p, fspec) for p in params_list]), repl
    )
    ostack = global_put(
        stack_opt_states([tx.init(p) for p in params_list]), repl
    )
    data = global_put(split, batch_sharding(mesh))
    fn = make_stacked_train_epoch(
        module, spec.window_objective(), spec.metric_keys, tx, mesh, fspec,
        batch_size=AUDIT_BATCH,
    )
    lrs_dev = global_put(jnp.asarray(lrs, jnp.float32), repl)
    sums_hist = []
    for e in range(n_epochs):
        rngs = global_put(
            jnp.stack([epoch_rng(s, e) for s in seeds]), repl
        )
        pstack, ostack, sums = fn(pstack, ostack, lrs_dev, rngs, data)
        sums_hist.append(jax.device_get(sums))
    assert jit_cache_size(fn) == 1
    return jax.device_get(pstack), jax.device_get(ostack), sums_hist, fspec


@pytest.fixture(scope="module")
def stacked_setup():
    assert len(jax.devices()) == 8  # conftest forces the virtual mesh
    spec = small_spec()
    mesh = make_data_mesh(None)
    module = spec.build_module()
    split = _synthetic_split(
        mesh.size * AUDIT_BATCH * 2, np.random.default_rng(0)
    )
    return spec, mesh, module, split


class TestStackedVsIndependent:
    """R=3 heterogeneous (lr, seed) stacked run vs 3 solo FlatAdam runs
    over 2 epochs on the 8-device mesh."""

    def test_two_epoch_parity(self, stacked_setup):
        spec, mesh, module, split = stacked_setup
        pstack, ostack, s_hist, fspec = run_stacked(
            spec, module, split, mesh, SEEDS, LRS, n_epochs=2
        )
        for r, (seed, lr) in enumerate(zip(SEEDS, LRS)):
            p_solo, o_solo, solo_hist = run_independent(
                spec, module, split, mesh, seed, lr, n_epochs=2
            )
            solo_bufs = flatten(p_solo, fspec)
            # Epoch-0 metric sums are bitwise per replica (identical
            # starting params; the forward pass is reassociation-stable
            # at these shapes). From epoch 1 on, the forward runs on
            # ULP-drifted params, so sums get the same tight tolerance
            # as the params themselves.
            for e in range(2):
                for k in solo_hist[e]:
                    for solo_part, stacked_part in zip(
                        solo_hist[e][k], s_hist[e][k]
                    ):
                        a = np.asarray(solo_part)
                        b = np.asarray(stacked_part)[r]
                        if e == 0:
                            assert np.array_equal(a, b), (
                                f"replica {r} epoch 0 metric {k}"
                            )
                        else:
                            np.testing.assert_allclose(
                                b, a, rtol=1e-5, atol=0,
                                err_msg=f"replica {r} epoch {e} metric {k}",
                            )
            # Params/moments: tight tolerance, NOT bitwise — the batched
            # gemm backward reassociates on XLA:CPU (module docstring).
            for k, buf in solo_bufs.items():
                a, b = np.asarray(buf), np.asarray(pstack[k][r])
                np.testing.assert_allclose(b, a, rtol=1e-4, atol=1e-7)
            assert int(ostack.count[r]) == int(o_solo.count)
            for k in o_solo.mu:
                np.testing.assert_allclose(
                    np.asarray(ostack.mu[k][r]), np.asarray(o_solo.mu[k]),
                    rtol=1e-3, atol=1e-7,
                )

    def test_heterogeneous_lrs_actually_differ(self, stacked_setup):
        """Guard against a broadcast bug silently training every replica
        at the same lr: rows of the stack must NOT match each other."""
        spec, mesh, module, split = stacked_setup
        pstack, _, _, _ = run_stacked(
            spec, module, split, mesh, (0, 0, 0), LRS, n_epochs=1
        )
        for k, v in pstack.items():
            assert not np.array_equal(v[0], v[1])
            assert not np.array_equal(v[0], v[2])


class TestReplicaIsolation:
    """Row r of the stack depends on row r's (seed, lr) only: changing
    replica 2's config must leave replicas 0 and 1 BIT-identical."""

    def test_sibling_rows_bitwise_invariant(self, stacked_setup):
        spec, mesh, module, split = stacked_setup
        p_a, o_a, s_a, _ = run_stacked(
            spec, module, split, mesh, SEEDS, LRS, n_epochs=2
        )
        p_b, o_b, s_b, _ = run_stacked(
            spec, module, split, mesh, (SEEDS[0], SEEDS[1], 7),
            (LRS[0], LRS[1], 4e-2), n_epochs=2,
        )
        for r in (0, 1):
            for k in p_a:
                assert np.array_equal(p_a[k][r], p_b[k][r])
                assert np.array_equal(o_a.mu[k][r], o_b.mu[k][r])
                assert np.array_equal(o_a.nu[k][r], o_b.nu[k][r])
            for e in range(2):
                for k in s_a[e]:
                    for part_a, part_b in zip(s_a[e][k], s_b[e][k]):
                        assert np.array_equal(
                            np.asarray(part_a)[r], np.asarray(part_b)[r]
                        )
        # ... and replica 2 did change (the perturbation reached it).
        assert any(
            not np.array_equal(p_a[k][2], p_b[k][2]) for k in p_a
        )


class TestOptimizerLayerBitParity:
    """The vmapped FlatAdam fold (clip-norm included) and the per-replica
    RNG derivations are bitwise identical to their per-lane equivalents —
    the layers the stacked path adds on top of the (already bit-pinned)
    single-replica flat path."""

    def test_vmapped_update_flat_bitwise(self, stacked_setup):
        spec, _, module, _ = stacked_setup
        tx = FlatAdam(0.5, spec.weight_decay)  # clip ON: exercises the
        # _leaf_square_sum reduction under vmap
        params_list = [init_params(spec, module, s) for s in SEEDS]
        fspec = flatten_spec(params_list[0])
        pstack = stack_flat([flatten(p, fspec) for p in params_list])
        ostack = stack_opt_states([tx.init(p) for p in params_list])
        rng = np.random.default_rng(3)
        gstack = {
            k: jnp.asarray(
                rng.standard_normal(v.shape).astype(v.dtype) * 0.1
            )
            for k, v in pstack.items()
        }
        lrs = jnp.asarray(LRS, jnp.float32)

        def one(g, o, p, lr):
            u, o2 = tx.update_flat(g, o, p, fspec)
            p2 = {k: p[k] - lr * u[k].astype(p[k].dtype) for k in p}
            return p2, o2

        p_v, o_v = jax.vmap(one)(gstack, ostack, pstack, lrs)
        for r in range(len(SEEDS)):
            p_s, o_s = one(
                replica_flat(gstack, r),
                replica_opt_state(ostack, r),
                replica_flat(pstack, r),
                lrs[r],
            )
            for k in p_s:
                assert np.array_equal(np.asarray(p_v[k][r]), np.asarray(p_s[k]))
                assert np.array_equal(
                    np.asarray(o_v.mu[k][r]), np.asarray(o_s.mu[k])
                )
                assert np.array_equal(
                    np.asarray(o_v.nu[k][r]), np.asarray(o_s.nu[k])
                )
            assert int(o_v.count[r]) == int(o_s.count)

    def test_vmapped_rng_streams_bitwise(self):
        keys = jnp.stack([jax.random.key(s) for s in SEEDS])

        def derive(key):
            key = jax.random.fold_in(key, 3)
            a, b = jax.random.split(key)
            return jax.random.permutation(a, 16), jax.random.uniform(b, (4,))

        perm_v, u_v = jax.vmap(derive)(keys)
        for r, s in enumerate(SEEDS):
            perm_s, u_s = derive(jax.random.key(s))
            assert np.array_equal(np.asarray(perm_v[r]), np.asarray(perm_s))
            assert np.array_equal(np.asarray(u_v[r]), np.asarray(u_s))


class TestStackedCollectives:
    """TA207: the stacked program carries ONE batched all-reduce per dtype
    buffer per step — independent of R — and compiles exactly once."""

    @pytest.mark.parametrize("R", [1, 3])
    def test_one_batched_collective_per_buffer(self, stacked_setup, R):
        spec, mesh, module, split = stacked_setup
        repl = replicated_sharding(mesh)
        tx = FlatAdam(0.5, spec.weight_decay)
        params_list = [init_params(spec, module, s) for s in range(R)]
        fspec = flatten_spec(params_list[0])
        pstack = global_put(
            stack_flat([flatten(p, fspec) for p in params_list]), repl
        )
        ostack = global_put(
            stack_opt_states([tx.init(p) for p in params_list]), repl
        )
        data = global_put(split, batch_sharding(mesh))
        fn = make_stacked_train_epoch(
            module, spec.window_objective(), spec.metric_keys, tx, mesh,
            fspec, batch_size=AUDIT_BATCH,
        )
        lowered = fn.lower(
            pstack, ostack,
            global_put(jnp.ones((R,), jnp.float32) * 1e-2, repl),
            global_put(
                jnp.stack([jax.random.key(s) for s in range(R)]), repl
            ),
            data,
        )
        n = count_step_collectives(lowered.compile().as_text())
        assert n == num_buffers(fspec) == 1

    def test_stacked_trace_audit_clean(self, stacked_setup):
        _, mesh, _, _ = stacked_setup
        assert run_stacked_trace_audit(mesh=mesh, replicas=3, steps=2) == []

    def test_requires_flat_adam(self, stacked_setup):
        spec, mesh, module, _ = stacked_setup
        from masters_thesis_tpu.train.optim import make_optimizer

        with pytest.raises(TypeError, match="FlatAdam"):
            make_stacked_train_epoch(
                module, spec.window_objective(), spec.metric_keys,
                make_optimizer(0.5, spec.weight_decay), mesh,
                flatten_spec(init_params(spec, module, 0)),
                batch_size=AUDIT_BATCH,
            )


class TestStackedCheckpointRoundtrip:
    """A replica extracted from the stack round-trips through the
    (unflattened, params-shaped) checkpoint layout bitwise, and re-stacks
    into the same rows — the resume path StackedTrainer uses."""

    def test_replica_opt_state_roundtrip_bitwise(self, stacked_setup):
        spec, _, module, _ = stacked_setup
        tx = FlatAdam(0.5, spec.weight_decay)
        params_list = [init_params(spec, module, s) for s in SEEDS]
        fspec = flatten_spec(params_list[0])
        pstack = stack_flat([flatten(p, fspec) for p in params_list])
        ostack = stack_opt_states([tx.init(p) for p in params_list])
        # Take one real optimizer step so the moments are non-trivial.
        gstack = {k: jnp.full_like(v, 0.25) for k, v in pstack.items()}

        def one(g, o, p):
            u, o2 = tx.update_flat(g, o, p, fspec)
            return {k: p[k] - 1e-2 * u[k] for k in p}, o2

        pstack, ostack = jax.vmap(one)(gstack, ostack, pstack)

        r = 1
        params_r = unflatten(replica_flat(pstack, r), fspec)
        opt_r = replica_opt_state(ostack, r)
        with tempfile.TemporaryDirectory() as d:
            save_checkpoint(
                Path(d), "last", params_r, opt_r, spec, {"epoch": 0}
            )
            got_params, got_opt, _, _ = restore_checkpoint(Path(d), "last")
            template = jax.device_get(tx.init(params_list[r]))
            restored = restore_opt_state(
                template, got_opt, params=got_params
            )
        back_p = flatten(
            jax.tree_util.tree_map(jnp.asarray, got_params), fspec
        )
        for k in pstack:
            assert np.array_equal(np.asarray(back_p[k]), np.asarray(pstack[k][r]))
            assert np.array_equal(
                np.asarray(restored.mu[k]), np.asarray(ostack.mu[k][r])
            )
            assert np.array_equal(
                np.asarray(restored.nu[k]), np.asarray(ostack.nu[k][r])
            )
        assert int(restored.count) == int(ostack.count[r])
        # Re-stacking the restored replica reproduces the original rows.
        restacked = stack_opt_states(
            [replica_opt_state(ostack, 0), restored, replica_opt_state(ostack, 2)]
        )
        for k in ostack.mu:
            assert np.array_equal(
                np.asarray(restacked.mu[k]), np.asarray(ostack.mu[k])
            )


@pytest.fixture(scope="module")
def tiny_dm(tmp_path_factory) -> FinancialWindowDataModule:
    data_dir = tmp_path_factory.mktemp("stacked_data")
    r_stocks, r_market, alphas, betas = SyntheticLogReturns.generate(
        n_stocks=8, n_samples=4000, seed=1
    )
    np.save(data_dir / "stocks.npy", np.asarray(r_stocks))
    np.save(data_dir / "market.npy", np.asarray(r_market))
    np.save(data_dir / "alphas.npy", np.asarray(alphas))
    np.save(data_dir / "betas.npy", np.asarray(betas))
    dm = FinancialWindowDataModule(
        data_dir, lookback_window=16, target_window=8, stride=24, batch_size=2
    )
    dm.prepare_data(verbose=False)
    dm.setup()
    return dm


REPLICAS = [
    ReplicaSpec("a", 0, 1e-2),
    ReplicaSpec("b", 1, 5e-3),
    ReplicaSpec("c", 2, 2e-2),
]


def fit_spec():
    return ModelSpec(
        objective="mse", hidden_size=8, num_layers=1, dropout=0.0,
        learning_rate=1e-2,
    )


class TestStackedTrainer:
    """End-to-end driver: divergence isolation, per-replica checkpoints,
    resume contract."""

    @pytest.fixture(scope="class")
    def clean_run(self, tiny_dm, tmp_path_factory):
        ckpt = tmp_path_factory.mktemp("stacked_ckpt")
        trainer = StackedTrainer(
            max_epochs=3, gradient_clip_val=5.0,
            enable_progress_bar=False, ckpt_dir=ckpt,
        )
        return trainer.fit(fit_spec(), tiny_dm, REPLICAS), ckpt

    def test_all_replicas_train(self, clean_run):
        result, _ = clean_run
        assert [r.status for r in result.replicas] == ["active"] * 3
        for rep in result.replicas:
            losses = [h["loss/total/train"] for h in rep.history]
            assert all(np.isfinite(v) for v in losses)
            assert losses[-1] < losses[0]
            assert np.isfinite(rep.best_val_loss)
        # Heterogeneous lrs -> distinct trajectories.
        assert len({r.history[-1]["loss/total/train"]
                    for r in result.replicas}) == 3
        assert result.replica_steps_per_sec == pytest.approx(
            3 * result.steps_per_sec
        )

    def test_per_replica_checkpoints_and_resume(self, clean_run, tiny_dm):
        result, ckpt = clean_run
        for rep in REPLICAS:
            got_params, _, _, meta = restore_checkpoint(ckpt / rep.name, "last")
            assert meta["replica"]["name"] == rep.name
            assert meta["replica"]["seed"] == rep.seed
            assert meta["trainer"] == "stacked"
            assert meta["epoch"] == 2
        # Resume trains only the remaining epochs, for every replica.
        trainer = StackedTrainer(
            max_epochs=5, gradient_clip_val=5.0,
            enable_progress_bar=False, ckpt_dir=ckpt, resume=True,
        )
        resumed = trainer.fit(fit_spec(), tiny_dm, REPLICAS)
        assert resumed.epochs == 2
        assert all(len(r.history) == 2 for r in resumed.replicas)
        assert all(h["epoch"] == e for r in resumed.replicas
                   for e, h in zip((3, 4), r.history))

    def test_divergence_masks_one_replica_siblings_bitwise(
        self, clean_run, tiny_dm
    ):
        """Poison replica 1's loss readback twice: it must roll back, then
        mask — while replicas 0 and 2 finish BIT-identical to the clean
        run and the run as a whole keeps going."""
        clean, _ = clean_run
        plan = faults.FaultPlan(faults=[
            faults.FaultSpec(
                point="stacked.replica_loss", kind="nan", attempt=None,
                match={"replica": 1, "epoch": 1},
            ),
            faults.FaultSpec(
                point="stacked.replica_loss", kind="nan", attempt=None,
                match={"replica": 1, "epoch": 2},
            ),
        ])
        faults.install_plan(plan)
        try:
            trainer = StackedTrainer(
                max_epochs=3, gradient_clip_val=5.0,
                enable_progress_bar=False,
            )
            faulty = trainer.fit(fit_spec(), tiny_dm, REPLICAS)
        finally:
            faults.clear_plan()
        assert faulty.replicas[1].status == "masked"
        assert faulty.replicas[1].rollbacks == 2
        assert [faulty.replicas[r].status for r in (0, 2)] == ["active"] * 2
        for r in (0, 2):
            a = jax.tree_util.tree_leaves(clean.replicas[r].params)
            b = jax.tree_util.tree_leaves(faulty.replicas[r].params)
            assert all(np.array_equal(x, y) for x, y in zip(a, b))
            assert faulty.replicas[r].rollbacks == 0

    def test_single_fault_recovers(self, tiny_dm):
        """One transient NaN: roll back, halve lr, resume training —
        status returns to active and the final loss is finite."""
        plan = faults.FaultPlan(faults=[
            faults.FaultSpec(
                point="stacked.replica_loss", kind="nan", attempt=None,
                match={"replica": 0, "epoch": 1},
            ),
        ])
        faults.install_plan(plan)
        try:
            trainer = StackedTrainer(
                max_epochs=3, gradient_clip_val=5.0,
                enable_progress_bar=False,
            )
            result = trainer.fit(fit_spec(), tiny_dm, REPLICAS)
        finally:
            faults.clear_plan()
        rep = result.replicas[0]
        assert rep.status == "active"
        assert rep.rollbacks == 1
        assert np.isfinite(rep.history[-1]["loss/total/train"])
        # The recovery halved the lr from its configured value.
        assert rep.history[-1]["lr-Adam"] == pytest.approx(
            REPLICAS[0].learning_rate / 2
        )
