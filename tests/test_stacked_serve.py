"""Multi-tenant stacked serving (ISSUE 20): R checkpoints behind ONE
AOT predict program per bucket.

The contract under test is bitwise: every lane of the stack must answer
exactly as the solo engine serving the same checkpoint (a tenant
migrating onto the stack must not be able to observe the move), a lane
hot-swap must leave sibling lanes' outputs bit-untouched with zero
recompiles, and the stacked program-cache entry must re-key when any
lane's content changes while solo entries keep hitting.
"""

import time
from pathlib import Path

import numpy as np
import pytest

from masters_thesis_tpu.resilience import faults
from masters_thesis_tpu.resilience.faults import FaultPlan, FaultSpec
from masters_thesis_tpu.serve.queue import (
    STATUS_OK,
    STATUS_REJECTED_LATE,
    STATUS_SHED,
)

# Tiny window shape shared by every engine in this file.
K, T, F = 4, 8, 3
BUCKETS = (1, 2, 4)
R = 4


@pytest.fixture(autouse=True)
def _no_leaked_faults(monkeypatch):
    """Every test starts and ends with injection off, whatever it does."""
    monkeypatch.delenv(faults.FAULT_PLAN_ENV, raising=False)
    monkeypatch.delenv(faults.ATTEMPT_ENV, raising=False)
    yield
    faults.clear_plan()


def _tiny_spec(hidden=8):
    from masters_thesis_tpu.models.objectives import ModelSpec

    return ModelSpec(
        objective="mse", hidden_size=hidden, num_layers=1, dropout=0.0,
        kernel_impl="xla",
    )


def _init_params(spec, seed=0):
    import jax
    import jax.numpy as jnp

    module = spec.build_module()
    return module.init(
        jax.random.key(seed), jnp.zeros((1, T, F), jnp.float32)
    )["params"]


def _solo_engine(spec, params, buckets=BUCKETS, **kw):
    from masters_thesis_tpu.serve.engine import PredictEngine

    return PredictEngine(
        spec, params, n_stocks=K, lookback=T, n_features=F,
        buckets=buckets, **kw,
    )


def _stacked_engine(spec, params_list, buckets=BUCKETS, **kw):
    from masters_thesis_tpu.serve.stacked import StackedPredictEngine

    return StackedPredictEngine(
        spec, params_list, n_stocks=K, lookback=T, n_features=F,
        buckets=buckets, **kw,
    )


def _save_ckpt(d, spec, params, epoch=1):
    from masters_thesis_tpu.train.checkpoint import save_checkpoint

    save_checkpoint(
        Path(d), "best", params, {}, spec,
        meta={"epoch": epoch, "datamodule": {"lookback_window": T}},
    )


def _window(n=1, seed=0):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((n, K, T, F)).astype(np.float32)


@pytest.fixture(scope="module")
def stack_setup():
    """One warmed R=4 stack plus the 4 solo engines it must mirror
    bit-for-bit (read-only tests only — mutators build their own)."""
    spec = _tiny_spec()
    params = [_init_params(spec, seed=s) for s in range(R)]
    stacked = _stacked_engine(spec, params)
    stacked.warmup()
    solos = [_solo_engine(spec, p) for p in params]
    for s in solos:
        s.warmup()
    return spec, params, stacked, solos


# -------------------------------------------------------- bitwise parity


class TestLaneParity:
    @pytest.mark.parametrize("n", [1, 2, 4])
    def test_bitwise_parity_every_bucket(self, stack_setup, n):
        _, _, stacked, solos = stack_setup
        x = _window(n, seed=n)
        alpha, beta = stacked.predict(x)
        assert alpha.shape == (n, R, K) and beta.shape == (n, R, K)
        for lane, solo in enumerate(solos):
            sa, sb = solo.predict(x)
            # Exact equality, not allclose: the scan runs each lane
            # through the solo op sequence, so any ULP drift is a bug.
            np.testing.assert_array_equal(alpha[:, lane, :], sa)
            np.testing.assert_array_equal(beta[:, lane, :], sb)

    def test_bitwise_parity_through_pad_path(self, stack_setup):
        _, _, stacked, solos = stack_setup
        x = _window(3, seed=7)  # pads up to bucket 4 in both engines
        alpha, beta = stacked.predict(x)
        for lane, solo in enumerate(solos):
            sa, sb = solo.predict(x)
            np.testing.assert_array_equal(alpha[:, lane, :], sa)
            np.testing.assert_array_equal(beta[:, lane, :], sb)

    def test_predict_lane_is_stack_slice(self, stack_setup):
        _, _, stacked, _ = stack_setup
        x = _window(2, seed=9)
        alpha, beta = stacked.predict(x)
        la, lb = stacked.predict_lane(x, lane=2)
        np.testing.assert_array_equal(la, alpha[:, 2, :])
        np.testing.assert_array_equal(lb, beta[:, 2, :])
        with pytest.raises(IndexError):
            stacked.predict_lane(x, lane=R)

    def test_one_program_per_bucket(self, stack_setup):
        _, _, stacked, _ = stack_setup
        assert stacked.compile_events == len(BUCKETS)

    def test_bucket_overflow_and_bad_shape(self, stack_setup):
        from masters_thesis_tpu.serve.engine import BucketOverflowError

        _, _, stacked, _ = stack_setup
        with pytest.raises(BucketOverflowError):
            stacked.predict(_window(5))
        with pytest.raises(ValueError):
            stacked.predict(np.zeros((1, K, T + 1, F), np.float32))

    def test_mismatched_lane_architecture_refused(self, stack_setup):
        from masters_thesis_tpu.serve.stacked import LaneMismatchError

        spec, params, _, _ = stack_setup
        odd = _init_params(_tiny_spec(hidden=16), seed=0)
        with pytest.raises(LaneMismatchError):
            _stacked_engine(spec, [params[0], odd])

    def test_hlo_is_structurally_lane_count_invariant(self, stack_setup):
        """The serving twin of TA207: the lane loop stays rolled, so the
        compiled module's structure (SV307's fingerprint) must not grow
        with R — only lane-dim literals in shape annotations may move."""
        from masters_thesis_tpu.serve.preflight import _hlo_fingerprint

        spec, params, stacked, _ = stack_setup
        small = _stacked_engine(spec, params[:2], buckets=(1, 2))
        small.warmup()
        for b in (1, 2):
            assert _hlo_fingerprint(small.compiled_text(b)) == \
                _hlo_fingerprint(stacked.compiled_text(b))


# -------------------------------------------------------- ensemble math


class TestEnsemble:
    def test_ensemble_stats_math(self):
        from masters_thesis_tpu.serve.stacked import ensemble_stats

        rng = np.random.default_rng(3)
        alpha = rng.standard_normal((5, R, K))
        beta = rng.standard_normal((5, R, K))
        out = ensemble_stats(alpha, beta)
        np.testing.assert_array_equal(out["alpha_mean"], alpha.mean(axis=1))
        np.testing.assert_array_equal(out["alpha_std"], alpha.std(axis=1))
        np.testing.assert_array_equal(out["beta_lo"], beta.min(axis=1))
        np.testing.assert_array_equal(out["beta_hi"], beta.max(axis=1))
        assert out["alpha_mean"].shape == (5, K)
        assert out["alpha_mean"].dtype == np.float64

    def test_ensemble_stats_rejects_non_lane_outputs(self):
        from masters_thesis_tpu.serve.stacked import ensemble_stats

        flat = np.zeros((5, K))
        with pytest.raises(ValueError):
            ensemble_stats(flat, flat)

    def test_predict_ensemble_one_dispatch(self, stack_setup):
        _, _, stacked, _ = stack_setup
        x = _window(2, seed=11)
        before = stacked.compile_events
        out = stacked.predict_ensemble(x)
        assert stacked.compile_events == before  # no retrace
        np.testing.assert_array_equal(
            out["alpha_mean"],
            np.asarray(out["alpha"], np.float64).mean(axis=1),
        )
        band = out["alpha_hi"] - out["alpha_lo"]
        assert (band >= 0).all()


# ------------------------------------------------- lane swap + isolation


class TestLaneSwap:
    def _fresh(self):
        spec = _tiny_spec()
        params = [_init_params(spec, seed=s) for s in range(3)]
        eng = _stacked_engine(spec, params)
        eng.warmup()
        return spec, params, eng

    def test_set_lane_moves_one_row_only(self):
        from masters_thesis_tpu.serve.stacked import lane_digest

        spec, params, eng = self._fresh()
        candidate = _init_params(spec, seed=99)
        x = _window(4, seed=1)
        pre_a, pre_b = eng.predict(x)
        pre_digests = eng.lane_digests()
        compiles = eng.compile_events

        new_digest = eng.set_lane(1, candidate)

        assert eng.compile_events == compiles  # zero recompiles (SV308)
        post_digests = eng.lane_digests()
        assert post_digests[1] == new_digest != pre_digests[1]
        assert post_digests[0] == pre_digests[0]
        assert post_digests[2] == pre_digests[2]
        post_a, post_b = eng.predict(x)
        for lane in (0, 2):  # siblings: bit-untouched
            np.testing.assert_array_equal(pre_a[:, lane], post_a[:, lane])
            np.testing.assert_array_equal(pre_b[:, lane], post_b[:, lane])
        # The swapped lane now answers exactly as a solo engine on the
        # candidate params.
        solo = _solo_engine(spec, candidate)
        solo.warmup()
        sa, sb = solo.predict(x)
        np.testing.assert_array_equal(post_a[:, 1], sa)
        np.testing.assert_array_equal(post_b[:, 1], sb)

    def test_stage_lane_does_not_commit(self):
        spec, params, eng = self._fresh()
        x = _window(2, seed=2)
        pre = eng.predict(x)
        pre_digests = eng.lane_digests()
        staged = eng.stage_lane(0, _init_params(spec, seed=77))
        staged_out = eng.predict(x, params=staged)
        assert not np.array_equal(staged_out[0][:, 0], pre[0][:, 0])
        # Sibling lanes inside the staged stack are already bitwise.
        np.testing.assert_array_equal(staged_out[0][:, 1], pre[0][:, 1])
        assert eng.lane_digests() == pre_digests
        np.testing.assert_array_equal(eng.predict(x)[0], pre[0])

    def test_set_lane_shape_mismatch_refused(self):
        from masters_thesis_tpu.serve.stacked import LaneMismatchError

        _, _, eng = self._fresh()
        with pytest.raises(LaneMismatchError):
            eng.set_lane(0, _init_params(_tiny_spec(hidden=16), seed=0))

    def test_try_swap_lane_commits_with_sibling_proof(self, tmp_path):
        from masters_thesis_tpu.serve.swap import CheckpointSwapper
        from masters_thesis_tpu.telemetry.events import read_events
        from masters_thesis_tpu.telemetry.run import TelemetryRun

        spec, params, eng = self._fresh()
        candidate = _init_params(spec, seed=50)
        _save_ckpt(tmp_path / "cand", spec, candidate, epoch=3)
        tel = TelemetryRun(tmp_path / "tel", run_id="swap")
        ctl = CheckpointSwapper(eng, telemetry=tel)
        x = _window(4, seed=4)
        pre = eng.predict(x)

        verdict = ctl.try_swap_lane(2, tmp_path / "cand")

        assert verdict.ok, (verdict.reason, verdict.detail)
        assert verdict.checks.get("siblings_bitwise") is True
        assert ctl.lane_committed == 1 and ctl.lane_rejected == 0
        post = eng.predict(x)
        for lane in (0, 1):
            np.testing.assert_array_equal(pre[0][:, lane], post[0][:, lane])
        assert not np.array_equal(pre[0][:, 2], post[0][:, 2])
        kinds = [e["kind"] for e in read_events(tel.run_dir / "events.jsonl")]
        assert "lane_swap_committed" in kinds

    def test_try_swap_lane_rejects_corrupt_candidate(self, tmp_path):
        from masters_thesis_tpu.serve.swap import CheckpointSwapper

        spec, params, eng = self._fresh()
        _save_ckpt(tmp_path / "cand", spec, _init_params(spec, seed=51))
        ctl = CheckpointSwapper(eng)
        pre_digests = eng.lane_digests()
        faults.install_plan(FaultPlan(faults=[FaultSpec(
            point="serve.pre_swap", kind="corrupt",
        )]))
        verdict = ctl.try_swap_lane(1, tmp_path / "cand")
        assert not verdict.ok and verdict.reason == "verify_failed"
        assert ctl.lane_rejected == 1 and ctl.lane_committed == 0
        assert eng.lane_digests() == pre_digests

    def test_try_swap_lane_requires_stacked_engine(self, tmp_path):
        from masters_thesis_tpu.serve.swap import CheckpointSwapper

        spec = _tiny_spec()
        solo = _solo_engine(spec, _init_params(spec))
        solo.warmup()
        _save_ckpt(tmp_path / "cand", spec, _init_params(spec, seed=1))
        with pytest.raises(TypeError):
            CheckpointSwapper(solo).try_swap_lane(0, tmp_path / "cand")


# ------------------------------------------- program-cache lane identity


class TestProgramCacheLaneKeys:
    def test_lane_swap_rekeys_stack_but_not_solo(self, tmp_path):
        from masters_thesis_tpu.serve.program_cache import ProgramCache

        spec = _tiny_spec()
        params = [_init_params(spec, seed=s) for s in range(2)]
        buckets = (1, 2)
        cache = ProgramCache(tmp_path / "pc")

        # Cold boot: every stacked bucket compiles and is stored.
        cold = _stacked_engine(
            spec, params, buckets=buckets, program_cache=cache
        )
        cold.warmup()
        assert cold.compile_events == len(buckets)
        assert cold.cache_hits == 0

        # Solo engine for lane 0 stores its own (lane-digest-free) entries.
        solo_cold = _solo_engine(
            spec, params[0], buckets=buckets, program_cache=cache
        )
        solo_cold.warmup()
        assert solo_cold.compile_events == len(buckets)

        # Same lanes, same order -> every stacked program hits.
        warm = _stacked_engine(
            spec, params, buckets=buckets, program_cache=cache
        )
        warm.warmup()
        assert warm.compile_events == 0
        assert warm.cache_hits == len(buckets)
        x = _window(2, seed=6)
        np.testing.assert_array_equal(
            warm.predict(x)[0], cold.predict(x)[0]
        )

        # One lane's content changes -> the stacked identity re-keys (the
        # stored golden replays the OLD lane's outputs) and recompiles...
        swapped = _stacked_engine(
            spec, [params[0], _init_params(spec, seed=9)],
            buckets=buckets, program_cache=cache,
        )
        swapped.warmup()
        assert swapped.cache_hits == 0
        assert swapped.compile_events == len(buckets)

        # ...while the unchanged SOLO program still hits every bucket.
        solo_warm = _solo_engine(
            spec, params[0], buckets=buckets, program_cache=cache
        )
        solo_warm.warmup()
        assert solo_warm.compile_events == 0
        assert solo_warm.cache_hits == len(buckets)

    def test_lane_order_is_part_of_the_key(self, tmp_path):
        from masters_thesis_tpu.serve.program_cache import ProgramCache

        spec = _tiny_spec()
        params = [_init_params(spec, seed=s) for s in range(2)]
        cache = ProgramCache(tmp_path / "pc")
        a = _stacked_engine(spec, params, buckets=(1,), program_cache=cache)
        a.warmup()
        # Same two checkpoints, reversed lanes: a different stack.
        b = _stacked_engine(
            spec, params[::-1], buckets=(1,), program_cache=cache
        )
        b.warmup()
        assert b.cache_hits == 0 and b.compile_events == 1


# ------------------------------------------------- tenancy on the server


class TestServerTenancy:
    def test_tenant_deadline_class_and_accounting(self, stack_setup):
        from masters_thesis_tpu.serve.server import PredictServer

        _, _, stacked, _ = stack_setup
        server = PredictServer(stacked, max_wait_s=0.001)
        server.start()
        try:
            server.register_tenant("quant-a", deadline_s=5.0)
            pend = [
                server.submit(_window(1, seed=i)[0], tenant="quant-a")
                for i in range(3)
            ]
            pend.append(
                server.submit(_window(1, seed=9)[0], 5.0, tenant="quant-b")
            )
            results = [p.result(timeout=10.0) for p in pend]
            assert all(r.status == STATUS_OK for r in results)
            # Stacked engines answer (R, K) per request.
            assert results[0].outputs[0].shape == (R, K)
            with pytest.raises(ValueError):
                server.submit(_window()[0], tenant="no-class")
        finally:
            stats = server.stop()
        assert stats["tenants"]["quant-a"]["admitted"] == 3
        assert stats["tenants"]["quant-b"]["admitted"] == 1
        assert stats["lanes"] == R
        assert stats["late_deliveries"] == 0


# ----------------------------------------------- chaos: replica kill, R=4


@pytest.mark.slow
def test_stacked_fleet_replica_kill_zero_late():
    """A 2-replica stacked fleet (R=4 lanes each) loses one replica to an
    injected dispatch crash mid-stream: every request resolves with an
    explicit status, nothing is delivered late, and the survivor keeps
    answering for all four tenants."""
    from masters_thesis_tpu.resilience.supervisor import ReplicaRestartPolicy
    from masters_thesis_tpu.serve.fleet import FleetServer, partition_meshes

    spec = _tiny_spec()
    params = [_init_params(spec, seed=s) for s in range(R)]
    meshes = partition_meshes(2)

    def factory_for(m):
        return lambda: _stacked_engine(
            spec, params, buckets=(1, 2), mesh=m
        )

    fleet = FleetServer(
        {f"r{i}": factory_for(m) for i, m in enumerate(meshes)},
        max_wait_s=0.002,
        hang_timeout_s=2.0,
        restart_policy=ReplicaRestartPolicy(backoff_s=0.01),
    )
    fleet.start()
    try:
        faults.install_plan(FaultPlan(faults=[FaultSpec(
            point="serve.replica_dispatch", kind="raise", attempt=1,
            match={"replica": "r0"},
        )]))
        pend = [
            fleet.submit(_window(1, seed=i)[0], deadline_s=5.0)
            for i in range(30)
        ]
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and fleet.deaths < 1:
            time.sleep(0.01)
        faults.clear_plan()
        results = [p.result(timeout=10.0) for p in pend]
        assert all(
            r.status in (STATUS_OK, STATUS_SHED, STATUS_REJECTED_LATE)
            for r in results
        )
        ok = [r for r in results if r.status == STATUS_OK]
        assert ok and all(r.outputs[0].shape == (R, K) for r in ok)
    finally:
        stats = fleet.stop()
    assert stats["deaths"] >= 1
    assert stats["late_deliveries"] == 0
    assert stats["lanes"] == R


# ------------------------------------------------------- bucket plumbing


class TestBucketConfig:
    def test_resolve_buckets_forms(self):
        from masters_thesis_tpu.serve.engine import (
            DEFAULT_BUCKETS,
            resolve_buckets,
        )

        assert resolve_buckets(None) == DEFAULT_BUCKETS
        assert resolve_buckets("1,4, 8") == (1, 4, 8)
        assert resolve_buckets("64 32") == (32, 64)
        assert resolve_buckets([8, 1, 4, 4]) == (1, 4, 8)
        with pytest.raises(ValueError):
            resolve_buckets("0,4")

    def test_serve_config_group_composes(self):
        from masters_thesis_tpu.config import compose, register_resolver

        register_resolver(
            "input_size_from_interaction", lambda i: 3 if i else 5
        )
        cfg = compose("configs")
        assert list(cfg["serve"]["buckets"]) == [1, 2, 4, 8]
        deep = compose("configs", overrides=["serve=universe"])
        assert list(deep["serve"]["buckets"]) == [1, 2, 4, 8, 16, 32, 64]
        assert deep["serve"]["max_depth"] > cfg["serve"]["max_depth"]


# ------------------------------------------------ K-factor shadow quality


class TestKFactorShadow:
    def test_infer_factors_from_feature_layout(self):
        from masters_thesis_tpu.telemetry.quality import infer_factors

        # f = 2K + 1 (windows.py: [r_stock, f_1..f_K, cross terms]).
        assert infer_factors(3) == 1
        assert infer_factors(5) == 2
        assert infer_factors(7) == 3

    def test_shadow_ols_k1_is_the_scalar_path_bitwise(self):
        """The K=1 branch must stay op-for-op the original scalar shadow
        (the drift-sketch baselines in shipped fingerprints depend on its
        exact rounding)."""
        from masters_thesis_tpu.telemetry.quality import shadow_ols

        rng = np.random.default_rng(0)
        x = rng.standard_normal((5, K, T, 3))
        alpha, beta = shadow_ols(x)
        # Inline re-statement of the original scalar algorithm.
        xs = np.asarray(x, np.float64)
        market = xs[:, 0, :, 1]
        design = np.stack([np.ones_like(market), market], axis=-1)
        gram = np.einsum("nti,ntj->nij", design, design)
        moment = np.einsum("nti,nkt->nik", design, xs[..., 0])
        coef = np.linalg.pinv(gram) @ moment
        np.testing.assert_array_equal(alpha, coef[:, 0, :])
        np.testing.assert_array_equal(beta, coef[:, 1, :])
        assert alpha.shape == (5, K) and beta.shape == (5, K)

    def test_shadow_ols_k_factor_matches_device_twin(self):
        import jax.numpy as jnp

        from masters_thesis_tpu.ops.linalg import ols_k
        from masters_thesis_tpu.telemetry.quality import shadow_ols

        n_factors = 3
        f = 2 * n_factors + 1
        rng = np.random.default_rng(1)
        x = rng.standard_normal((4, 6, T, f)).astype(np.float32)
        alpha, beta = shadow_ols(x)
        assert alpha.shape == (4, 6) and beta.shape == (4, 6, n_factors)
        # Device twin takes the sliced series directly: factor returns
        # come from stock 0's broadcast channels, regressand is channel 0.
        factors = jnp.asarray(x[:, 0, :, 1 : 1 + n_factors])  # (n, t, K)
        y = jnp.asarray(x[..., 0])  # (n, k, t)
        da, db = ols_k(factors, y)
        np.testing.assert_allclose(alpha, np.asarray(da), atol=2e-4)
        np.testing.assert_allclose(beta, np.asarray(db), atol=2e-4)

    def test_shadow_error_scores_both_loading_conventions(self):
        from masters_thesis_tpu.telemetry.quality import (
            shadow_error,
            shadow_ols,
        )

        rng = np.random.default_rng(2)
        x = rng.standard_normal((3, K, T, 7))
        alpha, beta = shadow_ols(x)  # full loadings (n, k, K)
        assert shadow_error(x, alpha, beta) < 1e-9
        # A K=1-era model ships a single loading per stock; it is scored
        # against the FIRST factor's loading: self-consistent there too.
        assert shadow_error(x, alpha, beta[..., 0]) < 1e-9
