"""Mesh/sharding helpers on the 8-device virtual CPU platform."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec

from masters_thesis_tpu.parallel import (
    DATA_AXIS,
    batch_sharding,
    make_data_mesh,
    replicated_sharding,
)


def test_full_mesh():
    mesh = make_data_mesh()
    assert mesh.size == 8
    assert mesh.axis_names == (DATA_AXIS,)


def test_submesh():
    assert make_data_mesh(2).size == 2
    with pytest.raises(ValueError):
        make_data_mesh(99)


def test_batch_sharding_splits_leading_dim():
    mesh = make_data_mesh()
    x = np.arange(16 * 3, dtype=np.float32).reshape(16, 3)
    arr = jax.device_put(x, batch_sharding(mesh))
    assert arr.sharding.spec == PartitionSpec(DATA_AXIS)
    # each device holds 16/8 = 2 rows
    shard_shapes = {s.data.shape for s in arr.addressable_shards}
    assert shard_shapes == {(2, 3)}
    np.testing.assert_array_equal(np.asarray(arr), x)


def test_replicated_sharding_copies_everywhere():
    mesh = make_data_mesh()
    x = jnp.ones((4, 4))
    arr = jax.device_put(x, replicated_sharding(mesh))
    assert len(arr.addressable_shards) == 8
    assert all(s.data.shape == (4, 4) for s in arr.addressable_shards)


def test_psum_over_mesh_matches_sum():
    mesh = make_data_mesh()
    x = np.arange(8.0, dtype=np.float32)

    def local(v):
        return jax.lax.psum(jnp.sum(v), DATA_AXIS)

    total = jax.jit(
        jax.shard_map(
            local,
            mesh=mesh,
            in_specs=PartitionSpec(DATA_AXIS),
            out_specs=PartitionSpec(),
            check_vma=False,
        )
    )(x)
    assert float(total) == pytest.approx(x.sum())
