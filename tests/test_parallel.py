"""Mesh/sharding helpers on the 8-device virtual CPU platform."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec

from masters_thesis_tpu.parallel import (
    DATA_AXIS,
    batch_sharding,
    make_data_mesh,
    replicated_sharding,
    shard_map,
)


def test_full_mesh():
    mesh = make_data_mesh()
    assert mesh.size == 8
    assert mesh.axis_names == (DATA_AXIS,)


def test_submesh():
    assert make_data_mesh(2).size == 2
    with pytest.raises(ValueError):
        make_data_mesh(99)


def test_batch_sharding_splits_leading_dim():
    mesh = make_data_mesh()
    x = np.arange(16 * 3, dtype=np.float32).reshape(16, 3)
    arr = jax.device_put(x, batch_sharding(mesh))
    assert arr.sharding.spec == PartitionSpec(DATA_AXIS)
    # each device holds 16/8 = 2 rows
    shard_shapes = {s.data.shape for s in arr.addressable_shards}
    assert shard_shapes == {(2, 3)}
    np.testing.assert_array_equal(np.asarray(arr), x)


def test_replicated_sharding_copies_everywhere():
    mesh = make_data_mesh()
    x = jnp.ones((4, 4))
    arr = jax.device_put(x, replicated_sharding(mesh))
    assert len(arr.addressable_shards) == 8
    assert all(s.data.shape == (4, 4) for s in arr.addressable_shards)


def test_psum_over_mesh_matches_sum():
    mesh = make_data_mesh()
    x = np.arange(8.0, dtype=np.float32)

    def local(v):
        return jax.lax.psum(jnp.sum(v), DATA_AXIS)

    total = jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=PartitionSpec(DATA_AXIS),
            out_specs=PartitionSpec(),
            check_vma=False,
        )
    )(x)
    assert float(total) == pytest.approx(x.sum())


def test_dp_step_matches_single_device():
    """One pjit train step over an 8-device mesh must produce the same
    parameter update as the identical global batch on one device — data
    parallelism changes the schedule, not the math (the DDP invariant)."""
    import jax.numpy as jnp
    import numpy as np

    from masters_thesis_tpu.data.pipeline import Batch
    from masters_thesis_tpu.models.objectives import ModelSpec
    from masters_thesis_tpu.train.optim import make_optimizer
    from masters_thesis_tpu.train.steps import make_train_step

    spec = ModelSpec(
        objective="combined", hidden_size=8, num_layers=1, dropout=0.0
    )
    module = spec.build_module()
    rng = np.random.default_rng(3)
    batch = Batch(
        x=rng.normal(0.1, 0.5, size=(8, 4, 12, 3)).astype(np.float32),
        y=rng.normal(0.1, 0.5, size=(8, 4, 6, 4)).astype(np.float32),
        factor=np.abs(rng.normal(size=(8, 2))).astype(np.float32),
        inv_psi=rng.uniform(1, 2, size=(8, 4)).astype(np.float32),
    )
    # numpy leaves: each step call transfers a fresh buffer, so the step's
    # donation can't delete the template between mesh configurations.
    params = jax.device_get(
        module.init(jax.random.key(0), jnp.zeros((1, 12, 3)))["params"]
    )
    tx = make_optimizer(5.0, spec.weight_decay)
    key = jax.random.key(1)
    lr = jnp.float32(1e-3)

    results = {}
    for n_dev in (1, 8):
        mesh = make_data_mesh(n_dev)
        step = make_train_step(module, spec.window_objective(), tx, mesh)
        p, _, sums = step(params, tx.init(params), lr, key, batch)
        results[n_dev] = (jax.device_get(p), jax.device_get(sums))

    p1, s1 = results[1]
    p8, s8 = results[8]
    # The invariant is exact in math, but Adam's first update is
    # ~lr*sign(g) wherever v_hat ~ 0: at a zero-gradient element an
    # epsilon-level reduction-order difference between the two compiled
    # programs (which can flip with XLA's scheduling, e.g. cached vs fresh
    # executables) amplifies into a full lr-sized step. Tolerate isolated
    # epsilon-amplified elements; fail on structural divergence — many
    # differing elements, or any diff beyond the 2*lr amplification
    # ceiling.
    leaves1 = jax.tree_util.tree_leaves(p1)
    leaves8 = jax.tree_util.tree_leaves(p8)
    n_total = sum(a.size for a in leaves1)
    n_outliers = 0
    for a, b in zip(leaves1, leaves8):
        diff = np.abs(a - b)
        assert float(diff.max(initial=0.0)) <= 2.1 * float(lr)
        n_outliers += int((diff > 1e-5 + 1e-5 * np.abs(b)).sum())
    assert n_outliers <= max(1, n_total // 100)
    assert s1["total"][0] == pytest.approx(s8["total"][0], rel=1e-5)
