"""Simulated SPMD rank for the collective-schedule audit tests.

Not a pytest module (no ``test_`` prefix): tests/test_spmd_lint.py spawns
2 of these as a simulated fleet — jax-free, so the divergence scenario
exercises exactly the forensic path a wedged DCN mesh needs. The SAME
file doubles as the static fixture: the injected rank-divergent branch
below is what ``lint_spmd`` must catch (DV701), and what the runtime
hash-chain audit must name by rank and step when it actually runs.

Usage: python tests/_spmd_worker.py <root> <rank> <world> <scenario>

Scenarios:

- ``healthy``   — every rank issues the same 4-step schedule
  (pmean + barrier per step); chains match bitwise.
- ``divergent`` — rank 1 skips the step-2 barrier via an env-derived
  rank guard; the audit must name p1 and the fork entry.
"""

from __future__ import annotations

import os
import sys
from pathlib import Path


def fleet_barrier(name: str) -> None:
    """Jax-free stand-in for parallel.mesh.fleet_barrier: records the
    schedule entry exactly like the real one (same chain vocabulary)."""
    from masters_thesis_tpu.telemetry.schedule import record_collective

    record_collective("barrier", name=name)


def main() -> None:
    root, rank, world, scenario = (
        Path(sys.argv[1]),
        int(sys.argv[2]),
        int(sys.argv[3]),
        sys.argv[4],
    )
    os.environ["JAX_PROCESS_INDEX"] = str(rank)
    os.environ["JAX_PROCESS_COUNT"] = str(world)

    from masters_thesis_tpu.telemetry import TelemetryRun
    from masters_thesis_tpu.telemetry.schedule import record_collective

    tel = TelemetryRun(root / f"p{rank}", run_id=f"spmd-p{rank}")
    rec = tel.attach_flight_recorder(heartbeat_interval_s=0.05)
    rec.beat(phase="setup")
    tel.event(
        "run_started", platform="sim", n_devices=1, strategy="spmd-sim",
        epoch_mode="scan", steps_per_epoch=1, max_epochs=4, start_epoch=0,
        objective="mse", trainer="fleet", seed=0,
    )
    # Host-divergent identity, exactly as a real rank would derive it —
    # the taint source the static lint must track into the guard below.
    proc = int(os.environ["JAX_PROCESS_INDEX"])
    for step in range(4):
        rec.beat(phase="train", epoch=step)
        record_collective("pmean", name="grads.flat", step=step)
        if scenario == "divergent" and proc == 1 and step == 2:
            # The injected SPMD bug: one rank's control flow skips a
            # barrier every other rank blocks in. mtt --spmd flags this
            # line (DV701); at runtime the hash chains fork here.
            continue
        fleet_barrier(f"epoch.{step}")
    tel.event(
        "run_finished", epochs=4, total_steps=4, steps_per_sec=40.0,
        diverged=False, best_val=0.5, epoch_compiles=1, eval_compiles=0,
    )
    tel.close()
    print("done", flush=True)


if __name__ == "__main__":
    main()
