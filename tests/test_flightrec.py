"""Flight recorder + fleet aggregation/postmortem tests.

The subprocess scenarios simulate a 2-process multi-host run (jax-free
workers — tests/_fleet_worker.py) and kill/hang one process the way real
fleets die: SIGTERM from a watchdog, and a silent hang past the heartbeat
deadline. The postmortem CLI must then name the dead/straggler process and
exit 2 — without importing jax (that's the whole point: it runs when the
backend is wedged)."""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import pytest

from masters_thesis_tpu.telemetry.__main__ import main as cli_main
from masters_thesis_tpu.telemetry.aggregate import (
    aggregate_path,
    postmortem_path,
)
from masters_thesis_tpu.telemetry.flightrec import FlightRecorder
from masters_thesis_tpu.telemetry.run import TelemetryRun, process_identity

_REPO_ROOT = Path(__file__).resolve().parent.parent
_WORKER = _REPO_ROOT / "tests" / "_fleet_worker.py"


# ---------------------------------------------------------------- recorder


def _quiet_recorder(tmp_path, **kwargs):
    """A recorder safe inside pytest: no signal handlers (pytest owns the
    main thread's handlers), no global faulthandler takeover."""
    kwargs.setdefault("install_signal_handlers", False)
    kwargs.setdefault("enable_faulthandler", False)
    kwargs.setdefault("heartbeat_interval_s", 60.0)
    return FlightRecorder(tmp_path, **kwargs)


def test_ring_buffer_is_bounded(tmp_path):
    rec = _quiet_recorder(tmp_path, ring_size=8)
    for i in range(100):
        rec.record({"kind": "epoch", "epoch": i})
    rec.dump("test")
    rec.close()
    dump = json.loads((tmp_path / "crashdump.json").read_text())
    ring = dump["ring"]
    assert len(ring) == 8
    assert [e["epoch"] for e in ring] == list(range(92, 100))
    # The last-known-state mirror survives ring eviction.
    assert dump["state"]["last_epoch"]["epoch"] == 99


def test_dump_carries_stacks_state_and_scalars(tmp_path):
    rec = _quiet_recorder(tmp_path, scalar_history=4)
    rec.beat(phase="train", epoch=7)
    rec.note(step=123, compile_count=1)
    for i in range(10):
        rec.track_scalar("loss/total/train", float(i))
    path = rec.dump("signal:SIGTERM (test)")
    rec.close()
    dump = json.loads(path.read_text())
    assert dump["reason"] == "signal:SIGTERM (test)"
    assert dump["phase"] == "train" and dump["epoch"] == 7
    assert dump["state"]["step"] == 123
    # Bounded divergence context: only the newest scalar_history values.
    assert dump["scalars"]["loss/total/train"] == [6.0, 7.0, 8.0, 9.0]
    # All-thread stacks include the frame that called dump() — this test.
    flat = "\n".join(
        line for t in dump["threads"] for line in t["stack"]
    )
    assert "test_dump_carries_stacks_state_and_scalars" in flat


def test_first_dump_per_reason_wins(tmp_path):
    rec = _quiet_recorder(tmp_path)
    rec.note(marker="first")
    rec.dump("hang: test")
    rec.note(marker="second")
    rec.dump("hang: test")  # same reason: must not overwrite
    dump = json.loads(rec.crashdump_path.read_text())
    assert dump["state"]["marker"] == "first"
    rec.dump("signal:SIGTERM")  # new reason: overwrites
    dump = json.loads(rec.crashdump_path.read_text())
    assert dump["state"]["marker"] == "second"
    rec.close()


def test_hang_watchdog_dumps_without_progress(tmp_path):
    rec = _quiet_recorder(
        tmp_path, heartbeat_interval_s=0.05, hang_timeout_s=0.2
    )
    rec.beat(phase="train", epoch=0)
    deadline = time.monotonic() + 10.0
    while not rec.crashdump_path.exists() and time.monotonic() < deadline:
        time.sleep(0.05)
    rec.close()
    assert rec.crashdump_path.exists(), "hang watchdog never dumped"
    dump = json.loads(rec.crashdump_path.read_text())
    assert dump["reason"].startswith("hang")
    assert dump["phase"] == "train"


def test_beats_reset_the_hang_latch(tmp_path):
    rec = _quiet_recorder(
        tmp_path, heartbeat_interval_s=0.05, hang_timeout_s=0.4
    )
    for _ in range(8):  # keep beating faster than the timeout
        rec.beat(phase="train")
        time.sleep(0.1)
    rec.close()
    assert not rec.crashdump_path.exists()


def test_heartbeat_file_tracks_phase(tmp_path):
    rec = _quiet_recorder(tmp_path)
    rec.beat(phase="train", epoch=3)
    rec.close()  # close writes the final heartbeat synchronously
    hb = json.loads((tmp_path / "heartbeat.json").read_text())
    assert hb["closed"] is True and hb["phase"] == "closed"
    assert hb["epoch"] == 3 and hb["beats"] == 1


# ----------------------------------------------------- identity + envelope


def test_process_identity_env_fallback(monkeypatch):
    monkeypatch.setitem(sys.modules, "jax", None)  # jax "not imported"
    monkeypatch.setenv("JAX_PROCESS_INDEX", "3")
    monkeypatch.setenv("JAX_PROCESS_COUNT", "8")
    assert process_identity() == (3, 8)
    monkeypatch.delenv("JAX_PROCESS_COUNT")
    assert process_identity() == (3, None)
    monkeypatch.delenv("JAX_PROCESS_INDEX")
    monkeypatch.setenv("MT_HOST_INDEX", "1")
    monkeypatch.setenv("MT_NUM_HOSTS", "4")
    assert process_identity() == (1, 4)


def test_events_carry_identity_before_distributed_init(
    tmp_path, monkeypatch
):
    monkeypatch.setitem(sys.modules, "jax", None)
    monkeypatch.setenv("JAX_PROCESS_INDEX", "2")
    monkeypatch.setenv("JAX_PROCESS_COUNT", "4")
    tel = TelemetryRun(tmp_path, run_id="ident")
    ev = tel.event("run_started")
    tel.close()
    assert ev["proc"] == 2 and ev["nproc"] == 4
    assert tel.registry.tags["process_index"] == 2
    assert tel.registry.tags["process_count"] == 4


# ------------------------------------------------------------- aggregation


def _write_sim_stream(
    root: Path, rank: int, world: int, monkeypatch, epochs=3,
    finish=True, wall=0.1, wall_by_epoch=None,
) -> TelemetryRun:
    monkeypatch.setitem(sys.modules, "jax", None)
    monkeypatch.setenv("JAX_PROCESS_INDEX", str(rank))
    monkeypatch.setenv("JAX_PROCESS_COUNT", str(world))
    tel = TelemetryRun(root / f"p{rank}", run_id=f"sim-p{rank}")
    tel.event("run_started", platform="sim", n_devices=1,
              strategy="sim", epoch_mode="scan", steps_per_epoch=4)
    for epoch in range(epochs):
        w = wall_by_epoch[epoch] if wall_by_epoch else wall
        tel.event("epoch", epoch=epoch, steps=4, wall_s=w,
                  steps_per_sec=4.0 / w)
    if finish:
        tel.event("run_finished", epochs=epochs, total_steps=4 * epochs,
                  steps_per_sec=40.0, diverged=False, best_val=0.1,
                  epoch_compiles=1, eval_compiles=0)
    tel.close()
    return tel


def test_aggregate_healthy_fleet(tmp_path, monkeypatch):
    _write_sim_stream(tmp_path, 0, 2, monkeypatch, wall=0.10)
    _write_sim_stream(tmp_path, 1, 2, monkeypatch, wall=0.15)
    report = aggregate_path(tmp_path)
    assert report["healthy"] and not report["failures"]
    assert report["expected_processes"] == 2
    assert report["finished_processes"] == 2
    skew = report["epoch_skew"]
    assert skew["epochs_compared"] == 3
    assert skew["max_s"] == pytest.approx(0.05)
    # Wait attribution: p0 idles in the collective while p1 finishes.
    assert report["collective_wait_s"]["p0"] == pytest.approx(0.15)
    assert report["collective_wait_s"]["p1"] == pytest.approx(0.0)
    # p1 is the straggler, but below the significance bar it is not a
    # failure (both finished).
    assert report["straggler"]["label"] == "p1"


def test_postmortem_missing_process_stream(tmp_path, monkeypatch):
    # nproc says 2, only p0 wrote a stream: the SIGKILL-before-first-event
    # case. The fleet is incomplete -> exit 2, and the failure says so.
    _write_sim_stream(tmp_path, 0, 2, monkeypatch)
    report = postmortem_path(tmp_path)
    assert report["exit_code"] == 2
    assert report["missing_processes"] == [1]
    assert any("p1" in f and "no event stream" in f
               for f in report["failures"])


def test_postmortem_dead_process_heartbeat_gap(tmp_path, monkeypatch):
    # p1 started, never finished, no crashdump (SIGKILL) and its last
    # activity is far behind the fleet: status 'dead', exit 2.
    _write_sim_stream(tmp_path, 0, 2, monkeypatch)
    _write_sim_stream(tmp_path, 1, 2, monkeypatch, epochs=1, finish=False)
    report = postmortem_path(
        tmp_path, now=time.time() + 3600.0, grace_s=30.0
    )
    assert report["exit_code"] == 2
    statuses = {d["label"]: d["status"] for d in report["processes"]}
    assert statuses == {"p0": "finished", "p1": "dead"}
    assert "p1" in report["headline"]


def test_postmortem_significant_straggler_not_finished(
    tmp_path, monkeypatch
):
    _write_sim_stream(tmp_path, 0, 3, monkeypatch, wall=0.10)
    _write_sim_stream(tmp_path, 1, 3, monkeypatch, wall=0.10)
    _write_sim_stream(tmp_path, 2, 3, monkeypatch, wall=0.50, finish=False)
    report = postmortem_path(tmp_path, now=time.time() + 3600.0)
    assert report["exit_code"] == 2
    s = report["straggler"]
    assert s["label"] == "p2" and s["significant"]
    assert any("straggles" in f for f in report["failures"])


def test_aggregate_cli_exit_codes(tmp_path, monkeypatch, capsys):
    _write_sim_stream(tmp_path, 0, 1, monkeypatch, epochs=2)
    assert cli_main(["aggregate", str(tmp_path)]) == 0
    assert "finished" in capsys.readouterr().out
    assert cli_main(["aggregate", str(tmp_path / "nope")]) == 1
    assert cli_main(["postmortem", str(tmp_path), "--json"]) == 0
    out = capsys.readouterr().out
    assert json.loads(out)["healthy"] is True


# --------------------------------------------------- subprocess scenarios


def _spawn(root: Path, rank: int, scenario: str) -> subprocess.Popen:
    env = {**os.environ, "PYTHONPATH": str(_REPO_ROOT)}
    return subprocess.Popen(
        [sys.executable, str(_WORKER), str(root), str(rank), "2", scenario],
        cwd=_REPO_ROOT,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )


def _wait_line(proc: subprocess.Popen, want: str):
    # readline returns "" at EOF (worker died before printing): the assert
    # then fails with the actual output instead of hanging the test.
    line = proc.stdout.readline().strip()
    assert line == want, f"worker said {line!r}, wanted {want!r}"


def test_sigterm_leaves_crashdump_and_postmortem_names_victim(tmp_path):
    p0 = _spawn(tmp_path, 0, "healthy")
    p1 = _spawn(tmp_path, 1, "victim-sigterm")
    try:
        _wait_line(p1, "ready")
        p1.send_signal(signal.SIGTERM)
        rc1 = p1.wait(timeout=30)
        assert p0.wait(timeout=30) == 0
    finally:
        for p in (p0, p1):
            if p.poll() is None:
                p.kill()
                p.wait()
    # The handler re-delivers SIGTERM after dumping: correct wait status.
    assert rc1 == -signal.SIGTERM
    dump = json.loads((tmp_path / "p1" / "crashdump.json").read_text())
    assert dump["reason"] == "signal:SIGTERM"
    assert dump["proc"] == 1 and dump["nproc"] == 2
    assert dump["scalars"]["loss/total/train"]  # divergence context
    # The dump event was flushed into the stream before death.
    kinds = [
        json.loads(line)["kind"]
        for line in (tmp_path / "p1" / "events.jsonl").read_text()
        .splitlines()
    ]
    assert "crashdump" in kinds
    report = postmortem_path(tmp_path)
    assert report["exit_code"] == 2
    statuses = {d["label"]: d["status"] for d in report["processes"]}
    assert statuses == {"p0": "finished", "p1": "killed"}
    assert "p1" in report["headline"]


def test_hang_watchdog_dumps_in_simulated_fleet(tmp_path):
    p0 = _spawn(tmp_path, 0, "healthy")
    p1 = _spawn(tmp_path, 1, "victim-hang")
    try:
        _wait_line(p1, "ready")
        _wait_line(p1, "dumped")  # the watchdog thread fired
        assert p0.wait(timeout=30) == 0
    finally:
        for p in (p0, p1):
            if p.poll() is None:
                p.kill()
                p.wait()
    dump = json.loads((tmp_path / "p1" / "crashdump.json").read_text())
    assert dump["reason"].startswith("hang")
    assert dump["phase"] == "train" and dump["epoch"] == 1
    report = postmortem_path(tmp_path)
    assert report["exit_code"] == 2
    statuses = {d["label"]: d["status"] for d in report["processes"]}
    assert statuses["p1"] == "hung"
    assert "p1" in report["headline"]
    assert "hang" in report["headline"]


def test_postmortem_cli_is_jax_free(tmp_path):
    # The CLI must work on a machine where importing jax would HANG (a
    # wedged relay lease): prove it never imports jax by poisoning the
    # import in a fresh interpreter.
    run_root = tmp_path / "run"
    p0 = _spawn(run_root, 0, "healthy")
    assert p0.wait(timeout=30) == 0
    poison = tmp_path / "poison"
    poison.mkdir()
    (poison / "jax.py").write_text(
        "raise ImportError('postmortem CLI imported jax')\n"
    )
    out = subprocess.run(
        [sys.executable, "-m", "masters_thesis_tpu.telemetry",
         "postmortem", str(run_root)],
        cwd=_REPO_ROOT,
        env={
            **os.environ,
            "PYTHONPATH": f"{poison}:{_REPO_ROOT}",
            "JAX_PROCESS_INDEX": "",  # don't inherit fleet identity
        },
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert out.returncode == 2, out.stderr  # 1 of 2 streams missing
    assert "postmortem" in out.stdout
    # And --selfcheck, the check.sh gate, under the same poison.
    out = subprocess.run(
        [sys.executable, "-m", "masters_thesis_tpu.telemetry",
         "postmortem", "--selfcheck"],
        cwd=_REPO_ROOT,
        env={**os.environ, "PYTHONPATH": f"{poison}:{_REPO_ROOT}"},
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert out.returncode == 0, out.stdout + out.stderr
