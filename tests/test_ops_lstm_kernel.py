"""Parity tests: Pallas fused LSTM kernel vs the lax.scan formulation.

The Pallas kernels run in interpreter mode here (CPU test harness); on TPU
the identical kernel code compiles via Mosaic. Forward AND backward (custom
VJP / BPTT kernel) must match the autodiff'd scan to tight tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from masters_thesis_tpu.ops.lstm_kernel import (
    ROW_TILE,
    lstm_pair_recurrence,
    lstm_pair_xla,
    lstm_recurrence,
    lstm_recurrence_xla,
    pair_fits,
    pair_rows_ok,
    stack_fits,
)


def _random_case(rng, n_t, b, hidden):
    x_proj = jnp.asarray(rng.normal(size=(n_t, b, 4 * hidden)), jnp.float32)
    w_hh_t = jnp.asarray(
        rng.normal(size=(hidden, 4 * hidden)) * 0.2, jnp.float32
    )
    return x_proj, w_hh_t


@pytest.mark.parametrize(
    "n_t,b,hidden",
    [
        (5, 4, 8),           # tiny
        (7, ROW_TILE, 16),   # exactly one row tile
        (3, ROW_TILE + 5, 8),  # row remainder -> padding path
        (60, 100, 64),       # the reference workload shape (model=small)
        (6, 150, 16),        # > SINGLE_TILE_MAX_ROWS -> row-tiled grid path
    ],
)
def test_forward_parity(rng, n_t, b, hidden):
    x_proj, w_hh_t = _random_case(rng, n_t, b, hidden)
    ref = lstm_recurrence_xla(x_proj, w_hh_t)
    out = lstm_recurrence(x_proj, w_hh_t, impl="interpret")
    assert out.shape == (n_t, b, hidden)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize(
    "n_t,b,hidden",
    [(5, 4, 8), (6, ROW_TILE + 3, 16), (4, 150, 16)],  # last: grid > 1
)
def test_gradient_parity(rng, n_t, b, hidden):
    x_proj, w_hh_t = _random_case(rng, n_t, b, hidden)
    # Nontrivial cotangent: weighted sum over all timesteps' hidden states.
    w_out = jnp.asarray(rng.normal(size=(n_t, b, hidden)), jnp.float32)

    def loss_ref(xp, w):
        return jnp.sum(lstm_recurrence_xla(xp, w) * w_out)

    def loss_pl(xp, w):
        return jnp.sum(lstm_recurrence(xp, w, impl="interpret") * w_out)

    gx_ref, gw_ref = jax.grad(loss_ref, argnums=(0, 1))(x_proj, w_hh_t)
    gx_pl, gw_pl = jax.grad(loss_pl, argnums=(0, 1))(x_proj, w_hh_t)
    np.testing.assert_allclose(
        np.asarray(gx_pl), np.asarray(gx_ref), atol=2e-5
    )
    # dw accumulates over T x B products; tolerance scales with row count
    # (accumulation-order differences between BPTT orderings).
    np.testing.assert_allclose(
        np.asarray(gw_pl), np.asarray(gw_ref), atol=2e-4 * max(1, b // 16)
    )


def test_encoder_parity_between_impls(rng):
    """Full encoder: xla vs interpret kernel paths give identical outputs."""
    from masters_thesis_tpu.models.lstm import LstmEncoder

    x = jnp.asarray(rng.normal(size=(9, 12, 3)), jnp.float32)
    enc_xla = LstmEncoder(hidden_size=16, num_layers=2, kernel_impl="xla")
    enc_pl = LstmEncoder(hidden_size=16, num_layers=2, kernel_impl="interpret")
    params = enc_xla.init(jax.random.key(0), x)["params"]
    a1, b1 = enc_xla.apply({"params": params}, x)
    a2, b2 = enc_pl.apply({"params": params}, x)
    np.testing.assert_allclose(np.asarray(a2), np.asarray(a1), atol=1e-5)
    np.testing.assert_allclose(np.asarray(b2), np.asarray(b1), atol=1e-5)


def test_row_tile_env_override_parity(rng, monkeypatch):
    """MT_LSTM_ROW_TILE retunes the grid-fallback block size; any legal
    tile must be numerically identical to the default (fwd AND bwd)."""
    x_proj = jnp.asarray(rng.normal(size=(4, 150, 64)).astype(np.float32))
    w_hh_t = jnp.asarray(rng.normal(size=(16, 64)).astype(np.float32))

    def loss(xp, w):
        return jnp.sum(lstm_recurrence(xp, w, impl="interpret") ** 2)

    base = jax.value_and_grad(loss, argnums=(0, 1))(x_proj, w_hh_t)
    monkeypatch.setenv("MT_LSTM_ROW_TILE", "64")
    tuned = jax.value_and_grad(loss, argnums=(0, 1))(x_proj, w_hh_t)
    np.testing.assert_allclose(float(base[0]), float(tuned[0]), rtol=1e-6)
    for a, b in zip(base[1], tuned[1]):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        )
    monkeypatch.setenv("MT_LSTM_ROW_TILE", "31")
    with pytest.raises(ValueError, match="multiple of 8"):
        lstm_recurrence(x_proj, w_hh_t, impl="interpret").block_until_ready()


def test_single_layer_fits_long_lookback_guard():
    """Long lookbacks scale the resident kernel's VMEM planes past budget
    at ANY row tile — the byte guard must reject them (the dispatcher then
    takes the time-blocked path instead of a Mosaic compile error)."""
    from masters_thesis_tpu.ops.lstm_kernel import single_layer_fits

    assert single_layer_fits(60, 100, 64, 4)     # canonical: resident
    assert not single_layer_fits(600, 100, 64, 4)  # 10x lookback: over
    assert not single_layer_fits(600, 32, 64, 4)   # smaller tile: still over
    assert single_layer_fits(600, 100, 8, 4)     # tiny hidden: fits


@pytest.mark.parametrize("n_t,b,hidden", [(9, 4, 8), (11, 40, 16)])
def test_time_blocked_kernel_parity(rng, monkeypatch, n_t, b, hidden):
    """Time-blocked kernel (h/c carried across sequential grid steps) must
    match the scan formulation fwd+bwd — forced to SMALL chunks so several
    time blocks and the cross-chunk carry are exercised."""
    import masters_thesis_tpu.ops.lstm_kernel as lk

    monkeypatch.setattr(lk, "_tb_time_chunk", lambda *a: 4)
    x_proj, w_hh_t = _random_case(rng, n_t, b, hidden)
    ref = lstm_recurrence_xla(x_proj, w_hh_t)
    out = lk._lstm_recurrence_tblocked(x_proj, w_hh_t, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    w_out = jnp.asarray(rng.normal(size=(n_t, b, hidden)), jnp.float32)

    def loss(fn):
        return lambda xp, w: jnp.sum(fn(xp, w) * w_out)

    g_ref = jax.grad(loss(lstm_recurrence_xla), argnums=(0, 1))(
        x_proj, w_hh_t
    )
    g_tb = jax.grad(
        loss(lambda xp, w: lk._lstm_recurrence_tblocked(xp, w, True)),
        argnums=(0, 1),
    )(x_proj, w_hh_t)
    np.testing.assert_allclose(np.asarray(g_tb[0]), np.asarray(g_ref[0]),
                               atol=2e-5)
    np.testing.assert_allclose(np.asarray(g_tb[1]), np.asarray(g_ref[1]),
                               atol=2e-4 * max(1, b // 16))


def test_long_lookback_dispatches_to_time_blocked(rng, monkeypatch):
    """lstm_recurrence must route over-budget lookbacks to the
    time-blocked kernel and still match the scan formulation."""
    import masters_thesis_tpu.ops.lstm_kernel as lk

    calls = []
    real = lk._lstm_recurrence_tblocked

    def spy(xp, w, interpret):
        calls.append(xp.shape)
        return real(xp, w, interpret)

    monkeypatch.setattr(lk, "_lstm_recurrence_tblocked", spy)
    monkeypatch.setattr(lk, "single_layer_fits", lambda *a: False)
    x_proj, w_hh_t = _random_case(rng, 10, 12, 8)
    out = lstm_recurrence(x_proj, w_hh_t, impl="interpret")
    assert calls, "time-blocked path not taken"
    ref = lstm_recurrence_xla(x_proj, w_hh_t)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def _random_pair_case(rng, n_t, b, hidden, *, dropout=0.0):
    """dropout=None -> maskless variant (mask arg is None)."""
    x1 = jnp.asarray(rng.normal(size=(n_t, b, 4 * hidden)), jnp.float32)
    w1 = jnp.asarray(rng.normal(size=(hidden, 4 * hidden)) * 0.2, jnp.float32)
    wi2 = jnp.asarray(rng.normal(size=(hidden, 4 * hidden)) * 0.2, jnp.float32)
    b2 = jnp.asarray(rng.normal(size=(4 * hidden,)) * 0.1, jnp.float32)
    w2 = jnp.asarray(rng.normal(size=(hidden, 4 * hidden)) * 0.2, jnp.float32)
    if dropout is None:
        mask = None
    elif dropout:
        keep = rng.random(size=(n_t, b, hidden)) > dropout
        mask = jnp.asarray(keep / (1.0 - dropout), jnp.float32)
    else:
        mask = jnp.ones((n_t, b, hidden), jnp.float32)
    return x1, w1, wi2, b2, w2, mask


@pytest.mark.parametrize(
    "n_t,b,hidden,dropout",
    [
        (5, 4, 8, 0.0),       # tiny, all-ones mask
        (5, 4, 8, None),      # tiny, MASKLESS variant
        (5, 4, 8, 0.3),       # with a dropout mask in the seam
        (3, 13, 8, None),     # row remainder + maskless
        (60, 100, 64, 0.2),   # the reference workload shape (model=small)
        (60, 100, 64, None),  # the reference EVAL shape (maskless)
    ],
)
def test_pair_forward_parity(rng, n_t, b, hidden, dropout):
    args = _random_pair_case(rng, n_t, b, hidden, dropout=dropout)
    ref = lstm_pair_xla(*args)
    out = lstm_pair_recurrence(*args, impl="interpret")
    assert out.shape == (n_t, b, hidden)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize(
    "n_t,b,hidden,dropout",
    [(5, 4, 8, 0.0), (5, 4, 8, None), (6, 13, 16, 0.3), (12, 40, 16, 0.2)],
)
def test_pair_gradient_parity(rng, n_t, b, hidden, dropout):
    args = _random_pair_case(rng, n_t, b, hidden, dropout=dropout)
    w_out = jnp.asarray(rng.normal(size=(n_t, b, hidden)), jnp.float32)

    def loss(fn):
        def inner(x1, w1, wi2, b2, w2):
            return jnp.sum(fn(x1, w1, wi2, b2, w2, args[5]) * w_out)

        return inner

    ref_fn = loss(lstm_pair_xla)
    pl_fn = loss(
        lambda *a: lstm_pair_recurrence(*a, impl="interpret")
    )
    grads_ref = jax.grad(ref_fn, argnums=(0, 1, 2, 3, 4))(*args[:5])
    grads_pl = jax.grad(pl_fn, argnums=(0, 1, 2, 3, 4))(*args[:5])
    names = ("dx1", "dw_hh1", "dw_ih2", "db2", "dw_hh2")
    for name, g_pl, g_ref in zip(names, grads_pl, grads_ref):
        np.testing.assert_allclose(
            np.asarray(g_pl),
            np.asarray(g_ref),
            atol=2e-4 * max(1, b // 16),
            err_msg=name,
        )


def test_pair_rows_guard():
    # Canonical window shape (T=60, H=64): the measured-working envelope.
    assert pair_rows_ok(100)
    assert pair_rows_ok(104)
    assert not pair_rows_ok(105)
    assert not pair_rows_ok(800)
    # The feasibility check is BYTE-based (ADVICE r3): growing T or hidden
    # past the canonical envelope must also reject, and small-T/H shapes
    # admit more rows than the old 104-row constant.
    assert pair_fits(60, 104, 64, True)
    assert not pair_fits(120, 104, 64, True)   # 2x lookback blows VMEM
    assert not pair_fits(60, 104, 128, True)   # 2x hidden blows VMEM
    assert pair_fits(3, 800, 8, True)          # tiny T/H: many rows fit
    # Maskless drops a (T,B,H) plane -> strictly more headroom.
    assert pair_fits(60, 112, 64, False)


def test_pair_large_rows_falls_back_to_xla(rng):
    """Above the VMEM budget the pair API silently uses the scan path."""
    args = _random_pair_case(rng, 60, 120, 64)
    assert not pair_fits(60, 120, 64, True)
    out = lstm_pair_recurrence(*args, impl="interpret")
    ref = lstm_pair_xla(*args)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_window_scheduled_forward_parity(rng):
    """Rows past the single-program limit with a known window size run
    window-per-program (lax.map of the fast path) and must match the scan
    formulation exactly — fwd and bwd (the bs>1 cliff fix, RESULTS.md)."""
    n_t, win, n_win, hidden = 6, 50, 3, 16
    b = win * n_win  # 150 > SINGLE_TILE_MAX_ROWS
    x_proj, w_hh_t = _random_case(rng, n_t, b, hidden)
    ref = lstm_recurrence_xla(x_proj, w_hh_t)
    out = lstm_recurrence(x_proj, w_hh_t, impl="interpret", window_rows=win)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    w_out = jnp.asarray(rng.normal(size=(n_t, b, hidden)), jnp.float32)

    def loss(fn):
        return lambda xp, w: jnp.sum(fn(xp, w) * w_out)

    g_ref = jax.grad(loss(lstm_recurrence_xla), argnums=(0, 1))(x_proj, w_hh_t)
    g_win = jax.grad(
        loss(lambda xp, w: lstm_recurrence(
            xp, w, impl="interpret", window_rows=win
        )),
        argnums=(0, 1),
    )(x_proj, w_hh_t)
    np.testing.assert_allclose(np.asarray(g_win[0]), np.asarray(g_ref[0]),
                               atol=2e-5)
    np.testing.assert_allclose(np.asarray(g_win[1]), np.asarray(g_ref[1]),
                               atol=2e-4 * max(1, b // 16))


@pytest.mark.parametrize("dropout", [None, 0.3])
@pytest.mark.slow
def test_window_scheduled_pair_parity(rng, dropout):
    """The fused pair keeps fusing past its VMEM budget when the batch is a
    stack of windows that each fit — one pair program per window."""
    n_t, win, n_win, hidden = 30, 80, 3, 64
    b = win * n_win  # 240 rows exceeds the pair budget; 80-row windows fit
    assert not pair_fits(n_t, b, hidden, dropout is not None)
    assert pair_fits(n_t, win, hidden, dropout is not None)
    args = _random_pair_case(rng, n_t, b, hidden, dropout=dropout)
    ref = lstm_pair_xla(*args)
    out = lstm_pair_recurrence(*args, impl="interpret", window_rows=win)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    w_out = jnp.asarray(rng.normal(size=(n_t, b, hidden)), jnp.float32)

    def loss(fn):
        def inner(x1, w1, wi2, b2, w2):
            return jnp.sum(fn(x1, w1, wi2, b2, w2, args[5]) * w_out)

        return inner

    ref_fn = loss(lstm_pair_xla)
    win_fn = loss(
        lambda *a: lstm_pair_recurrence(*a, impl="interpret", window_rows=win)
    )
    grads_ref = jax.grad(ref_fn, argnums=(0, 1, 2, 3, 4))(*args[:5])
    grads_win = jax.grad(win_fn, argnums=(0, 1, 2, 3, 4))(*args[:5])
    for name, g_w, g_r in zip(
        ("dx1", "dw_hh1", "dw_ih2", "db2", "dw_hh2"), grads_win, grads_ref
    ):
        np.testing.assert_allclose(
            np.asarray(g_w), np.asarray(g_r),
            atol=2e-4 * max(1, b // 16), err_msg=name,
        )


def test_window_scheduled_pair_over_budget_shape(rng):
    """A canonical-geometry batch (T=60, H=64) over the pair budget but
    made of in-budget windows must still produce xla-parity output through
    the window-scheduled fused path."""
    args = _random_pair_case(rng, 60, 200, 64, dropout=None)
    assert not pair_fits(60, 200, 64, False)
    assert pair_fits(60, 100, 64, False)
    out = lstm_pair_recurrence(*args, impl="interpret", window_rows=100)
    ref = lstm_pair_xla(*args)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_encoder_window_rows_matches_flat(rng):
    """Encoder outputs must be IDENTICAL with and without the window_rows
    hint (deterministic mode) — scheduling must never change numerics."""
    from masters_thesis_tpu.models.lstm import LstmEncoder

    x = jnp.asarray(rng.normal(size=(150, 12, 3)), jnp.float32)
    enc = LstmEncoder(hidden_size=16, num_layers=2, kernel_impl="interpret")
    params = enc.init(jax.random.key(0), x)["params"]
    a_flat, b_flat = enc.apply({"params": params}, x)
    a_win, b_win = enc.apply({"params": params}, x, window_rows=50)
    np.testing.assert_allclose(np.asarray(a_win), np.asarray(a_flat),
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(b_win), np.asarray(b_flat),
                               atol=1e-5)


def _random_stack_case(rng, n_t, b, hidden, n_layers, *, dropout=None):
    x1 = jnp.asarray(rng.normal(size=(n_t, b, 4 * hidden)), jnp.float32)
    mk = lambda: jnp.asarray(  # noqa: E731
        rng.normal(size=(hidden, 4 * hidden)) * 0.2, jnp.float32
    )
    w_hh = tuple(mk() for _ in range(n_layers))
    w_in = tuple(mk() for _ in range(n_layers - 1))
    bias = tuple(
        jnp.asarray(rng.normal(size=(4 * hidden,)) * 0.1, jnp.float32)
        for _ in range(n_layers - 1)
    )
    if dropout is None:
        masks = None
    else:
        masks = tuple(
            jnp.asarray(
                (rng.random(size=(n_t, b, hidden)) > dropout)
                / (1.0 - dropout),
                jnp.float32,
            )
            for _ in range(n_layers - 1)
        )
    return x1, (w_hh, w_in, bias), masks


@pytest.mark.parametrize(
    "n_t,b,hidden,n_layers,dropout",
    [
        (5, 4, 8, 3, None),
        (5, 4, 8, 3, 0.3),
        (6, 12, 8, 4, None),
        (6, 12, 8, 4, 0.2),
        (4, 13, 8, 5, None),  # row padding + depth 5
    ],
)
@pytest.mark.slow
def test_stack_forward_and_gradient_parity(rng, n_t, b, hidden, n_layers,
                                           dropout):
    """L-layer wavefront vs chained scans: fwd and all weight grads."""
    from masters_thesis_tpu.ops.lstm_kernel import (
        lstm_stack_recurrence,
        lstm_stack_xla,
    )

    x1, weights, masks = _random_stack_case(
        rng, n_t, b, hidden, n_layers, dropout=dropout
    )
    ref = lstm_stack_xla(x1, weights, masks)
    out = lstm_stack_recurrence(x1, weights, masks, impl="interpret")
    assert out.shape == (n_t, b, hidden)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    w_out = jnp.asarray(rng.normal(size=(n_t, b, hidden)), jnp.float32)

    def loss(fn):
        return lambda xp, w: jnp.sum(fn(xp, w, masks) * w_out)

    g_ref = jax.grad(loss(lstm_stack_xla), argnums=(0, 1))(x1, weights)
    g_pl = jax.grad(
        loss(lambda xp, w, m: lstm_stack_recurrence(
            xp, w, m, impl="interpret"
        )),
        argnums=(0, 1),
    )(x1, weights)
    for g_p, g_r in zip(
        jax.tree_util.tree_leaves(g_pl), jax.tree_util.tree_leaves(g_ref)
    ):
        np.testing.assert_allclose(
            np.asarray(g_p), np.asarray(g_r), atol=5e-4
        )


def test_stack_fits_depth_frontier():
    """The byte model's depth frontier at the canonical shape: f32 caps at
    the pair; bf16 (itemsize 2) unlocks the 4-deep wavefront (model=medium
    in one program)."""
    assert stack_fits(60, 104, 64, 2, True, 4)       # the pair (f32)
    assert not stack_fits(60, 104, 64, 3, True, 4)   # f32 depth 3: over
    assert stack_fits(60, 104, 64, 4, True, 2)       # bf16 medium: fits
    assert not stack_fits(60, 104, 64, 5, True, 2)   # bf16 depth 5: over
    assert stack_fits(60, 104, 64, 4, False, 2)      # bf16 eval: fits too
    # L=2 must agree with the pair model exactly.
    assert pair_fits(60, 104, 64, True) == stack_fits(60, 104, 64, 2, True)
    assert pair_fits(60, 112, 64, False) == stack_fits(60, 112, 64, 2, False)


def test_stack_window_scheduled_parity(rng):
    """Stack over-budget batches made of in-budget windows keep the fused
    wavefront via window-per-program scheduling."""
    from masters_thesis_tpu.ops.lstm_kernel import (
        lstm_stack_recurrence,
        lstm_stack_xla,
    )

    n_t, win, n_win, hidden, ell = 30, 64, 3, 64, 3
    b = win * n_win
    assert not stack_fits(n_t, b, hidden, ell, False, 4)
    assert stack_fits(n_t, win, hidden, ell, False, 4)
    x1, weights, masks = _random_stack_case(rng, n_t, b, hidden, ell)
    out = lstm_stack_recurrence(
        x1, weights, masks, impl="interpret", window_rows=win
    )
    ref = lstm_stack_xla(x1, weights, masks)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.slow
def test_encoder_deep_wavefront_matches_per_layer(rng, monkeypatch):
    """Full encoder, deterministic mode: the deep-wavefront grouping must
    agree with both the per-layer path and the pair grouping for depths
    where it engages (small f32 shapes fit depth 3-4 here)."""
    from masters_thesis_tpu.models.lstm import LstmEncoder

    x = jnp.asarray(rng.normal(size=(9, 12, 3)), jnp.float32)
    for layers in (3, 4, 5):
        enc = LstmEncoder(hidden_size=16, num_layers=layers)
        params = enc.init(jax.random.key(0), x)["params"]
        a_ref, b_ref = LstmEncoder(
            hidden_size=16, num_layers=layers, kernel_impl="xla"
        ).apply({"params": params}, x)
        # Wavefront ON (default): deep grouping through the stack kernel.
        monkeypatch.delenv("MT_LSTM_WAVEFRONT", raising=False)
        a_wf, b_wf = LstmEncoder(
            hidden_size=16, num_layers=layers, kernel_impl="interpret"
        ).apply({"params": params}, x)
        # Wavefront OFF: falls back to the pair grouping.
        monkeypatch.setenv("MT_LSTM_WAVEFRONT", "0")
        a_pair, b_pair = LstmEncoder(
            hidden_size=16, num_layers=layers, kernel_impl="interpret"
        ).apply({"params": params}, x)
        monkeypatch.delenv("MT_LSTM_WAVEFRONT", raising=False)
        for got, want in ((a_wf, a_ref), (b_wf, b_ref),
                          (a_pair, a_ref), (b_pair, b_ref)):
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), atol=1e-5
            )


def test_encoder_bf16_deep_wavefront_close_to_f32(rng):
    """bf16 compute engages the deep wavefront at shapes f32 cannot fit;
    outputs must stay within bf16 tolerance of the f32 per-layer path."""
    from masters_thesis_tpu.models.lstm import LstmEncoder

    x = jnp.asarray(rng.normal(size=(32, 20, 3)), jnp.float32)
    enc_f32 = LstmEncoder(hidden_size=16, num_layers=4, kernel_impl="xla")
    params = enc_f32.init(jax.random.key(0), x)["params"]
    a32, b32 = enc_f32.apply({"params": params}, x)
    a16, b16 = LstmEncoder(
        hidden_size=16, num_layers=4, kernel_impl="interpret",
        compute_dtype=jnp.bfloat16,
    ).apply({"params": params}, x)
    np.testing.assert_allclose(np.asarray(a16), np.asarray(a32), atol=0.05)
    np.testing.assert_allclose(np.asarray(b16), np.asarray(b32), atol=0.05)


@pytest.mark.slow
def test_encoder_fused_pair_matches_unfused(rng, monkeypatch):
    """Full encoder, deterministic mode: fused-pair and per-layer paths
    must agree for every depth (2 = one pair, 3 = pair + tail, 4 = two
    pairs)."""
    from masters_thesis_tpu.models.lstm import LstmEncoder

    x = jnp.asarray(rng.normal(size=(9, 12, 3)), jnp.float32)
    for layers in (2, 3, 4):
        enc = LstmEncoder(hidden_size=16, num_layers=layers)
        monkeypatch.delenv("MT_LSTM_FUSED_PAIR", raising=False)
        params = enc.init(jax.random.key(0), x)["params"]
        a_ref, b_ref = LstmEncoder(
            hidden_size=16, num_layers=layers, kernel_impl="xla"
        ).apply({"params": params}, x)
        monkeypatch.setenv("MT_LSTM_FUSED_PAIR", "1")
        a_fused, b_fused = LstmEncoder(
            hidden_size=16, num_layers=layers, kernel_impl="interpret"
        ).apply({"params": params}, x)
        np.testing.assert_allclose(
            np.asarray(a_fused), np.asarray(a_ref), atol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(b_fused), np.asarray(b_ref), atol=1e-5
        )


@pytest.mark.slow
def test_encoder_fused_pair_gradients(rng, monkeypatch):
    """Fused-path encoder gradients match the per-layer path (no dropout)."""
    from masters_thesis_tpu.models.lstm import LstmEncoder

    x = jnp.asarray(rng.normal(size=(7, 10, 3)), jnp.float32)
    enc_ref = LstmEncoder(hidden_size=16, num_layers=2, kernel_impl="xla")
    params = enc_ref.init(jax.random.key(1), x)["params"]

    def loss(encoder, p):
        a, b = encoder.apply({"params": p}, x)
        return jnp.sum(a**2) + jnp.sum(jnp.abs(b))

    monkeypatch.delenv("MT_LSTM_FUSED_PAIR", raising=False)
    g_ref = jax.grad(lambda p: loss(enc_ref, p))(params)
    monkeypatch.setenv("MT_LSTM_FUSED_PAIR", "1")
    enc_fused = LstmEncoder(
        hidden_size=16, num_layers=2, kernel_impl="interpret"
    )
    g_fused = jax.grad(lambda p: loss(enc_fused, p))(params)
    flat_ref = jax.tree.leaves_with_path(g_ref)
    flat_fused = jax.tree.flatten(g_fused)[0]
    for (path, leaf_ref), leaf_fused in zip(flat_ref, flat_fused):
        np.testing.assert_allclose(
            np.asarray(leaf_fused),
            np.asarray(leaf_ref),
            atol=5e-5,
            err_msg=str(path),
        )


def test_auto_falls_back_to_xla_on_cpu(rng):
    x_proj, w_hh_t = _random_case(rng, 4, 3, 8)
    out = lstm_recurrence(x_proj, w_hh_t, impl="auto")
    ref = lstm_recurrence_xla(x_proj, w_hh_t)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)
