"""Real multi-process distributed integration test.

Unlike every other test (which runs on the in-process 8-device virtual
mesh), this spawns TWO actual OS processes that rendezvous through
``jax.distributed.initialize`` with one CPU device each — the same
machinery a multi-host TPU pod uses, minus the hardware. It proves:

- the coordinator handshake works (``distributed_initialize`` with explicit
  coordinator/rank args, ``required=True``),
- the bootstrap + dataset-cache rendezvous works across processes
  (rank 0 writes, rank 1 blocks on the completion marker),
- the scan-epoch shard_map program runs over a mesh whose devices live in
  DIFFERENT processes (``global_put`` materializing per-process shards),
- both ranks converge to IDENTICAL final params and loss history — the
  DDP invariant, for real this time.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # 2-process rendezvous runs ~3 min

_REPO_ROOT = Path(__file__).resolve().parent.parent
_WORKER = Path(__file__).resolve().parent / "_distributed_worker.py"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_world(tmp_path, local_devices: int) -> list[dict]:
    coord = f"127.0.0.1:{_free_port()}"
    env = os.environ.copy()
    # Hermetic from the TPU relay (see conftest.py); local_devices CPU
    # devices per process.
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={local_devices}"
    )
    env["PYTHONPATH"] = str(_REPO_ROOT)

    procs = [
        subprocess.Popen(
            [sys.executable, str(_WORKER), coord, str(rank), "2",
             str(tmp_path), str(local_devices)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for rank in (0, 1)
    ]
    outs = [p.communicate(timeout=600)[0] for p in procs]
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"

    meta = [
        json.loads((tmp_path / f"rank{r}.json").read_text()) for r in (0, 1)
    ]
    for m in meta:
        assert m["process_count"] == 2
        assert m["local_devices"] == local_devices
        assert m["n_dev"] == 2 * local_devices
        assert np.isfinite(m["best_val"])
        assert np.isfinite(m["test"]["mae"])
    return meta


def test_two_process_distributed_training(tmp_path):
    meta = _run_world(tmp_path, local_devices=1)
    # Same program, same psum'd grads => identical history on every rank.
    assert meta[0]["history"] == meta[1]["history"]
    assert meta[0]["history"]  # non-empty
    # Stream mode (host iterator + global_put prefetch) also runs across
    # processes and agrees between ranks.
    assert meta[0]["stream_history"] == meta[1]["stream_history"]
    assert np.isfinite(meta[0]["stream_history"][0]["loss/total/train"])

    a = np.load(tmp_path / "rank0.npz")
    b = np.load(tmp_path / "rank1.npz")
    assert a.files
    for k in a.files:
        np.testing.assert_array_equal(a[k], b[k])


def test_two_process_multi_device_pod_topology(tmp_path):
    """2 processes x 4 devices each = an 8-device global mesh — the real
    multi-host pod shape (DCN between processes, intra-host devices within),
    not one chip per host. Same DDP invariant: every rank sees identical
    history and final params."""
    meta = _run_world(tmp_path, local_devices=4)
    assert meta[0]["history"] == meta[1]["history"]
    assert meta[0]["history"]
    assert meta[0]["stream_history"] == meta[1]["stream_history"]

    a = np.load(tmp_path / "rank0.npz")
    b = np.load(tmp_path / "rank1.npz")
    for k in a.files:
        np.testing.assert_array_equal(a[k], b[k])
