"""Trainer tests: optimization semantics, end-to-end fit on the 8-device
virtual mesh, scan/stream parity, plateau scheduling, checkpoint roundtrip.

The fit tests are the synthetic-oracle smoke story from SURVEY.md §4: train
briefly on DGP data with known structure and assert the loss moves the right
way — something the reference itself never automated.
"""

from pathlib import Path

import jax
import numpy as np
import pytest

from masters_thesis_tpu.data.pipeline import FinancialWindowDataModule
from masters_thesis_tpu.data.synthetic import SyntheticLogReturns
from masters_thesis_tpu.models.objectives import ModelSpec
from masters_thesis_tpu.train import PlateauScheduler, Trainer
from masters_thesis_tpu.train.checkpoint import restore_checkpoint


@pytest.fixture(scope="module")
def tiny_dm(tmp_path_factory) -> FinancialWindowDataModule:
    data_dir = tmp_path_factory.mktemp("tiny_data")
    r_stocks, r_market, alphas, betas = SyntheticLogReturns.generate(
        n_stocks=8, n_samples=4000, seed=1
    )
    np.save(data_dir / "stocks.npy", np.asarray(r_stocks))
    np.save(data_dir / "market.npy", np.asarray(r_market))
    np.save(data_dir / "alphas.npy", np.asarray(alphas))
    np.save(data_dir / "betas.npy", np.asarray(betas))
    dm = FinancialWindowDataModule(
        data_dir, lookback_window=16, target_window=8, stride=24, batch_size=2
    )
    dm.prepare_data(verbose=False)
    dm.setup()
    return dm


def small_spec(objective="mse"):
    return ModelSpec(
        objective=objective,
        hidden_size=8,
        num_layers=1,
        dropout=0.0,
        learning_rate=1e-2,
    )


def make_trainer(**kw):
    defaults = dict(
        max_epochs=3,
        gradient_clip_val=5.0,
        check_val_every_n_epoch=1,
        enable_progress_bar=False,
        enable_model_summary=False,
        seed=0,
    )
    defaults.update(kw)
    return Trainer(**defaults)


class TestFit:
    def test_mse_loss_decreases_multidevice(self, tiny_dm):
        assert len(jax.devices()) == 8  # conftest forces the virtual mesh
        trainer = make_trainer(strategy="tpu_xla")
        assert trainer.n_dev == 8
        result = trainer.fit(small_spec(), tiny_dm)
        first = result.history[0]["loss/total/train"]
        last = result.history[-1]["loss/total/train"]
        assert np.isfinite(first) and np.isfinite(last)
        assert last < first

    def test_single_device_strategy(self, tiny_dm):
        trainer = make_trainer(strategy="single_device", max_epochs=2)
        assert trainer.n_dev == 1
        result = trainer.fit(small_spec(), tiny_dm)
        assert result.history[-1]["loss/total/train"] < result.history[0][
            "loss/total/train"
        ]

    @pytest.mark.parametrize("objective", ["nll", "combined"])
    def test_other_objectives_run_and_are_finite(self, tiny_dm, objective):
        trainer = make_trainer(max_epochs=2)
        result = trainer.fit(small_spec(objective), tiny_dm)
        for row in result.history:
            assert np.isfinite(row["loss/total/train"])
            assert np.isfinite(row["loss/total/val"])

    def test_val_metrics_and_best_val(self, tiny_dm):
        trainer = make_trainer()
        result = trainer.fit(small_spec(), tiny_dm)
        assert np.isfinite(result.best_val_loss)
        assert result.best_val_loss <= min(
            row["loss/total/val"] for row in result.history
        ) + 1e-12

    def test_lr_logged_under_reference_tag(self, tiny_dm):
        """LR is logged as 'lr-Adam', the tag the reference's
        LearningRateMonitor emits (reference: train.py:162-165)."""
        result = make_trainer(max_epochs=1).fit(small_spec(), tiny_dm)
        assert "lr-Adam" in result.history[0]
        assert "lr" not in result.history[0]

    def test_stream_mode_matches_scan_mode(self, tiny_dm):
        """Same seed, same data: the pjit stream path and the shard_map scan
        path must optimize comparably (not bitwise — shuffle orders differ —
        but both must converge to the same loss scale)."""
        r_scan = make_trainer(strategy="single_device").fit(
            small_spec(), tiny_dm
        )
        r_stream = make_trainer(
            strategy="single_device", epoch_mode="stream"
        ).fit(small_spec(), tiny_dm)
        a = r_scan.history[-1]["loss/total/train"]
        b = r_stream.history[-1]["loss/total/train"]
        assert abs(a - b) / max(abs(a), abs(b)) < 0.5

    def test_profile_writes_trace(self, tiny_dm, tmp_path):
        """trainer.profile=true captures a jax.profiler trace of a
        steady-state epoch into <log_dir>/profile (the reference has no
        profiling at all, SURVEY.md §5 — only progress-bar flags)."""
        from masters_thesis_tpu.train.logging import TensorBoardLogger

        logger = TensorBoardLogger(tmp_path, "prof", "v0")
        trainer = make_trainer(max_epochs=3, profile=True, logger=logger)
        trainer.fit(small_spec(), tiny_dm)
        logger.close()
        traces = list((logger.log_dir / "profile").rglob("*.xplane.pb"))
        assert traces, "no profiler trace written"

    def test_test_metrics(self, tiny_dm):
        trainer = make_trainer(max_epochs=1)
        result = trainer.fit(small_spec(), tiny_dm)
        metrics = trainer.test(small_spec(), result.params, tiny_dm)
        for key in ("mae", "nll", "mse", "total"):
            assert key in metrics and np.isfinite(metrics[key])


class TestCheckpoint:
    def test_best_last_roundtrip(self, tiny_dm, tmp_path):
        ckpt_dir = tmp_path / "ckpts"
        trainer = make_trainer(ckpt_dir=ckpt_dir)
        result = trainer.fit(small_spec(), tiny_dm)
        for tag in ("best", "last"):
            params, opt_state, spec, meta = restore_checkpoint(ckpt_dir, tag)
            assert spec.objective == "mse"
            assert spec.hidden_size == 8
            assert meta["datamodule"]["lookback_window"] == 16
        # 'last' params match the in-memory final params
        params, _, _, _ = restore_checkpoint(ckpt_dir, "last")
        for a, b in zip(
            jax.tree_util.tree_leaves(params),
            jax.tree_util.tree_leaves(jax.device_get(result.params)),
        ):
            np.testing.assert_allclose(a, b, rtol=1e-6)

    def test_warmup_params_only_fine_tune(self, tiny_dm, tmp_path):
        """Warmup protocol: pretrained weights + fresh optimizer continue to
        train (reference: tex/diplomski_rad.tex:1134-1147 — synthetic->real
        fine-tune; the fine-tune's first epoch should start from the
        pretrained loss level, not from random init)."""
        ckpt_dir = tmp_path / "ckpts"
        pre = make_trainer(ckpt_dir=ckpt_dir, max_epochs=3).fit(
            small_spec(), tiny_dm
        )
        params, _, spec, _ = restore_checkpoint(ckpt_dir, "last")

        fresh = make_trainer(max_epochs=1).fit(small_spec(), tiny_dm)
        warm = make_trainer(max_epochs=1).fit(
            small_spec(), tiny_dm, init_state=(params, None)
        )
        assert np.isfinite(warm.history[0]["loss/total/train"])
        # Warm start must begin near the pretrained loss, below random init.
        assert (
            warm.history[0]["loss/total/train"]
            < fresh.history[0]["loss/total/train"]
        )
        assert warm.history[0]["loss/total/train"] == pytest.approx(
            pre.history[-1]["loss/total/train"], rel=0.5
        )

    @pytest.mark.slow
    def test_warmup_transfers_across_dgp_variants(self, tiny_dm, tmp_path):
        """The thesis' warmup premise, cross-dataset: pretraining on one
        distribution (no_outliers DGP) then fine-tuning briefly on another
        (outliers DGP) must beat the same brief training from scratch on
        the target data (reference: tex/diplomski_rad.tex:1134-1147 —
        synthetic->real; real CSVs aren't downloadable here, so the
        distribution shift is the DGP's own outliers variant)."""
        from masters_thesis_tpu.data.synthetic import SyntheticLogReturns

        r_stocks, r_market, alphas, betas = SyntheticLogReturns.generate(
            n_stocks=8, n_samples=4000, seed=2, variant="outliers"
        )
        np.save(tmp_path / "stocks.npy", np.asarray(r_stocks))
        np.save(tmp_path / "market.npy", np.asarray(r_market))
        np.save(tmp_path / "alphas.npy", np.asarray(alphas))
        np.save(tmp_path / "betas.npy", np.asarray(betas))
        target_dm = FinancialWindowDataModule(
            tmp_path, lookback_window=16, target_window=8, stride=24,
            batch_size=2,
        )
        target_dm.prepare_data(verbose=False)
        target_dm.setup()

        pre = make_trainer(max_epochs=6).fit(small_spec(), tiny_dm)
        params = jax.device_get(pre.params)

        warm_tr = make_trainer(max_epochs=2)
        warm = warm_tr.fit(small_spec(), target_dm, init_state=(params, None))
        scratch_tr = make_trainer(max_epochs=2)
        scratch = scratch_tr.fit(small_spec(), target_dm)

        warm_test = warm_tr.test(small_spec(), warm.params, target_dm)
        scratch_test = scratch_tr.test(
            small_spec(), scratch.params, target_dm
        )
        assert np.isfinite(warm_test["total"])
        assert warm_test["total"] < scratch_test["total"]
        assert warm.best_val_loss < scratch.best_val_loss

    def test_auto_resume_continues_from_last(self, tiny_dm, tmp_path):
        """Elastic recovery: a killed run restarted with resume=True must
        continue from the 'last' checkpoint (epoch counter, optimizer
        moments, scheduler state) and end up matching an uninterrupted run's
        epoch count."""
        ckpt_dir = tmp_path / "ckpts"
        # Simulate a crash after 2 of 4 epochs.
        make_trainer(ckpt_dir=ckpt_dir, max_epochs=2).fit(
            small_spec(), tiny_dm
        )
        resumed = make_trainer(
            ckpt_dir=ckpt_dir, max_epochs=4, resume=True
        ).fit(small_spec(), tiny_dm)
        assert [row["epoch"] for row in resumed.history] == [2, 3]
        _, _, _, meta = restore_checkpoint(ckpt_dir, "last")
        assert meta["epoch"] == 3
        assert meta["scheduler"]["lr"] > 0
        # Resuming a finished run trains zero additional epochs.
        noop = make_trainer(
            ckpt_dir=ckpt_dir, max_epochs=4, resume=True
        ).fit(small_spec(), tiny_dm)
        assert noop.history == []

    def test_bf16_mixed_precision_trains(self, tiny_dm):
        """precision='bf16-mixed' (LSTM recurrence in bfloat16 on the MXU,
        f32 params and loss math) must train to a loss comparable to f32."""
        r32 = make_trainer(max_epochs=2).fit(small_spec(), tiny_dm)
        rbf = make_trainer(max_epochs=2, precision="bf16-mixed").fit(
            small_spec(), tiny_dm
        )
        a = r32.history[-1]["loss/total/train"]
        b = rbf.history[-1]["loss/total/train"]
        assert np.isfinite(b)
        assert abs(a - b) / max(abs(a), 1e-9) < 0.1

    def test_divergence_halts_training(self, tmp_path):
        """Failure detection: a non-finite train loss stops the run early
        instead of looping through the remaining epochs."""
        r_stocks, r_market, _, _ = SyntheticLogReturns.generate(
            n_stocks=8, n_samples=4000, seed=1
        )
        stocks = np.array(r_stocks)
        stocks[0, :200] = np.nan  # poisoned source series
        np.save(tmp_path / "stocks.npy", stocks)
        np.save(tmp_path / "market.npy", np.asarray(r_market))
        dm = FinancialWindowDataModule(
            tmp_path, lookback_window=16, target_window=8, stride=24,
            batch_size=2,
        )
        dm.prepare_data(verbose=False)
        dm.setup()
        ckpt_dir = tmp_path / "ckpts"
        result = make_trainer(max_epochs=5, ckpt_dir=ckpt_dir).fit(
            small_spec(), dm
        )
        assert len(result.history) == 1
        assert not np.isfinite(result.history[0]["loss/total/train"])
        # The diverged run must not publish NaN params as 'last'.
        assert not (ckpt_dir / "last").exists()

    def test_restored_params_reproduce_test_metrics(self, tiny_dm, tmp_path):
        ckpt_dir = tmp_path / "ckpts"
        trainer = make_trainer(ckpt_dir=ckpt_dir, max_epochs=2)
        result = trainer.fit(small_spec(), tiny_dm)
        live = trainer.test(small_spec(), result.params, tiny_dm)
        params, _, spec, _ = restore_checkpoint(ckpt_dir, "last")
        restored = trainer.test(spec, params, tiny_dm)
        assert restored["mae"] == pytest.approx(live["mae"], rel=1e-5)


class TestStreamTail:
    def test_padded_tail_step_matches_unpadded(self, tiny_dm):
        """A tail batch padded to the full batch shape by cycling its own
        windows with zero weight must produce the SAME parameter update and
        metric sums as stepping on the bare tail — the mechanism stream mode
        uses to train the epoch's partial batch without a recompile
        (the reference's DataLoader trains the tail too: drop_last=False)."""
        import jax.numpy as jnp

        from masters_thesis_tpu.data.pipeline import Batch
        from masters_thesis_tpu.parallel import make_data_mesh
        from masters_thesis_tpu.train.optim import make_optimizer
        from masters_thesis_tpu.train.steps import make_train_step

        spec = small_spec()
        module = spec.build_module()
        mesh = make_data_mesh(1)
        tx = make_optimizer(5.0, spec.weight_decay)
        tail = jax.tree_util.tree_map(
            lambda a: np.asarray(a)[:3], tiny_dm.train_arrays()
        )
        rng = jax.random.key(0)
        dummy = jnp.zeros(
            (1, tiny_dm.lookback_window, tiny_dm.n_features), jnp.float32
        )
        step = make_train_step(
            module, spec.window_objective(), tx, mesh, weighted=True
        )
        lr = jnp.float32(1e-2)

        def run(batch, weights):
            params = module.init(rng, dummy)["params"]  # donated per call
            opt_state = tx.init(params)
            return step(params, opt_state, lr, rng, batch, weights)

        p_tail, _, s_tail = run(tail, np.ones((3,), np.float32))
        idx = np.arange(4) % 3
        padded = Batch(*(np.asarray(a)[idx] for a in tail))
        p_pad, _, s_pad = run(padded, (np.arange(4) < 3).astype(np.float32))

        for a, b in zip(
            jax.tree_util.tree_leaves(p_tail), jax.tree_util.tree_leaves(p_pad)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
            )
        for key in s_tail:
            np.testing.assert_allclose(
                np.asarray(s_tail[key]), np.asarray(s_pad[key]), rtol=1e-6
            )

    def test_stream_epoch_with_tail_trains(self, tiny_dm):
        """Stream mode on a split whose size is NOT a multiple of the global
        batch must still run and converge (the tail is trained, not
        dropped)."""
        n_train = len(tiny_dm.train_range)
        assert n_train % 2 == 0  # fixture uses batch_size=2; force a tail
        tiny_dm.batch_size = 3
        try:
            assert n_train % 3 != 0
            result = make_trainer(
                strategy="single_device", epoch_mode="stream", max_epochs=2
            ).fit(small_spec(), tiny_dm)
        finally:
            tiny_dm.batch_size = 2
        assert np.isfinite(result.history[-1]["loss/total/train"])
        assert (
            result.history[-1]["loss/total/train"]
            < result.history[0]["loss/total/train"]
        )


class TestEmptyValSplit:
    def test_best_falls_back_to_last(self, tmp_path):
        """With zero val windows, fit must still publish a 'best' checkpoint
        (the final params) and return a finite best_val (the final TRAIN
        loss) instead of inf — a sweep minimizing best_val would otherwise
        silently rank such runs last."""
        r_stocks, r_market, _, _ = SyntheticLogReturns.generate(
            n_stocks=4, n_samples=48, seed=3
        )
        np.save(tmp_path / "stocks.npy", np.asarray(r_stocks))
        np.save(tmp_path / "market.npy", np.asarray(r_market))
        # 48 samples / (16+8 window, stride 24) -> 2 windows: train=1,
        # val=range(1,1) empty, test=1.
        dm = FinancialWindowDataModule(
            tmp_path, lookback_window=16, target_window=8, stride=24,
            batch_size=1,
        )
        dm.prepare_data(verbose=False)
        dm.setup()
        assert len(dm.val_range) == 0
        ckpt_dir = tmp_path / "ckpts"
        result = make_trainer(
            strategy="single_device", max_epochs=2, ckpt_dir=ckpt_dir
        ).fit(small_spec(), dm)
        assert np.isfinite(result.best_val_loss)
        assert result.best_val_loss == pytest.approx(
            result.history[-1]["loss/total/train"]
        )
        params, _, _, _ = restore_checkpoint(ckpt_dir, "best")
        for a, b in zip(
            jax.tree_util.tree_leaves(params),
            jax.tree_util.tree_leaves(jax.device_get(result.params)),
        ):
            np.testing.assert_allclose(a, b, rtol=1e-6)


class TestCrashSafeCheckpointPublish:
    """Every kill point of the staged checkpoint swap must leave a
    restorable checkpoint (see checkpoint.save_checkpoint's protocol)."""

    def _save(self, d, epoch):
        from masters_thesis_tpu.train.checkpoint import save_checkpoint

        save_checkpoint(
            d, "last", {"w": np.full((2,), float(epoch))}, {},
            small_spec(), meta={"epoch": epoch},
        )

    def _restore_epoch(self, d):
        from masters_thesis_tpu.train.checkpoint import restore_checkpoint

        params, _, _, meta = restore_checkpoint(d, "last")
        assert float(params["w"][0]) == float(meta["epoch"])  # pair intact
        return meta["epoch"]

    def test_staged_pair_supersedes(self, tmp_path):
        """Kill between staging and publish: the complete staged pair wins."""
        import shutil

        from masters_thesis_tpu.train.checkpoint import checkpoint_restorable

        a, b = tmp_path / "a", tmp_path / "b"
        self._save(a, 0)
        self._save(b, 1)
        shutil.move(str(b / "last"), str(a / "last.new"))
        shutil.move(str(b / "last.json"), str(a / "last.json.new"))
        assert checkpoint_restorable(a, "last")
        assert self._restore_epoch(a) == 1
        assert not (a / "last.new").exists()
        assert not (a / "last.json.new").exists()

    def test_orphan_staged_tree_dropped(self, tmp_path):
        """Kill before the staged sidecar exists: previous checkpoint stays
        current and the orphan tree is discarded."""
        import shutil

        from masters_thesis_tpu.train.checkpoint import checkpoint_restorable

        a, b = tmp_path / "a", tmp_path / "b"
        self._save(a, 0)
        self._save(b, 1)
        shutil.move(str(b / "last"), str(a / "last.new"))
        assert checkpoint_restorable(a, "last")
        assert self._restore_epoch(a) == 0
        assert not (a / "last.new").exists()

    def test_sidecar_swap_finished_on_recovery(self, tmp_path):
        """Kill between the tree swap and the sidecar rename: recovery
        finishes the sidecar so tree and meta pair up again."""
        from masters_thesis_tpu.train.checkpoint import checkpoint_restorable

        a = tmp_path / "a"
        self._save(a, 0)
        stale = (a / "last.json").read_text()
        self._save(a, 1)
        # Fabricate the kill: tree is epoch 1, sidecar rolled back to epoch
        # 0, epoch-1 sidecar still staged.
        (a / "last.json.new").write_text((a / "last.json").read_text())
        (a / "last.json").write_text(stale)
        assert checkpoint_restorable(a, "last")
        assert self._restore_epoch(a) == 1

    def test_mid_tree_swap_recovered(self, tmp_path):
        """Kill between moving the old tree aside and renaming the staged
        one in: <tag> is missing entirely, yet recovery restores the new
        checkpoint."""
        import shutil

        from masters_thesis_tpu.train.checkpoint import checkpoint_restorable

        a, b = tmp_path / "a", tmp_path / "b"
        self._save(a, 0)
        self._save(b, 1)
        shutil.move(str(b / "last"), str(a / "last.new"))
        shutil.move(str(b / "last.json"), str(a / "last.json.new"))
        (a / "last").rename(a / "last.old")  # old moved aside, swap unfinished
        assert checkpoint_restorable(a, "last")
        assert self._restore_epoch(a) == 1
        assert not (a / "last.old").exists()


class TestPlateauScheduler:
    def test_reduces_after_patience(self):
        sched = PlateauScheduler(1e-3, factor=0.5, patience=2)
        assert sched.step(1.0) == 1e-3  # new best
        assert sched.step(1.0) == 1e-3  # bad 1
        assert sched.step(1.0) == 1e-3  # bad 2
        assert sched.step(1.0) == 5e-4  # bad 3 > patience -> reduce
        assert sched.step(1.0) == 5e-4  # counter reset

    def test_improvement_resets(self):
        sched = PlateauScheduler(1e-3, patience=1)
        sched.step(1.0)
        sched.step(1.0)  # bad 1
        sched.step(0.5)  # improvement
        sched.step(0.6)  # bad 1
        assert sched.lr == 1e-3
        sched.step(0.6)  # bad 2 -> reduce
        assert sched.lr == 5e-4

    def test_rel_threshold(self):
        # improvement smaller than 1e-4 relative counts as bad (torch default)
        sched = PlateauScheduler(1e-3, patience=0)
        sched.step(1.0)
        sched.step(1.0 - 1e-6)
        assert sched.lr == 5e-4

    def test_state_roundtrip(self):
        sched = PlateauScheduler(1e-3)
        sched.step(1.0)
        sched.step(2.0)
        state = sched.state_dict()
        other = PlateauScheduler(9.9)
        other.load_state_dict(state)
        assert other.lr == sched.lr and other.best == sched.best


class TestReproducibility:
    @pytest.mark.slow
    def test_same_seed_same_history(self, tiny_dm):
        """Identical seeds must reproduce the loss history bit-for-bit —
        every RNG consumer (init, shuffle, dropout) is explicitly keyed."""
        spec = ModelSpec(
            objective="mse", hidden_size=8, num_layers=2, dropout=0.2,
            learning_rate=1e-3,
        )
        a = make_trainer(seed=7).fit(spec, tiny_dm)
        b = make_trainer(seed=7).fit(spec, tiny_dm)
        assert a.history == b.history
        c = make_trainer(seed=8).fit(spec, tiny_dm)
        assert a.history != c.history


class TestUniverseAssetSharding:
    """The universe-scale path: K-factor windows served from the sharded
    store, asset axis sharded over the mesh batch dimension."""

    @pytest.fixture(scope="class")
    def universe_dm(self, tmp_path_factory) -> FinancialWindowDataModule:
        from masters_thesis_tpu.data.pipeline import bootstrap_synthetic

        data_dir = tmp_path_factory.mktemp("universe_data") / "synthetic"
        bootstrap_synthetic(
            data_dir, n_stocks=16, n_samples=2000, seed=0, n_factors=3
        )
        dm = FinancialWindowDataModule(
            data_dir,
            lookback_window=16,
            target_window=8,
            stride=24,
            batch_size=2,
            engine="python",
            store_shards=8,
        )
        dm.prepare_data(verbose=False)
        dm.setup()
        return dm

    def test_asset_sharded_kfactor_fit_decreases_loss(self, universe_dm):
        assert len(jax.devices()) == 8
        spec = ModelSpec(
            objective="mse",
            input_size=7,  # 2K+1 interaction-only features at K=3
            hidden_size=8,
            num_layers=1,
            dropout=0.0,
            n_factors=3,
            learning_rate=1e-2,
        )
        trainer = make_trainer(strategy="tpu_xla", shard_axis="asset")
        result = trainer.fit(spec, universe_dm)
        first = result.history[0]["loss/total/train"]
        last = result.history[-1]["loss/total/train"]
        assert np.isfinite(first) and np.isfinite(last)
        assert last < first

    def test_asset_sharded_nll_runs_and_is_finite(self, universe_dm):
        spec = ModelSpec(
            objective="nll",
            input_size=7,
            hidden_size=8,
            num_layers=1,
            dropout=0.0,
            n_factors=3,
            learning_rate=1e-3,
        )
        trainer = make_trainer(strategy="tpu_xla", shard_axis="asset",
                               max_epochs=2)
        result = trainer.fit(spec, universe_dm)
        assert np.isfinite(result.history[-1]["loss/total/train"])

    def test_asset_window_modes_agree_at_start(self, universe_dm):
        """Both shard modes train the same global problem: with identical
        seeds the first-epoch loss must match closely (the batch grouping
        differs, so later epochs may drift)."""
        spec = ModelSpec(
            objective="mse", input_size=7, hidden_size=8, num_layers=1,
            dropout=0.0, n_factors=3, learning_rate=1e-3,
        )
        a = make_trainer(strategy="tpu_xla", shard_axis="asset",
                         max_epochs=1).fit(spec, universe_dm)
        w = make_trainer(strategy="tpu_xla", shard_axis="window",
                         max_epochs=1).fit(spec, universe_dm)
        assert a.history[0]["loss/total/train"] == pytest.approx(
            w.history[0]["loss/total/train"], rel=0.05
        )

    def test_asset_shard_rejects_stream_mode(self):
        with pytest.raises(ValueError, match="epoch_mode='scan'"):
            make_trainer(shard_axis="asset", epoch_mode="stream")

    def test_unknown_shard_axis_rejected(self):
        with pytest.raises(ValueError, match="unknown shard_axis"):
            make_trainer(shard_axis="columns")
