"""Property-based tests (hypothesis) for the numerical core invariants.

The oracle tests pin exact values; these pin *laws* that must hold for any
shape/stride/data the pipeline can produce — the class of bugs exact-value
tests miss (off-by-one window starts, stride/shape interactions, scale
covariance of the OLS fit).
"""

import numpy as np
import pytest

# The pinned container doesn't ship hypothesis; skip (not error) without it
# so the tier-1 gate reflects real regressions only.
pytest.importorskip("hypothesis")
from hypothesis import given, settings
from hypothesis import strategies as st

from masters_thesis_tpu.ops import (
    add_quadratic_features,
    lookback_target_split,
    ols,
)

# Keep examples small: every example traces through jnp on CPU.
SET = settings(max_examples=25, deadline=None)


@st.composite
def window_params(draw):
    look = draw(st.integers(2, 12))
    tgt = draw(st.integers(2, 8))
    stride = draw(st.integers(1, 20))
    n_extra = draw(st.integers(0, 30))
    n_samples = look + tgt + n_extra
    k = draw(st.integers(1, 4))
    return k, n_samples, look, tgt, stride


@given(window_params())
@SET
@pytest.mark.slow
def test_window_split_invariants(params):
    k, n_samples, look, tgt, stride = params
    rng = np.random.default_rng(0)
    stocks = rng.normal(size=(k, n_samples)).astype(np.float32)
    market = rng.normal(size=(n_samples,)).astype(np.float32)

    x, y = lookback_target_split(stocks, market, look, tgt, stride)
    n_win = (n_samples - (look + tgt)) // stride + 1

    # Law 1: window count follows the strided-coverage formula.
    assert x.shape == (n_win, k, look, 2)
    assert y.shape == (n_win, k, tgt, 2)

    # Law 2: every window is a verbatim strided slice of the source series
    # and the target follows the lookback with no gap or overlap.
    for w in (0, n_win - 1):
        s = w * stride
        np.testing.assert_array_equal(
            np.asarray(x[w, :, :, 0]), stocks[:, s : s + look]
        )
        np.testing.assert_array_equal(
            np.asarray(y[w, :, :, 0]), stocks[:, s + look : s + look + tgt]
        )
        np.testing.assert_array_equal(np.asarray(x[w, :, :, 1]),
                                      np.broadcast_to(market[s : s + look], (k, look)))


@given(
    st.integers(3, 40),
    st.floats(-2, 2),
    st.floats(-3, 3),
)
@SET
def test_ols_exact_on_noiseless_line(n, alpha, beta):
    rng = np.random.default_rng(1)
    x = rng.normal(size=(n,)).astype(np.float64)
    x[0] += 3.0  # guarantee spread
    y = (alpha + beta * x)[None, :]
    a_hat, b_hat = ols(x.astype(np.float32), y.astype(np.float32))
    assert abs(float(a_hat) - alpha) < 5e-3 + 1e-2 * abs(alpha)
    assert abs(float(b_hat) - beta) < 5e-3 + 1e-2 * abs(beta)


@given(st.floats(0.1, 10), st.integers(4, 30))
@SET
def test_ols_beta_scale_covariance(scale, n):
    """Scaling y scales (alpha, beta) linearly; scaling x scales beta by
    1/s and leaves alpha + beta*mean(x) relationships intact."""
    rng = np.random.default_rng(2)
    x = rng.normal(size=(n,)).astype(np.float32)
    y = rng.normal(size=(2, n)).astype(np.float32)
    a1, b1 = ols(x, y)
    a2, b2 = ols(x, np.float32(scale) * y)
    np.testing.assert_allclose(
        np.asarray(a2), scale * np.asarray(a1), rtol=2e-3, atol=2e-4
    )
    np.testing.assert_allclose(
        np.asarray(b2), scale * np.asarray(b1), rtol=2e-3, atol=2e-4
    )


@given(st.booleans(), st.booleans())
@SET
def test_quadratic_features_composition(interaction_only, include_bias):
    rng = np.random.default_rng(3)
    x = rng.normal(size=(2, 3, 5, 2)).astype(np.float32)
    out = np.asarray(
        add_quadratic_features(
            x, interaction_only=interaction_only, include_bias=include_bias
        )
    )
    expected_features = (3 if interaction_only else 5) + int(include_bias)
    assert out.shape[-1] == expected_features
    np.testing.assert_array_equal(out[..., 0], x[..., 0])
    np.testing.assert_array_equal(out[..., 1], x[..., 1])
    np.testing.assert_allclose(
        out[..., 2], x[..., 0] * x[..., 1], rtol=1e-6
    )
    if include_bias:
        np.testing.assert_array_equal(out[..., -1], np.ones_like(out[..., -1]))


# ---- fused layer-pair kernel: parity law over random shapes -------------

@st.composite
def pair_case(draw):
    n_t = draw(st.integers(1, 7))
    b = draw(st.integers(1, 20))
    hidden = draw(st.sampled_from([8, 16]))
    mask_mode = draw(st.sampled_from(["none", "ones", "dropout"]))
    return n_t, b, hidden, mask_mode


@given(pair_case())
@settings(max_examples=10, deadline=None)
@pytest.mark.slow
def test_pair_kernel_matches_scan_for_any_shape(case):
    """LAW: for every (T, B, H, mask) the fused wavefront Pallas program
    (interpreter mode) computes the same outputs AND gradients as the
    two-scan composition — including T=1 (empty wavefront overlap), B=1,
    and row-padding remainders the parametrized tests don't enumerate."""
    import jax
    import jax.numpy as jnp

    from masters_thesis_tpu.ops.lstm_kernel import (
        lstm_pair_recurrence,
        lstm_pair_xla,
    )

    n_t, b, hidden, mask_mode = case
    rng = np.random.default_rng(n_t * 1000 + b * 10 + hidden)
    x1 = jnp.asarray(rng.normal(size=(n_t, b, 4 * hidden)), jnp.float32)
    w1, wi2, w2 = (
        jnp.asarray(rng.normal(size=(hidden, 4 * hidden)) * 0.2, jnp.float32)
        for _ in range(3)
    )
    b2 = jnp.asarray(rng.normal(size=(4 * hidden,)) * 0.1, jnp.float32)
    if mask_mode == "none":
        mask = None
    elif mask_mode == "ones":
        mask = jnp.ones((n_t, b, hidden), jnp.float32)
    else:
        keep = rng.random(size=(n_t, b, hidden)) > 0.25
        mask = jnp.asarray(keep / 0.75, jnp.float32)

    def loss(fn):
        return lambda *a: jnp.sum(fn(*a, mask) ** 2)

    ref = jax.value_and_grad(loss(lstm_pair_xla), argnums=(0, 1, 2, 3, 4))(
        x1, w1, wi2, b2, w2
    )
    out = jax.value_and_grad(
        loss(lambda *a, **k: lstm_pair_recurrence(*a, **k, impl="interpret")),
        argnums=(0, 1, 2, 3, 4),
    )(x1, w1, wi2, b2, w2)
    np.testing.assert_allclose(float(out[0]), float(ref[0]), rtol=1e-4)
    for g_pl, g_ref in zip(out[1], ref[1]):
        np.testing.assert_allclose(
            np.asarray(g_pl), np.asarray(g_ref), atol=3e-4
        )


# ---- L-layer wavefront (stack) kernel: parity law over random shapes ----

@st.composite
def stack_case(draw):
    n_t = draw(st.integers(1, 6))
    b = draw(st.integers(1, 14))
    hidden = draw(st.sampled_from([8, 16]))
    n_layers = draw(st.integers(1, 5))
    mask_mode = draw(st.sampled_from(["none", "dropout"]))
    return n_t, b, hidden, n_layers, mask_mode


@given(stack_case())
@settings(max_examples=8, deadline=None)
@pytest.mark.slow
def test_stack_kernel_matches_scan_for_any_shape(case):
    """LAW: for every (T, B, H, L, mask) the L-deep wavefront Pallas
    program (interpreter mode) computes the same output AND every weight
    gradient as the chained-scan composition — including L=1 (degenerate
    wavefront), T=1, B=1, and row-padding remainders."""
    import jax
    import jax.numpy as jnp

    from masters_thesis_tpu.ops.lstm_kernel import (
        lstm_stack_recurrence,
        lstm_stack_xla,
    )

    n_t, b, hidden, n_layers, mask_mode = case
    rng = np.random.default_rng(
        n_t * 10000 + b * 100 + hidden * 10 + n_layers
    )
    x1 = jnp.asarray(rng.normal(size=(n_t, b, 4 * hidden)), jnp.float32)
    mk = lambda: jnp.asarray(  # noqa: E731
        rng.normal(size=(hidden, 4 * hidden)) * 0.2, jnp.float32
    )
    weights = (
        tuple(mk() for _ in range(n_layers)),
        tuple(mk() for _ in range(n_layers - 1)),
        tuple(
            jnp.asarray(rng.normal(size=(4 * hidden,)) * 0.1, jnp.float32)
            for _ in range(n_layers - 1)
        ),
    )
    if mask_mode == "none":
        masks = None
    else:
        masks = tuple(
            jnp.asarray(
                (rng.random(size=(n_t, b, hidden)) > 0.25) / 0.75,
                jnp.float32,
            )
            for _ in range(n_layers - 1)
        )

    def loss(fn):
        return lambda xp, w: jnp.sum(fn(xp, w, masks) ** 2)

    ref = jax.value_and_grad(loss(lstm_stack_xla), argnums=(0, 1))(
        x1, weights
    )
    out = jax.value_and_grad(
        loss(
            lambda xp, w, m: lstm_stack_recurrence(
                xp, w, m, impl="interpret"
            )
        ),
        argnums=(0, 1),
    )(x1, weights)
    np.testing.assert_allclose(float(out[0]), float(ref[0]), rtol=1e-4)
    for g_pl, g_ref in zip(
        jax.tree_util.tree_leaves(out[1]), jax.tree_util.tree_leaves(ref[1])
    ):
        np.testing.assert_allclose(
            np.asarray(g_pl), np.asarray(g_ref), atol=3e-4
        )
