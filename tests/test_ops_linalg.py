"""Unit tests for the numerical core: OLS and Woodbury inverse covariance.

Oracles are closed forms / numpy lstsq / dense inverses — independent of both
the reference implementation and the code under test (SURVEY.md §4 test plan).
"""

import jax
import jax.numpy as jnp
import numpy as np

from masters_thesis_tpu.ops import ols, inverse_returns_covariance


def _lstsq_oracle(x, y):
    """Per-row numpy lstsq fit of y ≈ a + b x."""
    design = np.stack([np.ones_like(x), x], axis=-1)
    coef, *_ = np.linalg.lstsq(design, y.T, rcond=None)
    return coef[0], coef[1]


def test_ols_unbatched_matches_lstsq(rng):
    x = rng.normal(size=50).astype(np.float32)
    y = rng.normal(size=(7, 50)).astype(np.float32)
    alphas, betas = ols(jnp.asarray(x), jnp.asarray(y))
    a_ref, b_ref = _lstsq_oracle(x, y)
    np.testing.assert_allclose(alphas, a_ref, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(betas, b_ref, rtol=1e-4, atol=1e-4)
    assert alphas.shape == (7,)


def test_ols_batched_matches_lstsq(rng):
    x = rng.normal(size=(4, 30)).astype(np.float32)
    y = rng.normal(size=(4, 5, 30)).astype(np.float32)
    alphas, betas = ols(jnp.asarray(x), jnp.asarray(y))
    assert alphas.shape == (4, 5)
    for b in range(4):
        a_ref, b_ref = _lstsq_oracle(x[b], y[b])
        np.testing.assert_allclose(alphas[b], a_ref, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(betas[b], b_ref, rtol=1e-4, atol=1e-4)


def test_ols_recovers_exact_line():
    x = jnp.linspace(-1.0, 1.0, 20)
    y = (2.5 + 0.5 * x)[None, :]
    alphas, betas = ols(x, y)
    np.testing.assert_allclose(float(alphas), 2.5, atol=1e-5)
    np.testing.assert_allclose(float(betas), 0.5, atol=1e-5)


def test_ols_degenerate_regressor_uses_pinv():
    # Constant market → singular Gram matrix; pinv must not blow up.
    x = jnp.ones(10)
    y = jnp.ones((3, 10)) * 2.0
    alphas, betas = ols(x, y)
    assert np.all(np.isfinite(np.asarray(alphas)))
    assert np.all(np.isfinite(np.asarray(betas)))
    # Pseudo-inverse solution predicts the mean: alpha + beta*1 == 2.
    np.testing.assert_allclose(np.asarray(alphas + betas), 2.0, atol=1e-4)


def test_ols_is_jittable(rng):
    x = jnp.asarray(rng.normal(size=(2, 16)).astype(np.float32))
    y = jnp.asarray(rng.normal(size=(2, 3, 16)).astype(np.float32))
    eager = ols(x, y)
    jitted = jax.jit(ols)(x, y)
    np.testing.assert_allclose(eager[0], jitted[0], rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(eager[1], jitted[1], rtol=1e-5, atol=1e-6)


def test_woodbury_matches_dense_inverse(rng):
    k = 12
    beta = rng.normal(loc=1.0, scale=0.3, size=(k, 1)).astype(np.float64)
    psi_diag = rng.uniform(0.5, 2.0, size=k).astype(np.float64)
    f_var = 0.7

    sigma = f_var * beta @ beta.T + np.diag(psi_diag)
    dense_inv = np.linalg.inv(sigma)

    woodbury = inverse_returns_covariance(
        jnp.asarray(beta, dtype=jnp.float32),
        jnp.asarray(np.diag(1.0 / psi_diag), dtype=jnp.float32),
        jnp.asarray(f_var, dtype=jnp.float32),
    )
    np.testing.assert_allclose(np.asarray(woodbury), dense_inv, rtol=2e-3, atol=2e-3)


def test_woodbury_symmetry(rng):
    k = 8
    beta = jnp.asarray(rng.normal(size=(k, 1)).astype(np.float32))
    inv_psi = jnp.diag(jnp.asarray(rng.uniform(0.5, 2.0, size=k).astype(np.float32)))
    out = inverse_returns_covariance(beta, inv_psi, jnp.float32(0.5))
    np.testing.assert_allclose(out, out.T, rtol=1e-5, atol=1e-6)
