"""Unit tests for the numerical core: OLS and Woodbury inverse covariance.

Oracles are closed forms / numpy lstsq / dense inverses — independent of both
the reference implementation and the code under test (SURVEY.md §4 test plan).
"""

import jax
import jax.numpy as jnp
import numpy as np

import pytest

from masters_thesis_tpu.ops import inverse_returns_covariance, ols, ols_k


def _lstsq_oracle(x, y):
    """Per-row numpy lstsq fit of y ≈ a + b x."""
    design = np.stack([np.ones_like(x), x], axis=-1)
    coef, *_ = np.linalg.lstsq(design, y.T, rcond=None)
    return coef[0], coef[1]


def _lstsq_k_oracle(f, y):
    """Per-row numpy lstsq fit of y ≈ a + B f with F regressors.

    ``f``: (T, F) factor returns; ``y``: (K, T). Returns (alphas (K,),
    betas (K, F)).
    """
    design = np.concatenate([np.ones((f.shape[0], 1)), f], axis=-1)
    coef, *_ = np.linalg.lstsq(design, y.T, rcond=None)
    return coef[0], coef[1:].T


def test_ols_unbatched_matches_lstsq(rng):
    x = rng.normal(size=50).astype(np.float32)
    y = rng.normal(size=(7, 50)).astype(np.float32)
    alphas, betas = ols(jnp.asarray(x), jnp.asarray(y))
    a_ref, b_ref = _lstsq_oracle(x, y)
    np.testing.assert_allclose(alphas, a_ref, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(betas, b_ref, rtol=1e-4, atol=1e-4)
    assert alphas.shape == (7,)


def test_ols_batched_matches_lstsq(rng):
    x = rng.normal(size=(4, 30)).astype(np.float32)
    y = rng.normal(size=(4, 5, 30)).astype(np.float32)
    alphas, betas = ols(jnp.asarray(x), jnp.asarray(y))
    assert alphas.shape == (4, 5)
    for b in range(4):
        a_ref, b_ref = _lstsq_oracle(x[b], y[b])
        np.testing.assert_allclose(alphas[b], a_ref, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(betas[b], b_ref, rtol=1e-4, atol=1e-4)


def test_ols_recovers_exact_line():
    x = jnp.linspace(-1.0, 1.0, 20)
    y = (2.5 + 0.5 * x)[None, :]
    alphas, betas = ols(x, y)
    np.testing.assert_allclose(float(alphas), 2.5, atol=1e-5)
    np.testing.assert_allclose(float(betas), 0.5, atol=1e-5)


def test_ols_degenerate_regressor_uses_pinv():
    # Constant market → singular Gram matrix; pinv must not blow up.
    x = jnp.ones(10)
    y = jnp.ones((3, 10)) * 2.0
    alphas, betas = ols(x, y)
    assert np.all(np.isfinite(np.asarray(alphas)))
    assert np.all(np.isfinite(np.asarray(betas)))
    # Pseudo-inverse solution predicts the mean: alpha + beta*1 == 2.
    np.testing.assert_allclose(np.asarray(alphas + betas), 2.0, atol=1e-4)


def test_ols_is_jittable(rng):
    x = jnp.asarray(rng.normal(size=(2, 16)).astype(np.float32))
    y = jnp.asarray(rng.normal(size=(2, 3, 16)).astype(np.float32))
    eager = ols(x, y)
    jitted = jax.jit(ols)(x, y)
    np.testing.assert_allclose(eager[0], jitted[0], rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(eager[1], jitted[1], rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("n_f", [1, 3, 5])
def test_ols_k_matches_lstsq(rng, n_f):
    f = rng.normal(size=(40, n_f)).astype(np.float32)
    y = rng.normal(size=(6, 40)).astype(np.float32)
    alphas, betas = ols_k(jnp.asarray(f), jnp.asarray(y))
    a_ref, b_ref = _lstsq_k_oracle(f, y)
    assert alphas.shape == (6,) and betas.shape == (6, n_f)
    np.testing.assert_allclose(alphas, a_ref, rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(betas, b_ref, rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize("n_f", [1, 3])
def test_ols_k_batched_matches_lstsq(rng, n_f):
    f = rng.normal(size=(4, 30, n_f)).astype(np.float32)
    y = rng.normal(size=(4, 5, 30)).astype(np.float32)
    alphas, betas = ols_k(jnp.asarray(f), jnp.asarray(y))
    assert alphas.shape == (4, 5) and betas.shape == (4, 5, n_f)
    for b in range(4):
        a_ref, b_ref = _lstsq_k_oracle(f[b], y[b])
        np.testing.assert_allclose(alphas[b], a_ref, rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(betas[b], b_ref, rtol=1e-3, atol=1e-3)


def test_ols_k_single_factor_bitwise_matches_ols(rng):
    # The K=1 branch of ols_k IS the scalar path op-for-op — the
    # bit-identity contract that keeps existing runs reproducible.
    x = rng.normal(size=(4, 30)).astype(np.float32)
    y = rng.normal(size=(4, 5, 30)).astype(np.float32)
    a1, b1 = ols(jnp.asarray(x), jnp.asarray(y))
    ak, bk = ols_k(jnp.asarray(x)[..., None], jnp.asarray(y))
    assert np.array_equal(np.asarray(a1), np.asarray(ak))
    assert np.array_equal(np.asarray(b1), np.asarray(bk)[..., 0])


def test_ols_k_recovers_exact_plane():
    t = 24
    f = jnp.stack(
        [jnp.linspace(-1.0, 1.0, t), jnp.linspace(2.0, -1.0, t) ** 2],
        axis=-1,
    )
    true_a = jnp.asarray([0.5, -1.5])
    true_b = jnp.asarray([[2.0, -0.5], [1.0, 3.0]])
    y = true_a[:, None] + true_b @ f.T
    alphas, betas = ols_k(f, y)
    np.testing.assert_allclose(np.asarray(alphas), true_a, atol=1e-4)
    np.testing.assert_allclose(np.asarray(betas), true_b, atol=1e-4)


def test_ols_k_is_jittable(rng):
    f = jnp.asarray(rng.normal(size=(2, 16, 3)).astype(np.float32))
    y = jnp.asarray(rng.normal(size=(2, 5, 16)).astype(np.float32))
    eager = ols_k(f, y)
    jitted = jax.jit(ols_k)(f, y)
    np.testing.assert_allclose(eager[0], jitted[0], rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(eager[1], jitted[1], rtol=1e-5, atol=1e-6)


def test_woodbury_matches_dense_inverse(rng):
    k = 12
    beta = rng.normal(loc=1.0, scale=0.3, size=(k, 1)).astype(np.float64)
    psi_diag = rng.uniform(0.5, 2.0, size=k).astype(np.float64)
    f_var = 0.7

    sigma = f_var * beta @ beta.T + np.diag(psi_diag)
    dense_inv = np.linalg.inv(sigma)

    woodbury = inverse_returns_covariance(
        jnp.asarray(beta, dtype=jnp.float32),
        jnp.asarray(np.diag(1.0 / psi_diag), dtype=jnp.float32),
        jnp.asarray(f_var, dtype=jnp.float32),
    )
    np.testing.assert_allclose(np.asarray(woodbury), dense_inv, rtol=2e-3, atol=2e-3)


def test_woodbury_symmetry(rng):
    k = 8
    beta = jnp.asarray(rng.normal(size=(k, 1)).astype(np.float32))
    inv_psi = jnp.diag(jnp.asarray(rng.uniform(0.5, 2.0, size=k).astype(np.float32)))
    out = inverse_returns_covariance(beta, inv_psi, jnp.float32(0.5))
    np.testing.assert_allclose(out, out.T, rtol=1e-5, atol=1e-6)
