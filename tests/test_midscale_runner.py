"""Control logic of the CPU midscale insurance runner.

The training itself is exercised by the live runs; these pin the pieces
that decide WHETHER and WHAT to run: core-yield behavior against the
orchestrator state file, recorded-cell resume, and the metadata schema
staying disjoint from eval_cell's row keys (a collision silently
overwrote the model ΔL dict once — caught in round 5)."""

from __future__ import annotations

import ast
import importlib.util
import json
from pathlib import Path

_REPO_ROOT = Path(__file__).resolve().parent.parent


def _load():
    spec = importlib.util.spec_from_file_location(
        "_midscale", _REPO_ROOT / "sweeps" / "run_warmup_cpu_midscale.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _eval_cell_row_keys() -> set[str]:
    """The keys of eval_cell.py's output row, read from its source.

    Parsed from the dict literal inside the ``json.dumps(...)`` call (the
    module itself imports the heavy jax stack, so importing it here would
    drag TPU/compile costs into a schema check). Parsing the source keeps
    the collision guard honest: a key added to eval_cell.py shows up here
    without anyone remembering to update a hardcoded copy."""
    tree = ast.parse((_REPO_ROOT / "sweeps" / "eval_cell.py").read_text())
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and getattr(node.func, "attr", "") == "dumps"
            and node.args
            and isinstance(node.args[0], ast.Dict)
        ):
            keys = {
                k.value
                for k in node.args[0].keys
                if isinstance(k, ast.Constant)
            }
            # Sanity floor so a refactor that empties the literal (or a
            # second json.dumps appearing first) fails loudly, not green.
            assert {"checkpoint", "model", "ols"} <= keys, keys
            return keys
    raise AssertionError("eval_cell.py row dict literal not found")


def test_scale_meta_never_collides_with_eval_row_schema():
    mod = _load()
    eval_row_keys = _eval_cell_row_keys() | {
        "cell", "train_wall_s",  # added by the runner itself
    }
    collisions = eval_row_keys & set(mod.SCALE_META)
    assert not collisions, (
        f"SCALE_META keys {collisions} would overwrite eval row fields "
        "on record_cell's row.update"
    )


def test_yields_core_whenever_orchestrator_is_not_waiting(
    monkeypatch, tmp_path
):
    mod = _load()
    state = tmp_path / "R5_STATE"
    monkeypatch.setattr(mod, "STATE", state)
    # No orchestrator at all: the core is ours.
    assert not mod.tpu_queue_active()
    state.write_text("wait\n")
    assert not mod.tpu_queue_active()
    # Measurement phases own the core unconditionally (their children are
    # timeout-capped; contention can kill a healthy TPU child).
    monkeypatch.setattr(mod, "_tpu_process_alive", lambda: False)
    for phase in ("gates", "bench", "ab_sweep", "profile"):
        state.write_text(phase)
        assert mod.tpu_queue_active(), phase
    # grid/done/interrupted defer to the live process table: a relay-backed
    # process running means yield; an idle wedge-wait means the core is
    # ours (r5: the state sat at "grid" for hours of wedge).
    for phase in ("grid", "done", "interrupted"):
        state.write_text(phase)
        assert not mod.tpu_queue_active(), phase
    monkeypatch.setattr(mod, "_tpu_process_alive", lambda: True)
    for phase in ("grid", "done", "interrupted"):
        state.write_text(phase)
        assert mod.tpu_queue_active(), phase


def test_tpu_process_scan_filters_self_and_supervisors(monkeypatch):
    """The /proc scan must key on comm==python*: supervisors whose argv
    merely EMBEDS script names (the session driver's prompt text contains
    'train.py') must not read as relay-backed processes — and this
    runner's own midscale children must be filtered."""
    mod = _load()

    fake = {
        "1": ("claude", "claude -p ... python train.py bench.py ..."),
        "2": ("python3", "python train.py trainer=slow midscale marker"),
        "3": ("python3", "python -c import jax; jax.devices()"),
    }

    class FakeEntry:
        def __init__(self, name):
            self.name = name

        def __truediv__(self, part):
            return FakeFile(self.name, part)

    class FakeFile:
        def __init__(self, pid, part):
            self.pid, self.part = pid, part

        def read_text(self):
            return fake[self.pid][0]

        def read_bytes(self):
            return fake[self.pid][1].encode()

    class FakeProc:
        def iterdir(self):
            return [FakeEntry(k) for k in fake]

    real_path = mod.Path
    monkeypatch.setattr(
        mod, "Path",
        lambda p="": FakeProc() if p == "/proc" else real_path(p),
    )
    assert not mod._tpu_process_alive()
    fake["4"] = ("python3", "/opt/venv/bin/python /root/repo/train.py x")
    assert mod._tpu_process_alive()


def test_done_cells_reads_last_rows(monkeypatch, tmp_path):
    mod = _load()
    out = tmp_path / "mid.jsonl"
    monkeypatch.setattr(mod, "OUT", out)
    assert mod.done_cells() == set()
    out.write_text(
        json.dumps({"cell": "a"}) + "\n" + json.dumps({"cell": "b"}) + "\n"
    )
    assert mod.done_cells() == {"a", "b"}


def test_run_and_record_skips_recorded_and_yields_when_active(
    monkeypatch, tmp_path
):
    mod = _load()
    out = tmp_path / "mid.jsonl"
    out.write_text(json.dumps({"cell": "done_cell"}) + "\n")
    monkeypatch.setattr(mod, "OUT", out)
    state = tmp_path / "R5_STATE"
    monkeypatch.setattr(mod, "STATE", state)
    trained = []
    monkeypatch.setattr(
        mod, "train_cell", lambda cell, ov, t: trained.append(cell) or True
    )
    monkeypatch.setattr(
        mod, "record_cell", lambda *a, **k: None
    )

    # Recorded: skipped without training — even while the TPU queue is
    # active (the skip check must run before the yield check, or resumed
    # runs would die on their first recorded cell).
    state.write_text("bench")
    assert mod.run_and_record("done_cell", [], tmp_path / "x", [])
    assert trained == []

    # Unrecorded but TPU queue active: exits instead of training.
    try:
        mod.run_and_record("fresh_cell", [], tmp_path / "x", [])
    except SystemExit as exc:
        assert exc.code == 0
    else:  # pragma: no cover - the yield MUST raise
        raise AssertionError("runner did not yield the core")
    assert trained == []
