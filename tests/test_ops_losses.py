"""Unit tests for the NLL / MSE loss cores against scipy oracles."""

import jax
import jax.numpy as jnp
import numpy as np
import scipy.stats

from masters_thesis_tpu.ops import (
    multivariate_gaussian_nll,
    mean_squared_error,
    inverse_returns_covariance,
)


def _random_spd(k, rng):
    a = rng.normal(size=(k, k))
    return a @ a.T + k * np.eye(k)


def test_nll_matches_scipy_logpdf(rng):
    k, n = 6, 9
    mean = rng.normal(size=(k, 1))
    cov = _random_spd(k, rng)
    target = rng.normal(size=(k, n))

    nll = multivariate_gaussian_nll(
        jnp.asarray(mean, jnp.float32),
        jnp.asarray(np.linalg.inv(cov), jnp.float32),
        jnp.asarray(target, jnp.float32),
    )
    oracle = -scipy.stats.multivariate_normal(mean[:, 0], cov).logpdf(target.T).sum()
    np.testing.assert_allclose(float(nll), oracle, rtol=1e-4)


def test_nll_nan_on_non_positive_definite(rng):
    k, n = 5, 5  # odd K so det(-I) < 0
    mean = jnp.zeros((k, 1))
    target = jnp.asarray(rng.normal(size=(k, n)), jnp.float32)
    bad = jnp.asarray(-np.eye(k), jnp.float32)  # negative determinant
    assert np.isnan(float(multivariate_gaussian_nll(mean, bad, target)))


def test_nll_grad_flows_through_woodbury(rng):
    """End-to-end differentiability: d NLL / d beta must be finite — this is
    the training path of the NLL objective (reference: src/model.py:245-249)."""
    k, n = 5, 7
    target = jnp.asarray(rng.normal(size=(k, n)), jnp.float32)
    inv_psi = jnp.eye(k) * 2.0
    f_var = jnp.float32(0.5)

    def loss_fn(beta):
        mean = beta * 0.1
        inv_cov = inverse_returns_covariance(beta, inv_psi, f_var)
        return multivariate_gaussian_nll(mean, inv_cov, target)

    g = jax.grad(loss_fn)(jnp.ones((k, 1)))
    assert np.all(np.isfinite(np.asarray(g)))


def test_mse_matches_numpy(rng):
    a = rng.normal(size=(10, 3))
    b = rng.normal(size=(10, 3))
    got = mean_squared_error(jnp.asarray(a, jnp.float32), jnp.asarray(b, jnp.float32))
    np.testing.assert_allclose(float(got), ((a - b) ** 2).mean(), rtol=1e-5)


class TestSingleFactorFusedNll:
    """The fused O(K*n) NLL must match the dense Woodbury+slogdet path."""

    def _random_inputs(self, rng, k=50, n=30):
        mean = rng.normal(size=(k, 1)).astype(np.float32)
        beta = rng.normal(1.0, 0.3, size=(k, 1)).astype(np.float32)
        inv_psi = rng.uniform(0.5, 5.0, size=(k,)).astype(np.float32)
        f_var = np.float32(rng.uniform(0.1, 2.0))
        target = rng.normal(size=(k, n)).astype(np.float32)
        return mean, beta, inv_psi, f_var, target

    def test_matches_dense_path(self, rng):
        from masters_thesis_tpu.ops import (
            inverse_returns_covariance,
            multivariate_gaussian_nll,
            single_factor_gaussian_nll,
        )

        for _ in range(5):
            mean, beta, inv_psi, f_var, target = self._random_inputs(rng)
            dense = multivariate_gaussian_nll(
                mean,
                inverse_returns_covariance(beta, jnp.diag(inv_psi), f_var),
                target,
            )
            fused = single_factor_gaussian_nll(
                mean, beta, inv_psi, f_var, target
            )
            np.testing.assert_allclose(
                float(fused), float(dense), rtol=2e-4
            )

    def test_non_psd_inputs_yield_nan(self, rng):
        from masters_thesis_tpu.ops import single_factor_gaussian_nll

        mean, beta, inv_psi, f_var, target = self._random_inputs(rng, k=8)
        inv_psi[2] = -1.0  # one non-positive idiosyncratic precision
        out = single_factor_gaussian_nll(mean, beta, inv_psi, f_var, target)
        assert np.isnan(float(out))

    def test_gradients_finite(self, rng):
        import jax

        from masters_thesis_tpu.ops import single_factor_gaussian_nll

        mean, beta, inv_psi, f_var, target = self._random_inputs(rng, k=12)

        def loss(mean, beta):
            return single_factor_gaussian_nll(
                mean, beta, inv_psi, f_var, target
            )

        g_mean, g_beta = jax.grad(loss, argnums=(0, 1))(mean, beta)
        assert np.isfinite(np.asarray(g_mean)).all()
        assert np.isfinite(np.asarray(g_beta)).all()
