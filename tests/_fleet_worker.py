"""Simulated fleet process for flight-recorder / postmortem tests.

Not a pytest module (no ``test_`` prefix): tests/test_flightrec.py spawns
2 of these as a simulated multi-host run — jax-free, so the scenarios
(SIGTERM a victim, hard-hang one past the heartbeat deadline) exercise
exactly the forensic path that must work when the backend is wedged.

Usage: python tests/_fleet_worker.py <root> <rank> <world> <scenario>

Writes its stream to ``<root>/p<rank>/`` with identity from the
``JAX_PROCESS_INDEX``/``JAX_PROCESS_COUNT`` env fallback (set here, the
same vars ``parallel.mesh.distributed_initialize`` exports on real pods).

Scenarios:

- ``healthy``        — 3 quick epochs, run_finished, clean close.
- ``victim-sigterm`` — emits 2 epochs then sleeps forever; the test sends
  SIGTERM and the recorder's handler dumps crashdump.json on the way down.
- ``victim-hang``    — emits 2 epochs then stops beating with a ~0.5s hang
  timeout; the watchdog thread dumps, then the worker prints the dump path
  and idles until the test kills it.
"""

from __future__ import annotations

import os
import sys
import time
from pathlib import Path


def main() -> None:
    root, rank, world, scenario = (
        Path(sys.argv[1]),
        int(sys.argv[2]),
        int(sys.argv[3]),
        sys.argv[4],
    )
    os.environ["JAX_PROCESS_INDEX"] = str(rank)
    os.environ["JAX_PROCESS_COUNT"] = str(world)

    from masters_thesis_tpu.telemetry import TelemetryRun

    tel = TelemetryRun(root / f"p{rank}", run_id=f"fleet-p{rank}")
    rec = tel.attach_flight_recorder(
        heartbeat_interval_s=0.1,
        hang_timeout_s=0.5 if scenario == "victim-hang" else None,
    )
    rec.beat(phase="setup")
    # The tracer adopts MTT_TRACE_ID / MTT_PARENT_SPAN from the env the
    # test (or a real supervisor) exported — one trace across the fleet.
    fit_span = tel.tracer.start("trainer.fit", trainer="fleet", rank=rank)
    tel.event(
        "run_started", platform="sim", n_devices=1, strategy="fleet-sim",
        epoch_mode="scan", steps_per_epoch=4, max_epochs=3, start_epoch=0,
        objective="mse", trainer="fleet", seed=0,
        trace_id=tel.tracer.trace_id,
    )
    epochs = 3 if scenario == "healthy" else 2
    for epoch in range(epochs):
        rec.beat(phase="train", epoch=epoch)
        rec.track_scalar("loss/total/train", 1.0 / (epoch + 1))
        # Rank-skewed walls so the aggregator has real skew to report.
        wall = 0.05 * (1 + rank) if scenario == "healthy" else 0.05
        tel.event(
            "epoch", epoch=epoch, steps=4, wall_s=wall, dispatch_s=0.001,
            device_s=None, data_wait_s=0.0, compile_events=0,
            compiled=False, fenced=False, steps_per_sec=4.0 / wall,
        )
        tel.tracer.emit_span(
            "train.epoch", start_ts=time.time() - wall, dur_s=wall,
            parent=fit_span, epoch=epoch, dispatch_s=0.001,
            data_wait_s=0.0,
        )

    if scenario == "healthy":
        tel.tracer.end(fit_span, status="ok", epochs=epochs)
        tel.event(
            "run_finished", epochs=epochs, total_steps=4 * epochs,
            steps_per_sec=40.0, diverged=False, best_val=0.5,
            epoch_compiles=1, eval_compiles=0,
        )
        tel.close()
        print("done", flush=True)
        return

    # Both victim scenarios: signal readiness, then stop making progress.
    print("ready", flush=True)
    if scenario == "victim-sigterm":
        # The SIGTERM handler dumps and re-delivers; this sleep never ends
        # from the worker's side.
        while True:
            time.sleep(0.5)
    if scenario == "victim-hang":
        # No more beats: the watchdog thread must fire within ~0.5s and
        # dump. Wait for the dump, report it, then idle for the kill.
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if rec.crashdump_path.exists():
                print("dumped", flush=True)
                break
            time.sleep(0.1)
        while True:
            time.sleep(0.5)
    raise SystemExit(f"unknown scenario: {scenario}")


if __name__ == "__main__":
    main()
