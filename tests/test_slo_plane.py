"""Live telemetry plane tests: SLO burn-rate math, debounce, the
Prometheus exposition surface, the tail-cursor reader, and the watch
console.

The e2e contract (ISSUE acceptance): a serve run under sustained load
with an injected latency fault must show ``alert_fired`` (burn-rate
rule) in the live ``/slo`` endpoint BEFORE the run ends, then
``alert_resolved`` after the fault clears — and ``watch --once`` plus
the post-hoc ``summarize`` alerts section must tell the same story the
live plane told.
"""

import json
import math
import time
import urllib.request
from pathlib import Path

import pytest

from masters_thesis_tpu.resilience import faults
from masters_thesis_tpu.telemetry.events import EventSink, read_new_lines
from masters_thesis_tpu.telemetry.exposition import (
    ExpositionServer,
    attach_exposition,
    escape_help,
    escape_label_value,
    render_prometheus,
    sanitize_metric_name,
)
from masters_thesis_tpu.telemetry.registry import MetricsRegistry
from masters_thesis_tpu.telemetry.report import alert_state, summarize_path
from masters_thesis_tpu.telemetry.run import TelemetryRun
from masters_thesis_tpu.telemetry.slo import (
    SLOEngine,
    SLORule,
    burn_rate,
    default_serve_rules,
    default_train_rules,
    window_stats,
)
from masters_thesis_tpu.telemetry.watch import (
    FleetWatch,
    render_watch,
    run_watch,
)


@pytest.fixture(autouse=True)
def _no_leaked_faults(monkeypatch):
    monkeypatch.delenv(faults.FAULT_PLAN_ENV, raising=False)
    monkeypatch.delenv(faults.ATTEMPT_ENV, raising=False)
    yield
    faults.clear_plan()


# ------------------------------------------------------- burn-rate math


class TestBurnRate:
    def test_burn_one_means_budget_lasts_the_period(self):
        # With a 99% target the budget is 1%; a 1% error rate burns it
        # exactly at sustainment rate.
        assert math.isclose(burn_rate(0.01, 0.99), 1.0)

    def test_fast_slow_pairs(self):
        assert math.isclose(burn_rate(0.02, 0.99), 2.0)
        assert math.isclose(burn_rate(0.10, 0.99), 10.0)
        assert math.isclose(burn_rate(0.05, 0.95), 1.0)
        assert burn_rate(0.0, 0.99) == 0.0

    def test_budget_exhaustion_edge(self):
        # target >= 1 leaves zero budget: any error burns infinitely
        # fast, but a clean window is still burn 0 (not NaN, not inf).
        assert burn_rate(0.001, 1.0) == math.inf
        assert burn_rate(1.0, 1.0) == math.inf
        assert burn_rate(0.0, 1.0) == 0.0

    def test_monotone_in_error_rate(self):
        burns = [burn_rate(e / 100, 0.99) for e in range(0, 11)]
        assert burns == sorted(burns)


def test_window_stats_counts_and_p99():
    now = 1000.0
    reqs = [(now - 1.0 - 0.01 * i, "ok", 0.001 * (i + 1)) for i in range(99)]
    reqs.append((now - 0.5, "shed", None))
    reqs.append((now - 5000.0, "ok", 9.9))  # far outside the window
    stats = window_stats(reqs, now, 60.0)
    assert stats["n"] == 100
    assert stats["ok"] == 99
    assert stats["shed"] == 1
    assert stats["errored"] == 1  # the shed consumes error budget
    assert math.isclose(stats["error_rate"], 0.01)
    assert math.isclose(stats["shed_pct"], 1.0)
    # Nearest-rank p99 over the 99 samples that carried a duration.
    assert math.isclose(stats["p99_s"], 0.098)
    assert math.isclose(stats["qps"], 100 / 60.0)


def test_window_stats_empty_window():
    stats = window_stats([], 0.0, 60.0)
    assert stats["n"] == 0
    assert stats["p99_s"] is None
    assert stats["error_rate"] == 0.0


def test_rule_validation():
    with pytest.raises(ValueError, match="unknown SLO rule kind"):
        SLORule("bad", "not_a_kind")
    with pytest.raises(ValueError, match="fast window"):
        SLORule("bad", "burn_rate", fast_window_s=300.0, slow_window_s=60.0)
    dup = [SLORule("x", "burn_rate"), SLORule("x", "p99_latency")]
    with pytest.raises(ValueError, match="duplicate"):
        SLOEngine("/nonexistent", rules=dup)


def test_default_rule_sets_cover_the_issue_signals():
    serve = {r.kind for r in default_serve_rules()}
    train = {r.kind for r in default_train_rules()}
    assert {"p99_latency", "shed_pct", "burn_rate"} <= serve
    assert {"starvation_pct", "recompile", "divergence"} <= train
    assert "heartbeat_staleness" in serve & train


# -------------------------------------------------- engine + debounce


def _spans(sink, now, n, status="ok", dur_s=0.005):
    for i in range(n):
        sink.emit(
            "span", name="serve.request", cat="serve", span_id=f"s{i}",
            start_ts=now, dur_s=dur_s, status=status,
        )


def _mk_run(tmp_path, name="serve"):
    return TelemetryRun(tmp_path / name, run_id=name)


def test_burn_rule_requires_both_windows(tmp_path):
    """A breach confined to the fast window must NOT fire: the slow
    window is exactly what stops a brief blip from paging."""
    tel = _mk_run(tmp_path)
    rule = SLORule(
        "burn", "burn_rate", threshold=2.0, target=0.99,
        fast_window_s=10.0, slow_window_s=1000.0,
    )
    engine = SLOEngine(tel.run_dir, rules=[rule])
    now = time.time()
    # 400 old ok requests dilute the slow window; 8 fresh sheds saturate
    # the fast one. Timestamps are controlled, so feed the request deque
    # directly (the ingest path is covered by the incremental-tail test).
    engine._requests.extend(
        [(now - 500.0, "ok", 0.001)] * 400 + [(now - 1.0, "shed", None)] * 8
    )
    value, breached, detail = engine._evaluate(rule, now)
    assert detail["burn_fast"] == pytest.approx(100.0)
    assert detail["burn_slow"] == pytest.approx(
        100.0 * 8 / 408, rel=1e-6
    )
    assert not breached  # slow window still under threshold
    # Once the sheds dominate the slow window too, the rule breaches.
    engine._requests.clear()
    engine._requests.extend([(now - 1.0, "shed", None)] * 8)
    value, breached, _ = engine._evaluate(rule, now)
    assert breached
    tel.close()


def test_debounce_for_ticks_delays_fire(tmp_path):
    tel = _mk_run(tmp_path)
    rule = SLORule(
        "p99", "p99_latency", threshold=0.01, fast_window_s=60.0,
        slow_window_s=60.0, for_ticks=2,
    )
    engine = SLOEngine(tel.run_dir, rules=[rule], sink=tel.sink)
    now = time.time()
    engine._requests.extend([(now, "ok", 0.5)] * 10)
    s1 = engine.tick(now)
    assert s1["firing"] == []  # first breaching tick: pending, not fired
    s2 = engine.tick(now)
    assert s2["firing"] == ["p99"]
    assert s2["just_fired"] == ["p99"]
    tel.close()


def test_debounce_flapping_fires_once(tmp_path):
    """A signal that alternates breach/clean every tick fires exactly
    once and stays firing — clear_ticks=2 never sees two clean ticks."""
    tel = _mk_run(tmp_path)
    rule = SLORule(
        "flap", "divergence", threshold=0.0, for_ticks=1, clear_ticks=2,
        fast_window_s=60.0, slow_window_s=60.0,
    )
    engine = SLOEngine(tel.run_dir, rules=[rule], sink=tel.sink)
    now = time.time()
    fired_events = 0
    for i in range(10):
        engine._diverged = i % 2 == 0  # flap the signal every tick
        state = engine.tick(now + i)
        fired_events += len(state["just_fired"])
        if i > 0:
            assert state["firing"] == ["flap"], f"tick {i} dropped the alert"
        assert state["just_resolved"] == []
    assert fired_events == 1
    assert engine._alerts["flap"].fired_count == 1
    tel.close()


def test_alert_resolves_after_clear_ticks_and_emits_events(tmp_path):
    tel = _mk_run(tmp_path)
    rule = SLORule(
        "burn", "burn_rate", threshold=2.0, target=0.99,
        fast_window_s=5.0, slow_window_s=5.0, for_ticks=1, clear_ticks=2,
    )
    engine = SLOEngine(tel.run_dir, rules=[rule], sink=tel.sink)
    now = time.time()
    engine._requests.extend([(now, "shed", None)] * 10)
    assert engine.tick(now)["just_fired"] == ["burn"]
    # The breach ages out of both windows; two clean ticks resolve it.
    assert engine.tick(now + 10)["firing"] == ["burn"]
    state = engine.tick(now + 11)
    assert state["just_resolved"] == ["burn"]
    assert state["firing"] == []
    tel.close()

    events = [
        json.loads(line)
        for line in (tel.run_dir / "events.jsonl").read_text().splitlines()
    ]
    fired = [e for e in events if e["kind"] == "alert_fired"]
    resolved = [e for e in events if e["kind"] == "alert_resolved"]
    assert len(fired) == 1 and len(resolved) == 1
    assert fired[0]["rule"] == "burn"
    assert fired[0]["slo_kind"] == "burn_rate"
    assert fired[0]["burn_fast"] == pytest.approx(100.0)
    assert resolved[0]["active_s"] == pytest.approx(11.0, abs=0.5)
    # The post-hoc fold agrees with what the live engine did.
    st = alert_state(events)
    assert st["fired"] == 1 and st["resolved"] == 1
    assert st["active"] == []


def test_engine_tails_stream_incrementally(tmp_path):
    tel = _mk_run(tmp_path)
    engine = SLOEngine(
        tel.run_dir,
        rules=[SLORule("p99", "p99_latency", threshold=10.0)],
    )
    _spans(tel.sink, time.time(), 3)
    engine.tick()
    seen_after_first = engine._events_seen
    assert seen_after_first >= 3
    engine.tick()
    assert engine._events_seen == seen_after_first  # cursor at EOF
    _spans(tel.sink, time.time(), 2)
    engine.tick()
    assert engine._events_seen == seen_after_first + 2
    tel.close()


def test_slo_evaluate_wedge_fault_stalls_the_plane(tmp_path):
    """Chaos: wedging ``slo.evaluate`` makes ticks no-ops (stale state)
    without touching anything else — monitoring fails safe."""
    tel = _mk_run(tmp_path)
    engine = SLOEngine(
        tel.run_dir, rules=[SLORule("div", "divergence")],
    )
    engine.tick()
    assert engine.state()["ticks"] == 1
    faults.install_plan(
        faults.FaultPlan(
            [faults.FaultSpec("slo.evaluate", "wedge", attempt=None)]
        )
    )
    engine._diverged = True
    stale = engine.tick()
    assert stale["ticks"] == 1  # no-op: the published state is stale
    assert stale["firing"] == []
    faults.clear_plan()
    assert engine.tick()["firing"] == ["div"]
    tel.close()


# ------------------------------------------------- exposition rendering


def test_sanitize_metric_name():
    assert sanitize_metric_name("serve/request_wall_s") == (
        "mtt_serve_request_wall_s"
    )
    assert sanitize_metric_name("9lives") == "mtt__9lives"
    assert sanitize_metric_name("a:b.c-d") == "mtt_a:b_c_d"


def test_escaping_text_format():
    assert escape_label_value('say "hi"\n\\x') == r"say \"hi\"\n\\x"
    assert escape_help("line1\nline2\\end") == r"line1\nline2\\end"


def test_render_prometheus_full_surface():
    reg = MetricsRegistry(tags={"host": 'h"1"', "pid": 7})
    reg.counter("serve/requests").inc(5)
    reg.gauge("fleet/n_live").set(3)
    h = reg.histogram("serve/wall_s")
    for v in (0.01, 0.02, 0.03):
        h.observe(v)
    slo_state = {
        "rules": {
            "burn": {"firing": True, "value": 12.5},
            "p99": {"firing": False, "value": None},
        }
    }
    text = render_prometheus(reg.snapshot(), slo_state)
    assert text.endswith("\n")
    assert "# TYPE mtt_serve_requests counter" in text
    assert "# TYPE mtt_fleet_n_live gauge" in text
    assert "# TYPE mtt_serve_wall_s summary" in text
    assert 'quantile="0.99"' in text
    assert "mtt_serve_wall_s_count" in text
    # Label escaping survives into the rendered exposition.
    assert r'host="h\"1\""' in text
    assert 'mtt_slo_firing{host="h\\"1\\"",pid="7",rule="burn"} 1' in text
    assert 'rule="p99"} 0' in text
    assert "mtt_slo_value" in text
    # None renders as NaN, never as the string "None".
    assert " None" not in text


def test_render_prometheus_empty_snapshot():
    assert render_prometheus({"tags": {}, "metrics": {}}) == "\n"


def test_exposition_server_routes(tmp_path):
    tel = _mk_run(tmp_path)
    tel.counter("serve/requests").inc(2)
    engine = SLOEngine(
        tel.run_dir, rules=[SLORule("div", "divergence")], sink=tel.sink
    )
    engine.tick()
    server = attach_exposition(tel, port=0, slo=engine)
    try:
        base = server.url
        body = urllib.request.urlopen(base + "/metrics", timeout=10)
        assert body.headers["Content-Type"].startswith("text/plain")
        text = body.read().decode()
        assert "mtt_serve_requests" in text
        assert "mtt_slo_firing" in text
        hz = json.loads(
            urllib.request.urlopen(base + "/healthz", timeout=10).read()
        )
        assert hz["ok"] is True and hz["firing"] == []
        slo = json.loads(
            urllib.request.urlopen(base + "/slo", timeout=10).read()
        )
        assert slo["ticks"] == 1
        assert "div" in slo["rules"]
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(base + "/nope", timeout=10)
        assert err.value.code == 404
    finally:
        server.close()
        tel.close()
    # The attach is recorded in the stream so operators can find the URL.
    events = [
        json.loads(line)
        for line in (tel.run_dir / "events.jsonl").read_text().splitlines()
    ]
    started = [e for e in events if e["kind"] == "exposition_started"]
    assert started and started[0]["port"] == server.port


def test_exposition_provider_error_answers_500():
    class Boom:
        def snapshot(self):
            raise RuntimeError("registry on fire")

    server = ExpositionServer(registry=Boom()).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(server.url + "/metrics", timeout=10)
        assert err.value.code == 500
    finally:
        server.close()


# ------------------------------------------------- tail-cursor reading


def test_read_new_lines_torn_tail(tmp_path):
    path = tmp_path / "events.jsonl"
    path.write_bytes(b'{"kind": "a"}\n{"kind": "b"}\n{"kind": "c"')
    events, cursor = read_new_lines(path, 0)
    assert [e["kind"] for e in events] == ["a", "b"]
    # The torn tail is NOT consumed: the cursor stops at the last newline.
    events2, cursor2 = read_new_lines(path, cursor)
    assert events2 == [] and cursor2 == cursor
    # Once the writer finishes the line, the same cursor picks it up.
    with path.open("ab") as f:
        f.write(b'}\n')
    events3, cursor3 = read_new_lines(path, cursor)
    assert [e["kind"] for e in events3] == ["c"]
    assert cursor3 == path.stat().st_size


def test_read_new_lines_corrupt_line_consumed_once(tmp_path):
    path = tmp_path / "events.jsonl"
    path.write_bytes(b'not json\n{"kind": "ok"}\n')
    events, cursor = read_new_lines(path, 0)
    assert [e["kind"] for e in events] == ["ok"]
    assert read_new_lines(path, cursor)[0] == []  # never retried


def test_read_new_lines_truncation_resets(tmp_path):
    path = tmp_path / "events.jsonl"
    path.write_bytes(b'{"kind": "a"}\n' * 10)
    _, cursor = read_new_lines(path, 0)
    path.write_bytes(b'{"kind": "fresh"}\n')  # stream shrank under us
    events, cursor2 = read_new_lines(path, cursor)
    assert [e["kind"] for e in events] == ["fresh"]
    assert cursor2 == path.stat().st_size


def test_read_new_lines_missing_file(tmp_path):
    events, cursor = read_new_lines(tmp_path / "nope.jsonl", 5)
    assert events == [] and cursor == 5


# ----------------------------------------------------- watch console


def _fleet_fixture(tmp_path):
    """A simulated 2-process fleet: rank 0 serves + alerts, rank 1 idles.

    Streams are written through explicit-identity sinks (proc/nproc
    passed directly) — under pytest jax is already imported, so the env
    fallback would stamp every stream as process 0.
    """
    root = tmp_path / "fleet"
    now = time.time()
    for rank in range(2):
        sink = EventSink(
            root / f"p{rank}" / "events.jsonl",
            run_id=f"fix-p{rank}", proc=rank, nproc=2,
        )
        sink.emit(
            "run_started", platform="cpu", n_devices=1,
            strategy="fixture", epoch_mode="scan", steps_per_epoch=4,
        )
        for epoch in range(2):
            sink.emit(
                "epoch", epoch=epoch, steps=4, wall_s=0.4,
                dispatch_s=0.01, device_s=None, data_wait_s=0.0,
                compile_events=0, compiled=False, fenced=False,
                steps_per_sec=10.0,
            )
        if rank == 0:
            for i in range(20):
                sink.emit(
                    "span", name="serve.request", cat="serve",
                    span_id=f"r{i}", start_ts=now - 2.0, dur_s=0.004,
                    status="ok" if i < 18 else "shed",
                )
            sink.emit(
                "alert_fired", rule="shed-rate", slo_kind="shed_pct",
                value=10.0, threshold=5.0, burn_fast=None,
                burn_slow=None, active_s=None,
            )
        sink.close()
    return root


def test_watch_once_renders_fixture(tmp_path, capsys):
    root = _fleet_fixture(tmp_path)
    assert run_watch(root, once=True) == 0
    frame = capsys.readouterr().out
    assert "2 stream(s)" in frame
    assert "ALERTS FIRING  : shed-rate" in frame
    assert "serving" in frame
    assert "p0" in frame and "p1" in frame


def test_watch_incremental_refresh(tmp_path):
    root = _fleet_fixture(tmp_path)
    watch = FleetWatch(root)
    snap = watch.refresh()
    assert snap["streams"] == 2
    assert snap["serve"]["n"] == 20
    assert snap["alerts"]["active"] == ["shed-rate"]
    cursors = dict(watch._cursors)
    snap2 = watch.refresh()
    assert watch._cursors == cursors  # EOF cursors: nothing re-read
    assert snap2["serve"]["n"] == 20
    # A new stream event is picked up from the stored cursor.
    stream = root / "p0" / "events.jsonl"
    with stream.open("a") as f:
        f.write(
            json.dumps(
                {"ts": time.time(), "kind": "alert_resolved",
                 "rule": "shed-rate", "value": 0.0}
            ) + "\n"
        )
    snap3 = watch.refresh()
    assert snap3["alerts"]["active"] == []
    assert "none firing" in render_watch(snap3)


def test_watch_empty_root(tmp_path):
    snap = FleetWatch(tmp_path / "empty").refresh()
    assert snap["report"] is None
    assert "(no event streams yet)" in render_watch(snap)


# --------------------------------------------- e2e: the ISSUE contract


def test_live_fire_resolve_roundtrip_matches_posthoc(tmp_path):
    """The acceptance path: under load, a latency fault fires the
    burn-rate alert in the LIVE ``/slo`` endpoint before the run ends;
    after the fault clears the alert resolves; ``watch --once`` and the
    post-hoc summarize alerts section then confirm exactly that
    timeline."""
    tel = TelemetryRun(tmp_path / "serve", run_id="e2e-serve")
    deadline_s = 0.05
    rules = [
        SLORule(
            "error-budget-burn", "burn_rate", threshold=2.0, target=0.99,
            fast_window_s=5.0, slow_window_s=20.0, clear_ticks=2,
        ),
        SLORule(
            "p99-latency", "p99_latency", threshold=deadline_s,
            fast_window_s=5.0, slow_window_s=20.0, for_ticks=2,
        ),
    ]
    engine = SLOEngine(tel.run_dir, rules=rules, sink=tel.sink)
    server = attach_exposition(tel, port=0, slo=engine)

    def scrape():
        return json.loads(
            urllib.request.urlopen(server.url + "/slo", timeout=10).read()
        )

    try:
        t0 = time.time()
        # Phase 1 — healthy sustained load: fast responses, no errors.
        for i in range(40):
            tel.event(
                "span", name="serve.request", cat="serve",
                span_id=f"h{i}", start_ts=t0 - 4.0, dur_s=0.004,
                status="ok",
            )
        engine.tick(t0)
        live = scrape()
        assert live["firing"] == []
        assert live["requests"]["n"] == 40

        # Phase 2 — injected latency fault: the engine wedges past its
        # deadline, requests shed and blow the budget. The LIVE plane
        # must show the burn alert while the "run" is still going.
        for i in range(40):
            tel.event(
                "span", name="serve.request", cat="serve",
                span_id=f"f{i}", start_ts=t0 - 1.0,
                dur_s=deadline_s * 4, status="shed",
            )
        engine.tick(t0 + 1)
        live = scrape()
        assert "error-budget-burn" in live["firing"], (
            "burn alert must fire in the live /slo before the run ends"
        )
        fired_live = list(live["firing"])

        # Phase 3 — fault clears: healthy again, breach ages out of both
        # windows, two clean ticks resolve the alert.
        t1 = t0 + 30.0
        for i in range(40):
            tel.event(
                "span", name="serve.request", cat="serve",
                span_id=f"c{i}", start_ts=t1 - 1.0, dur_s=0.004,
                status="ok",
            )
        engine.tick(t1)
        engine.tick(t1 + 1)
        live = scrape()
        assert live["firing"] == []
        engine.emit_snapshot()
        tel.event(
            "serve_finished", requests=120, completed=80, shed=40,
            deadline_ms=deadline_s * 1e3,
        )
    finally:
        # No engine.stop(): the monitor thread never started, and stop's
        # final tick runs at REAL time — it would see the simulated
        # Phase-2 sheds back inside the fast window and re-fire.
        server.close()
        tel.close()

    # The live console's post-hoc view tells the same story.
    snap = FleetWatch(tmp_path).refresh()
    assert snap["alerts"]["active"] == []
    rules_seen = snap["alerts"]["rules"]
    assert rules_seen["error-budget-burn"]["fired"] == 1
    assert rules_seen["error-budget-burn"]["resolved"] == 1
    frame = render_watch(snap)
    assert "none firing" in frame

    # And summarize confirms the alert timeline from the stream alone.
    report = summarize_path(tel.run_dir)
    alerts = report["alerts"]
    assert alerts["fired"] == len(fired_live) == 1
    assert alerts["resolved"] == 1
    assert alerts["active"] == []
    snapshots = [
        e
        for e in (tel.run_dir / "events.jsonl").read_text().splitlines()
        if '"slo_snapshot"' in e
    ]
    assert snapshots, "emit_snapshot must land in the stream"


def test_monitor_thread_lifecycle(tmp_path):
    """start() spawns the monitor, stop() joins it and runs a final
    tick — the state always reflects the stream's end."""
    tel = _mk_run(tmp_path)
    engine = SLOEngine(
        tel.run_dir, rules=[SLORule("div", "divergence")], sink=tel.sink
    )
    engine.start(interval_s=0.05, snapshot_every=0)
    assert engine._thread is not None and engine._thread.daemon
    time.sleep(0.2)
    engine.stop()
    assert engine._thread is None
    assert engine.state()["ticks"] >= 1
    # Idempotent: a second stop is safe.
    engine.stop()
    tel.close()
