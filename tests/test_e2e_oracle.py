"""Synthetic-oracle end-to-end test (SURVEY.md §4).

The synthetic DGP plants known per-stock alpha/beta coefficients, so a
correct pipeline — data generation, windowing, feature expansion, training,
and evaluation working together — must recover parameters that correlate
strongly with the truth and land in the same ballpark as the analytical OLS
estimator. This is the correctness story the reference relies on by eye
(test.py:119-145 plots the estimate-vs-truth correlation) but never
automates.
"""

import numpy as np
import pytest

from masters_thesis_tpu.data.pipeline import FinancialWindowDataModule
from masters_thesis_tpu.data.synthetic import SyntheticLogReturns
from masters_thesis_tpu.evaluation import collect_test_results, delta_losses
from masters_thesis_tpu.models.objectives import ModelSpec
from masters_thesis_tpu.train import Trainer


@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("oracle")
    r_stocks, r_market, alphas, betas = SyntheticLogReturns.generate(
        16, 12000, seed=5
    )
    np.save(tmp / "stocks.npy", np.asarray(r_stocks))
    np.save(tmp / "market.npy", np.asarray(r_market))
    np.save(tmp / "alphas.npy", np.asarray(alphas))
    np.save(tmp / "betas.npy", np.asarray(betas))
    dm = FinancialWindowDataModule(
        tmp, lookback_window=16, target_window=8, stride=24, batch_size=4
    )
    dm.prepare_data(verbose=False)
    dm.setup()
    spec = ModelSpec(
        objective="mse", hidden_size=16, num_layers=1, dropout=0.0,
        learning_rate=1e-2,
    )
    trainer = Trainer(
        max_epochs=25, gradient_clip_val=5.0, check_val_every_n_epoch=5,
        enable_progress_bar=False, enable_model_summary=False, seed=0,
    )
    result = trainer.fit(spec, dm)
    return spec, result, dm


def _corr(a, b):
    return np.corrcoef(np.ravel(a), np.ravel(b))[0, 1]


def test_recovers_planted_coefficients(trained):
    spec, result, dm = trained
    out = collect_test_results(spec, result.params, dm)

    beta_corr = _corr(out["beta"]["model"], out["beta"]["true"])
    alpha_corr = _corr(out["alpha"]["model"], out["alpha"]["true"])
    ols_beta_corr = _corr(out["beta"]["ols"], out["beta"]["true"])

    # A trained encoder must track the planted betas strongly...
    assert beta_corr > 0.8, f"beta corr {beta_corr:.3f}"
    assert alpha_corr > 0.5, f"alpha corr {alpha_corr:.3f}"
    # ...and sit in the analytical estimator's ballpark (calibrated run:
    # model 0.904 vs OLS 0.905).
    assert beta_corr > ols_beta_corr - 0.1


def test_trained_model_delta_loss_near_ols(trained):
    """On the thesis' ΔL scale, brief MSE training must land within 3x of
    the lookback-OLS row (both above the target-OLS baseline by
    construction)."""
    spec, result, dm = trained
    deltas = delta_losses(spec, result.params, dm)
    assert deltas["model"]["delta_mse"] < 3.0 * deltas["ols"]["delta_mse"]
    assert np.isfinite(deltas["model"]["delta_mix"])
