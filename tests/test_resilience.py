"""Chaos suite: fault-injection harness, crash-safe checkpoints with
manifest verification, BackendHealth failover policy, and the self-healing
run supervisor — including the headline kill-resume determinism test
(SIGKILL mid-epoch + supervised resume == bit-identical final params on
the 8-device virtual mesh).

The supervisor/fault tests that need a separate trainee process use either
the jax-free ``resilience worker`` subcommand (fast policy scenarios) or
``tests/_resilient_worker.py`` (a real Trainer.fit, for the determinism
test). Everything here restores fault-injection state — the harness must
stay globally OFF for the rest of the suite.
"""

import json
import os
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from masters_thesis_tpu.resilience import FaultInjected, FaultPlan, FaultSpec, faults
from masters_thesis_tpu.resilience.supervisor import (
    RunSupervisor,
    SupervisorConfig,
)

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _no_leaked_faults(monkeypatch):
    """Every test starts and ends with injection off, whatever it does."""
    monkeypatch.delenv(faults.FAULT_PLAN_ENV, raising=False)
    monkeypatch.delenv(faults.ATTEMPT_ENV, raising=False)
    yield
    faults.clear_plan()


def fast_cfg(**kw):
    defaults = dict(
        max_retries=3, backoff_s=0.05, backoff_factor=1.0, term_grace_s=2.0
    )
    defaults.update(kw)
    return SupervisorConfig(**defaults)


# --------------------------------------------------------------- fault plan


class TestFaultPlan:
    def test_parse_roundtrip_and_forms(self):
        plan = FaultPlan.parse(
            '[{"point": "trainer.loss", "kind": "nan", "attempt": 2}]'
        )
        assert plan.faults[0].attempt == 2
        again = FaultPlan.parse(plan.to_json())
        assert again.faults == plan.faults
        wrapped = FaultPlan.parse(
            '{"seed": 7, "faults": [{"point": "data.epoch", "kind": "raise"}]}'
        )
        assert wrapped.seed == 7

    def test_unknown_point_or_kind_rejected(self):
        with pytest.raises(ValueError):
            FaultSpec(point="trainer.typo", kind="nan")
        with pytest.raises(ValueError):
            FaultSpec(point="trainer.loss", kind="explode")

    def test_disabled_is_inert(self):
        assert faults.fire("trainer.loss", epoch=0) is None

    def test_install_plan_and_ctx_match(self):
        plan = FaultPlan(
            faults=(
                FaultSpec(
                    point="trainer.loss", kind="nan", match={"epoch": 2}
                ),
            )
        )
        faults.install_plan(plan)
        assert faults.fire("trainer.loss", epoch=1) is None
        assert faults.fire("trainer.loss", epoch=2) == "nan"
        assert faults.fire("trainer.epoch_start", epoch=2) is None
        faults.clear_plan()
        assert faults.fire("trainer.loss", epoch=2) is None

    def test_attempt_scoping(self, monkeypatch):
        plan = FaultPlan(
            faults=(FaultSpec(point="worker.epoch", kind="nan", attempt=1),)
        )
        faults.install_plan(plan)
        assert faults.fire("worker.epoch", epoch=0) == "nan"
        monkeypatch.setenv(faults.ATTEMPT_ENV, "2")
        assert faults.fire("worker.epoch", epoch=0) is None

    def test_env_activation_and_raise(self, monkeypatch):
        monkeypatch.setenv(
            faults.FAULT_PLAN_ENV,
            '[{"point": "data.epoch", "kind": "raise", "attempt": null}]',
        )
        with pytest.raises(FaultInjected):
            faults.fire("data.epoch", epoch=0)

    def test_install_none_overrides_env(self, monkeypatch):
        monkeypatch.setenv(
            faults.FAULT_PLAN_ENV,
            '[{"point": "data.epoch", "kind": "raise", "attempt": null}]',
        )
        faults.install_plan(None)
        assert faults.fire("data.epoch", epoch=0) is None


# ----------------------------------------------------------- backend health


class TestBackendHealth:
    def _health(self, tmp_path, **kw):
        from masters_thesis_tpu.utils.backend_probe import BackendHealth

        defaults = dict(timeout_s=1.0, budget_s=10.0, backoff_s=0.0)
        defaults.update(kw)
        return BackendHealth(tmp_path / "probe_cache.json", **defaults)

    def test_healthy_probe_recorded(self, tmp_path, monkeypatch):
        import masters_thesis_tpu.utils.backend_probe as bp

        monkeypatch.setattr(
            bp,
            "probe_tpu_backend",
            lambda **kw: bp.ProbeResult(True, 1, ""),
        )
        health = self._health(tmp_path)
        decision = health.ensure_responsive()
        assert decision.ok and not decision.degraded
        cached = health.read_cache()
        assert cached and cached["ok"]

    def test_known_wedged_gets_single_attempt(self, tmp_path, monkeypatch):
        import masters_thesis_tpu.utils.backend_probe as bp

        seen = {}

        def fake_probe(**kw):
            seen.update(kw)
            return bp.ProbeResult(False, 1, "probe timed out")

        monkeypatch.setattr(bp, "probe_tpu_backend", fake_probe)
        health = self._health(tmp_path)
        health.record_wedge("test wedge")
        decision = health.ensure_responsive()
        assert not decision.ok and decision.known_wedged
        assert seen["budget_s"] == 0.0  # no 600s retry burn
        assert decision.attempts == 1

    def test_single_attempt_flag_forces_budget_zero(self, tmp_path, monkeypatch):
        import masters_thesis_tpu.utils.backend_probe as bp

        seen = {}

        def fake_probe(**kw):
            seen.update(kw)
            return bp.ProbeResult(False, 1, "nope")

        monkeypatch.setattr(bp, "probe_tpu_backend", fake_probe)
        decision = self._health(tmp_path).ensure_responsive(single_attempt=True)
        assert seen["budget_s"] == 0.0
        assert not decision.ok

    def test_cache_ttl_expiry(self, tmp_path):
        health = self._health(tmp_path, ttl_s=0.05)
        health.record(True, "fine")
        assert health.read_cache() is not None
        time.sleep(0.1)
        assert health.read_cache() is None

    def test_injected_wedge_fails_probe_instantly(self, tmp_path):
        """A simulated wedged backend flows through the real probe loop
        (retry/budget logic intact) without the real 120s timeout."""
        from masters_thesis_tpu.utils.backend_probe import probe_tpu_backend

        faults.install_plan(
            FaultPlan(
                faults=(
                    FaultSpec(
                        point="probe.attempt", kind="wedge", attempt=None
                    ),
                )
            )
        )
        t0 = time.monotonic()
        probe = probe_tpu_backend(timeout_s=60.0, budget_s=0.0, backoff_s=0.0)
        assert not probe.ok and probe.attempts == 1
        assert time.monotonic() - t0 < 5.0
        assert "timed out" in probe.detail


# ------------------------------------------------- checkpoint manifest path


class TestCheckpointManifest:
    def _save(self, d, epoch):
        from masters_thesis_tpu.models.objectives import ModelSpec
        from masters_thesis_tpu.train.checkpoint import save_checkpoint

        spec = ModelSpec(
            objective="mse",
            hidden_size=8,
            num_layers=1,
            dropout=0.0,
            learning_rate=1e-2,
        )
        save_checkpoint(
            d, "last", {"w": np.full((64,), float(epoch))}, {},
            spec, meta={"epoch": epoch},
        )

    def test_manifest_written_and_verifies(self, tmp_path):
        from masters_thesis_tpu.train.checkpoint import (
            MANIFEST_NAME,
            verify_checkpoint,
        )

        self._save(tmp_path, 0)
        assert (tmp_path / "last" / MANIFEST_NAME).exists()
        assert verify_checkpoint(tmp_path / "last")

    def test_corrupt_latest_falls_back_to_previous_good(self, tmp_path):
        from masters_thesis_tpu.train.checkpoint import (
            MANIFEST_NAME,
            checkpoint_restorable,
            restore_checkpoint,
            verify_checkpoint,
        )

        self._save(tmp_path, 0)
        self._save(tmp_path, 1)  # rotates epoch 0 to last.prev
        assert (tmp_path / "last.prev").exists()
        # Flip one byte in the largest data file of the latest tree.
        victim = max(
            (
                p
                for p in (tmp_path / "last").rglob("*")
                if p.is_file() and p.name != MANIFEST_NAME
            ),
            key=lambda p: p.stat().st_size,
        )
        blob = bytearray(victim.read_bytes())
        blob[len(blob) // 2] ^= 0xFF
        victim.write_bytes(bytes(blob))
        assert not verify_checkpoint(tmp_path / "last")
        assert checkpoint_restorable(tmp_path, "last")
        params, _, _, meta = restore_checkpoint(tmp_path, "last")
        assert meta["epoch"] == 0  # the previous good one
        assert float(params["w"][0]) == 0.0

    def test_corrupt_with_no_fallback_raises(self, tmp_path):
        from masters_thesis_tpu.train.checkpoint import (
            CorruptCheckpointError,
            MANIFEST_NAME,
            checkpoint_restorable,
            restore_checkpoint,
        )

        self._save(tmp_path, 0)
        victim = max(
            (
                p
                for p in (tmp_path / "last").rglob("*")
                if p.is_file() and p.name != MANIFEST_NAME
            ),
            key=lambda p: p.stat().st_size,
        )
        blob = bytearray(victim.read_bytes())
        blob[0] ^= 0xFF
        victim.write_bytes(bytes(blob))
        assert not checkpoint_restorable(tmp_path, "last")
        with pytest.raises(CorruptCheckpointError):
            restore_checkpoint(tmp_path, "last")

    def test_legacy_tree_without_manifest_still_restores(self, tmp_path):
        from masters_thesis_tpu.train.checkpoint import (
            MANIFEST_NAME,
            restore_checkpoint,
            verify_checkpoint,
        )

        self._save(tmp_path, 0)
        (tmp_path / "last" / MANIFEST_NAME).unlink()
        assert verify_checkpoint(tmp_path / "last")  # legacy = trusted
        _, _, _, meta = restore_checkpoint(tmp_path, "last")
        assert meta["epoch"] == 0

    def _corrupt_primary(self, tmp_path):
        from masters_thesis_tpu.train.checkpoint import MANIFEST_NAME

        victim = max(
            (
                p
                for p in (tmp_path / "last").rglob("*")
                if p.is_file() and p.name != MANIFEST_NAME
            ),
            key=lambda p: p.stat().st_size,
        )
        blob = bytearray(victim.read_bytes())
        blob[len(blob) // 2] ^= 0xFF
        victim.write_bytes(bytes(blob))

    def test_interrupted_rotation_missing_prev_sidecar(self, tmp_path):
        """A ``.prev`` tree whose sidecar rename was lost mid-rotation is
        SKIPPED as a fallback: healthy primary restores cleanly; corrupt
        primary raises deterministically — never a crash, never a
        half-paired restore."""
        from masters_thesis_tpu.train.checkpoint import (
            CorruptCheckpointError,
            checkpoint_restorable,
            restore_checkpoint,
        )

        self._save(tmp_path, 0)
        self._save(tmp_path, 1)
        (tmp_path / "last.prev.json").unlink()
        assert checkpoint_restorable(tmp_path, "last")
        _, _, _, meta = restore_checkpoint(tmp_path, "last")
        assert meta["epoch"] == 1  # torn pair ignored, primary served
        self._corrupt_primary(tmp_path)
        assert not checkpoint_restorable(tmp_path, "last")
        with pytest.raises(CorruptCheckpointError):
            restore_checkpoint(tmp_path, "last")

    def test_interrupted_rotation_missing_prev_tree(self, tmp_path):
        """The mirror tear: an orphan ``.prev.json`` sidecar without its
        tree must not be restored from (or crash the candidate scan)."""
        import shutil

        from masters_thesis_tpu.train.checkpoint import (
            CorruptCheckpointError,
            checkpoint_restorable,
            restore_checkpoint,
        )

        self._save(tmp_path, 0)
        self._save(tmp_path, 1)
        shutil.rmtree(tmp_path / "last.prev")
        assert (tmp_path / "last.prev.json").exists()
        _, _, _, meta = restore_checkpoint(tmp_path, "last")
        assert meta["epoch"] == 1
        self._corrupt_primary(tmp_path)
        assert not checkpoint_restorable(tmp_path, "last")
        with pytest.raises(CorruptCheckpointError):
            restore_checkpoint(tmp_path, "last")

    def test_injected_post_publish_corruption_detected(self, tmp_path):
        """The corrupted-checkpoint fault (flip a byte AFTER publish) is
        exactly what verification must catch."""
        from masters_thesis_tpu.train.checkpoint import verify_checkpoint

        self._save(tmp_path, 0)
        faults.install_plan(
            FaultPlan(
                faults=(
                    FaultSpec(
                        point="checkpoint.post_publish",
                        kind="corrupt",
                        attempt=None,
                    ),
                ),
                seed=3,
            )
        )
        try:
            self._save(tmp_path, 1)
        finally:
            faults.clear_plan()
        assert not verify_checkpoint(tmp_path / "last")
        assert verify_checkpoint(tmp_path / "last.prev")


# ------------------------------------------------------ supervisor policies


class TestSupervisorPolicies:
    """Jax-free scenarios against trivial children / the worker subcommand."""

    def test_success_first_try(self, tmp_path):
        res = RunSupervisor(
            [sys.executable, "-c", "print('fine')"],
            run_dir=tmp_path / "sup",
            cfg=fast_cfg(),
        ).run()
        assert res.ok and res.verdict == "completed" and res.n_attempts == 1
        assert res.lost_work_s == 0.0

    def test_deterministic_crash_halts_after_reproduction(self, tmp_path):
        res = RunSupervisor(
            [
                sys.executable,
                "-c",
                "import sys; print('RuntimeError: boom', file=sys.stderr); "
                "sys.exit(3)",
            ],
            run_dir=tmp_path / "sup",
            cfg=fast_cfg(),
        ).run()
        assert not res.ok
        assert res.verdict == "deterministic"
        assert res.n_attempts == 2  # once + the reproduction, not 1+retries
        fps = [a.classification.fingerprint for a in res.attempts]
        assert fps[0] == fps[1] is not None

    def test_retries_exhausted_on_changing_crash(self, tmp_path):
        # Each attempt crashes differently (attempt number in the message)
        # -> never a reproduced fingerprint -> burns the retry budget.
        code = (
            "import os, sys; "
            "print('RuntimeError: boom-' + os.environ['MTT_ATTEMPT'], "
            "file=sys.stderr); sys.exit(9)"
        )
        res = RunSupervisor(
            [sys.executable, "-c", code],
            run_dir=tmp_path / "sup",
            cfg=fast_cfg(max_retries=2),
        ).run()
        assert res.verdict == "retries_exhausted"
        assert res.n_attempts == 3

    def test_sigkill_classified_transient_then_resumed(self, tmp_path):
        """Preempt-shaped death (SIGKILL mid-epoch) retries and the relaunch
        RESUMES: the work log must cover every epoch exactly once."""
        out = tmp_path / "w"
        env = dict(os.environ)
        env[faults.FAULT_PLAN_ENV] = json.dumps(
            [{"point": "worker.epoch", "kind": "kill", "attempt": 1,
              "match": {"epoch": 2}}]
        )
        res = RunSupervisor(
            [sys.executable, "-m", "masters_thesis_tpu.resilience", "worker",
             "--out", str(out), "--mode", "ok", "--epochs", "4"],
            run_dir=out / "sup",
            cfg=fast_cfg(),
            env=env,
            watch_dir=out / "telemetry",
        ).run()
        assert res.ok and res.n_attempts == 2
        assert res.attempts[0].classification.kind == "transient"
        lines = (out / "work.log").read_text().splitlines()
        assert [int(ln.split()[1]) for ln in lines] == [0, 1, 2, 3]
        # Attempt 2 did epochs 2-3; attempt 1 did 0-1 — resumed, not redone.
        assert [int(ln.split()[0]) for ln in lines] == [1, 1, 2, 2]

    def test_divergence_rolls_back_with_scaled_lr(self, tmp_path):
        out = tmp_path / "w"
        res = RunSupervisor(
            [sys.executable, "-m", "masters_thesis_tpu.resilience", "worker",
             "--out", str(out), "--mode", "nan", "--epochs", "4", "--at", "1"],
            run_dir=out / "sup",
            cfg=fast_cfg(),
            watch_dir=out / "telemetry",
        ).run()
        assert res.ok and res.n_attempts == 2
        assert res.attempts[0].classification.kind == "divergence"
        from masters_thesis_tpu.telemetry.events import read_events

        sup_events = read_events(out / "sup" / "events.jsonl")
        rollbacks = [e for e in sup_events if e["kind"] == "rollback"]
        assert len(rollbacks) == 1 and rollbacks[0]["lr_scale"] == 0.5

    def test_hang_watchdog_kills_and_retries(self, tmp_path):
        out = tmp_path / "w"
        env = dict(os.environ)
        # Hang only on attempt 1 (the worker's hang mode is unconditional,
        # so gate it with a fault-plan-free trick: mode=hang at epoch 1,
        # attempt 2 runs mode selection again... instead use the plan).
        env[faults.FAULT_PLAN_ENV] = json.dumps(
            [{"point": "worker.epoch", "kind": "hang", "attempt": 1,
              "match": {"epoch": 1}}]
        )
        res = RunSupervisor(
            [sys.executable, "-m", "masters_thesis_tpu.resilience", "worker",
             "--out", str(out), "--mode", "ok", "--epochs", "3"],
            run_dir=out / "sup",
            cfg=fast_cfg(hang_timeout_s=2.0),
            env=env,
            watch_dir=out / "telemetry",
        ).run()
        assert res.ok and res.n_attempts == 2
        assert res.attempts[0].hang_killed
        assert res.attempts[0].classification.kind == "transient"

    def test_attempt_events_carry_report_contract(self, tmp_path):
        """summarize's _restart_stats reads attempt_finished.ok and
        .lost_work_s from supervisor streams — pin the field names."""
        RunSupervisor(
            [sys.executable, "-c", "import sys; sys.exit(1)"],
            run_dir=tmp_path / "sup",
            cfg=fast_cfg(max_retries=0),
        ).run()
        from masters_thesis_tpu.telemetry.events import read_events

        events = read_events(tmp_path / "sup" / "events.jsonl")
        fin = [e for e in events if e["kind"] == "attempt_finished"]
        assert fin and "ok" in fin[0] and "lost_work_s" in fin[0]
        assert any(e["kind"] == "supervisor_verdict" for e in events)


# -------------------------------------------------------- wedge -> CPU mesh


class TestWedgeFailover:
    def test_wedged_backend_degrades_to_cpu_in_one_probe(self, tmp_path):
        """Acceptance: an injected wedged-backend fault triggers CPU
        failover after a SINGLE probe attempt (no retry burn), the child
        runs pinned to CPU, and the degradation shows up in `telemetry
        summarize` output."""
        faults.install_plan(
            FaultPlan(
                faults=(
                    FaultSpec(
                        point="probe.attempt", kind="wedge", attempt=None
                    ),
                )
            )
        )
        out = tmp_path / "sup"
        t0 = time.monotonic()
        try:
            res = RunSupervisor(
                [
                    sys.executable,
                    "-c",
                    "import os; print(os.environ.get('JAX_PLATFORMS'))",
                ],
                run_dir=out,
                cfg=fast_cfg(
                    probe=True,
                    probe_timeout_s=60.0,
                    probe_cache=tmp_path / "probe_cache.json",
                ),
            ).run()
        finally:
            faults.clear_plan()
        assert time.monotonic() - t0 < 30.0  # not a 600s budget burn
        assert res.ok and res.degraded
        assert (out / "attempt_1.out").read_text().strip() == "cpu"

        from masters_thesis_tpu.telemetry.events import read_events
        from masters_thesis_tpu.telemetry.report import (
            render_text,
            summarize_events,
        )

        events = read_events(out / "events.jsonl")
        degr = [e for e in events if e["kind"] == "degradation"]
        assert degr and degr[0]["fallback"] == "cpu"
        assert degr[0]["probe_attempts"] == 1
        report = summarize_events(events)
        assert report["restarts"]["degradations"] == 1
        assert "degradation" in render_text(report)


# ------------------------------------------------- restarts in summarize


class TestRestartReporting:
    def test_trainer_stream_restart_stats(self, tmp_path):
        """A resumed trainer stream (two run_started segments, checkpoint
        saves) yields restart count + lost-work seconds in the report."""
        from masters_thesis_tpu.telemetry.events import EventSink, read_events
        from masters_thesis_tpu.telemetry.report import (
            render_text,
            summarize_events,
        )

        path = tmp_path / "events.jsonl"
        s1 = EventSink(path, "run", attempt=1)
        s1.emit("run_started", resumed_from=None)
        s1.emit("checkpoint_saved", tag="last", epoch=0, wall_s=0.1)
        s1.emit("epoch", epoch=1, wall_s=1.0)  # work after the save: lost
        s1.close()
        s2 = EventSink(path, "run", attempt=2)
        s2.emit("run_started", resumed_from=str(tmp_path / "ckpts" / "last"))
        s2.emit("epoch", epoch=1, wall_s=1.0)
        s2.emit("run_finished", epochs_trained=2, diverged=False)
        s2.close()

        report = summarize_events(read_events(path))
        r = report["restarts"]
        assert r["attempts"] == 2 and r["restarts"] == 1
        assert r["resumed"] is True
        assert r["lost_work_s"] >= 0.0
        assert "restarts" in render_text(report)


# ------------------------------------------- the determinism acceptance test


class TestKillResumeDeterminism:
    def test_sigkill_mid_epoch_resume_bit_identical(self, tmp_path):
        """THE acceptance test: a real Trainer.fit on the 8-device virtual
        mesh, SIGKILLed right after an epoch is dispatched, supervised back
        to completion — final params bit-identical to an uninterrupted run."""
        worker = REPO / "tests" / "_resilient_worker.py"
        env = {
            k: v
            for k, v in os.environ.items()
            if k not in (faults.FAULT_PLAN_ENV, faults.ATTEMPT_ENV)
        }

        ref_dir = tmp_path / "ref"
        ref = subprocess.run(
            [sys.executable, str(worker), str(ref_dir), "4"],
            cwd=REPO,
            env=env,
            timeout=600,
            capture_output=True,
            text=True,
        )
        assert ref.returncode == 0, ref.stderr[-2000:]

        sup_dir = tmp_path / "sup"
        chaos_env = dict(env)
        chaos_env[faults.FAULT_PLAN_ENV] = json.dumps(
            [{"point": "trainer.epoch_dispatched", "kind": "kill",
              "attempt": 1, "match": {"epoch": 2}}]
        )
        res = RunSupervisor(
            [sys.executable, str(worker), str(sup_dir), "4"],
            run_dir=sup_dir / "supervisor",
            cfg=fast_cfg(),
            env=chaos_env,
            cwd=REPO,
            watch_dir=sup_dir / "telemetry",
            ckpt_dir=sup_dir / "ckpts",
        ).run()
        assert res.ok, [a.classification.reason for a in res.attempts]
        assert res.n_attempts == 2
        assert res.attempts[0].classification.kind == "transient"

        a = np.load(ref_dir / "params.npz")
        b = np.load(sup_dir / "params.npz")
        assert sorted(a.files) == sorted(b.files)
        for k in a.files:
            assert a[k].dtype == b[k].dtype
            assert np.array_equal(a[k], b[k]), f"params differ at {k}"

        # The child's own stream shows the attempt chain: envelope attempts
        # {1, 2} and a resumed_from on the second run_started.
        from masters_thesis_tpu.telemetry.events import read_events
        from masters_thesis_tpu.telemetry.report import summarize_events

        events = read_events(sup_dir / "telemetry" / "events.jsonl")
        assert {e.get("attempt") for e in events} == {1, 2}
        starts = [e for e in events if e["kind"] == "run_started"]
        assert len(starts) == 2
        assert starts[0]["resumed_from"] is None
        assert starts[1]["resumed_from"]
        report = summarize_events(events)
        assert report["restarts"]["restarts"] == 1
