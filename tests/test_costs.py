"""Cost & utilization observability tests (telemetry/costs.py, ledger.py).

The e2e contract (ISSUE acceptance): a tiny run on the hermetic 8-device
virtual CPU mesh must emit ``cost_profile`` events whose FLOPs scale
linearly with the work (batch rows, packed windows); the perf ledger must
round-trip append/read and its regression gate must exit 2 on a doctored
slow round; and the summarize/ledger CLIs must render the utilization
section without importing jax (proved under a poisoned import).
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from masters_thesis_tpu.data.pipeline import FinancialWindowDataModule
from masters_thesis_tpu.data.synthetic import SyntheticLogReturns
from masters_thesis_tpu.models.objectives import ModelSpec
from masters_thesis_tpu.ops.lstm_kernel import route_plan
from masters_thesis_tpu.telemetry import TelemetryRun, read_events
from masters_thesis_tpu.telemetry import costs
from masters_thesis_tpu.telemetry import ledger as led
from masters_thesis_tpu.telemetry.__main__ import main as cli_main
from masters_thesis_tpu.telemetry.report import render_text, summarize_path
from masters_thesis_tpu.train import Trainer

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ----------------------------------------------------------- pure roofline


class TestRoofline:
    def test_utilization_numbers(self):
        # 2e10 flops/step at 1 step/s on the cpu peaks (5e10 / 2e10).
        u = costs.utilization(2e10, 2e10, 1.0, "cpu")
        assert u["achieved_flops_per_sec"] == pytest.approx(2e10)
        assert u["flops_utilization_pct"] == pytest.approx(40.0)
        assert u["bytes_utilization_pct"] == pytest.approx(100.0)
        assert u["arithmetic_intensity"] == pytest.approx(1.0)
        # ridge = 5e10/2e10 = 2.5 flops/byte; intensity 1.0 sits below it.
        assert u["regime"] == "memory-bound"

    def test_compute_bound_above_ridge(self):
        u = costs.utilization(1e12, 1e9, 1.0, "cpu")
        assert u["regime"] == "compute-bound"

    def test_comms_bound_overrides_intensity(self):
        assert (
            costs.roofline_regime(1000.0, "cpu", comms_frac=0.5)
            == "comms-bound"
        )
        assert (
            costs.roofline_regime(1000.0, "cpu", comms_frac=0.1)
            == "compute-bound"
        )

    def test_none_tolerance(self):
        u = costs.utilization(None, None, None, "not-a-platform")
        assert u["achieved_flops_per_sec"] is None
        assert u["flops_utilization_pct"] is None
        assert u["regime"] is None

    def test_peak_env_override(self, monkeypatch):
        monkeypatch.setenv("MT_PEAK_FLOPS", "1e10")
        u = costs.utilization(1e10, 1e10, 1.0, "cpu")
        assert u["flops_utilization_pct"] == pytest.approx(100.0)

    def test_n_devices_scales_the_denominator(self):
        one = costs.utilization(2e10, 2e10, 1.0, "cpu", n_devices=1)
        eight = costs.utilization(2e10, 2e10, 1.0, "cpu", n_devices=8)
        assert one["flops_utilization_pct"] == pytest.approx(
            8 * eight["flops_utilization_pct"]
        )


# ------------------------------------------------------------- extraction


class TestExtraction:
    def test_profile_jit_compiled_source(self):
        w = jnp.ones((16, 16), jnp.float32)

        @jax.jit
        def f(x):
            return jnp.tanh(x @ w).sum()

        cost = costs.profile_jit(
            f, jnp.ones((8, 16), jnp.float32), program="unit_matmul"
        )
        assert cost.available and cost.source == "compiled"
        assert cost.flops and cost.flops > 0
        assert cost.peak_bytes and cost.peak_bytes > 0
        # The payload must be a JSON-serializable flat dict (event body).
        payload = json.loads(json.dumps(cost.to_payload()))
        assert payload["program"] == "unit_matmul"
        assert payload["flops_per_step"] == pytest.approx(cost.flops)

    def test_flops_linear_in_batch_rows(self):
        base = costs.lstm_route_cost(4, 8, 8, 1, compile=False)
        doubled = costs.lstm_route_cost(4, 16, 8, 1, compile=False)
        assert base.available and doubled.available
        assert doubled.flops / base.flops == pytest.approx(2.0, rel=0.15)

    def test_flops_linear_in_packed_windows(self):
        # rows = pack * window_rows: each extra packed window adds the
        # same recurrence work, so FLOPs scale linearly in the pack count.
        one = costs.lstm_route_cost(4, 8, 8, 1, window_rows=8, compile=False)
        two = costs.lstm_route_cost(4, 16, 8, 1, window_rows=8, compile=False)
        four = costs.lstm_route_cost(4, 32, 8, 1, window_rows=8, compile=False)
        assert two.flops / one.flops == pytest.approx(2.0, rel=0.15)
        assert four.flops / one.flops == pytest.approx(4.0, rel=0.15)
        # The router's plan rides along in meta for the telemetry stream.
        assert one.meta["route"]
        assert one.meta["predicted_vmem_bytes"] > 0

    def test_route_plan_mirrors_tpu_packing(self):
        # The canonical 25-stock shape packs 2 windows/program on TPU
        # (RESULTS.md round-6); the plan must report the same decision the
        # dispatch predicates would take, without needing a TPU.
        plan = route_plan(60, 4160, 64, 1, window_rows=52, backend="tpu")
        assert plan["route"] == "pallas-packed"
        assert plan["pack_width"] == 2
        cpu = route_plan(60, 4160, 64, 1, window_rows=52, backend="cpu")
        assert cpu["route"] == "xla-scan"

    def test_extract_cost_never_raises(self):
        class Broken:
            def cost_analysis(self):
                raise RuntimeError("backend says no")

            def memory_analysis(self):
                raise RuntimeError("backend says no")

        cost = costs.extract_cost(Broken(), Broken(), program="broken")
        assert not cost.available and cost.source == "unavailable"
        assert cost.peak_bytes is None

    def test_emit_warn_once_when_unavailable(self):
        class FakeTel:
            def __init__(self):
                self.events = []

            def event(self, kind, **payload):
                self.events.append({"kind": kind, **payload})
                return self.events[-1]

        tel = FakeTel()
        dead = costs.CostModel(program="dead")
        costs.emit_cost_profile(tel, dead)
        costs.emit_cost_profile(tel, dead)
        kinds = [e["kind"] for e in tel.events]
        assert kinds == ["cost_unavailable"]  # once, not per program
        live = costs.CostModel(program="live", flops=1.0, bytes_accessed=2.0)
        costs.emit_cost_profile(tel, live)
        assert tel.events[-1]["kind"] == "cost_profile"
        assert tel.events[-1]["program"] == "live"


# -------------------------------------------------------- CP401-403 rules


class TestCostFindings:
    def test_cp401_unavailable_on_xla_backend(self):
        out = costs.cost_findings(
            costs.CostModel(program="p"), platform="cpu"
        )
        assert [f.rule for f in out] == ["CP401"]

    def test_cp402_over_budget(self):
        cost = costs.CostModel(
            program="p", flops=1.0, bytes_accessed=1.0,
            argument_bytes=600, output_bytes=300, temp_bytes=200,
        )
        out = costs.cost_findings(cost, platform="cpu", budget_bytes=1000)
        assert [f.rule for f in out] == ["CP402"]
        assert costs.cost_findings(
            cost, platform="cpu", budget_bytes=2000
        ) == []

    def test_cp403_tpu_floor_only(self):
        cost = costs.CostModel(program="p", flops=1.0, bytes_accessed=1.0)
        low = costs.cost_findings(
            cost, platform="tpu", flops_utilization_pct=0.5
        )
        assert [f.rule for f in low] == ["CP403"]
        # The virtual CPU mesh can't feed a TPU roofline — no CP403 there.
        assert costs.cost_findings(
            cost, platform="cpu", flops_utilization_pct=0.5
        ) == []

    def test_alias_bytes_subtracted_once(self):
        cost = costs.CostModel(
            program="p", argument_bytes=100, output_bytes=100,
            temp_bytes=50, alias_bytes=100,
        )
        assert cost.peak_bytes == 150


# ----------------------------------------------- trainer + serve wiring


@pytest.fixture(scope="module")
def tiny_dm(tmp_path_factory) -> FinancialWindowDataModule:
    data_dir = tmp_path_factory.mktemp("cost_data")
    r_stocks, r_market, alphas, betas = SyntheticLogReturns.generate(
        n_stocks=8, n_samples=4000, seed=1
    )
    np.save(data_dir / "stocks.npy", np.asarray(r_stocks))
    np.save(data_dir / "market.npy", np.asarray(r_market))
    np.save(data_dir / "alphas.npy", np.asarray(alphas))
    np.save(data_dir / "betas.npy", np.asarray(betas))
    dm = FinancialWindowDataModule(
        data_dir, lookback_window=16, target_window=8, stride=24, batch_size=2
    )
    dm.prepare_data(verbose=False)
    dm.setup()
    return dm


def _small_spec():
    return ModelSpec(
        objective="mse", hidden_size=8, num_layers=1, dropout=0.0,
        learning_rate=1e-2,
    )


@pytest.fixture(scope="module")
def cost_run(tiny_dm, tmp_path_factory):
    """One telemetry-on 2-epoch scan run; (run_dir, TrainResult)."""
    run_dir = tmp_path_factory.mktemp("cost_run")
    tel = TelemetryRun(run_dir)
    trainer = Trainer(
        max_epochs=2,
        gradient_clip_val=5.0,
        check_val_every_n_epoch=1,
        enable_progress_bar=False,
        enable_model_summary=False,
        seed=0,
        strategy="tpu_xla",
        telemetry=tel,
    )
    result = trainer.fit(_small_spec(), tiny_dm)
    tel.close()
    return run_dir, result


class TestTrainerCostProfile:
    def test_cost_profile_event_emitted(self, cost_run):
        run_dir, result = cost_run
        events = read_events(run_dir / "events.jsonl")
        profiles = [e for e in events if e["kind"] == "cost_profile"]
        assert len(profiles) == 1
        p = profiles[0]
        assert p["program"] == "train_epoch_scan"
        assert p["available"] and p["flops"] > 0
        # The scan program amortizes steps_per_epoch optimizer steps.
        assert p["steps_per_execution"] > 1
        assert p["flops_per_step"] == pytest.approx(
            p["flops"] / p["steps_per_execution"]
        )
        # The routing decision rides along: plan + predicted VMEM bytes.
        route = p["meta"]["lstm_route"]
        assert route["route"] == "xla-scan"  # CPU backend
        assert route["predicted_vmem_bytes"] > 0

    def test_train_result_carries_payload(self, cost_run):
        _, result = cost_run
        assert result.cost_profile is not None
        assert result.cost_profile["available"]
        assert result.cost_profile["peak_bytes"] > 0

    def test_summarize_reports_utilization(self, cost_run):
        run_dir, _ = cost_run
        report = summarize_path(run_dir)
        util = report["utilization"]
        assert util["available"]
        assert util["program"] == "train_epoch_scan"
        assert util["flops_per_step"] > 0
        assert util["regime"] in ("compute-bound", "memory-bound")
        assert util["flops_utilization_pct"] > 0
        text = render_text(report)
        assert "utilization" in text and "flops/step" in text

    def test_summarize_cli_is_jax_free(self, cost_run, tmp_path):
        # The utilization section must render on a machine where importing
        # jax would hang (wedged relay): poison the import and run the CLI
        # in a fresh interpreter against the real run's events.
        run_dir, _ = cost_run
        poison = tmp_path / "poison"
        poison.mkdir()
        (poison / "jax.py").write_text(
            "raise ImportError('summarize CLI imported jax')\n"
        )
        out = subprocess.run(
            [sys.executable, "-m", "masters_thesis_tpu.telemetry",
             "summarize", str(run_dir)],
            cwd=_REPO_ROOT,
            env={**os.environ, "PYTHONPATH": f"{poison}:{_REPO_ROOT}"},
            capture_output=True,
            text=True,
            timeout=60,
        )
        assert out.returncode == 0, out.stdout + out.stderr
        assert "utilization" in out.stdout
        assert "flops/step" in out.stdout

    def test_stream_mode_profiles_the_step(self, tiny_dm):
        trainer = Trainer(
            max_epochs=1,
            gradient_clip_val=5.0,
            check_val_every_n_epoch=1,
            enable_progress_bar=False,
            enable_model_summary=False,
            seed=0,
            strategy="tpu_xla",
            epoch_mode="stream",
            cost_profile=True,
        )
        result = trainer.fit(_small_spec(), tiny_dm)
        assert result.cost_profile is not None
        assert result.cost_profile["program"] == "train_step_stream"
        assert result.cost_profile["steps_per_execution"] == 1
        assert result.cost_profile["flops"] > 0

    def test_unavailable_renders_na_not_omitted(self, tmp_path):
        tel = TelemetryRun(tmp_path)
        tel.event("run_started", platform="cpu", n_devices=1)
        tel.event("cost_unavailable", program="train_epoch_scan")
        tel.event("run_finished", status="ok")
        tel.close()
        report = summarize_path(tmp_path)
        util = report["utilization"]
        assert util is not None and not util["available"]
        text = render_text(report)
        assert "n/a" in text and "cost_unavailable" in text


class TestServeCost:
    def test_buckets_profiled_and_preflight_clean(self):
        from masters_thesis_tpu.serve.engine import PredictEngine
        from masters_thesis_tpu.serve.preflight import run_serve_preflight

        spec = ModelSpec(
            objective="mse", hidden_size=8, num_layers=1, dropout=0.0,
            kernel_impl="xla",
        )
        module = spec.build_module()
        dummy = jnp.zeros((1, 8, 3), jnp.float32)
        params = module.init(jax.random.key(0), dummy)["params"]
        engine = PredictEngine(
            spec, params, n_stocks=4, lookback=8, n_features=3,
            buckets=(1, 2),
        )
        engine.warmup()
        for b in engine.buckets:
            payload = engine.cost_profiles[b]
            assert payload["program"] == f"serve_bucket_{b}"
            assert payload["available"]
            assert payload["peak_bytes"] > 0
        # Bigger bucket moves at least as many bytes per execution.
        assert (
            engine.cost_profiles[2]["bytes_accessed"]
            >= engine.cost_profiles[1]["bytes_accessed"]
        )
        # SV304 is budget-gated: the CPU mesh reports no budget, so the
        # preflight stays clean rather than inventing a limit.
        assert run_serve_preflight(buckets=(1, 2), requests=4) == []


# ---------------------------------------------------------------- ledger


def _row(round_id, sps, util, ts, **over):
    base = dict(
        point="mse/bs=1", round_id=round_id, platform="cpu",
        steps_per_sec=sps, objective="mse", batch_size=1,
        mesh_shape=[8], pack_width=1, flops_per_step=1.6e5,
        bytes_per_step=7.2e5, peak_memory_bytes=3_000_000,
        utilization_pct=util, regime="memory-bound", rev="deadbee", ts=ts,
    )
    base.update(over)
    return led.ledger_record(**base)


class TestLedger:
    def test_append_read_round_trip(self, tmp_path):
        path = tmp_path / "perf_ledger.jsonl"
        r1 = _row("r1", 100.0, 4.0, 1.0)
        r2 = _row("r2", 101.0, 4.1, 2.0)
        led.append_record(path, r1)
        led.append_record(path, r2)
        rows = led.read_ledger(path)
        assert [r["round"] for r in rows] == ["r1", "r2"]
        assert rows[0]["schema"] == led.LEDGER_SCHEMA_VERSION
        assert rows[0]["steps_per_sec"] == 100.0
        # Torn tail (killed writer): the partial line is skipped, not fatal.
        with open(path, "a") as fh:
            fh.write('{"schema": 1, "point": "mse/bs=1", "trunc')
        assert len(led.read_ledger(path)) == 2

    def test_equal_rounds_not_regressed(self, tmp_path):
        path = tmp_path / "perf_ledger.jsonl"
        led.append_record(path, _row("r1", 100.0, 4.0, 1.0))
        led.append_record(path, _row("r2", 98.0, 3.9, 2.0))
        report = led.diff_path(path)
        assert not report["regressed"]
        assert report["compared"]
        assert cli_main(["ledger", str(path)]) == 0

    def test_doctored_slow_round_exits_2(self, tmp_path):
        path = tmp_path / "perf_ledger.jsonl"
        led.append_record(path, _row("r1", 100.0, 4.0, 1.0))
        led.append_record(path, _row("r2", 98.0, 3.9, 2.0))
        # Doctored: latest round runs 40% slower at the SAME config.
        led.append_record(path, _row("r3", 60.0, 2.4, 3.0))
        report = led.diff_path(path)
        assert report["regressed"]
        (reg,) = report["regressions"]
        assert set(reg["regressed_metrics"]) == {
            "steps_per_sec", "utilization_pct",
        }
        assert cli_main(["ledger", str(path)]) == 2
        # A looser threshold lets the same round pass.
        assert cli_main(["ledger", str(path), "--threshold", "50"]) == 0

    def test_config_drift_is_not_a_regression(self, tmp_path):
        path = tmp_path / "perf_ledger.jsonl"
        led.append_record(path, _row("r1", 100.0, 4.0, 1.0))
        # Same point name, different batch size: a NEW config — comparing
        # its 60 steps/s against the bs=1 baseline would be a lie.
        led.append_record(
            path, _row("r2", 60.0, 2.4, 2.0, batch_size=64)
        )
        report = led.diff_path(path)
        assert not report["regressed"]
        assert report["new_configs"]

    def test_missing_ledger_is_rc_1(self, tmp_path):
        assert cli_main(["ledger", str(tmp_path / "nope.jsonl")]) == 1

    def test_ledger_cli_is_jax_free(self, tmp_path):
        path = tmp_path / "perf_ledger.jsonl"
        led.append_record(path, _row("r1", 100.0, 4.0, 1.0))
        led.append_record(path, _row("r2", 50.0, 2.0, 2.0))
        poison = tmp_path / "poison"
        poison.mkdir()
        (poison / "jax.py").write_text(
            "raise ImportError('ledger CLI imported jax')\n"
        )
        env = {**os.environ, "PYTHONPATH": f"{poison}:{_REPO_ROOT}"}
        out = subprocess.run(
            [sys.executable, "-m", "masters_thesis_tpu.telemetry",
             "ledger", str(path)],
            cwd=_REPO_ROOT, env=env, capture_output=True, text=True,
            timeout=60,
        )
        assert out.returncode == 2, out.stdout + out.stderr
        assert "regress" in out.stdout.lower()
        # And --selfcheck, the check.sh gate, under the same poison.
        out = subprocess.run(
            [sys.executable, "-m", "masters_thesis_tpu.telemetry",
             "ledger", "--selfcheck"],
            cwd=_REPO_ROOT, env=env, capture_output=True, text=True,
            timeout=60,
        )
        assert out.returncode == 0, out.stdout + out.stderr
