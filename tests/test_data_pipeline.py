"""Datamodule pipeline: preparation, cache semantics, splits, batching,
prefetch, and bootstrap helpers."""

import numpy as np
import pytest

from masters_thesis_tpu.data import (
    Batch,
    FinancialWindowDataModule,
    bootstrap_synthetic,
    prefetch_to_device,
)


@pytest.fixture
def synth_dir(tmp_path):
    bootstrap_synthetic(tmp_path / "synthetic", n_stocks=6, n_samples=3000, seed=0)
    return tmp_path / "synthetic"


def _dm(synth_dir, **kw):
    defaults = dict(
        lookback_window=30, target_window=10, stride=40, batch_size=4
    )
    defaults.update(kw)
    return FinancialWindowDataModule(synth_dir, **defaults)


def test_bootstrap_synthetic_writes_once(synth_dir):
    stocks = np.load(synth_dir / "stocks.npy")
    assert stocks.shape == (6, 3000)
    mtime = (synth_dir / "stocks.npy").stat().st_mtime_ns
    bootstrap_synthetic(synth_dir, n_stocks=6, n_samples=3000, seed=0)
    assert (synth_dir / "stocks.npy").stat().st_mtime_ns == mtime


def test_prepare_and_setup_shapes(synth_dir):
    dm = _dm(synth_dir)
    dm.prepare_data(verbose=False)
    dm.setup()
    n_win = (3000 - 40) // 40 + 1
    full = dm._arrays
    assert full.x.shape == (n_win, 6, 30, 3)
    assert full.y.shape == (n_win, 6, 10, 4)
    assert full.factor.shape == (n_win, 2)
    assert full.inv_psi.shape == (n_win, 6)
    # Chronological 70/20/10.
    assert dm.train_range == range(0, int(0.7 * n_win))
    assert dm.val_range == range(int(0.7 * n_win), int(0.9 * n_win))
    assert dm.test_range == range(int(0.9 * n_win), n_win)


def test_synthetic_labels_are_ground_truth_constants(synth_dir):
    dm = _dm(synth_dir)
    dm.prepare_data(verbose=False)
    dm.setup()
    alphas = np.load(synth_dir / "alphas.npy")
    betas = np.load(synth_dir / "betas.npy")
    y = dm._arrays.y
    # Channels 2/3 carry the per-stock ground truth, constant across windows
    # and time steps (reference: src/data.py:209-214 appends true alpha/beta).
    np.testing.assert_allclose(y[0, :, 0, 2], alphas, rtol=1e-6)
    np.testing.assert_allclose(y[5, :, 3, 3], betas, rtol=1e-6)
    assert np.all(y[:, :, :, 2] == y[:1, :, :1, 2])


def test_real_data_fallback_uses_target_ols_labels(tmp_path):
    # No alphas.npy/betas.npy -> labels come from the target-window OLS fit.
    rng = np.random.default_rng(0)
    d = tmp_path / "real"
    d.mkdir()
    np.save(d / "stocks.npy", rng.normal(size=(4, 1000)).astype(np.float32))
    np.save(d / "market.npy", rng.normal(size=1000).astype(np.float32))
    dm = FinancialWindowDataModule(
        d, lookback_window=20, target_window=10, stride=30, batch_size=2
    )
    dm.prepare_data(verbose=False)
    dm.setup()
    y = dm._arrays.y
    # Labels vary per window (OLS of that window), unlike the synthetic case.
    assert not np.all(y[:, :, 0, 3] == y[:1, :, 0, 3])


def test_cache_hit_skips_rebuild_and_param_change_rebuilds(synth_dir):
    dm = _dm(synth_dir)
    dm.prepare_data(verbose=False)
    ds_file = synth_dir / "datasets" / "dataset.npz"
    mtime = ds_file.stat().st_mtime_ns
    dm.prepare_data(verbose=False)  # cache hit
    assert ds_file.stat().st_mtime_ns == mtime
    dm2 = _dm(synth_dir, stride=50)
    dm2.prepare_data(verbose=False)  # different hparams -> rebuild
    assert ds_file.stat().st_mtime_ns != mtime


def test_train_batches_shuffled_deterministic(synth_dir):
    dm = _dm(synth_dir)
    dm.prepare_data(verbose=False)
    dm.setup("fit")
    b1 = [b.factor for b in dm.train_batches(epoch=0, seed=7)]
    b2 = [b.factor for b in dm.train_batches(epoch=0, seed=7)]
    b3 = [b.factor for b in dm.train_batches(epoch=1, seed=7)]
    for x, y in zip(b1, b2):
        np.testing.assert_array_equal(x, y)
    assert not all(np.array_equal(x, y) for x, y in zip(b1, b3))
    # All windows served exactly once.
    assert sum(b.shape[0] for b in b1) == len(dm.train_range)


def test_val_test_batches_sequential_bs1(synth_dir):
    dm = _dm(synth_dir)
    dm.prepare_data(verbose=False)
    dm.setup()
    vals = list(dm.val_batches())
    assert all(b.x.shape[0] == 1 for b in vals)
    np.testing.assert_array_equal(
        vals[0].factor[0], dm._arrays.factor[dm.val_range.start]
    )


def test_train_arrays_device_resident_path(synth_dir):
    dm = _dm(synth_dir)
    dm.prepare_data(verbose=False)
    dm.setup("fit")
    arrays = dm.train_arrays()
    assert isinstance(arrays, Batch)
    assert arrays.x.shape[0] == len(dm.train_range)


def test_prefetch_preserves_order_and_content(synth_dir):
    dm = _dm(synth_dir)
    dm.prepare_data(verbose=False)
    dm.setup("fit")
    host = list(dm.train_batches(epoch=0, seed=0))
    fetched = list(prefetch_to_device(dm.train_batches(epoch=0, seed=0), size=3))
    assert len(host) == len(fetched)
    for h, f in zip(host, fetched):
        np.testing.assert_allclose(np.asarray(f.x), h.x, rtol=1e-6)


def test_reconstruction_guard(synth_dir):
    with pytest.raises(ValueError, match="reconstruction"):
        FinancialWindowDataModule(
            synth_dir, lookback_window=10, target_window=20, prediction_task=False
        )


def test_teardown_cleanup_removes_cache(synth_dir):
    dm = _dm(synth_dir)
    dm.prepare_data(verbose=False)
    assert (synth_dir / "datasets" / "dataset.npz").exists()
    dm.teardown("cleanup")
    assert not (synth_dir / "datasets").exists()


def test_bootstrap_rejects_mismatched_dgp_params(tmp_path):
    """Re-bootstrapping a data_dir with different DGP parameters must fail
    loudly instead of silently reusing the stale arrays."""
    from masters_thesis_tpu.data.pipeline import bootstrap_synthetic

    bootstrap_synthetic(tmp_path, n_stocks=4, n_samples=500, seed=0)
    # Same params: idempotent.
    bootstrap_synthetic(tmp_path, n_stocks=4, n_samples=500, seed=0)
    with pytest.raises(ValueError, match="different data_dir"):
        bootstrap_synthetic(
            tmp_path, n_stocks=4, n_samples=500, seed=0, variant="outliers"
        )


def test_bootstrap_refuses_unmarked_arrays(tmp_path):
    """Arrays without the dgp.json completion marker (torn bootstrap or a
    dataset of unknown provenance) are refused loudly, never overwritten or
    trusted."""
    from masters_thesis_tpu.data.pipeline import bootstrap_synthetic

    sentinel = np.zeros((2, 50), np.float32)
    np.save(tmp_path / "stocks.npy", sentinel)  # torn / pre-sidecar
    with pytest.raises(ValueError, match="sidecar"):
        bootstrap_synthetic(
            tmp_path, n_stocks=4, n_samples=500, seed=0, marker_grace_s=0.1
        )
    # The unmarked arrays were not touched.
    assert np.load(tmp_path / "stocks.npy").shape == sentinel.shape


def test_window_cache_rebuilds_when_source_changes(tmp_path):
    """The windowed-dataset cache must track the SOURCE arrays, not just the
    window hyperparameters (silent-staleness guard)."""
    import time

    from masters_thesis_tpu.data.pipeline import (
        FinancialWindowDataModule,
        bootstrap_synthetic,
    )

    kw = dict(lookback_window=8, target_window=4, stride=12)
    bootstrap_synthetic(tmp_path, n_stocks=4, n_samples=500, seed=0)
    dm = FinancialWindowDataModule(tmp_path, **kw)
    dm.prepare_data(verbose=False)
    dm.setup()
    before = np.array(dm.train_arrays().x)

    # Regenerate the source with a different DGP; same window hparams.
    for name in ("stocks.npy", "market.npy", "alphas.npy", "betas.npy",
                 "dgp.json"):
        (tmp_path / name).unlink()
    time.sleep(0.01)  # ensure a distinct mtime on coarse filesystems
    bootstrap_synthetic(
        tmp_path, n_stocks=4, n_samples=500, seed=1, variant="outliers"
    )
    dm2 = FinancialWindowDataModule(tmp_path, **kw)
    dm2.prepare_data(verbose=False)
    dm2.setup()
    after = np.array(dm2.train_arrays().x)
    assert before.shape == after.shape
    assert not np.allclose(before, after)


# ------------------------------------------------------- K-factor pipeline


def test_bootstrap_kfactor_writes_factor_series(tmp_path):
    import json

    bootstrap_synthetic(tmp_path, n_stocks=6, n_samples=600, seed=0, n_factors=3)
    assert np.load(tmp_path / "factors.npy").shape == (3, 600)
    assert np.load(tmp_path / "betas.npy").shape == (6, 3)
    assert json.loads((tmp_path / "dgp.json").read_text())["n_factors"] == 3
    # Re-bootstrapping the same dir at a different K is an error, not reuse.
    with pytest.raises(ValueError, match="different data_dir"):
        bootstrap_synthetic(
            tmp_path, n_stocks=6, n_samples=600, seed=0, n_factors=5
        )


def test_bootstrap_k1_marker_is_unchanged_by_the_kfactor_path(tmp_path):
    """Explicit ``n_factors=1`` must produce the exact pre-K dgp.json (no
    ``n_factors`` key) so existing scalar datasets keep validating."""
    import json

    bootstrap_synthetic(
        tmp_path / "a", n_stocks=4, n_samples=500, seed=0, n_factors=1
    )
    bootstrap_synthetic(tmp_path / "b", n_stocks=4, n_samples=500, seed=0)
    assert (
        (tmp_path / "a" / "dgp.json").read_bytes()
        == (tmp_path / "b" / "dgp.json").read_bytes()
    )
    assert "n_factors" not in json.loads(
        (tmp_path / "a" / "dgp.json").read_text()
    )
    assert not (tmp_path / "a" / "factors.npy").exists()
    # And the scalar arrays themselves are the untouched K=1 DGP.
    for name in ("stocks.npy", "market.npy", "alphas.npy", "betas.npy"):
        np.testing.assert_array_equal(
            np.load(tmp_path / "a" / name), np.load(tmp_path / "b" / name)
        )


def test_kfactor_window_schema(tmp_path):
    """K=3 windows: x carries [rs, f_1..f_3, rs*f_k...] (2K+1 features with
    interaction_only), y carries [r, f_1..f_3, alpha, beta_1..beta_3]
    (2K+2 channels), factor carries [mean (K,) | cov.ravel() (K^2,)]."""
    bootstrap_synthetic(tmp_path, n_stocks=6, n_samples=800, seed=0, n_factors=3)
    dm = FinancialWindowDataModule(
        tmp_path,
        lookback_window=20,
        target_window=10,
        stride=30,
        batch_size=2,
        engine="python",
    )
    assert dm.n_factors == 3
    assert dm.n_features == 7
    dm.prepare_data(verbose=False)
    dm.setup()
    n_win = (800 - 30) // 30 + 1
    full = dm._arrays
    assert full.x.shape == (n_win, 6, 20, 7)
    assert full.y.shape == (n_win, 6, 10, 8)
    assert full.factor.shape == (n_win, 12)
    assert full.inv_psi.shape == (n_win, 6)
    # Ground-truth label channels are the sampled alpha/beta constants.
    alphas = np.load(tmp_path / "alphas.npy")
    betas = np.load(tmp_path / "betas.npy")
    y = np.asarray(full.y)
    np.testing.assert_allclose(y[..., 4], np.broadcast_to(
        alphas[None, :, None], y.shape[:3]), rtol=1e-6)
    for k in range(3):
        np.testing.assert_allclose(y[..., 5 + k], np.broadcast_to(
            betas[None, :, k, None], y.shape[:3]), rtol=1e-6)
