"""The measured per-shape precision policy behind ``precision=auto``.

Defaults are flipped by hardware evidence, not by the byte model alone
(VERDICT r4 #5): ``preferred_compute_dtype`` picks bfloat16 only when the
shape class has a recorded on-TPU win in ``MEASURED_BF16_WAVEFRONT_WINS``
AND bf16's halved VMEM planes admit a strictly deeper wavefront than f32.
With the table empty (no measurement yet), auto is f32 everywhere — the
reference-parity numerics.
"""

from __future__ import annotations

import jax.numpy as jnp
import pytest

from masters_thesis_tpu.ops import lstm_kernel as lk


def test_auto_is_f32_until_a_win_is_measured():
    # Empty table (ships empty until the A/B records a win): every shape,
    # including the deep-stack ones bf16 would help, resolves f32.
    assert lk.MEASURED_BF16_WAVEFRONT_WINS == ()
    for layers in (1, 2, 4, 8):
        assert lk.preferred_compute_dtype(layers, 64) == jnp.float32


def test_bf16_vmem_halving_admits_deeper_wavefronts():
    # The premise of the policy, stated by the byte model itself: at the
    # canonical window shape (T=60, 100 stock rows padded to 104),
    # halving the per-plane itemsize admits a strictly deeper fused stack.
    f32_depth = lk.max_wavefront_depth(60, 100, 64, 8, True, 4)
    bf16_depth = lk.max_wavefront_depth(60, 100, 64, 8, True, 2)
    assert bf16_depth > f32_depth >= 2


def test_measured_win_flips_only_depth_unlocking_shapes(monkeypatch):
    monkeypatch.setattr(
        lk, "MEASURED_BF16_WAVEFRONT_WINS", ((4, 64),), raising=True
    )
    # Deep model in the measured class: bf16 unlocks depth -> flips.
    assert lk.preferred_compute_dtype(8, 64, backend="tpu") == jnp.bfloat16
    # Too shallow for the class (min_layers=4): stays f32.
    assert lk.preferred_compute_dtype(2, 64, backend="tpu") == jnp.float32
    # Different hidden size: not the measured class, stays f32.
    assert lk.preferred_compute_dtype(8, 96, backend="tpu") == jnp.float32


def test_flip_requires_the_wavefront_path_to_actually_run(monkeypatch):
    # The deeper-wavefront rationale only exists on the fused Pallas path:
    # an xla/scan kernel_impl, a tripped kill-switch, or a non-TPU backend
    # must keep the reference-parity f32 numerics even for a measured win.
    monkeypatch.setattr(
        lk, "MEASURED_BF16_WAVEFRONT_WINS", ((4, 64),), raising=True
    )
    flip = dict(backend="tpu")
    assert lk.preferred_compute_dtype(8, 64, **flip) == jnp.bfloat16
    assert lk.preferred_compute_dtype(
        8, 64, kernel_impl="xla", **flip
    ) == jnp.float32
    assert lk.preferred_compute_dtype(8, 64, backend="cpu") == jnp.float32
    monkeypatch.setenv("MT_LSTM_FUSED_PAIR", "0")
    assert lk.preferred_compute_dtype(8, 64, **flip) == jnp.float32
    monkeypatch.delenv("MT_LSTM_FUSED_PAIR")
    monkeypatch.setenv("MT_LSTM_WAVEFRONT", "0")
    assert lk.preferred_compute_dtype(8, 64, **flip) == jnp.float32


def test_trainer_auto_resolves_through_the_policy(monkeypatch):
    from masters_thesis_tpu.models.objectives import ModelSpec
    from masters_thesis_tpu.train import Trainer

    class _Windows:
        lookback_window = 60

    trainer = Trainer(max_epochs=1, precision="auto",
                      enable_progress_bar=False, enable_model_summary=False)
    assert trainer.compute_dtype is None  # deferred to fit/test time

    spec = ModelSpec(objective="mse", hidden_size=64, num_layers=8)
    assert trainer._resolve_dtype(spec, _Windows()) == jnp.float32

    monkeypatch.setattr(
        lk, "MEASURED_BF16_WAVEFRONT_WINS", ((4, 64),), raising=True
    )
    # The trainer resolves against the REAL backend (cpu in tests), where
    # the wavefront path doesn't run — a measured win still stays f32.
    assert trainer._resolve_dtype(spec, _Windows()) == jnp.float32
    # On a TPU backend the same spec flips (policy called directly).
    assert lk.preferred_compute_dtype(
        spec.num_layers, spec.hidden_size, 60, 100,
        kernel_impl=spec.kernel_impl, backend="tpu",
    ) == jnp.bfloat16

    # Explicit precisions are untouched by the policy.
    pinned = Trainer(max_epochs=1, precision="bf16-mixed",
                     enable_progress_bar=False, enable_model_summary=False)
    assert pinned._resolve_dtype(spec, _Windows()) == jnp.bfloat16


def test_unknown_precision_still_rejected():
    from masters_thesis_tpu.train import Trainer

    with pytest.raises(ValueError, match="unknown precision"):
        Trainer(max_epochs=1, precision="fp8")
