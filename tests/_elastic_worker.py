"""Fleet rank for the REAL elastic-fleet chaos test.

One rank of an N-process ``jax.distributed`` CPU fleet launched by the
:class:`~masters_thesis_tpu.resilience.fleetsup.FleetSupervisor`. Joins
the generation's coordinator via :func:`parallel.mesh.join_fleet` (the
supervisor exports ``MTT_COORDINATOR`` + ``JAX_PROCESS_INDEX``/``COUNT``
per generation), runs a real Trainer.fit with epoch-granular
checkpointing and auto-resume against a SHARED checkpoint dir, then
rank 0 dumps the final params to ``<state>/params.npz``.

Chaos: when ``MTT_CHAOS_KILL_RANK`` names this rank and this is
generation 0, the rank installs an in-process fault plan that SIGKILLs
it right after epoch ``MTT_CHAOS_KILL_EPOCH`` is dispatched (before the
checkpoint save) — a host dying mid-epoch. The supervisor must then
tear down the survivors and relaunch the whole fleet from the last
manifest-verified checkpoint; tests/test_fleetsup.py asserts the final
params are bit-identical to a fault-free fleet's.

Usage (as a supervisor cmd template):
    python tests/_elastic_worker.py --state <shared> --out {out} \\
        [--epochs N]
"""

from __future__ import annotations

import argparse
import os
import sys
from pathlib import Path

# The package is run from the repo, not installed: python <this file> puts
# tests/ (not the repo root) on sys.path.
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")  # beat the axon sitecustomize

import numpy as np  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--state", type=Path, required=True,
                    help="shared dir: data + checkpoints + final params")
    ap.add_argument("--out", type=Path, required=True,
                    help="this rank's per-generation telemetry dir")
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--coordinator", default=None,
                    help="host:port minted per generation by the supervisor")
    args = ap.parse_args()
    args.out.mkdir(parents=True, exist_ok=True)

    gen = int(os.environ.get("MTT_GENERATION", "0") or 0)
    kill_rank = os.environ.get("MTT_CHAOS_KILL_RANK")
    kill_epoch = int(os.environ.get("MTT_CHAOS_KILL_EPOCH", "1") or 1)

    from masters_thesis_tpu.parallel import join_fleet

    rank, world = join_fleet(coordinator_address=args.coordinator or None)
    assert jax.process_count() == world, jax.process_count()

    if kill_rank is not None and int(kill_rank) == rank and gen == 0:
        # SIGKILL self right after the chosen epoch is dispatched but
        # BEFORE its checkpoint save: the relaunch must redo this epoch
        # from the last published checkpoint. Installed in-process (not
        # via MTT_FAULT_PLAN) because the supervisor exports one env to
        # every rank and only this rank may die.
        from masters_thesis_tpu.resilience import faults
        from masters_thesis_tpu.resilience.faults import FaultPlan, FaultSpec

        faults.install_plan(FaultPlan([
            FaultSpec(point="trainer.epoch_dispatched", kind="kill",
                      attempt=None, match={"epoch": kill_epoch}),
        ]))

    from masters_thesis_tpu.data.pipeline import (
        FinancialWindowDataModule,
        bootstrap_synthetic,
    )
    from masters_thesis_tpu.models.objectives import ModelSpec
    from masters_thesis_tpu.telemetry import TelemetryRun
    from masters_thesis_tpu.train import Trainer

    # Rank 0 generates the shared dataset; the rest block on the
    # completion marker (same rendezvous as the distributed test). The
    # cache persists across generations, so a relaunch skips regen.
    data_dir = args.state / "data"
    bootstrap_synthetic(data_dir, n_stocks=4, n_samples=3820, seed=0)
    dm = FinancialWindowDataModule(
        data_dir, lookback_window=16, target_window=8, stride=24,
        batch_size=1,
    )
    dm.prepare_data(verbose=False)
    dm.setup()

    telemetry = TelemetryRun(args.out / "telemetry")
    rec = telemetry.attach_flight_recorder(heartbeat_interval_s=0.2)
    rec.beat(phase="setup")
    trainer = Trainer(
        max_epochs=args.epochs,
        gradient_clip_val=5.0,
        check_val_every_n_epoch=1,
        checkpoint_every_n_epochs=1,
        strategy="tpu_xla",
        enable_progress_bar=False,
        enable_model_summary=False,
        seed=0,
        ckpt_dir=args.state / "ckpts",
        resume="auto",
        telemetry=telemetry,
    )
    spec = ModelSpec(
        objective="mse", hidden_size=8, num_layers=1, dropout=0.0,
        learning_rate=1e-2,
    )
    result = trainer.fit(spec, dm)
    if rank == 0:
        leaves = jax.tree_util.tree_leaves(jax.device_get(result.params))
        np.savez(
            args.state / "params.npz",
            **{f"p{i}": np.asarray(a) for i, a in enumerate(leaves)},
        )
    telemetry.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
