"""Window store: round-trip, torn/corrupt refusal, and bitwise parity of
store-backed loads against the in-memory ``_build_windows`` path.

The store is the universe-scale data plane (docs/perf.md "Universe
scale"): windows are built shard-by-shard, published atomically with
content hashes, and memory-mapped at train time. Its correctness
contract is exact — a store-backed datamodule must serve bit-identical
rows to the all-in-memory build on the same source series.
"""

import numpy as np
import pytest

from masters_thesis_tpu.data import (
    FinancialWindowDataModule,
    bootstrap_synthetic,
)
from masters_thesis_tpu.data.window_store import (
    FIELDS,
    MANIFEST_NAME,
    WindowStore,
    WindowStoreError,
)


@pytest.fixture
def series(rng):
    r_stocks = rng.normal(size=(6, 800)).astype(np.float32)
    r_factors = rng.normal(size=800).astype(np.float32)
    return r_stocks, r_factors


def _build(tmp_path, series, n_shards=4, **kw):
    r_stocks, r_factors = series
    defaults = dict(
        lookback_window=30,
        target_window=10,
        stride=40,
        n_shards=n_shards,
        source_hash="deadbeef",
    )
    defaults.update(kw)
    return WindowStore.build_from_series(
        tmp_path / "store", r_stocks, r_factors, **defaults
    )


# ----------------------------------------------------------- round-trip


def test_round_trip_reopen_bitwise(tmp_path, series):
    built = _build(tmp_path, series)
    reopened = WindowStore.open(tmp_path / "store", verify=True)
    assert reopened.n_windows == built.n_windows
    assert reopened.n_shards == 4
    assert reopened.source_hash == "deadbeef"
    for a, b in zip(built.load_all(), reopened.load_all()):
        assert np.array_equal(a, b)


def test_shards_tile_the_window_axis(tmp_path, series):
    store = _build(tmp_path, series)
    bounds = [store.bounds(s) for s in range(store.n_shards)]
    assert bounds[0][0] == 0 and bounds[-1][1] == store.n_windows
    for (_, hi), (lo, _) in zip(bounds, bounds[1:]):
        assert hi == lo  # contiguous, no gaps or overlap


def test_contiguous_take_is_zero_copy_memmap(tmp_path, series):
    store = _build(tmp_path, series)
    lo, hi = store.bounds(1)
    rows = store.take(np.arange(lo, hi))
    for arr in rows:
        # A same-shard contiguous run must come back as memmap views —
        # the zero-copy hot path the prefetcher's fault accounting and
        # the ~0% starvation claim both rest on.
        assert isinstance(arr, np.memmap)
    full = store.load_all()
    for field, arr in zip(FIELDS, rows):
        ref = full[FIELDS.index(field)][lo:hi]
        assert np.array_equal(np.asarray(arr), ref)


def test_scattered_take_gathers_across_shards(tmp_path, series):
    store = _build(tmp_path, series)
    idx = np.asarray([store.n_windows - 1, 0, store.bounds(1)[0]])
    rows = store.take(idx)
    full = store.load_all()
    for got, ref in zip(rows, full):
        assert not isinstance(got, np.memmap)
        assert np.array_equal(got, ref[idx])


def test_more_shards_than_windows_clamps(tmp_path, series):
    store = _build(tmp_path, series, n_shards=64)
    assert store.n_shards == store.n_windows


# ------------------------------------------------------ refusal semantics


def test_open_refuses_missing_manifest(tmp_path, series):
    _build(tmp_path, series)
    (tmp_path / "store" / MANIFEST_NAME).unlink()
    with pytest.raises(WindowStoreError, match="torn before completion"):
        WindowStore.open(tmp_path / "store")


def test_open_refuses_missing_shard_file(tmp_path, series):
    _build(tmp_path, series)
    (tmp_path / "store" / "shard00002.y.npy").unlink()
    with pytest.raises(WindowStoreError, match="missing"):
        WindowStore.open(tmp_path / "store")


def test_open_refuses_truncated_shard(tmp_path, series):
    _build(tmp_path, series)
    victim = tmp_path / "store" / "shard00001.x.npy"
    victim.write_bytes(victim.read_bytes()[:-16])
    with pytest.raises(WindowStoreError, match="torn or truncated"):
        WindowStore.open(tmp_path / "store")


def test_open_refuses_content_hash_mismatch(tmp_path, series):
    _build(tmp_path, series)
    victim = tmp_path / "store" / "shard00000.factor.npy"
    raw = bytearray(victim.read_bytes())
    raw[-1] ^= 0xFF  # same size, different content
    victim.write_bytes(bytes(raw))
    # The structural fast path cannot see a same-size flip...
    WindowStore.open(tmp_path / "store")
    # ...but the verify path must refuse it (the corrupt-shard runbook).
    with pytest.raises(WindowStoreError, match="altered or corrupted"):
        WindowStore.open(tmp_path / "store", verify=True)


def test_open_refuses_version_skew(tmp_path, series):
    import json

    _build(tmp_path, series)
    manifest = tmp_path / "store" / MANIFEST_NAME
    doc = json.loads(manifest.read_text())
    doc["version"] = 999
    manifest.write_text(json.dumps(doc))
    with pytest.raises(WindowStoreError, match="version"):
        WindowStore.open(tmp_path / "store")


# -------------------------------------- parity vs the in-memory pipeline


@pytest.mark.parametrize("n_factors", [1, 3])
def test_store_backed_datamodule_matches_in_memory_bitwise(
    tmp_path, n_factors
):
    """A store built with the 8-way mesh shard layout serves every split
    bit-identically to the all-in-memory ``_build_windows`` path."""
    data_dir = tmp_path / "synthetic"
    bootstrap_synthetic(
        data_dir, n_stocks=8, n_samples=2000, seed=0, n_factors=n_factors
    )
    kw = dict(
        lookback_window=30,
        target_window=10,
        stride=40,
        batch_size=2,
        engine="python",
    )
    dm_mem = FinancialWindowDataModule(data_dir, **kw)
    dm_mem.prepare_data(verbose=False)
    dm_mem.setup()
    dm_store = FinancialWindowDataModule(data_dir, store_shards=8, **kw)
    dm_store.prepare_data(verbose=False)
    dm_store.setup()

    assert dm_store._store.n_shards == 8
    assert dm_store.train_range == dm_mem.train_range
    assert dm_store.n_factors == dm_mem.n_factors == n_factors
    for split in ("train_arrays", "val_arrays", "test_arrays"):
        mem, stored = getattr(dm_mem, split)(), getattr(dm_store, split)()
        for field, a, b in zip(FIELDS, mem, stored):
            assert np.array_equal(
                np.asarray(a), np.asarray(b)
            ), f"{split}.{field} diverges from the in-memory build"


def test_store_batches_match_in_memory_batches(tmp_path):
    # Same mesh-aligned geometry as the parity test above: the claim
    # under test here is the shuffled batch STREAM (ordering/indexing),
    # on a layout whose numerical parity the previous test establishes.
    data_dir = tmp_path / "synthetic"
    bootstrap_synthetic(data_dir, n_stocks=8, n_samples=2000, seed=0)
    # engine pinned to python: stores always build through the jnp path,
    # so the in-memory side must too for an exact comparison.
    kw = dict(
        lookback_window=30,
        target_window=10,
        stride=40,
        batch_size=3,
        engine="python",
    )
    dm_mem = FinancialWindowDataModule(data_dir, **kw)
    dm_mem.prepare_data(verbose=False)
    dm_mem.setup()
    dm_store = FinancialWindowDataModule(data_dir, store_shards=8, **kw)
    dm_store.prepare_data(verbose=False)
    dm_store.setup()
    # Same epoch, same shuffle seed -> identical batch streams.
    for mem, stored in zip(
        dm_mem.train_batches(epoch=2, seed=7),
        dm_store.train_batches(epoch=2, seed=7),
    ):
        for a, b in zip(mem, stored):
            assert np.array_equal(np.asarray(a), np.asarray(b))
