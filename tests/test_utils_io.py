"""Direct unit tests for the crash-safe IO primitives.

These invariants underpin the dataset cache, checkpoint sidecars, and
multi-process rendezvous (utils/io.py); until now they were only exercised
indirectly through those subsystems.
"""

import pytest

from masters_thesis_tpu.utils import atomic_publish, atomic_write_text, wait_until


def _no_tmp_leftovers(directory):
    return not [p for p in directory.iterdir() if ".tmp" in p.name]


class TestAtomicPublish:
    def test_clean_exit_publishes(self, tmp_path):
        target = tmp_path / "artifact.json"
        with atomic_publish(target) as tmp:
            tmp.write_text("payload")
            assert not target.exists()  # invisible until the rename
        assert target.read_text() == "payload"
        assert _no_tmp_leftovers(tmp_path)

    def test_exception_leaves_target_untouched(self, tmp_path):
        target = tmp_path / "artifact.json"
        target.write_text("old")
        with pytest.raises(RuntimeError):
            with atomic_publish(target) as tmp:
                tmp.write_text("half-written")
                raise RuntimeError("writer died")
        assert target.read_text() == "old"
        assert _no_tmp_leftovers(tmp_path)

    def test_overwrite_is_atomic_replace(self, tmp_path):
        target = tmp_path / "artifact.json"
        atomic_write_text(target, "v1")
        atomic_write_text(target, "v2")
        assert target.read_text() == "v2"
        assert _no_tmp_leftovers(tmp_path)

    def test_concurrent_writers_each_get_own_scratch(self, tmp_path):
        target = tmp_path / "artifact.json"
        with atomic_publish(target) as a, atomic_publish(target) as b:
            assert a != b  # uuid scratch names: no cross-writer clobbering
            a.write_text("A")
            b.write_text("B")
        # Context exit is LIFO: b renames first, a's rename lands LAST —
        # the docstring's "last rename wins with an intact artifact".
        assert target.read_text() == "A"
        assert _no_tmp_leftovers(tmp_path)


class TestWaitUntil:
    def test_true_when_predicate_flips(self):
        calls = {"n": 0}

        def pred():
            calls["n"] += 1
            return calls["n"] >= 3

        assert wait_until(pred, timeout_s=10.0, interval_s=0.01)
        assert calls["n"] == 3

    def test_false_on_timeout(self):
        assert not wait_until(lambda: False, timeout_s=0.2, interval_s=0.05)
