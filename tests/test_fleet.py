"""Serving fleet + exported-program cache (ISSUE 13).

Three layers, cheapest first:

- jax-free fleet state machine: fake engines drive dispatch, per-replica
  admission, failover (crash / hang / deterministic halt), and the
  explicit no-live-replicas shed — no backend, milliseconds per test.
- ProgramCache: round-trip, torn/stale/injected-corruption refusal, key
  identity — real engines on the 8-device virtual CPU mesh (conftest).
- chaos: a real 2-replica fleet with a shared cache loses a replica at
  load; survivors absorb the work (zero late, zero silent drops) and the
  resurrection boots warm from the cache with ZERO compiles.
"""

import json
import time
from pathlib import Path

import numpy as np
import pytest

from masters_thesis_tpu.resilience import faults
from masters_thesis_tpu.resilience.faults import FaultPlan, FaultSpec
from masters_thesis_tpu.resilience.supervisor import ReplicaRestartPolicy
from masters_thesis_tpu.serve.fleet import (
    STATE_DEAD,
    STATE_LIVE,
    FleetServer,
)
from masters_thesis_tpu.serve.queue import (
    STATUS_ERROR,
    STATUS_OK,
    STATUS_REJECTED_LATE,
    STATUS_SHED,
    MicroBatchQueue,
    ServeRequest,
)

K, T, F = 4, 8, 3
CACHE_BUCKETS = (1, 2)


@pytest.fixture(autouse=True)
def _no_leaked_faults(monkeypatch):
    """Every test starts and ends with injection off, whatever it does."""
    monkeypatch.delenv(faults.FAULT_PLAN_ENV, raising=False)
    monkeypatch.delenv(faults.ATTEMPT_ENV, raising=False)
    yield
    faults.clear_plan()


# ------------------------------------------------------------ fake engines


class FakeEngine:
    """Engine-protocol stand-in: configurable service time, no jax."""

    def __init__(self, service_s: float = 0.001, buckets=(1, 2, 4)):
        self.service_s = service_s
        self.buckets = tuple(buckets)
        self.window_shape = (2, 3, 1)
        self.max_bucket = self.buckets[-1]
        self.compile_events = len(self.buckets)
        self.cache_hits = 0
        self.platform = "fake"
        self.predicted = 0

    def warmup(self) -> float:
        return self.service_s

    def predict(self, x, params=None):
        time.sleep(self.service_s)
        self.predicted += x.shape[0]
        n, k = x.shape[0], self.window_shape[0]
        return (
            np.zeros((n, k), np.float32),
            np.zeros((n, k), np.float32),
        )

    def degrade_to_cpu(self) -> None:
        pass


def _fake_fleet(n=3, service_s=0.001, **kwargs):
    kwargs.setdefault("max_wait_s", 0.002)
    kwargs.setdefault(
        "restart_policy", ReplicaRestartPolicy(backoff_s=0.01)
    )
    if not isinstance(service_s, (list, tuple)):
        service_s = [service_s] * n
    factories = {
        f"r{i}": (lambda s=s: FakeEngine(service_s=s))
        for i, s in enumerate(service_s)
    }
    return FleetServer(factories, **kwargs)


def _window():
    return np.zeros((2, 3, 1), np.float32)


def _wait_for(cond, timeout=8.0, period=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(period)
    return False


# ------------------------------------------------- fleet dispatch (jax-free)


def test_fleet_serves_across_replicas():
    fleet = _fake_fleet(n=3)
    fleet.start()
    try:
        pend = [fleet.submit(_window(), deadline_s=5.0) for _ in range(40)]
        results = [p.result(timeout=10.0) for p in pend]
    finally:
        stats = fleet.stop()
    assert all(r.status == STATUS_OK for r in results)
    assert stats["late_deliveries"] == 0
    # stop() drains serving replicas; draining (not dead) means every
    # replica was alive to the end.
    assert all(
        r["state"] == "draining" for r in stats["replicas"].values()
    )
    assert sum(r["completed"] for r in stats["replicas"].values()) == 40


def test_least_loaded_dispatch_prefers_fast_replica():
    # r0 is 50x slower; the backlog estimate should route almost all
    # batches to r1 (a degraded/slow replica keeps serving, it just
    # stops winning work).
    fleet = _fake_fleet(n=2, service_s=[0.05, 0.001])
    fleet.start()
    try:
        pend = [fleet.submit(_window(), deadline_s=5.0) for _ in range(30)]
        for p in pend:
            assert p.result(timeout=10.0).status == STATUS_OK
    finally:
        stats = fleet.stop()
    assert (
        stats["replicas"]["r1"]["completed"]
        > stats["replicas"]["r0"]["completed"]
    )


def test_admission_uses_best_replica_not_worst():
    # Deadline feasible only on the fast replica: a fleet that admitted
    # on a global (or worst-replica) estimate would shed everything.
    slow_only = _fake_fleet(n=1, service_s=[0.2])
    slow_only.start()
    try:
        r = slow_only.submit(_window(), deadline_s=0.05).result(timeout=5.0)
        assert r.status == STATUS_SHED
    finally:
        slow_only.stop()

    mixed = _fake_fleet(n=2, service_s=[0.2, 0.001])
    mixed.start()
    try:
        results = [
            mixed.submit(_window(), deadline_s=0.05).result(timeout=5.0)
            for _ in range(10)
        ]
    finally:
        stats = mixed.stop()
    assert all(r.status == STATUS_OK for r in results)
    assert stats["replicas"]["r1"]["completed"] == 10


def test_queue_feasibility_hook_sheds_with_reason():
    q = MicroBatchQueue(feasibility=lambda req, depth: "too slow today")
    pending = q.submit(
        ServeRequest(rid=1, x=None, deadline_ts=time.monotonic() + 1.0)
    )
    assert pending.done
    response = pending.result(timeout=1.0)
    assert response.status == STATUS_SHED
    assert "too slow today" in response.detail
    q.close()


# ---------------------------------------------------- failover (jax-free)


def test_replica_crash_redispatches_then_restarts():
    fleet = _fake_fleet(n=2)
    plan = FaultPlan(faults=[FaultSpec(
        point="serve.replica_dispatch", kind="raise", attempt=1,
        match={"replica": "r0"},
    )])
    fleet.start()
    try:
        faults.install_plan(plan)
        pend = [fleet.submit(_window(), deadline_s=5.0) for _ in range(30)]
        assert _wait_for(lambda: fleet.deaths >= 1)
        faults.clear_plan()
        results = [p.result(timeout=10.0) for p in pend]
        # One death, every request still resolved explicitly, no lates.
        assert all(
            r.status in (STATUS_OK, STATUS_SHED, STATUS_REJECTED_LATE)
            for r in results
        )
        assert _wait_for(lambda: fleet.replicas["r0"].generation >= 2)
        assert _wait_for(
            lambda: fleet.replicas["r0"].state == STATE_LIVE
        )
    finally:
        stats = fleet.stop()
    assert stats["deaths"] >= 1
    assert stats["late_deliveries"] == 0
    assert stats["replicas"]["r0"]["restarts"] >= 1


def test_hang_watchdog_declares_replica_dead():
    fleet = _fake_fleet(n=2, hang_timeout_s=0.3)
    plan = FaultPlan(faults=[FaultSpec(
        point="serve.replica_dispatch", kind="hang", attempt=1,
        match={"replica": "r1"},
    )])
    fleet.start()
    try:
        faults.install_plan(plan)
        pend = [fleet.submit(_window(), deadline_s=5.0) for _ in range(20)]
        assert _wait_for(lambda: fleet.deaths >= 1)
        faults.clear_plan()
        for p in pend:
            r = p.result(timeout=10.0)
            assert r.status in (STATUS_OK, STATUS_SHED, STATUS_REJECTED_LATE)
        assert _wait_for(lambda: fleet.replicas["r1"].generation >= 2)
    finally:
        stats = fleet.stop()
    assert stats["deaths"] >= 1
    assert stats["late_deliveries"] == 0


def test_repeated_identical_crash_halts_deterministically():
    fleet = _fake_fleet(n=2)
    plan = FaultPlan(faults=[FaultSpec(
        point="serve.replica_dispatch", kind="raise", attempt=None,
        match={"replica": "r0"},
    )])
    fleet.start()
    try:
        faults.install_plan(plan)
        halted = False
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and not halted:
            p = fleet.submit(_window(), deadline_s=2.0)
            p.result(timeout=5.0)
            halted = fleet.replicas["r0"].halted
        assert halted, "identical crash fingerprints never halted r0"
        faults.clear_plan()
        # The survivor keeps serving after the halt.
        r = fleet.submit(_window(), deadline_s=5.0).result(timeout=5.0)
        assert r.status == STATUS_OK
    finally:
        stats = fleet.stop()
    assert fleet.replicas["r0"].state == STATE_DEAD
    assert stats["replicas"]["r1"]["state"] in ("live", "draining")


def test_all_replicas_dead_sheds_explicitly():
    fleet = _fake_fleet(
        n=1, restart_policy=ReplicaRestartPolicy(max_restarts=0),
    )
    plan = FaultPlan(faults=[FaultSpec(
        point="serve.replica_dispatch", kind="raise", attempt=1,
        match={"replica": "r0"},
    )])
    fleet.start()
    try:
        faults.install_plan(plan)
        fleet.submit(_window(), deadline_s=2.0).result(timeout=5.0)
        assert _wait_for(lambda: fleet.replicas["r0"].halted)
        faults.clear_plan()
        r = fleet.submit(_window(), deadline_s=2.0).result(timeout=5.0)
        assert r.status == STATUS_SHED
        assert "no live replicas" in r.detail
    finally:
        stats = fleet.stop()
    assert stats["shed_by_reason"].get("no_live_replicas", 0) >= 1


def test_injected_corruption_errors_but_replica_stays_live():
    fleet = _fake_fleet(n=2)
    plan = FaultPlan(faults=[FaultSpec(
        point="serve.replica_dispatch", kind="nan", attempt=1,
    )])
    fleet.start()
    try:
        faults.install_plan(plan)
        poisoned = fleet.submit(_window(), deadline_s=5.0).result(
            timeout=5.0
        )
        faults.clear_plan()
        clean = [
            fleet.submit(_window(), deadline_s=5.0).result(timeout=5.0)
            for _ in range(6)
        ]
    finally:
        stats = fleet.stop()
    assert poisoned.status == STATUS_ERROR  # refused, not served
    assert all(r.status == STATUS_OK for r in clean)
    assert stats["deaths"] == 0  # bad output is not a crash
    assert stats["errors"] >= 1


def test_boot_fault_then_successful_retry():
    fleet = _fake_fleet(n=2)
    # Wedge ONLY generation 1: boot faults match on the attempt context,
    # so the inline retry (generation 2) comes up clean.
    plan = FaultPlan(faults=[FaultSpec(
        point="serve.replica_boot", kind="wedge", attempt=1,
        match={"replica": "r0", "generation": 1},
    )])
    faults.install_plan(plan)
    try:
        fleet.start()  # initial boot retries inline after the wedge
        faults.clear_plan()
        assert fleet.replicas["r0"].state == STATE_LIVE
        r = fleet.submit(_window(), deadline_s=5.0).result(timeout=5.0)
        assert r.status == STATUS_OK
    finally:
        fleet.stop()


# ------------------------------------------------------------ program cache


def _tiny_spec():
    from masters_thesis_tpu.models.objectives import ModelSpec

    return ModelSpec(
        objective="mse", hidden_size=8, num_layers=1, dropout=0.0,
        kernel_impl="xla",
    )


def _init_params(spec, seed=0):
    import jax
    import jax.numpy as jnp

    module = spec.build_module()
    return module.init(
        jax.random.key(seed), jnp.zeros((1, T, F), jnp.float32)
    )["params"]


def _cached_engine(cache, seed=0, buckets=CACHE_BUCKETS):
    from masters_thesis_tpu.serve.engine import PredictEngine

    spec = _tiny_spec()
    return PredictEngine(
        spec, _init_params(spec, seed),
        n_stocks=K, lookback=T, n_features=F, buckets=buckets,
        program_cache=cache,
    )


def _rejections(cache, reason=None):
    evs = [e for e in cache.events if e["kind"] == "cache_rejected"]
    return [e for e in evs if reason is None or e["reason"] == reason]


def test_program_cache_round_trip_zero_compiles(tmp_path):
    from masters_thesis_tpu.serve.program_cache import ProgramCache

    cold_cache = ProgramCache(tmp_path)
    cold = _cached_engine(cold_cache)
    cold.warmup()
    assert cold.compile_events == len(CACHE_BUCKETS)
    assert cold_cache.stores == len(CACHE_BUCKETS)

    warm_cache = ProgramCache(tmp_path)
    warm = _cached_engine(warm_cache)
    warm.warmup()
    assert warm.compile_events == 0
    assert warm.cache_hits == len(CACHE_BUCKETS)
    assert warm_cache.hits == len(CACHE_BUCKETS)

    x = cold.golden_batch(2, seed=123)
    a0, b0 = cold.predict(x)
    a1, b1 = warm.predict(x)
    np.testing.assert_array_equal(np.asarray(a0), np.asarray(a1))
    np.testing.assert_array_equal(np.asarray(b0), np.asarray(b1))


def test_program_cache_refuses_torn_entry(tmp_path):
    from masters_thesis_tpu.serve.program_cache import ProgramCache

    cold = _cached_engine(ProgramCache(tmp_path))
    cold.warmup()
    victim = next(tmp_path.glob("*.bin"))
    blob = bytearray(victim.read_bytes())
    blob[len(blob) // 2] ^= 0xFF
    victim.write_bytes(bytes(blob))

    cache = ProgramCache(tmp_path)
    eng = _cached_engine(cache)
    eng.warmup()
    assert cache.rejections >= 1
    assert _rejections(cache, "torn")
    # The torn bucket compiled fresh; the intact one still hit.
    assert eng.compile_events >= 1
    assert eng.compile_events + eng.cache_hits == len(CACHE_BUCKETS)
    x = cold.golden_batch(2, seed=7)
    np.testing.assert_array_equal(
        np.asarray(cold.predict(x)[0]), np.asarray(eng.predict(x)[0])
    )


def test_program_cache_refuses_stale_fingerprint(tmp_path):
    from masters_thesis_tpu.serve.program_cache import ProgramCache

    _cached_engine(ProgramCache(tmp_path)).warmup()
    manifest_path = tmp_path / "MANIFEST.json"
    manifest = json.loads(manifest_path.read_text())
    for entry in manifest["entries"].values():
        entry["fingerprint"]["jaxlib"] = "some-other-build"
    manifest_path.write_text(json.dumps(manifest))

    cache = ProgramCache(tmp_path)
    eng = _cached_engine(cache)
    eng.warmup()
    assert cache.hits == 0
    assert len(_rejections(cache, "stale")) == len(CACHE_BUCKETS)
    assert eng.compile_events == len(CACHE_BUCKETS)


def test_program_cache_fault_point_corrupts_then_refuses(tmp_path):
    from masters_thesis_tpu.serve.program_cache import ProgramCache

    _cached_engine(ProgramCache(tmp_path)).warmup()
    plan = FaultPlan(faults=[FaultSpec(
        point="cache.load", kind="corrupt", attempt=1,
    )])
    faults.install_plan(plan)
    try:
        cache = ProgramCache(tmp_path)
        eng = _cached_engine(cache)
        eng.warmup()
    finally:
        faults.clear_plan()
    assert cache.rejections >= 1
    assert _rejections(cache, "torn")
    assert eng.compile_events >= 1  # the corrupted entry compiled fresh


def test_entry_key_tracks_identity(tmp_path):
    from masters_thesis_tpu.serve.program_cache import entry_key

    base = {
        "spec": {"objective": "mse"}, "params": {"n": 1},
        "window": [4, 8, 3], "bucket": 2,
        "fingerprint": {"jaxlib": "x", "device_ids": [0]},
    }
    k0 = entry_key(base)
    assert k0 == entry_key(dict(base))  # deterministic
    for field, value in (
        ("bucket", 4),
        ("window", [4, 8, 4]),
        ("fingerprint", {"jaxlib": "x", "device_ids": [1]}),
        ("params", {"n": 2}),
    ):
        assert entry_key({**base, field: value}) != k0


# ----------------------------------------------------------- chaos (real)


def test_fleet_kill_at_load_survives_and_resurrects_warm(tmp_path):
    """The acceptance drill: kill a replica mid-load. Survivors absorb
    the work (zero late, zero silent drops), the request spans record the
    cross-replica hop, and the resurrection boots from the shared
    program cache with ZERO compiles."""
    from masters_thesis_tpu.serve.engine import PredictEngine
    from masters_thesis_tpu.serve.fleet import partition_meshes
    from masters_thesis_tpu.serve.program_cache import ProgramCache
    from masters_thesis_tpu.telemetry import TelemetryRun

    spec = _tiny_spec()
    params = _init_params(spec)
    cache = ProgramCache(tmp_path / "cache")
    meshes = partition_meshes(2)

    def factory_for(m):
        return lambda: PredictEngine(
            spec, params, n_stocks=K, lookback=T, n_features=F,
            buckets=CACHE_BUCKETS, mesh=m, program_cache=cache,
        )

    tel = TelemetryRun(tmp_path / "tel", run_id="fleet-chaos")
    fleet = FleetServer(
        {f"r{i}": factory_for(m) for i, m in enumerate(meshes)},
        telemetry=tel, max_wait_s=0.003,
        restart_policy=ReplicaRestartPolicy(backoff_s=0.01),
    )
    plan = FaultPlan(faults=[FaultSpec(
        point="serve.replica_dispatch", kind="raise", attempt=1,
        match={"replica": "r0"},
    )])
    rng = np.random.default_rng(0)
    fleet.start()
    try:
        faults.install_plan(plan)
        pend = [
            fleet.submit(
                rng.standard_normal((K, T, F)).astype(np.float32),
                deadline_s=3.0,
            )
            for _ in range(24)
        ]
        assert _wait_for(lambda: fleet.deaths >= 1, timeout=15.0)
        faults.clear_plan()
        results = [p.result(timeout=20.0) for p in pend]
        assert _wait_for(
            lambda: fleet.replicas["r0"].generation >= 2, timeout=15.0
        )
        assert _wait_for(
            lambda: fleet.replicas["r0"].state == STATE_LIVE, timeout=15.0
        )
        resurrected = fleet.replicas["r0"].engine
        # Drive the resurrected replica: post-restart traffic must land
        # on BOTH replicas (proof r0 is really back in rotation).
        assert _wait_for(
            lambda: (
                fleet.submit(
                    rng.standard_normal((K, T, F)).astype(np.float32),
                    deadline_s=3.0,
                ).result(timeout=10.0).ok
                and fleet.replicas["r0"].completed > 0
            ),
            timeout=15.0,
        )
    finally:
        stats = fleet.stop()
        tel.close()
        faults.clear_plan()

    # Zero silent drops, zero late answers, at least one explicit death.
    assert all(
        r.status in (STATUS_OK, STATUS_SHED, STATUS_REJECTED_LATE)
        for r in results
    )
    assert stats["late_deliveries"] == 0
    assert stats["deaths"] >= 1
    # The resurrection was warm: programs came from the shared cache.
    assert resurrected.compile_events == 0
    assert resurrected.cache_hits == len(CACHE_BUCKETS)

    # The trace stream shows the failover: a redispatched request span
    # and device spans on BOTH replicas.
    from masters_thesis_tpu.telemetry.report import resolve_events_path

    events = [
        json.loads(line)
        for line in Path(
            resolve_events_path(tmp_path / "tel")
        ).read_text().splitlines()
        if line.strip()
    ]
    spans = [e for e in events if e.get("kind") == "span"]
    hops = [
        s for s in spans
        if (s.get("attrs") or {}).get("redispatched_from") == "r0"
    ]
    device_replicas = {
        (s.get("attrs") or {}).get("replica")
        for s in spans if s.get("name") == "serve.device"
    }
    redispatch_events = [
        e for e in events if e.get("kind") == "redispatch"
    ]
    assert hops or redispatch_events
    assert {"r0", "r1"} <= device_replicas


def test_preflight_sv305_sv306_clean():
    from masters_thesis_tpu.serve.preflight import (
        run_fleet_preflight,
        run_program_cache_preflight,
    )

    assert run_program_cache_preflight() == []
    assert run_fleet_preflight() == []


# ------------------------------------------------------- ledger + report


def _ledger_row(round_id, point, **extra):
    from masters_thesis_tpu.telemetry.ledger import ledger_record

    return ledger_record(
        point=point, round_id=round_id, platform="cpu",
        steps_per_sec=None, objective="mse", rev="test", **extra,
    )


def test_ledger_gates_knee_qps_drop():
    from masters_thesis_tpu.telemetry.ledger import ledger_diff

    rows = [
        _ledger_row("r1", "serve/knee_qps", knee_qps=100.0),
        _ledger_row("r2", "serve/knee_qps", knee_qps=50.0),
    ]
    report = ledger_diff(rows)
    assert report["regressed"]
    assert report["regressions"][0]["regressed_metrics"] == ["knee_qps"]

    rows_up = [
        _ledger_row("r1", "serve/knee_qps", knee_qps=100.0),
        _ledger_row("r2", "serve/knee_qps", knee_qps=120.0),
    ]
    assert not ledger_diff(rows_up)["regressed"]


def test_ledger_gates_restart_time_rise():
    from masters_thesis_tpu.telemetry.ledger import ledger_diff

    worse = [
        _ledger_row("r1", "serve/restart_s", restart_s=1.0),
        _ledger_row("r2", "serve/restart_s", restart_s=2.0),
    ]
    report = ledger_diff(worse)
    assert report["regressed"]
    assert report["regressions"][0]["regressed_metrics"] == ["restart_s"]

    better = [
        _ledger_row("r1", "serve/restart_s", restart_s=2.0),
        _ledger_row("r2", "serve/restart_s", restart_s=1.0),
    ]
    assert not ledger_diff(better)["regressed"]


def test_ledger_render_shows_serving_metrics():
    from masters_thesis_tpu.telemetry.ledger import (
        ledger_diff,
        render_ledger_text,
    )

    rows = [
        _ledger_row("r1", "serve/knee_qps", knee_qps=100.0),
        _ledger_row("r1", "serve/restart_s", restart_s=0.5),
        _ledger_row("r2", "serve/knee_qps", knee_qps=99.0),
        _ledger_row("r2", "serve/restart_s", restart_s=0.51),
    ]
    report = ledger_diff(rows)
    report["path"] = "x"
    text = render_ledger_text(report)
    assert "knee 99.0 vs 100.0" in text
    assert "restart 0.510 vs 0.500" in text
    assert not report["regressed"]


def test_report_fleet_section_and_contracts():
    from masters_thesis_tpu.telemetry.report import summarize_events

    ok_events = [
        {"kind": "fleet_started", "replicas": ["r0", "r1"]},
        {"kind": "replica_started", "replica": "r0", "restart": False,
         "compile_events": 2, "cache_hits": 0},
        {"kind": "replica_dead", "replica": "r0", "cause": "crash"},
        {"kind": "replica_started", "replica": "r0", "restart": True,
         "compile_events": 0, "cache_hits": 2},
        {"kind": "cache_hit", "key": "k"},
        {"kind": "fleet_finished", "replicas": {
            "r0": {"state": "draining", "utilization": 0.4},
            "r1": {"state": "draining", "utilization": 0.5}},
         "n_live": 0, "deaths": 1, "late_deliveries": 0,
         "redispatched": 3},
    ]
    report = summarize_events(ok_events)
    fleet = report["fleet"]
    assert fleet["deaths"] == 1
    assert fleet["restarts"] == 1
    assert fleet["redispatched"] == 3
    assert fleet["cache"]["hits"] == 1
    assert not any(v.startswith("fleet:") for v in report["violations"])

    # Every replica dead/halted at a clean stop is a contract violation
    # (draining is the normal shutdown state, not a loss).
    dead_events = [
        {"kind": "fleet_finished", "replicas": {
            "r0": {"state": "dead"}, "r1": {"state": "dead"}},
         "n_live": 0, "deaths": 2, "late_deliveries": 0},
    ]
    violations = summarize_events(dead_events)["violations"]
    assert any("ZERO live replicas" in v for v in violations)

    # A restart that compiled despite an active cache is a violation.
    cold_restart = [
        {"kind": "replica_started", "replica": "r0", "restart": True,
         "compile_events": 2, "cache_hits": 1},
        {"kind": "fleet_finished", "replicas": {
            "r0": {"state": "draining"}}, "n_live": 0,
         "deaths": 1, "late_deliveries": 0},
    ]
    violations = summarize_events(cold_restart)["violations"]
    assert any("exported-program cache" in v for v in violations)
