"""Unit tests for bench.py's mid-measurement watchdog.

The device probe only guards backend INIT; the relay can also wedge
mid-measurement and hang the bench forever with no JSON line printed
(the driver's one recorded artifact). `_measure_point` runs every
TPU-touching section in a watchdog subprocess so a hang costs that
section, never the line."""

import importlib.util
import json
import subprocess
import types
from pathlib import Path

import pytest

_REPO_ROOT = Path(__file__).resolve().parent.parent


@pytest.fixture()
def bench():
    spec = importlib.util.spec_from_file_location(
        "_bench", _REPO_ROOT / "bench.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_measure_point_returns_payload(bench, monkeypatch):
    payload = {"steps_per_sec": 123.4, "platform": "tpu",
               "windows_per_epoch": 777}

    def fake_run(cmd, **kwargs):
        assert "--point" in cmd
        return types.SimpleNamespace(
            returncode=0, stdout=json.dumps(payload) + "\n", stderr=""
        )

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    assert bench._measure_point("mse", 1, 8, 60.0) == payload


def test_measure_point_none_on_hang(bench, monkeypatch, capsys):
    def hang(cmd, **kwargs):
        raise subprocess.TimeoutExpired(cmd, kwargs.get("timeout"))

    monkeypatch.setattr(bench.subprocess, "run", hang)
    assert bench._measure_point("mse", 1, 8, 60.0) is None
    assert "wedge" in capsys.readouterr().err


def test_measure_point_none_on_crash(bench, monkeypatch, capsys):
    monkeypatch.setattr(
        bench.subprocess, "run",
        lambda cmd, **k: types.SimpleNamespace(
            returncode=1, stdout="", stderr="boom"
        ),
    )
    assert bench._measure_point("nll", 1, 4, 60.0) is None
    assert "boom" in capsys.readouterr().err


def test_measure_point_none_on_garbage_stdout(bench, monkeypatch, capsys):
    monkeypatch.setattr(
        bench.subprocess, "run",
        lambda cmd, **k: types.SimpleNamespace(
            returncode=0, stdout="not json", stderr=""
        ),
    )
    assert bench._measure_point("mse", 8, 4, 60.0) is None
    assert "no JSON" in capsys.readouterr().err


def _tpu_line(value: float) -> str:
    return json.dumps(
        {"value": value, "detail": {"device": "tpu"}}
    )


def test_carry_prefers_the_live_cache(bench, tmp_path):
    cache = tmp_path / "cache.json"
    cache.write_text(json.dumps({"measured_at": "t", "value": 5306.0}))
    (tmp_path / "bench_r4_tpu.json").write_text(_tpu_line(1.0))
    carried = bench._carry_last_tpu(cache, tmp_path)
    assert carried["value"] == 5306.0


def test_carry_falls_back_to_committed_artifacts(bench, tmp_path):
    # No cache (environment reset wiped data/): the newest committed
    # healthy-TPU artifact is carried, labeled with its source.
    (tmp_path / "bench_r4_tpu.json").write_text(_tpu_line(5306.0))
    carried = bench._carry_last_tpu(tmp_path / "missing.json", tmp_path)
    assert carried["carried_from"] == "results/bench_r4_tpu.json"
    assert carried["value"] == 5306.0
    # A newer round's artifact wins when present.
    (tmp_path / "bench_r5_tpu.json").write_text(_tpu_line(6000.0))
    carried = bench._carry_last_tpu(tmp_path / "missing.json", tmp_path)
    assert carried["carried_from"] == "results/bench_r5_tpu.json"


def test_carry_skips_degraded_and_corrupt_artifacts(bench, tmp_path):
    # A CPU-fallback line (device != tpu), a torn file, and parseable
    # non-dict JSON ('null') are all skipped without an exception — the
    # one-JSON-line invariant survives any artifact content.
    (tmp_path / "bench_r6_tpu.json").write_text("null")
    (tmp_path / "bench_r5_tpu.json").write_text(
        json.dumps({"value": 13.8, "detail": {"device": "cpu"}})
    )
    (tmp_path / "bench_r4_tpu.json").write_text("{torn")
    assert bench._carry_last_tpu(tmp_path / "missing.json", tmp_path) is None


def test_carry_discovers_future_round_artifacts(bench, tmp_path):
    # Next round's artifact (r10, numerically > r9) wins without bench.py
    # edits, and a non-dict cache falls through to the artifacts.
    (tmp_path / "cache.json").write_text("null")
    (tmp_path / "bench_r9_tpu.json").write_text(_tpu_line(1.0))
    (tmp_path / "bench_r10_tpu.json").write_text(_tpu_line(2.0))
    carried = bench._carry_last_tpu(tmp_path / "cache.json", tmp_path)
    assert carried["carried_from"] == "results/bench_r10_tpu.json"
