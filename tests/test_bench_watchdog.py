"""Unit tests for bench.py's mid-measurement watchdog.

The device probe only guards backend INIT; the relay can also wedge
mid-measurement and hang the bench forever with no JSON line printed
(the driver's one recorded artifact). `_measure_point` runs every
TPU-touching section in a watchdog subprocess so a hang costs that
section, never the line."""

import importlib.util
import json
import subprocess
from pathlib import Path

import pytest

_REPO_ROOT = Path(__file__).resolve().parent.parent


@pytest.fixture()
def bench():
    spec = importlib.util.spec_from_file_location(
        "_bench", _REPO_ROOT / "bench.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class FakePopen:
    """Stand-in for the watchdog child: scripted communicate() behaviour.

    ``hang_until_kill=False`` hangs the first communicate (the watchdog
    timeout) but exits within the SIGTERM grace; ``True`` only dies at
    SIGKILL — distinguishing a child whose flight recorder dumped from one
    too wedged to die.
    """

    def __init__(self, returncode=0, stdout="", stderr="",
                 hang=False, hang_until_kill=False):
        self.returncode = returncode
        self._stdout = stdout
        self._stderr = stderr
        self._hang = hang
        self._hang_until_kill = hang_until_kill
        self.terminated = False
        self.killed = False

    def communicate(self, timeout=None):
        if self._hang and not self.terminated and not self.killed:
            raise subprocess.TimeoutExpired("bench --point", timeout)
        if self._hang_until_kill and not self.killed:
            raise subprocess.TimeoutExpired("bench --point", timeout)
        return self._stdout, self._stderr

    def terminate(self):
        self.terminated = True
        self.returncode = -15

    def kill(self):
        self.killed = True
        self.returncode = -9


def _patch_popen(monkeypatch, bench, proc: FakePopen) -> None:
    def fake_popen(cmd, **kwargs):
        assert "--point" in cmd
        return proc

    monkeypatch.setattr(bench.subprocess, "Popen", fake_popen)


def test_measure_point_returns_payload(bench, monkeypatch):
    payload = {"steps_per_sec": 123.4, "platform": "tpu",
               "windows_per_epoch": 777}
    _patch_popen(
        monkeypatch, bench, FakePopen(stdout=json.dumps(payload) + "\n")
    )
    assert bench._measure_point("mse", 1, 8, 60.0) == payload


def test_measure_point_sigterm_grace_on_hang(bench, monkeypatch, capsys):
    # A hung child is SIGTERMed first (the flight recorder's chance to
    # dump crashdump.json) and reported as a failure record with its
    # output tail — not silently dropped.
    proc = FakePopen(stdout="", stderr="stuck in dispatch", hang=True)
    _patch_popen(monkeypatch, bench, proc)
    record = bench._measure_point("mse", 1, 8, 60.0)
    assert proc.terminated and not proc.killed
    assert record["failed"] and "hung" in record["reason"]
    assert "stuck in dispatch" in record["tail"]
    assert not bench._point_ok(record)
    assert "wedge" in capsys.readouterr().err


def test_measure_point_sigkill_after_grace(bench, monkeypatch):
    # Too wedged to die on SIGTERM: escalate to SIGKILL, still return a
    # failure record.
    proc = FakePopen(hang=True, hang_until_kill=True)
    _patch_popen(monkeypatch, bench, proc)
    record = bench._measure_point("mse", 1, 8, 60.0)
    assert proc.terminated and proc.killed
    assert record["failed"] and "hung" in record["reason"]


def test_measure_point_failure_record_on_crash(bench, monkeypatch, capsys):
    _patch_popen(
        monkeypatch, bench, FakePopen(returncode=1, stderr="boom")
    )
    record = bench._measure_point("nll", 1, 4, 60.0)
    assert record["failed"] and record["reason"] == "crashed"
    assert record["rc"] == 1 and "boom" in record["tail"]
    assert "boom" in capsys.readouterr().err


def test_measure_point_failure_record_on_garbage_stdout(
    bench, monkeypatch, capsys
):
    _patch_popen(monkeypatch, bench, FakePopen(stdout="not json"))
    record = bench._measure_point("mse", 8, 4, 60.0)
    assert record["failed"] and "no JSON" in record["reason"]
    assert "no JSON" in capsys.readouterr().err


def test_failure_record_carries_crashdump_path(bench, monkeypatch, tmp_path):
    # When the SIGTERMed child's flight recorder got a dump out, the
    # failure record points at it (the postmortem entry point).
    monkeypatch.setenv("MTT_TELEMETRY_DIR", str(tmp_path))
    crash_dir = tmp_path / "point_mse_bs1"
    crash_dir.mkdir(parents=True)
    (crash_dir / "crashdump.json").write_text("{}")
    _patch_popen(
        monkeypatch, bench, FakePopen(returncode=-15, hang=True)
    )
    record = bench._measure_point("mse", 1, 8, 60.0)
    assert record["crashdump"] == str(crash_dir / "crashdump.json")


def _tpu_line(value: float) -> str:
    return json.dumps(
        {"value": value, "detail": {"device": "tpu"}}
    )


def test_carry_prefers_the_live_cache(bench, tmp_path):
    cache = tmp_path / "cache.json"
    cache.write_text(json.dumps({"measured_at": "t", "value": 5306.0}))
    (tmp_path / "bench_r4_tpu.json").write_text(_tpu_line(1.0))
    carried = bench._carry_last_tpu(cache, tmp_path)
    assert carried["value"] == 5306.0


def test_carry_falls_back_to_committed_artifacts(bench, tmp_path):
    # No cache (environment reset wiped data/): the newest committed
    # healthy-TPU artifact is carried, labeled with its source.
    (tmp_path / "bench_r4_tpu.json").write_text(_tpu_line(5306.0))
    carried = bench._carry_last_tpu(tmp_path / "missing.json", tmp_path)
    assert carried["carried_from"] == "results/bench_r4_tpu.json"
    assert carried["value"] == 5306.0
    # A newer round's artifact wins when present.
    (tmp_path / "bench_r5_tpu.json").write_text(_tpu_line(6000.0))
    carried = bench._carry_last_tpu(tmp_path / "missing.json", tmp_path)
    assert carried["carried_from"] == "results/bench_r5_tpu.json"


def test_carry_skips_degraded_and_corrupt_artifacts(bench, tmp_path):
    # A CPU-fallback line (device != tpu), a torn file, and parseable
    # non-dict JSON ('null') are all skipped without an exception — the
    # one-JSON-line invariant survives any artifact content.
    (tmp_path / "bench_r6_tpu.json").write_text("null")
    (tmp_path / "bench_r5_tpu.json").write_text(
        json.dumps({"value": 13.8, "detail": {"device": "cpu"}})
    )
    (tmp_path / "bench_r4_tpu.json").write_text("{torn")
    assert bench._carry_last_tpu(tmp_path / "missing.json", tmp_path) is None


def test_carry_discovers_future_round_artifacts(bench, tmp_path):
    # Next round's artifact (r10, numerically > r9) wins without bench.py
    # edits, and a non-dict cache falls through to the artifacts.
    (tmp_path / "cache.json").write_text("null")
    (tmp_path / "bench_r9_tpu.json").write_text(_tpu_line(1.0))
    (tmp_path / "bench_r10_tpu.json").write_text(_tpu_line(2.0))
    carried = bench._carry_last_tpu(tmp_path / "cache.json", tmp_path)
    assert carried["carried_from"] == "results/bench_r10_tpu.json"
