"""Unit tests for bench.py's mid-measurement watchdog.

The device probe only guards backend INIT; the relay can also wedge
mid-measurement and hang the bench forever with no JSON line printed
(the driver's one recorded artifact). `_measure_point` runs every
TPU-touching section in a watchdog subprocess so a hang costs that
section, never the line."""

import importlib.util
import json
import subprocess
import types
from pathlib import Path

import pytest

_REPO_ROOT = Path(__file__).resolve().parent.parent


@pytest.fixture()
def bench():
    spec = importlib.util.spec_from_file_location(
        "_bench", _REPO_ROOT / "bench.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_measure_point_returns_payload(bench, monkeypatch):
    payload = {"steps_per_sec": 123.4, "platform": "tpu",
               "windows_per_epoch": 777}

    def fake_run(cmd, **kwargs):
        assert "--point" in cmd
        return types.SimpleNamespace(
            returncode=0, stdout=json.dumps(payload) + "\n", stderr=""
        )

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    assert bench._measure_point("mse", 1, 8, 60.0) == payload


def test_measure_point_none_on_hang(bench, monkeypatch, capsys):
    def hang(cmd, **kwargs):
        raise subprocess.TimeoutExpired(cmd, kwargs.get("timeout"))

    monkeypatch.setattr(bench.subprocess, "run", hang)
    assert bench._measure_point("mse", 1, 8, 60.0) is None
    assert "wedge" in capsys.readouterr().err


def test_measure_point_none_on_crash(bench, monkeypatch, capsys):
    monkeypatch.setattr(
        bench.subprocess, "run",
        lambda cmd, **k: types.SimpleNamespace(
            returncode=1, stdout="", stderr="boom"
        ),
    )
    assert bench._measure_point("nll", 1, 4, 60.0) is None
    assert "boom" in capsys.readouterr().err


def test_measure_point_none_on_garbage_stdout(bench, monkeypatch, capsys):
    monkeypatch.setattr(
        bench.subprocess, "run",
        lambda cmd, **k: types.SimpleNamespace(
            returncode=0, stdout="not json", stderr=""
        ),
    )
    assert bench._measure_point("mse", 8, 4, 60.0) is None
    assert "no JSON" in capsys.readouterr().err
