"""End-to-end CLI driver tests: train.py -> checkpoint -> test.py figures.

The reference's drivers are only ever exercised by hand (SURVEY.md §4); here
the full CLI surface — config composition, bootstrap, training, checkpoint
layout, eval driver, figure/delta-loss output — runs in-process on the
virtual CPU platform.
"""

import importlib.util
import sys
from pathlib import Path

import pytest
from tensorboard.backend.event_processing.event_accumulator import (
    EventAccumulator,
)

_REPO_ROOT = Path(__file__).resolve().parent.parent


def _load_driver(name: str):
    """Import a repo-root driver script by file path.

    A plain ``import test``/``import train`` only works when the repo root
    happens to lead sys.path (and ``test`` collides with CPython's stdlib
    test package); loading by location is entry-point-independent.
    """
    if str(_REPO_ROOT) not in sys.path:
        # test.py itself does `from train import ...` — the root must be
        # importable for the drivers' own cross-imports.
        sys.path.insert(0, str(_REPO_ROOT))
    spec = importlib.util.spec_from_file_location(
        f"_driver_{name}", _REPO_ROOT / f"{name}.py"
    )
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


train_mod = _load_driver("train")
test_mod = _load_driver("test")


@pytest.fixture(scope="module")
def cli_run(tmp_path_factory):
    """One trained CLI run; each test inspects its artifacts independently."""
    root = tmp_path_factory.mktemp("cli")
    overrides = [
        "trainer=fast",
        "trainer.max_epochs=2",
        "trainer.enable_progress_bar=false",
        "trainer.enable_model_summary=false",
        "model.hidden_size=8",
        "model.num_layers=1",
        "datamodule.n_samples=20000",
        "datamodule.n_stocks=6",
        f"datamodule.data_dir={root}/data",
        f"logger.save_dir={root}/logs",
        "logger.version=cli_test",
    ]
    train_mod.main(overrides)
    return root, overrides


def test_train_cli_end_to_end(cli_run):
    root, _ = cli_run
    version_dir = root / "logs" / "FinancialLstm" / "synthetic" / "cli_test"
    assert (version_dir / "checkpoints" / "best").exists()
    assert (version_dir / "checkpoints" / "last.json").exists()
    assert list(version_dir.glob("events.out.tfevents.*"))


@pytest.mark.slow
def test_eval_cli_renders_figures_and_deltas(cli_run, capsys):
    root, overrides = cli_run
    ckpt = root / "logs" / "FinancialLstm" / "synthetic" / "cli_test"
    ckpt = ckpt / "checkpoints" / "best"

    test_mod.main(overrides + [f"checkpoint={ckpt}"])
    out = capsys.readouterr().out
    assert "dL_MSE" in out and "dL_MIX" in out

    version_dir = ckpt.parent.parent
    acc = EventAccumulator(str(version_dir), size_guidance={"images": 0})
    acc.Reload()
    image_tags = acc.Tags()["images"]
    for tag in ("scatter/alphas", "hist/betas", "estimation/alpha"):
        assert tag in image_tags, f"missing figure {tag}"
    scalar_tags = acc.Tags()["scalars"]
    assert "delta/model/mix" in scalar_tags
    assert "delta/ols/mix" in scalar_tags


def _write_real_raw_fixtures(raw_dir, n_days=420, seed=0):
    """Reference-format Ken French CSVs (preambles, quoted p25 header, RF
    column, percent returns) with a noisy single-factor DGP, including one
    sentinel day with NONZERO RF inside the surviving region — the exact
    edge case the loader's raw-value masking handles
    (data/fama_french.py:72-79; reference: src/data.py:112-115)."""
    from masters_thesis_tpu.data import FamaFrench25Portfolios as FF

    rng = __import__("numpy").random.default_rng(seed)
    np = __import__("numpy")
    n_rows = FF.skip_old_data + n_days
    sentinel_day = FF.skip_old_data + n_days // 2
    betas = rng.uniform(0.5, 1.5, 25)
    alphas = rng.normal(0.0, 0.01, 25)
    ff3_lines = ["preamble"] * FF.ff3_skip + [",".join(FF.ff3_cols)]
    p25_lines = ["preamble"] * FF.p25_skip + [
        ",".join(f'"{c}"' for c in FF.p25_cols)
    ]
    for i in range(n_rows):
        date = 19260700 + i
        mkt = rng.normal(0.03, 1.0)
        rf = 0.002 + 0.001 * rng.random()  # always nonzero
        ff3_lines.append(f"{date},{mkt:.4f},0.0,0.0,{rf:.4f}")
        if i == sentinel_day:
            vals = ["-99.99"] * 25
        else:
            port = alphas + betas * mkt + rng.normal(0.0, 0.3, 25) + rf
            vals = [f"{v:.4f}" for v in port]
        p25_lines.append(f"{date}," + ",".join(vals))
    raw_dir.mkdir(parents=True, exist_ok=True)
    (raw_dir / FF.ff3_filename).write_text("\n".join(ff3_lines) + "\n")
    (raw_dir / FF.p25_filename).write_text("\n".join(p25_lines) + "\n")


@pytest.mark.slow
def test_real_datamodule_cli_end_to_end(tmp_path, capsys):
    """`train.py datamodule=real` -> `test.py` through the CLI on
    reference-format fixture CSVs: bootstrap (CSV -> arrays), training,
    checkpoint, eval figures and ΔL all land (reference: test.py:199-207
    exercises the real datamodule end to end)."""
    _write_real_raw_fixtures(tmp_path / "raw")
    overrides = [
        "datamodule=real",
        f"datamodule.raw_dir={tmp_path}/raw",
        f"datamodule.data_dir={tmp_path}/data",
        "trainer=fast",
        "trainer.max_epochs=2",
        "trainer.enable_progress_bar=false",
        "trainer.enable_model_summary=false",
        "model.hidden_size=8",
        "model.num_layers=1",
        f"logger.save_dir={tmp_path}/logs",
        "logger.version=cli_real",
    ]
    train_mod.main(overrides)
    version_dir = tmp_path / "logs" / "FinancialLstm" / "real" / "cli_real"
    ckpt = version_dir / "checkpoints" / "best"
    assert ckpt.exists()

    test_mod.main(overrides + [f"checkpoint={ckpt}"])
    out = capsys.readouterr().out
    assert "dL_MSE" in out and "dL_MIX" in out
    acc = EventAccumulator(str(version_dir), size_guidance={"images": 0})
    acc.Reload()
    image_tags = acc.Tags()["images"]
    for tag in ("scatter/alphas", "hist/betas", "estimation/alpha"):
        assert tag in image_tags, f"missing figure {tag}"
    assert "delta/model/mix" in acc.Tags()["scalars"]


def test_real_datamodule_cli_missing_csvs_exits_cleanly(tmp_path, capsys):
    """Without the raw CSVs the driver must explain the manual download
    instead of crashing (reference: train.py:19-22 documents the manual
    step)."""
    result = train_mod._run_job(
        str(_REPO_ROOT / "configs"),
        [
            "datamodule=real",
            f"datamodule.raw_dir={tmp_path}/raw",
            f"datamodule.data_dir={tmp_path}/data",
            f"logger.save_dir={tmp_path}/logs",
        ],
    )
    assert result == float("inf")  # sweep objective: worst possible
    assert "CSVs not found" in capsys.readouterr().err


def test_eval_cli_without_checkpoint_exits_cleanly(cli_run, capsys):
    root, overrides = cli_run
    test_mod.main(overrides)  # checkpoint stays null
    assert "No model checkpoint found" in capsys.readouterr().err


@pytest.mark.slow
def test_sigkill_mid_training_then_cli_resume(tmp_path):
    """Elastic recovery, for real: SIGKILL a training PROCESS mid-run, then
    re-invoke the same CLI command with trainer.resume=true and finish.
    (The in-process resume tests simulate the crash; this one doesn't.)"""
    import os
    import signal
    import subprocess
    import sys
    import time

    env = os.environ.copy()
    env.pop("PALLAS_AXON_POOL_IPS", None)  # hermetic from the TPU relay
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(_REPO_ROOT)
    base = [
        sys.executable, str(_REPO_ROOT / "train.py"),
        "trainer=fast",
        "trainer.enable_progress_bar=false",
        "trainer.enable_model_summary=false",
        "trainer.resume=true",
        "model.hidden_size=8",
        "model.num_layers=1",
        "datamodule.n_samples=20000",
        "datamodule.n_stocks=6",
        f"datamodule.data_dir={tmp_path}/data",
        f"logger.save_dir={tmp_path}/logs",
        "logger.version=crashy",
    ]
    last_json = (
        tmp_path / "logs" / "FinancialLstm" / "synthetic" / "crashy"
        / "checkpoints" / "last.json"
    )
    # Run 1: enough epochs that it cannot finish before we kill it.
    p = subprocess.Popen(
        base + ["trainer.max_epochs=500"], env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    deadline = time.time() + 300
    try:
        while not last_json.exists():
            assert p.poll() is None, "run finished before a checkpoint?!"
            assert time.time() < deadline, "no checkpoint within 300s"
            time.sleep(0.5)
    finally:
        if p.poll() is None:
            p.send_signal(signal.SIGKILL)
            p.wait(timeout=60)

    import json

    # Recover any save interrupted by the kill BEFORE reading the epoch:
    # last.json can be one epoch stale if the SIGKILL landed inside the
    # publish window (a staged pair awaiting its swap).
    from masters_thesis_tpu.train.checkpoint import checkpoint_restorable

    assert checkpoint_restorable(last_json.parent, "last")
    crashed_epoch = json.loads(last_json.read_text())["meta"]["epoch"]
    # Run 2: resume and run a couple more epochs to completion. The
    # progress bar goes back on so the "resuming from" line is observable
    # (a from-scratch run would also end at max_epochs-1, so the epoch
    # assert alone can't distinguish resume from restart).
    done = subprocess.run(
        base + [
            f"trainer.max_epochs={crashed_epoch + 3}",
            "trainer.enable_progress_bar=true",
        ],
        env=env, capture_output=True, text=True, timeout=600,
    )
    assert done.returncode == 0, done.stderr[-1500:]
    assert "resuming from" in done.stdout
    # Resumed at the right epoch: the first epoch it trains is crashed+1.
    assert f"epoch {crashed_epoch + 1:4d}" in done.stdout
    final = json.loads(last_json.read_text())
    # trained the remaining epochs
    assert final["meta"]["epoch"] == crashed_epoch + 2


def test_warmup_checkpoint_keeps_config_objective(tmp_path):
    """checkpoint_mode=params must fine-tune under the CONFIG's objective,
    not the pretrain checkpoint's: the thesis warmup protocol fine-tunes a
    combined-pretrained model under each of the three losses
    (sweeps/experiment_warmup.sh; reference: tex/diplomski_rad.tex:1134-1147).
    Regression: run() used to rebind the spec from the restored checkpoint,
    silently training the pretrain objective for every fine-tune."""
    base = [
        "trainer=fast",
        "trainer.max_epochs=1",
        "trainer.enable_progress_bar=false",
        "trainer.enable_model_summary=false",
        "model.hidden_size=8",
        "model.num_layers=1",
        "datamodule.n_samples=8000",
        "datamodule.n_stocks=4",
        f"datamodule.data_dir={tmp_path}/data",
        f"logger.save_dir={tmp_path}/logs",
    ]
    train_mod._run_job(
        str(_REPO_ROOT / "configs"),
        base + ["loss=combined", "logger.version=pre"],
    )
    pre = (
        tmp_path / "logs" / "FinancialLstm" / "synthetic" / "pre"
        / "checkpoints" / "best"
    )
    assert pre.exists()
    train_mod._run_job(
        str(_REPO_ROOT / "configs"),
        base + [
            "loss=nll", f"checkpoint={pre}", "checkpoint_mode=params",
            "logger.version=warm",
        ],
    )
    from masters_thesis_tpu.train.checkpoint import restore_checkpoint

    warm = (
        tmp_path / "logs" / "FinancialLstm" / "synthetic" / "warm"
        / "checkpoints"
    )
    _, _, spec, _ = restore_checkpoint(warm, "last")
    assert spec.objective == "nll"

    # And a mismatched architecture must fail loudly, not load garbage.
    with pytest.raises(ValueError, match="matching architecture"):
        train_mod._run_job(
            str(_REPO_ROOT / "configs"),
            base + [
                "loss=nll", "model.hidden_size=4",
                f"checkpoint={pre}", "checkpoint_mode=params",
                "logger.version=warm_bad",
            ],
        )


def test_multirun_numbered_job_dirs(tmp_path, capsys, monkeypatch):
    """With a relative logger.save_dir, every sweep point writes into a
    numbered Hydra-style job dir <sweep_dir>/<job_idx>/ carrying .hydra
    metadata (config.yaml + overrides.yaml), logs, and checkpoints —
    the layout a Hydra user expects from `python train.py -m ...`
    (reference: configs/config.yaml:6,17-19)."""
    monkeypatch.chdir(tmp_path)
    overrides = [
        "trainer=fast",
        "trainer.max_epochs=1",
        "trainer.enable_progress_bar=false",
        "trainer.enable_model_summary=false",
        "model.hidden_size=4,8",  # 2 sweep points
        "model.num_layers=1",
        "datamodule.n_samples=8000",
        "datamodule.n_stocks=4",
        f"datamodule.data_dir={tmp_path}/data",
        "logger.save_dir=logs",
        "launcher.sweep_dir=sweep",
    ]
    train_mod.main(["-m"] + overrides)
    for i, hidden in enumerate((4, 8)):
        job = tmp_path / "sweep" / str(i)
        assert (job / ".hydra" / "overrides.yaml").exists()
        import yaml

        cfg = yaml.safe_load((job / ".hydra" / "config.yaml").read_text())
        assert cfg["model"]["hidden_size"] == hidden
        versions = list(
            (job / "logs" / "FinancialLstm" / "synthetic").iterdir()
        )
        assert len(versions) == 1
        assert (versions[0] / "checkpoints" / "best").exists()


@pytest.mark.slow
def test_multirun_parallel_launcher_numbered_dirs(tmp_path, capsys, monkeypatch):
    """launcher=joblib worker processes also write the numbered Hydra-style
    job dirs when save_dir is relative (the sweep_dir plumbing survives
    cloudpickle into the pool)."""
    monkeypatch.delenv("PALLAS_AXON_POOL_IPS", raising=False)
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.chdir(tmp_path)
    train_mod.main([
        "-m",
        "trainer=fast",
        "trainer.max_epochs=1",
        "trainer.enable_progress_bar=false",
        "trainer.enable_model_summary=false",
        "model.hidden_size=4,8",
        "model.num_layers=1",
        "datamodule.n_samples=8000",
        "datamodule.n_stocks=4",
        f"datamodule.data_dir={tmp_path}/data",
        "logger.save_dir=logs",
        "launcher=joblib",
        "launcher.n_jobs=2",
        "launcher.sweep_dir=sweep",
    ])
    for i in (0, 1):
        job = tmp_path / "sweep" / str(i)
        assert (job / ".hydra" / "overrides.yaml").exists()
        versions = list(
            (job / "logs" / "FinancialLstm" / "synthetic").iterdir()
        )
        assert len(versions) == 1
        assert (versions[0] / "checkpoints" / "best").exists()


@pytest.mark.slow
def test_multirun_parallel_launcher(tmp_path, capsys, monkeypatch):
    """`-m` with launcher.n_jobs=2 runs each sweep point in its own worker
    process (the reference's joblib launcher semantics,
    configs/config.yaml:6,17-19)."""
    # Worker processes have no conftest: strip the ambient TPU-relay plugin
    # trigger so their inherited JAX_PLATFORMS=cpu actually takes effect
    # (and two workers never contend for the one relay session).
    monkeypatch.delenv("PALLAS_AXON_POOL_IPS", raising=False)
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    overrides = [
        "trainer=fast",
        "trainer.max_epochs=1",
        "trainer.enable_progress_bar=false",
        "trainer.enable_model_summary=false",
        "model.hidden_size=4,8",  # 2 sweep points
        "model.num_layers=1",
        "datamodule.n_samples=8000",
        "datamodule.n_stocks=4",
        f"datamodule.data_dir={tmp_path}/data",
        f"logger.save_dir={tmp_path}/logs",
        "launcher.n_jobs=2",
    ]
    train_mod.main(["-m"] + overrides)
    out = capsys.readouterr().out
    assert "multirun: 2 jobs, n_jobs=2" in out
    versions = list((tmp_path / "logs" / "FinancialLstm" / "synthetic").iterdir())
    assert len(versions) == 2
    for v in versions:
        assert (v / "checkpoints" / "best").exists()
