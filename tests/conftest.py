"""Test harness: force an 8-device virtual CPU platform BEFORE jax imports.

This is the fake-backend multi-chip story the reference lacks (SURVEY.md §4):
every test — including sharding/collective tests — runs against a simulated
8-device mesh on CPU, so distributed code paths are exercised without TPU
hardware.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

# Tests must be hermetic from the TPU: the ambient axon plugin
# (sitecustomize in /root/.axon_site) registers at interpreter boot and
# force-overrides the jax_platforms *config* to "axon,cpu" — so the env var
# above is not enough, and any dispatch would claim the TPU relay session
# (hanging every test run whenever the relay lease is wedged). Overriding the
# config again, before any backend initializes, keeps the axon backend
# registered-but-never-touched.
jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest

# NO persistent compilation cache here: executables deserialized from the
# cache on the forced multi-device host platform diverge numerically from
# fresh compiles (observed 0.7% on the 8-device shard_map epoch loss,
# jaxlib 0.4.x — a cached reload is not the same program; see the guard in
# masters_thesis_tpu/utils/compilation_cache.py). Warm restarts are not
# worth numerically-unsound tests.


@pytest.fixture
def rng():
    return np.random.default_rng(0)
