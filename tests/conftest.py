"""Test harness: force an 8-device virtual CPU platform BEFORE jax imports.

This is the fake-backend multi-chip story the reference lacks (SURVEY.md §4):
every test — including sharding/collective tests — runs against a simulated
8-device mesh on CPU, so distributed code paths are exercised without TPU
hardware.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

# Tests must be hermetic from the TPU: the ambient axon plugin
# (sitecustomize in /root/.axon_site) registers at interpreter boot and
# force-overrides the jax_platforms *config* to "axon,cpu" — so the env var
# above is not enough, and any dispatch would claim the TPU relay session
# (hanging every test run whenever the relay lease is wedged). Overriding the
# config again, before any backend initializes, keeps the axon backend
# registered-but-never-touched.
jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest

# Persistent compilation cache: repeated test runs skip XLA recompiles.
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)


@pytest.fixture
def rng():
    return np.random.default_rng(0)
