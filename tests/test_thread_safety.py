"""Regression tests for the races/lock hazards the Pass-3 concurrency
lint surfaced (CL502/CL504), pinned against the concrete fixes:

- ``FlightRecorder.dump`` used to take ``self._lock`` with a blocking
  acquire on the signal path — a handler interrupting ``record()``/
  ``note()`` mid-update would self-deadlock the process. Now bounded.
- ``EventSink.try_emit`` is the bounded-acquire twin of ``emit`` for
  handler paths; it must give up, not wait.
- ``CircuitBreaker`` counters and ``MicroBatchQueue.submitted``/``shed``
  were bare ``+=`` read-modify-writes reachable from multiple threads;
  under contention they lose updates. Now locked.

The deadlock tests are deterministic (they fail by timeout on the old
code). The counter tests are contention tests: with a tiny switch
interval and tens of thousands of increments, the old unlocked code
loses updates with overwhelming probability.
"""

from __future__ import annotations

import sys
import threading
import time

import numpy as np
import pytest

from masters_thesis_tpu.serve.queue import MicroBatchQueue, ServeRequest
from masters_thesis_tpu.telemetry.events import EventSink
from masters_thesis_tpu.telemetry.flightrec import FlightRecorder
from masters_thesis_tpu.utils.backend_probe import CircuitBreaker


@pytest.fixture
def tight_switching():
    old = sys.getswitchinterval()
    sys.setswitchinterval(1e-5)
    yield
    sys.setswitchinterval(old)


def test_dump_survives_held_state_lock(tmp_path):
    """A dump on the signal path must not block on the state lock.

    Old code: ``dump`` did ``with self._lock:`` — with the lock held by
    the interrupted frame, the worker below never finishes and the join
    times out.
    """
    rec = FlightRecorder(
        tmp_path,
        run_id="t",
        install_signal_handlers=False,
        enable_faulthandler=False,
    )
    try:
        rec.note(step="pretend-mid-update")
        result = {}
        rec._lock.acquire()
        try:
            t = threading.Thread(
                target=lambda: result.update(p=rec.dump("held-lock-test")),
                daemon=True,
            )
            t.start()
            t.join(timeout=5.0)
            assert not t.is_alive(), (
                "dump() blocked forever on a held state lock — the "
                "signal-path self-deadlock is back"
            )
        finally:
            rec._lock.release()
        # The dump still produced a crashdump (best-effort state copy).
        assert result["p"] is not None
        assert rec.crashdump_path.exists()
    finally:
        rec.close()


def test_try_emit_gives_up_when_lock_held(tmp_path):
    sink = EventSink(tmp_path / "events.jsonl", run_id="t")
    sink.emit("epoch", epoch=0)  # open the file under normal conditions
    sink._lock.acquire()
    try:
        t0 = time.monotonic()
        out = sink.try_emit("crashdump", timeout=0.05, reason="x")
        elapsed = time.monotonic() - t0
        assert out is None
        assert elapsed < 2.0
    finally:
        sink._lock.release()
    # And with the lock free it emits normally.
    ev = sink.try_emit("crashdump", reason="x")
    assert ev is not None and ev["kind"] == "crashdump"
    sink.close()


def test_breaker_concurrent_failures_lose_nothing(tight_switching):
    """4 threads x 25k failures with threshold=1: every failure trips.

    Old code: ``self.trips += 1`` was an unlocked read-modify-write;
    under a tiny switch interval the interleaved loads/stores drop
    increments and the total comes up short.
    """
    breaker = CircuitBreaker(threshold=1)
    n_threads, per_thread = 4, 25_000

    def hammer():
        for _ in range(per_thread):
            breaker.record_failure()

    threads = [threading.Thread(target=hammer) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert breaker.trips == n_threads * per_thread


def test_queue_submit_counter_exact_under_contention(tight_switching):
    """Concurrent submits must be counted exactly (was a bare +=)."""
    q = MicroBatchQueue(max_batch=8, max_wait_s=0.001, max_depth=1 << 30)
    n_threads, per_thread = 4, 2_000
    deadline = time.monotonic() + 3600.0
    x = np.zeros((1, 2, 3))

    def hammer(base):
        for i in range(per_thread):
            q.submit(ServeRequest(rid=base + i, x=x, deadline_ts=deadline))

    threads = [
        threading.Thread(target=hammer, args=(k * per_thread,))
        for k in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert q.submitted == n_threads * per_thread
    q.close()


def test_shed_counter_consistent_with_responses(tight_switching):
    """shed is bumped under the queue lock; every shed response is
    matched by exactly one count even when submits race."""
    q = MicroBatchQueue(max_batch=4, max_wait_s=0.001, max_depth=1)
    deadline = time.monotonic() + 3600.0
    x = np.zeros((1, 2, 3))
    n_threads, per_thread = 4, 500
    shed_responses = [0] * n_threads

    def hammer(k):
        for i in range(per_thread):
            p = q.submit(
                ServeRequest(rid=k * per_thread + i, x=x, deadline_ts=deadline)
            )
            if p.done and p.result(0).status == "shed":
                shed_responses[k] += 1

    threads = [
        threading.Thread(target=hammer, args=(k,)) for k in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert q.shed == sum(shed_responses)
    assert q.submitted == n_threads * per_thread
    q.close()
