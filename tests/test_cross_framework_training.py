"""Cross-framework TRAINING parity: torch vs this framework, step by step.

SURVEY.md §7 flags the hard part: if gate ordering, init, loss math, or
optimizer semantics drift from the reference's torch stack, loss curves
drift. test_models_lstm pins the FORWARD pass; this test pins the whole
training step — identical weights, identical window sequence, torch
Adam(weight_decay)+grad-clip vs the optax chain — and requires the per-step
loss trajectories to track each other to float32 tolerance.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

pytestmark = pytest.mark.slow  # epoch-scale torch-vs-jax fits, ~2 min

from masters_thesis_tpu.data.pipeline import Batch
from masters_thesis_tpu.models.objectives import ModelSpec
from masters_thesis_tpu.parallel import make_data_mesh
from masters_thesis_tpu.train.optim import make_optimizer
from masters_thesis_tpu.train.steps import make_train_step

torch = pytest.importorskip("torch")

HIDDEN = 8
K, LOOK, TGT = 6, 16, 8
LR, WD, CLIP = 1e-2, 1e-5, 5.0
N_STEPS = 20


# One torch re-statement of the reference stack, shared by the 20-step
# exact-trajectory test below and the epoch-scale harness.
from torch_reference_stack import (  # noqa: E402
    TorchReferenceStack,
    fit_reference,
    flax_params_from_torch,
)


def make_batches(rng, n_steps):
    """Fixed sequence of windows in the pipeline's Batch schema."""
    batches = []
    for _ in range(n_steps):
        x = rng.normal(0.1, 0.5, size=(1, K, LOOK, 3)).astype(np.float32)
        y = rng.normal(0.1, 0.5, size=(1, K, TGT, 4)).astype(np.float32)
        factor = rng.normal(size=(1, 2)).astype(np.float32)
        inv_psi = rng.uniform(1, 2, size=(1, K)).astype(np.float32)
        batches.append(Batch(x, y, factor, inv_psi))
    return batches


def torch_trajectory(model, batches):
    opt = torch.optim.Adam(model.parameters(), lr=LR, weight_decay=WD)
    losses = []
    for b in batches:
        # flatten(0,1) preamble (reference: src/model.py:193-194).
        x = torch.from_numpy(np.asarray(b.x)).flatten(0, 1)
        y = torch.from_numpy(np.asarray(b.y)).flatten(0, 1)
        alpha, beta = model(x)
        pred = alpha + beta * y[:, :, 1]
        loss = torch.nn.functional.mse_loss(pred, y[:, :, 0])
        opt.zero_grad()
        loss.backward()
        # Lightning clips raw grads before the step (reference:
        # train.py:172 gradient_clip_val).
        torch.nn.utils.clip_grad_norm_(model.parameters(), CLIP)
        opt.step()
        losses.append(float(loss.detach()))
    return losses


def framework_trajectory(params, batches):
    spec = ModelSpec(
        objective="mse", hidden_size=HIDDEN, num_layers=1, dropout=0.0,
        learning_rate=LR,
    )
    mesh = make_data_mesh(1)
    module = spec.build_module()
    tx = make_optimizer(CLIP, spec.weight_decay)
    opt_state = tx.init(params)
    step_fn = make_train_step(module, spec.window_objective(), tx, mesh)
    lr = jnp.float32(LR)
    rng = jax.random.key(0)  # dropout=0: rng is inert
    losses = []
    for b in batches:
        params, opt_state, sums = step_fn(params, opt_state, lr, rng, b)
        value, weight = jax.device_get(sums["total"])
        losses.append(float(value) / float(weight))
    return losses


def test_training_trajectories_match():
    torch.manual_seed(0)
    model = TorchReferenceStack(hidden_size=HIDDEN, num_layers=1, dropout=0.0)
    params = flax_params_from_torch(model)
    batches = make_batches(np.random.default_rng(7), N_STEPS)

    t_losses = torch_trajectory(model, batches)
    f_losses = framework_trajectory(params, batches)

    np.testing.assert_allclose(f_losses, t_losses, rtol=2e-4)
    # The trajectory must actually move (optimizer engaged on both sides).
    assert t_losses[-1] != pytest.approx(t_losses[0])


# --------------------------------------------------------------------------
# Epoch-scale loss-curve parity — the BASELINE.md north-star claim
# ("reproducing the experiment_synthetic.sh loss curves within 1%") as
# tests, against the faithful torch re-statement of the reference stack
# (tests/torch_reference_stack.py; reference: src/model.py:176-331,
# train.py:169-198). Two complementary experiments:
#
# 1. EXACT parity: dropout off, shuffle order MATCHED (the torch loop
#    consumes the framework's own stream-mode epoch iterator), so the two
#    stacks see identical optimization problems. Full multi-epoch
#    Trainer.fit — val cadence + ReduceLROnPlateau in the loop — must
#    reproduce torch's train/val curves within a fraction of the 1%
#    target, and make identical LR decisions.
#
# 2. DROPOUT-ACTIVE statistical parity: masks and shuffle order are
#    necessarily different RNG draws across frameworks (SURVEY.md §7), and
#    the same-framework noise floor (torch vs torch with different seeds)
#    is itself measured at 1.4-3.2% at this scale — so "within 1%" is not
#    a statistically meaningful bar for a single dropout-active run. The
#    honest assertion: the cross-framework curve gap must be
#    indistinguishable from same-framework RNG noise (<= 1.5x the measured
#    torch-vs-torch envelope, and never worse than 1% + envelope).
# --------------------------------------------------------------------------

PARITY_EPOCHS = 8
PARITY_LR = 1e-3
PARITY_HIDDEN = 16  # the thesis' small hidden size (tex:1106-1122)


@pytest.fixture(scope="module")
def parity_dm(tmp_path_factory):
    from masters_thesis_tpu.data.pipeline import FinancialWindowDataModule
    from masters_thesis_tpu.data.synthetic import SyntheticLogReturns

    data_dir = tmp_path_factory.mktemp("parity_data")
    r_stocks, r_market, alphas, betas = SyntheticLogReturns.generate(
        n_stocks=8, n_samples=6000, seed=11
    )
    np.save(data_dir / "stocks.npy", np.asarray(r_stocks))
    np.save(data_dir / "market.npy", np.asarray(r_market))
    np.save(data_dir / "alphas.npy", np.asarray(alphas))
    np.save(data_dir / "betas.npy", np.asarray(betas))
    dm = FinancialWindowDataModule(
        data_dir, lookback_window=16, target_window=8, stride=24, batch_size=1
    )
    dm.prepare_data(verbose=False)
    dm.setup()
    return dm


def _torch_model_and_params(dropout):
    torch.manual_seed(3)
    tmodel = TorchReferenceStack(
        hidden_size=PARITY_HIDDEN, num_layers=2, dropout=dropout
    )
    return tmodel, flax_params_from_torch(tmodel)


def _framework_fit(parity_dm, objective, params, *, dropout, epoch_mode,
                   seed=5, epochs=PARITY_EPOCHS):
    from masters_thesis_tpu.train import Trainer

    spec = ModelSpec(
        objective=objective,
        hidden_size=PARITY_HIDDEN,
        num_layers=2,
        dropout=dropout,
        learning_rate=PARITY_LR,
    )
    trainer = Trainer(
        max_epochs=epochs,
        gradient_clip_val=5.0,
        check_val_every_n_epoch=1,
        strategy="single_device",
        epoch_mode=epoch_mode,
        enable_progress_bar=False,
        enable_model_summary=False,
        seed=seed,
    )
    result = trainer.fit(spec, parity_dm, init_state=(params, None))
    return [
        {
            "train": row["loss/total/train"],
            "val": row["loss/total/val"],
            "lr": row["lr-Adam"],
        }
        for row in result.history
    ]


def _curve_gap(a, b, key):
    """Max per-epoch relative deviation between two histories."""
    xa = np.array([r[key] for r in a])
    xb = np.array([r[key] for r in b])
    return float(np.max(np.abs(xa - xb) / np.abs(xa)))


class TestEpochScaleLossCurveParity:
    @pytest.mark.parametrize("objective", ["mse", "nll", "combined"])
    def test_exact_curves_match(self, parity_dm, objective):
        """Matched shuffle, dropout off: the full fit loop (val cadence +
        plateau LR) reproduces the torch reference curves well inside the
        1% north-star envelope."""
        tmodel, params = _torch_model_and_params(dropout=0.0)
        # The torch loop consumes the framework's OWN epoch iterator
        # (stream mode shuffles host-side with seed (trainer.seed, epoch);
        # train_batches is that exact public contract at batch_size=1), so
        # both stacks step through identical window sequences.
        seed = 5
        t_hist = fit_reference(
            tmodel,
            parity_dm.train_arrays(),
            parity_dm.val_arrays(),
            objective,
            epochs=PARITY_EPOCHS,
            lr=PARITY_LR,
            epoch_batches=lambda epoch: parity_dm.train_batches(
                epoch=epoch, seed=seed
            ),
        )
        f_hist = _framework_fit(
            parity_dm, objective, params, dropout=0.0, epoch_mode="stream",
            seed=seed,
        )
        assert len(f_hist) == len(t_hist) == PARITY_EPOCHS
        t_train = [r["train"] for r in t_hist]
        np.testing.assert_allclose(
            [r["train"] for r in f_hist], t_train, rtol=1e-3
        )
        np.testing.assert_allclose(
            [r["val"] for r in f_hist], [r["val"] for r in t_hist], rtol=1e-3
        )
        # The run must actually optimize (not a flat-curve vacuous match).
        assert t_train[-1] < t_train[0]
        # Identical reduce-on-plateau decisions epoch by epoch.
        np.testing.assert_allclose(
            [r["lr"] for r in f_hist], [r["lr"] for r in t_hist], rtol=1e-12
        )

    @pytest.mark.parametrize("objective", ["mse", "nll", "combined"])
    def test_dropout_active_curves_within_rng_noise(self, parity_dm, objective):
        """Dropout ACTIVE: cross-framework curve gap must be no worse than
        same-framework RNG noise (torch vs torch, different mask/shuffle
        seeds), i.e. the frameworks are statistically indistinguishable."""
        import copy

        tmodel, params = _torch_model_and_params(dropout=0.2)
        replicas = [copy.deepcopy(tmodel) for _ in range(3)]
        tr, va = parity_dm.train_arrays(), parity_dm.val_arrays()
        t_hist = fit_reference(
            tmodel, tr, va, objective, epochs=PARITY_EPOCHS, lr=PARITY_LR,
            shuffle_seed=0,
        )
        # Same-framework noise envelope from independently-seeded torch
        # replicas of the identical run. An n-run max-pairwise-gap
        # UNDERSTATES the spread a fresh sample can show (order
        # statistics); 4 runs = 6 pairwise gaps tighten that estimate vs
        # the 1.5x headroom below.
        t_replica_hists = []
        for i, m in enumerate(replicas):
            torch.manual_seed(100 + i)
            t_replica_hists.append(
                fit_reference(
                    m, tr, va, objective, epochs=PARITY_EPOCHS, lr=PARITY_LR,
                    shuffle_seed=1 + i,
                )
            )
        f_hist = _framework_fit(
            parity_dm, objective, params, dropout=0.2, epoch_mode="scan",
        )
        assert len(f_hist) == len(t_hist) == PARITY_EPOCHS
        torch_runs = [t_hist] + t_replica_hists
        for key in ("train", "val"):
            envelope = max(
                _curve_gap(a, b, key)
                for i, a in enumerate(torch_runs)
                for b in torch_runs[i + 1:]
            )
            gap = max(_curve_gap(t, f_hist, key) for t in torch_runs)
            assert gap <= max(1.5 * envelope, 0.01 + envelope), (
                f"{key} curve gap {gap:.4f} exceeds RNG-noise envelope "
                f"{envelope:.4f}"
            )
        # Both stacks must actually learn.
        assert t_hist[-1]["train"] < t_hist[0]["train"]
        assert f_hist[-1]["train"] < f_hist[0]["train"]
