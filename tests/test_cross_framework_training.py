"""Cross-framework TRAINING parity: torch vs this framework, step by step.

SURVEY.md §7 flags the hard part: if gate ordering, init, loss math, or
optimizer semantics drift from the reference's torch stack, loss curves
drift. test_models_lstm pins the FORWARD pass; this test pins the whole
training step — identical weights, identical window sequence, torch
Adam(weight_decay)+grad-clip vs the optax chain — and requires the per-step
loss trajectories to track each other to float32 tolerance.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from masters_thesis_tpu.data.pipeline import Batch
from masters_thesis_tpu.models.objectives import ModelSpec
from masters_thesis_tpu.parallel import make_data_mesh
from masters_thesis_tpu.train.optim import make_optimizer
from masters_thesis_tpu.train.steps import make_train_step

torch = pytest.importorskip("torch")

HIDDEN = 8
K, LOOK, TGT = 6, 16, 8
LR, WD, CLIP = 1e-2, 1e-5, 5.0
N_STEPS = 20


class TorchReferenceModel(torch.nn.Module):
    """The reference encoder + MSE decoder shape (reference:
    src/model.py:88-109,192-202), minimal torch re-statement."""

    def __init__(self):
        super().__init__()
        self.lstm = torch.nn.LSTM(3, HIDDEN, 1, batch_first=True)
        self.alpha = torch.nn.Linear(HIDDEN, 1)
        self.beta = torch.nn.Linear(HIDDEN, 1)

    def forward(self, x):
        out, _ = self.lstm(x)
        final = out[:, -1, :]
        return self.alpha(final), self.beta(final)


def flax_params_from_torch(model: TorchReferenceModel):
    # jnp.array (copy), NOT jnp.asarray: .numpy() shares the torch tensor's
    # buffer, and torch's in-place opt.step() would mutate an aliased view.
    params = {
        "w_ih_l0": jnp.array(model.lstm.weight_ih_l0.detach().numpy()),
        "w_hh_l0": jnp.array(model.lstm.weight_hh_l0.detach().numpy()),
        "b_ih_l0": jnp.array(model.lstm.bias_ih_l0.detach().numpy()),
        "b_hh_l0": jnp.array(model.lstm.bias_hh_l0.detach().numpy()),
        "alpha_head": {
            "kernel": jnp.array(model.alpha.weight.detach().numpy().T),
            "bias": jnp.array(model.alpha.bias.detach().numpy()),
        },
        "beta_head": {
            "kernel": jnp.array(model.beta.weight.detach().numpy().T),
            "bias": jnp.array(model.beta.bias.detach().numpy()),
        },
    }
    return params


def make_batches(rng, n_steps):
    """Fixed sequence of windows in the pipeline's Batch schema."""
    batches = []
    for _ in range(n_steps):
        x = rng.normal(0.1, 0.5, size=(1, K, LOOK, 3)).astype(np.float32)
        y = rng.normal(0.1, 0.5, size=(1, K, TGT, 4)).astype(np.float32)
        factor = rng.normal(size=(1, 2)).astype(np.float32)
        inv_psi = rng.uniform(1, 2, size=(1, K)).astype(np.float32)
        batches.append(Batch(x, y, factor, inv_psi))
    return batches


def torch_trajectory(model, batches):
    opt = torch.optim.Adam(model.parameters(), lr=LR, weight_decay=WD)
    losses = []
    for b in batches:
        # flatten(0,1) preamble (reference: src/model.py:193-194).
        x = torch.from_numpy(np.asarray(b.x)).flatten(0, 1)
        y = torch.from_numpy(np.asarray(b.y)).flatten(0, 1)
        alpha, beta = model(x)
        pred = alpha + beta * y[:, :, 1]
        loss = torch.nn.functional.mse_loss(pred, y[:, :, 0])
        opt.zero_grad()
        loss.backward()
        # Lightning clips raw grads before the step (reference:
        # train.py:172 gradient_clip_val).
        torch.nn.utils.clip_grad_norm_(model.parameters(), CLIP)
        opt.step()
        losses.append(float(loss.detach()))
    return losses


def framework_trajectory(params, batches):
    spec = ModelSpec(
        objective="mse", hidden_size=HIDDEN, num_layers=1, dropout=0.0,
        learning_rate=LR,
    )
    mesh = make_data_mesh(1)
    module = spec.build_module()
    tx = make_optimizer(CLIP, spec.weight_decay)
    opt_state = tx.init(params)
    step_fn = make_train_step(module, spec.window_objective(), tx, mesh)
    lr = jnp.float32(LR)
    rng = jax.random.key(0)  # dropout=0: rng is inert
    losses = []
    for b in batches:
        params, opt_state, sums = step_fn(params, opt_state, lr, rng, b)
        value, weight = jax.device_get(sums["total"])
        losses.append(float(value) / float(weight))
    return losses


def test_training_trajectories_match():
    torch.manual_seed(0)
    model = TorchReferenceModel()
    params = flax_params_from_torch(model)
    batches = make_batches(np.random.default_rng(7), N_STEPS)

    t_losses = torch_trajectory(model, batches)
    f_losses = framework_trajectory(params, batches)

    np.testing.assert_allclose(f_losses, t_losses, rtol=2e-4)
    # The trajectory must actually move (optimizer engaged on both sides).
    assert t_losses[-1] != pytest.approx(t_losses[0])
