"""A faithful torch re-statement of the reference training stack, for the
epoch-scale cross-framework parity harness.

This mirrors — without copying — what the reference's Lightning loop does
per epoch (reference: src/model.py:72-331, train.py:169-198):

- 2-head LSTM encoder: ``torch.nn.LSTM(input, hidden, layers, dropout,
  batch_first)`` + two ``Linear(hidden, 1)`` heads on the last hidden state
  (reference: src/model.py:88-109),
- the three objectives — MSE on ``alpha + beta * r_market``, the
  multivariate-Gaussian NLL with the Woodbury single-factor inverse
  covariance, and Combined = NLL + mse_weight * MSE (reference:
  src/model.py:176-331, src/common.py:50-78),
- Adam(lr, weight_decay=1e-5) + gradient clipping + torch's own
  ReduceLROnPlateau(factor .5, patience 2) stepped on the epoch's val loss
  (reference: src/model.py:149-172, train.py:172),
- shuffled batch_size=1-window epochs, eval with dropout off
  (reference: src/data.py:236-244).

The harness trains THIS stack and the JAX framework from identical initial
weights on identical windows and requires the epoch loss curves to agree —
the BASELINE.md north-star "loss curves within 1%" claim, as a test.
"""

from __future__ import annotations

import math

import numpy as np
import torch

CLIP = 5.0
WEIGHT_DECAY = 1e-5


class TorchReferenceStack(torch.nn.Module):
    """Reference encoder shape (reference: src/model.py:88-109)."""

    def __init__(self, input_size=3, hidden_size=16, num_layers=2, dropout=0.2):
        super().__init__()
        self.lstm = torch.nn.LSTM(
            input_size,
            hidden_size,
            num_layers,
            batch_first=True,
            dropout=dropout if num_layers > 1 else 0.0,
        )
        self.alpha = torch.nn.Linear(hidden_size, 1)
        self.beta = torch.nn.Linear(hidden_size, 1)

    def forward(self, x):
        out, _ = self.lstm(x)
        final = out[:, -1, :]
        return self.alpha(final), self.beta(final)


def flax_params_from_torch(model: TorchReferenceStack) -> dict:
    """Copy torch weights into the LstmEncoder param tree (any layer count).

    jnp.array (copy), NOT asarray: ``.numpy()`` aliases the torch buffer,
    which ``opt.step()`` mutates in place.
    """
    import jax.numpy as jnp

    params: dict = {}
    for layer in range(model.lstm.num_layers):
        for t_name, f_name in (
            ("weight_ih", "w_ih"),
            ("weight_hh", "w_hh"),
            ("bias_ih", "b_ih"),
            ("bias_hh", "b_hh"),
        ):
            t = getattr(model.lstm, f"{t_name}_l{layer}")
            params[f"{f_name}_l{layer}"] = jnp.array(t.detach().numpy())
    for head, name in ((model.alpha, "alpha_head"), (model.beta, "beta_head")):
        params[name] = {
            "kernel": jnp.array(head.weight.detach().numpy().T),
            "bias": jnp.array(head.bias.detach().numpy()),
        }
    return params


def window_loss(model, x, y, factor, inv_psi, objective, mse_weight=100.0):
    """One window's training loss (reference: src/model.py:192-202 MSE,
    :234-249 NLL via src/common.py:50-78 Woodbury, :308-319 combined)."""
    alpha, beta = model(x)  # (K, 1) each
    r_target = y[:, :, 0]  # (K, T)
    r_market = y[:, :, 1]
    mse = torch.nn.functional.mse_loss(alpha + beta * r_market, r_target)
    if objective == "mse":
        return mse
    f_mean, f_var = factor[0], factor[1]
    mu = alpha + beta * f_mean  # (K, 1)
    psi_inv = torch.diag(inv_psi)
    denom = 1.0 / f_var + beta.T @ psi_inv @ beta
    sigma_inv = psi_inv - (psi_inv @ beta @ beta.T @ psi_inv) / denom
    diff = r_target - mu  # (K, n)
    k, n = diff.shape
    nll = 0.5 * (
        n * (k * math.log(2.0 * math.pi) - torch.logdet(sigma_inv))
        + torch.sum((sigma_inv @ diff) * diff)
    )
    if objective == "nll":
        return nll
    return nll + mse_weight * mse


def _window(arrays, i):
    x = torch.from_numpy(np.asarray(arrays.x[i]))
    y = torch.from_numpy(np.asarray(arrays.y[i]))
    factor = torch.from_numpy(np.asarray(arrays.factor[i]))
    inv_psi = torch.from_numpy(np.asarray(arrays.inv_psi[i]))
    return x, y, factor, inv_psi


def fit_reference(
    model: TorchReferenceStack,
    train_arrays,
    val_arrays,
    objective: str,
    *,
    epochs: int,
    lr: float,
    mse_weight: float = 100.0,
    shuffle_seed: int = 0,
    epoch_batches=None,
) -> list[dict]:
    """Train the torch stack the way the reference's Lightning loop would;
    returns per-epoch rows {train, val, lr} (epoch-mean losses).

    ``epoch_batches``: optional ``fn(epoch) -> iterator of batch_size=1
    Batch pytrees`` — lets the exact-parity harness feed torch the
    framework's OWN epoch iterator so both stacks see identical window
    sequences (cross-framework RNG replication being impossible otherwise).
    """
    opt = torch.optim.Adam(model.parameters(), lr=lr, weight_decay=WEIGHT_DECAY)
    sched = torch.optim.lr_scheduler.ReduceLROnPlateau(
        opt, factor=0.5, patience=2
    )
    rng = np.random.default_rng(shuffle_seed)
    n_train = train_arrays.x.shape[0]
    n_val = val_arrays.x.shape[0]
    history = []
    for epoch in range(epochs):
        model.train()
        losses = []
        if epoch_batches is not None:

            def one_window(b):
                # The harness is batch_size=1 only; silently taking leaf[0]
                # from a bigger batch would train torch on a fraction of
                # the stream and void the parity premise.
                assert b.x.shape[0] == 1, f"batch_size=1 only, got {b.x.shape[0]}"
                return tuple(
                    torch.from_numpy(np.asarray(leaf[0]))
                    for leaf in (b.x, b.y, b.factor, b.inv_psi)
                )

            windows = (one_window(b) for b in epoch_batches(epoch))
        else:
            windows = (
                _window(train_arrays, i) for i in rng.permutation(n_train)
            )
        for w in windows:
            loss = window_loss(model, *w, objective, mse_weight)
            opt.zero_grad()
            loss.backward()
            # Lightning clips raw grads before the step (train.py:172).
            torch.nn.utils.clip_grad_norm_(model.parameters(), CLIP)
            opt.step()
            losses.append(float(loss.detach()))
        model.eval()
        with torch.no_grad():
            val = float(
                np.mean(
                    [
                        float(
                            window_loss(
                                model, *_window(val_arrays, i), objective,
                                mse_weight,
                            )
                        )
                        for i in range(n_val)
                    ]
                )
            )
        sched.step(val)
        history.append(
            {
                "train": float(np.mean(losses)),
                "val": val,
                "lr": opt.param_groups[0]["lr"],
            }
        )
    return history
