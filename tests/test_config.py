"""Config engine tests: composition, interpolation, overrides, multirun.

Exercises the real configs/ tree at the repo root (the same one train.py
uses) plus synthetic fixtures for edge cases.
"""

from pathlib import Path

import pytest

from masters_thesis_tpu.config import (
    compose,
    expand_multirun,
    register_resolver,
    to_flat_dict,
)

CONFIG_DIR = Path(__file__).resolve().parent.parent / "configs"


@pytest.fixture(autouse=True)
def _resolver():
    # Same derived config the reference registers (reference: train.py:39-42).
    register_resolver(
        "input_size_from_interaction", lambda interaction: 3 if interaction else 5
    )


def test_defaults_composition():
    cfg = compose(CONFIG_DIR)
    assert cfg.datamodule.name == "synthetic"
    assert cfg.model.name == "small"
    assert cfg.loss.name == "mse"
    assert cfg.trainer.name == "fast"
    assert cfg.model.num_layers == 2
    assert cfg.checkpoint is None


def test_group_override():
    cfg = compose(CONFIG_DIR, overrides=["model=large", "loss=nll", "trainer=slow"])
    assert cfg.model.num_layers == 8
    assert cfg.loss.module_class == "FinancialLstmNll"
    assert cfg.trainer.max_epochs == 32


def test_value_override_is_typed():
    cfg = compose(CONFIG_DIR, overrides=["model.learning_rate=1e-3"])
    assert cfg.model.learning_rate == pytest.approx(1e-3)
    assert isinstance(cfg.model.learning_rate, float)


def test_unknown_value_override_rejected():
    with pytest.raises(KeyError):
        compose(CONFIG_DIR, overrides=["model.does_not_exist=3"])


def test_add_and_delete_overrides():
    cfg = compose(CONFIG_DIR, overrides=["+model.extra=7", "~launcher.verbose"])
    assert cfg.model.extra == 7
    assert "verbose" not in cfg.launcher


def test_resolver_interpolation_nested():
    # ${input_size_from_interaction:${datamodule.interaction_only}}
    cfg = compose(CONFIG_DIR)
    assert cfg.model.input_size == 3
    cfg = compose(CONFIG_DIR, overrides=["datamodule.interaction_only=false"])
    assert cfg.model.input_size == 5


def test_string_interpolation_composes_version():
    cfg = compose(CONFIG_DIR, overrides=["loss=combined", "model=medium"])
    assert cfg.logger.name == "FinancialLstm/synthetic"
    assert cfg.logger.version == "combined_medium_lr0.0001_fast"


def test_interpolation_tracks_overrides():
    cfg = compose(CONFIG_DIR, overrides=["model.learning_rate=0.01"])
    assert "lr0.01" in cfg.logger.version


def test_multirun_expansion_cartesian():
    runs = expand_multirun(
        ["datamodule=real", "model.learning_rate=1e-3,1e-4,1e-5", "trainer.max_epochs=100,200"]
    )
    assert len(runs) == 6
    assert ["datamodule=real", "model.learning_rate=1e-3", "trainer.max_epochs=100"] in runs
    assert ["datamodule=real", "model.learning_rate=1e-5", "trainer.max_epochs=200"] in runs


def test_multirun_single_run_passthrough():
    assert expand_multirun(["model=large"]) == [["model=large"]]


def test_multirun_brackets_not_split():
    # commas inside [] are value syntax, not sweep separators
    runs = expand_multirun(["+model.dims=[16,32]", "model.learning_rate=1e-3,1e-4"])
    assert len(runs) == 2
    assert all(ov[0] == "+model.dims=[16,32]" for ov in runs)


def test_interpolation_cycle_detected(tmp_path):
    (tmp_path / "config.yaml").write_text("a: ${b}\nb: ${a}\n")
    with pytest.raises(ValueError, match="cycle"):
        compose(tmp_path)


def test_flat_dict():
    cfg = compose(CONFIG_DIR)
    flat = to_flat_dict(cfg)
    assert flat["model.hidden_size"] == 64
    assert flat["datamodule.lookback_window"] == 60


def test_partition_jobs_round_robin():
    """Multi-host sweep dispatch: hosts cover all jobs exactly once."""
    import train as train_mod

    jobs = [[f"j={i}"] for i in range(7)]
    shards = [train_mod.partition_jobs(jobs, h, 3) for h in range(3)]
    assert [len(s) for s in shards] == [3, 2, 2]
    flat = [j for s in shards for j in s]
    assert sorted(flat) == sorted(jobs)
    with pytest.raises(ValueError):
        train_mod.partition_jobs(jobs, 3, 3)
