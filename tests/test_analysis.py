"""tracelint tests: every AST rule fires on a seeded fixture, the shipped
package lints clean, suppressions work, and the trace-time audit holds the
compile-once / no-transfer / sharding invariants on the virtual 8-device
mesh (conftest.py).
"""

from pathlib import Path

import jax
import pytest

from masters_thesis_tpu.analysis import Finding, format_report, lint_paths
from masters_thesis_tpu.analysis.__main__ import main as cli_main
from masters_thesis_tpu.analysis.findings import (
    RULES,
    is_suppressed,
    suppressed_rules_by_line,
)
from masters_thesis_tpu.analysis.traceaudit import (
    PreflightError,
    assert_trace_clean,
    run_trace_audit,
)

PACKAGE_ROOT = Path(__file__).resolve().parents[1] / "masters_thesis_tpu"


def lint_snippet(tmp_path: Path, source: str) -> list[Finding]:
    f = tmp_path / "snippet.py"
    f.write_text(source)
    return lint_paths([f])


def rules_of(findings) -> set[str]:
    return {f.rule for f in findings}


# ----------------------------------------------------------------- Pass 1


class TestAstRules:
    def test_tracer_host_cast_in_jit(self, tmp_path):
        findings = lint_snippet(
            tmp_path,
            """
import jax

@jax.jit
def f(x):
    return float(x) + x.item()
""",
        )
        assert rules_of(findings) == {"TL101"}
        assert len(findings) == 2

    def test_python_control_flow_on_tracer(self, tmp_path):
        findings = lint_snippet(
            tmp_path,
            """
import jax
import jax.numpy as jnp

@jax.jit
def f(x):
    if x > 0:
        return jnp.log(x)
    while jnp.any(x < 0):
        x = x + 1
    return x
""",
        )
        assert rules_of(findings) == {"TL102"}
        assert len(findings) == 2

    def test_key_reuse(self, tmp_path):
        findings = lint_snippet(
            tmp_path,
            """
import jax

def sample(rng):
    a = jax.random.normal(rng, (4,))
    b = jax.random.uniform(rng, (4,))
    return a + b
""",
        )
        assert rules_of(findings) == {"TL103"}

    def test_key_reuse_across_loop(self, tmp_path):
        findings = lint_snippet(
            tmp_path,
            """
import jax

def sample(rng):
    out = []
    for i in range(3):
        out.append(jax.random.normal(rng, (2,)))
    return out
""",
        )
        assert rules_of(findings) == {"TL103"}

    def test_split_resets_key_state(self, tmp_path):
        findings = lint_snippet(
            tmp_path,
            """
import jax

def sample(rng):
    a_rng, b_rng = jax.random.split(rng)
    a = jax.random.normal(a_rng, (4,))
    b = jax.random.uniform(b_rng, (4,))
    return a + b

def folded(rng, xs):
    out = []
    for i in range(3):
        step = jax.random.fold_in(rng, i)
        out.append(jax.random.normal(step, (2,)))
    return out
""",
        )
        assert findings == []

    def test_f64_literal(self, tmp_path):
        findings = lint_snippet(
            tmp_path,
            """
import jax.numpy as jnp

def widen(x):
    return jnp.asarray(x, dtype="float64") + jnp.zeros(3, jnp.float64)
""",
        )
        assert rules_of(findings) == {"TL104"}

    def test_x64_enablement(self, tmp_path):
        findings = lint_snippet(
            tmp_path,
            """
import jax

jax.config.update("jax_enable_x64", True)
""",
        )
        assert rules_of(findings) == {"TL104"}

    def test_host_transfer_in_jit(self, tmp_path):
        findings = lint_snippet(
            tmp_path,
            """
import jax
import numpy as np

@jax.jit
def f(x):
    y = np.asarray(x * 2)
    jax.device_get(x)
    return y
""",
        )
        assert rules_of(findings) == {"TL105"}
        assert len(findings) == 2

    def test_host_code_not_flagged(self, tmp_path):
        # The same constructs OUTSIDE jit-reachable code are the host
        # loop's job — must not be flagged.
        findings = lint_snippet(
            tmp_path,
            """
import jax
import numpy as np

def readback(x):
    if x is None:
        return None
    host = np.asarray(jax.device_get(x))
    return float(host.sum())
""",
        )
        assert findings == []

    def test_jit_reachability_propagates_through_calls(self, tmp_path):
        # helper() is not decorated, but is called from inside a jitted
        # function — rules apply transitively.
        findings = lint_snippet(
            tmp_path,
            """
import jax

def helper(x):
    return float(x)

@jax.jit
def f(x):
    return helper(x)
""",
        )
        assert rules_of(findings) == {"TL101"}

    def test_shape_access_breaks_taint(self, tmp_path):
        findings = lint_snippet(
            tmp_path,
            """
import jax

@jax.jit
def f(x):
    n = x.shape[0]
    if n > 4:
        return x[:4]
    return x
""",
        )
        assert findings == []

    def test_suppression_comment(self, tmp_path):
        findings = lint_snippet(
            tmp_path,
            """
import jax

@jax.jit
def f(x):
    return float(x)  # tracelint: disable=TL101
""",
        )
        assert findings == []

    def test_bare_noqa_does_not_swallow(self, tmp_path):
        findings = lint_snippet(
            tmp_path,
            """
import jax

@jax.jit
def f(x):
    return float(x)  # noqa
""",
        )
        assert rules_of(findings) == {"TL101"}

    def test_suppression_parser(self):
        sup = suppressed_rules_by_line(
            "a = 1  # tracelint: disable=TL101, TL105\n"
            "b = 2  # tracelint: disable\n"
            "c = 3  # noqa: TL103\n"
        )
        assert sup[1] == {"TL101", "TL105"}
        assert sup[2] is None
        assert sup[3] == {"TL103"}
        assert is_suppressed(Finding("TL101", "m", "f", 1), sup)
        assert not is_suppressed(Finding("TL102", "m", "f", 1), sup)
        assert is_suppressed(Finding("TL102", "m", "f", 2), sup)

    def test_every_finding_rule_is_registered(self, tmp_path):
        findings = lint_snippet(
            tmp_path,
            """
import jax
import jax.numpy as jnp
import numpy as np

@jax.jit
def f(x, rng):
    if x > 0:
        y = float(x)
    a = jax.random.normal(rng, (2,))
    b = jax.random.normal(rng, (2,))
    np.log(x)
    return jnp.zeros(2, jnp.float64)
""",
        )
        assert rules_of(findings) <= set(RULES)
        assert {"TL101", "TL102", "TL103", "TL104", "TL105"} <= rules_of(
            findings
        )

    def test_package_tree_is_clean(self):
        findings = lint_paths([PACKAGE_ROOT], package_root=PACKAGE_ROOT)
        assert findings == [], format_report(findings)


# ------------------------------------------------------------------- CLI


class TestCli:
    def test_clean_tree_exits_zero(self):
        assert cli_main(["--skip-trace", str(PACKAGE_ROOT)]) == 0

    def test_findings_exit_nonzero(self, tmp_path, capsys):
        bad = tmp_path / "bad.py"
        bad.write_text(
            "import jax\n\n@jax.jit\ndef f(x):\n    return float(x)\n"
        )
        assert cli_main(["--skip-trace", str(bad)]) == 1
        out = capsys.readouterr().out
        assert "TL101" in out

    def test_json_output(self, tmp_path, capsys):
        bad = tmp_path / "bad.py"
        bad.write_text(
            "import jax\n\n@jax.jit\ndef f(x):\n    return float(x)\n"
        )
        assert cli_main(["--skip-trace", "--json", str(bad)]) == 1
        out = capsys.readouterr().out
        assert '"rule": "TL101"' in out


# ----------------------------------------------------------------- Pass 2


class TestTraceAudit:
    def test_audit_is_clean_on_virtual_mesh(self):
        findings = run_trace_audit()
        assert findings == [], format_report(findings)

    def test_train_epoch_compiles_exactly_once_across_steps(self):
        # The compile-count regression pin: 3 epochs with varying rngs
        # through the real epoch program must hit ONE cache entry. This is
        # the audit's TA201 check asserted directly against the jit cache.
        import jax.numpy as jnp
        import numpy as np

        from masters_thesis_tpu.analysis import traceaudit as ta
        from masters_thesis_tpu.models.objectives import ModelSpec
        from masters_thesis_tpu.parallel import (
            batch_sharding,
            global_put,
            make_data_mesh,
            replicated_sharding,
        )
        from masters_thesis_tpu.train.optim import make_optimizer
        from masters_thesis_tpu.train.steps import make_train_epoch

        mesh = make_data_mesh(None)
        spec = ModelSpec(
            objective="mse", hidden_size=8, num_layers=1, dropout=0.0,
            kernel_impl="xla",
        )
        module = spec.build_module()
        tx = make_optimizer(None, spec.weight_decay)
        split = ta._synthetic_split(
            mesh.size * ta.AUDIT_BATCH * 2, np.random.default_rng(0)
        )
        params = module.init(
            jax.random.key(0),
            jnp.zeros((1, ta.AUDIT_LOOKBACK, ta.AUDIT_FEATURES)),
        )["params"]
        opt_state = tx.init(params)
        repl = replicated_sharding(mesh)
        params = global_put(params, repl)
        opt_state = global_put(opt_state, repl)
        data = global_put(split, batch_sharding(mesh))
        epoch_fn = make_train_epoch(
            module, spec.window_objective(), spec.metric_keys, tx, mesh,
            batch_size=ta.AUDIT_BATCH,
        )
        lr = global_put(jnp.float32(1e-3), repl)
        for e in range(3):
            epoch_rng = global_put(
                jax.random.fold_in(jax.random.key(1), e), repl
            )
            params, opt_state, sums = epoch_fn(
                params, opt_state, lr, epoch_rng, data
            )
        jax.block_until_ready(sums)
        assert epoch_fn._cache_size() == 1

    def test_audit_is_clean_for_kfactor_asset_sharding(self):
        """The universe-scale program holds every trace invariant: K=3
        windows, asset axis sharded over the mesh, and still exactly one
        all-reduce per dtype buffer in the scan body (TA206) plus the
        single batched all-reduce in the stacked program (TA207)."""
        from masters_thesis_tpu.models.objectives import ModelSpec

        spec = ModelSpec(
            objective="mse", hidden_size=8, num_layers=1, dropout=0.0,
            kernel_impl="xla", n_factors=3,
        )
        findings = run_trace_audit(spec=spec, shard_axis="asset")
        assert findings == [], format_report(findings)

    def test_audit_reports_infrastructure_failure_as_ta205(self):
        class NotASpec:
            pass

        findings = run_trace_audit(spec=NotASpec())
        assert rules_of(findings) == {"TA205"}

    def test_assert_trace_clean_raises_preflight_error(self, monkeypatch):
        from masters_thesis_tpu.analysis import traceaudit as ta

        monkeypatch.setattr(
            ta,
            "run_trace_audit",
            lambda **kw: [Finding("TA201", "boom")],
        )
        with pytest.raises(PreflightError) as exc_info:
            ta.assert_trace_clean()
        assert "TA201" in str(exc_info.value)
        assert exc_info.value.findings[0].rule == "TA201"

    def test_assert_trace_clean_passes(self):
        assert_trace_clean()


# -------------------------------------------------------------- preflight


class TestTrainerPreflight:
    def test_preflight_runs_before_fit(self, monkeypatch, tmp_path):
        from masters_thesis_tpu.analysis import traceaudit as ta
        from masters_thesis_tpu.train.trainer import Trainer

        calls = {}

        def fake_audit(**kw):
            calls["mesh"] = kw.get("mesh")
            return [Finding("TA203", "seeded failure")]

        monkeypatch.setattr(ta, "run_trace_audit", fake_audit)
        trainer = Trainer(
            max_epochs=1,
            enable_progress_bar=False,
            enable_model_summary=False,
            preflight=True,
        )
        from masters_thesis_tpu.models.objectives import ModelSpec

        with pytest.raises(PreflightError):
            trainer.fit(
                ModelSpec(objective="mse", hidden_size=8, num_layers=1),
                dm=None,  # preflight raises before the datamodule is touched
            )
        assert calls["mesh"] is trainer.mesh
