"""Worker process for the REAL 2-process distributed integration test.

Not a pytest module (no ``test_`` prefix): tests/test_distributed.py spawns
two of these with a shared coordinator address, one CPU device each —
exercising ``jax.distributed.initialize``, the multi-host bootstrap/cache
rendezvous, and the cross-process shard_map train/eval path for real
(everything the reference's latent DDP story would do over NCCL,
reference: train.py:169-180, src/model.py:24-25).

Usage: python tests/_distributed_worker.py <coord_addr> <rank> <world> \
           <workdir> [devices_per_process]

With devices_per_process > 1 the 2-process world forms a (world x local)
global mesh — the multi-host pod topology (DCN between processes, ICI
within a host) rather than one chip per host.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path


def main() -> None:
    coord, rank, world, workdir = (
        sys.argv[1],
        int(sys.argv[2]),
        int(sys.argv[3]),
        Path(sys.argv[4]),
    )
    local = int(sys.argv[5]) if len(sys.argv) > 5 else 1
    from masters_thesis_tpu.parallel import distributed_initialize

    distributed_initialize(
        coordinator_address=coord,
        num_processes=world,
        process_id=rank,
        required=True,
    )
    import jax

    assert jax.process_count() == world, jax.process_count()
    assert len(jax.local_devices()) == local
    assert len(jax.devices()) == world * local

    import numpy as np

    from masters_thesis_tpu.data.pipeline import (
        FinancialWindowDataModule,
        bootstrap_synthetic,
    )
    from masters_thesis_tpu.models.objectives import ModelSpec
    from masters_thesis_tpu.train import Trainer

    # Every rank calls bootstrap against the SHARED dir: rank 0 generates,
    # the others block on the dgp.json completion marker (the rendezvous
    # that was previously only ever monkeypatch-simulated).
    data_dir = workdir / "data"
    # 3820 samples -> 159 windows -> train split 111, which is ODD: with a
    # global batch of 2 (1 window x 2 processes) the stream run below hits
    # the weight-masked tail-batch path cross-process, not just full
    # batches.
    bootstrap_synthetic(data_dir, n_stocks=4, n_samples=3820, seed=0)
    dm = FinancialWindowDataModule(
        data_dir, lookback_window=16, target_window=8, stride=24, batch_size=1
    )
    dm.prepare_data(verbose=False)
    dm.setup()
    assert len(dm.train_range) % 2 == 1  # forces a stream tail batch

    trainer = Trainer(
        max_epochs=2,
        gradient_clip_val=5.0,
        check_val_every_n_epoch=1,
        strategy="tpu_xla",
        enable_progress_bar=False,
        enable_model_summary=False,
        seed=0,
    )
    assert trainer.n_dev == world * local
    spec = ModelSpec(
        objective="mse", hidden_size=8, num_layers=1, dropout=0.0,
        learning_rate=1e-2,
    )
    result = trainer.fit(spec, dm)
    test_metrics = trainer.test(spec, result.params, dm)

    # Stream mode across processes too: host iterator -> global_put
    # prefetch -> pjit step over the cross-process mesh (incl. the
    # weight-masked tail batch).
    stream_trainer = Trainer(
        max_epochs=1,
        gradient_clip_val=5.0,
        check_val_every_n_epoch=1,
        strategy="tpu_xla",
        epoch_mode="stream",
        enable_progress_bar=False,
        enable_model_summary=False,
        seed=0,
    )
    stream = stream_trainer.fit(spec, dm)

    leaves = jax.tree_util.tree_leaves(jax.device_get(result.params))
    np.savez(workdir / f"rank{rank}.npz", *[np.asarray(l) for l in leaves])
    (workdir / f"rank{rank}.json").write_text(
        json.dumps(
            {
                "history": result.history,
                "best_val": result.best_val_loss,
                "test": test_metrics,
                "stream_history": stream.history,
                "process_count": jax.process_count(),
                "n_dev": trainer.n_dev,
                "local_devices": len(jax.local_devices()),
            }
        )
    )


if __name__ == "__main__":
    main()
