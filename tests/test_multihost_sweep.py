"""Multi-host sweep sharding x multi-device mesh, end to end.

``partition_jobs`` is unit-tested (tests/test_config.py) and 2-process DDP
is integration-tested (tests/test_distributed.py); this closes the last
untested composition (VERDICT r4 #7): FOUR separate OS processes — one per
"host" of a pod — each running the SAME ``train.py -m`` sweep command with
its own ``MT_HOST_INDEX``, each on its own 2-virtual-device CPU mesh
(strategy=auto picks the sharded tpu_xla path), writing into one shared
sweep tree. The multi-host contract under test (reference parity:
Hydra's joblib launcher fans a sweep across GPU processes,
reference: configs/config.yaml:6,17-19):

- every host takes exactly its round-robin share of the sweep (4 jobs /
  4 hosts = 1 each, by GLOBAL sweep index),
- numbered job dirs are collision-free fleet-wide (0..3, one per job,
  each with Hydra-compatible .hydra metadata and a completed checkpoint),
- concurrent hosts bootstrapping one shared data_dir rendezvous through
  the atomic-publish marker protocol instead of corrupting each other.
"""

from __future__ import annotations

import os
import subprocess
import sys
from pathlib import Path

import pytest
import yaml

pytestmark = pytest.mark.slow  # 4 concurrent training processes, ~2-4 min

_REPO_ROOT = Path(__file__).resolve().parent.parent

NUM_HOSTS = 4
SWEEP = [
    "loss=mse,nll",
    "model.hidden_size=8,12",  # 2x2 = 4 sweep points
    "model.num_layers=1",
    "trainer=fast",
    "trainer.max_epochs=1",
    # progress bar ON: the "mesh: 2xdata | tpu_xla" summary line asserted
    # below prints through the progress-gated _print path.
    "trainer.enable_progress_bar=true",
    "datamodule.n_samples=8000",
    "datamodule.n_stocks=4",
]


def _host_env(host_index: int, sweep_dir: Path) -> dict:
    env = os.environ.copy()
    # Hermetic from the TPU relay; a 2-device virtual mesh per host.
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["PYTHONPATH"] = str(_REPO_ROOT)
    env["MT_HOST_INDEX"] = str(host_index)
    env["MT_NUM_HOSTS"] = str(NUM_HOSTS)
    env["MT_SWEEP_DIR"] = str(sweep_dir)
    return env


def test_four_host_sweep_shard_end_to_end(tmp_path):
    sweep_dir = tmp_path / "sweep"
    data_dir = tmp_path / "data"  # SHARED: all hosts bootstrap it at once
    procs = [
        subprocess.Popen(
            [
                sys.executable, "train.py", "-m", *SWEEP,
                f"datamodule.data_dir={data_dir}",
            ],
            cwd=_REPO_ROOT,
            env=_host_env(h, sweep_dir),
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for h in range(NUM_HOSTS)
    ]
    outs = []
    try:
        for h, p in enumerate(procs):
            out, _ = p.communicate(timeout=600)
            outs.append(out)
            assert p.returncode == 0, f"host {h} failed:\n{out[-3000:]}"
    finally:
        # A failed/timed-out host must not leak the others: they would keep
        # training on the single host core for minutes after the test died.
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()

    # Each host announced and ran exactly its 1/4 share.
    for h, out in enumerate(outs):
        assert f"multirun: host {h}/{NUM_HOSTS} takes 1/4 jobs" in out, (
            f"host {h} took the wrong share:\n{out[-1500:]}"
        )
        # strategy=auto saw the 2-device mesh and took the sharded path.
        assert "| mesh: 2xdata | tpu_xla" in out, (
            f"host {h} did not run on the 2-device mesh:\n{out[-1500:]}"
        )

    # Collision-free numbered job dirs: every global sweep index exactly
    # once, each with Hydra-style metadata and a COMPLETED run.
    job_dirs = sorted(d.name for d in sweep_dir.iterdir() if d.is_dir())
    assert job_dirs == [str(i) for i in range(NUM_HOSTS)]
    seen_points = set()
    for i in range(NUM_HOSTS):
        job_dir = sweep_dir / str(i)
        overrides = yaml.safe_load(
            (job_dir / ".hydra" / "overrides.yaml").read_text()
        )
        point = tuple(
            ov for ov in overrides
            if ov.startswith(("loss=", "model.hidden_size="))
        )
        seen_points.add(point)
        ckpts = list(job_dir.glob("logs/**/checkpoints/best"))
        assert ckpts, f"job {i} left no checkpoint under {job_dir}"
        assert list(job_dir.glob("logs/**/checkpoints/last.json")), (
            f"job {i} run did not complete"
        )
    # The 4 job dirs cover the full 2x2 cartesian sweep, no duplicates.
    assert len(seen_points) == NUM_HOSTS

    # The shared bootstrap rendezvous left ONE coherent dataset.
    assert (data_dir / "dgp.json").exists()
    assert (data_dir / "stocks.npy").exists()
