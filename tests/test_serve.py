"""Hardened serving engine (ISSUE 9): AOT predict path, admission
control, deadline enforcement, canaried hot-swap, breaker degradation.

The chaos suite at the bottom drives the REAL server loop on the 8-device
virtual CPU mesh (conftest.py) through the three drills the issue names —
overload, corrupt-swap, wedge — and checks each leaves a distinct
signature in ``telemetry summarize``.
"""

import time
from pathlib import Path

import numpy as np
import pytest

from masters_thesis_tpu.resilience import faults
from masters_thesis_tpu.resilience.faults import FaultPlan, FaultSpec
from masters_thesis_tpu.serve.queue import (
    STATUS_ERROR,
    STATUS_OK,
    STATUS_SHED,
    MicroBatchQueue,
    PendingRequest,
    ServeRequest,
    ServeResponse,
    ServiceTimeModel,
)

# Tiny window shape shared by every engine in this file.
K, T, F = 4, 8, 3
BUCKETS = (1, 2, 4)


@pytest.fixture(autouse=True)
def _no_leaked_faults(monkeypatch):
    """Every test starts and ends with injection off, whatever it does."""
    monkeypatch.delenv(faults.FAULT_PLAN_ENV, raising=False)
    monkeypatch.delenv(faults.ATTEMPT_ENV, raising=False)
    yield
    faults.clear_plan()


def _tiny_spec():
    from masters_thesis_tpu.models.objectives import ModelSpec

    return ModelSpec(
        objective="mse", hidden_size=8, num_layers=1, dropout=0.0,
        kernel_impl="xla",
    )


def _init_params(spec, seed=0):
    import jax
    import jax.numpy as jnp

    module = spec.build_module()
    return module.init(
        jax.random.key(seed), jnp.zeros((1, T, F), jnp.float32)
    )["params"]


def _make_engine(buckets=BUCKETS, seed=0):
    from masters_thesis_tpu.serve.engine import PredictEngine

    spec = _tiny_spec()
    return PredictEngine(
        spec, _init_params(spec, seed),
        n_stocks=K, lookback=T, n_features=F, buckets=buckets,
    )


@pytest.fixture(scope="module")
def shared_engine():
    """One warmed engine for the read-only predict tests (swap/degrade
    tests build their own — they mutate params or the mesh)."""
    eng = _make_engine()
    eng.warmup()
    return eng


def _events(tel):
    from masters_thesis_tpu.telemetry.events import read_events

    return read_events(tel.run_dir / "events.jsonl")


# ------------------------------------------------------- queue + admission


class TestQueueAdmission:
    def _req(self, rid=1, deadline_s=10.0):
        return ServeRequest(
            rid=rid, x=None, deadline_ts=time.monotonic() + deadline_s
        )

    def test_submit_admits_within_capacity(self):
        q = MicroBatchQueue(max_batch=4)
        p = q.submit(self._req(1))
        assert not p.done
        assert len(q) == 1 and q.submitted == 1 and q.shed == 0

    def test_queue_full_sheds_explicitly(self):
        sheds = []
        q = MicroBatchQueue(
            max_batch=4, max_depth=2,
            on_shed=lambda r, reason: sheds.append((r.rid, reason)),
        )
        q.submit(self._req(1))
        q.submit(self._req(2))
        p = q.submit(self._req(3))
        r = p.result(timeout=0)
        assert r.status == STATUS_SHED and "queue full" in r.detail
        assert sheds == [(3, r.detail)]

    def test_infeasible_deadline_shed_at_admission(self):
        q = MicroBatchQueue(max_batch=2)
        q.service_model.seed(1.0)  # 1s per batch, deterministic forecast
        r = q.submit(self._req(1, deadline_s=0.1)).result(timeout=0)
        assert r.status == STATUS_SHED
        assert "deadline infeasible" in r.detail

    def test_closed_queue_sheds(self):
        q = MicroBatchQueue()
        q.close()
        r = q.submit(self._req(1)).result(timeout=0)
        assert r.status == STATUS_SHED and "shutting down" in r.detail

    def test_batch_fires_on_max_batch(self):
        q = MicroBatchQueue(max_batch=2, max_wait_s=60.0)
        q.service_model.seed(1e-6)
        for rid in (1, 2, 3):
            q.submit(self._req(rid))
        batch = q.next_batch(timeout_s=1.0)
        assert [p.request.rid for p in batch] == [1, 2]
        assert len(q) == 1

    def test_batch_fires_on_max_wait(self):
        q = MicroBatchQueue(max_batch=8, max_wait_s=0.01)
        q.service_model.seed(1e-6)
        q.submit(self._req(1))
        t0 = time.monotonic()
        batch = q.next_batch(timeout_s=5.0)
        assert [p.request.rid for p in batch] == [1]
        assert time.monotonic() - t0 < 4.0  # max-wait fired, not timeout

    def test_next_batch_times_out_empty(self):
        q = MicroBatchQueue()
        assert q.next_batch(timeout_s=0.01) == []

    def test_admit_fault_forces_shed(self):
        faults.install_plan(
            FaultPlan(
                faults=(
                    FaultSpec(
                        point="serve.admit", kind="wedge", attempt=None
                    ),
                )
            )
        )
        q = MicroBatchQueue()
        r = q.submit(self._req(1)).result(timeout=0)
        assert r.status == STATUS_SHED and "fault" in r.detail

    def test_first_resolution_wins(self):
        p = PendingRequest(self._req(1))
        p.resolve(ServeResponse(rid=1, status=STATUS_SHED))
        p.resolve(ServeResponse(rid=1, status=STATUS_OK))
        assert p.result(timeout=0).status == STATUS_SHED

    def test_service_model_estimate_and_ewma(self):
        m = ServiceTimeModel(alpha=0.5)
        m.seed(0.1)
        # depth 0 -> one batch; depth 2*max_batch -> three batches.
        assert m.estimate_completion_s(0, 4) == pytest.approx(0.1)
        assert m.estimate_completion_s(8, 4) == pytest.approx(0.3)
        m.update(0.2)
        assert m.batch_s == pytest.approx(0.15)


# ----------------------------------------------------- fault-point surface


class TestServeFaultPoints:
    def test_serve_points_registered(self):
        for point in ("serve.admit", "serve.dispatch", "serve.pre_swap"):
            assert point in faults.POINTS

    def test_unknown_point_error_lists_valid_points(self):
        with pytest.raises(ValueError) as ei:
            FaultSpec(point="serve.bogus", kind="wedge")
        msg = str(ei.value)
        assert "valid points" in msg and "serve.admit" in msg

    def test_unknown_kind_error_lists_valid_kinds(self):
        with pytest.raises(ValueError) as ei:
            FaultSpec(point="serve.admit", kind="bogus")
        msg = str(ei.value)
        assert "valid kinds" in msg and "wedge" in msg


# ------------------------------------------------- strict checkpoint verify


class TestStrictVerify:
    def _save(self, d):
        from masters_thesis_tpu.models.objectives import ModelSpec
        from masters_thesis_tpu.train.checkpoint import save_checkpoint

        spec = ModelSpec(
            objective="mse", hidden_size=8, num_layers=1, dropout=0.0,
            learning_rate=1e-2,
        )
        save_checkpoint(
            d, "last", {"w": np.zeros((8,))}, {}, spec, meta={"epoch": 0}
        )

    def test_manifestless_tree_lenient_vs_strict(self, tmp_path):
        from masters_thesis_tpu.train.checkpoint import (
            MANIFEST_NAME,
            verify_checkpoint,
        )

        self._save(tmp_path)
        tree = tmp_path / "last"
        assert verify_checkpoint(tree, require_manifest=True)
        (tree / MANIFEST_NAME).unlink()
        # Training restore stays lenient (pre-manifest saves are trusted);
        # the serve swap path refuses anything it cannot prove.
        assert verify_checkpoint(tree)
        assert not verify_checkpoint(tree, require_manifest=True)

    def test_missing_tree_fails_both_modes(self, tmp_path):
        from masters_thesis_tpu.train.checkpoint import verify_checkpoint

        assert not verify_checkpoint(tmp_path / "nope")
        assert not verify_checkpoint(
            tmp_path / "nope", require_manifest=True
        )


# ------------------------------------------------------------- AOT engine


class TestPredictEngine:
    def test_warmup_compiles_exactly_once_per_bucket(self, shared_engine):
        assert shared_engine.compile_events == len(shared_engine.buckets)

    def test_steady_state_never_traces(self, shared_engine, rng):
        before = shared_engine.compile_events
        for n in (1, 2, 3, 4, 1, 3, 4, 2):
            x = rng.standard_normal((n, K, T, F)).astype(np.float32)
            alpha, beta = shared_engine.predict(x)
            assert alpha.shape == (n, K) and beta.shape == (n, K)
            assert np.isfinite(alpha).all() and np.isfinite(beta).all()
        assert shared_engine.compile_events == before

    def test_pad_to_bucket_parity(self, shared_engine):
        x = shared_engine.golden_batch(4, seed=3)
        a4, b4 = shared_engine.predict(x)
        a3, b3 = shared_engine.predict(x[:3])  # pads 3 -> bucket 4
        np.testing.assert_allclose(a3, a4[:3], rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(b3, b4[:3], rtol=1e-5, atol=1e-6)

    def test_bucket_overflow_raises(self, shared_engine):
        from masters_thesis_tpu.serve.engine import BucketOverflowError

        assert shared_engine.bucket_for(3) == 4
        with pytest.raises(BucketOverflowError):
            shared_engine.predict(shared_engine.golden_batch(5))

    def test_bad_window_shape_raises(self, shared_engine):
        with pytest.raises(ValueError):
            shared_engine.predict(np.zeros((2, K + 1, T, F), np.float32))

    def test_golden_batch_deterministic(self, shared_engine):
        a = shared_engine.golden_batch(2, seed=7)
        b = shared_engine.golden_batch(2, seed=7)
        assert np.array_equal(a, b)

    def test_preflight_clean_on_test_mesh(self):
        from masters_thesis_tpu.serve.preflight import run_serve_preflight

        assert run_serve_preflight(buckets=(1, 2), requests=4) == []


# ------------------------------------------------------- canaried hot-swap


class TestCanaryChecks:
    def test_verdict_ordering(self):
        from masters_thesis_tpu.serve.swap import canary_checks

        z = (np.zeros((1, 2)), np.zeros((1, 2)))
        good = canary_checks(z, z)
        assert good.ok and good.reason == "committed"
        assert good.checks["finite"] and good.checks["drift"] == 0.0
        nan = canary_checks(z, (np.full((1, 2), np.nan), np.zeros((1, 2))))
        assert not nan.ok and nan.reason == "canary_nonfinite"
        big = canary_checks(z, (np.full((1, 2), 1e9), np.zeros((1, 2))))
        assert not big.ok and big.reason == "canary_abs"
        drift = canary_checks(
            z, (np.ones((1, 2)), np.zeros((1, 2))), max_drift=0.5
        )
        assert not drift.ok and drift.reason == "canary_drift"
        # No drift budget -> arbitrary (finite, bounded) movement commits.
        assert canary_checks(z, (np.ones((1, 2)), np.zeros((1, 2)))).ok


def _save_ckpt(d, spec, params, epoch):
    from masters_thesis_tpu.train.checkpoint import save_checkpoint

    save_checkpoint(
        Path(d), "best", params, {}, spec,
        meta={"epoch": epoch, "datamodule": {"lookback_window": T}},
    )


@pytest.fixture
def swap_setup(tmp_path):
    """Engine booted from a published checkpoint (the serving boot path,
    strict verification) plus the directory new candidates land in."""
    from masters_thesis_tpu.serve.engine import PredictEngine

    spec = _tiny_spec()
    d = tmp_path / "ckpt"
    d.mkdir()
    _save_ckpt(d, spec, _init_params(spec, seed=0), epoch=0)
    engine = PredictEngine.from_checkpoint(
        d, "best", n_stocks=K, n_features=F, buckets=(1,)
    )
    engine.warmup()
    return d, spec, engine


class TestCheckpointSwap:
    def test_good_candidate_commits(self, swap_setup):
        from masters_thesis_tpu.serve.swap import CheckpointSwapper

        d, spec, engine = swap_setup
        swapper = CheckpointSwapper(engine)
        golden = swapper.golden_x
        before = engine.predict(golden)
        _save_ckpt(d, spec, _init_params(spec, seed=7), epoch=1)
        verdict = swapper.try_swap(d)
        assert verdict.ok and verdict.reason == "committed"
        assert swapper.committed == 1 and swapper.rejected == 0
        after = engine.predict(golden)
        # Different params now serve: outputs moved.
        assert not np.allclose(before[0], after[0])

    def test_corrupt_candidate_refused_with_output_parity(
        self, swap_setup, tmp_path
    ):
        from masters_thesis_tpu.serve.swap import CheckpointSwapper
        from masters_thesis_tpu.telemetry import TelemetryRun
        from masters_thesis_tpu.telemetry.report import summarize_events

        d, spec, engine = swap_setup
        tel = TelemetryRun(tmp_path / "tel", run_id="swap-chaos")
        swapper = CheckpointSwapper(engine, telemetry=tel)
        before = engine.predict(swapper.golden_x)
        _save_ckpt(d, spec, _init_params(spec, seed=7), epoch=1)
        faults.install_plan(
            FaultPlan(
                faults=(
                    FaultSpec(
                        point="serve.pre_swap", kind="corrupt", attempt=None
                    ),
                ),
                seed=5,
            )
        )
        try:
            verdict = swapper.try_swap(d)
        finally:
            faults.clear_plan()
        tel.close()
        assert not verdict.ok and verdict.reason == "verify_failed"
        assert swapper.rejected == 1 and swapper.committed == 0
        # The replica keeps serving the EXACT old params.
        after = engine.predict(swapper.golden_x)
        assert np.array_equal(before[0], after[0])
        assert np.array_equal(before[1], after[1])
        # Distinct signature in telemetry summarize.
        report = summarize_events(_events(tel))
        assert report["serve"]["swaps_rejected"] == 1
        assert report["serve"]["swaps_committed"] == 0

    def test_manifestless_candidate_refused(self, swap_setup):
        from masters_thesis_tpu.serve.swap import CheckpointSwapper
        from masters_thesis_tpu.train.checkpoint import (
            MANIFEST_NAME,
            verify_checkpoint,
        )

        d, spec, engine = swap_setup
        _save_ckpt(d, spec, _init_params(spec, seed=7), epoch=1)
        (d / "best" / MANIFEST_NAME).unlink()
        assert verify_checkpoint(d / "best")  # training would accept it
        verdict = CheckpointSwapper(engine).try_swap(d)
        assert not verdict.ok and verdict.reason == "verify_failed"

    def test_shape_mismatch_refused(self, swap_setup):
        from masters_thesis_tpu.models.objectives import ModelSpec
        from masters_thesis_tpu.serve.swap import CheckpointSwapper

        d, _, engine = swap_setup
        wide = ModelSpec(
            objective="mse", hidden_size=16, num_layers=1, dropout=0.0,
            kernel_impl="xla",
        )
        module = wide.build_module()
        import jax
        import jax.numpy as jnp

        params = module.init(
            jax.random.key(0), jnp.zeros((1, T, F), jnp.float32)
        )["params"]
        _save_ckpt(d, wide, params, epoch=1)
        verdict = CheckpointSwapper(engine).try_swap(d)
        assert not verdict.ok and verdict.reason == "shape_mismatch"


# ------------------------------------------------------------- chaos suite


class TestChaosServer:
    def test_overload_sheds_explicitly_never_late(
        self, shared_engine, tmp_path
    ):
        from masters_thesis_tpu.serve.server import PredictServer
        from masters_thesis_tpu.telemetry import TelemetryRun
        from masters_thesis_tpu.telemetry.report import summarize_events

        tel = TelemetryRun(tmp_path / "tel", run_id="overload-chaos")
        server = PredictServer(
            shared_engine, telemetry=tel, max_wait_s=0.002
        )
        server.start()
        feasible = [
            server.submit(shared_engine.golden_batch(1, seed=i)[0], 10.0)
            for i in range(10)
        ]
        # Zero budget: the admission forecast can never fit, every one of
        # these must be shed explicitly (not queued, not answered late).
        hopeless = [
            server.submit(
                shared_engine.golden_batch(1, seed=i)[0], deadline_s=0.0
            )
            for i in range(30)
        ]
        results_ok = [p.result(timeout=60.0) for p in feasible]
        results_shed = [p.result(timeout=60.0) for p in hopeless]
        stats = server.stop()
        tel.close()

        assert all(r.status == STATUS_OK for r in results_ok)
        assert all(r.status == STATUS_SHED for r in results_shed)
        assert all("deadline infeasible" in r.detail for r in results_shed)
        # The no-late-answers contract, checked from the caller's side.
        assert not any(
            r.ok and r.delivered_ts > p.request.deadline_ts
            for p, r in zip(feasible, results_ok)
        )
        assert stats["shed"] == 30 and stats["completed"] == 10
        assert stats["late_deliveries"] == 0

        report = summarize_events(_events(tel))
        assert report["serve"]["shed"] == 30
        assert report["serve"]["clean_stop"]
        assert report["serve"]["p99_ms"] is not None
        assert report["violations"] == []

    def test_nan_fault_withholds_outputs(self, shared_engine):
        from masters_thesis_tpu.serve.server import PredictServer

        faults.install_plan(
            FaultPlan(
                faults=(
                    FaultSpec(
                        point="serve.dispatch", kind="nan", attempt=None
                    ),
                )
            )
        )
        server = PredictServer(shared_engine, max_wait_s=0.001)
        server.start()
        r = server.submit(
            shared_engine.golden_batch(1)[0], deadline_s=10.0
        ).result(timeout=30.0)
        server.stop()
        assert r.status == STATUS_ERROR and "non-finite" in r.detail
        assert r.outputs is None

    def test_wedge_degrades_to_cpu_after_one_probe(self, tmp_path):
        from masters_thesis_tpu.serve.server import (
            InjectedDeviceError,  # noqa: F401 — the error the wedge raises
            PredictServer,
        )
        from masters_thesis_tpu.telemetry import TelemetryRun
        from masters_thesis_tpu.telemetry.report import summarize_events
        from masters_thesis_tpu.utils.backend_probe import BackendHealth

        engine = _make_engine(buckets=(1, 2))
        tel = TelemetryRun(tmp_path / "tel", run_id="wedge-chaos")
        health = BackendHealth(tmp_path / "probe_cache.json", timeout_s=5.0)
        # Dispatches 0 and 1 hit a device error; the backend probe itself
        # is wedged, so the tripped breaker must degrade to CPU.
        faults.install_plan(
            FaultPlan(
                faults=(
                    FaultSpec(
                        point="serve.dispatch", kind="wedge",
                        attempt=None, match={"seq": 0},
                    ),
                    FaultSpec(
                        point="serve.dispatch", kind="wedge",
                        attempt=None, match={"seq": 1},
                    ),
                    FaultSpec(
                        point="probe.attempt", kind="wedge", attempt=None
                    ),
                )
            )
        )
        server = PredictServer(
            engine, telemetry=tel, health=health, breaker_threshold=2,
            max_wait_s=0.001,
        )
        server.start()
        x = engine.golden_batch(1)[0]
        # Sequential submits: each scripted failure is its own dispatch,
        # so exactly two consecutive failures reach the breaker.
        for _ in range(2):
            r = server.submit(x, deadline_s=30.0).result(timeout=60.0)
            assert r.status == STATUS_ERROR
            assert "InjectedDeviceError" in r.detail
        deadline = time.monotonic() + 120.0
        while server.degradations < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        after = server.submit(x, deadline_s=30.0).result(timeout=60.0)
        stats = server.stop()
        tel.close()

        assert after.status == STATUS_OK  # traffic recovered on CPU
        assert stats["degradations"] == 1 and stats["errors"] == 2
        assert stats["late_deliveries"] == 0
        assert engine.platform == "cpu"
        events = _events(tel)
        degr = [e for e in events if e.get("kind") == "degradation"]
        assert len(degr) == 1
        assert degr[0]["scope"] == "serve"
        # Exactly ONE probe: single_attempt=True forces budget 0.
        assert degr[0]["probe_attempts"] == 1
        report = summarize_events(events)
        assert report["serve"]["degradations"] == 1
        assert report["violations"] == []


# ------------------------------------------------------ summarize contract


class TestServeTelemetryContract:
    def test_no_serve_section_without_serve_events(self):
        from masters_thesis_tpu.telemetry.report import summarize_events

        assert summarize_events([])["serve"] is None

    def test_late_delivery_is_a_contract_violation(self):
        from masters_thesis_tpu.telemetry.report import summarize_events

        report = summarize_events(
            [
                {"kind": "serve_started"},
                {
                    "kind": "serve_finished",
                    "requests": 5,
                    "completed": 5,
                    "late_deliveries": 2,
                },
            ]
        )
        assert any("delivered past" in v for v in report["violations"])
