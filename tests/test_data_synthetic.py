"""Statistical tests for the synthetic DGP (distribution parity is the
contract — exact sample parity with torch RNG is impossible, SURVEY.md §7)."""

import numpy as np
import pytest

from masters_thesis_tpu.data import SyntheticLogReturns
from masters_thesis_tpu.data.synthetic import SyntheticKFactorReturns


def test_generate_shapes_and_dtype():
    r_stocks, r_market, alphas, betas = SyntheticLogReturns.generate(7, 500, seed=0)
    assert r_stocks.shape == (7, 500)
    assert r_market.shape == (500,)
    assert alphas.shape == (7,)
    assert betas.shape == (7,)
    assert r_stocks.dtype == np.float32


def test_generate_is_deterministic_in_seed():
    a = SyntheticLogReturns.generate(3, 100, seed=42)
    b = SyntheticLogReturns.generate(3, 100, seed=42)
    c = SyntheticLogReturns.generate(3, 100, seed=43)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
    assert not np.array_equal(a[0], c[0])


def test_market_moments_match_student_t_parameters():
    _, r_market, _, _ = SyntheticLogReturns.generate(1, 200_000, seed=1)
    p = SyntheticLogReturns.mkt_params
    # Student-t(df) scaled: mean=loc, var=scale^2 * df/(df-2).
    expected_var = p["scale"] ** 2 * p["df"] / (p["df"] - 2.0)
    assert abs(r_market.mean() - p["loc"]) < 0.02
    assert abs(r_market.var() - expected_var) < 0.15 * expected_var


def test_alpha_beta_population_moments():
    _, _, alphas, betas = SyntheticLogReturns.generate(20_000, 2, seed=2)
    pa, pb = SyntheticLogReturns.alpha_params, SyntheticLogReturns.beta_params
    assert abs(alphas.mean() - pa["loc"]) < 0.01
    assert abs(alphas.std() - pa["scale"]) < 0.01
    assert abs(betas.mean() - pb["loc"]) < 0.02
    assert abs(betas.std() - pb["scale"]) < 0.02


def test_factor_structure_regression_recovers_beta():
    """End-to-end oracle: regressing generated stocks on the generated market
    must recover the sampled betas (SURVEY.md §4, synthetic-oracle strategy)."""
    s, m, alphas, betas = SyntheticLogReturns.generate(10, 50_000, seed=3)
    cov = ((s - s.mean(1, keepdims=True)) * (m - m.mean())).mean(1)
    beta_hat = cov / m.var()
    np.testing.assert_allclose(beta_hat, betas, atol=0.05)
    alpha_hat = s.mean(1) - beta_hat * m.mean()
    np.testing.assert_allclose(alpha_hat, alphas, atol=0.05)


def test_kfactor_shapes_and_dtype():
    r, f, a, b = SyntheticKFactorReturns.generate(7, 500, n_factors=3, seed=0)
    assert r.shape == (7, 500)
    assert f.shape == (3, 500)
    assert a.shape == (7,)
    assert b.shape == (7, 3)
    assert all(x.dtype == np.float32 for x in (r, f, a, b))


def test_kfactor_is_deterministic_in_seed():
    a = SyntheticKFactorReturns.generate(3, 100, n_factors=3, seed=42)
    b = SyntheticKFactorReturns.generate(3, 100, n_factors=3, seed=42)
    c = SyntheticKFactorReturns.generate(3, 100, n_factors=3, seed=43)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
    assert not np.array_equal(a[0], c[0])
    with pytest.raises(ValueError):
        SyntheticKFactorReturns.generate(3, 100, n_factors=0)
    with pytest.raises(ValueError):
        SyntheticKFactorReturns.generate(3, 100, n_factors=3, variant="bogus")


def test_kfactor_factor_moments():
    """Factor 0 keeps the market's Student-t drift; style factors are
    zero-mean with the same scale/tails."""
    _, f, _, _ = SyntheticKFactorReturns.generate(
        1, 200_000, n_factors=3, seed=1
    )
    p = SyntheticLogReturns.mkt_params
    expected_var = p["scale"] ** 2 * p["df"] / (p["df"] - 2.0)
    assert abs(f[0].mean() - p["loc"]) < 0.02
    for k in (1, 2):
        assert abs(f[k].mean()) < 0.02
        assert abs(f[k].var() - expected_var) < 0.15 * expected_var


def test_kfactor_loading_cross_section():
    """Market loadings keep the reference Normal cross-section; style
    loadings are zero-centered with the same dispersion."""
    _, _, _, b = SyntheticKFactorReturns.generate(
        20_000, 2, n_factors=3, seed=2
    )
    pb = SyntheticLogReturns.beta_params
    assert abs(b[:, 0].mean() - pb["loc"]) < 0.02
    for k in (1, 2):
        assert abs(b[:, k].mean()) < 0.02
        assert abs(b[:, k].std() - pb["scale"]) < 0.02


def test_kfactor_regression_recovers_loadings():
    """Multivariate OLS on the generated panel must recover the sampled
    alpha/beta — the K-factor synthetic-oracle contract."""
    r, f, alphas, betas = SyntheticKFactorReturns.generate(
        10, 50_000, n_factors=3, seed=3
    )
    design = np.concatenate([np.ones((f.shape[1], 1)), f.T], axis=-1)
    coef, *_ = np.linalg.lstsq(design, r.T, rcond=None)
    np.testing.assert_allclose(coef[0], alphas, atol=0.05)
    np.testing.assert_allclose(coef[1:].T, betas, atol=0.05)


def test_outliers_variant_differs_and_matches_params():
    """The outliers variant is selectable and produces wider-tailed data."""
    r_s, r_m, a, b = SyntheticLogReturns.generate(
        32, 200_000, seed=0, variant="outliers"
    )
    p = SyntheticLogReturns
    assert np.mean(b) == pytest.approx(p.beta_params_outliers["loc"], abs=0.2)
    # t(5) with the outliers scale has a larger market std than the default.
    _, r_m0, _, _ = SyntheticLogReturns.generate(32, 200_000, seed=0)
    assert np.std(r_m) > np.std(r_m0)
    with pytest.raises(ValueError):
        SyntheticLogReturns.generate(4, 100, variant="bogus")
