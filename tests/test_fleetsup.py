"""Fleet supervisor: all-rank relaunch, elastic resize, hang watchdog,
generation-stitched postmortem, decorrelated backoff, and the
torn-mid-publish checkpoint rotation.

The fast tests drive the REAL FleetSupervisor over the jax-free
``fleet-worker`` simulant (subprocess fleets, ~a second per generation).
The slow class at the bottom runs an actual 4-process ``jax.distributed``
CPU fleet through a mid-epoch SIGKILL and proves the relaunch resumes
bit-identically from the published checkpoint.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from masters_thesis_tpu.resilience.__main__ import (
    _fleet_expected_value,
    _fleet_shard,
)
from masters_thesis_tpu.resilience.backoff import DecorrelatedBackoff
from masters_thesis_tpu.resilience.fleetsup import (
    FleetConfig,
    FleetSupervisor,
)
from masters_thesis_tpu.telemetry.aggregate import postmortem_path
from masters_thesis_tpu.telemetry.events import read_events

_REPO = Path(__file__).resolve().parent.parent


# ------------------------------------------------------------ shard bounds


class TestShardBounds:
    def test_partition_covers_everything_once(self):
        from masters_thesis_tpu.parallel.mesh import (
            balanced_shard_sizes,
            shard_bounds,
        )

        for n in (0, 1, 5, 64, 101):
            for world in (1, 2, 3, 4, 7):
                bounds = [shard_bounds(n, world, r) for r in range(world)]
                # Contiguous, ordered, exactly covering [0, n).
                assert bounds[0][0] == 0
                assert bounds[-1][1] == n
                for (_, hi), (lo2, _) in zip(bounds, bounds[1:]):
                    assert hi == lo2
                sizes = balanced_shard_sizes(n, world)
                assert sum(sizes) == n
                assert max(sizes) - min(sizes) <= 1

    def test_rebalance_after_resize_still_covers(self):
        # The elastic-resize contract: shards are a pure function of
        # (n, world, rank), so survivors re-cover everything at N-1.
        from masters_thesis_tpu.parallel.mesh import shard_bounds

        n = 64
        for world in (4, 3, 2, 1):
            covered = set()
            for r in range(world):
                lo, hi = shard_bounds(n, world, r)
                covered.update(range(lo, hi))
            assert covered == set(range(n))

    def test_errors(self):
        from masters_thesis_tpu.parallel.mesh import shard_bounds

        with pytest.raises(ValueError):
            shard_bounds(8, 0, 0)
        with pytest.raises(ValueError):
            shard_bounds(8, 2, 2)
        with pytest.raises(ValueError):
            shard_bounds(8, 2, -1)

    def test_jax_free_worker_mirror_stays_in_lockstep(self):
        from masters_thesis_tpu.parallel.mesh import shard_bounds

        for n in (0, 1, 5, 64, 101):
            for world in (1, 2, 3, 4, 7):
                for r in range(world):
                    assert _fleet_shard(n, world, r) == shard_bounds(
                        n, world, r
                    )


# ------------------------------------------------------------------ backoff


class _HighRng:
    def uniform(self, a, b):
        return b


class _LowRng:
    def uniform(self, a, b):
        return a


class TestDecorrelatedBackoff:
    def test_first_delay_is_base(self):
        assert DecorrelatedBackoff(0.5, 60.0).next() == 0.5

    def test_factor_one_degrades_to_constant_base(self):
        # The deterministic test configs (backoff_factor=1.0) must keep
        # their exact sleep schedule: jitter range collapses to a point.
        b = DecorrelatedBackoff(0.05, 60.0, factor=1.0)
        assert [b.next() for _ in range(5)] == [0.05] * 5

    def test_delays_stay_within_base_and_cap(self):
        import random

        b = DecorrelatedBackoff(1.0, 8.0, factor=3.0,
                                rng=random.Random(7))
        delays = [b.next() for _ in range(50)]
        assert all(1.0 <= d <= 8.0 for d in delays)
        # With factor 3 and cap 8 the chain must actually reach the cap
        # region — decorrelated, not stuck at base.
        assert max(delays) > 4.0

    def test_upper_bound_grows_decorrelated(self):
        b = DecorrelatedBackoff(1.0, 100.0, factor=2.0, rng=_HighRng())
        assert [b.next() for _ in range(4)] == [1.0, 2.0, 4.0, 8.0]
        b2 = DecorrelatedBackoff(1.0, 3.0, factor=2.0, rng=_HighRng())
        assert [b2.next() for _ in range(4)] == [1.0, 2.0, 3.0, 3.0]

    def test_lower_bound_resets_chain_memory(self):
        b = DecorrelatedBackoff(1.0, 100.0, factor=4.0, rng=_LowRng())
        # A lucky low draw keeps the next upper bound small: the chain
        # decorrelates instead of marching deterministically upward.
        assert [b.next() for _ in range(3)] == [1.0, 1.0, 1.0]

    def test_reset_forgets_history(self):
        b = DecorrelatedBackoff(1.0, 100.0, factor=2.0, rng=_HighRng())
        b.next(), b.next(), b.next()
        b.reset()
        assert b.next() == 1.0

    def test_invalid_config_raises(self):
        with pytest.raises(ValueError):
            DecorrelatedBackoff(-1.0, 5.0)
        with pytest.raises(ValueError):
            DecorrelatedBackoff(1.0, -5.0)


# ------------------------------------------------- envelope generation tag


class TestGenerationEnvelope:
    def test_generation_tag_only_when_fleet_sets_env(
        self, tmp_path, monkeypatch
    ):
        from masters_thesis_tpu.telemetry import TelemetryRun

        monkeypatch.delenv("MTT_GENERATION", raising=False)
        tel = TelemetryRun(tmp_path / "plain")
        ev = tel.event("probe")
        tel.close()
        # Single-process streams stay byte-stable: no generation key.
        assert "generation" not in ev

        monkeypatch.setenv("MTT_GENERATION", "2")
        tel = TelemetryRun(tmp_path / "fleet")
        ev = tel.event("probe")
        tel.close()
        assert ev["generation"] == 2

    def test_generation_is_reserved_in_payloads(self, tmp_path):
        from masters_thesis_tpu.telemetry import TelemetryRun

        tel = TelemetryRun(tmp_path)
        with pytest.raises(ValueError):
            tel.event("probe", generation=1)
        tel.close()


# --------------------------------------------------- simulated fleet runs


def _fleet_env():
    env = dict(os.environ)
    env["PYTHONPATH"] = str(_REPO)
    return env


def _worker_cmd(state: Path, epochs: int, *extra: str) -> list[str]:
    return [
        sys.executable, "-m", "masters_thesis_tpu.resilience",
        "fleet-worker", "--state", str(state), "--out", "{out}",
        "--epochs", str(epochs), "--items", "64", "--sleep-s", "0.05",
        *extra,
    ]


def _fast_cfg(**over) -> FleetConfig:
    kw = dict(
        nprocs=2, min_nprocs=1, max_relaunches_per_size=2,
        backoff_s=0.05, backoff_factor=1.0, term_grace_s=2.0,
        poll_interval_s=0.05,
    )
    kw.update(over)
    return FleetConfig(**kw)


def _sup_events(run_dir: Path) -> dict[str, list[dict]]:
    events = read_events(run_dir / "supervisor" / "events.jsonl")
    by_kind: dict[str, list[dict]] = {}
    for ev in events:
        by_kind.setdefault(ev["kind"], []).append(ev)
    return by_kind


def _assert_no_orphans(result) -> None:
    # Every pid the supervisor ever launched must be gone (reaped by the
    # supervisor itself — they were its direct children).
    for gen in result.generations:
        for pid in gen.pids:
            with pytest.raises(ProcessLookupError):
                os.kill(pid, 0)


class TestFleetKillRelaunch:
    def test_rank_sigkill_relaunches_whole_fleet_and_resumes(
        self, tmp_path
    ):
        epochs = 5
        state = tmp_path / "state"
        result = FleetSupervisor(
            _worker_cmd(state, epochs, "--crash-rank", "1", "--at", "1",
                        "--crash-kind", "kill"),
            run_dir=tmp_path / "run",
            cfg=_fast_cfg(),
            env=_fleet_env(),
        ).run()

        assert result.ok and result.verdict == "completed"
        assert result.n_generations == 2 and not result.resized
        _assert_no_orphans(result)

        # Bit-identical resume: the atomic progress commit means every
        # epoch lands in the history exactly once and the rolling value
        # matches a fault-free run's.
        obj = json.loads((state / "progress.json").read_text())
        assert [e[3] for e in obj["history"]] == list(range(epochs))
        assert obj["value"] == _fleet_expected_value(epochs)
        # Generation is threaded through the committed history too:
        # the relaunch really ran as generation 1.
        assert sorted({e[1] for e in obj["history"]}) == [0, 1]

        by_kind = _sup_events(tmp_path / "run")
        assert len(by_kind["fleet_started"]) == 1
        fail = by_kind["fleet_failure"][0]
        assert fail["rank"] == 1 and fail["rc"] == -9
        assert fail["classification"] == "transient"
        assert by_kind["fleet_relaunch"][0]["gen"] == 1
        verdict = by_kind["fleet_verdict"][-1]
        assert verdict["ok"] and verdict["generations"] == 2

    def test_generation_tag_and_single_trace_across_generations(
        self, tmp_path
    ):
        state = tmp_path / "state"
        result = FleetSupervisor(
            _worker_cmd(state, 4, "--crash-rank", "1", "--at", "1",
                        "--crash-kind", "kill"),
            run_dir=tmp_path / "run",
            cfg=_fast_cfg(),
            env=_fleet_env(),
        ).run()
        assert result.ok and result.n_generations == 2

        # Every envelope in a g1 worker stream carries generation=1.
        g1_stream = next((tmp_path / "run" / "g1").rglob("events.jsonl"))
        evs = read_events(g1_stream)
        assert evs and all(ev.get("generation") == 1 for ev in evs)

        # ONE trace id spans the supervisor and both generations.
        report = postmortem_path(tmp_path / "run")
        assert report["exit_code"] == 0
        assert report["trace_ids"] == [result.trace_id]
        assert report["generations"] == 2


class TestFleetHangWatchdog:
    def test_hung_rank_restarts_fleet(self, tmp_path):
        state = tmp_path / "state"
        result = FleetSupervisor(
            _worker_cmd(state, 4, "--hang-rank", "1", "--at", "1"),
            run_dir=tmp_path / "run",
            cfg=_fast_cfg(hang_timeout_s=1.5),
            env=_fleet_env(),
        ).run()
        assert result.ok and result.n_generations == 2
        _assert_no_orphans(result)
        fail = _sup_events(tmp_path / "run")["fleet_failure"][0]
        assert fail["hang"] is True and fail["rank"] == 1
        assert fail["classification"] == "transient"
        obj = json.loads((state / "progress.json").read_text())
        assert [e[3] for e in obj["history"]] == list(range(4))


class TestFleetElasticResize:
    def test_deterministic_rank_loss_resizes_4_to_3_and_completes(
        self, tmp_path
    ):
        epochs = 4
        state = tmp_path / "state"
        result = FleetSupervisor(
            _worker_cmd(state, epochs, "--crash-rank", "3", "--at", "1",
                        "--crash-mode", "always"),
            run_dir=tmp_path / "run",
            cfg=_fast_cfg(nprocs=4),
            env=_fleet_env(),
        ).run()

        # gen 0 fails (fingerprint A), gen 1 fails (A again ->
        # deterministic) -> resize to 3 -> gen 2 has no rank 3 and
        # completes.
        assert result.ok and result.resized
        assert result.final_nprocs == 3 and result.n_generations == 3
        _assert_no_orphans(result)

        by_kind = _sup_events(tmp_path / "run")
        resized = by_kind["fleet_resized"][0]
        assert resized["from_nprocs"] == 4 and resized["to_nprocs"] == 3
        assert "deterministic" in resized["reason"]
        assert resized["fingerprint"]

        # Shards re-balance from the new world size: the final
        # generation's 3 ranks still cover all 64 items exactly once.
        final_gen = max(
            int(ln.split()[0])
            for ln in (state / "shards.log").read_text().splitlines()
        )
        covered: list[int] = []
        for ln in (state / "shards.log").read_text().splitlines():
            gen, world, rank, lo, hi = map(int, ln.split())
            if gen == final_gen:
                assert world == 3
                covered.extend(range(lo, hi))
        assert sorted(covered) == list(range(64))

        # Work history is complete despite the resize.
        obj = json.loads((state / "progress.json").read_text())
        assert [e[3] for e in obj["history"]] == list(range(epochs))

        # Acceptance: the postmortem stitches the whole incident into
        # ONE trace id across all three generations and exits 0.
        report = postmortem_path(tmp_path / "run")
        assert report["exit_code"] == 0
        assert report["trace_ids"] == [result.trace_id]
        assert report["generations"] == 3
        assert len(report["resizes"]) == 1
        assert report["fleet_verdict"]["ok"]

    def test_deterministic_loss_at_floor_halts_with_no_orphans(
        self, tmp_path
    ):
        state = tmp_path / "state"
        result = FleetSupervisor(
            _worker_cmd(state, 4, "--crash-rank", "1", "--at", "1",
                        "--crash-mode", "always"),
            run_dir=tmp_path / "run",
            cfg=_fast_cfg(nprocs=2, min_nprocs=2),
            env=_fleet_env(),
        ).run()
        assert not result.ok and result.verdict == "deterministic"
        assert result.n_generations == 2 and not result.resized
        _assert_no_orphans(result)
        verdict = _sup_events(tmp_path / "run")["fleet_verdict"][-1]
        assert verdict["ok"] is False
        assert verdict["verdict"] == "deterministic"
        # The failed-fleet postmortem reports the supervisor's verdict.
        report = postmortem_path(tmp_path / "run")
        assert report["exit_code"] == 2
        assert any("DETERMINISTIC" in f for f in report["failures"])


# ------------------------------------- aggregate generation stitching


def _write_stream(dir: Path, events: list[dict]) -> None:
    dir.mkdir(parents=True, exist_ok=True)
    with open(dir / "events.jsonl", "w") as f:
        for ev in events:
            f.write(json.dumps(ev) + "\n")


def _ev(seq, kind, *, proc, nproc, gen, attempt=1, ts=1000.0, **payload):
    ev = {
        "ts": ts + seq * 0.1, "kind": kind, "run": "r", "seq": seq,
        "host": "h", "pid": (100 + proc) if proc is not None else 99,
        "proc": proc, "nproc": nproc, "attempt": attempt,
    }
    if gen is not None:
        ev["generation"] = gen
    ev.update(payload)
    return ev


class TestAggregateGenerationStitching:
    def _fleet_root(self, tmp_path, *, second_gen_nprocs: int,
                    second_gen_procs: list[int]) -> Path:
        root = tmp_path / "run"
        # Generation 0: two ranks, both torn down unfinished.
        for p in (0, 1):
            _write_stream(root / "g0" / f"p{p}", [
                _ev(0, "run_started", proc=p, nproc=2, gen=0,
                    trace_id="t1"),
                _ev(1, "epoch", proc=p, nproc=2, gen=0, epoch=0,
                    wall_s=0.1),
            ])
        # Generation 1 (after the resize): the survivors finish.
        for p in second_gen_procs:
            _write_stream(root / "g1" / f"p{p}", [
                _ev(0, "run_started", proc=p, nproc=second_gen_nprocs,
                    gen=1, attempt=2, ts=1100.0, trace_id="t1"),
                _ev(1, "run_finished", proc=p, nproc=second_gen_nprocs,
                    gen=1, attempt=2, ts=1100.0),
            ])
        _write_stream(root / "supervisor", [
            _ev(0, "fleet_started", proc=None, nproc=None, gen=None,
                nprocs=2, trace_id="t1"),
            _ev(1, "fleet_generation_started", proc=None, nproc=None,
                gen=None, nprocs=2),
            _ev(2, "fleet_failure", proc=None, nproc=None, gen=None,
                rank=1, rc=3, classification="deterministic"),
            _ev(3, "fleet_resized", proc=None, nproc=None, gen=None,
                from_nprocs=2, to_nprocs=second_gen_nprocs,
                reason="deterministic host loss", fingerprint="abc",
                ts=1050.0),
            _ev(4, "fleet_generation_started", proc=None, nproc=None,
                gen=None, nprocs=second_gen_nprocs, ts=1050.0),
            _ev(5, "fleet_verdict", proc=None, nproc=None, gen=None,
                ok=True, verdict="completed", generations=2,
                final_nprocs=second_gen_nprocs, trace_id="t1",
                ts=1100.0),
        ])
        return root

    def test_retired_rank_is_not_missing_after_resize(self, tmp_path):
        # nproc shrinks 2 -> 1 across generations: the retired rank 1
        # must read as SUPERSEDED history, not as dead-forever or as a
        # missing process in the latest generation.
        root = self._fleet_root(tmp_path, second_gen_nprocs=1,
                                second_gen_procs=[0])
        report = postmortem_path(root, now=1100.0 + 3600.0, grace_s=30.0)
        assert report["exit_code"] == 0, report["failures"]
        assert report["missing_processes"] == []
        assert report["expected_processes"] == 1
        statuses = {d["label"]: d["status"] for d in report["processes"]}
        assert statuses["g0/p0"] == "superseded"
        assert statuses["g0/p1"] == "superseded"
        assert statuses["g1/p0"] == "finished"
        assert len(report["resizes"]) == 1
        assert report["fleet_verdict"]["ok"]
        assert report["trace_ids"] == ["t1"]
        assert report["generations"] == 2

    def test_genuinely_missing_rank_in_latest_generation_still_flags(
        self, tmp_path
    ):
        # Same shape but the latest generation EXPECTS 2 ranks and only
        # p0 left a stream: that rank really is missing.
        root = self._fleet_root(tmp_path, second_gen_nprocs=2,
                                second_gen_procs=[0])
        report = postmortem_path(root, now=1100.0 + 3600.0, grace_s=30.0)
        assert report["exit_code"] == 2
        assert report["missing_processes"] == [1]
        assert any("p1" in f and "no event stream" in f
                   for f in report["failures"])


# ----------------------------------------- torn-mid-publish checkpoint


class TestTornMidPublish:
    def _save_inline(self, ckpt_dir: Path, epoch: int) -> None:
        from masters_thesis_tpu.models.objectives import ModelSpec
        from masters_thesis_tpu.train.checkpoint import save_checkpoint

        spec = ModelSpec(objective="mse", hidden_size=8, num_layers=1,
                         dropout=0.0, learning_rate=1e-2)
        save_checkpoint(
            ckpt_dir, "last", {"w": np.full((64,), float(epoch))}, {},
            spec, meta={"epoch": epoch},
        )

    def test_kill_mid_publish_leaves_prev_verified(self, tmp_path):
        ckpt_dir = tmp_path / "ckpts"
        self._save_inline(ckpt_dir, 1)

        # Second save killed at checkpoint.mid_publish: the rotation has
        # moved last -> last.prev but the staged tree is not yet live —
        # the single most exposed instant of the publish protocol.
        code = (
            "import numpy as np\n"
            "from masters_thesis_tpu.models.objectives import ModelSpec\n"
            "from masters_thesis_tpu.train.checkpoint import save_checkpoint\n"
            "spec = ModelSpec(objective='mse', hidden_size=8,"
            " num_layers=1, dropout=0.0, learning_rate=1e-2)\n"
            f"save_checkpoint({str(ckpt_dir)!r}, 'last',"
            " {'w': np.full((64,), 2.0)}, {}, spec, meta={'epoch': 2})\n"
        )
        env = dict(os.environ)
        env["PYTHONPATH"] = str(_REPO)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["MTT_FAULT_PLAN"] = json.dumps(
            [{"point": "checkpoint.mid_publish", "kind": "kill"}]
        )
        proc = subprocess.run(
            [sys.executable, "-c", code], env=env, cwd=_REPO,
            capture_output=True, text=True, timeout=300,
        )
        assert proc.returncode == -9, proc.stderr

        # Torn layout: rotation done, staged pair intact, primary gone.
        assert not (ckpt_dir / "last").exists()
        assert (ckpt_dir / "last.prev").is_dir()
        assert (ckpt_dir / "last.new").is_dir()
        assert (ckpt_dir / "last.json.new").is_file()

        # The jax-free fleet-supervisor view: the .prev rotation is a
        # manifest-verified resume point even mid-tear.
        from masters_thesis_tpu.train.manifest import (
            last_verified_checkpoint,
            verify_checkpoint,
        )

        found = last_verified_checkpoint(ckpt_dir)
        assert found == str(ckpt_dir / "last.prev")
        assert verify_checkpoint(Path(found))

        # Restore finishes the staged swap (the pair was complete and
        # fsync'd before the rotation began) and yields save #2; the
        # previous-good rotation survives as the fallback.
        from masters_thesis_tpu.train.checkpoint import restore_checkpoint

        params, _, _, meta = restore_checkpoint(ckpt_dir, "last")
        assert meta["epoch"] == 2
        np.testing.assert_array_equal(
            np.asarray(params["w"]), np.full((64,), 2.0)
        )
        assert verify_checkpoint(ckpt_dir / "last")
        assert (ckpt_dir / "last.prev").is_dir()
        assert not (ckpt_dir / "last.new").exists()


# --------------------------------------- REAL 4-rank elastic fleet (slow)


@pytest.mark.slow
class TestFleetElastic4RankReal:
    """An actual ``jax.distributed`` CPU fleet (4 processes, 1 device
    each) supervised end-to-end: SIGKILL one rank mid-epoch, the fleet
    relaunches from the last manifest-verified checkpoint, and the final
    params are bit-identical to a fault-free 4-rank fleet's."""

    def _run_fleet(self, tmp_path: Path, name: str, chaos: bool):
        worker = _REPO / "tests" / "_elastic_worker.py"
        state = tmp_path / name / "state"
        state.mkdir(parents=True)
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        env["PYTHONPATH"] = str(_REPO)
        if chaos:
            env["MTT_CHAOS_KILL_RANK"] = "1"
            env["MTT_CHAOS_KILL_EPOCH"] = "1"
        sup = FleetSupervisor(
            [
                sys.executable, str(worker), "--state", str(state),
                "--out", "{out}", "--coordinator", "{coordinator}",
                "--epochs", "3",
            ],
            run_dir=tmp_path / name / "run",
            cfg=FleetConfig(
                nprocs=4, min_nprocs=1, max_relaunches_per_size=2,
                backoff_s=0.1, backoff_factor=1.0, term_grace_s=5.0,
                poll_interval_s=0.2, hang_timeout_s=180.0,
            ),
            env=env,
            ckpt_dir=state / "ckpts",
        )
        return sup.run(), state

    def test_sigkill_mid_epoch_resumes_bit_identical(self, tmp_path):
        clean, clean_state = self._run_fleet(tmp_path, "clean",
                                             chaos=False)
        assert clean.ok and clean.n_generations == 1, clean.verdict

        chaos, chaos_state = self._run_fleet(tmp_path, "chaos",
                                             chaos=True)
        assert chaos.ok, chaos.verdict
        assert chaos.n_generations == 2 and not chaos.resized
        _assert_no_orphans(chaos)

        # The relaunch resumed from a manifest-verified checkpoint.
        by_kind = _sup_events(tmp_path / "chaos" / "run")
        relaunch = by_kind["fleet_relaunch"][0]
        assert relaunch["resumed_from"] is not None
        assert relaunch["resumed_from"].endswith(("last", "last.prev"))

        ref = np.load(clean_state / "params.npz")
        got = np.load(chaos_state / "params.npz")
        assert set(ref.files) == set(got.files)
        for key in ref.files:
            np.testing.assert_array_equal(ref[key], got[key])
