"""Telemetry tests: registry/event primitives, the async-dispatch-aware
trainer wiring on the 8-device virtual mesh, and the summarize CLI.

The e2e contract (ISSUE acceptance): a tiny run must produce an
events.jsonl from which ``summarize`` reports steps/sec, p50/p99 step
time, a compile count of exactly 1 (TA201 at runtime), the data-wait vs
device-time split, and peak device memory — and an intentionally
shape-varying run must be flagged (CLI exit 2).
"""

import json
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from masters_thesis_tpu.data.pipeline import FinancialWindowDataModule
from masters_thesis_tpu.data.prefetch import PrefetchStats, prefetch_to_device
from masters_thesis_tpu.data.synthetic import SyntheticLogReturns
from masters_thesis_tpu.models.objectives import ModelSpec
from masters_thesis_tpu.telemetry import (
    CompileTracker,
    EpochRecorder,
    EventSink,
    MetricsRegistry,
    TelemetryRun,
    read_events,
)
from masters_thesis_tpu.telemetry.__main__ import main as cli_main
from masters_thesis_tpu.telemetry.report import summarize_path
from masters_thesis_tpu.train import Trainer
from masters_thesis_tpu.train.steps import jit_cache_size


@pytest.fixture(scope="module")
def tiny_dm(tmp_path_factory) -> FinancialWindowDataModule:
    data_dir = tmp_path_factory.mktemp("tel_data")
    r_stocks, r_market, alphas, betas = SyntheticLogReturns.generate(
        n_stocks=8, n_samples=4000, seed=1
    )
    np.save(data_dir / "stocks.npy", np.asarray(r_stocks))
    np.save(data_dir / "market.npy", np.asarray(r_market))
    np.save(data_dir / "alphas.npy", np.asarray(alphas))
    np.save(data_dir / "betas.npy", np.asarray(betas))
    dm = FinancialWindowDataModule(
        data_dir, lookback_window=16, target_window=8, stride=24, batch_size=2
    )
    dm.prepare_data(verbose=False)
    dm.setup()
    return dm


def small_spec():
    return ModelSpec(
        objective="mse", hidden_size=8, num_layers=1, dropout=0.0,
        learning_rate=1e-2,
    )


def make_trainer(**kw):
    defaults = dict(
        max_epochs=2,
        gradient_clip_val=5.0,
        check_val_every_n_epoch=1,
        enable_progress_bar=False,
        enable_model_summary=False,
        seed=0,
        strategy="tpu_xla",
    )
    defaults.update(kw)
    return Trainer(**defaults)


# --------------------------------------------------------------- primitives


class TestRegistry:
    def test_counter_gauge_histogram(self):
        reg = MetricsRegistry()
        reg.counter("c").inc()
        reg.counter("c").inc(2.5)
        reg.gauge("g").set(7)
        for v in range(100):
            reg.histogram("h").observe(float(v))
        snap = reg.snapshot()
        assert snap["metrics"]["c"]["value"] == 3.5
        assert snap["metrics"]["g"]["value"] == 7.0
        h = snap["metrics"]["h"]
        assert h["count"] == 100 and h["min"] == 0.0 and h["max"] == 99.0
        assert h["p50"] is not None and h["p99"] is not None
        assert "host" in snap["tags"] and "pid" in snap["tags"]

    def test_counter_rejects_negative(self):
        with pytest.raises(ValueError):
            MetricsRegistry().counter("c").inc(-1)

    def test_type_clash_raises(self):
        reg = MetricsRegistry()
        reg.counter("x")
        with pytest.raises(TypeError):
            reg.gauge("x")

    def test_histogram_bounded_memory(self):
        reg = MetricsRegistry()
        h = reg.histogram("h")
        for v in range(100_000):
            h.observe(float(v))
        assert len(h._samples) < h._max_samples
        assert h.count == 100_000
        # Decimation keeps the sample spread over the run, not clustered.
        assert h.quantile(0.99) > h.quantile(0.5) > 0


class TestEvents:
    def test_envelope_and_roundtrip(self, tmp_path):
        sink = EventSink(tmp_path / "events.jsonl", run_id="r1", proc=0)
        sink.emit("alpha", value=1)
        sink.emit("beta", nested={"a": [1, 2]})
        sink.close()
        events = read_events(tmp_path / "events.jsonl")
        assert [e["kind"] for e in events] == ["alpha", "beta"]
        assert events[0]["run"] == "r1" and events[0]["seq"] == 0
        assert events[1]["seq"] == 1 and events[1]["nested"] == {"a": [1, 2]}

    def test_payload_envelope_clash_rejected(self, tmp_path):
        sink = EventSink(tmp_path / "e.jsonl", run_id="r")
        with pytest.raises(ValueError):
            sink.emit("x", run="spoofed")

    def test_torn_final_line_tolerated(self, tmp_path):
        path = tmp_path / "e.jsonl"
        sink = EventSink(path, run_id="r")
        sink.emit("ok")
        sink.close()
        with open(path, "a") as f:
            f.write('{"kind": "torn", "no_clos')  # SIGKILL mid-write
        events = read_events(path)
        assert len(events) == 1 and events[0]["kind"] == "ok"


class TestPrefetchStats:
    def test_counts_and_depth(self):
        stats = PrefetchStats()
        items = [np.ones((2,)) for _ in range(5)]
        out = list(prefetch_to_device(iter(items), size=2, stats=stats))
        assert len(out) == 5
        assert stats.gets == 5 and stats.yields == 5
        assert stats.exhausted
        assert stats.get_wait_s > 0
        assert stats.min_depth >= 1 and stats.mean_depth >= 1


# ------------------------------------------------------------------ e2e fit


class TestTrainerTelemetry:
    @pytest.fixture(scope="class")
    def fit_report(self, tiny_dm, tmp_path_factory):
        run_dir = tmp_path_factory.mktemp("tel_run")
        tel = TelemetryRun(run_dir, run_id="e2e")
        trainer = make_trainer(telemetry=tel)
        result = trainer.fit(small_spec(), tiny_dm)
        tel.close()
        return run_dir, summarize_path(run_dir), result

    def test_compiles_exactly_once(self, fit_report):
        _, report, _ = fit_report
        assert report["compiles"]["train_epoch"] == 1
        assert report["violations"] == []

    def test_throughput_and_step_quantiles(self, fit_report):
        _, report, result = fit_report
        assert report["steps_per_sec"] == pytest.approx(
            result.steps_per_sec, rel=1e-6
        )
        assert report["steps_per_sec"] > 0
        assert report["step_time_ms"]["p50"] > 0
        assert report["step_time_ms"]["p99"] >= report["step_time_ms"]["p50"]

    def test_time_split_and_memory(self, fit_report):
        _, report, _ = fit_report
        t = report["time_split_s"]
        # Scan mode: the split is device-resident, so data-wait is 0 and
        # the first (compile) epoch dominates total wall.
        assert t["compile"] > 0 and t["total"] >= t["compile"]
        assert t["device"] > 0  # val epochs carry exact fenced device time
        assert t["data_wait"] == 0.0
        assert report["data"]["starvation_pct"] == 0.0
        assert report["memory"]["peak_bytes"] > 0

    def test_epoch_events_are_fenced_only_at_boundaries(self, fit_report):
        run_dir, _, _ = fit_report
        events = read_events(run_dir / "events.jsonl")
        epochs = [e for e in events if e["kind"] == "epoch"]
        assert len(epochs) == 2
        assert epochs[0]["compiled"] and epochs[0]["compile_events"] == 1
        assert not epochs[1]["compiled"]
        # check_val_every_n_epoch=1: every epoch is a val fence the trainer
        # takes anyway — telemetry must mark them fenced with device time.
        assert all(e["fenced"] and e["device_s"] is not None for e in epochs)
        kinds = {e["kind"] for e in events}
        assert {"run_started", "run_finished", "eval", "memory",
                "metrics"} <= kinds

    def test_cli_exit_codes(self, fit_report, capsys, tmp_path):
        run_dir, _, _ = fit_report
        assert cli_main(["summarize", str(run_dir)]) == 0
        out = capsys.readouterr().out
        assert "steps/sec" in out and "contracts      : ok" in out
        assert cli_main(["summarize", str(run_dir), "--json"]) == 0
        report = json.loads(capsys.readouterr().out)
        assert report["compiles"]["train_epoch"] == 1
        assert cli_main(["summarize", str(tmp_path / "nope")]) == 1

    def test_selfcheck(self, capsys):
        assert cli_main(["selfcheck"]) == 0
        assert "selfcheck ok" in capsys.readouterr().out


class TestShapeVaryingRunFlagged:
    def test_recompiles_flagged(self, tmp_path, capsys):
        """A run whose jitted program recompiles every epoch (the TA201
        shape-leak bug class) must be flagged by summarize (exit 2)."""

        @jax.jit
        def step(x):
            return x * 2.0

        tel = TelemetryRun(tmp_path, run_id="shapeleak")
        tel.event("run_started", platform="cpu", n_devices=1,
                  strategy="single_device", epoch_mode="scan",
                  steps_per_epoch=4)
        tracker = CompileTracker(step, size_fn=jit_cache_size)
        rec = EpochRecorder(tel, steps_per_epoch=4)
        for epoch in range(3):
            rec.begin(epoch)
            # Shape varies per epoch -> a fresh executable every time.
            jax.block_until_ready(step(jnp.zeros((epoch + 1,))))
            rec.dispatched(compiles=tracker.poll())
        rec.finish()
        tel.close()
        assert tracker.total == 3

        report = summarize_path(tmp_path)
        assert report["compiles"]["train_epoch"] == 3
        assert any("recompile" in v for v in report["violations"])
        assert cli_main(["summarize", str(tmp_path)]) == 2
        assert "CONTRACT VIOLATIONS" in capsys.readouterr().out


class TestStreamModeDataWait:
    def test_data_wait_recorded(self, tiny_dm, tmp_path):
        tel = TelemetryRun(tmp_path, run_id="stream")
        trainer = make_trainer(epoch_mode="stream", telemetry=tel)
        trainer.fit(small_spec(), tiny_dm)
        tel.close()
        report = summarize_path(tmp_path)
        # Stream mode produces batches on the host: the wall-time split must
        # show a nonzero data-wait, and the registry must carry the
        # prefetch queue gauges.
        assert report["data"]["data_wait_s"] > 0
        assert report["violations"] == []
        events = read_events(tmp_path / "events.jsonl")
        metrics = [e for e in events if e["kind"] == "metrics"][-1]["metrics"]
        assert metrics["data/batches"]["value"] > 0
        assert metrics["data/prefetch_mean_depth"]["value"] >= 0


class TestPreflightEvent:
    def test_preflight_ok_recorded(self, tiny_dm, tmp_path):
        tel = TelemetryRun(tmp_path, run_id="pre")
        trainer = make_trainer(preflight=True, telemetry=tel)
        trainer.fit(small_spec(), tiny_dm)
        tel.close()
        report = summarize_path(tmp_path)
        assert report["preflight"] == "ok"
        assert report["violations"] == []


class TestProfileWindow:
    def test_profile_steps_window(self, tiny_dm, tmp_path):
        tel = TelemetryRun(tmp_path, run_id="prof")
        trainer = make_trainer(
            max_epochs=3, profile_steps=(1, 1), telemetry=tel
        )
        trainer.fit(small_spec(), tiny_dm)
        tel.close()
        traces = list((tmp_path / "profile").rglob("*.xplane.pb"))
        assert traces, "no profiler trace under the telemetry run dir"
        report = summarize_path(tmp_path)
        assert report["profile_windows"] == [
            {"start_epoch": 1, "end_epoch": 1,
             "trace_dir": str(tmp_path / "profile")}
        ]


class TestLoggerDegradesWithoutTensorboardX:
    def test_no_tensorboardx_is_noop(self, tmp_path, monkeypatch):
        from masters_thesis_tpu.train import logging as tblog

        # None in sys.modules makes `from tensorboardX import ...` raise
        # ImportError — the exact shape of a missing optional dep.
        monkeypatch.setitem(sys.modules, "tensorboardX", None)
        monkeypatch.setattr(tblog, "_MISSING_WARNED", False)
        logger = tblog.TensorBoardLogger(tmp_path, "x", "v0")
        logger.log_scalar("a", 1.0, 0)
        logger.log_scalars({"b": 2.0}, 0)
        logger.log_hparams({"h": 1}, {"m": 0.5})
        logger.close()
        assert logger.writer is None
        assert not list(logger.log_dir.glob("events.out.tfevents*"))
